"""Graceful-overload span sampling over the staged columns.

Following "Trace Sampling 2.0" (PAPERS.md), overload degrades to a
REPRESENTATIVE sampled stream instead of a hard 429 cliff: when the
device scheduler's live-ingest pressure pushes the process keep-fraction
below 1.0 (`sched.keep_fraction`, the same signal that feeds
`IngestBackpressure`), the distributor runs this keep/drop stage over
the already-interned staging columns BEFORE trace grouping, ring
replication, and the generator tee — one decision, shared by every tee
target through the row-view filtering.

Scoring is cheap by construction (the decode-once path already paid for
the columns) and deterministic where it must be:

- **error spans** (`status_code == ERROR`) are always kept, exactly;
- **latency-tail spans** — duration above the tenant's own recent
  `tail_quantile` (host log2 sketch, the qlog geometry) — are always
  kept, exactly;
- everything else keeps iff `hash64(trace_id) / 2^53 < keep_fraction`:
  a pure function of (trace id, keep fraction), so the ingester tee and
  the in-process generator agree on every span, and raising the
  fraction only ADDS spans (monotone — a trace kept at f stays kept at
  every f' > f). Across replicas/retries the hash-DROPPED set is
  deterministic; the forced-keep classes can only diverge ADDITIVELY
  (a replica with a colder tail sketch keeps no fewer hash-passing
  spans, it just force-keeps fewer tail ones).

Kept spans carry a Horvitz-Thompson weight (1 for force-kept spans,
1/keep_fraction for hash-kept ones) that rides the staged view into the
generator, so spanmetrics rates upscale to the true stream and latency
quantiles stay bounded on the sampled stream.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable

import numpy as np

from tempo_tpu.overrides.limits import SamplingLimits

_LOG = logging.getLogger("tempo_tpu.ingest")

_STATUS_ERROR = 2          # OTLP STATUS_CODE_ERROR

# qlog LatencySketch geometry: bucket b>0 holds [2^(b-1-_OFFSET),
# 2^(b-_OFFSET)) seconds — covers ~2^-32s .. ~2^31s in 64 buckets
_NBUCKETS = 64
_OFFSET = 32
# decay the duration sketch once it holds this many observations so the
# tail threshold tracks RECENT traffic, not the process's whole history
_DECAY_AFTER = 1 << 20

_FNV_OFFSET = np.uint64(14695981039346656037)
_FNV_PRIME = np.uint64(1099511628211)


def trace_hash_u01(tids: np.ndarray) -> np.ndarray:
    """[n,16] uint8 trace-id matrix → float64 in [0,1): FNV-1a over the
    padded 16 bytes, top 53 bits as the uniform variate. Vectorized,
    byte-order-stable, and a pure function of the id bytes — the
    determinism contract the keep/drop decision rests on."""
    tids = np.ascontiguousarray(tids, np.uint8)
    h = np.full(len(tids), _FNV_OFFSET, np.uint64)
    with np.errstate(over="ignore"):
        for col in range(tids.shape[1]):
            h ^= tids[:, col].astype(np.uint64)
            h *= _FNV_PRIME
    return (h >> np.uint64(11)).astype(np.float64) / float(1 << 53)


class _DurationSketch:
    """Host log2 duration histogram per tenant (the write-path twin of
    `obs.qlog.LatencySketch`, vectorized): feeds the latency-tail
    always-keep threshold. One bincount per push."""

    __slots__ = ("counts", "total")

    def __init__(self) -> None:
        self.counts = np.zeros(_NBUCKETS, np.int64)
        self.total = 0

    def record(self, dur_s: np.ndarray) -> None:
        if not len(dur_s):
            return
        b = np.zeros(len(dur_s), np.int64)
        pos = dur_s > 0
        if pos.any():
            b[pos] = np.clip(
                np.floor(np.log2(dur_s[pos])).astype(np.int64) + 1 + _OFFSET,
                0, _NBUCKETS - 1)
        self.counts += np.bincount(b, minlength=_NBUCKETS)
        self.total += len(dur_s)
        if self.total > _DECAY_AFTER:
            self.counts //= 2
            self.total = int(self.counts.sum())

    def quantile(self, q: float) -> float:
        """Interpolated q-quantile in seconds (0.0 when empty). `q` is
        clamped to [0, 1] — a misconfigured tenant policy (e.g.
        tail_quantile: 1.5) must degrade, never crash the push path."""
        if self.total <= 0:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        target = max(q * self.total, 1e-12)
        cum = np.cumsum(self.counts)
        b = int(np.searchsorted(cum, target))
        if b <= 0:
            return 0.0
        c = int(self.counts[b])
        prev = int(cum[b]) - c
        frac = (target - prev) / c if c else 1.0
        return 2.0 ** (b - 1 - _OFFSET + frac)


class _TenantState:
    __slots__ = ("sketch", "last_fraction", "last_seen", "dropped_total",
                 "kept_forced_total", "exemplars", "_band")

    def __init__(self, now: float) -> None:
        self.sketch = _DurationSketch()
        self.last_fraction = 1.0
        self.last_seen = now
        self.dropped_total = 0
        self.kept_forced_total = 0
        self.exemplars: list[str] = []     # recent dropped trace-id hexes
        self._band = 10                    # fraction band for the qlog line


class SpanSampler:
    """The distributor's overload sampling stage (one per distributor).

    `fraction_source` is the process keep-fraction signal — defaults to
    `sched.ingest_keep_fraction` and is injectable for tests/bench so a
    pressure ramp can be driven deterministically."""

    # sweep idle tenant states like the rate limiter's buckets
    IDLE_TTL_S = 900.0
    MAX_TENANTS = 10_000
    N_EXEMPLARS = 5

    def __init__(self,
                 fraction_source: "Callable[[], float] | None" = None,
                 now: Callable[[], float] = time.time) -> None:
        self.now = now
        self._source = fraction_source
        # re-entrant: public methods hold it around every read/write of
        # per-tenant state — receivers push from many threads (HTTP
        # ThreadingServer, gRPC executor), and numpy in-place updates on
        # the shared sketch release the GIL mid-read-modify-write
        self._lock = threading.RLock()
        self._tenants: dict[str, _TenantState] = {}
        self._next_sweep = 0.0

    # -- the pressure signal ------------------------------------------------

    def global_fraction(self) -> float:
        if self._source is not None:
            return self._source()
        from tempo_tpu import sched
        return sched.ingest_keep_fraction()

    def effective_fraction(self, tenant: str, pol: SamplingLimits) -> float:
        """This tenant's keep-fraction right now: the process controller
        clamped by the tenant floor; exactly 1.0 when the tenant opted
        out or the controller is idle (sampling bypassed entirely).
        Called once per staged push — it also book-keeps the value the
        per-tenant gauge exports, including the recovery back to 1.0."""
        frac = 1.0
        if pol.enabled:
            g = self.global_fraction()
            if g < 1.0:
                frac = max(g, min(max(pol.floor, 0.0), 1.0))
        with self._lock:
            st = self._state(tenant)
            st.last_fraction = frac
            if frac >= 1.0 and st._band != 10:
                # recovery closes the episode: emit the final line (an
                # operator must be able to bound the sampled window from
                # the log alone) and reset the band so the NEXT episode
                # logs even if it lands in the same 0.1-band
                st._band = 10
                _LOG.warning(json.dumps({
                    "msg": "ingest sampling",
                    "tenant": tenant,
                    "keepFraction": 1.0,
                    "droppedSpansTotal": st.dropped_total,
                    "forcedKeepTotal": st.kept_forced_total,
                    "droppedTraceExemplars": st.exemplars,
                }, sort_keys=True))
        return frac

    # -- scoring ------------------------------------------------------------

    def observe(self, tenant: str, recs: np.ndarray,
                dur_s: "np.ndarray | None" = None) -> None:
        """Feed the tenant's duration sketch (every push, sampled or
        not) so the latency-tail threshold is warm when overload hits.
        Observing never changes the push's own output. `dur_s` lets the
        caller share one durations pass with `sample()`."""
        if dur_s is None:
            dur_s = self.durations_s(recs)
        with self._lock:
            self._state(tenant).sketch.record(dur_s)

    def sample(self, tenant: str, recs: np.ndarray, valid: np.ndarray,
               fraction: float, pol: SamplingLimits,
               dur_s: "np.ndarray | None" = None
               ) -> tuple[np.ndarray, np.ndarray]:
        """(keep mask, Horvitz-Thompson weights) over the staged rows.

        keep = error-status ∪ latency-tail ∪ (trace hash < fraction);
        weights are 1.0 for force-kept spans (P(keep)=1 → exact) and
        1/fraction for hash-kept ones. Rows outside `valid` are left
        unkept with weight 1 (they were never admitted)."""
        n = len(recs)
        if dur_s is None:
            dur_s = self.durations_s(recs)
        forced = np.zeros(n, bool)
        if pol.keep_errors:
            forced |= recs["status_code"] == _STATUS_ERROR
        u = trace_hash_u01(recs["trace_id"])
        hash_keep = u < fraction
        with self._lock:
            st = self._state(tenant)
            if pol.tail_quantile > 0 and \
                    st.sketch.total >= pol.tail_min_spans:
                thr = st.sketch.quantile(pol.tail_quantile)
                if thr > 0:
                    forced |= dur_s >= thr
            keep = (forced | hash_keep) & valid
            weights = np.ones(n, np.float32)
            scaled = hash_keep & ~forced
            weights[scaled] = np.float32(1.0 / max(fraction, 1e-6))
            self._note(st, tenant, recs, valid, keep, forced, fraction)
        return keep, weights

    @staticmethod
    def durations_s(recs: np.ndarray) -> np.ndarray:
        start = recs["start_ns"].astype(np.int64)
        end = recs["end_ns"].astype(np.int64)
        return np.maximum(end - start, 0) / 1e9

    # -- book-keeping / observability ---------------------------------------

    def _state(self, tenant: str) -> _TenantState:
        now = self.now()
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                st = self._tenants[tenant] = _TenantState(now)
            st.last_seen = now
            if now >= self._next_sweep or len(self._tenants) > self.MAX_TENANTS:
                self._sweep_locked(now)
            return st

    def _sweep_locked(self, now: float) -> None:
        self._next_sweep = now + self.IDLE_TTL_S / 4
        dead = [t for t, s in self._tenants.items()
                if now - s.last_seen > self.IDLE_TTL_S]
        for t in dead:
            del self._tenants[t]
        if len(self._tenants) > self.MAX_TENANTS:
            by_age = sorted(self._tenants.items(),
                            key=lambda kv: kv[1].last_seen)
            for t, _ in by_age[:len(self._tenants) - self.MAX_TENANTS]:
                del self._tenants[t]

    def _note(self, st: _TenantState, tenant: str, recs: np.ndarray,
              valid: np.ndarray, keep: np.ndarray, forced: np.ndarray,
              fraction: float) -> None:
        dropped = valid & ~keep
        n_dropped = int(dropped.sum())
        st.dropped_total += n_dropped
        st.kept_forced_total += int((forced & valid).sum())
        if n_dropped:
            # a handful of dropped trace ids as exemplars for the
            # structured overload log line (bounded, newest win)
            tids = recs["trace_id"][dropped][: self.N_EXEMPLARS]
            tls = recs["tid_len"][dropped][: self.N_EXEMPLARS]
            st.exemplars = [bytes(t)[: int(ln)].hex()
                            for t, ln in zip(tids, tls)]
        # one JSON line per fraction BAND transition (0.1-wide), not per
        # push: the overload story is greppable without being a log storm
        band = min(int(fraction * 10), 10)
        if band != st._band:
            st._band = band
            _LOG.warning(json.dumps({
                "msg": "ingest sampling",
                "tenant": tenant,
                "keepFraction": round(fraction, 4),
                "droppedSpansTotal": st.dropped_total,
                "forcedKeepTotal": st.kept_forced_total,
                "droppedTraceExemplars": st.exemplars,
            }, sort_keys=True))

    def fractions(self) -> list:
        """Callback-family shape for the per-tenant keep-fraction gauge:
        [((tenant,), fraction), ...]."""
        with self._lock:
            return [((t,), float(s.last_fraction))
                    for t, s in self._tenants.items()]

    def tenants(self) -> int:
        with self._lock:
            return len(self._tenants)


__all__ = ["SpanSampler", "trace_hash_u01"]
