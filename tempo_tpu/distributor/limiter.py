"""Per-tenant ingestion rate limiting (token bucket).

Analog of the dskit limiter the distributor consults per push
(`checkForRateLimits` `distributor.go:368` + `ingestion_rate_strategy.go`):
`local` gives each distributor the full per-tenant rate; `global` divides
the rate by the (healthy) distributor count so the fleet-wide total holds.
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class _Bucket:
    __slots__ = ("tokens", "last", "rate", "burst")

    def __init__(self, burst: float, now: float) -> None:
        self.tokens = burst
        self.last = now
        self.rate = 0.0        # last-seen limits, for refill-aware eviction
        self.burst = burst


class RateLimiter:
    """Token buckets per tenant, with idle eviction: under tenant churn
    (ephemeral tenant ids, fuzzing, abuse) the bucket map would otherwise
    grow without bound. Eviction is REFILL-AWARE: a bucket is evicted
    only once enough idle time has passed that its refill would have
    reached the burst cap anyway — recreating it full on the next push
    is then byte-identical to having kept it. A freshly drained bucket
    (unrefilled debt) is never TTL-evicted, and the max-size trim takes
    refilled buckets first, so churning ephemeral tenant ids cannot be
    used to launder away another tenant's spent burst."""

    IDLE_TTL_S = 900.0
    MAX_BUCKETS = 100_000

    def __init__(self, now: Callable[[], float] = time.time,
                 idle_ttl_s: float = IDLE_TTL_S,
                 max_buckets: int = MAX_BUCKETS) -> None:
        self.now = now
        self.idle_ttl_s = idle_ttl_s
        self.max_buckets = max_buckets
        self._buckets: dict[str, _Bucket] = {}
        self._lock = threading.Lock()
        self._next_sweep = 0.0

    def allow(self, tenant: str, n_bytes: int, rate: float, burst: float) -> bool:
        """Take n_bytes from the tenant bucket; False = over limit (caller
        returns ResourceExhausted / RetryInfo like the receiver shim)."""
        if rate <= 0:
            return True
        t = self.now()
        with self._lock:
            if t >= self._next_sweep or len(self._buckets) > self.max_buckets:
                self._sweep_locked(t)
            b = self._buckets.get(tenant)
            if b is None:
                b = self._buckets[tenant] = _Bucket(burst, t)
            b.rate = rate
            b.burst = burst
            b.tokens = min(burst, b.tokens + (t - b.last) * rate)
            b.last = t
            if n_bytes > b.tokens:
                return False
            b.tokens -= n_bytes
            return True

    @staticmethod
    def _refilled(b: _Bucket, t: float) -> bool:
        """True when evicting b loses nothing: its refill has reached
        the burst cap, so recreation starts from the same state."""
        return b.tokens + (t - b.last) * b.rate >= b.burst

    def _sweep_locked(self, t: float) -> None:
        """Amortized eviction (caller holds the lock): refill-aware TTL
        pass first, then a trim toward 90% of max (hysteresis — trimming
        to exactly the cap would re-sort the whole map on every push
        while churn holds it at the limit), refilled buckets first."""
        self._next_sweep = t + self.idle_ttl_s / 4
        dead = [k for k, b in self._buckets.items()
                if t - b.last > self.idle_ttl_s and self._refilled(b, t)]
        for k in dead:
            del self._buckets[k]
        if len(self._buckets) > self.max_buckets:
            target = int(self.max_buckets * 0.9)
            by_age = sorted(self._buckets.items(),
                            key=lambda kv: kv[1].last)
            # pass 1 evicts only refilled buckets (lossless); pass 2
            # evicts anything (bounded memory beats perfect accounting
            # under pathological churn)
            for lossless_only in (True, False):
                if len(self._buckets) <= target:
                    break
                for k, b in by_age:
                    if len(self._buckets) <= target:
                        break
                    if k in self._buckets and \
                            (not lossless_only or self._refilled(b, t)):
                        del self._buckets[k]


def effective_rate(strategy: str, rate: float, n_distributors: int) -> float:
    """`local`: per-replica rate; `global`: fleet rate split evenly
    (`ingestion_rate_strategy.go`)."""
    if strategy == "global" and n_distributors > 0:
        return rate / n_distributors
    return rate


class IngestBackpressure:
    """Admission gate fed by the device scheduler's ingest queue.

    The token-bucket limiter above protects against tenants exceeding
    their CONFIGURED rate; this hook protects the process itself: when
    the shared device-execution scheduler's live-ingest queue is
    saturated (the chip cannot keep up), the distributor rejects pushes
    with 429 + Retry-After instead of queuing unboundedly — clients back
    off, memory stays bounded, and the queue drains. Rejections are
    visible as `tempo_discarded_spans_total{reason="sched_backpressure"}`
    and the queue itself as `tempo_sched_queue_depth{class="ingest"}`.
    """

    def __init__(self, retry_after_fn: "Callable[[], float | None] | None"
                 = None) -> None:
        # injectable for tests; default consults the process scheduler
        self._fn = retry_after_fn

    def retry_after(self) -> "float | None":
        """Seconds the producer should back off, or None to admit."""
        if self._fn is not None:
            return self._fn()
        from tempo_tpu import sched
        sc = sched.scheduler()
        return sc.ingest_retry_after() if sc is not None else None
