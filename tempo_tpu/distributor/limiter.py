"""Per-tenant ingestion rate limiting (token bucket).

Analog of the dskit limiter the distributor consults per push
(`checkForRateLimits` `distributor.go:368` + `ingestion_rate_strategy.go`):
`local` gives each distributor the full per-tenant rate; `global` divides
the rate by the (healthy) distributor count so the fleet-wide total holds.
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class _Bucket:
    __slots__ = ("tokens", "last")

    def __init__(self, burst: float, now: float) -> None:
        self.tokens = burst
        self.last = now


class RateLimiter:
    def __init__(self, now: Callable[[], float] = time.time) -> None:
        self.now = now
        self._buckets: dict[str, _Bucket] = {}
        self._lock = threading.Lock()

    def allow(self, tenant: str, n_bytes: int, rate: float, burst: float) -> bool:
        """Take n_bytes from the tenant bucket; False = over limit (caller
        returns ResourceExhausted / RetryInfo like the receiver shim)."""
        if rate <= 0:
            return True
        t = self.now()
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                b = self._buckets[tenant] = _Bucket(burst, t)
            b.tokens = min(burst, b.tokens + (t - b.last) * rate)
            b.last = t
            if n_bytes > b.tokens:
                return False
            b.tokens -= n_bytes
            return True


def effective_rate(strategy: str, rate: float, n_distributors: int) -> float:
    """`local`: per-replica rate; `global`: fleet rate split evenly
    (`ingestion_rate_strategy.go`)."""
    if strategy == "global" and n_distributors > 0:
        return rate / n_distributors
    return rate


class IngestBackpressure:
    """Admission gate fed by the device scheduler's ingest queue.

    The token-bucket limiter above protects against tenants exceeding
    their CONFIGURED rate; this hook protects the process itself: when
    the shared device-execution scheduler's live-ingest queue is
    saturated (the chip cannot keep up), the distributor rejects pushes
    with 429 + Retry-After instead of queuing unboundedly — clients back
    off, memory stays bounded, and the queue drains. Rejections are
    visible as `tempo_discarded_spans_total{reason="sched_backpressure"}`
    and the queue itself as `tempo_sched_queue_depth{class="ingest"}`.
    """

    def __init__(self, retry_after_fn: "Callable[[], float | None] | None"
                 = None) -> None:
        # injectable for tests; default consults the process scheduler
        self._fn = retry_after_fn

    def retry_after(self) -> "float | None":
        """Seconds the producer should back off, or None to admit."""
        if self._fn is not None:
            return self._fn()
        from tempo_tpu import sched
        sc = sched.scheduler()
        return sc.ingest_retry_after() if sc is not None else None
