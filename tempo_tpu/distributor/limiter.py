"""Per-tenant ingestion rate limiting (token bucket).

Analog of the dskit limiter the distributor consults per push
(`checkForRateLimits` `distributor.go:368` + `ingestion_rate_strategy.go`):
`local` gives each distributor the full per-tenant rate; `global` divides
the rate by the (healthy) distributor count so the fleet-wide total holds.
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class _Bucket:
    __slots__ = ("tokens", "last")

    def __init__(self, burst: float, now: float) -> None:
        self.tokens = burst
        self.last = now


class RateLimiter:
    def __init__(self, now: Callable[[], float] = time.time) -> None:
        self.now = now
        self._buckets: dict[str, _Bucket] = {}
        self._lock = threading.Lock()

    def allow(self, tenant: str, n_bytes: int, rate: float, burst: float) -> bool:
        """Take n_bytes from the tenant bucket; False = over limit (caller
        returns ResourceExhausted / RetryInfo like the receiver shim)."""
        if rate <= 0:
            return True
        t = self.now()
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                b = self._buckets[tenant] = _Bucket(burst, t)
            b.tokens = min(burst, b.tokens + (t - b.last) * rate)
            b.last = t
            if n_bytes > b.tokens:
                return False
            b.tokens -= n_bytes
            return True


def effective_rate(strategy: str, rate: float, n_distributors: int) -> float:
    """`local`: per-replica rate; `global`: fleet rate split evenly
    (`ingestion_rate_strategy.go`)."""
    if strategy == "global" and n_distributors > 0:
        return rate / n_distributors
    return rate
