"""The distributor service.

The hot regrouping loop (`requestsByTraceID` `distributor.go:694-801`)
becomes a vectorized pass: trace ids stack into an [n,16] uint8 matrix, ring
tokens come from one batched fnv hash (`token_for`), and replication sets
resolve with a single searchsorted per unique trace (ring.do_batch).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Protocol, Sequence

import numpy as np

from tempo_tpu.distributor.limiter import RateLimiter, effective_rate
from tempo_tpu.native import token_for  # native fnv batch; numpy fallback
from tempo_tpu.overrides import Overrides
from tempo_tpu.ring import InstanceDesc, Ring, do_batch
from tempo_tpu.utils.livetraces import _approx_size

# discard reasons (mirroring the reference's discard metric reasons,
# `modules/distributor/distributor.go` reasonRateLimited etc.)
REASON_RATE_LIMITED = "rate_limited"
REASON_TRACE_TOO_LARGE = "trace_too_large"
REASON_INVALID_TRACE_ID = "invalid_trace_id"
REASON_INTERNAL = "internal_error"
REASON_UNKNOWN_ERROR = "unknown_error"


class IngesterClient(Protocol):
    def push(self, tenant: str,
             traces: Sequence[tuple[bytes, list[dict]]]) -> list[str | None]: ...


class GeneratorClient(Protocol):
    def push_otlp(self, tenant: str, data: bytes) -> int: ...


@dataclasses.dataclass
class DistributorConfig:
    rf: int = 3
    generator_rf: int = 1            # generator forwarding is RF1
    # per-tenant forwarder configs: {tenant: [{name, endpoint, filter}, ...]}
    # (`modules/distributor/forwarder` per-tenant tee)
    forwarders: dict = dataclasses.field(default_factory=dict)


class RateLimited(RuntimeError):
    """Maps to gRPC ResourceExhausted + RetryInfo at the receiver shim
    (`modules/distributor/receiver/shim.go` RetryableError)."""

    def __init__(self, tenant: str, n_bytes: int):
        super().__init__(f"tenant {tenant} over ingestion rate ({n_bytes}B)")
        self.tenant = tenant


class Distributor:
    def __init__(self,
                 ingester_ring: Ring,
                 ingester_clients: dict[str, IngesterClient],
                 overrides: Overrides | None = None,
                 generator_ring: Ring | None = None,
                 generator_clients: dict[str, GeneratorClient] | None = None,
                 cfg: DistributorConfig | None = None,
                 n_distributors: Callable[[], int] = lambda: 1,
                 bus: "object | None" = None,
                 now: Callable[[], float] = time.time) -> None:
        self.bus = bus
        self.cfg = cfg or DistributorConfig()
        self.overrides = overrides or Overrides()
        self.ingester_ring = ingester_ring
        self.ingester_clients = ingester_clients
        self.generator_ring = generator_ring
        self.generator_clients = generator_clients or {}
        self.limiter = RateLimiter(now=now)
        self.n_distributors = n_distributors
        from tempo_tpu.distributor.forwarder import (
            Forwarder,
            ForwarderConfig,
            ForwarderManager,
        )
        from tempo_tpu.utils.dataquality import DataQuality
        from tempo_tpu.utils.usage import UsageTracker
        self.usage = UsageTracker()
        self.dataquality = DataQuality(now=now)
        self.forwarders = ForwarderManager()
        for tenant, fwd_cfgs in (self.cfg.forwarders or {}).items():
            for fc in fwd_cfgs:
                cfg_obj = fc if isinstance(fc, ForwarderConfig) \
                    else ForwarderConfig(**fc)
                self.forwarders.register(tenant, Forwarder(cfg_obj))
        # self-metrics (tempo_distributor_* naming)
        self.metrics: dict[str, float] = {
            "spans_received_total": 0, "bytes_received_total": 0,
            "traces_pushed_total": 0, "push_failures_total": 0,
        }
        self.discarded: dict[str, int] = {}

    # -- entry -------------------------------------------------------------

    def push_spans(self, tenant: str, spans: Sequence[dict],
                   size_bytes: int | None = None,
                   raw_otlp: bytes | None = None,
                   raw_recs: "np.ndarray | None" = None) -> dict[str, int]:
        """The PushTraces path (`distributor.go:398-488`): returns discard
        reason counts for partial failures; raises RateLimited when the
        tenant bucket is empty.

        `raw_otlp` is the original OTLP wire payload when the receiver had
        one (OTLP http/grpc); the generator tee then forwards raw byte
        slices instead of re-encoding (`sendToGenerators` ships proto, not
        dicts). `spans` must be in payload scan order in that case;
        `raw_recs` is the receiver's native SpanRec scan of the same bytes
        (passed along so the tee does not scan twice)."""
        from tempo_tpu.utils import tracing
        with tracing.span_for_tenant("distributor.PushSpans", tenant,
                                     n_spans=len(spans)):
            return self._push_spans(tenant, spans, size_bytes, raw_otlp,
                                    raw_recs)

    def _push_spans(self, tenant, spans, size_bytes, raw_otlp,
                    raw_recs) -> dict[str, int]:
        lim = self.overrides.for_tenant(tenant)
        sz = size_bytes if size_bytes is not None else _approx_bytes(spans)
        rate = effective_rate(lim.ingestion.rate_strategy,
                              lim.ingestion.rate_limit_bytes,
                              self.n_distributors())
        if not self.limiter.allow(tenant, sz, rate,
                                  lim.ingestion.burst_size_bytes):
            self._discard(REASON_RATE_LIMITED, len(spans))
            raise RateLimited(tenant, sz)

        self.metrics["spans_received_total"] += len(spans)
        self.metrics["bytes_received_total"] += sz
        self.usage.observe(tenant, spans, sz)
        self.dataquality.observe_spans(tenant, spans)

        orig_spans = spans
        if lim.ingestion.max_attribute_bytes:
            # truncation rewrites attrs; the raw payload no longer matches
            raw_otlp = None
            raw_recs = None

        spans, errs = self._validate(spans, lim)
        if not spans:
            return errs
        self.forwarders.offer(tenant, spans)  # async tee, never blocks

        groups, tid_matrix = _group_by_trace(spans)
        tokens = token_for(tenant, tid_matrix)
        if self.bus is not None:
            # ingest-storage path: partition-keyed records onto the bus
            # (`sendToKafka` distributor.go:612). REPLACES both the
            # ingester replication (the blockbuilder is the persister on
            # this path) and the direct generator tee (generators consume
            # the bus) — running either in parallel would persist or count
            # every span twice.
            from tempo_tpu.ingest.encoding import produce_traces
            produce_traces(self.bus, tenant, groups, tokens)
            self.metrics["traces_pushed_total"] += len(groups)
            return errs
        errs2 = self._send_to_ingesters(tenant, groups, tokens, lim)
        for k, v in errs2.items():
            errs[k] = errs.get(k, 0) + v
        self._send_to_generators(tenant, groups, tokens, lim,
                                 raw_otlp=raw_otlp, raw_recs=raw_recs,
                                 orig_spans=orig_spans)
        return errs

    # -- stages ------------------------------------------------------------

    def _validate(self, spans: Sequence[dict],
                  lim) -> tuple[list[dict], dict[str, int]]:
        """Trace-id validation + attribute truncation
        (`pkg/validation` + distributor attr limits)."""
        errs: dict[str, int] = {}
        out: list[dict] = []
        max_attr = lim.ingestion.max_attribute_bytes
        for s in spans:
            tid = s.get("trace_id") or b""
            if not tid or len(tid) > 16:
                errs[REASON_INVALID_TRACE_ID] = errs.get(REASON_INVALID_TRACE_ID, 0) + 1
                self._discard(REASON_INVALID_TRACE_ID, 1)
                continue
            if max_attr:
                s = _truncate_attrs(s, max_attr)
            out.append(s)
        return out, errs

    def _send_to_ingesters(self, tenant: str,
                           groups: list[tuple[bytes, list[dict]]],
                           tokens: np.ndarray, lim) -> dict[str, int]:
        ring = self.ingester_ring
        if lim.ingestion.tenant_shard_size:
            ring = ring.shuffle_shard(tenant, lim.ingestion.tenant_shard_size)
        # per-trace reason, deduped across replicas: a trace rejected by all
        # RF replicas is one discarded trace, not RF of them
        item_reason: dict[int, str] = {}

        def send(inst: InstanceDesc, items: list[int]) -> None:
            client = self.ingester_clients[inst.id]
            res = client.push(tenant, [groups[i] for i in items])
            for i, reason in zip(items, res or ()):
                if reason:
                    item_reason.setdefault(i, reason)

        errs: dict[str, int] = {}
        try:
            do_batch(ring, tokens, list(range(len(groups))), send,
                     rf=self.cfg.rf)
            self.metrics["traces_pushed_total"] += len(groups)
        except RuntimeError:
            self.metrics["push_failures_total"] += 1
            n = sum(len(g[1]) for g in groups)
            self._discard(REASON_INTERNAL, n)
            errs[REASON_INTERNAL] = errs.get(REASON_INTERNAL, 0) + n
        for reason in item_reason.values():
            errs[reason] = errs.get(reason, 0) + 1
            self._discard(reason, 1)
        return errs

    def _send_to_generators(self, tenant: str,
                            groups: list[tuple[bytes, list[dict]]],
                            tokens: np.ndarray, lim,
                            raw_otlp: bytes | None = None,
                            raw_recs: "np.ndarray | None" = None,
                            orig_spans: Sequence[dict] | None = None) -> None:
        """Tee traces to metrics-generators (RF1, best-effort — generator
        loss degrades metrics, not trace durability; `distributor.go:563`).

        Always OTLP bytes on the wire (PushOTLP → the generator's
        vectorized staging): raw payload slices when the receiver handed
        one over, re-encoded from the span dicts otherwise. The per-span
        dict JSON tee is gone — it paid a triple decode (VERDICT r2 #10)."""
        if self.generator_ring is None or not self.generator_clients:
            return
        if not lim.generator.processors:
            return

        # original-order index per span object: maps validated dicts back
        # to raw wire slices without annotating them. Built only here —
        # the bus path and processor-less tenants never pay for it.
        recs = None
        n_scanned = -1
        wi_by_id: dict[int, int] = {}
        if raw_otlp is not None and orig_spans is not None:
            recs = raw_recs
            if recs is None:
                from tempo_tpu import native
                try:
                    recs = native.otlp_scan(raw_otlp)
                except ValueError:
                    recs = None
            if recs is not None:
                n_scanned = len(recs)
                if n_scanned != len(orig_spans):
                    recs = None    # decode disagreement: re-encode instead
                else:
                    wi_by_id = {id(s): i for i, s in enumerate(orig_spans)}

        from tempo_tpu.model.otlp import encode_spans_otlp, slice_otlp_payload

        def send(inst: InstanceDesc, items: list[int]) -> None:
            client = self.generator_clients[inst.id]
            if recs is not None:
                wis = [wi_by_id.get(id(s))
                       for i in items for s in groups[i][1]]
                if None not in wis:
                    if len(wis) == n_scanned:
                        client.push_otlp(tenant, raw_otlp)   # single target
                    else:
                        client.push_otlp(
                            tenant, slice_otlp_payload(raw_otlp, recs, wis))
                    return
            spans = [s for i in items for s in groups[i][1]]
            client.push_otlp(tenant, encode_spans_otlp(spans))

        try:
            do_batch(self.generator_ring, tokens, list(range(len(groups))),
                     send, rf=self.cfg.generator_rf)
        except RuntimeError:
            self.metrics["push_failures_total"] += 1

    def _discard(self, reason: str, n: int) -> None:
        self.discarded[reason] = self.discarded.get(reason, 0) + n


# -- helpers ---------------------------------------------------------------

def _group_by_trace(spans: Sequence[dict]
                    ) -> tuple[list[tuple[bytes, list[dict]]], np.ndarray]:
    """Regroup spans by trace id; returns groups + [n_groups,16] id matrix."""
    by_id: dict[bytes, list[dict]] = {}
    for s in spans:
        by_id.setdefault(s["trace_id"], []).append(s)
    groups = list(by_id.items())
    mat = np.zeros((len(groups), 16), np.uint8)
    for i, (tid, _) in enumerate(groups):
        b = tid.ljust(16, b"\0")[:16]
        mat[i] = np.frombuffer(b, np.uint8)
    return groups, mat


def _truncate_attrs(s: dict, max_bytes: int) -> dict:
    def trunc(attrs: dict | None) -> dict | None:
        if not attrs:
            return attrs
        out = {}
        for k, v in attrs.items():
            if len(k.encode()) > max_bytes:
                continue
            if isinstance(v, str) and len(v.encode()) > max_bytes:
                v = v.encode()[:max_bytes].decode(errors="ignore")
            out[k] = v
        return out

    s = dict(s)
    s["attrs"] = trunc(s.get("attrs"))
    s["res_attrs"] = trunc(s.get("res_attrs"))
    return s


def _approx_bytes(spans: Sequence[dict]) -> int:
    # shares the ingester's size heuristic so the distributor's rate limit
    # and the ingester's per-trace byte limit stay in the same units
    return _approx_size(list(spans))


__all__ = ["Distributor", "DistributorConfig", "RateLimited"]
