"""The distributor service.

The hot regrouping loop (`requestsByTraceID` `distributor.go:694-801`)
becomes a vectorized pass: trace ids stack into an [n,16] uint8 matrix, ring
tokens come from one batched fnv hash (`token_for`), and replication sets
resolve with a single searchsorted per unique trace (ring.do_batch).
"""

from __future__ import annotations

import dataclasses
import errno
import random
import time
import urllib.error
from typing import Callable, Protocol, Sequence

import numpy as np

from tempo_tpu.distributor.limiter import (IngestBackpressure, RateLimiter,
                                           effective_rate)
from tempo_tpu.native import group_keys  # native hash group; numpy fallback
from tempo_tpu.native import token_for   # native fnv batch; numpy fallback
from tempo_tpu.obs import Registry
from tempo_tpu.overrides import Overrides
from tempo_tpu.ring import InstanceDesc, Ring, do_batch
from tempo_tpu.utils.livetraces import _approx_size

# discard reasons (mirroring the reference's discard metric reasons,
# `modules/distributor/distributor.go` reasonRateLimited etc.)
REASON_RATE_LIMITED = "rate_limited"
REASON_BACKPRESSURE = "sched_backpressure"
REASON_SAMPLED = "sampled"           # graceful-overload sampling (sampler.py)
REASON_TRACE_TOO_LARGE = "trace_too_large"
REASON_INVALID_TRACE_ID = "invalid_trace_id"
REASON_INTERNAL = "internal_error"
REASON_UNKNOWN_ERROR = "unknown_error"


def _never_committed(e: BaseException) -> bool:
    """True iff the failed generator-tee send provably never reached a
    listener (connection refused). ONLY those are safe to re-send to a
    re-resolved ring owner: timeouts / resets / client-level retry
    exhaustion may have committed server-side, and the inner
    RemoteGeneratorClient already retried them under ONE X-Push-Id —
    re-sending here would mint a new id past the receiver's dedupe."""
    if isinstance(e, urllib.error.URLError) and \
            not isinstance(e, urllib.error.HTTPError):
        e = e.reason if isinstance(e.reason, BaseException) else e
    return isinstance(e, ConnectionRefusedError) or (
        isinstance(e, OSError)
        and getattr(e, "errno", None) == errno.ECONNREFUSED)


class IngesterClient(Protocol):
    def push(self, tenant: str,
             traces: Sequence[tuple[bytes, list[dict]]]) -> list[str | None]: ...


class GeneratorClient(Protocol):
    # in-process implementations may set accepts_local_trust = True and
    # take push_otlp(..., trusted=True) for bytes validated in THIS
    # process; remote clients must not (their process re-validates)
    def push_otlp(self, tenant: str, data: bytes) -> int: ...


@dataclasses.dataclass
class DistributorConfig:
    rf: int = 3
    generator_rf: int = 1            # generator forwarding is RF1
    # generator-tee placement: "trace" spreads a tenant's spans over the
    # whole generator ring by trace token (the single-logical-generator
    # shape); "tenant" hashes the TENANT onto the ring so its entire
    # stream lands on the owning member — the fleet topology
    # (tempo_tpu.fleet), where each member holds complete per-tenant
    # series/sketch state that can checkpoint and move
    generator_placement: str = "trace"
    # per-tenant forwarder configs: {tenant: [{name, endpoint, filter}, ...]}
    # (`modules/distributor/forwarder` per-tenant tee)
    forwarders: dict = dataclasses.field(default_factory=dict)
    # jaeger agent UDP receiver (thrift-compact emitBatch, port 6831 —
    # shim.go:165-171 jaeger protocols; deprecated upstream but still
    # deployed). 0 = disabled. EXPOSURE: the agent protocol is
    # unauthenticated single-tenant ingest, so the receiver binds
    # `jaeger_agent_host` (loopback by default); binding 0.0.0.0
    # additionally requires `jaeger_agent_allow_wildcard: true`.
    jaeger_agent_port: int = 0
    jaeger_agent_host: str = "127.0.0.1"
    jaeger_agent_allow_wildcard: bool = False


class RateLimited(RuntimeError):
    """Maps to gRPC ResourceExhausted + RetryInfo at the receiver shim
    (`modules/distributor/receiver/shim.go` RetryableError) and to 429 +
    Retry-After on the HTTP receivers. Raised for per-tenant rate limits
    AND for process-wide device-scheduler backpressure (`reason`
    distinguishes them; `retry_after_s` is advertised to the client)."""

    def __init__(self, tenant: str, n_bytes: int,
                 retry_after_s: float = 1.0,
                 reason: str = REASON_RATE_LIMITED):
        super().__init__(f"tenant {tenant} over ingestion rate ({n_bytes}B)"
                         if reason == REASON_RATE_LIMITED else
                         f"ingest backpressure: device scheduler saturated "
                         f"({n_bytes}B rejected)")
        self.tenant = tenant
        self.retry_after_s = retry_after_s
        self.reason = reason


class MalformedPayload(ValueError):
    """Decode-phase failure of a wire payload: the CLIENT's fault (HTTP
    400 / gRPC INVALID_ARGUMENT). Distinct from internal pipeline errors,
    which must surface as server faults, not as payload blame."""


class Distributor:
    def __init__(self,
                 ingester_ring: Ring,
                 ingester_clients: dict[str, IngesterClient],
                 overrides: Overrides | None = None,
                 generator_ring: Ring | None = None,
                 generator_clients: dict[str, GeneratorClient] | None = None,
                 cfg: DistributorConfig | None = None,
                 n_distributors: Callable[[], int] = lambda: 1,
                 bus: "object | None" = None,
                 registry: Registry | None = None,
                 now: Callable[[], float] = time.time) -> None:
        self.bus = bus
        self.cfg = cfg or DistributorConfig()
        self.overrides = overrides or Overrides()
        self.ingester_ring = ingester_ring
        self.ingester_clients = ingester_clients
        self.generator_ring = generator_ring
        self.generator_clients = generator_clients or {}
        self.limiter = RateLimiter(now=now)
        self.backpressure = IngestBackpressure()
        # graceful-overload sampling stage (runs on the staged decode-once
        # path BEFORE grouping/replication; see distributor/sampler.py) —
        # replaceable with one carrying an injected fraction_source
        from tempo_tpu.distributor.sampler import SpanSampler
        self.sampler = SpanSampler(now=now)
        self.n_distributors = n_distributors
        from tempo_tpu.distributor.forwarder import (
            Forwarder,
            ForwarderConfig,
            ForwarderManager,
        )
        from tempo_tpu.utils.dataquality import DataQuality
        from tempo_tpu.utils.usage import UsageTracker
        self.usage = UsageTracker()
        self.dataquality = DataQuality(now=now)
        # resource-bytes -> service.name memo (usage attribution): steady
        # traffic repeats the same few Resource messages every push
        self._svc_cache: dict[bytes, str] = {}
        self.forwarders = ForwarderManager()
        for tenant, fwd_cfgs in (self.cfg.forwarders or {}).items():
            for fc in fwd_cfgs:
                cfg_obj = fc if isinstance(fc, ForwarderConfig) \
                    else ForwarderConfig(**fc)
                self.forwarders.register(tenant, Forwarder(cfg_obj))
        # self-metrics (tempo_distributor_* naming): the plain dicts stay
        # the hot-path store; the obs registry renders them through
        # callback families registered below
        self.metrics: dict[str, float] = {
            "spans_received_total": 0, "bytes_received_total": 0,
            "traces_pushed_total": 0, "push_failures_total": 0,
            "push_retries_total": 0,
        }
        self.discarded: dict[str, int] = {}
        self.obs = registry if registry is not None else Registry()
        self._register_obs(self.obs)

    def _register_obs(self, reg: Registry) -> None:
        """This module's metric families — owned here, not scraped by the
        API layer."""
        helps = {
            "spans_received_total": "Spans accepted by the distributor",
            "bytes_received_total": "Wire bytes accepted by the distributor",
            "traces_pushed_total":
                "Distinct traces replicated to the ingester ring",
            "push_failures_total":
                "Quorum replication failures (ingester or generator ring)",
            "push_retries_total":
                "Tenant-placement generator pushes retried after a send "
                "failure (owner re-resolved off the live ring each "
                "attempt; the RPC push id makes the retry idempotent)",
        }
        for key, help_text in helps.items():
            reg.counter_func(
                f"tempo_distributor_{key}",
                lambda key=key: [((), self.metrics[key])], help=help_text)
        reg.counter_func(
            "tempo_discarded_spans_total",
            lambda: [((r,), v) for r, v in self.discarded.items()],
            help="Spans discarded by the distributor, by reason",
            labels=("reason",))
        reg.gauge_func(
            "tempo_distributor_sampling_keep_fraction",
            lambda: self.sampler.fractions(),
            help="Effective overload keep-fraction per tenant (1.0 = "
                 "sampling off; policy floor clamps the sched controller)",
            labels=("tenant",))
        reg.counter_func(
            "tempo_warnings_total",
            lambda: [((t, r), v) for (t, r), v in
                     self.dataquality.snapshot().items() if v],
            help="Data-quality warnings (clock skew, suspect timestamps)",
            labels=("tenant", "reason"))
        self.push_duration = reg.histogram(
            "tempo_distributor_push_duration_seconds",
            "End-to-end distributor push latency: validation, regrouping, "
            "ring replication, and the generator tee")

    # -- entry -------------------------------------------------------------

    def push_spans(self, tenant: str, spans: Sequence[dict],
                   size_bytes: int | None = None,
                   raw_otlp: bytes | None = None,
                   raw_recs: "np.ndarray | None" = None) -> dict[str, int]:
        """The PushTraces path (`distributor.go:398-488`): returns discard
        reason counts for partial failures; raises RateLimited when the
        tenant bucket is empty.

        `raw_otlp` is the original OTLP wire payload when the receiver had
        one (OTLP http/grpc); the generator tee then forwards raw byte
        slices instead of re-encoding (`sendToGenerators` ships proto, not
        dicts). `spans` must be in payload scan order in that case;
        `raw_recs` is the receiver's native SpanRec scan of the same bytes
        (passed along so the tee does not scan twice)."""
        from tempo_tpu.utils import tracing
        t0 = time.perf_counter()
        try:
            with tracing.span_for_tenant("distributor.PushSpans", tenant,
                                         n_spans=len(spans)):
                return self._push_spans(tenant, spans, size_bytes, raw_otlp,
                                        raw_recs)
        finally:
            self.push_duration.observe(time.perf_counter() - t0)

    def push_otlp(self, tenant: str, raw: bytes,
                  recs: "np.ndarray | None" = None) -> dict[str, int]:
        """The COLUMNAR PushTraces path: raw OTLP wire bytes in, no span
        dicts anywhere in the distributor. The native scan's fixed columns
        drive vectorized validation, data-quality, usage attribution,
        trace grouping, and token hashing; replicas and the generator tee
        receive raw wire slices and unmarshal at THEIR end, exactly as the
        reference's ingesters unmarshal PushBytesV2 bodies. Falls back to
        the dict path whenever a feature needs per-span dicts (no native
        layer, attr truncation configured, non-service usage dimensions,
        or the ingest bus)."""
        from tempo_tpu import native
        from tempo_tpu.utils import tracing

        lim = self.overrides.for_tenant(tenant)
        # config gates first: a fallback tenant must pay ONE decode, not
        # a columnar scan plus a dict decode
        needs_dicts = (lim.ingestion.max_attribute_bytes
                       or self.bus is not None
                       or not self.forwarders.empty
                       or set(self.usage.cfg.dimensions) - {"service"})
        if not needs_dicts:
            # decode-once staged tee: when EVERY ring target can consume
            # row views over one shared columnar staging, the payload is
            # decoded exactly once and never re-sliced or re-encoded
            plan = self._staging_plan(tenant, lim)
            if plan is not None:
                from tempo_tpu.model.otlp_batch import stage_otlp

                # admission BEFORE staging: a rejected push must not
                # intern its strings into the tenant registry's interner
                # (unbounded growth under sustained 429s) nor pay the
                # full decode during exactly the stall backpressure
                # sheds. Rejected span counts come from a lazy cheap
                # NON-interning scan — only a rejection pays it. (A
                # payload that then fails staging has already debited
                # the bucket; malformed input spending the sender's own
                # rate budget is an acceptable divergence.)
                def _count_spans() -> int:
                    try:
                        got = native.otlp_scan(raw)
                    except ValueError:
                        return 0
                    return len(got) if got is not None else 0

                self._admit(tenant, lim, len(raw), _count_spans)
                interner, need_span, need_res = plan
                try:
                    staged = stage_otlp(raw, interner,
                                        include_span_attrs=need_span,
                                        include_res_attrs=need_res)
                except ValueError as e:
                    raise MalformedPayload(str(e)) from None
                if staged is not None:
                    t0 = time.perf_counter()
                    try:
                        with tracing.span_for_tenant(
                                "distributor.PushSpans", tenant,
                                n_spans=staged.n):
                            return self._push_staged(tenant, raw, staged,
                                                     lim)
                    finally:
                        self.push_duration.observe(time.perf_counter() - t0)
            if recs is None:
                try:
                    recs = native.otlp_scan(raw)
                except ValueError as e:
                    raise MalformedPayload(str(e)) from None
            if recs is not None:
                t0 = time.perf_counter()
                try:
                    with tracing.span_for_tenant("distributor.PushSpans",
                                                 tenant, n_spans=len(recs)):
                        return self._push_otlp_columnar(tenant, raw, recs,
                                                        lim)
                finally:
                    self.push_duration.observe(time.perf_counter() - t0)
        try:
            got = native.spans_from_otlp_proto_native(raw, return_recs=True)
            if got[0] is None:
                from tempo_tpu.model.otlp import spans_from_otlp_proto
                got = (list(spans_from_otlp_proto(raw)), None)
        except ValueError as e:
            raise MalformedPayload(str(e)) from None
        spans, recs2 = got
        return self.push_spans(tenant, spans, size_bytes=len(raw),
                               raw_otlp=raw, raw_recs=recs2)

    def _admit(self, tenant: str, lim, sz: int, n_spans) -> None:
        """Admission shared by every push path: process-wide backpressure
        BEFORE the tenant token bucket — a shed push must not debit the
        tenant's rate budget, or retries during a device stall would
        exhaust the bucket and misreport the 429 cause as rate_limited
        long after the scheduler recovers. `n_spans` may be a lazy
        callable: the staged route attributes rejected span counts from a
        cheap non-interning scan only when a rejection actually happens."""
        retry = self.backpressure.retry_after()
        if retry is not None:
            self._discard(REASON_BACKPRESSURE,
                          n_spans() if callable(n_spans) else n_spans)
            raise RateLimited(tenant, sz, retry_after_s=retry,
                              reason=REASON_BACKPRESSURE)
        rate = effective_rate(lim.ingestion.rate_strategy,
                              lim.ingestion.rate_limit_bytes,
                              self.n_distributors())
        if not self.limiter.allow(tenant, sz, rate,
                                  lim.ingestion.burst_size_bytes):
            self._discard(REASON_RATE_LIMITED,
                          n_spans() if callable(n_spans) else n_spans)
            raise RateLimited(tenant, sz)

    def _service_cached(self, raw: bytes, off: int, ln: int) -> str:
        """Memoized `_resource_service` keyed by the resource BYTES."""
        key = raw[off:off + ln] if ln > 0 else b""
        got = self._svc_cache.get(key)
        if got is None:
            if len(self._svc_cache) >= 4096:
                self._svc_cache.clear()
            got = self._svc_cache[key] = _resource_service(raw, off, ln)
        return got

    def _push_otlp_columnar(self, tenant: str, raw: bytes,
                            recs: np.ndarray, lim) -> dict[str, int]:
        n = len(recs)
        sz = len(raw)
        self._admit(tenant, lim, sz, n)
        self.metrics["spans_received_total"] += n
        self.metrics["bytes_received_total"] += sz
        self.dataquality.observe_start_ns(tenant, recs["start_ns"])

        # usage attribution by service: scan records arrive grouped by
        # ResourceSpans, so each distinct res_off is ONE contiguous run —
        # run detection replaces the sorting np.unique, and the resource
        # parse is memoized on the resource BYTES (payload shapes repeat
        # push to push; same attributed result, no per-push re-parse)
        if n and self.usage.cfg.dimensions == ("service",):
            ro = recs["res_off"]
            change = np.empty(n, bool)
            change[0] = True
            np.not_equal(ro[1:], ro[:-1], out=change[1:])
            first_r = np.flatnonzero(change)
            run_lens = np.diff(np.append(first_r, n))
            # even split of the wire size, matching observe(size_bytes=..)
            # so path choice cannot shift a tenant's attributed bytes
            per_span = sz / max(n, 1)
            self.usage.observe_grouped(tenant, [
                ((self._service_cached(raw, int(ro[i]),
                                       int(recs["res_len"][i])),),
                 int(c), float(c) * per_span)
                for i, c in zip(first_r.tolist(), run_lens.tolist())])

        # validation: vectorized trace-id check (pkg/validation)
        errs: dict[str, int] = {}
        valid = (recs["tid_len"] > 0) & (recs["tid_len"] <= 16)
        n_bad = int(n - valid.sum())
        if n_bad:
            errs[REASON_INVALID_TRACE_ID] = n_bad
            self._discard(REASON_INVALID_TRACE_ID, n_bad)
        if not valid.any():
            return errs

        # regroup by trace: one native hash pass over (padded 16-byte id ‖
        # wire length) — the length disambiguates a short id from the
        # 16-byte id that shares its zero-padded form (the dict path keys
        # on exact bytes). `requestsByTraceID` distributor.go:694 without
        # the O(n log n) sort numpy's void unique would pay — and read
        # straight from the records, skipping the key-matrix copies.
        from tempo_tpu import native as _native

        vrows = np.flatnonzero(valid)
        got = _native.group_keys_recs(recs, valid)
        if got is not None:
            first, inverse = got
        else:
            tids_all = np.ascontiguousarray(recs["trace_id"])
            keys = np.concatenate(
                [tids_all[vrows],
                 recs["tid_len"][vrows, None].astype(np.uint8)], axis=1)
            first, inverse = group_keys(keys)
        uniq_mat = np.ascontiguousarray(recs["trace_id"][vrows[first]])
        uniq_len = recs["tid_len"][vrows[first]]
        tokens = token_for(tenant, uniq_mat)
        n_traces = len(first)

        from tempo_tpu.model.otlp import slice_otlp_payload

        def payload_for(items: list[int]) -> bytes:
            if len(items) == n_traces and len(vrows) == len(recs):
                # full coverage AND nothing failed validation — only then
                # is the raw payload the correct slice
                return raw
            pick = np.zeros(n_traces, bool)
            pick[np.asarray(items, np.int64)] = True
            wis = vrows[pick[inverse]]       # O(n) gather, no isin sort
            if len(wis) == len(recs):
                return raw
            return slice_otlp_payload(raw, recs, wis.tolist())

        # replicate to ingesters (RF quorum, per-trace reason dedupe)
        ring = self.ingester_ring
        if lim.ingestion.tenant_shard_size:
            ring = ring.shuffle_shard(tenant, lim.ingestion.tenant_shard_size)
        item_reason: dict[int, str] = {}
        # keyed by (padded hex, wire length): replicas reply with exact
        # wire bytes, scan records pad — normalize without merging ids
        # that differ only in trailing-zero padding. Built LAZILY: the
        # happy path (no per-trace errors) never pays the n_traces
        # tobytes+hex loop that showed up in the tee-path profile.
        tid_to_item: dict = {}

        def _item_of(tid_hex: str) -> "int | None":
            if not tid_to_item:
                tid_to_item.update(
                    {(uniq_mat[i].tobytes().hex(), int(uniq_len[i])): i
                     for i in range(n_traces)})
            return tid_to_item.get((tid_hex.ljust(32, "0"),
                                    len(tid_hex) // 2))

        def send_ing(inst: InstanceDesc, items: list[int]) -> None:
            client = self.ingester_clients[inst.id]
            fn = getattr(client, "push_otlp", None)
            if fn is not None:
                for tid_hex, reason in (fn(tenant, payload_for(items))
                                        or {}).items():
                    i = _item_of(tid_hex)
                    if i is not None and reason:
                        item_reason.setdefault(i, reason)
                return
            # client without the OTLP seam: decode just its slice
            from tempo_tpu.model.otlp import spans_from_otlp_proto
            spans = list(spans_from_otlp_proto(payload_for(items)))
            groups: dict[bytes, list] = {}
            for s in spans:
                groups.setdefault(s["trace_id"], []).append(s)
            res = client.push(tenant, list(groups.items()))
            for (tid, _g), reason in zip(groups.items(), res or ()):
                if reason:
                    i = _item_of(tid.hex())
                    if i is not None:
                        item_reason.setdefault(i, reason)

        try:
            do_batch(ring, tokens, list(range(n_traces)), send_ing,
                     rf=self.cfg.rf)
            self.metrics["traces_pushed_total"] += n_traces
        except RuntimeError:
            self.metrics["push_failures_total"] += 1
            nv = int(valid.sum())
            self._discard(REASON_INTERNAL, nv)
            errs[REASON_INTERNAL] = errs.get(REASON_INTERNAL, 0) + nv
        for reason in item_reason.values():
            errs[reason] = errs.get(reason, 0) + 1
            self._discard(reason, 1)

        # generator tee (RF1, best-effort, raw slices)
        if self.generator_ring is not None and self.generator_clients \
                and lim.generator.processors:
            def recs_for(items: list[int]) -> np.ndarray:
                if len(items) == n_traces and len(vrows) == len(recs):
                    return recs
                pick = np.zeros(n_traces, bool)
                pick[np.asarray(items, np.int64)] = True
                return recs[vrows[pick[inverse]]]

            def send_gen(inst: InstanceDesc, items: list[int]) -> None:
                client = self.generator_clients[inst.id]
                if getattr(client, "accepts_local_trust", False):
                    # in-process generator (explicit marker — never
                    # inferred): these bytes already passed this process's
                    # scan validation, so the stage may trust them. Remote
                    # clients re-validate at their own process boundary.
                    # Fastest route: hand over the scan RECORDS (subset
                    # for sharded tees) + the original payload — the
                    # generator resolves without re-parsing or slicing.
                    fn = getattr(client, "push_otlp_recs", None)
                    if fn is not None and \
                            fn(tenant, raw, recs_for(items)) is not None:
                        return
                    client.push_otlp(tenant, payload_for(items),
                                     trusted=True)
                else:
                    client.push_otlp(tenant, payload_for(items))

            self._send_generator_tee(tenant, tokens, n_traces, send_gen)
        return errs

    def _send_generator_tee(self, tenant: str, tokens: np.ndarray,
                            n_items: int, send_fn) -> None:
        """Route one generator-tee batch; failures count, never raise.

        Default placement ("trace"): per-trace tokens spread one tenant
        over the whole ring via `do_batch`. Fleet mode ("tenant"): the
        WHOLE batch goes to the tenant's single ring owner resolved with
        `Ring.owner_of` — the same hash AND the same health-spillover
        walk the fleet ownership watch uses, so routing and checkpoint
        placement agree even while a member is dead-but-registered
        (heartbeat expiry with no leave()): `do_batch`'s replica walk
        does not skip unhealthy instances, which would black-hole the
        dead member's tenants until its descriptor was removed."""
        from tempo_tpu.utils import tracing

        if self.cfg.generator_placement == "tenant":
            from tempo_tpu.fleet.placement import tenant_token

            # owner-moved retry: a REFUSED send (dead/killed member, the
            # one failure that provably never committed) re-resolves the
            # owner off the LIVE ring view — heartbeat expiry or handoff
            # may have moved the tenant mid-push — and retries with
            # jitter. Ambiguous failures stay failures: the client-level
            # idempotent retry (same X-Push-Id) already covered them.
            # ONE tee span for the whole walk (like the RPC client's
            # one-span retry loop): owner moves widen it, never fork it.
            with tracing.span_for_tenant("distributor.GeneratorTee",
                                         tenant, n_items=n_items) as sp:
                last_owner = None
                for attempt in range(3):
                    inst = self.generator_ring.owner_of(
                        tenant_token(tenant))
                    if inst is None:
                        break
                    if sp is not None:
                        sp.attrs["owner"] = inst.id
                    try:
                        send_fn(inst, list(range(n_items)))
                        return
                    except Exception as e:
                        if attempt == 2 or not _never_committed(e):
                            break
                        if last_owner == inst.id:
                            # same owner still refusing: brief jittered
                            # pause before the ring view names a new one
                            time.sleep(0.05 * (1 + attempt)
                                       * (0.5 + random.random()))
                        last_owner = inst.id
                        self.metrics["push_retries_total"] += 1
                self.metrics["push_failures_total"] += 1
                if sp is not None:
                    sp.status_code = 2
                    sp.attrs["error.message"] = "generator tee failed"
            return
        try:
            with tracing.span_for_tenant("distributor.GeneratorTee",
                                         tenant, n_items=n_items):
                do_batch(self.generator_ring, tokens,
                         list(range(n_items)), send_fn,
                         rf=self.cfg.generator_rf)
        except RuntimeError:
            self.metrics["push_failures_total"] += 1

    # -- decode-once staged tee --------------------------------------------

    def _staging_plan(self, tenant: str, lim
                      ) -> "tuple[object, bool, bool] | None":
        """(interner, need_span_attrs, need_res_attrs) when the staged tee
        can serve this push, else None (columnar byte-slice route).

        Eligible only when every generator client is an IN-PROCESS staged
        consumer (`staging_profile` — staging must share the tenant
        registry's interner) agreeing on ONE interner, and every ingester
        client accepts staged views. Remote clients unmarshal at their own
        process boundary, exactly as before."""
        if self.generator_ring is None or not self.generator_clients \
                or not lim.generator.processors:
            return None
        # ring-KV deployments hand us a live client POOL, not a dict —
        # those clients are remote by construction, so the staged tee
        # (an in-process seam) never applies
        if not hasattr(self.generator_clients, "values") \
                or not hasattr(self.ingester_clients, "values"):
            return None
        interner = None
        need_span = need_res = False
        for client in self.generator_clients.values():
            if not getattr(client, "accepts_local_trust", False) \
                    or getattr(client, "push_staged_view", None) is None:
                return None
            prof = getattr(client, "staging_profile", None)
            if prof is None:
                return None
            it, ns, nr = prof(tenant)
            if interner is None:
                interner = it
            elif it is not interner:
                # distinct in-process generators with distinct id spaces:
                # one shared staging cannot serve both
                return None
            need_span |= ns
            need_res |= nr
        for client in self.ingester_clients.values():
            if getattr(client, "push_staged", None) is None:
                return None
            if getattr(client, "staged_needs_attrs", True):
                # persisting ingesters need the attr columns in the
                # staging (the block schema keeps them)
                need_span = need_res = True
        return interner, need_span, need_res

    def _push_staged(self, tenant: str, raw: bytes, staged,
                     lim) -> dict[str, int]:
        """The decode-once write path: ONE staging pass produced `staged`;
        validation, data quality, usage attribution, trace grouping, and
        token hashing all read the staged columns, and every ring target
        receives a row-index VIEW over the same arrays — no re-slicing,
        no re-encoding, no second decode anywhere in the process.
        Admission (`_admit`) already ran in the caller, BEFORE staging."""
        recs = staged.spans
        n = staged.n
        sz = len(raw)
        self.metrics["spans_received_total"] += n
        self.metrics["bytes_received_total"] += sz
        self.dataquality.observe_start_ns(tenant, recs["start_ns"])

        # usage attribution by service: staged records arrive grouped by
        # resource, so res_idx changes delimit runs; the staged
        # service_id column (fixup applied) replaces the resource-bytes
        # memo parse entirely
        if n and self.usage.cfg.dimensions == ("service",):
            ri = recs["res_idx"]
            change = np.empty(n, bool)
            change[0] = True
            np.not_equal(ri[1:], ri[:-1], out=change[1:])
            first_r = np.flatnonzero(change)
            run_lens = np.diff(np.append(first_r, n))
            svc_ids = staged.service_ids()
            it = staged.interner
            per_span = sz / max(n, 1)
            self.usage.observe_grouped(tenant, [
                ((it.lookup(int(svc_ids[int(ri[i])]))
                  if len(svc_ids) else "",),
                 int(c), float(c) * per_span)
                for i, c in zip(first_r.tolist(), run_lens.tolist())])

        # validation: vectorized trace-id check
        errs: dict[str, int] = {}
        valid = (recs["tid_len"] > 0) & (recs["tid_len"] <= 16)
        n_bad = int(n - valid.sum())
        if n_bad:
            errs[REASON_INVALID_TRACE_ID] = n_bad
            self._discard(REASON_INVALID_TRACE_ID, n_bad)
        if not valid.any():
            return errs

        # graceful-overload sampling stage (sampler.py): under rising
        # sched pressure the keep-fraction drops below 1.0 and spans are
        # hash-sampled HERE — before grouping, replication, and the tee —
        # so every target shares one decision through the row views.
        # Error/latency-tail spans are always kept; kept spans carry
        # Horvitz-Thompson weights the generator uses to upscale rates.
        # At fraction 1.0 (no pressure / tenant opt-out) this whole block
        # is a no-op and the path is bit-identical to pre-sampling.
        pol = lim.sampling
        dur_s = None
        if pol.enabled and pol.tail_quantile > 0:
            # warm the tail sketch only for tenants whose policy reads
            # it — an opted-out tenant pays nothing on the hot path;
            # the durations pass is shared with sample() below
            dur_s = self.sampler.durations_s(recs)
            self.sampler.observe(tenant, recs, dur_s=dur_s)
        frac = self.sampler.effective_fraction(tenant, pol)
        if frac < 1.0:
            keep, weights = self.sampler.sample(tenant, recs, valid, frac,
                                                pol, dur_s=dur_s)
            n_drop = int((valid & ~keep).sum())
            if n_drop:
                self._discard(REASON_SAMPLED, n_drop)
            valid = valid & keep
            staged.sample_weight = weights
            # sampled spans are an intentional degradation, not a client
            # error: the push succeeds and errs stays clean (a retry
            # would re-offer bytes the process just chose to shed)
            if not valid.any():
                return errs

        # regroup by trace over the staged id columns (id ‖ wire length,
        # as the columnar path keys) — straight off the StageRec rows
        from tempo_tpu import native as _native

        vrows = np.flatnonzero(valid)
        got = _native.group_keys_strided(recs, valid)
        if got is not None:
            first, inverse = got
        else:
            tids_all = np.ascontiguousarray(recs["trace_id"])
            keys = np.concatenate(
                [tids_all[vrows],
                 recs["tid_len"][vrows, None].astype(np.uint8)], axis=1)
            first, inverse = group_keys(keys)
        uniq_mat = np.ascontiguousarray(recs["trace_id"][vrows[first]])
        uniq_len = recs["tid_len"][vrows[first]]
        tokens = token_for(tenant, uniq_mat)
        n_traces = len(first)

        def rows_for(items: list[int]) -> np.ndarray:
            if len(items) == n_traces:
                return vrows
            pick = np.zeros(n_traces, bool)
            pick[np.asarray(items, np.int64)] = True
            return vrows[pick[inverse]]

        ring = self.ingester_ring
        if lim.ingestion.tenant_shard_size:
            ring = ring.shuffle_shard(tenant, lim.ingestion.tenant_shard_size)
        item_reason: dict[int, str] = {}
        tid_to_item: dict = {}

        def _item_of(tid_hex: str) -> "int | None":
            if not tid_to_item:
                tid_to_item.update(
                    {(uniq_mat[i].tobytes().hex(), int(uniq_len[i])): i
                     for i in range(n_traces)})
            return tid_to_item.get((tid_hex.ljust(32, "0"),
                                    len(tid_hex) // 2))

        def send_ing(inst: InstanceDesc, items: list[int]) -> None:
            client = self.ingester_clients[inst.id]
            got = client.push_staged(tenant, staged.view(rows_for(items)))
            for tid_hex, reason in (got or {}).items():
                i = _item_of(tid_hex)
                if i is not None and reason:
                    item_reason.setdefault(i, reason)

        try:
            do_batch(ring, tokens, list(range(n_traces)), send_ing,
                     rf=self.cfg.rf)
            self.metrics["traces_pushed_total"] += n_traces
        except RuntimeError:
            self.metrics["push_failures_total"] += 1
            nv = int(valid.sum())
            self._discard(REASON_INTERNAL, nv)
            errs[REASON_INTERNAL] = errs.get(REASON_INTERNAL, 0) + nv
        for reason in item_reason.values():
            errs[reason] = errs.get(reason, 0) + 1
            self._discard(reason, 1)

        # generator tee (RF1, best-effort, staged views)
        def send_gen(inst: InstanceDesc, items: list[int]) -> None:
            client = self.generator_clients[inst.id]
            view = staged.view(rows_for(items))
            if client.push_staged_view(tenant, view) is not None:
                return
            # declined (e.g. the tenant instance was rebuilt with a fresh
            # interner between planning and send): compatibility fallback
            # through the OTLP-bytes surface. The bytes surface has no
            # weight channel, so a SAMPLED push falls back un-upscaled —
            # rare (one race window per instance rebuild), but it must
            # not be silent: that window's rates read low.
            if staged.sample_weight is not None:
                import logging
                logging.getLogger("tempo_tpu.ingest").warning(
                    "staged tee declined for tenant %s during sampling: "
                    "falling back to bytes, sample weights dropped "
                    "(rates under-reported for this push)", tenant)
            if view.is_full:
                client.push_otlp(tenant, raw, trusted=True)
            elif staged.has_span_attrs:
                from tempo_tpu.model.otlp import encode_spans_otlp
                client.push_otlp(tenant,
                                 encode_spans_otlp(view.to_span_dicts()))
            else:
                # staged without span attrs (every ingester opted out):
                # dict re-encode would silently drop attributes — slice
                # the raw payload instead (scan rows align with staged
                # rows: both scans emit in payload order)
                from tempo_tpu import native
                from tempo_tpu.model.otlp import slice_otlp_payload
                recs2 = native.otlp_scan(raw)
                client.push_otlp(
                    tenant,
                    slice_otlp_payload(raw, recs2,
                                       view.row_indices().tolist()),
                    trusted=True)

        self._send_generator_tee(tenant, tokens, n_traces, send_gen)
        return errs

    def _push_spans(self, tenant, spans, size_bytes, raw_otlp,
                    raw_recs) -> dict[str, int]:
        lim = self.overrides.for_tenant(tenant)
        sz = size_bytes if size_bytes is not None else _approx_bytes(spans)
        self._admit(tenant, lim, sz, len(spans))

        self.metrics["spans_received_total"] += len(spans)
        self.metrics["bytes_received_total"] += sz
        self.usage.observe(tenant, spans, sz)
        self.dataquality.observe_spans(tenant, spans)

        orig_spans = spans
        if lim.ingestion.max_attribute_bytes:
            # truncation rewrites attrs; the raw payload no longer matches
            raw_otlp = None
            raw_recs = None

        spans, errs = self._validate(spans, lim)
        if not spans:
            return errs
        self.forwarders.offer(tenant, spans)  # async tee, never blocks

        groups, tid_matrix = _group_by_trace(spans)
        tokens = token_for(tenant, tid_matrix)
        if self.bus is not None:
            # ingest-storage path: partition-keyed records onto the bus
            # (`sendToKafka` distributor.go:612). REPLACES both the
            # ingester replication (the blockbuilder is the persister on
            # this path) and the direct generator tee (generators consume
            # the bus) — running either in parallel would persist or count
            # every span twice.
            from tempo_tpu.ingest.encoding import produce_traces
            produce_traces(self.bus, tenant, groups, tokens)
            self.metrics["traces_pushed_total"] += len(groups)
            return errs
        errs2 = self._send_to_ingesters(tenant, groups, tokens, lim)
        for k, v in errs2.items():
            errs[k] = errs.get(k, 0) + v
        self._send_to_generators(tenant, groups, tokens, lim,
                                 raw_otlp=raw_otlp, raw_recs=raw_recs,
                                 orig_spans=orig_spans)
        return errs

    # -- stages ------------------------------------------------------------

    def _validate(self, spans: Sequence[dict],
                  lim) -> tuple[list[dict], dict[str, int]]:
        """Trace-id validation + attribute truncation
        (`pkg/validation` + distributor attr limits)."""
        errs: dict[str, int] = {}
        out: list[dict] = []
        max_attr = lim.ingestion.max_attribute_bytes
        for s in spans:
            tid = s.get("trace_id") or b""
            if not tid or len(tid) > 16:
                errs[REASON_INVALID_TRACE_ID] = errs.get(REASON_INVALID_TRACE_ID, 0) + 1
                self._discard(REASON_INVALID_TRACE_ID, 1)
                continue
            if max_attr:
                s = _truncate_attrs(s, max_attr)
            out.append(s)
        return out, errs

    def _send_to_ingesters(self, tenant: str,
                           groups: list[tuple[bytes, list[dict]]],
                           tokens: np.ndarray, lim) -> dict[str, int]:
        ring = self.ingester_ring
        if lim.ingestion.tenant_shard_size:
            ring = ring.shuffle_shard(tenant, lim.ingestion.tenant_shard_size)
        # per-trace reason, deduped across replicas: a trace rejected by all
        # RF replicas is one discarded trace, not RF of them
        item_reason: dict[int, str] = {}

        def send(inst: InstanceDesc, items: list[int]) -> None:
            client = self.ingester_clients[inst.id]
            res = client.push(tenant, [groups[i] for i in items])
            for i, reason in zip(items, res or ()):
                if reason:
                    item_reason.setdefault(i, reason)

        errs: dict[str, int] = {}
        try:
            do_batch(ring, tokens, list(range(len(groups))), send,
                     rf=self.cfg.rf)
            self.metrics["traces_pushed_total"] += len(groups)
        except RuntimeError:
            self.metrics["push_failures_total"] += 1
            n = sum(len(g[1]) for g in groups)
            self._discard(REASON_INTERNAL, n)
            errs[REASON_INTERNAL] = errs.get(REASON_INTERNAL, 0) + n
        for reason in item_reason.values():
            errs[reason] = errs.get(reason, 0) + 1
            self._discard(reason, 1)
        return errs

    def _send_to_generators(self, tenant: str,
                            groups: list[tuple[bytes, list[dict]]],
                            tokens: np.ndarray, lim,
                            raw_otlp: bytes | None = None,
                            raw_recs: "np.ndarray | None" = None,
                            orig_spans: Sequence[dict] | None = None) -> None:
        """Tee traces to metrics-generators (RF1, best-effort — generator
        loss degrades metrics, not trace durability; `distributor.go:563`).

        Always OTLP bytes on the wire (PushOTLP → the generator's
        vectorized staging): raw payload slices when the receiver handed
        one over, re-encoded from the span dicts otherwise. The per-span
        dict JSON tee is gone — it paid a triple decode (VERDICT r2 #10)."""
        if self.generator_ring is None or not self.generator_clients:
            return
        if not lim.generator.processors:
            return

        # original-order index per span object: maps validated dicts back
        # to raw wire slices without annotating them. Built only here —
        # the bus path and processor-less tenants never pay for it.
        recs = None
        n_scanned = -1
        wi_by_id: dict[int, int] = {}
        if raw_otlp is not None and orig_spans is not None:
            recs = raw_recs
            if recs is None:
                from tempo_tpu import native
                try:
                    recs = native.otlp_scan(raw_otlp)
                except ValueError:
                    recs = None
            if recs is not None:
                n_scanned = len(recs)
                if n_scanned != len(orig_spans):
                    recs = None    # decode disagreement: re-encode instead
                else:
                    wi_by_id = {id(s): i for i, s in enumerate(orig_spans)}

        from tempo_tpu.model.otlp import encode_spans_otlp, slice_otlp_payload

        def send(inst: InstanceDesc, items: list[int]) -> None:
            client = self.generator_clients[inst.id]
            if recs is not None:
                wis = [wi_by_id.get(id(s))
                       for i in items for s in groups[i][1]]
                if None not in wis:
                    if len(wis) == n_scanned:
                        client.push_otlp(tenant, raw_otlp)   # single target
                    else:
                        client.push_otlp(
                            tenant, slice_otlp_payload(raw_otlp, recs, wis))
                    return
            spans = [s for i in items for s in groups[i][1]]
            client.push_otlp(tenant, encode_spans_otlp(spans))

        self._send_generator_tee(tenant, tokens, len(groups), send)

    def _discard(self, reason: str, n: int) -> None:
        self.discarded[reason] = self.discarded.get(reason, 0) + n


# -- helpers ---------------------------------------------------------------

def _resource_service(raw: bytes, off: int, ln: int) -> str:
    """service.name of one Resource message region (columnar usage path)."""
    if off < 0 or ln <= 0:
        return ""
    from tempo_tpu.model import proto_wire as pw
    from tempo_tpu.model.otlp import _pb_attrs

    ra = _pb_attrs([v for f, _, v in pw.iter_fields(raw[off:off + ln])
                    if f == 1])
    v = ra.get("service.name")
    # dict-path parity: absent service attributes label as "" (the span
    # dict carries service="" there), not usage.MISSING
    return str(v) if v is not None else ""


def _group_by_trace(spans: Sequence[dict]
                    ) -> tuple[list[tuple[bytes, list[dict]]], np.ndarray]:
    """Regroup spans by trace id; returns groups + [n_groups,16] id matrix."""
    by_id: dict[bytes, list[dict]] = {}
    for s in spans:
        by_id.setdefault(s["trace_id"], []).append(s)
    groups = list(by_id.items())
    mat = np.zeros((len(groups), 16), np.uint8)
    for i, (tid, _) in enumerate(groups):
        b = tid.ljust(16, b"\0")[:16]
        mat[i] = np.frombuffer(b, np.uint8)
    return groups, mat


def _truncate_attrs(s: dict, max_bytes: int) -> dict:
    def trunc(attrs: dict | None) -> dict | None:
        if not attrs:
            return attrs
        out = {}
        for k, v in attrs.items():
            if len(k.encode()) > max_bytes:
                continue
            if isinstance(v, str) and len(v.encode()) > max_bytes:
                v = v.encode()[:max_bytes].decode(errors="ignore")
            out[k] = v
        return out

    s = dict(s)
    s["attrs"] = trunc(s.get("attrs"))
    s["res_attrs"] = trunc(s.get("res_attrs"))
    return s


def _approx_bytes(spans: Sequence[dict]) -> int:
    # shares the ingester's size heuristic so the distributor's rate limit
    # and the ingester's per-trace byte limit stay in the same units
    return _approx_size(list(spans))


__all__ = ["Distributor", "DistributorConfig", "RateLimited"]
