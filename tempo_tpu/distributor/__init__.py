"""Distributor: write-path entry — validate, limit, regroup, replicate.

Analog of `modules/distributor`: receives decoded span batches, enforces
per-tenant rate limits (`ingestion_rate_strategy.go`), validates and
truncates, regroups spans by trace id with vectorized token hashing
(`requestsByTraceID` `distributor.go:694-801` + `pkg/util/hash.go:8`),
replicates to ingesters over the ring with RF quorum
(`sendToIngestersViaBytes` `distributor.go:490`), and tees to the
metrics-generators (`sendToGenerators` `distributor.go:563`).
"""

from tempo_tpu.distributor.distributor import Distributor, DistributorConfig
from tempo_tpu.distributor.limiter import RateLimiter

__all__ = ["Distributor", "DistributorConfig", "RateLimiter"]
