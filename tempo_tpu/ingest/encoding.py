"""Record encoding: span-dict groups ↔ bus record bytes, with size splits.

Analog of `pkg/ingest/encoding.go:40` (`Encode` splits a PushBytesRequest
into ≤max_record_bytes records so one huge push can't exceed the bus's
record limit; `Decode` reassembles). The wire format here is the
framework's own compact msgpack-less encoding built on the proto_wire
varint helpers: repeated (trace_id, n_spans, span_json...) — JSON per span
keeps it debuggable; the hot columnar path never touches this (records
stage back into SpanBatches at the consumer).
"""

from __future__ import annotations

import json
from typing import Iterable, Iterator, Sequence

from tempo_tpu.model import proto_wire as pw

MAX_RECORD_BYTES = 1 << 20  # franz-go default-ish ceiling


def _enc_span(s: dict) -> bytes:
    d = dict(s)
    for k in ("trace_id", "span_id", "parent_span_id"):
        if k in d and isinstance(d[k], bytes):
            d[k] = d[k].hex()
    if d.get("links"):     # link ids are bytes in span dicts too
        d["links"] = [
            {**ln, **{k: ln[k].hex() for k in ("trace_id", "span_id")
                      if isinstance(ln.get(k), bytes)}}
            for ln in d["links"]]
    return json.dumps(d, separators=(",", ":")).encode()


def _dec_span(b: bytes) -> dict:
    d = json.loads(b)
    for k in ("trace_id", "span_id", "parent_span_id"):
        if k in d:
            d[k] = bytes.fromhex(d[k])
    if d.get("links"):
        d["links"] = [
            {**ln, **{k: bytes.fromhex(ln[k]) for k in ("trace_id", "span_id")
                      if isinstance(ln.get(k), str)}}
            for ln in d["links"]]
    return d


def encode_push(traces: Sequence[tuple[bytes, list[dict]]],
                max_record_bytes: int = MAX_RECORD_BYTES) -> list[bytes]:
    """Encode (trace_id, spans) groups into 1+ records of bounded size."""
    records: list[bytes] = []
    buf = bytearray()
    for tid, spans in traces:
        group = bytearray()
        group += pw.enc_field_bytes(1, tid)
        for s in spans:
            group += pw.enc_field_bytes(2, _enc_span(s))
        framed = pw.enc_field_bytes(3, bytes(group))
        if buf and len(buf) + len(framed) > max_record_bytes:
            records.append(bytes(buf))
            buf = bytearray()
        buf += framed
    if buf:
        records.append(bytes(buf))
    return records


def decode_push(record: bytes) -> Iterator[tuple[bytes, list[dict]]]:
    for fnum, _, group in pw.iter_fields(record):
        if fnum != 3:
            continue
        tid = b""
        spans: list[dict] = []
        for f2, _, v in pw.iter_fields(bytes(group)):
            if f2 == 1:
                tid = bytes(v)
            elif f2 == 2:
                spans.append(_dec_span(bytes(v)))
        yield tid, spans


def produce_traces(bus, tenant: str,
                   traces: Sequence[tuple[bytes, list[dict]]],
                   tokens, n_partitions: int | None = None) -> None:
    """Producer side: encode trace groups and spread them over partitions
    by token (`sendToKafka` `distributor.go:612`). Lives with the encoding
    so producers don't depend on any consumer service."""
    nparts = n_partitions or bus.n_partitions
    parts = partition_for(tokens, nparts)
    by_part: dict[int, list] = {}
    for (tid_spans, part) in zip(traces, parts):
        by_part.setdefault(int(part), []).append(tid_spans)
    for part, group in by_part.items():
        for record in encode_push(group):
            bus.produce(part, tenant, record)


def partition_for(tokens, n_partitions: int):
    """Token → partition (the partition ring's stable assignment,
    `distributor.go:612-679` ActivePartitionBatchRing). Tokens are remixed
    first: raw fnv tokens have parity artifacts (all-equal-byte trace ids
    always hash odd), so `token % n` would starve even partitions. Pure
    numpy — the producer hot path never dispatches to a device."""
    import numpy as np

    with np.errstate(over="ignore"):
        h = np.asarray(tokens, np.uint32)
        h = h + np.uint32(0x9E3779B9)
        h = (h ^ (h >> np.uint32(16))) * np.uint32(0x21F0AAAD)
        h = (h ^ (h >> np.uint32(15))) * np.uint32(0x735A2D97)
        h = h ^ (h >> np.uint32(15))
    return h % n_partitions
