"""Partitioned ingest bus: the Kafka ingest-storage path, in-process.

Analog of `pkg/ingest` (franz-go layer) + `pkg/ingest/testkafka`: an
append-only partitioned record log with consumer-group offset commits.
The distributor produces trace records onto partitions chosen by trace
token (`sendToKafka` `distributor.go:612`); the blockbuilder and the
metrics-generator consume partitions and commit offsets only after their
output is durable (exactly-once-ish replay, `blockbuilder.go:209-265`).

The in-memory `Bus` is both the test double (kfake analog) and the
single-process implementation; a networked bus would implement the same
produce/fetch/commit surface.
"""

from tempo_tpu.ingest.bus import Bus, Record
from tempo_tpu.ingest.encoding import decode_push, encode_push

__all__ = ["Bus", "Record", "encode_push", "decode_push"]
