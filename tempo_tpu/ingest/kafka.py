"""Kafka wire-protocol client: the external half of pkg/ingest.

The reference's ingest-storage path speaks to real Kafka through franz-go
(`pkg/ingest/writer_client.go:168-325`, `reader_client.go`); the
in-memory `Bus` covered only the testkafka half. This is an SDK-free
client of the Kafka binary protocol — the subset the bus seam needs:

- Metadata v1 (broker list + per-partition leaders)
- Produce v3 with v2 RecordBatches (varint records, CRC32C integrity)
- Fetch v4 (record batches decoded back into `Record`s)
- FindCoordinator v1 (consumer-group coordinator discovery)
- OffsetCommit v2 / OffsetFetch v1 (consumer-group offsets)
- ListOffsets v1 (high watermark)

Requests route to the PARTITION LEADER (produce/fetch) or the GROUP
COORDINATOR (offsets) from a cached metadata map, refreshed once on
NOT_LEADER/NOT_COORDINATOR class errors before the retry — the franz-go
behavior (`writer_client.go:168-325`) a multi-broker cluster requires;
against a single broker the bootstrap connection answers everything.

`KafkaBus` exposes the same surface as `ingest.bus.Bus`, so the
blockbuilder and the generator's consume loop run unchanged against a
real broker (or the signature-verifying mock in tests — the minio-style
pattern used for S3/Azure). Tenant rides the record KEY, as the
reference encodes it.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

from tempo_tpu.ingest.bus import Record

# -- crc32c (Castagnoli), table-based ---------------------------------------

_CRC_TABLE: list[int] = []


def _crc_init() -> None:
    poly = 0x82F63B78
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_crc_init()


def crc32c(data: bytes) -> int:
    from tempo_tpu import native

    got = native.crc32c(data)       # C++ table (~GB/s); the python loop
    if got is not None:             # below is the no-native fallback
        return got
    crc = 0xFFFFFFFF
    tab = _CRC_TABLE
    for b in data:
        crc = tab[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- primitive encoders -----------------------------------------------------

def _i8(v: int) -> bytes:
    return struct.pack(">b", v)


def _i16(v: int) -> bytes:
    return struct.pack(">h", v)


def _i32(v: int) -> bytes:
    return struct.pack(">i", v)


def _i64(v: int) -> bytes:
    return struct.pack(">q", v)


def _string(s: "str | None") -> bytes:
    if s is None:
        return _i16(-1)
    b = s.encode()
    return _i16(len(b)) + b


def _bytes(b: "bytes | None") -> bytes:
    if b is None:
        return _i32(-1)
    return _i32(len(b)) + b


def _uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        x = v & 0x7F
        v >>= 7
        if v:
            out.append(x | 0x80)
        else:
            out.append(x)
            return bytes(out)


def _varint(v: int) -> bytes:
    return _uvarint((v << 1) ^ (v >> 63))       # zigzag


class _R:
    __slots__ = ("b", "i")

    def __init__(self, b: bytes):
        self.b = b
        self.i = 0

    def i8(self):
        v = struct.unpack_from(">b", self.b, self.i)[0]; self.i += 1; return v

    def i16(self):
        v = struct.unpack_from(">h", self.b, self.i)[0]; self.i += 2; return v

    def i32(self):
        v = struct.unpack_from(">i", self.b, self.i)[0]; self.i += 4; return v

    def i64(self):
        v = struct.unpack_from(">q", self.b, self.i)[0]; self.i += 8; return v

    def u32(self):
        v = struct.unpack_from(">I", self.b, self.i)[0]; self.i += 4; return v

    def string(self) -> "str | None":
        n = self.i16()
        if n < 0:
            return None
        v = self.b[self.i:self.i + n]; self.i += n
        return v.decode()

    def bytes_(self) -> "bytes | None":
        n = self.i32()
        if n < 0:
            return None
        v = self.b[self.i:self.i + n]; self.i += n
        return v

    def uvarint(self) -> int:
        out = shift = 0
        while True:
            b = self.b[self.i]; self.i += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def varint(self) -> int:
        v = self.uvarint()
        return (v >> 1) ^ -(v & 1)              # un-zigzag


# -- record batches (message format v2) -------------------------------------

def encode_record_batch(base_offset: int, records: "list[tuple[bytes, bytes]]",
                        first_ts_ms: int = 0) -> bytes:
    """One v2 RecordBatch of (key, value) records."""
    recs = bytearray()
    for i, (key, value) in enumerate(records):
        body = (_i8(0) + _varint(0) + _varint(i) +
                _varint(len(key)) + key +
                _varint(len(value)) + value + _uvarint(0))
        recs += _varint(len(body)) + body
    n = len(records)
    after_crc = (_i16(0) +                       # attributes
                 _i32(n - 1) +                   # lastOffsetDelta
                 _i64(first_ts_ms) + _i64(first_ts_ms) +
                 _i64(-1) + _i16(-1) + _i32(-1) +  # producer id/epoch/seq
                 _i32(n) + bytes(recs))
    crc = crc32c(after_crc)
    body = (_i32(0) +                            # partitionLeaderEpoch
            _i8(2) +                             # magic
            struct.pack(">I", crc) + after_crc)
    return _i64(base_offset) + _i32(len(body)) + body


def decode_record_batches(buf: bytes, *, verify_crc: bool = True
                          ) -> "list[tuple[int, bytes, bytes]]":
    """[(offset, key, value)] from concatenated v2 RecordBatches."""
    out = []
    r = _R(buf)
    while r.i + 61 <= len(buf):
        base = r.i64()
        blen = r.i32()
        if r.i + blen > len(buf):
            break                               # truncated trailing batch
        end = r.i + blen
        r.i32()                                 # partitionLeaderEpoch
        magic = r.i8()
        if magic != 2:
            raise ValueError(f"unsupported magic {magic}")
        crc = r.u32()
        if verify_crc and crc32c(buf[r.i:end]) != crc:
            raise ValueError("record batch crc32c mismatch")
        r.i16()                                 # attributes
        r.i32()                                 # lastOffsetDelta
        r.i64(); r.i64()                        # timestamps
        r.i64(); r.i16(); r.i32()               # producer id/epoch/seq
        n = r.i32()
        for _ in range(n):
            r.varint()                          # record length
            r.i8()                              # attributes
            r.varint()                          # timestampDelta
            od = r.varint()
            klen = r.varint()
            key = buf[r.i:r.i + max(klen, 0)]; r.i += max(klen, 0)
            vlen = r.varint()
            value = buf[r.i:r.i + max(vlen, 0)]; r.i += max(vlen, 0)
            for _h in range(r.uvarint()):       # headers
                hk = r.varint(); r.i += max(hk, 0)
                hv = r.varint(); r.i += max(hv, 0)
            out.append((base + od, bytes(key), bytes(value)))
        r.i = end
    return out


# -- connection -------------------------------------------------------------

class _Conn:
    """One broker connection with lazy (re)connect across a bootstrap
    list: a socket fault or stream desync closes the socket and the next
    request redials — one broker restart must not brick the bus for the
    life of the process."""

    def __init__(self, bootstrap: str, client_id: str,
                 timeout_s: float = 10.0):
        self.addrs = []
        for part in bootstrap.split(","):
            host, _, port = part.strip().partition(":")
            if host:
                self.addrs.append((host, int(port or 9092)))
        if not self.addrs:
            raise ValueError(f"no kafka bootstrap address in {bootstrap!r}")
        self.client_id = client_id
        self.timeout = timeout_s
        self.sock: "socket.socket | None" = None
        self._corr = 0
        self._lock = threading.Lock()

    def _connect(self) -> None:
        errs = []
        for host, port in self.addrs:
            try:
                self.sock = socket.create_connection(
                    (host, port), timeout=self.timeout)
                return
            except OSError as e:
                errs.append(e)
        raise ConnectionError(
            f"no kafka broker reachable ({self.addrs}): {errs[-1]}")

    def _reset(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        self.sock = None

    def request(self, api_key: int, api_version: int, body: bytes) -> bytes:
        with self._lock:
            last: Exception | None = None
            for _attempt in (0, 1):      # one transparent redial
                try:
                    return self._request_locked(api_key, api_version, body)
                except (OSError, ConnectionError, RuntimeError) as e:
                    last = e
                    self._reset()        # desynced/dead stream: redial
            raise KafkaError(f"kafka request failed: {last}")

    def _request_locked(self, api_key: int, api_version: int,
                        body: bytes) -> bytes:
        if self.sock is None:
            self._connect()
        self._corr += 1
        corr = self._corr
        msg = (_i16(api_key) + _i16(api_version) + _i32(corr) +
               _string(self.client_id) + body)
        self.sock.sendall(_i32(len(msg)) + msg)
        raw = self._read(4)
        (n,) = struct.unpack(">i", raw)
        resp = self._read(n)
        r = _R(resp)
        got = r.i32()
        if got != corr:
            raise RuntimeError(f"kafka correlation mismatch {got} != {corr}")
        return resp[r.i:]

    def _read(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("kafka broker closed connection")
            out += chunk
        return out

    def close(self) -> None:
        self._reset()


class KafkaError(RuntimeError):
    def __init__(self, msg: str, code: "int | None" = None):
        super().__init__(msg)
        self.code = code


def _check(code: int, what: str) -> None:
    if code != 0:
        raise KafkaError(f"kafka {what} error code {code}", code)


# error classes that mean "your routing map is stale, refresh and retry":
# UNKNOWN_TOPIC_OR_PARTITION(3), LEADER_NOT_AVAILABLE(5),
# NOT_LEADER_FOR_PARTITION(6); COORDINATOR_NOT_AVAILABLE(15),
# NOT_COORDINATOR(16)
_STALE_LEADER = {3, 5, 6}
_STALE_COORD = {15, 16}


class KafkaBus:
    """The `ingest.bus.Bus` surface over a real Kafka cluster."""

    def __init__(self, bootstrap: str, *, topic: str = "tempo-ingest",
                 n_partitions: int = 2, client_id: str = "tempo-tpu",
                 timeout_s: float = 10.0) -> None:
        self.topic = topic
        self.n_partitions = n_partitions
        self._client_id = client_id
        self._timeout = timeout_s
        self._conn = _Conn(bootstrap, client_id, timeout_s)
        self._meta_lock = threading.Lock()
        self._brokers: dict[int, tuple[str, int]] = {}   # node → addr
        self._leaders: dict[int, int] = {}               # partition → node
        self._coord: "tuple[str, int] | None" = None
        self._conns: dict[tuple[str, int], _Conn] = {}

    # -- routing ------------------------------------------------------------

    def _conn_to(self, addr: "tuple[str, int] | None") -> _Conn:
        if addr is None:
            return self._conn
        with self._meta_lock:
            c = self._conns.get(addr)
            if c is None:
                c = self._conns[addr] = _Conn(
                    f"{addr[0]}:{addr[1]}", self._client_id, self._timeout)
        return c

    def refresh_metadata(self) -> None:
        """Metadata v1 → broker addresses + per-partition leaders, asked
        of the bootstrap connection first and then any previously-known
        broker (the bootstrap broker itself may be the dead one). Total
        failure leaves the maps unchanged."""
        for conn in self._candidate_conns():
            try:
                self._refresh_via(conn)
                return
            except Exception:
                continue             # keep old maps; next candidate

    def _candidate_conns(self) -> "list[_Conn]":
        """Bootstrap connection first, then every known broker (deduped
        against the bootstrap address) — shared by metadata refresh and
        coordinator discovery so both heal around any single dead
        broker."""
        with self._meta_lock:
            fallbacks = list(self._brokers.values())
        boot = set(self._conn.addrs)
        return [self._conn] + [self._conn_to(a) for a in fallbacks
                               if a not in boot]

    def _refresh_via(self, conn: _Conn) -> None:
        r = _R(conn.request(3, 1, _i32(1) + _string(self.topic)))
        brokers: dict[int, tuple[str, int]] = {}
        for _b in range(r.i32()):
            nid = r.i32()
            host = r.string() or ""
            port = r.i32()
            r.string()                           # rack
            brokers[nid] = (host, port)
        r.i32()                                  # controller id
        leaders: dict[int, int] = {}
        for _t in range(r.i32()):
            r.i16()                              # topic error
            name = r.string()
            r.i8()                               # is_internal
            for _p in range(r.i32()):
                r.i16()                          # partition error
                pid = r.i32()
                leader = r.i32()
                for _x in range(max(r.i32(), 0)):
                    r.i32()                      # replicas
                for _x in range(max(r.i32(), 0)):
                    r.i32()                      # isr
                if name == self.topic:
                    leaders[pid] = leader
        with self._meta_lock:
            self._brokers = brokers
            self._leaders = leaders

    def _leader_conn(self, partition: int) -> _Conn:
        with self._meta_lock:
            known = partition in self._leaders
        if not known:
            self.refresh_metadata()
        with self._meta_lock:
            addr = self._brokers.get(self._leaders.get(partition, -1))
        return self._conn_to(addr)

    def _coord_conn(self, group: str, force: bool = False) -> _Conn:
        with self._meta_lock:
            addr = self._coord
        if addr is None or force:
            addr = None
            # same candidate order as refresh_metadata: the bootstrap
            # broker may be the dead one (blockbuilder offsets survive)
            for conn in self._candidate_conns():
                try:
                    r = _R(conn.request(10, 1, _string(group) + _i8(0)))
                    r.i32()                      # throttle
                    err = r.i16()
                    r.string()                   # error message
                    r.i32()                      # coordinator node id
                    host = r.string() or ""
                    port = r.i32()
                    if err == 0:
                        addr = (host, port)
                        break
                except Exception:
                    continue
            with self._meta_lock:
                self._coord = addr
        return self._conn_to(addr)

    # -- produce ------------------------------------------------------------

    def produce(self, partition: int, tenant: str, value: bytes) -> int:
        partition %= self.n_partitions
        batch = encode_record_batch(0, [(tenant.encode(), value)])
        body = (_string(None) + _i16(-1) + _i32(30_000) +   # acks=all
                _i32(1) + _string(self.topic) +
                _i32(1) + _i32(partition) + _bytes(batch))
        for attempt in (0, 1):
            try:
                return self._produce_once(self._leader_conn(partition), body)
            except KafkaError as e:
                # code=None = connection-level failure (dead broker): the
                # leader may have MOVED — remap before giving up, else a
                # crashed leader bricks its partitions forever
                if attempt or (e.code is not None
                               and e.code not in _STALE_LEADER):
                    raise
                self.refresh_metadata()          # stale leader: remap once
        raise AssertionError("unreachable")

    def _produce_once(self, conn: _Conn, body: bytes) -> int:
        r = _R(conn.request(0, 3, body))
        base = -1
        for _t in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                r.i32()                          # partition
                _check(r.i16(), "produce")
                base = r.i64()
                r.i64()                          # log append time
        r.i32()                                  # throttle
        if base < 0:
            raise KafkaError("produce: no partition response")
        return base

    # -- fetch --------------------------------------------------------------

    def _fetch_raw(self, partition: int, offset: int,
                   max_bytes: int = 1 << 20) -> tuple[bytes, int]:
        body = (_i32(-1) + _i32(200) + _i32(1) + _i32(max_bytes) +
                _i8(0) +                         # isolation: read uncommitted
                _i32(1) + _string(self.topic) +
                _i32(1) + _i32(partition) + _i64(offset) + _i32(max_bytes))
        for attempt in (0, 1):
            try:
                return self._fetch_once(self._leader_conn(partition), body)
            except KafkaError as e:
                if attempt or (e.code is not None
                               and e.code not in _STALE_LEADER):
                    raise
                self.refresh_metadata()          # incl. dead-broker remap
        raise AssertionError("unreachable")

    def _fetch_once(self, conn: _Conn, body: bytes) -> tuple[bytes, int]:
        r = _R(conn.request(1, 4, body))
        r.i32()                                  # throttle
        batches = b""
        hw = 0
        for _t in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                r.i32()                          # partition
                _check(r.i16(), "fetch")
                hw = r.i64()
                r.i64()                          # last stable offset
                for _a in range(max(r.i32(), 0)):   # aborted txns
                    r.i64(); r.i64()
                batches = r.bytes_() or b""
        return batches, hw

    def fetch(self, partition: int, offset: int, max_records: int = 100
              ) -> list[Record]:
        partition %= self.n_partitions
        max_bytes = 1 << 20
        while True:
            batches, hw = self._fetch_raw(partition, offset, max_bytes)
            out = []
            for off, key, value in decode_record_batches(batches):
                if off < offset:
                    continue                     # batch overlaps the ask
                out.append(Record(off, key.decode("utf-8", "replace"),
                                  value))
                if len(out) >= max_records:
                    break
            if out or hw <= offset or not batches:
                return out
            # data exists but one batch exceeds max_bytes (truncated by
            # the broker): grow and retry instead of livelocking the
            # partition at this offset forever
            if max_bytes >= 1 << 26:
                raise KafkaError(
                    f"record batch at {self.topic}/{partition}@{offset} "
                    f"exceeds {max_bytes} bytes")
            max_bytes *= 8

    # -- offsets ------------------------------------------------------------

    def commit(self, group: str, partition: int, offset: int) -> None:
        body = (_string(group) + _i32(-1) + _string("") +
                _i64(-1) +                       # retention
                _i32(1) + _string(self.topic) +
                _i32(1) + _i32(partition % self.n_partitions) +
                _i64(offset) + _string(None))
        for attempt in (0, 1):
            try:
                r = _R(self._coord_conn(group, force=bool(attempt))
                       .request(8, 2, body))
                for _t in range(r.i32()):
                    r.string()
                    for _p in range(r.i32()):
                        r.i32()
                        _check(r.i16(), "offset commit")
                return
            except KafkaError as e:
                if attempt or (e.code is not None
                               and e.code not in _STALE_COORD):
                    raise                        # retry re-finds coordinator
        raise AssertionError("unreachable")

    def committed(self, group: str, partition: int) -> int:
        body = (_string(group) + _i32(1) + _string(self.topic) +
                _i32(1) + _i32(partition % self.n_partitions))
        for attempt in (0, 1):
            try:
                r = _R(self._coord_conn(group, force=bool(attempt))
                       .request(9, 1, body))
                off = 0
                for _t in range(r.i32()):
                    r.string()
                    for _p in range(r.i32()):
                        r.i32()
                        off = r.i64()
                        r.string()               # metadata
                        _check(r.i16(), "offset fetch")
                return max(off, 0)               # -1 = no commit yet
            except KafkaError as e:
                if attempt or (e.code is not None
                               and e.code not in _STALE_COORD):
                    raise                        # retry re-finds coordinator
        raise AssertionError("unreachable")

    def high_watermark(self, partition: int) -> int:
        _b, hw = self._fetch_raw(partition % self.n_partitions, 0,
                                 max_bytes=64)
        return hw

    def lag(self, group: str, partition: int) -> int:
        return self.high_watermark(partition) - self.committed(group, partition)

    def close(self) -> None:
        self._conn.close()
        with self._meta_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()

    # -- consumer-group seam (used by ConsumerGroup; coordinator-routed) ---

    def group_request(self, group: str, api_key: int, api_version: int,
                      body: bytes) -> bytes:
        """One coordinator-routed request with a single re-discovery retry
        (the same healing commit/committed use)."""
        for attempt in (0, 1):
            conn = self._coord_conn(group, force=bool(attempt))
            try:
                return conn.request(api_key, api_version, body)
            except Exception:
                if attempt:
                    raise
        raise AssertionError("unreachable")


# error codes the group state machine reacts to
_E_ILLEGAL_GENERATION = 22
_E_UNKNOWN_MEMBER = 25
_E_REBALANCE_IN_PROGRESS = 27
_E_MEMBER_ID_REQUIRED = 79
_REJOIN_CODES = {_E_ILLEGAL_GENERATION, _E_UNKNOWN_MEMBER,
                 _E_REBALANCE_IN_PROGRESS}


class ConsumerGroup:
    """Kafka consumer-group membership over the SDK-free wire client:
    JoinGroup v5 / SyncGroup v3 / Heartbeat v3 / LeaveGroup v1, with
    range assignment computed client-side by the elected leader — the
    franz-go group management the reference consumes via
    `pkg/ingest/reader_client.go` + partition balancing `balancer.go`,
    rebuilt on the raw protocol.

    Drive it with `ensure_active()` from the consume loop: it (re)joins
    when needed, heartbeats at half the session timeout, and returns the
    CURRENT partition assignment (possibly [] mid-rebalance — the loop
    simply owns nothing that tick; offsets replay on the next owner, so a
    member death moves partitions without message loss). Commits carry
    the generation + member id so zombies are fenced
    (ILLEGAL_GENERATION)."""

    def __init__(self, bus: KafkaBus, group: str, *,
                 session_timeout_ms: int = 30_000,
                 rebalance_timeout_ms: int = 60_000,
                 now=time.time) -> None:
        self.bus = bus
        self.group = group
        self.session_timeout_ms = session_timeout_ms
        self.rebalance_timeout_ms = rebalance_timeout_ms
        self.now = now
        self.member_id = ""
        self.generation = -1
        self.assignment: list[int] = []
        self._joined = False
        self._last_hb = 0.0

    # -- wire bodies -------------------------------------------------------

    def _subscription(self) -> bytes:
        # ConsumerProtocolSubscription v0: topics + user data
        return (_i16(0) + _i32(1) + _string(self.bus.topic) + _bytes(None))

    @staticmethod
    def _parse_subscription(meta: bytes) -> list[str]:
        r = _R(meta)
        r.i16()                                  # version
        return [r.string() or "" for _ in range(max(r.i32(), 0))]

    def _assignment_bytes(self, parts: list[int]) -> bytes:
        return (_i16(0) + _i32(1) + _string(self.bus.topic) +
                _i32(len(parts)) + b"".join(_i32(p) for p in parts) +
                _bytes(None))

    @staticmethod
    def _parse_assignment(body: bytes) -> list[int]:
        if not body:
            return []
        r = _R(body)
        r.i16()                                  # version
        parts: list[int] = []
        for _t in range(max(r.i32(), 0)):
            r.string()                           # topic
            for _p in range(max(r.i32(), 0)):
                parts.append(r.i32())
        return sorted(parts)

    # -- protocol steps ----------------------------------------------------

    def _coord_call(self, api_key: int, api_version: int,
                    body: bytes) -> bytes:
        """Coordinator-routed exchange healing BOTH failure shapes: dead
        connections (group_request re-discovers on transport errors) and
        NOT_COORDINATOR/LOAD_IN_PROGRESS responses after the coordinator
        MOVES to another broker — the join/sync/heartbeat/leave responses
        all carry (throttle i32, error i16) up front, so one peek decides
        the forced re-discovery retry."""
        for attempt in (0, 1):
            raw = self.bus.group_request(self.group, api_key, api_version,
                                         body)
            if attempt == 0 and len(raw) >= 6 and \
                    struct.unpack(">h", raw[4:6])[0] in _STALE_COORD:
                self.bus._coord_conn(self.group, force=True)
                continue
            return raw
        raise AssertionError("unreachable")

    def _join_once(self) -> "tuple[int, str, list[tuple[str, bytes]]] | None":
        """One JoinGroup v5 exchange. Returns (error, leader, members) —
        members only for the leader; None-equivalent via error code."""
        body = (_string(self.group) + _i32(self.session_timeout_ms) +
                _i32(self.rebalance_timeout_ms) + _string(self.member_id) +
                _string(None) +                  # group instance id
                _string("consumer") +
                _i32(1) + _string("range") + _bytes(self._subscription()))
        r = _R(self._coord_call(11, 5, body))
        r.i32()                                  # throttle
        err = r.i16()
        gen = r.i32()
        r.string()                               # protocol
        leader = r.string() or ""
        member_id = r.string() or ""
        members: list[tuple[str, bytes]] = []
        for _m in range(max(r.i32(), 0)):
            mid = r.string() or ""
            r.string()                           # instance id
            members.append((mid, r.bytes_() or b""))
        if member_id:
            self.member_id = member_id
        if err == 0:
            self.generation = gen
        return err, leader, members

    def _sync(self, assignments: "list[tuple[str, bytes]]") -> int:
        body = (_string(self.group) + _i32(self.generation) +
                _string(self.member_id) + _string(None) +
                _i32(len(assignments)) +
                b"".join(_string(m) + _bytes(a) for m, a in assignments))
        r = _R(self._coord_call(14, 3, body))
        r.i32()                                  # throttle
        err = r.i16()
        assignment = r.bytes_() or b""
        if err == 0:
            self.assignment = self._parse_assignment(assignment)
            self._joined = True
            self._last_hb = self.now()
        return err

    def _range_assign(self, members: "list[tuple[str, bytes]]"
                      ) -> "list[tuple[str, bytes]]":
        """Range assignment over the topic's partitions (balancer.go's
        default shape): contiguous runs, first members get the remainder.
        Members whose subscription metadata names other topics only get
        nothing (the group may mix consumers of different topics)."""
        n = self.bus.n_partitions
        ids = sorted(m for m, meta in members
                     if not meta
                     or self.bus.topic in self._parse_subscription(meta))
        out = []
        base, rem = divmod(n, max(len(ids), 1))
        start = 0
        for i, mid in enumerate(ids):
            take = base + (1 if i < rem else 0)
            out.append((mid, self._assignment_bytes(
                list(range(start, start + take)))))
            start += take
        return out

    def _rejoin(self) -> None:
        self._joined = False
        self.assignment = []
        for _attempt in range(3):
            err, leader, members = self._join_once()
            if err == _E_MEMBER_ID_REQUIRED:
                continue                         # retry WITH the new id
            if err != 0:
                return                           # next tick retries
            if leader == self.member_id:
                self._sync(self._range_assign(members))
            else:
                self._sync([])
            return

    def heartbeat(self) -> bool:
        """One Heartbeat v3; False = membership lost/rebalancing (caller's
        next ensure_active rejoins)."""
        body = (_string(self.group) + _i32(self.generation) +
                _string(self.member_id) + _string(None))
        r = _R(self._coord_call(12, 3, body))
        r.i32()
        err = r.i16()
        if err in _REJOIN_CODES:
            self._joined = False
            if err == _E_UNKNOWN_MEMBER:
                self.member_id = ""
            return False
        self._last_hb = self.now()
        return err == 0

    def ensure_active(self) -> list[int]:
        """Join/heartbeat as needed; returns the current assignment."""
        if not self._joined:
            self._rejoin()
        elif (self.now() - self._last_hb) * 1000 >= \
                self.session_timeout_ms / 2:
            if not self.heartbeat():
                self._rejoin()
        return list(self.assignment)

    def leave(self) -> None:
        if not self.member_id:
            return
        body = _string(self.group) + _string(self.member_id)
        try:
            self._coord_call(13, 1, body)
        except Exception:
            pass
        self._joined = False
        self.assignment = []
        self.member_id = ""
        self.generation = -1

    # -- generation-fenced offsets ----------------------------------------

    def commit(self, partition: int, offset: int) -> None:
        """OffsetCommit v2 carrying generation + member id: a commit from
        a fenced zombie (dead member, stale generation) is REJECTED by
        the coordinator instead of clobbering the new owner's offsets."""
        body = (_string(self.group) + _i32(self.generation) +
                _string(self.member_id) + _i64(-1) +
                _i32(1) + _string(self.bus.topic) +
                _i32(1) + _i32(partition % self.bus.n_partitions) +
                _i64(offset) + _string(None))
        for attempt in (0, 1):
            r = _R(self.bus.group_request(self.group, 8, 2, body))
            try:
                for _t in range(r.i32()):
                    r.string()
                    for _p in range(r.i32()):
                        r.i32()
                        _check(r.i16(), "group offset commit")
                return
            except KafkaError as e:
                # coordinator moved: per-partition NOT_COORDINATOR —
                # re-discover and retry once (same healing bus.commit has)
                if attempt or e.code not in _STALE_COORD:
                    raise
                self.bus._coord_conn(self.group, force=True)

    def committed(self, partition: int) -> int:
        return self.bus.committed(self.group, partition)


__all__ = ["KafkaBus", "KafkaError", "ConsumerGroup", "crc32c",
           "encode_record_batch", "decode_record_batches"]
