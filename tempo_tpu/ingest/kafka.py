"""Kafka wire-protocol client: the external half of pkg/ingest.

The reference's ingest-storage path speaks to real Kafka through franz-go
(`pkg/ingest/writer_client.go:168-325`, `reader_client.go`); the
in-memory `Bus` covered only the testkafka half. This is an SDK-free
client of the Kafka binary protocol — the subset the bus seam needs:

- Metadata v1 (broker list + per-partition leaders)
- Produce v3 with v2 RecordBatches (varint records, CRC32C integrity)
- Fetch v4 (record batches decoded back into `Record`s)
- FindCoordinator v1 (consumer-group coordinator discovery)
- OffsetCommit v2 / OffsetFetch v1 (consumer-group offsets)
- ListOffsets v1 (high watermark)

Requests route to the PARTITION LEADER (produce/fetch) or the GROUP
COORDINATOR (offsets) from a cached metadata map, refreshed once on
NOT_LEADER/NOT_COORDINATOR class errors before the retry — the franz-go
behavior (`writer_client.go:168-325`) a multi-broker cluster requires;
against a single broker the bootstrap connection answers everything.

`KafkaBus` exposes the same surface as `ingest.bus.Bus`, so the
blockbuilder and the generator's consume loop run unchanged against a
real broker (or the signature-verifying mock in tests — the minio-style
pattern used for S3/Azure). Tenant rides the record KEY, as the
reference encodes it.
"""

from __future__ import annotations

import socket
import struct
import threading

from tempo_tpu.ingest.bus import Record

# -- crc32c (Castagnoli), table-based ---------------------------------------

_CRC_TABLE: list[int] = []


def _crc_init() -> None:
    poly = 0x82F63B78
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_crc_init()


def crc32c(data: bytes) -> int:
    from tempo_tpu import native

    got = native.crc32c(data)       # C++ table (~GB/s); the python loop
    if got is not None:             # below is the no-native fallback
        return got
    crc = 0xFFFFFFFF
    tab = _CRC_TABLE
    for b in data:
        crc = tab[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- primitive encoders -----------------------------------------------------

def _i8(v: int) -> bytes:
    return struct.pack(">b", v)


def _i16(v: int) -> bytes:
    return struct.pack(">h", v)


def _i32(v: int) -> bytes:
    return struct.pack(">i", v)


def _i64(v: int) -> bytes:
    return struct.pack(">q", v)


def _string(s: "str | None") -> bytes:
    if s is None:
        return _i16(-1)
    b = s.encode()
    return _i16(len(b)) + b


def _bytes(b: "bytes | None") -> bytes:
    if b is None:
        return _i32(-1)
    return _i32(len(b)) + b


def _uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        x = v & 0x7F
        v >>= 7
        if v:
            out.append(x | 0x80)
        else:
            out.append(x)
            return bytes(out)


def _varint(v: int) -> bytes:
    return _uvarint((v << 1) ^ (v >> 63))       # zigzag


class _R:
    __slots__ = ("b", "i")

    def __init__(self, b: bytes):
        self.b = b
        self.i = 0

    def i8(self):
        v = struct.unpack_from(">b", self.b, self.i)[0]; self.i += 1; return v

    def i16(self):
        v = struct.unpack_from(">h", self.b, self.i)[0]; self.i += 2; return v

    def i32(self):
        v = struct.unpack_from(">i", self.b, self.i)[0]; self.i += 4; return v

    def i64(self):
        v = struct.unpack_from(">q", self.b, self.i)[0]; self.i += 8; return v

    def u32(self):
        v = struct.unpack_from(">I", self.b, self.i)[0]; self.i += 4; return v

    def string(self) -> "str | None":
        n = self.i16()
        if n < 0:
            return None
        v = self.b[self.i:self.i + n]; self.i += n
        return v.decode()

    def bytes_(self) -> "bytes | None":
        n = self.i32()
        if n < 0:
            return None
        v = self.b[self.i:self.i + n]; self.i += n
        return v

    def uvarint(self) -> int:
        out = shift = 0
        while True:
            b = self.b[self.i]; self.i += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def varint(self) -> int:
        v = self.uvarint()
        return (v >> 1) ^ -(v & 1)              # un-zigzag


# -- record batches (message format v2) -------------------------------------

def encode_record_batch(base_offset: int, records: "list[tuple[bytes, bytes]]",
                        first_ts_ms: int = 0) -> bytes:
    """One v2 RecordBatch of (key, value) records."""
    recs = bytearray()
    for i, (key, value) in enumerate(records):
        body = (_i8(0) + _varint(0) + _varint(i) +
                _varint(len(key)) + key +
                _varint(len(value)) + value + _uvarint(0))
        recs += _varint(len(body)) + body
    n = len(records)
    after_crc = (_i16(0) +                       # attributes
                 _i32(n - 1) +                   # lastOffsetDelta
                 _i64(first_ts_ms) + _i64(first_ts_ms) +
                 _i64(-1) + _i16(-1) + _i32(-1) +  # producer id/epoch/seq
                 _i32(n) + bytes(recs))
    crc = crc32c(after_crc)
    body = (_i32(0) +                            # partitionLeaderEpoch
            _i8(2) +                             # magic
            struct.pack(">I", crc) + after_crc)
    return _i64(base_offset) + _i32(len(body)) + body


def decode_record_batches(buf: bytes, *, verify_crc: bool = True
                          ) -> "list[tuple[int, bytes, bytes]]":
    """[(offset, key, value)] from concatenated v2 RecordBatches."""
    out = []
    r = _R(buf)
    while r.i + 61 <= len(buf):
        base = r.i64()
        blen = r.i32()
        if r.i + blen > len(buf):
            break                               # truncated trailing batch
        end = r.i + blen
        r.i32()                                 # partitionLeaderEpoch
        magic = r.i8()
        if magic != 2:
            raise ValueError(f"unsupported magic {magic}")
        crc = r.u32()
        if verify_crc and crc32c(buf[r.i:end]) != crc:
            raise ValueError("record batch crc32c mismatch")
        r.i16()                                 # attributes
        r.i32()                                 # lastOffsetDelta
        r.i64(); r.i64()                        # timestamps
        r.i64(); r.i16(); r.i32()               # producer id/epoch/seq
        n = r.i32()
        for _ in range(n):
            r.varint()                          # record length
            r.i8()                              # attributes
            r.varint()                          # timestampDelta
            od = r.varint()
            klen = r.varint()
            key = buf[r.i:r.i + max(klen, 0)]; r.i += max(klen, 0)
            vlen = r.varint()
            value = buf[r.i:r.i + max(vlen, 0)]; r.i += max(vlen, 0)
            for _h in range(r.uvarint()):       # headers
                hk = r.varint(); r.i += max(hk, 0)
                hv = r.varint(); r.i += max(hv, 0)
            out.append((base + od, bytes(key), bytes(value)))
        r.i = end
    return out


# -- connection -------------------------------------------------------------

class _Conn:
    """One broker connection with lazy (re)connect across a bootstrap
    list: a socket fault or stream desync closes the socket and the next
    request redials — one broker restart must not brick the bus for the
    life of the process."""

    def __init__(self, bootstrap: str, client_id: str,
                 timeout_s: float = 10.0):
        self.addrs = []
        for part in bootstrap.split(","):
            host, _, port = part.strip().partition(":")
            if host:
                self.addrs.append((host, int(port or 9092)))
        if not self.addrs:
            raise ValueError(f"no kafka bootstrap address in {bootstrap!r}")
        self.client_id = client_id
        self.timeout = timeout_s
        self.sock: "socket.socket | None" = None
        self._corr = 0
        self._lock = threading.Lock()

    def _connect(self) -> None:
        errs = []
        for host, port in self.addrs:
            try:
                self.sock = socket.create_connection(
                    (host, port), timeout=self.timeout)
                return
            except OSError as e:
                errs.append(e)
        raise ConnectionError(
            f"no kafka broker reachable ({self.addrs}): {errs[-1]}")

    def _reset(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        self.sock = None

    def request(self, api_key: int, api_version: int, body: bytes) -> bytes:
        with self._lock:
            last: Exception | None = None
            for _attempt in (0, 1):      # one transparent redial
                try:
                    return self._request_locked(api_key, api_version, body)
                except (OSError, ConnectionError, RuntimeError) as e:
                    last = e
                    self._reset()        # desynced/dead stream: redial
            raise KafkaError(f"kafka request failed: {last}")

    def _request_locked(self, api_key: int, api_version: int,
                        body: bytes) -> bytes:
        if self.sock is None:
            self._connect()
        self._corr += 1
        corr = self._corr
        msg = (_i16(api_key) + _i16(api_version) + _i32(corr) +
               _string(self.client_id) + body)
        self.sock.sendall(_i32(len(msg)) + msg)
        raw = self._read(4)
        (n,) = struct.unpack(">i", raw)
        resp = self._read(n)
        r = _R(resp)
        got = r.i32()
        if got != corr:
            raise RuntimeError(f"kafka correlation mismatch {got} != {corr}")
        return resp[r.i:]

    def _read(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("kafka broker closed connection")
            out += chunk
        return out

    def close(self) -> None:
        self._reset()


class KafkaError(RuntimeError):
    def __init__(self, msg: str, code: "int | None" = None):
        super().__init__(msg)
        self.code = code


def _check(code: int, what: str) -> None:
    if code != 0:
        raise KafkaError(f"kafka {what} error code {code}", code)


# error classes that mean "your routing map is stale, refresh and retry":
# UNKNOWN_TOPIC_OR_PARTITION(3), LEADER_NOT_AVAILABLE(5),
# NOT_LEADER_FOR_PARTITION(6); COORDINATOR_NOT_AVAILABLE(15),
# NOT_COORDINATOR(16)
_STALE_LEADER = {3, 5, 6}
_STALE_COORD = {15, 16}


class KafkaBus:
    """The `ingest.bus.Bus` surface over a real Kafka cluster."""

    def __init__(self, bootstrap: str, *, topic: str = "tempo-ingest",
                 n_partitions: int = 2, client_id: str = "tempo-tpu",
                 timeout_s: float = 10.0) -> None:
        self.topic = topic
        self.n_partitions = n_partitions
        self._client_id = client_id
        self._timeout = timeout_s
        self._conn = _Conn(bootstrap, client_id, timeout_s)
        self._meta_lock = threading.Lock()
        self._brokers: dict[int, tuple[str, int]] = {}   # node → addr
        self._leaders: dict[int, int] = {}               # partition → node
        self._coord: "tuple[str, int] | None" = None
        self._conns: dict[tuple[str, int], _Conn] = {}

    # -- routing ------------------------------------------------------------

    def _conn_to(self, addr: "tuple[str, int] | None") -> _Conn:
        if addr is None:
            return self._conn
        with self._meta_lock:
            c = self._conns.get(addr)
            if c is None:
                c = self._conns[addr] = _Conn(
                    f"{addr[0]}:{addr[1]}", self._client_id, self._timeout)
        return c

    def refresh_metadata(self) -> None:
        """Metadata v1 → broker addresses + per-partition leaders, asked
        of the bootstrap connection first and then any previously-known
        broker (the bootstrap broker itself may be the dead one). Total
        failure leaves the maps unchanged."""
        for conn in self._candidate_conns():
            try:
                self._refresh_via(conn)
                return
            except Exception:
                continue             # keep old maps; next candidate

    def _candidate_conns(self) -> "list[_Conn]":
        """Bootstrap connection first, then every known broker (deduped
        against the bootstrap address) — shared by metadata refresh and
        coordinator discovery so both heal around any single dead
        broker."""
        with self._meta_lock:
            fallbacks = list(self._brokers.values())
        boot = set(self._conn.addrs)
        return [self._conn] + [self._conn_to(a) for a in fallbacks
                               if a not in boot]

    def _refresh_via(self, conn: _Conn) -> None:
        r = _R(conn.request(3, 1, _i32(1) + _string(self.topic)))
        brokers: dict[int, tuple[str, int]] = {}
        for _b in range(r.i32()):
            nid = r.i32()
            host = r.string() or ""
            port = r.i32()
            r.string()                           # rack
            brokers[nid] = (host, port)
        r.i32()                                  # controller id
        leaders: dict[int, int] = {}
        for _t in range(r.i32()):
            r.i16()                              # topic error
            name = r.string()
            r.i8()                               # is_internal
            for _p in range(r.i32()):
                r.i16()                          # partition error
                pid = r.i32()
                leader = r.i32()
                for _x in range(max(r.i32(), 0)):
                    r.i32()                      # replicas
                for _x in range(max(r.i32(), 0)):
                    r.i32()                      # isr
                if name == self.topic:
                    leaders[pid] = leader
        with self._meta_lock:
            self._brokers = brokers
            self._leaders = leaders

    def _leader_conn(self, partition: int) -> _Conn:
        with self._meta_lock:
            known = partition in self._leaders
        if not known:
            self.refresh_metadata()
        with self._meta_lock:
            addr = self._brokers.get(self._leaders.get(partition, -1))
        return self._conn_to(addr)

    def _coord_conn(self, group: str, force: bool = False) -> _Conn:
        with self._meta_lock:
            addr = self._coord
        if addr is None or force:
            addr = None
            # same candidate order as refresh_metadata: the bootstrap
            # broker may be the dead one (blockbuilder offsets survive)
            for conn in self._candidate_conns():
                try:
                    r = _R(conn.request(10, 1, _string(group) + _i8(0)))
                    r.i32()                      # throttle
                    err = r.i16()
                    r.string()                   # error message
                    r.i32()                      # coordinator node id
                    host = r.string() or ""
                    port = r.i32()
                    if err == 0:
                        addr = (host, port)
                        break
                except Exception:
                    continue
            with self._meta_lock:
                self._coord = addr
        return self._conn_to(addr)

    # -- produce ------------------------------------------------------------

    def produce(self, partition: int, tenant: str, value: bytes) -> int:
        partition %= self.n_partitions
        batch = encode_record_batch(0, [(tenant.encode(), value)])
        body = (_string(None) + _i16(-1) + _i32(30_000) +   # acks=all
                _i32(1) + _string(self.topic) +
                _i32(1) + _i32(partition) + _bytes(batch))
        for attempt in (0, 1):
            try:
                return self._produce_once(self._leader_conn(partition), body)
            except KafkaError as e:
                # code=None = connection-level failure (dead broker): the
                # leader may have MOVED — remap before giving up, else a
                # crashed leader bricks its partitions forever
                if attempt or (e.code is not None
                               and e.code not in _STALE_LEADER):
                    raise
                self.refresh_metadata()          # stale leader: remap once
        raise AssertionError("unreachable")

    def _produce_once(self, conn: _Conn, body: bytes) -> int:
        r = _R(conn.request(0, 3, body))
        base = -1
        for _t in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                r.i32()                          # partition
                _check(r.i16(), "produce")
                base = r.i64()
                r.i64()                          # log append time
        r.i32()                                  # throttle
        if base < 0:
            raise KafkaError("produce: no partition response")
        return base

    # -- fetch --------------------------------------------------------------

    def _fetch_raw(self, partition: int, offset: int,
                   max_bytes: int = 1 << 20) -> tuple[bytes, int]:
        body = (_i32(-1) + _i32(200) + _i32(1) + _i32(max_bytes) +
                _i8(0) +                         # isolation: read uncommitted
                _i32(1) + _string(self.topic) +
                _i32(1) + _i32(partition) + _i64(offset) + _i32(max_bytes))
        for attempt in (0, 1):
            try:
                return self._fetch_once(self._leader_conn(partition), body)
            except KafkaError as e:
                if attempt or (e.code is not None
                               and e.code not in _STALE_LEADER):
                    raise
                self.refresh_metadata()          # incl. dead-broker remap
        raise AssertionError("unreachable")

    def _fetch_once(self, conn: _Conn, body: bytes) -> tuple[bytes, int]:
        r = _R(conn.request(1, 4, body))
        r.i32()                                  # throttle
        batches = b""
        hw = 0
        for _t in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                r.i32()                          # partition
                _check(r.i16(), "fetch")
                hw = r.i64()
                r.i64()                          # last stable offset
                for _a in range(max(r.i32(), 0)):   # aborted txns
                    r.i64(); r.i64()
                batches = r.bytes_() or b""
        return batches, hw

    def fetch(self, partition: int, offset: int, max_records: int = 100
              ) -> list[Record]:
        partition %= self.n_partitions
        max_bytes = 1 << 20
        while True:
            batches, hw = self._fetch_raw(partition, offset, max_bytes)
            out = []
            for off, key, value in decode_record_batches(batches):
                if off < offset:
                    continue                     # batch overlaps the ask
                out.append(Record(off, key.decode("utf-8", "replace"),
                                  value))
                if len(out) >= max_records:
                    break
            if out or hw <= offset or not batches:
                return out
            # data exists but one batch exceeds max_bytes (truncated by
            # the broker): grow and retry instead of livelocking the
            # partition at this offset forever
            if max_bytes >= 1 << 26:
                raise KafkaError(
                    f"record batch at {self.topic}/{partition}@{offset} "
                    f"exceeds {max_bytes} bytes")
            max_bytes *= 8

    # -- offsets ------------------------------------------------------------

    def commit(self, group: str, partition: int, offset: int) -> None:
        body = (_string(group) + _i32(-1) + _string("") +
                _i64(-1) +                       # retention
                _i32(1) + _string(self.topic) +
                _i32(1) + _i32(partition % self.n_partitions) +
                _i64(offset) + _string(None))
        for attempt in (0, 1):
            try:
                r = _R(self._coord_conn(group, force=bool(attempt))
                       .request(8, 2, body))
                for _t in range(r.i32()):
                    r.string()
                    for _p in range(r.i32()):
                        r.i32()
                        _check(r.i16(), "offset commit")
                return
            except KafkaError as e:
                if attempt or (e.code is not None
                               and e.code not in _STALE_COORD):
                    raise                        # retry re-finds coordinator
        raise AssertionError("unreachable")

    def committed(self, group: str, partition: int) -> int:
        body = (_string(group) + _i32(1) + _string(self.topic) +
                _i32(1) + _i32(partition % self.n_partitions))
        for attempt in (0, 1):
            try:
                r = _R(self._coord_conn(group, force=bool(attempt))
                       .request(9, 1, body))
                off = 0
                for _t in range(r.i32()):
                    r.string()
                    for _p in range(r.i32()):
                        r.i32()
                        off = r.i64()
                        r.string()               # metadata
                        _check(r.i16(), "offset fetch")
                return max(off, 0)               # -1 = no commit yet
            except KafkaError as e:
                if attempt or (e.code is not None
                               and e.code not in _STALE_COORD):
                    raise                        # retry re-finds coordinator
        raise AssertionError("unreachable")

    def high_watermark(self, partition: int) -> int:
        _b, hw = self._fetch_raw(partition % self.n_partitions, 0,
                                 max_bytes=64)
        return hw

    def lag(self, group: str, partition: int) -> int:
        return self.high_watermark(partition) - self.committed(group, partition)

    def close(self) -> None:
        self._conn.close()
        with self._meta_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()


__all__ = ["KafkaBus", "KafkaError", "crc32c",
           "encode_record_batch", "decode_record_batches"]
