"""In-memory partitioned record log with consumer-group offsets."""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass
class Record:
    offset: int
    tenant: str
    value: bytes


class Bus:
    """N partitions of (tenant, bytes) records; committed offsets per
    (group, partition). Thread-safe."""

    def __init__(self, n_partitions: int = 2) -> None:
        self.n_partitions = n_partitions
        self._logs: list[list[Record]] = [[] for _ in range(n_partitions)]
        self._commits: dict[tuple[str, int], int] = {}
        self._lock = threading.Lock()

    def produce(self, partition: int, tenant: str, value: bytes) -> int:
        with self._lock:
            log = self._logs[partition % self.n_partitions]
            rec = Record(len(log), tenant, value)
            log.append(rec)
            return rec.offset

    def fetch(self, partition: int, offset: int, max_records: int = 100
              ) -> list[Record]:
        with self._lock:
            log = self._logs[partition % self.n_partitions]
            return log[offset: offset + max_records]

    def commit(self, group: str, partition: int, offset: int) -> None:
        """Commit = next offset to consume (kafka semantics)."""
        with self._lock:
            self._commits[(group, partition)] = offset

    def committed(self, group: str, partition: int) -> int:
        with self._lock:
            return self._commits.get((group, partition), 0)

    def high_watermark(self, partition: int) -> int:
        with self._lock:
            return len(self._logs[partition % self.n_partitions])

    def lag(self, group: str, partition: int) -> int:
        return self.high_watermark(partition) - self.committed(group, partition)
