"""The querier service.

Read-side counterpart of the distributor: resolves the trace's replication
set on the ring, requires quorum successful responses
(`forIngesterRings` `querier.go:318`), merges ingester recent data with
backend blocks (tempodb), and executes frontend-sharded block jobs.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Protocol, Sequence

import numpy as np

from tempo_tpu.backend.meta import BlockMeta
from tempo_tpu.db.tempodb import TempoDB
from tempo_tpu.model.combine import combine_spans, sort_spans
from tempo_tpu.obs import Registry
from tempo_tpu.obs import querystats
from tempo_tpu.ops.hashing import token_for
from tempo_tpu.overrides import Overrides
from tempo_tpu.ring import Ring
from tempo_tpu.traceql.engine import MetadataCombiner
from tempo_tpu.utils import tracing


class IngesterQueryClient(Protocol):
    def find_trace_by_id(self, tenant: str, trace_id: bytes) -> list[dict] | None: ...
    def search(self, tenant: str, query: str, limit: int = 20,
               start_s: float = 0, end_s: float = 0): ...
    def tag_names(self, tenant: str) -> dict[str, list[str]]: ...


@dataclasses.dataclass
class QuerierConfig:
    rf: int = 3
    query_mode_all: bool = True     # ingesters + blocks (QueryModeAll)


class Querier:
    def __init__(self, db: TempoDB,
                 ingester_ring: Ring | None = None,
                 ingester_clients: dict[str, IngesterQueryClient] | None = None,
                 overrides: Overrides | None = None,
                 cfg: QuerierConfig | None = None,
                 registry: Registry | None = None,
                 now: Callable[[], float] = time.time) -> None:
        self.db = db
        self.ring = ingester_ring
        self.clients = ingester_clients or {}
        self.overrides = overrides or Overrides()
        self.cfg = cfg or QuerierConfig()
        self.now = now
        self.obs = registry if registry is not None else Registry()
        self.block_scan_duration = self.obs.histogram(
            "tempo_querier_block_scan_duration_seconds",
            "One frontend-sharded backend block job, by op "
            "(search or metrics)", labels=("op",))

    # -- trace by id -------------------------------------------------------

    def find_trace_by_id(self, tenant: str, trace_id: bytes,
                         start_s: float | None = None,
                         end_s: float | None = None) -> list[dict] | None:
        """Quorum read across the trace's replication set + backend blocks;
        results combined/deduped (RF3 write → spans appear ≤3 times)."""
        parts: list[list[dict]] = []
        if self.ring is not None and self.clients:
            mat = np.frombuffer(trace_id.ljust(16, b"\0")[:16], np.uint8)[None, :]
            token = int(token_for(tenant, mat)[0])
            rs = self.ring.get(token, self.cfg.rf)
            failures = 0
            for inst in rs.instances:
                try:
                    spans = self.clients[inst.id].find_trace_by_id(tenant, trace_id)
                except Exception:
                    failures += 1
                    if failures > rs.max_errors:
                        raise
                    continue
                if spans:
                    parts.append(spans)
        if self.cfg.query_mode_all:
            spans = self.db.find_trace_by_id(tenant, trace_id, start_s, end_s)
            if spans:
                parts.append(spans)
        if not parts:
            return None
        return sort_spans(combine_spans(*parts))

    # -- search ------------------------------------------------------------

    def search_recent(self, tenant: str, query: str, limit: int = 20,
                      start_s: float = 0, end_s: float = 0):
        """Fan search to every healthy ingester; merge top-N metadata.
        (Search fans to all ingesters — any of them may hold any trace's
        replicas; quorum applies per-ring-health not per-result.)"""
        combiner = MetadataCombiner(limit)
        if self.ring is None:
            return []
        for inst in self.ring.healthy_instances():
            client = self.clients.get(inst.id)
            if client is None:
                continue
            for md in client.search(tenant, query, limit, start_s, end_s):
                combiner.add(md)
        return combiner.results()

    def search_block(self, tenant: str, query: str, meta: BlockMeta,
                     row_groups: Sequence[int] | None = None,
                     limit: int = 20,
                     start_s: float | None = None, end_s: float | None = None):
        """One frontend-sharded backend job (`SearchBlock` `querier.go:780`)."""
        t0 = time.perf_counter()
        querystats.add(blocks_scanned=1)
        try:
            with tracing.span_for_tenant(
                    "querier.SearchBlock", tenant,
                    block_id=str(meta.block_id),
                    row_groups=len(row_groups) if row_groups else 0):
                return self.db.search(tenant, query, limit=limit,
                                      start_s=start_s, end_s=end_s,
                                      metas=[meta], row_groups=row_groups)
        finally:
            self.block_scan_duration.observe(time.perf_counter() - t0,
                                             ("search",))

    def query_range_block(self, tenant: str, req, meta: BlockMeta,
                          row_groups: Sequence[int] | None = None,
                          clip_start_ns: int | None = None,
                          clip_end_ns: int | None = None):
        """One metrics job: raw evaluator over a block slice; job-level
        series to be combined at the frontend (AggregateModeSum)."""
        t0 = time.perf_counter()
        querystats.add(blocks_scanned=1)
        try:
            with tracing.span_for_tenant(
                    "querier.QueryRangeBlock", tenant,
                    block_id=str(meta.block_id),
                    row_groups=len(row_groups) if row_groups else 0):
                return self.db.query_range(tenant, req, metas=[meta],
                                           row_groups=row_groups,
                                           clip_start_ns=clip_start_ns,
                                           clip_end_ns=clip_end_ns)
        finally:
            self.block_scan_duration.observe(time.perf_counter() - t0,
                                             ("metrics",))

    # -- tags --------------------------------------------------------------

    def tag_names(self, tenant: str, scopes: Sequence[str] = ("span", "resource"),
                  limit_bytes: int = 0,
                  on_partial=None) -> dict[str, list[str]]:
        """`on_partial` (optional) receives the current merged snapshot
        after the ingester pass and after each backend block that
        contributed new names — the incremental feed the streaming
        SearchTags endpoint diffs (`tempo.proto` StreamingQuerier)."""
        out: dict[str, set] = {}

        def snap() -> dict[str, list[str]]:
            return {k: sorted(v) for k, v in out.items()
                    if k in scopes or not scopes}

        if self.ring is not None:
            for inst in self.ring.healthy_instances():
                client = self.clients.get(inst.id)
                if client is None:
                    continue
                for scope, names in client.tag_names(tenant).items():
                    out.setdefault(scope, set()).update(names)
            if on_partial is not None and out:
                on_partial(snap())
        # backend blocks: key-list columns only, under a global byte budget
        from tempo_tpu.block.fetch import block_tag_names
        limit_bytes = limit_bytes or \
            self.overrides.for_tenant(tenant).read.max_bytes_per_tag_values_query
        used = sum(len(n) for names in out.values() for n in names)
        for m in self.db.blocks(tenant):
            if limit_bytes and used >= limit_bytes:
                break
            per_block = block_tag_names(
                self.db.backend_block(m),
                byte_budget=(limit_bytes - used) if limit_bytes else 0)
            grew = False
            for scope, names in per_block.items():
                fresh = names - out.setdefault(scope, set())
                used += sum(len(n) for n in fresh)
                grew = grew or bool(fresh)
                out[scope] |= fresh
            if on_partial is not None and grew:
                on_partial(snap())
        return snap()

    def tag_values(self, tenant: str, name: str, limit: int = 1000,
                   on_partial=None) -> list[dict]:
        """Autocomplete values: ingester recent data + backend block scans,
        deduped (`ExecuteTagValues` fan-out, querier side). `on_partial`
        receives the current snapshot after the ingester pass (the
        streaming SearchTagValues feed)."""
        from tempo_tpu.traceql.engine import execute_tag_values, tag_values_request

        seen: dict[str, dict] = {}
        if self.ring is not None:
            for inst in self.ring.healthy_instances():
                client = self.clients.get(inst.id)
                if client is None or not hasattr(client, "tag_values"):
                    continue
                for v in client.tag_values(tenant, name, limit):
                    seen.setdefault(v["value"], v)
            if on_partial is not None and seen:
                on_partial(list(seen.values())[:limit])
        req = tag_values_request(name)
        # ride the plane cache's retained views when a block is ALREADY
        # resident (autocomplete repeats per keystroke); cold blocks take
        # the projected one-column scan — a metadata endpoint must not
        # trigger full-block reads or evict the query working set
        views = (v for m in self.db.blocks(tenant)
                 for v in self.db.scan_source(m, req, cached_only=True))
        for v in execute_tag_values(name, views, limit=limit):
            seen.setdefault(v["value"], v)
        return list(seen.values())[:limit]
