"""Querier: executes sub-queries against ingesters and backend blocks.

Analog of `modules/querier`: trace-by-id with RF quorum across the
ingester replication set plus backend fan-out (`FindTraceByID`
`querier.go:199`, `forIngesterRings` `querier.go:318`), recent-data search
fan-out, and per-block jobs dispatched by the frontend
(`SearchBlock` `querier.go:780`, query-range `querier_query_range.go`).
"""

from tempo_tpu.querier.querier import Querier, QuerierConfig

__all__ = ["Querier", "QuerierConfig"]
