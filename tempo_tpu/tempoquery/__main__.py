"""tempo-query binary: `python -m tempo_tpu.tempoquery --tempo URL`.

Serves the jaeger.storage.v1 gRPC plugin (cmd/tempo-query analog) so a
Jaeger Query instance can use a tempo_tpu cluster as its span store.
"""

from __future__ import annotations

import argparse
import time


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser("tempo_tpu.tempoquery")
    ap.add_argument("--tempo", required=True, help="tempo_tpu base URL")
    ap.add_argument("--tenant", default="")
    ap.add_argument("--listen", default="0.0.0.0:7777")
    args = ap.parse_args(argv)
    from tempo_tpu.tempoquery import build_tempo_query_server
    server, port = build_tempo_query_server(
        args.tempo, tenant=args.tenant, address=args.listen)
    print(f"tempo-query plugin serving jaeger.storage.v1 on port {port} "
          f"→ {args.tempo}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop(0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
