from tempo_tpu.tempoquery.plugin import build_tempo_query_server

__all__ = ["build_tempo_query_server"]
