"""tempo-query: the Jaeger storage gRPC plugin analog.

The reference's `cmd/tempo-query` bridges Jaeger Query (the UI backend)
to Tempo's HTTP API by implementing the `jaeger.storage.v1` SpanReader
gRPC plugin (`cmd/tempo-query/main.go`, tempo/plugin.go). Same bridge
here: a gRPC server exposing

  jaeger.storage.v1.SpanReaderPlugin/ GetTrace | FindTraces |
      GetServices | GetOperations
  jaeger.storage.v1.DependenciesReaderPlugin/ GetDependencies

backed by `tempo_tpu.client.Client` against any tempo_tpu HTTP endpoint.
Requests/responses are the public jaeger proto shapes (storage_v1 +
api_v2 model.proto), hand-rolled on the proto_wire codec like the rest
of the framework's wire layer.
"""

from __future__ import annotations

from concurrent import futures

import grpc

from tempo_tpu.client import Client
from tempo_tpu.model import proto_wire as pw

_SVC = "jaeger.storage.v1.SpanReaderPlugin"
_DEP = "jaeger.storage.v1.DependenciesReaderPlugin"


def _ident(b):
    return b


# -- jaeger api_v2 model encoding (model.proto) -----------------------------

def _ts(ns: int) -> bytes:
    """google.protobuf.Timestamp{seconds=1, nanos=2}."""
    return (pw.enc_field_varint(1, ns // 1_000_000_000) +
            pw.enc_field_varint(2, ns % 1_000_000_000))


def _dur(ns: int) -> bytes:
    return (pw.enc_field_varint(1, ns // 1_000_000_000) +
            pw.enc_field_varint(2, ns % 1_000_000_000))


def _kv_str(key: str, v) -> bytes:
    """jaeger KeyValue{key=1, vType=2, vStr=3|vBool=4|vInt64=5|vFloat64=6}."""
    out = pw.enc_field_str(1, key)
    if isinstance(v, bool):
        out += pw.enc_field_varint(2, 1) + pw.enc_field_varint(4, 1 if v else 0)
    elif isinstance(v, int):
        out += pw.enc_field_varint(2, 2) + pw.enc_field_varint(
            5, v & ((1 << 64) - 1))
    elif isinstance(v, float):
        out += pw.enc_field_varint(2, 3) + pw.enc_field_double(6, v)
    else:
        out += pw.enc_field_str(3, str(v))
    return out


def _jaeger_span(s: dict, tid: bytes) -> bytes:
    """One api_v2 model.Span from a tempo span dict (the inverse of the
    receiver's translation)."""
    start = int(s.get("start_unix_nano", 0))
    dur = max(int(s.get("end_unix_nano", 0)) - start, 0)
    out = (pw.enc_field_bytes(1, tid.rjust(16, b"\0")) +
           pw.enc_field_bytes(2, _hexb(s.get("span_id", ""), 8)) +
           pw.enc_field_str(3, s.get("name", "")) +
           pw.enc_field_msg(6, _ts(start)) +
           pw.enc_field_msg(7, _dur(dur)))
    kind = int(s.get("kind", 0))
    kind_str = {1: "internal", 2: "server", 3: "client",
                4: "producer", 5: "consumer"}.get(kind)
    if kind_str:
        out += pw.enc_field_msg(8, _kv_str("span.kind", kind_str))
    if int(s.get("status_code", 0)) == 2:
        out += pw.enc_field_msg(8, _kv_str("error", True))
    for k, v in (s.get("attrs") or {}).items():
        out += pw.enc_field_msg(8, _kv_str(k, v))
    psid = _hexb(s.get("parent_span_id", ""), 8)
    if psid.strip(b"\0"):
        # references[4]: SpanRef{trace_id=1, span_id=2, ref_type=3}
        out += pw.enc_field_msg(4, pw.enc_field_bytes(1, tid.rjust(16, b"\0"))
                                + pw.enc_field_bytes(2, psid)
                                + pw.enc_field_varint(3, 0))
    # process[10]: Process{service_name=1, tags=2}
    proc = pw.enc_field_str(1, str(s.get("service", "")))
    for k, v in (s.get("res_attrs") or {}).items():
        if k != "service.name":
            proc += pw.enc_field_msg(2, _kv_str(k, v))
    out += pw.enc_field_msg(10, proc)
    return out


def _hexb(v, width: int) -> bytes:
    if isinstance(v, bytes):
        return v.ljust(width, b"\0")[:width]
    try:
        return bytes.fromhex(v).ljust(width, b"\0")[:width]
    except (ValueError, TypeError):
        return b"\0" * width


def _chunk(spans: list[bytes]) -> bytes:
    """SpansResponseChunk{repeated Span spans = 1}."""
    return b"".join(pw.enc_field_msg(1, sp) for sp in spans)


class _Plugin:
    def __init__(self, client: Client):
        self.c = client

    # GetTrace(GetTraceRequest{trace_id=1 bytes}) -> stream chunks
    def get_trace(self, request: bytes, context):
        import urllib.error

        d = pw.decode_fields(request)
        tid = bytes(d.get(1, [b""])[0])
        try:
            trace = self.c.trace_by_id(tid.hex())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                context.abort(grpc.StatusCode.NOT_FOUND, "trace not found")
            raise
        spans = trace.get("spans") or []
        if not spans:
            context.abort(grpc.StatusCode.NOT_FOUND, "trace not found")
        yield _chunk([_jaeger_span(sp, tid) for sp in spans])

    # GetServices() -> {services: repeated string 1}
    def get_services(self, request: bytes, context) -> bytes:
        vals = self.c.search_tag_values("resource.service.name")
        names = sorted({v.get("value", v) if isinstance(v, dict) else v
                        for v in vals.get("tagValues", [])})
        return b"".join(pw.enc_field_str(1, str(n)) for n in names)

    # GetOperations(req{service=1}) -> {operations 2: Operation{name=1}}
    def get_operations(self, request: bytes, context) -> bytes:
        d = pw.decode_fields(request)
        svc = bytes(d[1][0]).decode("utf-8", "replace") if 1 in d else ""
        if svc:
            # per-service operations: names of recent spans of that service
            # (the tag-values endpoint has no service filter)
            res = self.c.search(
                "{ resource.service.name = " + _tql_str(svc) + " }",
                limit=200)
            names = sorted({sp.get("name", "")
                            for md in res.get("traces", [])
                            for ss in md.get("spanSets", [])
                            for sp in ss.get("spans", [])} - {""})
        else:
            vals = self.c.search_tag_values("name")
            names = sorted({v.get("value", v) if isinstance(v, dict) else v
                            for v in vals.get("tagValues", [])})
        out = b""
        for n in names:
            out += pw.enc_field_str(1, str(n))              # operationNames
            out += pw.enc_field_msg(2, pw.enc_field_str(1, str(n)))
        return out

    # FindTraces(FindTracesRequest{query=1 TraceQueryParameters}) -> stream
    def find_traces(self, request: bytes, context):
        d = pw.decode_fields(request)
        q = pw.decode_fields(bytes(d[1][0])) if 1 in d else {}
        # TraceQueryParameters: service_name=1, operation_name=2, tags=3,
        # start_time_min=4, start_time_max=5, duration_min=6, duration_max=7,
        # num_traces=8
        conds = []
        svc = q.get(1)
        if svc and bytes(svc[0]):
            conds.append("resource.service.name = "
                         + _tql_str(bytes(svc[0]).decode("utf-8", "replace")))
        op = q.get(2)
        if op and bytes(op[0]):
            conds.append(
                "name = " + _tql_str(bytes(op[0]).decode("utf-8", "replace")))
        import re as _re

        for tag in q.get(3, ()):       # map<string,string> entries
            td = pw.decode_fields(bytes(tag))
            k = bytes(td.get(1, [b""])[0]).decode("utf-8", "replace")
            v = bytes(td.get(2, [b""])[0]).decode("utf-8", "replace")
            # the KEY is interpolated bare: restrict it to attribute-name
            # characters so UI input cannot alter the query structure.
            # Unsupported keys REJECT the request — silently dropping a
            # filter would return unfiltered results as if they matched.
            if not k:
                continue
            if not _re.fullmatch(r"[\w.\-/:]+", k):
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"unsupported characters in tag key {k!r}")
            conds.append(f"span.{k} = " + _tql_str(v))
        if 6 in q:                     # duration_min (Duration msg)
            conds.append(f"duration >= {_dur_ns(bytes(q[6][0]))}ns")
        if 7 in q:
            conds.append(f"duration <= {_dur_ns(bytes(q[7][0]))}ns")
        traceql = "{ " + " && ".join(conds) + " }" if conds else "{ }"
        limit = q.get(8, [20])[0] or 20
        start_s = end_s = None
        if 4 in q:
            t = pw.decode_fields(bytes(q[4][0]))
            start_s = t.get(1, [0])[0] + t.get(2, [0])[0] / 1e9
        if 5 in q:
            t = pw.decode_fields(bytes(q[5][0]))
            end_s = t.get(1, [0])[0] + t.get(2, [0])[0] / 1e9
        import urllib.error

        res = self.c.search(traceql, limit=int(limit),
                            start_s=start_s, end_s=end_s)
        for md in res.get("traces", []):
            tid_hex = md.get("traceID", "")
            try:
                trace = self.c.trace_by_id(tid_hex)
            except urllib.error.HTTPError:
                continue        # vanished between search and fetch
            spans = trace.get("spans") or []
            if spans:
                tid = bytes.fromhex(tid_hex)
                yield _chunk([_jaeger_span(sp, tid)
                              for sp in spans])

    # DependenciesReader: service graph edges are a metrics question here;
    # return the empty set like the reference plugin does
    def get_dependencies(self, request: bytes, context) -> bytes:
        return b""


def _tql_str(s: str) -> str:
    """TraceQL string literal with quote/backslash escaping — Jaeger UI
    input must not be able to break out of the query."""
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def _dur_ns(buf: bytes) -> int:
    """google.protobuf.Duration → nanoseconds."""
    d = pw.decode_fields(buf)
    return d.get(1, [0])[0] * 1_000_000_000 + d.get(2, [0])[0]


def build_tempo_query_server(tempo_url: str, tenant: str = "",
                             address: str = "127.0.0.1:0",
                             max_workers: int = 8
                             ) -> tuple[grpc.Server, int]:
    """Start the plugin gRPC server; returns (server, bound_port)."""
    plugin = _Plugin(Client(tempo_url, tenant=tenant))
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))

    def unary(fn):
        return grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=_ident, response_serializer=_ident)

    def sstream(fn):
        return grpc.unary_stream_rpc_method_handler(
            fn, request_deserializer=_ident, response_serializer=_ident)

    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
        _SVC, {
            "GetTrace": sstream(plugin.get_trace),
            "FindTraces": sstream(plugin.find_traces),
            "GetServices": unary(plugin.get_services),
            "GetOperations": unary(plugin.get_operations),
        }),))
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
        _DEP, {"GetDependencies": unary(plugin.get_dependencies)}),))
    port = server.add_insecure_port(address)
    server.start()
    return server, port
