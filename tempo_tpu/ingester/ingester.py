"""The ingester service: tenant instances + flush machinery + replay.

Analog of `modules/ingester/ingester.go` + `flush.go`: a push entry point
(`PushBytesV2` `ingester.go:301`), a periodic cut loop (`cutToWalLoop`
`flush.go:142`), two-phase flush ops (opKindComplete → opKindFlush
`flush.go:70-73`) through deduping retry queues, shutdown flush-all, and
WAL replay on construction.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Sequence

import numpy as np

from tempo_tpu.backend.raw import RawWriter, block_keypath
from tempo_tpu.ingester.instance import InstanceConfig, TenantInstance
from tempo_tpu.obs import Registry
from tempo_tpu.overrides import Overrides
from tempo_tpu.utils.flushqueues import FlushQueues, backoff_at

OP_COMPLETE = "complete"
OP_FLUSH = "flush"


@dataclasses.dataclass
class IngesterConfig:
    instance: InstanceConfig = dataclasses.field(default_factory=InstanceConfig)
    concurrent_flushes: int = 4
    flush_check_period_s: float = 10.0
    complete_block_timeout_s: float = 900.0   # keep local 15m after flush
    max_flush_attempts: int = 10
    flush_backoff_base_s: float = 30.0


@dataclasses.dataclass
class _FlushOp:
    kind: str
    tenant: str
    block_id: str
    attempts: int = 0
    wal_block: object = None


class Ingester:
    def __init__(self, data_dir: str,
                 flush_writer: RawWriter | None = None,
                 cfg: IngesterConfig | None = None,
                 overrides: Overrides | None = None,
                 now: Callable[[], float] = time.time,
                 instance_id: str = "ingester-0",
                 registry: Registry | None = None) -> None:
        self.cfg = cfg or IngesterConfig()
        self.overrides = overrides or Overrides()
        self.now = now
        self.id = instance_id
        self.wal_root = os.path.join(data_dir, "wal")
        self.local_root = os.path.join(data_dir, "blocks")
        self.flush_writer = flush_writer
        self.instances: dict[str, TenantInstance] = {}
        self.lock = threading.RLock()
        self.queues = FlushQueues(self.cfg.concurrent_flushes, now=now)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.obs = registry if registry is not None else Registry()
        self._register_obs(self.obs)
        self.replay()

    def _register_obs(self, reg: Registry) -> None:
        def live():
            with self.lock:
                insts = dict(self.instances)
            return [((t,), len(inst.live)) for t, inst in insts.items()]

        def discarded():
            with self.lock:
                insts = dict(self.instances)
            return [((t, r), v) for t, inst in insts.items()
                    for r, v in inst.discarded.items()]

        reg.gauge_func("tempo_ingester_live_traces", live,
                       help="Traces currently held in memory, per tenant",
                       labels=("tenant",))
        reg.counter_func(
            "tempo_ingester_discarded_traces_total", discarded,
            help="Traces rejected by the ingester after the distributor "
                 "accepted them, by tenant and reason",
            labels=("tenant", "reason"))
        self.cut_duration = reg.histogram(
            "tempo_ingester_cut_duration_seconds",
            "One cut sweep for a tenant: idle-trace cut plus head-block "
            "seal decision")
        self.flush_duration = reg.histogram(
            "tempo_ingester_flush_duration_seconds",
            "One flush-queue operation, by kind (complete = WAL to local "
            "block; flush = local block to object storage)",
            labels=("op",))

    # -- instances ---------------------------------------------------------

    def instance(self, tenant: str) -> TenantInstance:
        with self.lock:
            inst = self.instances.get(tenant)
            if inst is None:
                inst = self.instances[tenant] = TenantInstance(
                    tenant,
                    wal_dir=self.wal_root,
                    local_dir=self.local_root,
                    cfg=self.cfg.instance,
                    limits=self.overrides.for_tenant(tenant),
                    now=self.now)
            return inst

    # -- write -------------------------------------------------------------

    def push(self, tenant: str,
             traces: Sequence[tuple[bytes, list[dict]]]) -> list[str | None]:
        """Push (trace_id, spans) groups; returns a per-trace error reason
        (or None) aligned with the input — the PushResponse error slice of
        `PushBytesV2`, letting the distributor dedupe reasons across
        replicas instead of summing them RF times."""
        inst = self.instance(tenant)
        return [inst.push_trace(tid, spans) for tid, spans in traces]

    def push_otlp(self, tenant: str, payload: bytes) -> dict[str, str]:
        """OTLP wire-slice push (the columnar distributor's PushBytesV2
        shape: raw proto per replica, unmarshalled HERE — as the reference
        ingester unmarshals trace bytes). Returns {trace_id_hex: reason}
        for rejected traces only."""
        from tempo_tpu import native
        from tempo_tpu.model.otlp import spans_from_otlp_proto

        spans = native.spans_from_otlp_proto_native(payload)
        if spans is None:
            spans = list(spans_from_otlp_proto(payload))
        by_tid: dict[bytes, list[dict]] = {}
        for s in spans:
            by_tid.setdefault(s["trace_id"], []).append(s)
        inst = self.instance(tenant)
        out: dict[str, str] = {}
        for tid, group in by_tid.items():
            reason = inst.push_trace(tid, group)
            if reason:
                out[tid.hex()] = reason
        return out

    def push_staged(self, tenant: str, view) -> dict[str, str]:
        """Staged-view push (the decode-once distributor tee): this
        replica's traces arrive as a row-index slice over the shared
        columnar staging (`model.otlp_batch.StagedView`) — live-trace
        groups come straight off the trace-id column and span dicts
        convert from the staged columns, with events/links restored from
        the staging's one lazy payload pass. No per-replica protobuf
        re-decode. Same return contract as `push_otlp`:
        {trace_id_hex: reason} for rejected traces only."""
        inst = self.instance(tenant)
        out: dict[str, str] = {}
        for tid, rows in view.trace_groups():
            reason = inst.push_trace(tid, view.to_span_dicts(rows))
            if reason:
                out[tid.hex()] = reason
        return out

    # -- cut/flush machinery ----------------------------------------------

    def sweep_instance(self, tenant: str, immediate: bool = False) -> None:
        """One cut tick for a tenant (`sweepInstance` flush.go:142):
        cut idle traces, maybe seal head, enqueue completion."""
        t0 = time.perf_counter()
        inst = self.instance(tenant)
        inst.cut_complete_traces(immediate=immediate)
        sealed = inst.cut_block_if_ready(immediate=immediate)
        self.cut_duration.observe(time.perf_counter() - t0)
        if sealed is not None:
            self.queues.enqueue(
                f"{tenant}/{sealed.block_id}",
                _FlushOp(OP_COMPLETE, tenant, sealed.block_id, wal_block=sealed))

    def sweep_all(self, immediate: bool = False) -> None:
        with self.lock:
            tenants = list(self.instances)
        for t in tenants:
            self.sweep_instance(t, immediate=immediate)

    def _handle_op(self, key: str, op: _FlushOp) -> bool:
        t0 = time.perf_counter()
        try:
            return self._handle_op_inner(key, op)
        finally:
            self.flush_duration.observe(time.perf_counter() - t0,
                                        (op.kind,))

    def _handle_op_inner(self, key: str, op: _FlushOp) -> bool:
        inst = self.instance(op.tenant)
        try:
            if op.kind == OP_COMPLETE:
                if op.wal_block is not None:
                    inst.complete_block(op.wal_block)
                # chain to flush (two-phase, `flush.go:264-364`)
                self.queues.done(key)
                self.queues.enqueue(f"{key}/flush",
                                    _FlushOp(OP_FLUSH, op.tenant, op.block_id))
                return True
            # OP_FLUSH: copy the completed local block to object storage
            if self.flush_writer is not None:
                entry = inst.complete.get(op.block_id)
                if entry is None:
                    self.queues.done(key)
                    return True
                _copy_block_files(inst, op.block_id, self.flush_writer)
            inst.mark_flushed(op.block_id)
            self.queues.done(key)
            return True
        except Exception:
            op.attempts += 1
            if op.attempts >= self.cfg.max_flush_attempts:
                self.queues.done(key)   # abandon (`flush.go` op abandonment)
                return False
            self.queues.requeue(key, op, backoff_at(
                self.now(), op.attempts, self.cfg.flush_backoff_base_s))
            return False

    def flush_tick(self, queue_idx: int | None = None) -> int:
        """Drain due ops (one queue when an index is given — the per-worker
        loop — or all queues until quiescent, for tests/manual ticks: an
        OP_COMPLETE chains an OP_FLUSH that may hash to any queue, so a
        single pass is not enough)."""
        n = 0
        if queue_idx is not None:
            while True:
                got = self.queues.dequeue(queue_idx)
                if got is None:
                    return n
                self._handle_op(*got)
                n += 1
        progressed = True
        while progressed:
            progressed = False
            for qi in range(self.cfg.concurrent_flushes):
                while True:
                    got = self.queues.dequeue(qi)
                    if got is None:
                        break
                    self._handle_op(*got)
                    n += 1
                    progressed = True
        return n

    def flush_all(self) -> None:
        """/flush + shutdown behavior: cut everything, complete, flush."""
        self.sweep_all(immediate=True)
        self.queues.drain(self._handle_op)
        # completion enqueues flush ops; drain those too
        self.queues.drain(self._handle_op)

    # -- read path (recent data, `instance_search.go`) ---------------------

    def find_trace_by_id(self, tenant: str, trace_id: bytes) -> list[dict] | None:
        with self.lock:
            if tenant not in self.instances:
                return None
        return self.instance(tenant).find_trace_by_id(trace_id)

    def search(self, tenant: str, query: str, limit: int = 20,
               start_s: float = 0, end_s: float = 0):
        """TraceQL over live+WAL data (in-memory ColumnView) and local
        complete blocks — the ingester side of querier fan-out."""
        from tempo_tpu.block.fetch import scan_views
        from tempo_tpu.traceql.engine import compile_query, execute_search
        from tempo_tpu.traceql.memview import view_from_traces

        with self.lock:
            if tenant not in self.instances:
                return []
        inst = self.instance(tenant)
        q, req = compile_query(query, int(start_s * 1e9), int(end_s * 1e9))

        def views():
            traces = inst.all_recent_traces()
            if traces:
                v = view_from_traces(traces)
                yield v, np.arange(v.n)
            for b in inst.complete_blocks():
                yield from scan_views(b, req)

        return execute_search(q, views(), limit=limit,
                              start_ns=int(start_s * 1e9),
                              end_ns=int(end_s * 1e9))

    def tag_names(self, tenant: str) -> dict[str, list[str]]:
        from tempo_tpu.block.fetch import block_tag_names
        from tempo_tpu.traceql.engine import execute_tag_names
        from tempo_tpu.traceql.memview import view_from_traces

        with self.lock:
            if tenant not in self.instances:
                return {}
        inst = self.instance(tenant)
        traces = inst.all_recent_traces()
        out: dict[str, set] = {"span": set(), "resource": set()}
        if traces:
            v = view_from_traces(traces)
            for scope, names in execute_tag_names([(v, np.arange(v.n))]).items():
                out.setdefault(scope, set()).update(names)
        for b in inst.complete_blocks():
            for scope, names in block_tag_names(b).items():
                out.setdefault(scope, set()).update(names)
        return {k: sorted(v) for k, v in out.items()}

    def tag_values(self, tenant: str, name: str, limit: int = 1000) -> list[dict]:
        """Distinct values of one attribute over live+WAL data and local
        complete blocks (the ingester leg of `ExecuteTagValues`)."""
        from tempo_tpu.block.fetch import scan_views
        from tempo_tpu.traceql.engine import execute_tag_values, tag_values_request
        from tempo_tpu.traceql.memview import view_from_traces

        with self.lock:
            if tenant not in self.instances:
                return []
        inst = self.instance(tenant)
        req = tag_values_request(name)

        def views():
            traces = inst.all_recent_traces()
            if traces:
                v = view_from_traces(traces)
                yield v, np.arange(v.n)
            for b in inst.complete_blocks():
                yield from scan_views(b, req)

        return execute_tag_values(name, views(), limit=limit)

    # -- replay ------------------------------------------------------------

    def replay(self) -> None:
        """Adopt WAL + local complete blocks left by a previous process and
        queue them for (re)completion and flush."""
        if not os.path.isdir(self.wal_root):
            return
        from tempo_tpu.block.wal import rescan_blocks
        for wb in rescan_blocks(self.wal_root):
            inst = self.instance(wb.tenant)
            with inst.lock:
                if wb.block_id not in [b.block_id for b in inst.completing]:
                    inst.completing.append(wb)
            self.queues.enqueue(
                f"{wb.tenant}/{wb.block_id}",
                _FlushOp(OP_COMPLETE, wb.tenant, wb.block_id, wal_block=wb))
        if os.path.isdir(self.local_root):
            for tenant in os.listdir(self.local_root):
                inst = self.instance(tenant)
                _, n = inst.replay()
                for bid, e in inst.complete.items():
                    if not e.flushed_ts:
                        self.queues.enqueue(f"{tenant}/{bid}/flush",
                                            _FlushOp(OP_FLUSH, tenant, bid))

    # -- loops -------------------------------------------------------------

    def start(self) -> None:
        def cut_loop():
            while not self._stop.wait(self.cfg.flush_check_period_s):
                self.sweep_all()
        def flush_loop(qi: int):
            while not self._stop.wait(1.0):
                self.flush_tick(qi)
        self._threads = [threading.Thread(target=cut_loop, daemon=True)]
        self._threads += [threading.Thread(target=flush_loop, args=(i,), daemon=True)
                          for i in range(self.cfg.concurrent_flushes)]
        for t in self._threads:
            t.start()

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self.flush_all()


def _copy_block_files(inst: TenantInstance, block_id: str, dst: RawWriter) -> None:
    kp = block_keypath(block_id, inst.tenant)
    src = inst.local_backend
    for name in src.find(kp):
        dst.write(name, kp, src.read(name, kp))
