"""Per-tenant ingester instance: live traces → head block → local blocks.

Mirrors `modules/ingester/instance.go`: push with limit enforcement
(`push` `instance.go:199-228` → `PushErrorReason`), complete-trace cutting,
head-block lifecycle, WAL→columnar completion, and recent-data reads
(find/search) across live traces + head + completing + complete blocks.

TPU-first twist: completed blocks are columnar from birth (the parquet
writing path shared with the storage engine), and search over the
in-memory span dicts goes through the same vectorized `ColumnView`
evaluation as block scans — there is no separate row-at-a-time read path.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import uuid
from typing import Callable, Sequence

from tempo_tpu.backend.local import LocalBackend
from tempo_tpu.backend.meta import BlockMeta, read_block_meta
from tempo_tpu.block.reader import BackendBlock
from tempo_tpu.block.wal import WALBlock, rescan_blocks
from tempo_tpu.block.writer import write_block
from tempo_tpu.model.combine import combine_spans, sort_spans
from tempo_tpu.overrides.limits import Limits
from tempo_tpu.utils.livetraces import (
    ERR_LIVE_TRACES_EXCEEDED,
    ERR_TRACE_TOO_LARGE,
    LiveTraceStore,
)

PUSH_ERRORS = (ERR_LIVE_TRACES_EXCEEDED, ERR_TRACE_TOO_LARGE)


@dataclasses.dataclass
class InstanceConfig:
    max_block_duration_s: float = 1800.0   # ingester default 30m
    max_block_bytes: int = 500_000_000
    trace_idle_s: float = 5.0              # trace_idle_period
    trace_live_s: float = 30.0             # max live time before forced cut
    dedicated_columns: tuple = ()
    row_group_rows: int = 50_000
    replication_factor: int = 3            # 1 for generator localblocks


@dataclasses.dataclass
class LocalBlockEntry:
    """A completed, locally owned block (`modules/ingester/local_block.go`):
    flushed_ts tracks backend flush for replay-safe deletion."""
    meta: BlockMeta
    block: BackendBlock
    flushed_ts: float = 0.0


class TenantInstance:
    def __init__(self, tenant: str, wal_dir: str, local_dir: str,
                 cfg: InstanceConfig | None = None,
                 limits: Limits | None = None,
                 now: Callable[[], float] = time.time) -> None:
        self.tenant = tenant
        self.cfg = cfg or InstanceConfig()
        self.now = now
        lim = limits or Limits()
        self.live = LiveTraceStore(
            max_live_traces=lim.ingestion.max_traces_per_user,
            max_trace_bytes=lim.read.max_bytes_per_trace,
            now=now)
        self.wal_dir = wal_dir
        self.local_dir = local_dir
        os.makedirs(wal_dir, exist_ok=True)
        self.local_backend = LocalBackend(local_dir)
        self.head: WALBlock | None = None
        self.head_created = 0.0
        self.completing: list[WALBlock] = []     # cut, awaiting completion
        self.complete: dict[str, LocalBlockEntry] = {}
        self.lock = threading.RLock()
        self.discarded: dict[str, int] = {}

    # -- write path --------------------------------------------------------

    def push_trace(self, trace_id: bytes, spans: Sequence[dict],
                   size_bytes: int | None = None) -> str | None:
        """Append one trace's spans; returns a PushErrorReason or None."""
        with self.lock:
            err = self.live.push(trace_id, spans, size_bytes)
            if err:
                self.discarded[err] = self.discarded.get(err, 0) + 1
            return err

    def cut_complete_traces(self, immediate: bool = False) -> int:
        """Idle/aged live traces → head WAL block (`CutCompleteTraces`)."""
        with self.lock:
            cut = self.live.cut(idle_s=self.cfg.trace_idle_s,
                                max_age_s=self.cfg.trace_live_s,
                                immediate=immediate)
            if not cut:
                return 0
            if self.head is None:
                self.head = WALBlock(self.wal_dir, self.tenant)
                self.head_created = self.now()
            for lt in cut:
                self.head.append(sort_spans(combine_spans(lt.spans)))
            return len(cut)

    def head_bytes(self) -> int:
        if self.head is None:
            return 0
        return sum(os.path.getsize(os.path.join(self.head.dir, s))
                   for s in self.head.segments())

    def cut_block_if_ready(self, immediate: bool = False) -> WALBlock | None:
        """Seal the head block when over age/size (`CutBlockIfReady`);
        returns the sealed WAL block to enqueue for completion."""
        with self.lock:
            if self.head is None:
                return None
            age = self.now() - self.head_created
            if not (immediate
                    or age >= self.cfg.max_block_duration_s
                    or self.head_bytes() >= self.cfg.max_block_bytes):
                return None
            sealed = self.head
            self.head = None
            if not sealed.segments():
                sealed.clear()
                return None
            self.completing.append(sealed)
            return sealed

    def complete_block(self, wal_block: WALBlock) -> BlockMeta:
        """WAL → columnar complete block on local disk (`CompleteBlock`
        `instance.go:316`): read back every trace, dedupe/sort, write the
        same block format the storage engine serves."""
        traces = wal_block.complete()
        meta = write_block(
            self.local_backend, self.tenant,
            [(tid, sort_spans(combine_spans(spans))) for tid, spans in traces],
            block_id=wal_block.block_id,
            dedicated_columns=self.cfg.dedicated_columns,
            row_group_rows=self.cfg.row_group_rows,
            replication_factor=self.cfg.replication_factor)
        with self.lock:
            self.complete[meta.block_id] = LocalBlockEntry(
                meta, BackendBlock(self.local_backend, meta))
            if wal_block in self.completing:
                self.completing.remove(wal_block)
        wal_block.clear()
        return meta

    def mark_flushed(self, block_id: str) -> None:
        with self.lock:
            e = self.complete.get(block_id)
            if e:
                e.flushed_ts = self.now()

    def delete_old_flushed(self, after_s: float) -> list[str]:
        """Drop local complete blocks flushed more than after_s ago
        (complete_block_timeout semantics)."""
        out = []
        with self.lock:
            for bid in list(self.complete):
                e = self.complete[bid]
                if e.flushed_ts and self.now() - e.flushed_ts >= after_s:
                    del self.complete[bid]
                    out.append(bid)
        for bid in out:
            try:
                self.local_backend.delete("", _kp(bid, self.tenant), recursive=True)
            except Exception:
                pass
        return out

    # -- replay ------------------------------------------------------------

    def replay(self) -> tuple[int, int]:
        """Restart recovery: re-adopt WAL blocks and local complete blocks
        (`instance.go:601` + `ingester.go:159`). Returns (wal, complete)."""
        n_wal = 0
        for wb in rescan_blocks(self.wal_dir):
            if wb.tenant != self.tenant:
                continue
            with self.lock:
                if wb.block_id in {b.block_id for b in self.completing}:
                    continue
                self.completing.append(wb)
            n_wal += 1
        n_complete = 0
        blocks_root = os.path.join(self.local_dir, self.tenant)
        if os.path.isdir(blocks_root):
            for bid in os.listdir(blocks_root):
                try:
                    meta = read_block_meta(self.local_backend, bid, self.tenant)
                except Exception:
                    continue
                with self.lock:
                    self.complete[bid] = LocalBlockEntry(
                        meta, BackendBlock(self.local_backend, meta))
                n_complete += 1
        return n_wal, n_complete

    # -- read path ---------------------------------------------------------

    def find_trace_by_id(self, trace_id: bytes) -> list[dict] | None:
        """Combine across live + head + completing + complete blocks
        (the recent-data side of `Querier.FindTraceByID`)."""
        parts: list[list[dict]] = []
        with self.lock:
            lt = self.live.traces.get(trace_id)
            if lt:
                parts.append(list(lt.spans))
            heads = [b for b in ([self.head] if self.head else [])] + list(self.completing)
            complete = list(self.complete.values())
        for wb in heads:
            spans = wb.find_trace_by_id(trace_id)
            if spans:
                parts.append(spans)
        for e in complete:
            spans = e.block.find_trace_by_id(trace_id)
            if spans:
                parts.append(spans)
        if not parts:
            return None
        return sort_spans(combine_spans(*parts))

    def all_recent_traces(self) -> list[tuple[bytes, list[dict]]]:
        """Snapshot of live + WAL data as (trace_id, spans) groups, for
        vectorized search over an in-memory ColumnView."""
        by_id: dict[bytes, list[dict]] = {}
        with self.lock:
            for tid, lt in self.live.traces.items():
                by_id.setdefault(tid, []).extend(lt.spans)
            heads = [b for b in ([self.head] if self.head else [])] + list(self.completing)
        for wb in heads:
            for s in wb.iter_spans():
                by_id.setdefault(s["trace_id"], []).append(s)
        return [(tid, sort_spans(combine_spans(spans)))
                for tid, spans in by_id.items()]

    def complete_blocks(self) -> list[BackendBlock]:
        with self.lock:
            return [e.block for e in self.complete.values()]


def _kp(block_id: str, tenant: str):
    from tempo_tpu.backend.raw import block_keypath
    return block_keypath(block_id, tenant)
