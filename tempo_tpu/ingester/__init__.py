"""Ingester: live-trace accumulation → WAL → complete blocks → backend flush.

Analog of `modules/ingester`: per-tenant instances accumulate spans in live
traces (`instance.go:145,199`), cut complete traces to a head WAL block
(`CutCompleteTraces` `instance.go:237`), cut the head block when full
(`CutBlockIfReady` `instance.go:272`), convert WAL→columnar complete blocks
(`CompleteBlock` `instance.go:316`), and flush them to object storage
through retrying flush queues (`flush.go:213-427`). WAL replay on restart
(`instance.go:601`, `ingester.go:159`) restores in-flight data.
"""

from tempo_tpu.ingester.ingester import Ingester, IngesterConfig
from tempo_tpu.ingester.instance import PUSH_ERRORS, TenantInstance

__all__ = ["Ingester", "IngesterConfig", "TenantInstance", "PUSH_ERRORS"]
