"""Metric series registry with device-resident aggregation state.

The analog of the reference's `ManagedRegistry`
(`modules/generator/registry/registry.go:58-136`): per-tenant metric series
(counters, gauges, classic histograms, native/exponential histograms) with
active-series limits, staleness eviction, and a collection tick that turns
device state into Prometheus samples.

Split of responsibilities on a TPU machine:

- host (`series.py`): label-string interning, label-combo → dense slot-id
  tables (the `LabelValueCombo`/series-hash role of `registry/hash.go`),
  last-seen bookkeeping, staleness purge.
- device (`metrics.py`): one array row per series slot; batched updates are
  scatter-add/set kernels; collection is a single device→host gather.
"""

from tempo_tpu.registry.series import Exemplar, Sample, SeriesBudget, SeriesTable
from tempo_tpu.registry.metrics import (
    CounterState,
    GaugeState,
    HistogramState,
    NativeHistogramState,
    counter_init,
    counter_update,
    gauge_init,
    gauge_set,
    histogram_init,
    histogram_update,
    native_histogram_init,
    native_histogram_update,
    zero_slots,
)
from tempo_tpu.registry.registry import (
    Counter,
    Gauge,
    Histogram,
    ManagedRegistry,
    NativeHistogram,
    RegistryOverrides,
)

__all__ = [k for k in dir() if not k.startswith("_")]
