"""Process-wide device page pool: paged, ragged registry/sketch state.

The dense layout sizes every tenant family for the worst tenant
(`capacity` rows up front — ~85MB/tenant for the DDSketch plane alone at
defaults). This module kills that: one large per-(dtype, row-width) HBM
arena per process, carved into fixed-size pages (pow-2 rows each),
allocated ON DEMAND as series tables hand out slots and returned to the
free list by the existing staleness sweeps. A sparse tenant costs a few
pages instead of a full dense plane; thousands of tenants share the
arenas (ROADMAP item 2, "Ragged Paged Attention" style — PAPERS.md).

Pieces:

- `PagePool` — process-level state like `tempo_tpu.sched` and the
  serving mesh: `App` calls `configure()` from the `pages:` config block
  (AFTER the mesh — arenas shard page-aligned over 'series' when the
  serving mesh is on); standalone callers use `use()` / `reset()`.
  The pool's RLock is THE state lock for every paged tenant: arenas are
  shared and donated at dispatch, so all device reads/rebinds serialize
  through it (ManagedRegistry adopts it as `state_lock`).
- `_Arena` — one device buffer per (dtype, width): `[rows]` or
  `[rows, width]`, rows = `arena_slots` rounded up to whole pages (and
  to a page-aligned multiple of the mesh's series shards).
- `PagedPlane` — a family plane's view: host page map (logical page →
  physical page or -1), per-page active-slot refcounts, cached device
  copy of the map (re-uploaded only when allocation/eviction dirties
  it — the indirection table is an extra OPERAND of the fused kernels,
  not a new trace per tenant).
- `PageBacking` — per-SeriesTable allocator: `ensure_slot` backs the
  slot's page in every attached plane (all-or-nothing; exhaustion makes
  the series allocation fail exactly like a spent series budget),
  `release` decrements refcounts and frees empty pages (rows already
  zeroed by the eviction sweep; `free` re-zeroes the whole page anyway
  so a reused page can never leak rows).

Device kernels live in `tempo_tpu.ops.pages`. Nothing here imports jax
at module import time — `Config` imports this for the `pages:`
dataclass and must stay light.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

import numpy as np

_LOG = logging.getLogger("tempo_tpu.pages")

_DTYPE_BYTES = {"float32": 4, "int32": 4, "bfloat16": 2}


@dataclasses.dataclass
class PagePoolConfig:
    """Knobs for the device page pool (`pages:` in the app YAML)."""

    enabled: bool = False
    # rows per page; must be a power of two and divide every paged
    # family's capacity (max_active_series, sketch_max_series)
    page_rows: int = 256
    # arena size per (dtype, width) kind, in SLOTS (rows) — every active
    # series consumes one row in each plane kind it touches, so this is
    # the process-wide active-series budget of the paged layout
    arena_slots: int = 131072

    def check(self, capacities: "tuple[int, ...]" = ()) -> list[str]:
        """Config problems (chained into `app.config.Config.check()`).
        `capacities` are the per-family logical capacities the serving
        config implies (max_active_series, sketch_max_series): paged
        mode refuses page sizes that do not divide them."""
        problems = []
        if self.page_rows < 1 or self.page_rows & (self.page_rows - 1):
            problems.append(
                f"pages.page_rows ({self.page_rows}) must be a power of two")
        if self.arena_slots < self.page_rows:
            problems.append(
                f"pages.arena_slots ({self.arena_slots}) < page_rows "
                f"({self.page_rows}): the pool could not back a single page")
        for cap in capacities:
            if self.page_rows >= 1 and \
                    not (self.page_rows & (self.page_rows - 1)) and \
                    cap % self.page_rows:
                problems.append(
                    f"pages.page_rows ({self.page_rows}) does not divide "
                    f"the configured series capacity {cap}: paged mode "
                    "refuses capacity-indivisible page sizes (pick a pow-2 "
                    "page_rows that divides max_active_series and "
                    "sketch_max_series)")
        if capacities and self.arena_slots < max(capacities):
            problems.append(
                f"pages.arena_slots ({self.arena_slots}) is below the "
                f"largest single-tenant capacity ({max(capacities)}): one "
                "full tenant exhausts the pool; size the arena for the "
                "expected ACTIVE series across all tenants (runbook "
                "'Sizing the page pool')")
        return ["pages: " + p for p in problems] if problems else []


class _Arena:
    """One device buffer per (dtype, width, role) + its page free list.

    The ROLE key (config-derived, e.g. "traces_spanmetrics_latency/
    buckets") keeps `arena_slots` meaning exactly "rows per plane role":
    every active series consumes ONE row in each role's arena, so the
    knob is the process-wide active-series budget — without it the five
    width-1 planes of a spanmetrics tenant would share (and 5x-starve)
    one anonymous arena. Tenants with the same family config share the
    same arenas."""

    def __init__(self, pool: "PagePool", dtype: str, width: int,
                 role: str) -> None:
        import jax
        import jax.numpy as jnp

        self.dtype = dtype
        self.width = width
        self.role = role
        self.n_pages = pool._arena_pages
        self.rows = self.n_pages * pool.page_rows
        shape = (self.rows,) if width == 1 else (self.rows, width)
        data = jnp.zeros(shape, dtype)
        if pool.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            spec = P("series") if width == 1 else P("series", None)
            data = jax.device_put(
                data, NamedSharding(pool.mesh.registry_mesh, spec))
        self.data = data
        # physical page 0 is RESERVED as the trash page: the Pallas
        # fused kernel's data-dependent BlockSpec index maps must name a
        # real block for unbacked logical pages, and redirecting them to
        # a page no tenant can ever own (written back unchanged, so it
        # stays zero) keeps the dense "-1 drops" semantics without a
        # host-side filter. The XLA kernels never see it: page tables
        # only hold allocated ids (all ≥ 1) or -1.
        self.free: list[int] = list(range(self.n_pages - 1, 0, -1))
        self.owners: list[str | None] = [None] * self.n_pages

    @property
    def page_bytes(self) -> int:
        return 0 if self.rows == 0 else \
            (self.rows // self.n_pages) * self.width * _DTYPE_BYTES[self.dtype]


class PagePool:
    """The process device page pool (see module docstring)."""

    def __init__(self, cfg: PagePoolConfig) -> None:
        self.cfg = cfg
        self.page_rows = cfg.page_rows
        self.page_shift = cfg.page_rows.bit_length() - 1
        # THE paged-state lock: arenas are shared across tenants and
        # donated at dispatch — every read and rebind serializes here
        # (re-entrant: collect()'s family snapshots nest gathers)
        self.lock = threading.RLock()
        self.arenas: dict[tuple[str, int, str], _Arena] = {}
        self.allocated_total = 0
        self.evicted_total = 0
        self.alloc_failures = 0
        self.gather_seconds = 0.0
        # serving-mesh composition: arenas shard page-aligned over
        # 'series' — page ownership replaces the per-tenant
        # capacity-divisibility requirement of the dense mesh placement.
        # Needs data axis 1 (the serving default): the fused paged step
        # is a no-collective owned-rows scatter.
        from tempo_tpu.parallel import serving
        sm = serving.active()
        if sm is not None and sm.data_shards != 1:
            _LOG.warning(
                "page pool: serving mesh has data_shards=%d — paged "
                "arenas need the series-only layout (data=1); arenas "
                "stay single-device", sm.data_shards)
            sm = None
        self.mesh = sm
        shards = sm.series_shards if sm is not None else 1
        # +1: physical page 0 is the reserved trash page (see _Arena) —
        # `arena_slots` keeps meaning USABLE rows per plane role
        pages = -(-cfg.arena_slots // cfg.page_rows) + 1
        if pages % shards:
            pages += shards - pages % shards  # page-aligned shard ranges
        self._arena_pages = pages

    # -- arenas ------------------------------------------------------------

    def arena(self, dtype: str, width: int, role: str) -> _Arena:
        """Get-or-create the (dtype, width, role) arena (device alloc is
        lazy: a process that never pages a role never pays its arena)."""
        key = (dtype, int(width), role)
        with self.lock:
            a = self.arenas.get(key)
            if a is None:
                a = self.arenas[key] = _Arena(self, dtype, width, role)
            return a

    def alloc_page(self, arena: _Arena, tenant: str) -> int:
        """One physical page off the free list, or -1 (pool exhausted —
        the caller's series allocation fails like a spent budget)."""
        with self.lock:
            if not arena.free:
                self.alloc_failures += 1
                return -1
            page = arena.free.pop()
            arena.owners[page] = tenant
            self.allocated_total += 1
            return page

    def release_pages(self, arena: _Arena, pages: np.ndarray) -> None:
        """Zero the pages' rows (ONE batched dispatch, pow-2 padded so a
        sweep of any size keeps a handful of warm shapes) and return
        them to the free list."""
        from tempo_tpu.ops import pages as op
        from tempo_tpu.sched import bucket_rows
        if not len(pages):
            return
        with self.lock:
            padded = np.full(bucket_rows(len(pages), lo=8), -1, np.int32)
            padded[:len(pages)] = pages
            arena.data = op.zero_pages_step(arena.data.ndim, self.page_rows)(
                arena.data, padded)
            for page in np.asarray(pages).tolist():
                arena.owners[page] = None
                arena.free.append(page)
            self.evicted_total += len(pages)

    # -- accounting --------------------------------------------------------

    def total_pages(self) -> int:
        """USABLE pages across arenas (the reserved trash page of each
        arena is not allocatable and not counted)."""
        with self.lock:
            return sum(a.n_pages - 1 for a in self.arenas.values())

    def free_pages(self) -> int:
        with self.lock:
            return sum(len(a.free) for a in self.arenas.values())

    def tenant_bytes(self) -> dict[str, int]:
        """Arena bytes held per tenant (page ownership × page bytes) —
        what the devtime ledger surfaces next to device-seconds."""
        out: dict[str, int] = {}
        with self.lock:
            for a in self.arenas.values():
                pb = a.page_bytes
                for owner in a.owners:
                    if owner is not None:
                        out[owner] = out.get(owner, 0) + pb
        return out

    def status(self) -> dict:
        """The /status "pages" object."""
        with self.lock:
            arenas = [{
                "role": a.role, "dtype": a.dtype, "width": a.width,
                "pages": a.n_pages - 1, "reserved": 1,
                "free": len(a.free),
                "page_bytes": a.page_bytes,
                "bytes": a.page_bytes * a.n_pages,
            } for a in self.arenas.values()]
        top = sorted(self.tenant_bytes().items(), key=lambda kv: -kv[1])[:10]
        return {
            "page_rows": self.page_rows,
            "arena_pages": self._arena_pages,
            "series_shards": self.mesh.series_shards
            if self.mesh is not None else 1,
            "allocated_total": self.allocated_total,
            "evicted_total": self.evicted_total,
            "alloc_failures": self.alloc_failures,
            "arenas": arenas,
            "top_tenant_bytes": [{"tenant": t, "bytes": b} for t, b in top],
        }


class PagedPlane:
    """One family plane's logical slot space over a pooled arena."""

    def __init__(self, pool: PagePool, dtype: str, width: int,
                 capacity: int, tenant: str, role: str = "") -> None:
        if capacity % pool.page_rows:
            raise ValueError(
                f"paged plane capacity {capacity} not divisible by "
                f"page_rows {pool.page_rows}")
        self.pool = pool
        self.width = int(width)
        self.capacity = capacity
        self.tenant = tenant
        self._arena = pool.arena(dtype, width, role)
        self.n_lpages = capacity // pool.page_rows
        self.page_map = np.full(self.n_lpages, -1, np.int32)
        self.refcnt = np.zeros(self.n_lpages, np.int64)
        self._dev_map = None

    # -- host management ---------------------------------------------------

    def backed(self, lpage: int) -> bool:
        return self.page_map[lpage] >= 0

    def alloc(self, lpage: int) -> bool:
        page = self.pool.alloc_page(self._arena, self.tenant)
        if page < 0:
            return False
        self.page_map[lpage] = page
        self._dev_map = None
        return True

    def free_lpages(self, lpages: np.ndarray) -> None:
        """Unmap + free the listed logical pages (one batched device
        zeroing for the whole set)."""
        lpages = np.asarray(lpages)
        phys = self.page_map[lpages]
        live = phys[phys >= 0]
        if not live.size:
            return
        self.page_map[lpages] = -1
        self._dev_map = None
        self.pool.release_pages(self._arena, live)

    def pages_backed(self) -> int:
        return int((self.page_map >= 0).sum())

    def device_state_bytes(self) -> int:
        return self.pages_backed() * self._arena.page_bytes

    # -- device views (callers hold pool.lock) -----------------------------

    def device_map(self):
        """The indirection table as a device operand (re-uploaded only
        when allocation/eviction dirtied it)."""
        if self._dev_map is None:
            import jax
            self._dev_map = jax.device_put(self.page_map)
        return self._dev_map

    @property
    def data(self):
        return self._arena.data

    def rebind(self, new_data) -> None:
        self._arena.data = new_data

    def gather(self, slots: np.ndarray) -> np.ndarray:
        """Host read of the slots' rows ([n] or [n, width]); unbacked or
        negative slots read 0. Caller holds pool.lock (arenas are
        donated by concurrent pushes)."""
        from tempo_tpu.ops import pages as op
        t0 = time.perf_counter()
        got = np.asarray(op.gather_step(self._arena.data.ndim,
                                        self.pool.page_shift)(
            self._arena.data, self.device_map(),
            np.ascontiguousarray(slots, np.int32)))
        self.pool.gather_seconds += time.perf_counter() - t0
        return got

    def gather_dev(self, slots: np.ndarray):
        """Like `gather` but stays on device (quantile pipelines)."""
        from tempo_tpu.ops import pages as op
        return op.gather_step(self._arena.data.ndim, self.pool.page_shift)(
            self._arena.data, self.device_map(),
            np.ascontiguousarray(slots, np.int32))

    def zero_slots(self, slots: np.ndarray) -> None:
        """Zero the slots' rows (eviction sweep; dense `zero_slots` twin).
        Caller holds pool.lock."""
        from tempo_tpu.ops import pages as op
        self._arena.data = op.zero_step(
            self._arena.data.ndim, self.pool.page_shift)(
            self._arena.data, self.device_map(),
            np.ascontiguousarray(slots, np.int32))


class PageBacking:
    """Per-SeriesTable page allocator over one or more planes.

    Families sharing a table (the spanmetrics trio + sketch sidecar)
    register every plane here; slot allocation backs the slot's page in
    ALL of them or fails atomically, so a series either fully exists in
    the paged layout or was never admitted (mirroring the budget gate).
    """

    def __init__(self, pool: PagePool) -> None:
        self.pool = pool
        self.planes: list[tuple[PagedPlane, int]] = []

    def add_plane(self, plane: PagedPlane, limit: "int | None" = None) -> None:
        """Attach a plane; `limit` caps the slot range it backs (the
        sketch plane may be a strict prefix of the series table)."""
        self.planes.append((plane, plane.capacity if limit is None
                            else min(limit, plane.capacity)))

    def adopt(self, other: "PageBacking") -> None:
        self.planes.extend(other.planes)

    def ensure_slot(self, slot: int) -> bool:
        """Back `slot`'s page in every attached plane (all-or-nothing)."""
        shift = self.pool.page_shift
        with self.pool.lock:
            need: list[tuple[PagedPlane, int]] = []
            per_arena: dict[int, int] = {}
            for plane, limit in self.planes:
                if slot >= limit or plane.backed(slot >> shift):
                    continue
                need.append((plane, slot >> shift))
                per_arena[id(plane._arena)] = \
                    per_arena.get(id(plane._arena), 0) + 1
            # feasibility first: a partial allocation must not strand pages
            arenas = {id(p._arena): p._arena for p, _ in need}
            for aid, want in per_arena.items():
                if len(arenas[aid].free) < want:
                    self.pool.alloc_failures += 1
                    return False
            for plane, lpage in need:
                if not plane.alloc(lpage):  # pragma: no cover — prechecked
                    return False
            for plane, limit in self.planes:
                if slot < limit:
                    plane.refcnt[slot >> shift] += 1
            return True

    def release(self, slots: np.ndarray) -> None:
        """Evicted slots: drop refcounts, free pages that emptied."""
        slots = np.asarray(slots)
        if not slots.size:
            return
        shift = self.pool.page_shift
        with self.pool.lock:
            for plane, limit in self.planes:
                ss = slots[slots < limit]
                if not ss.size:
                    continue
                np.subtract.at(plane.refcnt, ss >> shift, 1)
                empty = np.flatnonzero(
                    (plane.refcnt <= 0) & (plane.page_map >= 0))
                plane.free_lpages(empty)


# ---------------------------------------------------------------------------
# the process-wide pool (configured by App, consulted by ManagedRegistry)
# ---------------------------------------------------------------------------

_active: "PagePool | None" = None
_lock = threading.Lock()


def configure(cfg: "PagePoolConfig | None") -> "PagePool | None":
    """Build (or drop) the process page pool from the `pages:` config
    block. Returns the active pool or None when disabled. Never raises
    on a bad config — it warns and falls back to the dense layout
    (`Config.check()` already surfaced the problem)."""
    global _active
    with _lock:
        if cfg is None or not cfg.enabled:
            _active = None
            return None
        problems = cfg.check()
        if problems:
            _LOG.error("page pool disabled: %s", "; ".join(problems))
            _active = None
            return None
        _active = PagePool(cfg)
        return _active


def active() -> "PagePool | None":
    """The process page pool, or None — registries then build dense."""
    return _active


def reset() -> None:
    """Drop the process pool (test isolation)."""
    global _active
    with _lock:
        _active = None


class use:
    """Install a pool (or None) as the process page pool for a
    with-block (tests, bench arms)."""

    def __init__(self, pool: "PagePool | None") -> None:
        self.pool = pool
        self._prev: "PagePool | None" = None

    def __enter__(self) -> "PagePool | None":
        global _active
        with _lock:
            self._prev, _active = _active, self.pool
        return self.pool

    def __exit__(self, *exc) -> None:
        global _active
        with _lock:
            _active = self._prev


# ---------------------------------------------------------------------------
# obs: page-pool families in the process-wide runtime registry
# ---------------------------------------------------------------------------

from tempo_tpu.obs.jaxruntime import RUNTIME  # noqa: E402

_ARENA_LABELS = ("role", "dtype", "width")


def _arena_rows(field):
    pool = _active
    if pool is None:
        return []
    with pool.lock:
        return [((a.role, a.dtype, str(a.width)), float(field(a)))
                for a in pool.arenas.values()]


RUNTIME.gauge_func(
    "tempo_pages_total",
    lambda: _arena_rows(lambda a: a.n_pages - 1),
    help="Usable device pages per arena kind (absent families when the "
         "page pool is off; excludes each arena's reserved trash page)",
    labels=_ARENA_LABELS)
RUNTIME.gauge_func(
    "tempo_pages_free",
    lambda: _arena_rows(lambda a: len(a.free)),
    help="Free device pages per arena kind — 0 with allocation failures "
         "rising means the pool is exhausted (runbook 'Sizing the page "
         "pool')", labels=_ARENA_LABELS)
RUNTIME.counter_func(
    "tempo_pages_allocated_total",
    lambda: [] if _active is None else [((), float(_active.allocated_total))],
    help="Pages handed out since process start (demand-driven: series "
         "table slot allocation backs pages on first touch)")
RUNTIME.counter_func(
    "tempo_pages_evicted_total",
    lambda: [] if _active is None else [((), float(_active.evicted_total))],
    help="Pages returned to the free list by staleness sweeps / purges")
RUNTIME.counter_func(
    "tempo_pages_alloc_failures_total",
    lambda: [] if _active is None else [((), float(_active.alloc_failures))],
    help="Series allocations refused because the page pool was "
         "exhausted (the paged twin of a spent series budget)")
RUNTIME.counter_func(
    "tempo_pages_gather_overhead_seconds_total",
    lambda: [] if _active is None else [((), float(_active.gather_seconds))],
    help="Wall seconds spent gathering paged rows to the host through "
         "the indirection table (collect/native-payload reads)")


__all__ = ["PagePoolConfig", "PagePool", "PagedPlane", "PageBacking",
           "configure", "active", "reset", "use"]
