"""Paged metric families: the dense registry families over pooled pages.

Each class keeps the dense family's HOST half untouched (series table,
exemplars, staleness markers, collect formatting — inherited) and swaps
ONLY the device half: rows live in the process page pool's arenas behind
a per-family indirection table (`registry/pages.py`), updates go through
the paged scatter kernels (`ops/pages.py`), and snapshots gather active
slots back through the same table into capacity-shaped host arrays so
`collect()` emits bit-identical samples to the dense layout.

Every device op runs under the registry state lock, which for paged
tenants IS the pool's re-entrant lock: arenas are shared across tenants
and DONATED at dispatch, the same discipline as the dense fast paths.

`ManagedRegistry` picks these classes automatically when the process
page pool is configured (`pages.enabled`); nothing else changes for
callers.
"""

from __future__ import annotations

import numpy as np

from tempo_tpu.ops import pages as op
from tempo_tpu.registry import metrics as m
from tempo_tpu.registry.pages import PageBacking, PagedPlane
from tempo_tpu.registry.registry import (
    Counter,
    Gauge,
    Histogram,
    NativeHistogram,
    _MetricBase,
    _pad_len,
)


class _PagedBase(_MetricBase):
    """Shared paged plumbing: planes + backing + gather snapshots."""

    def _init_paged(self, registry, name, label_names, capacity) -> None:
        _MetricBase.__init__(self, registry, name, label_names, capacity)
        self.pool = registry.pages
        self.planes: dict[str, PagedPlane] = {}
        self.table.backing = PageBacking(self.pool)

    def _plane(self, role: str, width: int, dtype: str = "float32",
               limit: "int | None" = None) -> PagedPlane:
        p = PagedPlane(self.pool, dtype, width, self.table.capacity
                       if limit is None else limit,
                       self.registry.tenant,
                       role=f"{self.name}/{role}")
        self.planes[role] = p
        self.table.backing.add_plane(p, limit)
        return p

    def _padded_active(self) -> tuple[np.ndarray, int]:
        """Active slots padded to a pow-2 bucket (-1 rows read 0) so the
        gather kernel keeps a handful of warm shapes."""
        slots = self.table.active_slots()
        padded = np.full(_pad_len(max(slots.size, 1)), -1, np.int32)
        padded[:slots.size] = slots
        return padded, slots.size

    def _gather_full(self, plane: PagedPlane) -> np.ndarray:
        """Capacity-shaped host array with active rows filled — the shape
        the dense `_snap`/`collect` pipeline already consumes. Compact
        (int32) planes upcast at the snapshot boundary: integer counts
        below 2^24 round-trip f32 exactly, so formatting matches the
        dense layout sample-for-sample."""
        padded, n = self._padded_active()
        shape = (self.table.capacity,) if plane.width == 1 \
            else (self.table.capacity, plane.width)
        full = np.zeros(shape, np.float32)
        if n:
            full[padded[:n]] = plane.gather(padded)[:n].astype(np.float32)
        return full

    def zero_evicted(self, padded_slots: np.ndarray) -> None:
        for p in self.planes.values():
            # the registry pads the eviction batch with `table.capacity`
            # (dense OOB); the paged discard encoding is NEGATIVE slots
            # (positive OOB would clip into the last logical page), and
            # planes may cover a strict prefix of the table
            p.zero_slots(np.where(padded_slots < p.capacity,
                                  padded_slots, -1))

    def device_state_bytes(self) -> int:
        return sum(p.device_state_bytes() for p in self.planes.values())

    def _w(self, slots, weights) -> np.ndarray:
        return np.ones(len(slots), np.float32) if weights is None \
            else np.asarray(weights, np.float32)


class PagedCounter(_PagedBase, Counter):
    def __init__(self, registry, name, label_names, capacity,
                 compact: bool = False):
        self._init_paged(registry, name, label_names, capacity)
        # compact tier: int32 rows — per-row contributions round to
        # nearest (exact for unit/integer weights; the documented
        # tolerance tier otherwise — runbook "Choosing the update kernel")
        self.compact = compact
        self.values = self._plane("values", 1,
                                  dtype="int32" if compact else "float32")

    def add_slots(self, slots: np.ndarray,
                  weights: np.ndarray | None = None) -> None:
        with self.registry.state_lock:
            w = self._w(slots, weights)
            if self.compact:
                w = np.round(w).astype(np.int32)
            self.values.rebind(op.counter_add_step(self.pool.page_shift)(
                self.values.data, self.values.device_map(),
                np.ascontiguousarray(slots, np.int32), w))

    def _snap(self) -> tuple:
        return (self._gather_full(self.values),)


class PagedGauge(_PagedBase, Gauge):
    def __init__(self, registry, name, label_names, capacity):
        self._init_paged(registry, name, label_names, capacity)
        self.values = self._plane("values", 1)

    def _device_set(self, slots: np.ndarray, values: np.ndarray) -> None:
        with self.registry.state_lock:
            self.values.rebind(op.gauge_set_step(self.pool.page_shift)(
                self.values.data, self.values.device_map(),
                np.ascontiguousarray(slots, np.int32),
                np.asarray(values, np.float32)))

    def _snap(self) -> tuple:
        return (self._gather_full(self.values),)


class PagedHistogram(_PagedBase, Histogram):
    def __init__(self, registry, name, label_names, capacity,
                 edges: tuple[float, ...] = None, compact: bool = False):
        from tempo_tpu.registry.registry import DEFAULT_HISTOGRAM_EDGES
        self._init_paged(registry, name, label_names, capacity)
        self.edges = tuple(DEFAULT_HISTOGRAM_EDGES if edges is None else edges)
        # compact tier: bucket/count rows int32, the sum row a [2]-wide
        # bf16 Kahan PAIR (running sum + compensation; the Pallas kernel
        # maintains the compensation, the composed-scatter fallback
        # accumulates into the primary column only)
        self.compact = compact
        self.buckets = self._plane("buckets", len(self.edges) + 1,
                                   dtype="int32" if compact else "float32")
        self.sums = self._plane("sums", 2 if compact else 1,
                                dtype="bfloat16" if compact else "float32")
        self.counts = self._plane("counts", 1,
                                  dtype="int32" if compact else "float32")

    def hist_edges(self) -> tuple:
        return self.edges

    def observe_slots(self, slots: np.ndarray, values: np.ndarray,
                      weights: np.ndarray | None = None) -> None:
        with self.registry.state_lock:
            a_sums, a_counts, ab = op.histogram_observe_step(
                self.edges, self.pool.page_shift,
                compact=self.compact)(
                self.sums.data, self.counts.data, self.buckets.data,
                self.buckets.device_map(), self.sums.device_map(),
                self.counts.device_map(),
                np.ascontiguousarray(slots, np.int32),
                np.asarray(values, np.float32), self._w(slots, weights))
            self.sums.rebind(a_sums)
            self.counts.rebind(a_counts)
            self.buckets.rebind(ab)

    def _snap(self) -> tuple:
        if not self.compact:
            return (self._gather_full(self.buckets),
                    self._gather_full(self.sums),
                    self._gather_full(self.counts))
        # the pair plane folds to sum + compensation at the snapshot
        padded, n = self._padded_active()
        full = np.zeros((self.table.capacity,), np.float32)
        if n:
            pair = self.sums.gather(padded)[:n].astype(np.float32)
            full[padded[:n]] = pair[:, 0] + pair[:, 1]
        return (self._gather_full(self.buckets), full,
                self._gather_full(self.counts))


class PagedNativeHistogram(_PagedBase, NativeHistogram):
    def __init__(self, registry, name, label_names, capacity):
        self._init_paged(registry, name, label_names, capacity)
        self.offset = m.NATIVE_HISTOGRAM_OFFSET
        self.hist = self._plane("hist", 64)
        self.sums = self._plane("sums", 1)
        self.counts = self._plane("counts", 1)
        self.zeros = self._plane("zeros", 1)

    def hist_offset(self) -> int:
        return self.offset

    def observe_slots(self, slots: np.ndarray, values: np.ndarray,
                      weights: np.ndarray | None = None) -> None:
        with self.registry.state_lock:
            a_sums, a_counts, a_zeros, ah = op.native_hist_step(
                self.offset, self.pool.page_shift)(
                self.sums.data, self.counts.data, self.zeros.data,
                self.hist.data,
                self.hist.device_map(), self.sums.device_map(),
                self.counts.device_map(), self.zeros.device_map(),
                np.ascontiguousarray(slots, np.int32),
                np.asarray(values, np.float32), self._w(slots, weights))
            self.sums.rebind(a_sums)
            self.counts.rebind(a_counts)
            self.zeros.rebind(a_zeros)
            self.hist.rebind(ah)

    def _snap(self) -> tuple:
        return (self._gather_full(self.sums),
                self._gather_full(self.counts))

    def native_payload(self):
        padded, n = self._padded_active()
        slots = padded[:n]
        return (slots, [self.labels_of(s) for s in slots.tolist()],
                self.hist.gather(padded)[:n],
                self.sums.gather(padded)[:n],
                self.counts.gather(padded)[:n],
                self.zeros.gather(padded)[:n])


__all__ = ["PagedCounter", "PagedGauge", "PagedHistogram",
           "PagedNativeHistogram"]
