"""Host-side series tables: label combos → dense device slot ids.

Replaces the reference's per-series hash map + `LabelValueCombo` hashing
(`modules/generator/registry/registry.go:139-144`, `registry/hash.go`) with a
vectorized staging step: a batch of label-id rows is uniqued once (numpy),
unseen combos get slots from a free list, and every span row resolves to a
dense int32 slot usable as a device scatter index.

Slot lifecycle mirrors the reference's active-series accounting
(`registry.go:184-197` onAddSeries / max_active_series) and staleness purge
(`registry.go:258-277` removeStaleSeries): full table → new combos are
rejected (slot -1, counted as discarded); idle series are evicted and their
device rows zeroed (see `zero_slots`) with staleness markers emitted on the
next collect.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np


@dataclasses.dataclass(frozen=True)
class Exemplar:
    trace_id_hex: str
    value: float
    ts_ms: int


@dataclasses.dataclass(frozen=True)
class Sample:
    name: str
    labels: tuple[tuple[str, str], ...]  # sorted (name, value) pairs
    value: float
    ts_ms: int
    exemplar: Exemplar | None = None
    is_stale_marker: bool = False


class SeriesBudget:
    """Cross-family active-series budget shared by all tables of a tenant
    registry (`registry.go:184-197` onAddSeries/max_active_series)."""

    def __init__(self, limit: int):
        self.limit = limit
        self.used = 0

    def take(self) -> bool:
        if self.used >= self.limit:
            return False
        self.used += 1
        return True

    def release(self, n: int = 1) -> None:
        self.used = max(0, self.used - n)


class SeriesTable:
    """Fixed-capacity table of label-value-id rows → slot ids."""

    def __init__(self, capacity: int, n_labels: int,
                 budget: "SeriesBudget | None" = None,
                 backing=None):
        self.capacity = capacity
        self.n_labels = n_labels
        self.budget = budget
        # paged layout (registry/pages.py): a PageBacking that must back
        # a slot's device pages before the slot can be handed out; pool
        # exhaustion rejects the combo exactly like a spent budget
        self.backing = backing
        self._slots: dict[bytes, int] = {}
        self._free: list[int] = list(range(capacity - 1, -1, -1))
        self.slot_keys = np.full((capacity, n_labels), -1, np.int32)
        self.active = np.zeros(capacity, bool)
        self.last_seen = np.zeros(capacity, np.float64)
        self.discarded = 0  # combos rejected because the table was full
        self._nat = None
        try:
            from tempo_tpu import native
            if native.available():
                self._nat = native.NativeRowTable(n_labels)
        except Exception:
            self._nat = None

    @property
    def active_count(self) -> int:
        return self.capacity - len(self._free)

    def lookup_or_create(self, rows: np.ndarray, now: float,
                         valid: np.ndarray | None = None) -> np.ndarray:
        """Resolve [n, n_labels] int32 label rows to [n] int32 slots.

        Rows that cannot be allocated (table full) resolve to -1; callers must
        mask those out of the device update (the reference increments
        `tempo_metrics_generator_registry_series_limited_total` — we count in
        `self.discarded`).
        """
        n = rows.shape[0]
        out = np.full(n, -1, np.int32)
        if n == 0:
            return out
        if valid is None:
            valid = np.ones(n, bool)
        if self._nat is not None:
            return self._lookup_native(rows, now, valid)
        uniq, inverse = np.unique(rows, axis=0, return_inverse=True)
        uslots = np.full(uniq.shape[0], -1, np.int32)
        # Only unique rows that actually appear in valid positions allocate.
        used = np.zeros(uniq.shape[0], bool)
        np.logical_or.at(used, inverse, valid)
        for i in range(uniq.shape[0]):
            if not used[i]:
                continue
            key = uniq[i].tobytes()
            slot = self._slots.get(key)
            if slot is None:
                if not self._free or (self.budget is not None
                                      and not self.budget.take()):
                    self.discarded += 1
                    continue
                slot = self._free.pop()
                if self.backing is not None and \
                        not self.backing.ensure_slot(slot):
                    self._free.append(slot)
                    if self.budget is not None:
                        self.budget.release()
                    self.discarded += 1
                    continue
                self._slots[key] = slot
                self.slot_keys[slot] = uniq[i]
                self.active[slot] = True
            self.last_seen[slot] = now
            uslots[i] = slot
        out = uslots[inverse]
        out[~valid] = -1
        return out

    def _lookup_native(self, rows: np.ndarray, now: float,
                       valid: np.ndarray) -> np.ndarray:
        """C++ row-table resolution: one native pass resolves every known
        combo; only genuinely NEW combos (first occurrence per batch) cross
        back into Python for slot allocation + budget accounting."""
        rows = np.ascontiguousarray(rows, np.int32)
        out, miss = self._nat.lookup(rows, valid)
        if miss.size:
            self.apply_misses(rows, out, miss, valid, now)
        live = out[out >= 0]
        if live.size:
            self.last_seen[live] = now
        return out

    def apply_misses(self, rows: np.ndarray, out: np.ndarray,
                     miss: np.ndarray, valid: np.ndarray,
                     now: float) -> None:
        """Resolve the PENDING entries a native lookup reported: allocate
        slots (budget-gated) for first occurrences, then fix in-batch
        duplicates host-side. `out` is updated in place; `rows`/`valid`
        cover out[:len(rows)] (out may be padded longer)."""
        n = len(rows)
        pend: dict[bytes, int] = {}
        for i in miss.tolist():
            row = rows[i]
            key = row.tobytes()
            if not self._free or (self.budget is not None
                                  and not self.budget.take()):
                self.discarded += 1
                self._nat.remove(row)   # pending entry must not linger
                pend[key] = -1
                continue
            slot = self._free.pop()
            if self.backing is not None and \
                    not self.backing.ensure_slot(slot):
                self._free.append(slot)
                if self.budget is not None:
                    self.budget.release()
                self.discarded += 1
                self._nat.remove(row)
                pend[key] = -1
                continue
            self._nat.insert(row, slot)
            self.slot_keys[slot] = row
            self.active[slot] = True
            self.last_seen[slot] = now
            pend[key] = slot
            out[i] = slot
        # duplicates of new combos within this batch resolved host-side
        unres = np.flatnonzero((out[:n] < 0) & valid[:n])
        for i in unres.tolist():
            out[i] = pend.get(rows[i].tobytes(), -1)

    def purge_stale(self, older_than: float) -> np.ndarray:
        """Evict series idle since before `older_than`; returns evicted slots."""
        stale = np.flatnonzero(self.active & (self.last_seen < older_than))
        for slot in stale.tolist():
            if self._nat is not None:
                self._nat.remove(self.slot_keys[slot])
            else:
                self._slots.pop(self.slot_keys[slot].tobytes(), None)
            self.active[slot] = False
            self.slot_keys[slot] = -1
            self._free.append(slot)
        if self.budget is not None and stale.size:
            self.budget.release(stale.size)
        if self.backing is not None and stale.size:
            # AFTER the families zeroed the evicted rows (registry
            # purge order): pages that emptied return to the free list
            self.backing.release(stale)
        return stale

    def active_slots(self) -> np.ndarray:
        return np.flatnonzero(self.active)
