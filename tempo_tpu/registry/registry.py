"""ManagedRegistry: per-tenant metric families over device state.

Reference behavior being reproduced (`modules/generator/registry/registry.go`):

- `NewCounter/NewGauge/NewHistogram/NewNativeHistogram` → metric families
  sharing one per-tenant active-series budget (`max_active_series`,
  `registry.go:184-197`).
- `CollectMetrics` tick (`registry.go:206-256`): walk active series, append
  samples at a synchronized timestamp; histograms expand to cumulative
  `_bucket`/`_sum`/`_count`; exemplars carry trace ids.
- stale-series purge (`registry.go:258-277`): series idle > staleness window
  are dropped, device rows zeroed, staleness markers (NaN) appended once.
- extra const labels and per-tenant external labels merged into every series.

Device work is batched: each metric family stages (slots, values) on host and
runs one scatter kernel; `collect` gathers each family's arrays once.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Iterable, Sequence

import jax
import numpy as np

from tempo_tpu.model.interner import StringInterner
from tempo_tpu.registry import metrics as m
from tempo_tpu.registry.series import Exemplar, Sample, SeriesBudget, SeriesTable

STALE_NAN = float("nan")

_LOG = logging.getLogger("tempo_tpu.registry")

DEFAULT_HISTOGRAM_EDGES = (0.002, 0.004, 0.008, 0.016, 0.032, 0.064, 0.128,
                           0.256, 0.512, 1.024, 2.048, 4.096, 8.192, 16.384)


@dataclasses.dataclass
class RegistryOverrides:
    """Per-tenant knobs (subset of `modules/overrides/config.go:71-200`)."""

    max_active_series: int = 65536
    collection_interval_s: float = 15.0
    stale_duration_s: float = 900.0
    external_labels: dict[str, str] = dataclasses.field(default_factory=dict)
    disable_collection: bool = False


class _MetricBase:
    def __init__(self, registry: "ManagedRegistry", name: str,
                 label_names: Sequence[str], capacity: int):
        self.registry = registry
        self.name = name
        self.label_names = tuple(label_names)
        self.table = SeriesTable(capacity, len(self.label_names),
                                 budget=registry.budget)
        self.exemplars: dict[int, Exemplar] = {}  # slot -> last exemplar
        self._stale_pending: list[tuple[tuple[tuple[str, str], ...], float]] = []
        self._ex_cursor = 0   # rotating exemplar-sampling window offset
        # processor-owned sidecar planes keyed to this family's slots
        # (the spanmetrics DDSketch) register here so the staleness purge
        # zeroes THEIR rows too — slot reuse must not inherit another
        # series' sketch history. Called with the padded eviction batch,
        # inside the registry state lock.
        self.evict_hooks: list = []

    # -- staging helpers ---------------------------------------------------

    def resolve_slots(self, label_rows: np.ndarray,
                      valid: np.ndarray | None = None) -> np.ndarray:
        """[n, L] interned label-value rows → [n] slots (-1 = discarded)."""
        return self.table.lookup_or_create(label_rows, self.registry.now(), valid=valid)

    def labels_of(self, slot: int) -> tuple[tuple[str, str], ...]:
        it = self.registry.interner
        vals = it.lookup_many(self.table.slot_keys[slot])
        pairs = dict(zip(self.label_names, vals))
        pairs.update(self.registry.overrides.external_labels)
        pairs["__name__"] = self.name
        return tuple(sorted(pairs.items()))

    def note_exemplars(self, slots: np.ndarray, trace_ids: np.ndarray,
                       values: np.ndarray, ts_ms: int, max_new: int = 16) -> None:
        """Record up to max_new last-seen exemplars (budget per push, like
        the engine's exemplar budgeting `engine_metrics.go:1070`).
        Exemplars are last-seen hints that pushes continually overwrite —
        a small per-push budget keeps them fresh under steady traffic
        while keeping the hex/dict work off the ingest hot path. One
        exemplar per DISTINCT series per push (deduped before the hex
        conversions; repeatedly hexing 100 ids of the same few series was
        measurable at 4M spans/s)."""
        ok = np.flatnonzero(slots >= 0)
        if len(ok) == 0:
            return
        # dedupe over a bounded ROTATING window (a full-batch unique is a
        # 16k sort per push — 1.3ms, costlier than what it saved). The
        # rotation guarantees tail series of a stably-ordered batch get
        # their turn across pushes, which a fixed head would starve.
        win = max_new * 16
        start = self._ex_cursor % len(ok)
        self._ex_cursor = start + win
        head = ok[start:start + win]
        if len(head) < win and start:
            head = np.concatenate([head, ok[:win - len(head)]])
        _, first = np.unique(slots[head], return_index=True)
        for i in head[np.sort(first)[:max_new]].tolist():
            tid = trace_ids[i].tobytes().hex()
            self.exemplars[int(slots[i])] = Exemplar(tid, float(values[i]), ts_ms)

    def note_stale(self, slots: np.ndarray) -> None:
        """Capture label sets before slot_keys are wiped (markers emitted on
        the next collect) and forget exemplars for evicted slots."""
        for slot in slots.tolist():
            self._stale_pending.append((self.labels_of(slot), self.registry.now()))
            self.exemplars.pop(slot, None)

    def _drain_stale_markers(self, ts_ms: int) -> list[Sample]:
        out = [Sample(self.name, labels, STALE_NAN, ts_ms, is_stale_marker=True)
               for labels, _ in self._stale_pending]
        self._stale_pending = []
        return out

    def share_table(self, other: "_MetricBase") -> None:
        """Adopt `other`'s series table so the families stay slot-aligned
        (the spanmetrics calls/latency/size trio). In the paged layout
        the shared table's backing adopts THIS family's planes, so one
        slot allocation backs every co-tabled plane atomically."""
        mine = self.table
        if mine is other.table:
            return
        if getattr(other.table, "backing", None) is not None and \
                getattr(mine, "backing", None) is not None:
            other.table.backing.adopt(mine.backing)
        self.table = other.table

    def zero_evicted(self, padded_slots: np.ndarray) -> None:
        """Zero the device rows of evicted slots (staleness purge).
        Paged families override to scatter through their page tables."""
        self.state = m.zero_slots(self.state, padded_slots)

    def device_state_bytes(self) -> int:
        """Device bytes this family holds (dense: full pre-sized arrays;
        paged override: backed pages only)."""
        state = getattr(self, "state", None)
        return sum(int(getattr(leaf, "nbytes", 0))
                   for leaf in jax.tree.leaves(state))


class Counter(_MetricBase):
    def __init__(self, registry, name, label_names, capacity,
                 compact: bool = False):
        # `compact` is the paged-layout int32/bf16 state tier; the dense
        # layout has no compact storage — PagedCounter honors it
        super().__init__(registry, name, label_names, capacity)
        self.state = m.counter_init(capacity)

    def inc_batch(self, label_rows: np.ndarray, weights: np.ndarray | None = None,
                  valid: np.ndarray | None = None) -> np.ndarray:
        slots = self.resolve_slots(label_rows, valid)
        self.add_slots(slots, weights)
        return slots

    def add_slots(self, slots: np.ndarray,
                  weights: np.ndarray | None = None) -> None:
        """Device half with slots already resolved (processors that share
        one resolve across families — servicegraphs, spanmetrics)."""
        self.state = m.counter_update(self.state, slots, weights, None)

    def inc(self, label_values: Sequence[str], value: float = 1.0) -> None:
        row = self.registry.interner.intern_many(label_values)[None, :]
        self.inc_batch(row, np.array([value], np.float32))

    def _snap(self) -> tuple:
        return (np.asarray(self.state.values),)

    def collect(self, ts_ms: int, snap: tuple | None = None) -> list[Sample]:
        (vals,) = snap if snap is not None else self._snap()
        out = [Sample(self.name, self.labels_of(s), float(vals[s]), ts_ms,
                      exemplar=self.exemplars.get(s))
               for s in self.table.active_slots().tolist()]
        return out + self._drain_stale_markers(ts_ms)


class Gauge(_MetricBase):
    def __init__(self, registry, name, label_names, capacity):
        super().__init__(registry, name, label_names, capacity)
        self.state = m.gauge_init(capacity)

    def set_batch(self, label_rows: np.ndarray, values: np.ndarray,
                  valid: np.ndarray | None = None) -> None:
        slots = self.resolve_slots(label_rows, valid)
        # last-wins per slot, resolved on host (scatter order is unspecified)
        order = np.arange(slots.shape[0])
        keep = {}
        for i in order.tolist():
            if slots[i] >= 0:
                keep[int(slots[i])] = i
        if not keep:
            return
        idx = np.fromiter(keep.values(), int)
        # pad to a pow-2 shape bucket: the distinct-slot count varies per
        # batch and an unbucketed scatter would re-trace on every new
        # cardinality (padding slots are -1 → dropped on device)
        n = len(idx)
        cap = _pad_len(n)
        s = np.full(cap, -1, np.int32)
        s[:n] = slots[idx]
        v = np.zeros(cap, np.float32)
        v[:n] = values[idx]
        self._device_set(s, v)

    def _device_set(self, slots: np.ndarray, values: np.ndarray) -> None:
        self.state = m.gauge_set(self.state, slots, values, None)

    def set(self, label_values: Sequence[str], value: float) -> None:
        row = self.registry.interner.intern_many(label_values)[None, :]
        self.set_batch(row, np.array([value], np.float32))

    def _snap(self) -> tuple:
        return (np.asarray(self.state.values),)

    def collect(self, ts_ms: int, snap: tuple | None = None) -> list[Sample]:
        (vals,) = snap if snap is not None else self._snap()
        out = [Sample(self.name, self.labels_of(s), float(vals[s]), ts_ms)
               for s in self.table.active_slots().tolist()]
        return out + self._drain_stale_markers(ts_ms)


class Histogram(_MetricBase):
    """Classic histogram family → `_count`/`_sum`/`_bucket{le=...}` series."""

    def __init__(self, registry, name, label_names, capacity,
                 edges: tuple[float, ...] = DEFAULT_HISTOGRAM_EDGES,
                 compact: bool = False):
        super().__init__(registry, name, label_names, capacity)
        self.state = m.histogram_init(capacity, edges)

    def observe_batch(self, label_rows: np.ndarray, values: np.ndarray,
                      weights: np.ndarray | None = None,
                      valid: np.ndarray | None = None) -> np.ndarray:
        slots = self.resolve_slots(label_rows, valid)
        self.observe_slots(slots, values, weights)
        return slots

    def observe_slots(self, slots: np.ndarray, values: np.ndarray,
                      weights: np.ndarray | None = None) -> None:
        self.state = m.histogram_update(self.state, slots, values, weights, None)

    def observe(self, label_values: Sequence[str], value: float) -> None:
        row = self.registry.interner.intern_many(label_values)[None, :]
        self.observe_batch(row, np.array([value], np.float32))

    def hist_edges(self) -> tuple:
        return self.state.edges

    def _snap(self) -> tuple:
        return (np.asarray(self.state.bucket_counts),
                np.asarray(self.state.sums), np.asarray(self.state.counts))

    def collect(self, ts_ms: int, snap: tuple | None = None) -> list[Sample]:
        bc, sums, counts = snap if snap is not None else self._snap()
        out: list[Sample] = []
        edges = self.hist_edges()
        for s in self.table.active_slots().tolist():
            base = self.labels_of(s)
            ex = self.exemplars.get(s)
            cum = np.cumsum(bc[s])
            out.append(Sample(self.name + "_count", base, float(counts[s]), ts_ms))
            out.append(Sample(self.name + "_sum", base, float(sums[s]), ts_ms))
            for i, e in enumerate(edges):
                le = (("le", _fmt_le(e)),)
                out.append(Sample(self.name + "_bucket", base + le, float(cum[i]),
                                  ts_ms, exemplar=ex if ex and ex.value <= e else None))
            out.append(Sample(self.name + "_bucket", base + (("le", "+Inf"),),
                              float(cum[-1]), ts_ms, exemplar=ex))
        return out + self._drain_stale_markers(ts_ms)


class NativeHistogram(_MetricBase):
    """Exponential histogram family (remote-write native histogram payloads)."""

    def __init__(self, registry, name, label_names, capacity):
        super().__init__(registry, name, label_names, capacity)
        self.state = m.native_histogram_init(capacity)

    def observe_batch(self, label_rows: np.ndarray, values: np.ndarray,
                      weights: np.ndarray | None = None,
                      valid: np.ndarray | None = None) -> np.ndarray:
        slots = self.resolve_slots(label_rows, valid)
        self.observe_slots(slots, values, weights)
        return slots

    def observe_slots(self, slots: np.ndarray, values: np.ndarray,
                      weights: np.ndarray | None = None) -> None:
        self.state = m.native_histogram_update(self.state, slots, values,
                                               weights, None)

    def _snap(self) -> tuple:
        return (np.asarray(self.state.sums), np.asarray(self.state.counts))

    def collect(self, ts_ms: int, snap: tuple | None = None) -> list[Sample]:
        # Scalar samples for visibility; the remote-write encoder additionally
        # reads `native_payload()` for real native-histogram protos.
        sums, counts = snap if snap is not None else self._snap()
        out = []
        for s in self.table.active_slots().tolist():
            base = self.labels_of(s)
            out.append(Sample(self.name + "_count", base, float(counts[s]), ts_ms))
            out.append(Sample(self.name + "_sum", base, float(sums[s]), ts_ms))
        return out + self._drain_stale_markers(ts_ms)

    def hist_offset(self) -> int:
        return self.state.hist.offset

    def native_payload(self):
        """(slots, labels, log2 counts, sums, counts, zeros) for remote write."""
        slots = self.table.active_slots()
        return (slots, [self.labels_of(s) for s in slots.tolist()],
                np.asarray(self.state.hist.counts)[slots],
                np.asarray(self.state.sums)[slots],
                np.asarray(self.state.counts)[slots],
                np.asarray(self.state.zeros)[slots])


def _fmt_le(e: float) -> str:
    return repr(round(e, 9)) if e != int(e) else str(int(e))


class ManagedRegistry:
    """Per-tenant registry: metric families + limits + collection."""

    def __init__(self, tenant: str = "single-tenant",
                 overrides: RegistryOverrides | None = None,
                 interner: StringInterner | None = None,
                 now: Callable[[], float] = time.time):
        self.tenant = tenant
        self.overrides = overrides or RegistryOverrides()
        self.interner = interner if interner is not None else StringInterner()
        self.now = now
        self.budget = SeriesBudget(self.overrides.max_active_series)
        self._metrics: dict[str, _MetricBase] = {}
        # paged layout (registry/pages.py): when the process page pool is
        # on and this tenant's capacity splits into whole pages, families
        # are built PAGED — device rows live in the pooled arenas behind
        # per-family indirection tables instead of full dense planes
        from tempo_tpu.registry import pages as pages_mod
        self.pages = pages_mod.active()
        if self.pages is not None and \
                self.overrides.max_active_series % self.pages.page_rows:
            _LOG.warning(
                "registry %s: max_active_series %d not divisible by "
                "pages.page_rows %d — tenant stays on the dense layout",
                tenant, self.overrides.max_active_series,
                self.pages.page_rows)
            self.pages = None
        # serializes device-state REBINDS that donate the old buffers
        # (the packed ingest fast path) against state READERS (collect /
        # native_histograms / purge's zero_slots): a donated input is
        # DELETED at dispatch, so an unlocked concurrent np.asarray on the
        # collection thread would hit a dead array. Paged tenants share
        # the POOL's re-entrant lock — arenas are cross-tenant state.
        self.state_lock = self.pages.lock if self.pages is not None \
            else threading.Lock()

    # -- family constructors ----------------------------------------------

    def _capacity_share(self) -> int:
        # Every family's table has full capacity; the cross-family total of
        # allocated label combos is enforced by the shared `budget` that all
        # SeriesTables consult on allocation (registry.go:184-197 analog).
        return self.overrides.max_active_series

    def _family_types(self):
        if self.pages is not None:
            from tempo_tpu.registry import paged
            return (paged.PagedCounter, paged.PagedGauge,
                    paged.PagedHistogram, paged.PagedNativeHistogram)
        return (Counter, Gauge, Histogram, NativeHistogram)

    def new_counter(self, name: str, label_names: Sequence[str],
                    compact: bool = False) -> Counter:
        c = self._family_types()[0](self, name, label_names,
                                    self._capacity_share(), compact=compact)
        self._metrics[name] = c
        return c

    def new_gauge(self, name: str, label_names: Sequence[str]) -> Gauge:
        g = self._family_types()[1](self, name, label_names,
                                    self._capacity_share())
        self._metrics[name] = g
        return g

    def new_histogram(self, name: str, label_names: Sequence[str],
                      edges: tuple[float, ...] = DEFAULT_HISTOGRAM_EDGES,
                      compact: bool = False) -> Histogram:
        h = self._family_types()[2](self, name, label_names,
                                    self._capacity_share(), edges,
                                    compact=compact)
        self._metrics[name] = h
        return h

    def new_native_histogram(self, name: str, label_names: Sequence[str]) -> NativeHistogram:
        h = self._family_types()[3](self, name, label_names,
                                    self._capacity_share())
        self._metrics[name] = h
        return h

    # -- bookkeeping -------------------------------------------------------

    @property
    def active_series(self) -> int:
        # Families may share a SeriesTable (the spanmetrics trio); count each
        # table once so the figure is comparable to max_active_series, which
        # gates allocation per table.
        seen: dict[int, int] = {}
        for mt in self._metrics.values():
            seen[id(mt.table)] = mt.table.active_count
        return sum(seen.values())

    @property
    def discarded_series(self) -> int:
        return sum(mt.table.discarded for mt in self._metrics.values())

    def collect(self, ts_ms: int | None = None) -> list[Sample]:
        """The collection tick (`registry.go:206-256`): one synchronized
        timestamp across all families, device state gathered once each."""
        if self.overrides.disable_collection:
            return []
        ts = int(self.now() * 1000) if ts_ms is None else ts_ms
        # ONLY the device snapshots sit under the lock (they are what a
        # donating push would invalidate); the per-sample formatting —
        # the bulk of the tick at high cardinality — runs outside so
        # ingest never stalls behind it
        with self.state_lock:
            snaps = [(mt, mt._snap()) for mt in self._metrics.values()]
        out: list[Sample] = []
        for mt, snap in snaps:
            out.extend(mt.collect(ts, snap))
        return out

    def purge_stale(self) -> int:
        """Evict idle series and zero their device rows; returns eviction
        count (of label combos). Families may share a SeriesTable (e.g. the
        spanmetrics calls/latency/size trio stays slot-aligned); eviction is
        computed once per table but EVERY family on that table gets its
        device rows zeroed and its staleness markers queued."""
        cutoff = self.now() - self.overrides.stale_duration_s
        by_table: dict[int, list[_MetricBase]] = {}
        for mt in self._metrics.values():
            by_table.setdefault(id(mt.table), []).append(mt)
        total = 0
        for fams in by_table.values():
            table = fams[0].table
            stale = np.flatnonzero(table.active & (table.last_seen < cutoff))
            if not stale.size:
                continue
            # pad to a small set of static shapes to bound recompiles
            padded = np.full(_pad_len(stale.size), table.capacity, np.int32)
            padded[: stale.size] = stale
            # one lock over the WHOLE shared-table eviction: a concurrent
            # collect must never see the slot-aligned trio half-zeroed
            with self.state_lock:
                for mt in fams:
                    mt.note_stale(stale)
                    mt.zero_evicted(padded)
                    for hook in mt.evict_hooks:
                        hook(padded)
                table.purge_stale(cutoff)
            total += stale.size
        return total

    def device_state_bytes(self) -> int:
        """Device bytes across this registry's families (dense: full
        pre-sized planes; paged: backed pages only). Processor-owned
        sidecars (the spanmetrics DDSketch plane) are NOT included —
        `GeneratorInstance.device_state_bytes` adds those."""
        return sum(mt.device_state_bytes() for mt in self._metrics.values())

    def native_histograms(self, ts_ms: int | None = None) -> list[tuple]:
        """(labels, log2_counts, sum, count, zeros, ts, offset) per active
        native-histogram series, in the shape encode_write_request consumes."""
        ts = int(self.now() * 1000) if ts_ms is None else ts_ms
        out = []
        with self.state_lock:
            payloads = [(mt, getattr(mt, "native_payload", None))
                        for mt in self._metrics.values()]
            payloads = [(mt, p()) for mt, p in payloads if p is not None]
        for mt, payload in payloads:
            slots, labels, hists, sums, counts, zeros = payload
            offset = mt.hist_offset()
            for i in range(len(labels)):
                out.append((labels[i], hists[i], float(sums[i]),
                            float(counts[i]), float(zeros[i]), ts, offset))
        return out

    def metric(self, name: str) -> _MetricBase:
        return self._metrics[name]


def _pad_len(n: int) -> int:
    # the shared shape-bucket policy (device scheduler coalescer), floor 16
    from tempo_tpu.sched import bucket_rows

    return bucket_rows(max(n, 1), lo=16)
