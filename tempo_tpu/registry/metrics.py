"""Device metric states + pure batched update kernels.

One array row per series slot. These pure functions are the composable
device half of each metric type in the reference registry
(`modules/generator/registry/{counter,gauge,histogram,native_histogram}.go`);
processors fuse several of them into a single jitted step per span batch
(see tempo_tpu.generator.processors.spanmetrics).

All updates accept slot ids with -1 = "discard" (series-limited or padding).
JAX wraps negative indices, so discards are redirected to an index >= capacity,
which IS out of bounds, and scattered with `mode="drop"` — no host-side
filtering needed.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from tempo_tpu.ops import sketches


def _mask_slots(slots: jax.Array, mask: jax.Array | None, capacity: int) -> jax.Array:
    """Slot ids with discards redirected OOB (>= capacity) so scatters drop them."""
    s = jnp.asarray(slots, jnp.int32)
    if mask is not None:
        s = jnp.where(mask, s, -1)
    return jnp.where(s < 0, capacity, s)


# -- counter -----------------------------------------------------------------

@partial(jax.tree_util.register_dataclass, data_fields=["values"], meta_fields=[])
@dataclasses.dataclass(frozen=True)
class CounterState:
    values: jax.Array  # [S] f32


def counter_init(capacity: int) -> CounterState:
    return CounterState(values=jnp.zeros((capacity,), jnp.float32))


def counter_update(state: CounterState, slots: jax.Array,
                   weights: jax.Array | None = None,
                   mask: jax.Array | None = None) -> CounterState:
    s = _mask_slots(slots, mask, state.values.shape[0])
    w = jnp.ones(s.shape, jnp.float32) if weights is None else jnp.asarray(weights, jnp.float32)
    return CounterState(values=state.values.at[s].add(w, mode="drop"))


# -- gauge -------------------------------------------------------------------

@partial(jax.tree_util.register_dataclass, data_fields=["values"], meta_fields=[])
@dataclasses.dataclass(frozen=True)
class GaugeState:
    values: jax.Array  # [S] f32


def gauge_init(capacity: int) -> GaugeState:
    return GaugeState(values=jnp.zeros((capacity,), jnp.float32))


def gauge_set(state: GaugeState, slots: jax.Array, values: jax.Array,
              mask: jax.Array | None = None) -> GaugeState:
    """Set semantics; the host stages at most one row per slot per batch
    (last-wins resolved during staging, since scatter order is unspecified)."""
    s = _mask_slots(slots, mask, state.values.shape[0])
    v = jnp.asarray(values, jnp.float32)
    return GaugeState(values=state.values.at[s].set(v, mode="drop"))


def gauge_add(state: GaugeState, slots: jax.Array, values: jax.Array,
              mask: jax.Array | None = None) -> GaugeState:
    s = _mask_slots(slots, mask, state.values.shape[0])
    v = jnp.asarray(values, jnp.float32)
    return GaugeState(values=state.values.at[s].add(v, mode="drop"))


# -- classic histogram -------------------------------------------------------

@partial(jax.tree_util.register_dataclass,
         data_fields=["bucket_counts", "sums", "counts"], meta_fields=["edges"])
@dataclasses.dataclass(frozen=True)
class HistogramState:
    """Prometheus classic histogram rows (`registry/histogram.go:107-189`):
    cumulative `le` buckets are produced at collect; device keeps per-bucket
    increments. edges are the static upper bounds (seconds), +Inf implicit.
    """

    bucket_counts: jax.Array  # [S, B+1] f32 (last = +Inf overflow)
    sums: jax.Array           # [S] f32
    counts: jax.Array         # [S] f32
    edges: tuple              # static tuple[float, ...]


def histogram_init(capacity: int, edges: tuple[float, ...]) -> HistogramState:
    nb = len(edges) + 1
    return HistogramState(
        bucket_counts=jnp.zeros((capacity, nb), jnp.float32),
        sums=jnp.zeros((capacity,), jnp.float32),
        counts=jnp.zeros((capacity,), jnp.float32),
        edges=tuple(edges),
    )


def histogram_update(state: HistogramState, slots: jax.Array, values: jax.Array,
                     weights: jax.Array | None = None,
                     mask: jax.Array | None = None) -> HistogramState:
    cap = state.sums.shape[0]
    s = _mask_slots(slots, mask, cap)
    v = jnp.asarray(values, jnp.float32)
    w = jnp.ones(s.shape, jnp.float32) if weights is None else jnp.asarray(weights, jnp.float32)
    edges = jnp.asarray(state.edges, jnp.float32)  # [B]
    b = jnp.sum(v[:, None] > edges[None, :], axis=1).astype(jnp.int32)  # le-inclusive
    nb = len(state.edges) + 1
    flat = jnp.where(s < cap, s * nb + b, cap * nb)  # OOB for discards
    return dataclasses.replace(
        state,
        bucket_counts=state.bucket_counts.reshape(-1).at[flat].add(
            w, mode="drop").reshape(state.bucket_counts.shape),
        sums=state.sums.at[s].add(v * w, mode="drop"),
        counts=state.counts.at[s].add(w, mode="drop"),
    )


# -- native (exponential) histogram -----------------------------------------

@partial(jax.tree_util.register_dataclass,
         data_fields=["hist", "sums", "counts", "zeros"], meta_fields=[])
@dataclasses.dataclass(frozen=True)
class NativeHistogramState:
    """Exponential-bucket histogram (`registry/native_histogram.go:85,195`).

    Device representation is the log2 sketch (= Prometheus native histogram
    schema 0: one bucket per power of two), plus sum/count/zero-count — enough
    to emit remote-write `Histogram` protos losslessly at that schema. The
    sketch's bucket offset (default 32) keeps sub-second resolution for
    second-scale latencies; the exporter shifts Prometheus bucket indices
    back by the same amount.
    """

    hist: sketches.Log2Histogram  # [S, 64]
    sums: jax.Array               # [S]
    counts: jax.Array             # [S]
    zeros: jax.Array              # [S]


NATIVE_HISTOGRAM_OFFSET = 32


def native_histogram_init(capacity: int, offset: int = NATIVE_HISTOGRAM_OFFSET) -> NativeHistogramState:
    return NativeHistogramState(
        hist=sketches.log2_hist_init(capacity, offset=offset),
        sums=jnp.zeros((capacity,), jnp.float32),
        counts=jnp.zeros((capacity,), jnp.float32),
        zeros=jnp.zeros((capacity,), jnp.float32),
    )


def native_histogram_update(state: NativeHistogramState, slots: jax.Array,
                            values: jax.Array,
                            weights: jax.Array | None = None,
                            mask: jax.Array | None = None) -> NativeHistogramState:
    cap = state.sums.shape[0]
    s = _mask_slots(slots, mask, cap)
    keep = s < cap
    v = jnp.asarray(values, jnp.float32)
    w = jnp.ones(s.shape, jnp.float32) if weights is None else jnp.asarray(weights, jnp.float32)
    return NativeHistogramState(
        hist=sketches.log2_hist_update(
            state.hist, jnp.where(keep, s, 0), v,
            mask=keep, weights=w),
        sums=state.sums.at[s].add(v * w, mode="drop"),
        counts=state.counts.at[s].add(w, mode="drop"),
        zeros=state.zeros.at[s].add(jnp.where(v == 0, w, 0.0), mode="drop"),
    )


# -- placement ---------------------------------------------------------------

def place_state(state, sharding_1d, sharding_2d):
    """Re-place a metric state pytree's device arrays (serving-mesh mode:
    slot dims sharded over 'series'). [S] leaves take `sharding_1d`,
    [S, ...] leaves `sharding_2d`; static meta (histogram edges) rides
    along untouched. Idempotent — device_put to the current sharding is
    a no-op."""
    import jax

    def place(leaf):
        sh = sharding_1d if getattr(leaf, "ndim", 0) == 1 else sharding_2d
        return jax.device_put(leaf, sh)

    return jax.tree.map(place, state)


# -- eviction ----------------------------------------------------------------

def zero_slots(state, slots: jax.Array):
    """Zero the device rows of evicted slots (any metric state pytree)."""
    s = jnp.asarray(slots, jnp.int32)

    def z(arr):
        if arr.ndim == 1:
            return arr.at[s].set(0.0, mode="drop")
        flat = arr.reshape(arr.shape[0], -1)
        return flat.at[s, :].set(0.0, mode="drop").reshape(arr.shape)

    return jax.tree.map(z, state)
