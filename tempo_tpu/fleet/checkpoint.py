"""Tenant device-state checkpoint/restore through the object store.

A checkpoint is ONE blob per tenant: for every registry family the
active series' label rows (as interner ids + the interner's string
table) and the family's device plane rows (gathered through the page
table for paged tenants, sliced for dense ones), plus the spanmetrics
sketch sidecar rows and their metadata. The paged layout (PR 8) is what
makes this cheap — a snapshot is backed pages, not capacity-sized
planes — and the moments tier (PR 9) is what makes it mergeable:
~15 floats/series whose combine is an elementwise add (+ max for the
two bound columns).

Restore is a MERGE, not an overwrite: label rows re-intern into the
live registry, slots allocate through the normal series-table path
(budget- and page-backed, so restore can never overcommit state the
tenant couldn't have allocated live), and plane rows scatter-ADD into
the device state (set for gauges — last-wins semantics). Restoring into
a fresh instance is therefore bit-identical (add-to-zero), and
restoring into an instance that already took in-flight deltas during a
handoff window merges exactly like the cross-shard sketch combine.
Sketch compatibility is enforced by the existing ValueError-raising
merge guards (`sketches._merge_check`, `moments.merge_meta_check`)
before any row is written.

Wire format: `np.savez_compressed` (zip of .npy members, no pickle)
with a single JSON metadata member — readable by anything that can open
a zip, versioned for forward evolution.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import logging
import time
import urllib.parse

import numpy as np

from tempo_tpu.backend.raw import DoesNotExist, KeyPath, RawReader, RawWriter
from tempo_tpu.fleet import STATS

_LOG = logging.getLogger("tempo_tpu.fleet")

CHECKPOINT_VERSION = 1
CHECKPOINT_SUFFIX = ".ckpt"
_META_KEY = "__meta__"


class CheckpointMismatch(ValueError):
    """The checkpoint was cut under an incompatible tenant config
    (overrides fingerprint / family shapes / sketch metadata). Restoring
    it would corrupt state, so the caller must skip it loudly."""


# ---------------------------------------------------------------------------
# fingerprint: the config surface a checkpoint's state layout depends on
# ---------------------------------------------------------------------------

def overrides_fingerprint(inst) -> str:
    """Stable digest of everything that shapes this tenant's series/plane
    layout. A checkpoint cut under different overrides (capacity, label
    dimensions, histogram edges, sketch tier/params) must not merge."""
    reg = inst.registry
    sm = inst.cfg.spanmetrics
    doc = {
        "max_active_series": reg.overrides.max_active_series,
        "external_labels": sorted(reg.overrides.external_labels.items()),
        "processors": sorted(inst.processors),
        "spanmetrics": {
            "dimensions": list(sm.dimensions),
            "intrinsic_dimensions": list(sm.intrinsic_dimensions),
            "histogram_buckets": [float(e) for e in sm.histogram_buckets],
            "sketch": sm.sketch,
            "enable_quantile_sketch": bool(sm.enable_quantile_sketch),
            "sketch_rel_err": float(sm.sketch_rel_err),
            "sketch_min_s": float(sm.sketch_min_s),
            "sketch_max_s": float(sm.sketch_max_s),
            "sketch_max_series": int(sm.sketch_max_series),
            "moments_k": int(sm.moments_k),
            "enable_target_info": bool(sm.enable_target_info),
            # the compact tier changes plane DTYPES (int32 grids, bf16
            # Kahan sums): cross-compact merges would silently truncate
            "compact_state": bool(sm.compact_state),
        },
    }
    if "trace-analytics" in inst.processors:
        # conditional: tenants without the processor keep the exact
        # fingerprints their pre-analytics checkpoints carry
        ta = inst.cfg.traceanalytics
        doc["traceanalytics"] = {
            "enable_latency_share_sketch":
                bool(ta.enable_latency_share_sketch),
            "moments_k": int(ta.moments_k),
            "sketch_max_series": int(ta.sketch_max_series),
            "share_min": float(ta.share_min),
            "share_max": float(ta.share_max),
        }
    raw = json.dumps(doc, sort_keys=True).encode()
    return hashlib.sha256(raw).hexdigest()[:16]


# ---------------------------------------------------------------------------
# family plane access (dense + paged)
# ---------------------------------------------------------------------------

def _family_kind(mt) -> str:
    from tempo_tpu.registry.registry import (Counter, Gauge, Histogram,
                                             NativeHistogram)
    if isinstance(mt, Histogram):
        return "histogram"
    if isinstance(mt, NativeHistogram):
        return "native"
    if isinstance(mt, Gauge):
        return "gauge"
    if isinstance(mt, Counter):
        return "counter"
    raise CheckpointMismatch(f"unknown family type {type(mt).__name__}")


_KIND_ROLES = {
    "counter": ("values",),
    "gauge": ("values",),
    "histogram": ("buckets", "sums", "counts"),
    "native": ("hist", "sums", "counts", "zeros"),
}


def _pad_slots(slots: np.ndarray) -> np.ndarray:
    from tempo_tpu.registry.registry import _pad_len
    padded = np.full(_pad_len(max(slots.size, 1)), -1, np.int32)
    padded[:slots.size] = slots
    return padded


def _gather_paged(plane, slots: np.ndarray) -> np.ndarray:
    got = plane.gather(_pad_slots(slots))[:slots.size]
    return np.asarray(got).astype(np.float32) \
        if got.dtype not in (np.float32, np.int32) else np.asarray(got)


def _family_rows(mt, slots: np.ndarray) -> dict[str, np.ndarray]:
    """{role: [n(, width)] host rows} for the active slots. Caller holds
    the registry state lock (paged gathers ride shared donated arenas)."""
    kind = _family_kind(mt)
    if hasattr(mt, "planes"):            # paged family
        out = {}
        for role in _KIND_ROLES[kind]:
            rows = _gather_paged(mt.planes[role], slots)
            if kind == "histogram" and role == "sums" and rows.ndim == 2:
                # compact tier: bf16 Kahan pair folds at the boundary,
                # exactly like the collect snapshot
                rows = (rows[:, 0] + rows[:, 1]).astype(np.float32)
            out[role] = rows
        return out
    st = mt.state
    if kind == "counter" or kind == "gauge":
        return {"values": np.asarray(st.values)[slots]}
    if kind == "histogram":
        return {"buckets": np.asarray(st.bucket_counts)[slots],
                "sums": np.asarray(st.sums)[slots],
                "counts": np.asarray(st.counts)[slots]}
    return {"hist": np.asarray(st.hist.counts)[slots],
            "sums": np.asarray(st.sums)[slots],
            "counts": np.asarray(st.counts)[slots],
            "zeros": np.asarray(st.zeros)[slots]}


def _paged_phys(plane, slots: np.ndarray) -> np.ndarray:
    """Arena row index per slot through the host page map (restore runs
    right after ensure_slot backed these pages)."""
    shift = plane.pool.page_shift
    pages = plane.page_map[slots >> shift].astype(np.int64)
    if (pages < 0).any():                # pragma: no cover — ensure_slot ran
        raise CheckpointMismatch("restore hit an unbacked page")
    return (pages << shift) | (slots & (plane.pool.page_rows - 1))


def _plane_scatter(plane, slots: np.ndarray, rows: np.ndarray,
                   op: str = "add") -> None:
    """Merge host rows into a paged plane (caller holds the pool lock)."""
    phys = _paged_phys(plane, slots)
    data = plane.data
    vals = rows.astype(data.dtype) if str(rows.dtype) != str(data.dtype) \
        else rows
    if op == "add":
        plane.rebind(data.at[phys].add(vals))
    elif op == "max":
        plane.rebind(data.at[phys].max(vals))
    else:
        plane.rebind(data.at[phys].set(vals))


def _family_restore(mt, slots: np.ndarray, rows: dict[str, np.ndarray]
                    ) -> None:
    """Scatter-merge checkpoint rows into the family's device planes.
    Count-like planes ADD, so merge order never matters; gauges SET —
    last-write-wins in RESTORE order, so a checkpoint restored into an
    instance that already took newer live samples overwrites them until
    the next sample lands (gauges carry no per-slot timestamp to order
    by). Caller holds the registry state lock."""
    kind = _family_kind(mt)
    if hasattr(mt, "planes"):            # paged family
        for role in _KIND_ROLES[kind]:
            vals = rows[role]
            plane = mt.planes[role]
            if kind == "histogram" and role == "sums" and plane.width == 2:
                # compact pair plane: merge into the primary column (the
                # compensation restarts at 0 — within the documented
                # compact-tier tolerance)
                pair = np.zeros((len(vals), 2), np.float32)
                pair[:, 0] = vals
                vals = pair
            if kind == "counter" and getattr(mt, "compact", False):
                vals = np.round(vals)
            _plane_scatter(plane, slots, vals,
                           op="set" if kind == "gauge" else "add")
        return
    st = mt.state
    s = np.asarray(slots, np.int32)
    if kind == "counter":
        mt.state = dataclasses.replace(
            st, values=st.values.at[s].add(rows["values"]))
    elif kind == "gauge":
        mt.state = dataclasses.replace(
            st, values=st.values.at[s].set(rows["values"]))
    elif kind == "histogram":
        mt.state = dataclasses.replace(
            st,
            bucket_counts=st.bucket_counts.at[s].add(rows["buckets"]),
            sums=st.sums.at[s].add(rows["sums"]),
            counts=st.counts.at[s].add(rows["counts"]))
    else:
        mt.state = dataclasses.replace(
            st,
            hist=dataclasses.replace(
                st.hist, counts=st.hist.counts.at[s].add(rows["hist"])),
            sums=st.sums.at[s].add(rows["sums"]),
            counts=st.counts.at[s].add(rows["counts"]),
            zeros=st.zeros.at[s].add(rows["zeros"]))


# ---------------------------------------------------------------------------
# snapshot / restore
# ---------------------------------------------------------------------------

def snapshot_instance(inst) -> bytes:
    """One tenant's full metric state as a checkpoint blob.

    Drains the device scheduler first (the drain barrier: updates
    accepted before the snapshot must be IN it — the same barrier the
    collection tick uses), then gathers every family's active rows under
    the registry state lock so the cut is consistent across the
    slot-aligned families and their sketch sidecars.

    CALLER CONTRACT: no push may be in flight on this instance — the
    handoff path fences with `wait_pushes_idle` after `pop_instance`,
    and the shutdown path joins HTTP handler threads first. The WAL
    watermark read below claims every record appended so far; a push
    racing this function could scatter+append between the watermark
    read and the state gather, landing in the blob AND above the
    watermark — double-applied on crash recovery."""
    t0 = time.perf_counter()
    inst.drain()
    reg = inst.registry
    arrays: dict[str, np.ndarray] = {}
    # ingest-WAL watermark map {member instance_id: [segment, seq]}:
    # restored watermarks carry forward (a blob that passed through
    # another member still bounds THIS member's local replay) and the
    # live watermark is read here — after the caller's push fence, so
    # every record whose scatter this snapshot gathered is covered.
    # The caller truncates segments <= checkpointed_wal_seq once the
    # blob write lands.
    wal_meta = {k: [int(v[0]), int(v[1])]
                for k, v in getattr(inst, "wal_watermarks", {}).items()}
    mark = getattr(inst, "_wal_mark", None)
    if mark is not None:
        iid, seg, seq = mark()
        wal_meta[iid] = [int(seg), int(seq)]
        inst.checkpointed_wal_seq = int(seq)
    meta: dict = {
        "version": CHECKPOINT_VERSION,
        "tenant": inst.tenant,
        "created_ts": reg.now(),
        "fingerprint": overrides_fingerprint(inst),
        "layout": inst.state_layout,
        "wal": wal_meta,
        "families": {},
        "spanmetrics": None,
    }
    with reg.state_lock:
        snap = reg.interner.snapshot()
        # one slots/keys resolve per TABLE: share_table-merged trios
        # (spanmetrics, servicegraphs edges) must not triple the key
        # payload or re-run lookup_or_create on identical rows
        tables: dict[int, dict] = {}
        for name, mt in reg._metrics.items():
            t = tables.get(id(mt.table))
            if t is None:
                slots = mt.table.active_slots()
                t = tables[id(mt.table)] = {
                    "owner": name, "slots": slots,
                    "keys": mt.table.slot_keys[slots]}
            kind = _family_kind(mt)
            meta["families"][name] = {
                "kind": kind,
                "label_names": list(mt.label_names),
                "n": int(t["slots"].size),
                "roles": list(_KIND_ROLES[kind]),
                "keys_of": t["owner"],
            }
            for role, rows in _family_rows(mt, t["slots"]).items():
                arrays[f"{name}::{role}"] = rows
        # ship ONLY the strings the checkpointed keys reference, with
        # keys remapped to indices into that list: the full interner
        # table holds every string the tenant EVER saw (purged series
        # included), and restoring it would grow blobs and the receiving
        # member's interner monotonically across handoffs
        if tables:
            ref = np.unique(np.concatenate(
                [t["keys"].ravel() for t in tables.values()]))
        else:
            ref = np.zeros(0, np.int64)
        meta["strings"] = [snap[int(i)] for i in ref]
        for t in tables.values():
            arrays[f"{t['owner']}::keys"] = np.searchsorted(
                ref, t["keys"]).astype(np.int32)
        for proc in inst.processors.values():
            fn = getattr(proc, "sketch_checkpoint", None)
            if fn is None:
                continue
            calls_slots = proc.calls.table.active_slots()
            smeta, srows = fn(calls_slots)
            if smeta is None:
                continue
            meta["spanmetrics"] = smeta
            meta["spanmetrics"]["family"] = proc.calls.name
            for k, v in srows.items():
                arrays[f"__sketch__::{k}"] = v
        # processor-keyed aux sidecars (generalized sketch slot): any
        # processor exposing aux_checkpoint ships slot-aligned planes
        # tied to one family's active-slot order (trace-analytics
        # latency-share moments ride here)
        for pname, proc in inst.processors.items():
            fn = getattr(proc, "aux_checkpoint", None)
            if fn is None:
                continue
            fam = proc.aux_family()
            ameta, arows = fn(fam.table.active_slots())
            if ameta is None:
                continue
            ameta["family"] = fam.name
            meta.setdefault("aux", {})[pname] = ameta
            for k, v in arows.items():
                arrays[f"__aux__::{pname}::{k}"] = v
    blob = _encode(meta, arrays)
    STATS["checkpoint_seconds"] += time.perf_counter() - t0
    STATS["checkpoint_bytes"] += len(blob)
    STATS["checkpoints"] += 1
    return blob


def restore_instance(inst, blob: bytes) -> dict:
    """Merge a checkpoint into a live (possibly fresh, possibly already
    ingesting) tenant instance; returns {"series", "dropped"} counts.

    Raises CheckpointMismatch (a ValueError) when the checkpoint's
    fingerprint, family layout, or sketch metadata is incompatible —
    the same guard discipline as the cross-shard sketch merges."""
    meta, arrays = _decode(blob)
    if meta.get("version") != CHECKPOINT_VERSION:
        raise CheckpointMismatch(
            f"checkpoint version {meta.get('version')} != "
            f"{CHECKPOINT_VERSION}")
    reg = inst.registry
    want_fp = overrides_fingerprint(inst)
    if meta.get("fingerprint") != want_fp:
        raise CheckpointMismatch(
            f"overrides fingerprint {meta.get('fingerprint')} does not "
            f"match this instance's {want_fp} (tenant config changed "
            "since the checkpoint was cut)")
    # sketch metadata guards run BEFORE any row is written: a half-merged
    # tenant is worse than a refused checkpoint
    sk_proc = None
    if meta.get("spanmetrics") is not None:
        for proc in inst.processors.values():
            if getattr(proc, "sketch_restore", None) is not None:
                sk_proc = proc
                proc.sketch_meta_check(meta["spanmetrics"])  # ValueError
                break
        if sk_proc is None:
            raise CheckpointMismatch(
                "checkpoint carries sketch planes but this instance has "
                "no span-metrics processor")
    # aux guards follow the same no-write-before-validation discipline
    aux_meta = meta.get("aux") or {}
    aux_procs: dict = {}
    for pname, ameta in aux_meta.items():
        proc = inst.processors.get(pname)
        if proc is None or getattr(proc, "aux_restore", None) is None:
            raise CheckpointMismatch(
                f"checkpoint carries aux planes for processor {pname!r} "
                "which is not enabled on this instance")
        proc.aux_meta_check(ameta)  # ValueError on layout mismatch
        aux_procs[pname] = proc
    strings = meta.get("strings", [])
    idmap = reg.interner.intern_many(strings) if strings \
        else np.zeros(0, np.int32)
    stats = {"series": 0, "dropped": 0}
    now = reg.now()
    with reg.state_lock:
        # per-family layout guards run BEFORE any row is written too:
        # the fingerprint narrows the config surface but does not cover
        # every family's label layout (e.g. a processor whose dimension
        # config lives outside it), and a half-merged tenant is worse
        # than a refused checkpoint
        for name, fam in meta["families"].items():
            mt = reg._metrics.get(name)
            if mt is None:
                _LOG.warning("fleet restore %s: family %s not present "
                             "live — skipped", inst.tenant, name)
                continue
            if tuple(fam["label_names"]) != mt.label_names or \
                    fam["kind"] != _family_kind(mt):
                raise CheckpointMismatch(
                    f"family {name}: checkpoint layout "
                    f"({fam['kind']}, {fam['label_names']}) != live "
                    f"({_family_kind(mt)}, {list(mt.label_names)})")
        calls_live_slots = None
        calls_ok = None
        aux_slots: dict = {}  # processor name -> (slots, ok) of its family
        resolved: dict[str, tuple] = {}  # keys_of -> (slots, ok)
        for name, fam in meta["families"].items():
            mt = reg._metrics.get(name)
            if mt is None:
                continue
            n = int(fam["n"])
            if n == 0:
                continue
            owner = fam.get("keys_of", name)
            got = resolved.get(owner)
            if got is None:
                # one lookup_or_create per shared table — the series
                # budget debits once for the slot-aligned trio, like live
                keys = arrays[f"{owner}::keys"]
                live_rows = np.ascontiguousarray(idmap[keys], np.int32)
                slots = mt.table.lookup_or_create(live_rows, now)
                ok = slots >= 0
                got = resolved[owner] = (slots, ok)
                dropped = int(n - ok.sum())
                if dropped:
                    # budget/page exhaustion mid-restore: surviving
                    # series still merge (the budget gate behaves
                    # exactly as live)
                    stats["dropped"] += dropped
                stats["series"] += int(ok.sum())
            slots, ok = got
            rows = {role: arrays[f"{name}::{role}"][ok]
                    for role in fam["roles"]}
            _family_restore(mt, slots[ok], rows)
            if sk_proc is not None and name == sk_proc.calls.name:
                calls_live_slots, calls_ok = slots, ok
            for pname in aux_procs:
                if name == aux_meta[pname]["family"]:
                    aux_slots[pname] = (slots, ok)
        if sk_proc is not None and calls_live_slots is not None:
            srows = {k[len("__sketch__::"):]: v for k, v in arrays.items()
                     if k.startswith("__sketch__::")}
            sk_proc.sketch_restore(meta["spanmetrics"], calls_live_slots,
                                   calls_ok, srows)
        for pname, proc in aux_procs.items():
            got = aux_slots.get(pname)
            if got is None:
                continue  # anchor family empty in the blob: nothing to merge
            prefix = f"__aux__::{pname}::"
            arows = {k[len(prefix):]: v for k, v in arrays.items()
                     if k.startswith(prefix)}
            proc.aux_restore(aux_meta[pname], got[0], got[1], arows)
    # merge WAL watermarks (max seq per member): the local replay must
    # skip records this blob's lineage already holds
    marks = getattr(inst, "wal_watermarks", None)
    if marks is not None:
        for iid, wm in (meta.get("wal") or {}).items():
            cur = marks.get(iid)
            if cur is None or int(wm[1]) > int(cur[1]):
                marks[iid] = [int(wm[0]), int(wm[1])]
    STATS["restores"] += 1
    STATS["restore_merged_series"] += stats["series"]
    STATS["restore_dropped_series"] += stats["dropped"]
    return stats


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def _encode(meta: dict, arrays: dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    payload = {_META_KEY: np.frombuffer(
        json.dumps(meta).encode(), np.uint8)}
    for k, v in arrays.items():
        v = np.asarray(v)
        if v.dtype not in (np.float32, np.float64, np.int32, np.int64):
            v = v.astype(np.float32)     # bf16 etc. normalize at the wire
        payload[k] = v
    np.savez_compressed(buf, **payload)
    return buf.getvalue()


def _decode(blob: bytes) -> tuple[dict, dict[str, np.ndarray]]:
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
        meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
    return meta, arrays


# ---------------------------------------------------------------------------
# object-store layout: <prefix>/<quoted tenant>/<ts>-<instance>.ckpt
# ---------------------------------------------------------------------------

def _tenant_seg(tenant: str) -> str:
    return urllib.parse.quote(tenant, safe="")


def checkpoint_name(now: float, instance_id: str) -> str:
    # zero-padded nanoseconds sort lexically = chronologically; the
    # writer id makes concurrent cuts collision-free
    return (f"{int(now * 1e9):020d}-"
            f"{urllib.parse.quote(instance_id, safe='')}{CHECKPOINT_SUFFIX}")


def write_checkpoint(writer: RawWriter, prefix: str, tenant: str,
                     blob: bytes, name: str) -> None:
    from tempo_tpu.utils import faults
    if faults.ARMED:
        faults.fire("fleet.checkpoint.write")
    writer.write(name, KeyPath((prefix, _tenant_seg(tenant))), blob)


def list_checkpoints(reader: RawReader, prefix: str
                     ) -> dict[str, list[str]]:
    """{tenant: sorted checkpoint object names} under the prefix."""
    out: dict[str, list[str]] = {}
    try:
        found = reader.find(KeyPath((prefix,)), CHECKPOINT_SUFFIX)
    except (DoesNotExist, FileNotFoundError):
        return out
    for rel in found:
        rel = rel.replace("\\", "/")
        if "/" not in rel:
            continue
        seg, name = rel.rsplit("/", 1)
        out.setdefault(urllib.parse.unquote(seg), []).append(name)
    for names in out.values():
        names.sort()
    return out


def read_checkpoint(reader: RawReader, prefix: str, tenant: str,
                    name: str) -> bytes:
    return reader.read(name, KeyPath((prefix, _tenant_seg(tenant))))


def delete_checkpoint(writer: RawWriter, prefix: str, tenant: str,
                      name: str) -> None:
    writer.delete(name, KeyPath((prefix, _tenant_seg(tenant))))


# -- store-side consumed markers --------------------------------------------
#
# Restore is a scatter-ADD, so replaying a blob double-counts every
# count-kind series. A marker object written AFTER the merge lands and
# BEFORE the blob's delete makes consumption visible to EVERY process:
# a member that crashed mid-delete, or a peer whose stale ring view
# claims the same tenant, sees the marker and deletes instead of
# re-restoring. Marker-first ordering means a crash can strand a tiny
# marker object (never a replayable blob); the consumed-cleanup path
# deletes both. Markers don't end in CHECKPOINT_SUFFIX, so
# list_checkpoints never surfaces them as blobs. The remaining hole is
# two members reading the same blob before EITHER writes its marker —
# closing that needs store-side leases, out of scope here.

CONSUMED_SUFFIX = ".consumed"


def mark_consumed(writer: RawWriter, prefix: str, tenant: str,
                  name: str) -> None:
    writer.write(name + CONSUMED_SUFFIX,
                 KeyPath((prefix, _tenant_seg(tenant))), b"1")


def is_consumed(reader: RawReader, prefix: str, tenant: str,
                name: str) -> bool:
    try:
        reader.read(name + CONSUMED_SUFFIX,
                    KeyPath((prefix, _tenant_seg(tenant))))
        return True
    except (DoesNotExist, FileNotFoundError):
        return False


def delete_consumed_marker(writer: RawWriter, prefix: str, tenant: str,
                           name: str) -> None:
    writer.delete(name + CONSUMED_SUFFIX,
                  KeyPath((prefix, _tenant_seg(tenant))))
