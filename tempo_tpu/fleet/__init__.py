"""Multi-host generator fleet: N processes as ONE logical metrics-generator.

The reference's "millions of users" topology (PAPER.md layer 1) is a
fleet of generators dividing the tenant space over a dskit ring. This
package is that topology for the device-state world:

- **Placement** (`placement.py`): tenants hash onto the existing
  generator `ring.Ring` (RF1 with spillover past unhealthy members);
  the distributor routes a tenant's whole span stream to the owning
  process, and a membership watch recomputes ownership on
  join/leave/heartbeat-expiry.
- **Checkpoint/restore** (`checkpoint.py`): a tenant's device state —
  backed pages per plane role + page table + series-table interner +
  sketch metadata — snapshots to the object-store backend as one small
  mergeable blob (the paged layout made the snapshot cheap, the moments
  tier made the merge an elementwise add). Restore rebuilds
  `PageBacking` slots through the normal series-table allocation path
  and scatter-MERGES rows (add for count planes, add+max for moments
  bounds), guarded by the existing ValueError-raising sketch merge
  checks.
- **Drain/handoff** (`controller.py`): on ownership change the losing
  process drains its sched queue for the tenant, checkpoints, and drops
  the instance; the gaining process restores and merges any in-flight
  deltas checkpointed during the transfer window. Shutdown checkpoints
  + boot restores give single-host restart-without-data-loss for free.
- **Worker** (`worker.py`): the process entry (`python -m
  tempo_tpu.fleet.worker --config fleet.yaml`) plus a standalone /kv
  CAS server for harnesses that outlive any fleet member.

Only this module is imported by `app.config` — keep it free of jax and
of the heavy siblings (lazy attribute exports below).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class FleetConfig:
    """The `fleet:` config block (generator targets only)."""

    enabled: bool = False
    # ownership re-check cadence: the membership watch fires on KV
    # updates, but heartbeat EXPIRY is a clock event no KV write
    # announces — the controller re-walks held tenants this often
    rebalance_interval_s: float = 2.0
    # snapshot every held tenant to the backend on shutdown (the
    # restart-without-data-loss half of the protocol)
    checkpoint_on_shutdown: bool = True
    # consume checkpoints addressed to this member on boot and on
    # ownership gain (restore + merge)
    restore_on_boot: bool = True
    # object-store prefix the checkpoint blobs live under
    checkpoint_prefix: str = "fleet-checkpoints"
    # transient blob-write failures retry with jittered exponential
    # backoff before the handoff falls back to reattach/orphan; retries
    # are counted in tempo_fleet_checkpoint_retries_total{cause}
    checkpoint_write_retries: int = 3
    checkpoint_retry_backoff_s: float = 0.2

    def check(self) -> list[str]:
        problems = []
        if self.rebalance_interval_s <= 0:
            problems.append(
                f"fleet.rebalance_interval_s ({self.rebalance_interval_s}) "
                "must be > 0: the ownership watch would spin")
        if not self.checkpoint_prefix or "/" in self.checkpoint_prefix:
            problems.append(
                f"fleet.checkpoint_prefix {self.checkpoint_prefix!r} must "
                "be a single non-empty path segment")
        if self.checkpoint_write_retries < 0 or \
                self.checkpoint_retry_backoff_s <= 0:
            problems.append(
                "fleet.checkpoint_write_retries must be >= 0 and "
                "checkpoint_retry_backoff_s > 0")
        return ["fleet: " + p for p in problems] if problems else []


# ---------------------------------------------------------------------------
# obs: fleet checkpoint families in the process-wide runtime registry
# (registered here — the one module every deployment imports — so the
# dashboards/alerts drift gate sees them even on non-fleet targets)
# ---------------------------------------------------------------------------

# mutated by checkpoint.py / controller.py under their own locks; plain
# int/float adds are atomic enough for counters
STATS = {
    "checkpoint_bytes": 0,
    "checkpoint_seconds": 0.0,
    "checkpoints": 0,
    "restores": 0,
    "restore_merged_series": 0,
    "restore_dropped_series": 0,
    "handoffs": 0,
}

# checkpoint blob-write retries by exception class (controller backoff
# loop; a rising rate means the object store is flapping under handoffs)
RETRY_CAUSES: dict = {}

from tempo_tpu.obs.jaxruntime import RUNTIME  # noqa: E402

RUNTIME.counter_func(
    "tempo_fleet_checkpoint_bytes_total",
    lambda: [((), float(STATS["checkpoint_bytes"]))],
    help="Bytes of tenant device-state checkpoints written to the "
         "object store (runbook 'Operating a generator fleet')")
RUNTIME.counter_func(
    "tempo_fleet_checkpoint_seconds_total",
    lambda: [((), float(STATS["checkpoint_seconds"]))],
    help="Wall seconds spent cutting tenant checkpoints (drain + "
         "gather + encode + backend write)")
RUNTIME.counter_func(
    "tempo_fleet_checkpoints_total",
    lambda: [((), float(STATS["checkpoints"]))],
    help="Tenant checkpoints written (handoffs + shutdown snapshots)")
RUNTIME.counter_func(
    "tempo_fleet_checkpoint_restores_total",
    lambda: [((), float(STATS["restores"]))],
    help="Tenant checkpoints restored-and-merged into this process "
         "(boot restores + handoff receives)")
RUNTIME.counter_func(
    "tempo_fleet_checkpoint_retries_total",
    lambda: [((cause,), float(n)) for cause, n in RETRY_CAUSES.items()],
    help="Checkpoint blob-write retries by failure cause (jittered "
         "backoff before reattach/orphan fallback; runbook 'Operating "
         "a generator fleet')",
    labels=("cause",))
RUNTIME.counter_func(
    "tempo_fleet_handoffs_total",
    lambda: [((), float(STATS["handoffs"]))],
    help="Tenants this process drained, checkpointed, and released "
         "because ring ownership moved elsewhere")


def __getattr__(name: str):
    """Lazy exports: the heavy halves import jax/generator machinery."""
    if name in ("TenantPlacement", "tenant_token"):
        from tempo_tpu.fleet import placement
        return getattr(placement, name)
    if name in ("snapshot_instance", "restore_instance",
                "CheckpointMismatch", "write_checkpoint",
                "list_checkpoints", "read_checkpoint", "delete_checkpoint"):
        from tempo_tpu.fleet import checkpoint
        return getattr(checkpoint, name)
    if name == "FleetController":
        from tempo_tpu.fleet.controller import FleetController
        return FleetController
    raise AttributeError(name)


__all__ = ["FleetConfig", "FleetController", "TenantPlacement", "STATS",
           "tenant_token", "snapshot_instance", "restore_instance",
           "CheckpointMismatch"]
