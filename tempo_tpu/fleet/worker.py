"""Fleet process entries.

Two runnable shapes:

- `python -m tempo_tpu.fleet.worker --config fleet.yaml` — one fleet
  member: a normal App (usually `target: metrics-generator` with
  `fleet.enabled: true`) whose HTTP server carries the RPC plane, the
  /kv CAS routes when it hosts ring state, and /status.
- `python -m tempo_tpu.fleet.worker --kv-only --port N` — a standalone
  /kv CAS server (same wire surface as the App routes, backed by one
  `KVStore`). Harnesses use it so ring state SURVIVES any fleet member
  being killed — the memberlist-cluster stand-in that is nobody's
  single process.

Both print one JSON "ready" line to stdout (`{"ready": true, "port": N}`)
so a parent process can wait deterministically instead of polling.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote


def make_kv_server(port: int = 0, host: str = "127.0.0.1"
                   ) -> ThreadingHTTPServer:
    """A /kv-only CAS HTTP server over a fresh KVStore (wire-compatible
    with the App's /kv routes — `ring.kv._HttpEndpoint` is the client).
    Caller starts/stops it; `.kv_port` carries the bound port."""
    from tempo_tpu.ring.kv import KVStore, _value_from_json, _value_to_json

    store = KVStore()

    class _KVHandler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def _reply(self, code: int, body: dict | None = None) -> None:
            data = json.dumps(body or {}).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _key(self) -> str | None:
            if not self.path.startswith("/kv/"):
                self._reply(404, {"error": "kv-only server"})
                return None
            return unquote(self.path[len("/kv/"):])

        def do_GET(self) -> None:  # noqa: N802
            key = self._key()
            if key is None:
                return
            ver, val = store.get_versioned(key)
            if val is None and ver == 0:
                return self._reply(404, {"error": f"no key {key}"})
            self._reply(200, {"version": ver, "value": _value_to_json(val)})

        def do_POST(self) -> None:  # noqa: N802
            key = self._key()
            if key is None:
                return
            n = int(self.headers.get("Content-Length", 0) or 0)
            d = json.loads(self.rfile.read(n))
            ok, ver = store.cas_versioned(
                key, int(d["expect_version"]), _value_from_json(d["value"]))
            if not ok:
                return self._reply(409, {"error": "version conflict",
                                         "version": ver})
            self._reply(200, {"version": ver})

        def do_DELETE(self) -> None:  # noqa: N802
            key = self._key()
            if key is None:
                return
            store.delete(key)
            self._reply(200, {})

    srv = ThreadingHTTPServer((host, port), _KVHandler)
    srv.kv_store = store
    srv.kv_port = srv.server_address[1]
    return srv


def _announce_ready(port: int) -> None:
    print(json.dumps({"ready": True, "port": port}), flush=True)


# ---------------------------------------------------------------------------
# parent-side spawn/reap (bench.py and the test harness share these — the
# worker lifecycle must not drift between two copies)
# ---------------------------------------------------------------------------

def _discard_pipe(pipe) -> None:
    try:
        for _ in iter(pipe.readline, ""):
            pass
    except (ValueError, OSError):
        pass                            # reap closed the pipe under us


def spawn_worker(args: list[str], env: dict | None = None,
                 wait_ready_s: float = 60.0, cwd: str | None = None):
    """Spawn `python -m tempo_tpu.fleet.worker ...`; block until its JSON
    ready line (or death, surfaced with the stderr tail; not-ready
    timeout kills the child — never leaks). After ready, both pipes are
    handed to daemon drain threads: a chatty child (warning spew,
    handoff-retry tracebacks) must never block on a full 64KB pipe
    buffer mid-soak. Returns the Popen with `.ready` (the parsed line)
    attached."""
    import os
    import select
    import subprocess
    import time

    e = dict(os.environ)
    e.update(env or {})
    p = subprocess.Popen(
        [sys.executable, "-m", "tempo_tpu.fleet.worker", *args],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=cwd, env=e)
    # stderr must drain BEFORE ready too: heavy startup spew (platform
    # warnings, config.check noise) filling the unread 64KB pipe would
    # block the child in write() and it never reaches its ready line.
    # The tail is kept so a death/timeout still reports the real cause.
    err_tail: list[str] = []

    def read_err() -> None:
        line = p.stderr.readline()
        if line:
            err_tail.append(line)
            del err_tail[:-40]
    deadline = time.time() + wait_ready_s
    while time.time() < deadline:
        if p.poll() is not None:
            err_tail.append(p.stderr.read() or "")
            raise RuntimeError(
                f"fleet worker died rc={p.returncode} before ready: "
                f"{''.join(err_tail)[-2000:]}")
        readable, _, _ = select.select([p.stdout, p.stderr], [], [], 0.2)
        if p.stderr in readable:
            read_err()
        if p.stdout not in readable:
            continue
        line = p.stdout.readline()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if doc.get("ready"):
            p.ready = doc
            for pipe in (p.stdout, p.stderr):
                threading.Thread(target=_discard_pipe, args=(pipe,),
                                 daemon=True).start()
            return p
    p.kill()
    p.wait(timeout=5)
    raise RuntimeError(f"fleet worker not ready in {wait_ready_s}s: "
                       f"{''.join(err_tail)[-2000:]}")


def reap_workers(procs, term_wait_s: float = 10.0) -> None:
    """SIGTERM every child, bounded wait, SIGKILL fallback, close pipes
    — a failing caller must not leak generator processes."""
    import subprocess
    import time

    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + term_wait_s
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=5)
        for pipe in (p.stdout, p.stderr):
            if pipe:
                try:
                    pipe.close()
                except OSError:
                    pass


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tempo_tpu.fleet.worker",
        description="Run one generator-fleet member (or a KV-only "
                    "ring-state server)")
    ap.add_argument("--config", help="App YAML (fleet member mode)")
    ap.add_argument("--kv-only", action="store_true",
                    help="serve only the /kv CAS routes")
    ap.add_argument("--port", type=int, default=0,
                    help="kv-only listen port (0 = ephemeral)")
    args = ap.parse_args(argv)

    if args.kv_only:
        srv = make_kv_server(args.port)
        _announce_ready(srv.kv_port)
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            srv.shutdown()
        return 0

    if not args.config:
        ap.error("--config is required unless --kv-only")
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.app import App
    from tempo_tpu.app.config import load_config

    app = App(load_config(args.config))
    app.start_loops()
    srv = serve(app, block=False)
    # handler threads must be JOINABLE: a push acked to the client after
    # the shutdown checkpoint gathered would be silently lost, so
    # shutdown below stops accepting, JOINS in-flight handlers, and only
    # then lets App.shutdown cut the checkpoints
    srv.daemon_threads = False
    # announce the BOUND port, not the configured one: port 0 (ephemeral)
    # must hand the parent a dialable address. The ring joined at App
    # construction with the configured port, so ephemeral members must
    # also re-advertise: patch the config, rewrite each lifecycler's
    # addr, and heartbeat to republish the descriptor before traffic
    # resolves it. (Ephemeral mode needs an explicit instance_id — the
    # derived hostname-port id would collide between two :0 members.)
    bound = srv.server_address[1]
    if bound != app.cfg.server.http_listen_port:
        app.cfg.server.http_listen_port = bound
        for lc in app._lifecyclers:
            lc.desc.addr = app._advertise()
            lc.heartbeat()
    _announce_ready(bound)
    # SIGTERM must run the graceful path: App.shutdown cuts the
    # shutdown checkpoints the restart/handoff protocol depends on
    stop = threading.Event()
    import signal
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    srv.shutdown()
    srv.server_close()                  # joins in-flight handler threads
    app.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
