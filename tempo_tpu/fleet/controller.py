"""FleetController: the per-process half of the fleet protocol.

Owns this member's reaction to membership change:

- **watch loop** — re-walks ownership of every HELD tenant each tick
  (ticks fire on KV ring updates AND on a timer: heartbeat EXPIRY is a
  clock event no KV write announces), plus scans the checkpoint prefix
  for blobs addressed to tenants this member now owns.
- **drain/handoff** — a lost tenant is drained (sched flush + pipeline
  drain, inside `snapshot_instance`), checkpointed to the object store,
  and its local instance dropped; the distributor's tenant-placement
  routing converges to the new owner on its own ring view. Spans that
  still land here during the convergence window accrete into a fresh
  instance and are checkpointed again next tick — nothing is dropped,
  the receiving side MERGES (checkpoint.py restore semantics).
- **restore** — on boot and on ownership gain, checkpoints for owned
  tenants restore-and-merge into the live instance, then the consumed
  blob is deleted. Incompatible blobs (CheckpointMismatch /
  sketch-merge ValueError) are quarantined in place and surfaced on
  /status rather than retried forever or silently deleted.

Shutdown checkpoints + boot restores are the same two code paths, which
is how single-host restart-without-data-loss falls out for free.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable

from tempo_tpu.fleet import RETRY_CAUSES, STATS, FleetConfig
from tempo_tpu.fleet import checkpoint as ck
from tempo_tpu.fleet.placement import TenantPlacement
from tempo_tpu.utils import tracing

_LOG = logging.getLogger("tempo_tpu.fleet")

# a checkpoint that failed to restore N times is quarantined (kept in
# the store for inspection, skipped by the watch loop)
_RESTORE_ATTEMPTS = 3


class FleetController:
    def __init__(self, generator, ring, instance_id: str, reader, writer,
                 cfg: FleetConfig | None = None,
                 now: Callable[[], float] = time.time) -> None:
        self.generator = generator
        self.ring = ring
        self.id = instance_id
        self.reader = reader
        self.writer = writer
        self.cfg = cfg or FleetConfig()
        self.now = now
        self.placement = TenantPlacement(ring, instance_id)
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        # (tenant, name) -> consecutive restore failures; at
        # _RESTORE_ATTEMPTS the blob is quarantined
        self._restore_fails: dict[tuple[str, str], int] = {}
        # blobs restored whose DELETE failed: the restore is a
        # scatter-ADD, so replaying one double-counts every series —
        # these are never restored again by this process, only the
        # delete is retried. (In-memory: a crash between restore and
        # delete still replays on the next boot — closing that window
        # needs a restore marker in the store itself.)
        self._consumed: set[tuple[str, str]] = set()
        # instances popped for handoff whose checkpoint write failed
        # AND whose tenant slot was already re-occupied by a straggler
        # push: invisible to the lost() walk, retried every tick until
        # the snapshot lands (state + pool pages must not leak)
        self._orphans: dict[str, list] = {}
        self._lock = threading.Lock()   # serializes tick/shutdown
        self.last_tick_ts = 0.0
        # boot-time ingest-WAL replay runs exactly once, AFTER the boot
        # restore pass populated the per-member watermarks (a second
        # pass would re-apply scatter-adds)
        self._wal_replayed = False
        # ring updates should react faster than the poll interval:
        # a KV publish nudges the loop awake
        kv = getattr(ring, "kv", None)
        if kv is not None:
            try:
                kv.watch_key(ring.key, lambda _v: self._wake.set())
            except Exception:
                pass

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        if self.cfg.restore_on_boot:
            try:
                self.tick()          # boot restore before traffic builds
            except Exception:
                _LOG.exception("fleet %s: boot restore failed", self.id)
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                self._wake.wait(self.cfg.rebalance_interval_s)
                self._wake.clear()
                if self._stop.is_set():
                    return
                try:
                    self.tick()
                except Exception:
                    _LOG.exception("fleet %s: tick failed", self.id)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"fleet-{self.id}")
        self._thread.start()

    def shutdown(self) -> None:
        """Stop the watch loop, then snapshot every held tenant so a
        restart (or the next owner) restores without data loss."""
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)
        self._thread = None
        if self.cfg.checkpoint_on_shutdown:
            with self._lock:
                self._retry_orphans()
                for tenant in self._held():
                    try:
                        self._checkpoint(tenant, remove=False)
                    except Exception:
                        _LOG.exception("fleet %s: shutdown checkpoint of "
                                       "%s failed", self.id, tenant)

    def _shutdown_fence(self, inst) -> None:
        """Best-effort in-flight fence for the shutdown (non-remove)
        snapshot. The supported deployment entry (fleet.worker) JOINS
        its HTTP handler threads before App.shutdown, so nothing is in
        flight here; an embedding that keeps pushing through shutdown
        still gets the bounded wait, shrinking the watermark-vs-gather
        race snapshot_instance's caller contract describes."""
        if not inst.wait_pushes_idle(5.0):
            _LOG.warning("fleet %s: pushes still in flight for %s at "
                         "shutdown snapshot — join handler threads "
                         "before App.shutdown (fleet.worker does)",
                         self.id, inst.tenant)

    # -- the watch tick ----------------------------------------------------

    def _held(self) -> list[str]:
        # the selftrace loopback tenant never participates in placement:
        # its spans describe THIS process and must stay local to it —
        # handing it off would interleave two processes' self-traces in
        # one instance and checkpoint state the source can't replay
        reserved = tracing.reserved_tenant()
        return [t for t in self.generator.tenants() if t != reserved]

    def tick(self) -> None:
        """One ownership pass: hand off lost tenants, restore gained
        checkpoints. Safe to call concurrently with ingest — every state
        mutation rides the registry/sched locks."""
        with self._lock:
            self.last_tick_ts = self.now()
            self._retry_orphans()
            for tenant, new_owner in self.placement.lost(self._held()):
                try:
                    self._handoff(tenant, new_owner)
                except Exception:
                    _LOG.exception("fleet %s: handoff of %s to %s failed "
                                   "(state retained; retried next tick)",
                                   self.id, tenant, new_owner)
            if self.cfg.restore_on_boot:
                self._restore_owned()
            if not self._wal_replayed:
                # ingest-WAL replay: every tenant with local segments,
                # past the watermark the restore pass (above) merged in.
                # Owned or not — these acked records exist nowhere else;
                # a non-owned tenant's replayed state hands off next tick.
                self._wal_replayed = True
                try:
                    got = self.generator.replay_wal_all()
                    if got["batches"] or got["dead_letters"]:
                        _LOG.info(
                            "fleet %s: WAL replay recovered %d batches "
                            "across %d tenants (%d dead-lettered)",
                            self.id, got["batches"], got["tenants"],
                            got["dead_letters"])
                except Exception:
                    _LOG.exception("fleet %s: WAL replay failed", self.id)

    def _write_checkpoint_blob(self, tenant: str, blob: bytes) -> None:
        """Write one checkpoint blob with bounded jittered-backoff
        retries: a transient store failure during a handoff otherwise
        forces the whole reattach/orphan dance for nothing."""
        delay = self.cfg.checkpoint_retry_backoff_s
        for attempt in range(self.cfg.checkpoint_write_retries + 1):
            try:
                ck.write_checkpoint(
                    self.writer, self.cfg.checkpoint_prefix, tenant, blob,
                    ck.checkpoint_name(self.now(), self.id))
                return
            except Exception as e:
                if attempt >= self.cfg.checkpoint_write_retries:
                    raise
                cause = type(e).__name__
                RETRY_CAUSES[cause] = RETRY_CAUSES.get(cause, 0) + 1
                _LOG.warning(
                    "fleet %s: checkpoint write of %s failed (%s: %s), "
                    "retry %d/%d", self.id, tenant, cause, e,
                    attempt + 1, self.cfg.checkpoint_write_retries)
                time.sleep(delay * (0.5 + random.random()))
                delay = min(delay * 2, 5.0)

    def _truncate_wal(self, tenant: str, inst) -> None:
        """Drop WAL segments the just-written blob covers (the snapshot
        recorded its own watermark on the instance)."""
        try:
            self.generator.truncate_wal(
                tenant, getattr(inst, "checkpointed_wal_seq", None))
        except Exception:
            _LOG.exception("fleet %s: WAL truncation of %s failed "
                           "(replay stays watermark-guarded)",
                           self.id, tenant)

    def _retry_orphans(self) -> None:
        """Re-attempt checkpoints of handoff-popped instances whose
        snapshot/write failed while a replacement instance occupied the
        tenant slot (see _checkpoint): they are in nobody's tenant map,
        so only this loop can flush their state and free their pages."""
        for tenant, insts in list(self._orphans.items()):
            left = []
            for inst in insts:
                if not inst.wait_pushes_idle(2.0):
                    # detached: no new pushes can enter, so this drains
                    # eventually — snapshotting past the fence could
                    # lose the straggler (see _checkpoint)
                    left.append(inst)
                    continue
                try:
                    blob = ck.snapshot_instance(inst)
                    self._write_checkpoint_blob(tenant, blob)
                    self._truncate_wal(tenant, inst)
                    self.generator.release_instance_pages(inst)
                except Exception:
                    _LOG.exception("fleet %s: orphan checkpoint of %s "
                                   "still failing", self.id, tenant)
                    left.append(inst)
            if left:
                self._orphans[tenant] = left
            else:
                self._orphans.pop(tenant, None)

    def _handoff(self, tenant: str, new_owner: str) -> None:
        _LOG.info("fleet %s: handing off tenant %s to %s",
                  self.id, tenant, new_owner)
        with tracing.span_for_tenant("fleet.handoff", tenant,
                                     new_owner=new_owner):
            self._checkpoint(tenant, remove=True)
        STATS["handoffs"] += 1

    def _orphan(self, tenant: str, inst) -> None:
        """Stash a popped instance the tenant slot already replaced.
        Its eventual checkpoint must NOT claim the tenant's WAL
        watermark: the replacement instance owns the live WAL stream
        now, and a claim here would truncate records whose state lives
        only in the replacement."""
        inst._wal_mark = None
        self._orphans.setdefault(tenant, []).append(inst)

    def _checkpoint(self, tenant: str, remove: bool) -> None:
        if remove:
            # handoff order matters: POP first (later pushes build a
            # fresh instance that the next tick hands off again — and,
            # with the WAL on, skip appends for the duration of the cut
            # so the snapshot's watermark claim can never cover a
            # replacement instance's records), fence in-flight handler
            # threads, and only then cut the snapshot — an acked push
            # must always be in SOME checkpoint
            inst = self.generator.pop_instance(tenant)
            if inst is None:
                self.generator.end_handoff(tenant)
                return
            try:
                if not inst.wait_pushes_idle(5.0):
                    # NEVER checkpoint past the fence: a straggler
                    # scatter landing after the snapshot would be lost
                    # outright when the pages release below (acked push,
                    # zeroed page). The instance is detached, so no NEW
                    # push can enter it — put it back (or orphan it) and
                    # retry once it drains.
                    _LOG.warning("fleet %s: pushes still in flight for "
                                 "%s after 5s fence; handoff retried "
                                 "next tick", self.id, tenant)
                    if not self.generator.reattach_instance(tenant, inst):
                        self._orphan(tenant, inst)
                    return
                try:
                    with tracing.span_for_tenant("fleet.checkpoint",
                                                 tenant, remove=True):
                        blob = ck.snapshot_instance(inst)
                        self._write_checkpoint_blob(tenant, blob)
                except Exception:
                    # the pop already happened: a failed snapshot/write
                    # must not lose the accrued state or leak its pages
                    # — put the instance back (the lost() walk retries
                    # next tick), or stash it for the orphan loop if a
                    # straggler push already rebuilt the tenant slot
                    if not self.generator.reattach_instance(tenant, inst):
                        self._orphan(tenant, inst)
                    raise
                self._truncate_wal(tenant, inst)
                self.generator.release_instance_pages(inst)
            finally:
                self.generator.end_handoff(tenant)
            return
        inst = self.generator.instances.get(tenant)
        if inst is None:
            return
        self._shutdown_fence(inst)
        with tracing.span_for_tenant("fleet.checkpoint", tenant,
                                     remove=False):
            blob = ck.snapshot_instance(inst)
            self._write_checkpoint_blob(tenant, blob)
        self._truncate_wal(tenant, inst)

    def _restore_owned(self) -> None:
        all_ckpts = ck.list_checkpoints(self.reader,
                                        self.cfg.checkpoint_prefix)
        for tenant, names in all_ckpts.items():
            if not self.placement.owns(tenant):
                continue
            for name in names:
                key = (tenant, name)
                if key in self._consumed:
                    # already restored; only the delete failed. NEVER
                    # restore again (scatter-add replay double-counts) —
                    # just retry the delete
                    self._delete_consumed(tenant, name, key)
                    continue
                if self._restore_fails.get(key, 0) >= _RESTORE_ATTEMPTS:
                    continue            # quarantined
                try:
                    consumed = ck.is_consumed(self.reader,
                                              self.cfg.checkpoint_prefix,
                                              tenant, name)
                except Exception:
                    continue            # store unreachable: next tick
                if consumed:
                    # another process (or a prior crashed run of this
                    # one) merged this blob and died before deleting it:
                    # clean up, never replay
                    _LOG.info("fleet %s: checkpoint %s/%s carries a "
                              "consumed marker — deleting without "
                              "restore", self.id, tenant, name)
                    self._delete_consumed(tenant, name, key)
                    continue
                try:
                    blob = ck.read_checkpoint(
                        self.reader, self.cfg.checkpoint_prefix, tenant,
                        name)
                except Exception:
                    continue            # listed-then-consumed race: skip
                inst = self.generator.instance(tenant)
                try:
                    with tracing.span_for_tenant("fleet.restore", tenant,
                                                 blob=name):
                        stats = ck.restore_instance(inst, blob)
                except ValueError as e:
                    # CheckpointMismatch / sketch merge guard: poison —
                    # quarantine immediately, keep the blob for forensics
                    self._restore_fails[key] = _RESTORE_ATTEMPTS
                    _LOG.error("fleet %s: checkpoint %s/%s incompatible, "
                               "quarantined: %s", self.id, tenant, name, e)
                    continue
                except Exception:
                    self._restore_fails[key] = \
                        self._restore_fails.get(key, 0) + 1
                    _LOG.exception("fleet %s: restore of %s/%s failed "
                                   "(attempt %d/%d)", self.id, tenant, name,
                                   self._restore_fails[key],
                                   _RESTORE_ATTEMPTS)
                    continue
                _LOG.info("fleet %s: restored %s/%s (%d series, %d "
                          "dropped)", self.id, tenant, name,
                          stats["series"], stats["dropped"])
                self._consumed.add(key)
                try:
                    # marker BEFORE delete: a crash between the two
                    # strands a tiny marker, never a replayable blob
                    ck.mark_consumed(self.writer,
                                     self.cfg.checkpoint_prefix, tenant,
                                     name)
                except Exception:
                    _LOG.exception("fleet %s: consumed marker for %s/%s "
                                   "failed (in-memory guard still held)",
                                   self.id, tenant, name)
                self._delete_consumed(tenant, name, key)
                self._restore_fails.pop(key, None)

    def _delete_consumed(self, tenant: str, name: str,
                         key: tuple[str, str]) -> None:
        """Delete a restored blob + its consumed marker; key leaves the
        in-memory consumed set only once the blob is really gone."""
        from tempo_tpu.backend.raw import DoesNotExist
        try:
            ck.delete_checkpoint(self.writer, self.cfg.checkpoint_prefix,
                                 tenant, name)
        except (DoesNotExist, FileNotFoundError):
            pass                        # a peer already deleted it
        except Exception:
            self._consumed.add(key)
            _LOG.exception("fleet %s: delete of consumed checkpoint "
                           "%s/%s failed (retried next tick)",
                           self.id, tenant, name)
            return
        self._consumed.discard(key)
        try:
            ck.delete_consumed_marker(self.writer,
                                      self.cfg.checkpoint_prefix, tenant,
                                      name)
        except (DoesNotExist, FileNotFoundError):
            pass
        except Exception:
            _LOG.warning("fleet %s: stale consumed marker left for "
                         "%s/%s", self.id, tenant, name)

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        held = self._held()
        owned = [t for t in held if self.placement.owns(t)]
        # dict/set .copy() are atomic under the GIL; iterating the LIVE
        # containers would race the tick thread's inserts (RuntimeError:
        # changed size during iteration → intermittent /status 500s)
        fails = self._restore_fails.copy()
        orphans = self._orphans.copy()
        quarantined = [f"{t}/{n}" for (t, n), c in fails.items()
                       if c >= _RESTORE_ATTEMPTS]
        return {
            "instance": self.id,
            "held_tenants": len(held),
            "owned_tenants": len(owned),
            "foreign_tenants": sorted(set(held) - set(owned))[:20],
            "last_tick_age_s": round(self.now() - self.last_tick_ts, 3)
            if self.last_tick_ts else None,
            "quarantined_checkpoints": quarantined,
            "orphaned_instances": sum(len(v) for v in orphans.values()),
            "pending_checkpoint_deletes": len(self._consumed),
            "checkpoints_written": STATS["checkpoints"],
            "restores": STATS["restores"],
            "handoffs": STATS["handoffs"],
        }
