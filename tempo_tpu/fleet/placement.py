"""Tenant placement over the generator ring.

One tenant = one token = one healthy owner (RF1 with spillover past
unhealthy members — `Ring.owner_of`). The distributor and every fleet
member hash tenants the SAME way, so routing and ownership agree from
independent ring views; disagreement during convergence windows is
resolved by the checkpoint/merge protocol (controller.py), never by
dropping state.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterable

from tempo_tpu.ring.ring import _hash_str

if TYPE_CHECKING:  # pragma: no cover
    from tempo_tpu.ring import InstanceDesc, Ring


def tenant_token(tenant: str) -> int:
    """The ring token a tenant's whole series space hashes to. Shared by
    the distributor's tenant-placement routing and the fleet ownership
    watch — the two MUST agree or a tenant's spans and its checkpoints
    would land on different members."""
    return _hash_str("fleet-tenant/" + tenant)


class TenantPlacement:
    """This member's view of tenant→owner over a live ring."""

    def __init__(self, ring: "Ring", instance_id: str) -> None:
        self.ring = ring
        self.id = instance_id

    def owner(self, tenant: str) -> "InstanceDesc | None":
        return self.ring.owner_of(tenant_token(tenant))

    def owns(self, tenant: str) -> bool:
        return self.ring.owns(self.id, tenant_token(tenant))

    def lost(self, tenants: Iterable[str]) -> list[tuple[str, str]]:
        """(tenant, new_owner_id) for held tenants this member no longer
        owns. Tenants with NO resolvable owner (empty/all-dead ring) are
        not reported — releasing state with nowhere to send it would
        strand the checkpoint until the ring heals anyway, and the local
        instance keeps serving meanwhile."""
        out = []
        for t in tenants:
            owner = self.owner(t)
            if owner is not None and owner.id != self.id:
                out.append((t, owner.id))
        return out
