{{- define "tempo-tpu.fullname" -}}
{{- /* leave room for the longest "-<target>" suffix (-metrics-generator,
       18 chars) under the 63-char DNS label limit */ -}}
{{- printf "%s" .Release.Name | trunc 44 | trimSuffix "-" -}}
{{- end -}}

{{- define "tempo-tpu.labels" -}}
app.kubernetes.io/name: tempo-tpu
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
{{- end -}}

{{- define "tempo-tpu.selector" -}}
app.kubernetes.io/name: tempo-tpu
app.kubernetes.io/instance: {{ .Release.Name }}
{{- end -}}
