{{- define "tempo-tpu.fullname" -}}
{{- printf "%s" .Release.Name | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "tempo-tpu.labels" -}}
app.kubernetes.io/name: tempo-tpu
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
{{- end -}}
