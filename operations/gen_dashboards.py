#!/usr/bin/env python3
"""Single-source dashboard generator (tempo-mixin `dashboards.libsonnet`
analog — the reference generates its Grafana dashboards from jsonnet so
panels and recording rules cannot drift; here one Python spec generates
the dashboards under operations/dashboards/, and a CI test
regenerates them and fails on drift, the same guarantee without a jsonnet
toolchain).

Usage: python operations/gen_dashboards.py [--check]
  --check: exit 1 if any committed dashboard differs from the generated
  output (the drift gate tests/test_aux.py runs).

Every metric name referenced here is also covered by
tests/test_app.py::test_ops_files_reference_only_emitted_metrics, so a
panel can neither drift from this spec nor reference a metric the server
does not emit.
"""

from __future__ import annotations

import json
import os
import sys

OUT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "dashboards")

SERIES_BUDGET = 65536      # default max_active_series (overrides.py)


def p(title: str, *exprs: str, kind: str = "timeseries",
      unit: str | None = None, legend: str | None = None) -> dict:
    """One panel; grid position is assigned by `dash` (3 per row)."""
    panel: dict = {"title": title, "type": kind,
                   "targets": [{"expr": e} for e in exprs]}
    if legend:
        for t in panel["targets"]:
            t["legendFormat"] = legend
    if unit:
        panel["fieldConfig"] = {"defaults": {"max": 1, "min": 0,
                                             "unit": unit}}
    return panel


def dash(title: str, description: str, panels: list[dict]) -> dict:
    for i, panel in enumerate(panels):
        panel["gridPos"] = {"x": (i % 3) * 8, "y": (i // 3) * 8,
                            "w": 8, "h": 8}
    return {"title": title, "description": description,
            "schemaVersion": 39, "panels": panels}


def _rate(metric: str, by: str | None = None, win: str = "5m") -> str:
    e = f"rate({metric}[{win}])"
    return f"sum({e}) by ({by})" if by else f"sum({e})"


def _ratio(hit: str, miss: str, win: str = "5m") -> str:
    return (f"rate({hit}[{win}]) / (rate({hit}[{win}])"
            f" + rate({miss}[{win}]))")


def _p99(metric: str, by: str | None = None, win: str = "5m") -> str:
    """p99 from an obs-registry histogram's cumulative buckets."""
    grp = f"le, {by}" if by else "le"
    return (f"histogram_quantile(0.99, "
            f"sum(rate({metric}_bucket[{win}])) by ({grp}))")


def dashboards() -> dict[str, dict]:
    slo_ratio = (
        "sum(rate(tempo_query_frontend_queries_within_slo_total[5m])) by (op)"
        " / sum(rate(tempo_query_frontend_queries_total[5m])) by (op)")
    return {
        "tempo-tpu-overview.json": dash(
            "tempo-tpu / overview",
            "Operational overview over tempo_tpu self-metrics (tempo-mixin"
            " dashboard analog, rewritten for this build's metric names).",
            [
                p("Spans received /s",
                  _rate("tempo_distributor_spans_received_total")),
                p("Bytes received /s",
                  _rate("tempo_distributor_bytes_received_total")),
                p("Discarded spans /s by reason",
                  _rate("tempo_discarded_spans_total", "reason"),
                  legend="{{reason}}"),
                p("Live traces per ingester tenant",
                  "sum(tempo_ingester_live_traces) by (tenant)",
                  legend="{{tenant}}"),
                p("Generator spans /s",
                  _rate("tempo_metrics_generator_spans_received_total",
                        "tenant"), legend="{{tenant}}"),
                p("Generator active series",
                  "sum(tempo_metrics_generator_registry_active_series)"
                  " by (tenant)", legend="{{tenant}}"),
                p("Queries /s by op",
                  _rate("tempo_query_frontend_queries_total", "op"),
                  legend="{{op}}"),
                p("Within-SLO ratio by op", slo_ratio,
                  unit="percentunit", legend="{{op}}"),
                p("Data-quality warnings /s",
                  _rate("tempo_warnings_total", "reason"),
                  legend="{{reason}}"),
                p("HTTP p99 latency by route",
                  _p99("tempo_request_duration_seconds", "route"),
                  legend="{{route}}"),
                p("gRPC p99 latency by method",
                  _p99("tempo_grpc_request_duration_seconds", "method"),
                  legend="{{method}}"),
                p("Distributor push p99",
                  _p99("tempo_distributor_push_duration_seconds")),
                # self-tracing loopback (runbook "Tracing Tempo with
                # Tempo"): the system's own trace pipeline health — span
                # volume vs kept trees, the drop ratio the
                # TempoSelfTraceDropHigh alert fires on, and tail-keep
                # buffer pressure (sizing signal for max_trace_spans)
                p("Self-trace spans /s: recorded, kept trees, loopback",
                  _rate("tempo_selftrace_spans_total"),
                  _rate("tempo_selftrace_kept_traces_total"),
                  _rate("tempo_selftrace_loopback_batches_total")),
                p("Self-trace drop ratio (alert fires > 1%)",
                  "rate(tempo_selftrace_dropped_spans_total[5m]) /"
                  " clamp_min(rate(tempo_selftrace_spans_total[5m]),"
                  " 1e-9)", unit="percentunit"),
                p("Self-trace tail buffer + export retries /s",
                  "tempo_selftrace_tail_buffer_spans",
                  _rate("tempo_selftrace_export_retries_total")),
            ]),
        "tempo-tpu-reads.json": dash(
            "Tempo-TPU / Reads",
            "Read path: frontend SLOs, response cache, device read plane"
            " routing (tempo-mixin tempo-reads.json analog)",
            [
                p("Queries /s by op",
                  _rate("tempo_query_frontend_queries_total", "op")),
                p("Within-SLO ratio by op", slo_ratio),
                p("Frontend cache hit ratio",
                  _ratio("tempo_query_frontend_cache_hits_total",
                         "tempo_query_frontend_cache_misses_total")),
                p("Device-plane fused blocks /s",
                  "rate(tempo_read_plane_fused_metric_blocks_total[5m])"),
                p("Host-fallback blocks /s",
                  "rate(tempo_read_plane_host_metric_blocks_total[5m])"),
                # fused-vs-host routing ratio (runbook "Reading the read
                # plane"): the warm-read overhang in one number — the
                # TempoReadPlaneFallbackHigh alert fires when the host
                # share of metric blocks stays above 25%
                p("Host-fallback block share (alert fires > 25%)",
                  "rate(tempo_read_plane_host_metric_blocks_total[5m]) /"
                  " clamp_min("
                  "rate(tempo_read_plane_fused_metric_blocks_total[5m])"
                  " + rate(tempo_read_plane_host_metric_blocks_total[5m]),"
                  " 1e-9)", unit="percentunit"),
                p("Plane cache hit ratio",
                  _ratio("tempo_read_plane_cache_hits_total",
                         "tempo_read_plane_cache_misses_total")),
                p("Plane cache device bytes",
                  "tempo_read_plane_cache_device_bytes"),
                p("Plane cache host bytes",
                  "tempo_read_plane_cache_host_bytes"),
                p("Plane cache entries", "tempo_read_plane_cache_entries"),
                p("Host fallbacks /s by cause",
                  _rate("tempo_read_plane_fallback_total", "cause"),
                  legend="{{cause}}"),
                p("Frontend op p99 latency",
                  _p99("tempo_query_frontend_request_duration_seconds",
                       "op"), legend="{{op}}"),
                p("Queue wait p99",
                  _p99("tempo_query_frontend_queue_wait_seconds")),
                p("Block-scan p99 by op",
                  _p99("tempo_querier_block_scan_duration_seconds", "op"),
                  legend="{{op}}"),
                p("Query shard fan-out p99",
                  _p99("tempo_query_frontend_shard_fanout")),
                p("Inspected bytes /s by tenant",
                  _rate("tempo_tpu_query_inspected_bytes_total", "tenant"),
                  legend="{{tenant}}"),
                p("Blocks scanned /s by tenant",
                  _rate("tempo_tpu_query_blocks_scanned_total", "tenant"),
                  legend="{{tenant}}"),
                p("Query-log records /s by reason",
                  _rate("tempo_query_log_records_total", "reason"),
                  legend="{{reason}}"),
                # moments sketch tier (runbook "Choosing a quantile
                # sketch tier"): maxent solver health — fallbacks > 0 in
                # steady state means quantiles are being served from the
                # bucket-sketch fallback, not the moments rows
                p("Moments solver fallbacks /s",
                  _rate("tempo_moments_solver_fallback_total")),
                p("Moments solves /s vs cache hits /s",
                  _rate("tempo_moments_solves_total"),
                  _rate("tempo_moments_solve_cache_hits_total")),
                p("Moments solve wall s/s",
                  _rate("tempo_moments_solve_seconds_total")),
                # per-op response-cache split (the aggregate hit ratio
                # above cannot say WHICH endpoint is cold)
                p("Frontend cache hits /s by op",
                  _rate("tempo_tpu_frontend_cache_hits_total", "op"),
                  legend="{{op}}"),
                p("Frontend cache misses /s by op",
                  _rate("tempo_tpu_frontend_cache_misses_total", "op"),
                  legend="{{op}}"),
                # materialized query grids (runbook "Materialized query
                # grids"): hit share is the dashboard-scale win; misses
                # by reason say why a read recomputed instead
                p("Matview reads /s by outcome",
                  _rate("tempo_matview_reads_total", "result"),
                  legend="{{result}}"),
                p("Matview grids built / subscriptions",
                  "tempo_matview_grids",
                  "sum(tempo_matview_subscriptions)"),
                p("Matview appends /s vs spans /s",
                  _rate("tempo_matview_appends_total"),
                  _rate("tempo_matview_append_spans_total")),
                p("Matview staleness by tenant",
                  "tempo_matview_staleness_seconds",
                  legend="{{tenant}}"),
                p("Matview rebuilds /s by cause",
                  _rate("tempo_matview_rebuilds_total", "cause"),
                  legend="{{cause}}"),
                p("Matview dropped spans /s by reason",
                  _rate("tempo_matview_dropped_spans_total", "reason"),
                  legend="{{reason}}"),
                p("Matview device state bytes",
                  "tempo_matview_state_bytes"),
            ]),
        "tempo-tpu-writes.json": dash(
            "Tempo-TPU / Writes",
            "Write path: receivers -> distributor -> ingester/generator"
            " (operations/tempo-mixin tempo-writes.json analog, on this"
            " build's metric names)",
            [
                p("Spans received /s",
                  _rate("tempo_distributor_spans_received_total")),
                p("Bytes received /s",
                  _rate("tempo_distributor_bytes_received_total")),
                p("Traces pushed /s",
                  _rate("tempo_distributor_traces_pushed_total")),
                p("Discarded spans /s by reason",
                  _rate("tempo_discarded_spans_total", "reason")),
                p("Push failures /s (quorum)",
                  "rate(tempo_distributor_push_failures_total[5m])"),
                p("Ingester live traces",
                  "sum(tempo_ingester_live_traces) by (tenant)"),
                p("Ingester discards /s",
                  _rate("tempo_ingester_discarded_traces_total", "reason")),
                p("Generator spans /s",
                  _rate("tempo_metrics_generator_spans_received_total",
                        "tenant")),
                p("Data-quality warnings /s",
                  _rate("tempo_warnings_total", "reason")),
                p("Push p99 latency",
                  _p99("tempo_distributor_push_duration_seconds")),
                p("Ingester cut p99",
                  _p99("tempo_ingester_cut_duration_seconds")),
                p("Ingester flush p99 by op",
                  _p99("tempo_ingester_flush_duration_seconds", "op"),
                  legend="{{op}}"),
                # ingest staging pipeline (runbook: "Reading the ingest
                # pipeline"): decode/update overlap health
                p("Ingest pipeline in-flight batches",
                  "tempo_ingest_pipeline_inflight"),
                p("Ingest decode/dispatch overlap",
                  "tempo_ingest_pipeline_overlap_ratio",
                  unit="percentunit"),
                p("Ingest pipeline stall s/s (device-bound when high)",
                  "rate(tempo_ingest_pipeline_stall_seconds_total[5m])"),
                p("Staging buffer reuse ratio",
                  "rate(tempo_ingest_pipeline_staging_reuse_total[5m]) /"
                  " (rate(tempo_ingest_pipeline_staging_reuse_total[5m])"
                  " + rate(tempo_ingest_pipeline_staging_alloc_total[5m]))",
                  unit="percentunit"),
                # graceful-overload sampling (runbook: "Surviving
                # overload"): the pressure -> keep-fraction control loop
                p("Ingest keep fraction (controller)",
                  "tempo_sched_ingest_keep_fraction",
                  unit="percentunit"),
                p("Ingest keep fraction by tenant",
                  "tempo_distributor_sampling_keep_fraction",
                  legend="{{tenant}}", unit="percentunit"),
                p("Sampled spans dropped /s",
                  'sum(rate(tempo_discarded_spans_total{'
                  'reason="sampled"}[5m]))'),
                # serving mesh (runbook "Serving on a mesh"): per-shard
                # window fill of the mesh-coalesced fused dispatch — a
                # persistently cold tail shard means batch windows close
                # under-full for this mesh width
                p("Mesh shard occupancy p50 (write path)",
                  "histogram_quantile(0.5, sum(rate("
                  "tempo_sched_batch_occupancy_ratio_bucket"
                  '{shard!=""}[5m])) by (le, shard))',
                  unit="percentunit", legend="shard {{shard}}"),
                # device page pool (runbook "Sizing the page pool"):
                # demand-paged registry/sketch state health — free pages
                # by arena kind, churn, and the exhaustion signal
                p("Page pool free pages by arena",
                  "tempo_pages_free",
                  legend="{{role}}"),
                p("Page allocations / evictions /s",
                  _rate("tempo_pages_allocated_total"),
                  _rate("tempo_pages_evicted_total")),
                p("Page-pool alloc failures /s (exhaustion)",
                  _rate("tempo_pages_alloc_failures_total")),
                p("Registry state bytes by layout",
                  "sum(tempo_registry_state_bytes) by (layout)",
                  legend="{{layout}}"),
                # generator ingest WAL (runbook "Crash recovery and
                # fault injection"): acked-is-durable write rate, fsync
                # cost, and the recovery/dead-letter signals
                p("Ingest WAL appends (batches + bytes /s)",
                  _rate("tempo_wal_appended_batches_total"),
                  _rate("tempo_wal_appended_bytes_total")),
                p("Ingest WAL fsyncs /s + truncated segments /s",
                  _rate("tempo_wal_fsyncs_total"),
                  _rate("tempo_wal_truncated_segments_total")),
                p("WAL replay: batches /s, dead letters /s, lag",
                  _rate("tempo_wal_replayed_batches_total"),
                  _rate("tempo_wal_dead_letters_total"),
                  "max(tempo_wal_replay_lag_seconds)"),
                # structural trace analytics (runbook "Critical paths
                # and error propagation"): which services BOUND request
                # latency, which ROOT-CAUSE error cascades, and the
                # trace-hygiene signals that say how much structure the
                # analyzer could not trust
                p("Critical-path seconds /s by service",
                  _rate("tempo_critical_path_seconds_total", "service"),
                  legend="{{service}}"),
                p("Error root causes /s by root service",
                  _rate("tempo_error_root_cause_total", "root_service"),
                  legend="{{root_service}}"),
                p("Trace hygiene /s: late, cycle, orphan spans",
                  _rate("tempo_traceanalytics_late_spans_total"),
                  _rate("tempo_traceanalytics_cycle_spans_total"),
                  _rate("tempo_dataquality_orphan_spans_total")),
                p("Traces analyzed /s + analysis p99",
                  _rate("tempo_traceanalytics_cut_traces_total"),
                  _p99("tempo_traceanalytics_analysis_seconds")),
            ]),
        "tempo-tpu-resources.json": dash(
            "Tempo-TPU / Resources",
            "Capacity: series budgets, cache residency, usage accounting"
            " (tempo-mixin tempo-resources.json analog)",
            [
                p("Generator active series by tenant",
                  "tempo_metrics_generator_registry_active_series"),
                p("Series budget headroom",
                  "1 - max(tempo_metrics_generator_registry_active_series)"
                  f" / {SERIES_BUDGET}", kind="stat"),
                p("Device-plane memory (bytes)",
                  "tempo_read_plane_cache_device_bytes",
                  "tempo_read_plane_cache_host_bytes"),
                p("Live traces (memory proxy)",
                  "sum(tempo_ingester_live_traces)"),
                p("Ingest bytes /s (capacity driver)",
                  _rate("tempo_distributor_bytes_received_total")),
                p("Usage-stats reports written",
                  "tempo_usage_stats_reports_written_total", kind="stat"),
                p("JIT compiles /h by function",
                  _rate("tempo_jax_jit_compile_total", "fn", win="1h"),
                  legend="{{fn}}"),
                p("JIT compile seconds /h",
                  _rate("tempo_jax_jit_compile_seconds_total", win="1h")),
                p("Device uploads MB/s",
                  "sum(rate(tempo_jax_device_put_bytes_total[5m])) / 1e6"),
                p("Device kernel p99 by kernel",
                  _p99("tempo_jax_kernel_duration_seconds", "kernel"),
                  legend="{{kernel}}"),
                p("Generator collect p99",
                  _p99("tempo_metrics_generator_collect_duration_seconds")),
                p("Compaction cycle p99",
                  _p99("tempo_compactor_cycle_duration_seconds")),
                p("Compaction throughput (blocks, spans /s)",
                  _rate("tempo_compaction_blocks_total"),
                  _rate("tempo_compaction_spans_total")),
                p("Compaction device seconds + sidecars written /s",
                  _rate("tempo_compaction_device_seconds_total"),
                  _rate("tempo_compaction_sidecars_written_total")),
                p("Sidecar folds vs scan fallbacks /s",
                  _rate("tempo_compaction_sidecar_folds_total"),
                  _rate("tempo_compaction_sidecar_fallbacks_total")),
            ]),
        "tempo-tpu-sched.json": dash(
            "Tempo-TPU / Device scheduler",
            "Shared device-execution scheduler (tempo_tpu.sched):"
            " continuous micro-batching health — queue saturation,"
            " batch occupancy, padding waste, shedding, backpressure"
            " (runbook: 'Reading the scheduler')",
            [
                p("Queue depth by class",
                  "tempo_sched_queue_depth", legend="{{class}}"),
                p("Queue fill ratio by class",
                  "tempo_sched_queue_depth / tempo_sched_queue_limit",
                  unit="percentunit", legend="{{class}}"),
                p("Jobs /s by class",
                  _rate("tempo_sched_jobs_total", "class"),
                  legend="{{class}}"),
                p("Shed jobs /s by class",
                  _rate("tempo_sched_shed_jobs_total", "class"),
                  legend="{{class}}"),
                p("Batches /s by kernel",
                  _rate("tempo_sched_batches_total", "kernel"),
                  legend="{{kernel}}"),
                p("Jobs coalesced per batch",
                  "sum(rate(tempo_sched_coalesced_jobs_total[5m]))"
                  " by (kernel) /"
                  " sum(rate(tempo_sched_batches_total[5m])) by (kernel)",
                  legend="{{kernel}}"),
                p("Batch occupancy p50 by kernel",
                  "histogram_quantile(0.5, sum(rate("
                  "tempo_sched_batch_occupancy_ratio_bucket[5m]))"
                  " by (le, kernel))",
                  unit="percentunit", legend="{{kernel}}"),
                p("Padding waste MB/s by kernel",
                  "sum(rate(tempo_sched_padding_waste_bytes_total[5m]))"
                  " by (kernel) / 1e6", legend="{{kernel}}"),
                p("Dispatch p99 by kernel",
                  _p99("tempo_sched_dispatch_duration_seconds", "kernel"),
                  legend="{{kernel}}"),
                p("Queue wait p99 by class",
                  _p99("tempo_sched_queue_wait_seconds", "class"),
                  legend="{{class}}"),
                p("Shape-bucket warmups /h (flat = no re-traces)",
                  _rate("tempo_sched_bucket_warmups_total", "kernel",
                        win="1h"), legend="{{kernel}}"),
                p("Backpressure rejections /s (429s)",
                  'sum(rate(tempo_discarded_spans_total{'
                  'reason="sched_backpressure"}[5m]))'),
                p("Dispatch errors /s (dropped ingest batches)",
                  "rate(tempo_sched_dispatch_errors_total[5m])"),
                p("Frontend query sheds /s (503s) by op",
                  _rate("tempo_query_frontend_shed_total", "op"),
                  legend="{{op}}"),
            ]),
        "tempo-tpu-devtime.json": dash(
            "Tempo-TPU / Device time",
            "Device-time ledger + online dispatch cost model"
            " (tempo_tpu/obs/devtime.py): where every device-nanosecond"
            " goes, per-tenant attribution, and the cost-model fit that"
            " drives scheduler auto-tuning (runbook: 'Reading the"
            " device-time ledger' / 'Scheduler auto-tuning')",
            [
                p("Device seconds /s by kernel",
                  _rate("tempo_devtime_device_seconds_total", "kernel"),
                  legend="{{kernel}}"),
                p("Device seconds /s by shape bucket",
                  _rate("tempo_devtime_device_seconds_total", "bucket"),
                  legend="bucket {{bucket}}"),
                p("Device seconds /s by priority class",
                  _rate("tempo_devtime_device_seconds_total", "class"),
                  legend="{{class}}"),
                p("Device seconds /s by tenant (top costs)",
                  "topk(10, sum(rate("
                  "tempo_devtime_tenant_device_seconds_total[5m]))"
                  " by (tenant))", legend="{{tenant}}"),
                p("Queue-wait share of device latency",
                  "sum(rate(tempo_devtime_queue_wait_seconds_total[5m]))"
                  " / (sum(rate(tempo_devtime_queue_wait_seconds_total"
                  "[5m])) + sum(rate("
                  "tempo_devtime_device_seconds_total[5m])))",
                  unit="percentunit"),
                p("Padding overhead (padded / submitted rows)",
                  "sum(rate(tempo_devtime_padded_rows_total[5m]))"
                  " by (kernel) / sum(rate("
                  "tempo_devtime_submitted_rows_total[5m])) by (kernel)",
                  legend="{{kernel}}"),
                p("H2D MB/s by kernel",
                  "sum(rate(tempo_devtime_h2d_bytes_total[5m]))"
                  " by (kernel) / 1e6", legend="{{kernel}}"),
                p("Cost model: fixed cost a (µs) by pair",
                  "tempo_sched_cost_model_coeff_a_seconds * 1e6",
                  legend="{{kernel}}/{{bucket}}"),
                p("Cost model: per-row cost b (ns) by pair",
                  "tempo_sched_cost_model_coeff_b_seconds_per_row * 1e9",
                  legend="{{kernel}}/{{bucket}}"),
                p("Cost model typical-cost error (soak gate <= 0.25)",
                  "tempo_sched_cost_model_typical_error",
                  unit="percentunit", legend="{{kernel}}/{{bucket}}"),
                p("Per-sample rel error: median (jitter) + mean (stalls)",
                  "tempo_sched_cost_model_rel_error_median",
                  "tempo_sched_cost_model_rel_error",
                  unit="percentunit", legend="{{kernel}}/{{bucket}}"),
                p("Cost model staleness (s since last observation)",
                  "tempo_sched_cost_model_age_seconds",
                  legend="{{kernel}}/{{bucket}}"),
                p("Ingest-visible latency p99 by kernel (tuner target)",
                  _p99("tempo_devtime_ingest_visible_latency_seconds",
                       "kernel"), legend="{{kernel}}"),
                p("Auto-tuned batch window (ms) vs static",
                  "tempo_sched_tuned_window_ms",
                  legend="{{kernel}}"),
                p("Tuning active (1 = cost model driving windows)",
                  "tempo_sched_tuning_active", kind="stat"),
            ]),
        "tempo-tpu-fleet.json": dash(
            "Tempo-TPU / Generator fleet",
            "Multi-host generator fleet (tempo_tpu.fleet): ring"
            " membership, tenant placement balance, and the"
            " checkpoint/restore handoff protocol (runbook: 'Operating"
            " a generator fleet')",
            [
                p("Ring members", "tempo_ring_members",
                  legend="{{ring}}"),
                p("Ownership fraction by instance (generator ring)",
                  'tempo_ring_ownership_ratio{ring="generator"}',
                  unit="percentunit", legend="{{instance}}"),
                p("Oldest member heartbeat age (s)",
                  "tempo_ring_member_heartbeat_age_seconds",
                  legend="{{ring}}"),
                p("Checkpoints /h (handoffs + shutdown snapshots)",
                  _rate("tempo_fleet_checkpoints_total", win="1h")),
                p("Checkpoint MB/s written",
                  "sum(rate(tempo_fleet_checkpoint_bytes_total[5m]))"
                  " / 1e6"),
                p("Checkpoint wall s/s (drain+gather+encode+write)",
                  _rate("tempo_fleet_checkpoint_seconds_total")),
                p("Restores /h (boot + handoff receives)",
                  _rate("tempo_fleet_checkpoint_restores_total",
                        win="1h")),
                p("Handoffs /h (tenants moved off this process)",
                  _rate("tempo_fleet_handoffs_total", win="1h")),
                p("Generator spans /s by tenant (placement view)",
                  _rate("tempo_metrics_generator_spans_received_total",
                        "tenant"), legend="{{tenant}}"),
            ]),
    }


def main() -> int:
    check = "--check" in sys.argv
    drift = []
    for fname, spec in dashboards().items():
        path = os.path.join(OUT_DIR, fname)
        text = json.dumps(spec, indent=1) + "\n"
        if check:
            on_disk = open(path).read() if os.path.exists(path) else ""
            if on_disk != text:
                drift.append(fname)
        else:
            with open(path, "w") as f:
                f.write(text)
            print(f"wrote {path}")
    if drift:
        print(f"DRIFT: {drift} — run python operations/gen_dashboards.py",
              file=sys.stderr)
        return 1
    if check:
        # chain the alert/dashboard ↔ registry metric-name gate: a panel
        # may only reference metrics the process actually registers
        import subprocess
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "check_metrics_drift.py")])
        return proc.returncode
    return 0


if __name__ == "__main__":
    sys.exit(main())
