#!/usr/bin/env python3
"""Alert/dashboard ↔ metrics-registry drift gate (CLI).

Boots a `target=all` in-memory App, collects every metric family name
registered in its obs registry (plus the process-wide JAX runtime
registry), and fails if `alerts.yaml` or any dashboard references a
`tempo_*` metric the process would never expose. Run standalone or via
`python operations/gen_dashboards.py --check` (which chains into this).

Usage: python operations/check_metrics_drift.py
Exit codes: 0 clean, 1 drift found.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OPS_DIR = os.path.dirname(os.path.abspath(__file__))


def main() -> int:
    from tempo_tpu.obs import drift

    registries, app = drift.default_registries()
    try:
        problems = drift.check_drift(OPS_DIR, registries)
    finally:
        app.shutdown()
    problems += drift.check_bail_causes(OPS_DIR)
    if problems:
        print("METRIC DRIFT:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        print("register the metric in tempo_tpu/obs (module families), "
              "fix the alert/dashboard expression, or document the "
              "fallback cause in operations/runbook.md", file=sys.stderr)
        return 1
    n = len(drift.referenced_metric_names(OPS_DIR))
    print(f"ok: {n} referenced metric names all registered; "
          "fallback causes documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
