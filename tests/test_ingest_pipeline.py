"""Decode-once columnar ingest: staged distributor tee + double-buffered
host/device staging pipeline (+ the round-5 satellite regressions)."""

from __future__ import annotations

import time

import numpy as np
import pytest

from tempo_tpu import native, sched
from tempo_tpu.distributor import Distributor
from tempo_tpu.generator.generator import Generator
from tempo_tpu.generator.instance import GeneratorConfig
from tempo_tpu.model.otlp import encode_spans_otlp, spans_from_otlp_proto
from tempo_tpu.overrides import Overrides
from tempo_tpu.ring import ACTIVE, InstanceDesc, Ring
from tempo_tpu.ring.ring import _instance_tokens

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native staging kernel required")


def mkspan(tid: bytes, sid: bytes, name="op", svc="svc", t0=None,
           dur=1_000_000, **kw):
    t0 = t0 if t0 is not None else int(time.time() * 1e9)
    return {"trace_id": tid, "span_id": sid, "name": name, "service": svc,
            "start_unix_nano": t0, "end_unix_nano": t0 + dur, **kw}


def make_payload(n: int, t0: int | None = None) -> tuple[bytes, list[dict]]:
    t0 = t0 if t0 is not None else int(time.time() * 1e9)
    src = []
    for i in range(n):
        src.append(mkspan((b"%04d" % i).ljust(16, b"\0"), bytes([i % 251 + 1]) * 8,
                          name=f"op-{i % 5}", t0=t0 + i * 1000,
                          dur=1_000_000 + i * 10_000,
                          attrs={"http.status_code": 200 + (i % 100),
                                 "http.method": "GET"},
                          res_attrs={"service.name": f"svc-{i % 3}"}))
    return encode_spans_otlp(src), src


def _ring_of(ids, now):
    r = Ring(replication_factor=1, now=now)
    for iid in ids:
        r.register(InstanceDesc(id=iid, state=ACTIVE,
                                tokens=_instance_tokens(iid, 64),
                                heartbeat_ts=now()))
    return r


class _NullStagedIng:
    """Staged-capable null ingester (the bench's tee sink): consumes the
    view without needing the attr columns."""

    staged_needs_attrs = False

    def __init__(self):
        self.rows = 0

    def push(self, tenant, traces):
        return [None] * len(traces)

    def push_otlp(self, tenant, payload):
        return {}

    def push_staged(self, tenant, view):
        self.rows += view.n
        return {}


def _tee_rig(gen_clients, ov=None):
    now = time.time
    ing = _NullStagedIng()
    dist = Distributor(_ring_of(["i0"], now), {"i0": ing},
                       overrides=ov or Overrides(),
                       generator_ring=_ring_of(list(gen_clients), now),
                       generator_clients=gen_clients, now=now)
    return dist, ing


def _gen(processors=("span-metrics",)):
    cfg = GeneratorConfig(processors=processors)
    cfg.registry.disable_collection = True
    return Generator(cfg, overrides=Overrides())


def _state_of(gen, tenant="t1"):
    import jax
    proc = gen.instance(tenant).processors["span-metrics"]
    sched.flush()
    if hasattr(proc, "drain_pipeline"):
        proc.drain_pipeline()
    jax.block_until_ready(proc.calls.state.values)
    calls = np.asarray(proc.calls.state.values)
    lat = np.asarray(proc.latency.state.bucket_counts)
    dd = np.asarray(proc.dd.counts) if proc.dd is not None else None
    # label-keyed so intern-id assignment order cannot mask divergence
    by_label = {proc.calls.labels_of(int(s)): float(calls[int(s)])
                for s in proc.calls.table.active_slots()}
    return by_label, calls, lat, dd


# -- tentpole: staged tee --------------------------------------------------


def test_staged_plan_engages_for_staged_capable_targets():
    gen = _gen()
    ov = Overrides()
    ov.set_tenant_patch("t1", {"generator": {"processors": ["span-metrics"]}})
    dist, _ = _tee_rig({"g0": gen}, ov)
    plan = dist._staging_plan("t1", ov.for_tenant("t1"))
    assert plan is not None
    interner, _ns, _nr = plan
    assert interner is gen.instance("t1").registry.interner
    # a generator client without the staged surface disables the plan
    class Legacy:
        def push_otlp(self, tenant, data):
            return 0
    dist2, _ = _tee_rig({"g0": Legacy()}, ov)
    assert dist2._staging_plan("t1", ov.for_tenant("t1")) is None


def test_tee_path_vs_dict_path_registry_bitident():
    """The SAME spans through (a) the staged distributor tee and (b) the
    per-span-dict push_spans compatibility route must land bit-identical
    calls/latency/sketch registry state."""
    raw, src = make_payload(64)
    ov = Overrides()
    ov.set_tenant_patch("t1", {"generator": {"processors": ["span-metrics"]}})

    gen_a = _gen()
    dist, ing = _tee_rig({"g0": gen_a}, ov)
    assert dist._staging_plan("t1", ov.for_tenant("t1")) is not None
    errs = dist.push_otlp("t1", raw)
    assert errs == {}
    assert ing.rows == 64            # the ingester leg consumed the view

    gen_b = _gen()
    gen_b.push_spans("t1", list(spans_from_otlp_proto(raw)))

    la, calls_a, lat_a, dd_a = _state_of(gen_a)
    lb, calls_b, lat_b, dd_b = _state_of(gen_b)
    assert la == lb
    assert np.array_equal(calls_a, calls_b)
    assert np.array_equal(lat_a, lat_b)
    assert np.array_equal(dd_a, dd_b)


def test_staged_tee_ingester_dict_parity_with_events_links():
    """Ingester content through the staged view must match the dict path
    byte for byte — exact id lengths, attrs, events, links."""
    import tempfile

    from tempo_tpu.ingester import Ingester

    now = time.time
    raw, src = make_payload(12)
    src[3]["events"] = [{"time_unix_nano": 777, "name": "exception"}]
    src[5]["links"] = [{"trace_id": b"\x09" * 16, "span_id": b"\x08" * 8}]
    src.append(mkspan(b"\x07" * 7, b"\x06" * 8, name="short-id"))
    raw = encode_spans_otlp(src)

    ov = Overrides()
    ov.set_tenant_patch("t1", {"generator": {"processors": ["span-metrics"]}})
    gen = _gen()
    ing = Ingester(tempfile.mkdtemp(), now=now, instance_id="i0")
    dist = Distributor(_ring_of(["i0"], now), {"i0": ing}, overrides=ov,
                       generator_ring=_ring_of(["g0"], now),
                       generator_clients={"g0": gen}, now=now)
    assert dist.push_otlp("t1", raw) == {}
    # dict-path reference tenant
    assert dist.push_spans("t2", list(spans_from_otlp_proto(raw))) == {}

    for s in src:
        tid = s["trace_id"]
        a = ing.find_trace_by_id("t1", tid)
        b = ing.find_trace_by_id("t2", tid)
        assert a is not None and b is not None, tid
        sa = sorted(a, key=lambda d: d["span_id"])
        sb = sorted(b, key=lambda d: d["span_id"])
        assert sa == sb, tid
    got = ing.find_trace_by_id("t1", src[3]["trace_id"])
    assert any(s.get("events") == src[3]["events"] for s in got)
    got = ing.find_trace_by_id("t1", src[5]["trace_id"])
    assert any(s.get("links") == src[5]["links"] for s in got)


def test_sharded_staged_views_cover_every_span_once():
    """Two ring targets served by one in-process generator: each send is
    a row-subset VIEW; together they cover every span exactly once."""
    raw, _src = make_payload(40)
    ov = Overrides()
    ov.set_tenant_patch("t1", {"generator": {"processors": ["span-metrics"]}})
    gen = _gen()
    dist, _ = _tee_rig({"g0": gen, "g1": gen}, ov)
    assert dist._staging_plan("t1", ov.for_tenant("t1")) is not None
    assert dist.push_otlp("t1", raw) == {}
    inst = gen.instance("t1")
    assert inst.spans_received == 40
    by_label, *_ = _state_of(gen)
    assert sum(by_label.values()) == 40.0


def test_staged_view_slicing_ragged_batch_boundaries():
    """Views across pad-bucket boundaries: a subset whose padded capacity
    differs from the parent batch's must gather columns exactly and
    round-trip dicts identical to a wire decode of the same rows."""
    from tempo_tpu.model.interner import StringInterner
    from tempo_tpu.model.otlp_batch import stage_otlp

    raw, _src = make_payload(300)     # parent cap 512
    it = StringInterner()
    staged = stage_otlp(raw, it)
    assert staged is not None and staged.n == 300
    full_sb, full_sizes = staged.batch()
    assert full_sb.capacity == 512

    rows = np.arange(250, 300)        # crosses the 256-row pad bucket
    view = staged.view(rows)
    sb, sizes = view.batch_slice()
    assert sb.n == 50 and sb.capacity == 256
    assert np.array_equal(sb.name_id[:50], full_sb.name_id[rows])
    assert np.array_equal(sb.trace_id[:50], full_sb.trace_id[rows])
    assert np.array_equal(sb.span_attr_key[:50], full_sb.span_attr_key[rows])
    assert np.array_equal(sizes[:50], full_sizes[rows])
    assert not sb.valid[50:].any()

    decoded = list(spans_from_otlp_proto(raw))
    got = view.to_span_dicts()
    assert got == [decoded[i] for i in rows.tolist()]

    # full-coverage views share the parent arrays: genuinely zero-copy
    fv = staged.view()
    fsb, fsizes = fv.batch_slice()
    assert fsb is full_sb and fsizes is full_sizes
    assert fv.stage_rows() is staged.spans


# -- tentpole: staging pipeline --------------------------------------------


def _push_n(gen, payload, n=5, tenant="t1"):
    for _ in range(n):
        gen.push_otlp(tenant, payload)


def test_pipeline_overlap_and_buffer_reuse():
    raw, _ = make_payload(200)
    sched.reset()
    sched.configure(sched.SchedConfig(enabled=True, pipeline_depth=2))
    gen = _gen()
    _push_n(gen, raw, n=6)
    proc = gen.instance("t1").processors["span-metrics"]
    pipe = proc._pipe
    assert pipe is not None
    by_label, *_ = _state_of(gen)
    assert sum(by_label.values()) == 6 * 200
    assert pipe.submitted_total == 6
    assert pipe.reuse_total >= 3          # ring recycles after warmup
    assert pipe.alloc_total <= 3          # depth+1 bound on fresh allocs
    assert pipe.in_flight() == 0          # drained


def test_pipeline_drain_before_collect():
    """collect() behind the drain barrier must see EVERY accepted push —
    samples bit-identical to the synchronous no-scheduler mode."""
    raw, _ = make_payload(128)

    def run(pipelined: bool):
        sched.reset()
        if pipelined:
            sched.configure(sched.SchedConfig(enabled=True,
                                              pipeline_depth=2))
        gen = _gen()
        _push_n(gen, raw, n=4)
        inst = gen.instance("t1")
        # the PRODUCTION collect path: collect_and_push runs the drain
        # barrier (sched.flush + pipeline reap) before reading state
        n = inst.collect_and_push(ts_ms=12345)
        samples = inst.registry.collect(ts_ms=12345)
        assert n == len(samples)
        proc = inst.processors["span-metrics"]
        if pipelined:
            assert proc._pipe is not None and proc._pipe.in_flight() == 0
        out = sorted((s.name, s.labels, s.value) for s in samples)
        sched.reset()
        return out

    assert run(True) == run(False)


def test_pipeline_off_fallback_parity():
    """pipeline_depth=0 (ring off) and scheduler-off must both match the
    pipelined state bit for bit."""
    raw, _ = make_payload(96)

    def run(cfg):
        sched.reset()
        if cfg is not None:
            sched.configure(cfg)
        gen = _gen()
        _push_n(gen, raw, n=3)
        by_label, calls, lat, dd = _state_of(gen)
        sched.reset()
        return by_label, calls.copy(), lat.copy(), dd.copy()

    base = run(None)
    off = run(sched.SchedConfig(enabled=True, pipeline_depth=0))
    on = run(sched.SchedConfig(enabled=True, pipeline_depth=2))
    for other in (off, on):
        assert base[0] == other[0]
        assert np.array_equal(base[1], other[1])
        assert np.array_equal(base[2], other[2])
        assert np.array_equal(base[3], other[3])


def test_pipeline_depth_bounds_inflight():
    from tempo_tpu.generator.pipeline import IngestPipeline

    class _Job:
        def __init__(self):
            import threading
            self.event = threading.Event()

    pipe = IngestPipeline(depth=2)
    b1 = pipe.acquire(256, 4)
    j1 = _Job()
    pipe.track(j1, b1)
    b2 = pipe.acquire(256, 4)
    j2 = _Job()
    pipe.track(j2, b2)
    assert pipe.in_flight() == 2
    # third acquire blocks on the OLDEST job; release it from a timer
    import threading
    threading.Timer(0.05, j1.event.set).start()
    t0 = time.perf_counter()
    b3 = pipe.acquire(256, 4)
    assert time.perf_counter() - t0 >= 0.04     # actually waited
    assert pipe.stall_ns > 0
    assert b3 is b1                             # recycled, not fresh
    j2.event.set()
    assert pipe.drain()
    assert pipe.in_flight() == 0


def test_pipeline_obs_families_registered():
    from tempo_tpu.generator import pipeline  # noqa: F401 — registers
    from tempo_tpu.obs.jaxruntime import RUNTIME

    text = RUNTIME.render()
    for fam in ("tempo_ingest_pipeline_inflight",
                "tempo_ingest_pipeline_staging_reuse_total",
                "tempo_ingest_pipeline_overlap_ratio",
                "tempo_ingest_pipeline_stall_seconds_total"):
        assert fam in text, fam


def test_rejected_push_does_not_intern_or_stage():
    """Admission runs BEFORE staging: a rate-limited push must not grow
    the tenant registry's interner (unbounded growth under sustained
    429s) and must still attribute the rejected span count."""
    from tempo_tpu.distributor.distributor import RateLimited

    raw, _src = make_payload(32)
    ov = Overrides()
    ov.set_tenant_patch("t1", {
        "generator": {"processors": ["span-metrics"]},
        "ingestion": {"rate_limit_bytes": 1, "burst_size_bytes": 1}})
    gen = _gen()
    dist, _ = _tee_rig({"g0": gen}, ov)
    assert dist._staging_plan("t1", ov.for_tenant("t1")) is not None
    before = len(gen.instance("t1").registry.interner)
    with pytest.raises(RateLimited):
        dist.push_otlp("t1", raw)
    assert len(gen.instance("t1").registry.interner) == before
    assert dist.discarded.get("rate_limited") == 32


# -- satellites ------------------------------------------------------------


def test_memcached_close_releases_workers_on_full_queue():
    """ADVICE r5 #1: close() with a FULL write-behind queue must still
    stop every worker (no thread left blocked on q.get with its socket
    closed underneath)."""
    from tempo_tpu.backend.memcached import MemcachedCache

    c = MemcachedCache(["127.0.0.1:1"], timeout_s=0.05,
                       write_back_buffer=4, write_back_workers=2)
    for i in range(64):              # saturate the queue (dead server)
        c.put(f"k{i}", b"v")
    workers = list(c._workers)
    c.close()
    for t in workers:
        t.join(timeout=3.0)
        assert not t.is_alive()


def test_memcached_prunes_dead_thread_sockets():
    """ADVICE r5 #5: per-thread sockets of exited threads are pruned (and
    closed) on the next append, not retained until close()."""
    import socket as socket_mod
    import threading

    from tempo_tpu.backend.memcached import _ServerConn

    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    conn = _ServerConn(f"127.0.0.1:{srv.getsockname()[1]}", timeout_s=0.5)

    def connect_once():
        conn._connect()

    threads = [threading.Thread(target=connect_once) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # every connect appended; dead-thread entries were pruned (and their
    # sockets closed) as later appends observed the exits — after all
    # four exit, one more append from a live thread leaves exactly ours
    conn._connect()
    assert len(conn._all) == 1
    assert conn._all[0][1] is threading.current_thread()
    conn.close()
    srv.close()


def test_jaeger_agent_wildcard_bind_requires_opt_in():
    from tempo_tpu.distributor.receiver_agent import (JaegerAgentConfig,
                                                      JaegerAgentReceiver)

    rx = JaegerAgentReceiver(None, JaegerAgentConfig(host="0.0.0.0", port=0))
    with pytest.raises(ValueError, match="allow_wildcard_bind"):
        rx.start()
    rx = JaegerAgentReceiver(None, JaegerAgentConfig(
        host="0.0.0.0", port=0, allow_wildcard_bind=True))
    rx.start()
    try:
        assert rx.port > 0
    finally:
        rx.stop()
    # the default config binds loopback
    assert JaegerAgentConfig().host == "127.0.0.1"


def test_metrics_grid_returns_cause_not_shared_state():
    """ADVICE r5 #2: the fused-path refusal cause rides the return value
    (concurrent queries on one cached plane cannot misattribute)."""
    from tempo_tpu.block.device_scan import BlockScanPlane
    from tempo_tpu.traceql import ast as A

    plane = BlockScanPlane([])
    m = A.MetricsAggregate(kind=A.MetricsKind.COUNT_OVER_TIME, by=())
    handle, cause = plane.metrics_grid(m, [], True, 0, 10, 0)  # step 0
    assert handle is None and cause == "shape"
    assert plane.fallback_causes.get("shape", 0) >= 1
