"""App runtime: config load, module wiring, HTTP API end-to-end."""

from __future__ import annotations

import json
import socket
import urllib.error
import urllib.parse
import urllib.request

import pytest

from tempo_tpu.app import App, load_config
from tempo_tpu.app.config import Config


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_config_yaml_and_env(monkeypatch, tmp_path):
    monkeypatch.setenv("BUCKET", "my-bucket")
    p = tmp_path / "tempo.yaml"
    p.write_text("""
target: all
server:
  http_listen_port: 9999
storage:
  backend: mem
  cloud: {bucket: "${BUCKET}", region: "${REGION:-us-east1}"}
ingester:
  instance: {max_block_duration_s: 120.0}
frontend:
  target_bytes_per_job: 52428800
""")
    cfg = load_config(str(p))
    assert cfg.server.http_listen_port == 9999
    assert cfg.storage.cloud == {"bucket": "my-bucket", "region": "us-east1"}
    assert cfg.ingester.instance.max_block_duration_s == 120.0
    assert cfg.frontend.target_bytes_per_job == 50 * 1024 * 1024
    assert cfg.check() == []


def test_config_unknown_key_rejected():
    with pytest.raises(ValueError, match="unknown config key"):
        load_config(text="storage: {bukkit: x}")


def test_config_warnings():
    cfg = load_config(text="ingester: {instance: {max_block_duration_s: 5}}")
    assert any("max_block_duration" in w for w in cfg.check())


def test_target_wiring(tmp_path):
    cfg = Config()
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.target = "querier"
    app = App(cfg)
    assert app.querier is not None and app.db is not None
    assert app.distributor is None and app.ingester is None
    with pytest.raises(ValueError):
        App(Config(target="bogus"))


@pytest.fixture
def server(tmp_path):
    cfg = Config()
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "d" / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.server.http_listen_port = free_port()
    cfg.ingester.instance.trace_idle_s = 0.1
    app = App(cfg)
    app.overrides.set_tenant_patch("single-tenant", {
        "generator": {"processors": ["span-metrics", "local-blocks"]}})
    from tempo_tpu.app.api import serve
    app.start_loops()
    srv = serve(app, block=False)
    base = f"http://127.0.0.1:{cfg.server.http_listen_port}"
    yield app, base
    srv.shutdown()
    app.shutdown()


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, json.loads(r.read() or b"{}")


def _post(url: str, body: bytes, ctype="application/json"):
    req = urllib.request.Request(url, data=body,
                                 headers={"Content-Type": ctype})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, json.loads(r.read() or b"{}")


OTLP = {"resourceSpans": [{
    "resource": {"attributes": [
        {"key": "service.name", "value": {"stringValue": "shop"}}]},
    "scopeSpans": [{"spans": [{
        "traceId": "0102030405060708090a0b0c0d0e0f10",
        "spanId": "0102030405060708",
        "name": "checkout", "kind": 3,
        "startTimeUnixNano": "{t0}",
        "endTimeUnixNano": "{t1}",
        "attributes": [{"key": "http.status_code",
                        "value": {"intValue": "200"}}],
        "status": {"code": 0}}]}]}]}


def test_zipkin_receiver(server):
    import time
    app, base = server
    ts = int((time.time() - 3) * 1e6)
    spans = [{"traceId": "cc" * 16, "id": "dd" * 8, "name": "zip-op",
              "kind": "SERVER", "timestamp": ts, "duration": 50_000,
              "localEndpoint": {"serviceName": "zipkin-svc"},
              "tags": {"http.method": "GET"}}]
    req = urllib.request.Request(f"{base}/api/v2/spans",
                                 data=json.dumps(spans).encode(),
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 202
    code, tr = _get(f"{base}/api/traces/{'cc' * 16}")
    assert code == 200 and tr["spans"][0]["name"] == "zip-op"
    assert tr["spans"][0]["service"] == "zipkin-svc"
    assert tr["spans"][0]["attrs"]["http.method"] == "GET"


def test_http_e2e(server):
    import time
    app, base = server
    t0 = int((time.time() - 5) * 1e9)
    body = json.dumps(OTLP).replace('"{t0}"', str(t0)) \
                           .replace('"{t1}"', str(t0 + 50_000_000))
    code, _ = _post(f"{base}/v1/traces", body.encode())
    assert code == 200
    # ready/echo/status
    with urllib.request.urlopen(f"{base}/ready", timeout=10) as r:
        assert r.status == 200
    code, st = _get(f"{base}/status")
    assert st["target"] == "all" and "distributor" in st["modules"]
    # trace by id
    code, tr = _get(f"{base}/api/traces/0102030405060708090a0b0c0d0e0f10")
    assert code == 200 and len(tr["spans"]) == 1
    assert tr["spans"][0]["name"] == "checkout"
    # search (recent window → ingester)
    code, res = _get(f"{base}/api/search?q=" + urllib.parse.quote(
        '{ resource.service.name = "shop" }'))
    assert code == 200 and len(res["traces"]) == 1
    # tags
    code, tags = _get(f"{base}/api/search/tags")
    assert "http.status_code" in tags["tagNames"]          # v1: flat union
    code, tags2 = _get(f"{base}/api/v2/search/tags")
    span_tags = next(s["tags"] for s in tags2["scopes"] if s["name"] == "span")
    assert "http.status_code" in span_tags                 # v2: scoped
    # metrics query range (generator local-blocks path)
    now = time.time()
    code, qr = _get(f"{base}/api/metrics/query_range?q=" +
                    urllib.parse.quote("{ } | rate()") +
                    f"&start={now - 300}&end={now}&step=300")
    assert code == 200
    total = sum(d["value"] for s in qr["series"]
                for d in (s.get("samples") or []) if d["value"] == d["value"])
    assert total > 0
    # span-metrics summary
    code, sm = _get(f"{base}/api/metrics/summary?q=" +
                    urllib.parse.quote("{ }") + "&groupBy=name")
    assert code == 200 and sm["summaries"][0]["spanCount"] == 1
    # overrides API
    code, _ = _post(f"{base}/api/overrides", json.dumps(
        {"generator": {"collection_interval_s": 30.0}}).encode())
    assert code == 200
    code, ov = _get(f"{base}/api/overrides")
    assert ov["limits"]["generator"]["collection_interval_s"] == 30.0
    # prometheus self-metrics
    with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "tempo_distributor_spans_received_total 1" in text


def test_tag_values_includes_ingester_recent_data(server):
    """/api/search/tag/{name}/values must see unflushed ingester data
    (ADVICE r1: previously only backend blocks were scanned)."""
    import time
    app, base = server
    t0 = int((time.time() - 5) * 1e9)
    body = json.dumps(OTLP).replace('"{t0}"', str(t0)) \
                           .replace('"{t1}"', str(t0 + 50_000_000))
    code, _ = _post(f"{base}/v1/traces", body.encode())
    assert code == 200
    code, res = _get(f"{base}/api/search/tag/.http.status_code/values")
    assert code == 200
    assert "200" in res["tagValues"]                       # v1: bare strings
    code, res = _get(
        f"{base}/api/v2/search/tag/resource.service.name/values")
    assert any(v["value"] == "shop" for v in res["tagValues"])  # v2: typed


def test_otlp_malformed_and_gzip(server):
    import gzip
    import time
    app, base = server
    # malformed protobuf → 400, not 500
    req = urllib.request.Request(
        f"{base}/v1/traces", data=b"\xff\xfe not proto",
        headers={"Content-Type": "application/x-protobuf"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
    # gzipped OTLP JSON is accepted
    t0 = int((time.time() - 5) * 1e9)
    body = json.dumps(OTLP).replace('"{t0}"', str(t0)) \
                           .replace('"{t1}"', str(t0 + 50_000_000)) \
                           .replace("0102030405060708090a0b0c0d0e0f10",
                                    "ab" * 16)
    req = urllib.request.Request(
        f"{base}/v1/traces", data=gzip.compress(body.encode()),
        headers={"Content-Type": "application/json",
                 "Content-Encoding": "gzip"})
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200
    code, tr = _get(f"{base}/api/traces/{'ab' * 16}")
    assert code == 200 and tr["spans"][0]["name"] == "checkout"


def test_metrics_summary_without_generator(tmp_path):
    cfg = Config()
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.target = "query-frontend"
    cfg.server.http_listen_port = free_port()
    app = App(cfg)
    from tempo_tpu.app.api import serve
    srv = serve(app, block=False)
    base = f"http://127.0.0.1:{cfg.server.http_listen_port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/api/metrics/summary?q=%7B%20%7D",
                                   timeout=10)
        assert ei.value.code == 400  # clear error, not AttributeError 500
    finally:
        srv.shutdown()
        app.shutdown()


def _thrift_field(fid: int, ftype: int, payload: bytes) -> bytes:
    import struct
    return struct.pack(">bh", ftype, fid) + payload


def _thrift_str(s) -> bytes:
    import struct
    b = s if isinstance(s, bytes) else s.encode()
    return struct.pack(">i", len(b)) + b


def _thrift_list(etype: int, items: list[bytes]) -> bytes:
    import struct
    return struct.pack(">bi", etype, len(items)) + b"".join(items)


def _jaeger_tag(key: str, v) -> bytes:
    import struct
    out = _thrift_field(1, 11, _thrift_str(key))
    if isinstance(v, bool):
        out += _thrift_field(2, 8, struct.pack(">i", 2))
        out += _thrift_field(5, 2, b"\x01" if v else b"\x00")
    elif isinstance(v, int):
        out += _thrift_field(2, 8, struct.pack(">i", 3))
        out += _thrift_field(6, 10, struct.pack(">q", v))
    elif isinstance(v, float):
        out += _thrift_field(2, 8, struct.pack(">i", 1))
        out += _thrift_field(4, 4, struct.pack(">d", v))
    else:
        out += _thrift_field(2, 8, struct.pack(">i", 0))
        out += _thrift_field(3, 11, _thrift_str(v))
    return out + b"\x00"


def _jaeger_batch(service: str, spans: list[dict]) -> bytes:
    """Encode a jaeger.thrift Batch with TBinaryProtocol (test-side
    writer; the product only reads)."""
    import struct
    process = (_thrift_field(1, 11, _thrift_str(service)) +
               _thrift_field(2, 15, _thrift_list(
                   12, [_jaeger_tag("hostname", "h1")])) + b"\x00")
    enc_spans = []
    for s in spans:
        b = (_thrift_field(1, 10, struct.pack(">q", s["tid_lo"])) +
             _thrift_field(2, 10, struct.pack(">q", s.get("tid_hi", 0))) +
             _thrift_field(3, 10, struct.pack(">q", s["sid"])) +
             _thrift_field(4, 10, struct.pack(">q", s.get("psid", 0))) +
             _thrift_field(5, 11, _thrift_str(s["name"])) +
             _thrift_field(7, 8, struct.pack(">i", 1)) +
             _thrift_field(8, 10, struct.pack(">q", s["start_us"])) +
             _thrift_field(9, 10, struct.pack(">q", s["dur_us"])))
        tags = [_jaeger_tag(k, v) for k, v in s.get("tags", {}).items()]
        if tags:
            b += _thrift_field(10, 15, _thrift_list(12, tags))
        enc_spans.append(b + b"\x00")
    return (_thrift_field(1, 12, process) +
            _thrift_field(2, 15, _thrift_list(12, enc_spans)) + b"\x00")


def test_jaeger_receiver(server):
    import struct
    import time
    app, base = server
    start_us = int((time.time() - 3) * 1e6)
    batch = _jaeger_batch("jaeger-svc", [{
        "tid_lo": 0x0102030405060708, "tid_hi": 0x1112131415161718,
        "sid": 0x0A0B0C0D0E0F1011, "name": "jg-op",
        "start_us": start_us, "dur_us": 75_000,
        "tags": {"span.kind": "server", "http.status_code": 500,
                 "error": True, "peer.address": "10.0.0.9"},
    }])
    req = urllib.request.Request(f"{base}/api/traces", data=batch,
                                 headers={"Content-Type":
                                          "application/x-thrift"})
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 202
    tid_hex = "1112131415161718" + "0102030405060708"
    code, tr = _get(f"{base}/api/traces/{tid_hex}")
    assert code == 200 and tr["spans"][0]["name"] == "jg-op"
    sp = tr["spans"][0]
    assert sp["service"] == "jaeger-svc"
    assert sp["kind"] == 2                      # span.kind=server
    assert sp["status_code"] == 2               # error=true
    assert sp["attrs"]["http.status_code"] == 500
    assert sp["attrs"]["peer.address"] == "10.0.0.9"
    assert "span.kind" not in sp["attrs"]       # mapped, not duplicated
    assert sp["res_attrs"]["hostname"] == "h1"
    assert sp["end_unix_nano"] - sp["start_unix_nano"] == 75_000_000
    # the generator tee aggregated it (re-encoded OTLP wire path)
    inst = app.generator.instance("single-tenant")
    assert inst.spans_received >= 1
    # search finds it by service
    code, res = _get(f"{base}/api/search?q=" + urllib.parse.quote(
        '{ resource.service.name = "jaeger-svc" }'))
    assert code == 200 and len(res["traces"]) == 1
    # malformed payload -> 400
    bad = urllib.request.Request(f"{base}/api/traces", data=b"\x0b\x00\x01",
                                 headers={"Content-Type":
                                          "application/x-thrift"})
    try:
        urllib.request.urlopen(bad, timeout=10)
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_ops_files_reference_only_emitted_metrics(server):
    """Every tempo_* metric named in operations/ dashboards + alerts must
    be REGISTERED in the obs registry (the drift gate: no aspirational
    metric names), and the core write-path names must actually appear on
    /metrics after traffic — byte-compatible with the pre-registry
    exposition."""
    import os
    import re
    import time

    from tempo_tpu.obs import drift
    from tempo_tpu.obs.jaxruntime import RUNTIME

    app, base = server
    t0 = int((time.time() - 5) * 1e9)
    body = json.dumps(OTLP).replace('"{t0}"', str(t0)) \
                           .replace('"{t1}"', str(t0 + 50_000_000))
    _post(f"{base}/v1/traces", body.encode())
    _get(f"{base}/api/search?q=" + urllib.parse.quote("{ }"))
    now = time.time()
    _get(f"{base}/api/metrics/query_range?q=" +
         urllib.parse.quote("{ } | rate()") +
         f"&start={now - 300}&end={now}&step=300")

    import tempo_tpu.app.api as api_mod
    ops_dir = os.path.join(os.path.dirname(api_mod.__file__),
                           "..", "..", "operations")
    assert drift.referenced_metric_names(ops_dir), \
        "no metrics referenced — ops files missing?"
    problems = drift.check_drift(ops_dir, [app.obs, RUNTIME])
    assert not problems, "\n".join(problems)

    with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
        text = r.read().decode()
    emitted = set(re.findall(r"^(tempo_[a-z_]+)", text, re.M))
    for name in ("tempo_distributor_spans_received_total",
                 "tempo_distributor_bytes_received_total",
                 "tempo_query_frontend_queries_total",
                 "tempo_ingester_live_traces",
                 "tempo_request_duration_seconds_bucket"):
        assert name in emitted, name


def test_v2_api_endpoints(server):
    """v2 surface parity (`pkg/api/http.go:76-88`): buildinfo, v2 trace
    response, instant metrics query."""
    import time
    app, base = server
    t0 = int((time.time() - 5) * 1e9)
    body = json.dumps(OTLP).replace('"{t0}"', str(t0)) \
                           .replace('"{t1}"', str(t0 + 50_000_000))
    code, _ = _post(f"{base}/v1/traces", body.encode())
    assert code == 200
    # buildinfo needs no tenant
    code, bi = _get(f"{base}/api/status/buildinfo")
    assert code == 200 and bi["version"].startswith("tempo-tpu")
    # v2 trace-by-id wraps the trace with a status
    tid = OTLP["resourceSpans"][0]["scopeSpans"][0]["spans"][0]["traceId"]
    code, tr = _get(f"{base}/api/v2/traces/{tid}")
    assert code == 200 and tr["status"] == "COMPLETE"
    assert tr["trace"]["spans"][0]["name"] == "checkout"
    # instant metrics query: one value per series over [start, end)
    now = time.time()
    code, qi = _get(f"{base}/api/metrics/query?q=" +
                    urllib.parse.quote("{ } | rate()") +
                    f"&start={now - 300}&end={now}")
    assert code == 200
    assert any(s["value"] == s["value"] and s["value"] >= 0
               for s in qi["series"])


def test_status_usage_stats_endpoint(server):
    """PathUsageStats (`http.go:77`): the would-be-sent report, or 404
    when reporting is disabled."""
    app, base = server
    assert app.usage_reporter is not None
    code, rep = _get(f"{base}/status/usage-stats")
    assert code == 200 and "clusterID" in rep
    # a read poll must not mint a new seed per request
    code2, rep2 = _get(f"{base}/status/usage-stats")
    assert rep2["clusterID"] == rep["clusterID"]
    # disabled path → 404
    app.usage_reporter, saved = None, app.usage_reporter
    try:
        try:
            code, _ = _get(f"{base}/status/usage-stats")
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 404
    finally:
        app.usage_reporter = saved


# -- jaeger agent UDP (thrift-compact emitBatch, round 5) --------------------
#
# Test-side TCompactProtocol writer: an independent encoder so the
# decoder is checked against the SPEC (zigzag varints, delta field ids,
# header-embedded bools, little-endian doubles), not against itself.

def _c_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        x = v & 0x7F
        v >>= 7
        if v:
            out.append(x | 0x80)
        else:
            out.append(x)
            return bytes(out)


def _c_zig(v: int) -> bytes:
    return _c_varint((v << 1) ^ (v >> 63) if v >= 0 else ((v << 1) ^ -1))


def _c_field(last_fid: int, fid: int, ctype: int) -> bytes:
    delta = fid - last_fid
    if 0 < delta <= 15:
        return bytes([(delta << 4) | ctype])
    return bytes([ctype]) + _c_zig(fid)


def _c_str(s) -> bytes:
    b = s.encode() if isinstance(s, str) else s
    return _c_varint(len(b)) + b


def _c_tag(key: str, v) -> bytes:
    out = _c_field(0, 1, 8) + _c_str(key)          # key
    if isinstance(v, bool):
        out += _c_field(1, 2, 5) + _c_zig(2)       # vType BOOL
        out += _c_field(2, 5, 1 if v else 2)       # bool in the HEADER
    elif isinstance(v, int):
        out += _c_field(1, 2, 5) + _c_zig(3)       # vType LONG
        out += _c_field(2, 6, 6) + _c_zig(v)
    elif isinstance(v, float):
        import struct as _s
        out += _c_field(1, 2, 5) + _c_zig(1)       # vType DOUBLE
        out += _c_field(2, 4, 7) + _s.pack("<d", v)
    else:
        out += _c_field(1, 2, 5) + _c_zig(0)       # vType STRING
        out += _c_field(2, 3, 8) + _c_str(v)
    return out + b"\x00"


def _c_list(structs: list[bytes]) -> bytes:
    n = len(structs)
    if n < 15:
        hdr = bytes([(n << 4) | 12])
    else:
        hdr = bytes([0xF0 | 12]) + _c_varint(n)
    return hdr + b"".join(structs)


def _agent_datagram(service: str, spans: list[dict]) -> bytes:
    span_structs = []
    for s in spans:
        b = (_c_field(0, 1, 6) + _c_zig(s["tid_lo"]) +
             _c_field(1, 2, 6) + _c_zig(s["tid_hi"]) +
             _c_field(2, 3, 6) + _c_zig(s["sid"]) +
             _c_field(3, 4, 6) + _c_zig(s.get("psid", 0)) +
             _c_field(4, 5, 8) + _c_str(s["name"]) +
             _c_field(5, 8, 6) + _c_zig(s["start_us"]) +   # delta 3
             _c_field(8, 9, 6) + _c_zig(s["dur_us"]))
        tags = [_c_tag(k, v) for k, v in s.get("tags", {}).items()]
        if tags:
            b += _c_field(9, 10, 9) + _c_list(tags)
        span_structs.append(b + b"\x00")
    process = (_c_field(0, 1, 8) + _c_str(service) +
               _c_field(1, 2, 9) + _c_list([_c_tag("hostname", "h7")]) +
               b"\x00")
    batch = (_c_field(0, 1, 12) + process +
             _c_field(1, 2, 9) + _c_list(span_structs) + b"\x00")
    args = _c_field(0, 1, 12) + batch + b"\x00"
    return (b"\x82" + bytes([(4 << 5) | 1]) +       # ONEWAY, version 1
            _c_varint(7) + _c_str("emitBatch") + args)


def test_jaeger_agent_udp_receiver():
    import socket as _socket
    import time as _time

    from tempo_tpu.distributor.receiver_agent import (JaegerAgentConfig,
                                                      JaegerAgentReceiver)

    pushed = []

    class _Rec:
        def push_spans(self, tenant, spans, size_bytes=None, **kw):
            pushed.append((tenant, spans))
            return {}

    rx = JaegerAgentReceiver(_Rec(), JaegerAgentConfig(host="127.0.0.1",
                                                       port=0))
    rx.start()
    try:
        gram = _agent_datagram("udp-svc", [{
            "tid_lo": 0x1234, "tid_hi": 0, "sid": 0x77, "psid": 0x55,
            "name": "udp-op", "start_us": 1_700_000_000_000_000,
            "dur_us": 25_000,
            "tags": {"span.kind": "server", "error": True,
                     "retries": 3, "ratio": 0.5, "note": "hé"}}])
        s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        s.sendto(gram, ("127.0.0.1", rx.port))
        s.sendto(b"\xff junk not thrift", ("127.0.0.1", rx.port))
        deadline = _time.time() + 5
        while _time.time() < deadline and (not pushed or rx.errors < 1):
            _time.sleep(0.02)
        assert rx.batches_received == 1 and rx.errors == 1
        tenant, spans = pushed[0]
        assert tenant == "single-tenant" and len(spans) == 1
        sp = spans[0]
        assert sp["name"] == "udp-op" and sp["service"] == "udp-svc"
        assert sp["trace_id"].hex() == "0" * 16 + "0000000000001234"
        assert sp["span_id"].hex() == "0000000000000077"
        assert sp["parent_span_id"].hex() == "0000000000000055"
        assert sp["kind"] == 2                       # span.kind=server
        assert sp["status_code"] == 2                # error=true
        assert sp["start_unix_nano"] == 1_700_000_000_000_000_000
        assert sp["end_unix_nano"] - sp["start_unix_nano"] == 25_000_000
        assert sp["attrs"]["retries"] == 3
        assert sp["attrs"]["ratio"] == 0.5
        assert sp["attrs"]["note"] == "hé"
        assert sp["res_attrs"] == {"hostname": "h7",
                                   "service.name": "udp-svc"}
    finally:
        rx.stop()


def test_jaeger_agent_wired_into_app(tmp_path):
    """distributor.jaeger_agent_port boots the UDP receiver inside the
    app; a datagram lands as a searchable trace end-to-end."""
    import socket as _socket
    import time as _time

    cfg = Config()
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.distributor.jaeger_agent_port = free_port()
    app = App(cfg)
    app.start_loops()
    try:
        now_us = int(_time.time() * 1e6)
        gram = _agent_datagram("agent-svc", [{
            "tid_lo": 0xABCD, "tid_hi": 0, "sid": 1,
            "name": "agent-op", "start_us": now_us, "dur_us": 1000,
            "tags": {}}])
        s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        s.sendto(gram, ("127.0.0.1", app.jaeger_agent.port))
        deadline = _time.time() + 5
        while _time.time() < deadline and \
                app.jaeger_agent.spans_received < 1:
            _time.sleep(0.02)
        assert app.jaeger_agent.spans_received == 1
        tid = bytes(8) + (0xABCD).to_bytes(8, "big")
        spans = app.ingester.find_trace_by_id("single-tenant", tid)
        assert spans and spans[0]["name"] == "agent-op"
    finally:
        app.shutdown()


def test_jaeger_agent_dos_datagram_rejected_fast():
    """A crafted datagram claiming a huge fixed-size collection count must
    raise (and quickly) — fixed-size skips never touch the buffer, so an
    unbounded count would spin the receiver thread forever (remote
    unauthenticated DoS, round-5 review finding)."""
    import time as _time

    from tempo_tpu.model.jaeger import spans_from_jaeger_agent

    # message header + args struct holding field 1 as a LIST of BYTE with
    # a ~2^41 claimed count
    evil = (b"\x82" + bytes([(4 << 5) | 1]) + _c_varint(1) +
            _c_str("emitBatch") +
            bytes([(1 << 4) | 9]) +           # field 1, LIST
            bytes([0xF3]) +                   # long form, elem BYTE
            _c_varint(1 << 41) + b"\x00")
    t0 = _time.time()
    with pytest.raises(ValueError):
        spans_from_jaeger_agent(evil)
    assert _time.time() - t0 < 1.0
    # same for maps and doubles
    for elem in (7, 1):
        evil2 = (b"\x82" + bytes([(4 << 5) | 1]) + _c_varint(1) +
                 _c_str("emitBatch") +
                 bytes([(1 << 4) | 9]) + bytes([0xF0 | elem]) +
                 _c_varint(1 << 41) + b"\x00")
        with pytest.raises(ValueError):
            spans_from_jaeger_agent(evil2)


def test_app_rejects_both_cache_tiers():
    from tempo_tpu.app import App
    from tempo_tpu.app.config import Config

    cfg = Config(target="querier")
    cfg.storage.backend = "mem"
    cfg.storage.memcached_addrs = "127.0.0.1:11211"
    cfg.storage.redis_addrs = "127.0.0.1:6379"
    with pytest.raises(ValueError, match="ONE shared cache tier"):
        App(cfg)
