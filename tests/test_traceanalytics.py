"""trace-analytics processor: structural critical-path + error propagation.

Correctness contract: the vectorized device kernel (sorted-id parent
resolution, lexicographic bounding-child argmax, log-depth pointer
jumping) is differentially tested against a pure-Python oracle on random
DAGs — fan-out/depth mixes, async gaps, overlapping children, injected
cycles, orphans, duplicate span ids. Degradation contract: corrupt
structure COUNTS (cycle/orphan/late counters), never hangs or skews.
Durability contract: the share-moments sidecar rides fleet
checkpoint/restore via the aux mechanism and WAL replay reproduces
planes bit-identically.
"""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.fleet import checkpoint as ck
from tempo_tpu.generator.instance import GeneratorConfig, GeneratorInstance
from tempo_tpu.generator.processors import traceanalytics as ta_mod
from tempo_tpu.generator.processors.traceanalytics import (
    TraceAnalyticsConfig,
)
from tempo_tpu.model.span_batch import SpanBatchBuilder, void_keys
from tempo_tpu.ops import structure

T0 = 1_700_000_000.0


def _ns(s: float) -> int:
    return int(s * 1e9)


def _bucket(n: int, lo: int) -> int:
    import math
    return 1 << math.ceil(math.log2(max(n, lo)))


# ---------------------------------------------------------------------------
# kernel vs oracle differential
# ---------------------------------------------------------------------------


def _gen_structure_batch(n_traces: int, rng) -> tuple:
    """Random DAG batch with the full corruption menu: orphans, 2-cycles,
    duplicate span ids, async gaps (children ending after parents)."""
    grp, sid, pid, start, end, err = [], [], [], [], [], []
    for t in range(n_traces):
        n = int(rng.integers(1, 30))
        ids = rng.integers(1, 2**63, size=n, dtype=np.int64).view(np.uint64)
        rows = []
        base = int(rng.integers(0, 10**9)) * 1000
        for i in range(n):
            # extra roots model broken instrumentation (multi-root traces)
            p = 0 if i == 0 or rng.random() < 0.1 \
                else int(ids[rng.integers(0, i)])
            s = base + int(rng.integers(0, 10**6))
            e = s + int(rng.integers(1, 10**6))  # may overlap/outlive parent
            rows.append((int(ids[i]), p, s, e, rng.random() < 0.3))
        if rng.random() < 0.3:  # orphan: parent id that resolves nowhere
            rows.append((int(rng.integers(1, 2**62)),
                         int(rng.integers(2**62, 2**63)), base, base + 5,
                         True))
        if rng.random() < 0.3:  # 2-cycle: spans parenting each other
            a = int(rng.integers(1, 2**62))
            b = int(rng.integers(1, 2**62))
            rows.append((a, b, base, base + 10, False))
            rows.append((b, a, base, base + 11, True))
        if rng.random() < 0.2:  # duplicate span id (last definition wins)
            dup = rows[int(rng.integers(0, len(rows)))]
            rows.append((dup[0], dup[1], base + 3, base + 7, False))
        for (i8, p8, s, e, er) in rows:
            grp.append(t)
            sid.append(np.frombuffer(np.uint64(i8).tobytes(), np.uint8))
            pid.append(np.frombuffer(np.uint64(p8).tobytes(), np.uint8))
            start.append(s)
            end.append(e)
            err.append(er)
    return (np.array(grp, np.int32), np.stack(sid), np.stack(pid),
            np.array(start, np.int64), np.array(end, np.int64),
            np.array(err, bool))


def test_structure_kernel_matches_oracle():
    """Device kernel exactly equals the pure-Python reference on random
    corrupt DAGs: parent rows, path membership, bounding children,
    errored bounding children, cycle flags, anchors, root causes (on the
    settled mask), and int64 self-times."""
    rng = np.random.default_rng(0)
    for trial in range(12):
        nt = int(rng.integers(1, 12))
        grp, sid, pid, start, end, err = _gen_structure_batch(nt, rng)
        n = len(grp)
        res = structure.analyze(grp, sid, pid, end, err, nt,
                                _bucket(n, 256), _bucket(nt, 16))
        ref = structure.reference_analysis(grp, sid, pid, end, err)
        for k in ("parent_row", "on_path", "bc", "ebc", "cyclic", "anchor"):
            assert np.array_equal(res[k], ref[k]), (trial, k)
        # root cause compared on the settled mask (the same mask the
        # processor attributes under) — and the masks themselves agree
        ok = err & ~res["cyclic"] & (res["ebc"][np.clip(res["rc"], 0,
                                                        n - 1)] < 0)
        ok_ref = err & ~ref["cyclic"] & (ref["ebc"][np.clip(ref["rc"], 0,
                                                            n - 1)] < 0)
        assert np.array_equal(ok, ok_ref), trial
        assert np.array_equal(res["rc"][ok], ref["rc"][ok]), trial
        assert np.array_equal(structure.self_times_ns(start, end, res),
                              structure.self_times_ns(start, end, ref)), trial


def test_structure_padding_invariance():
    """Results must not depend on the pow-2 pad sizes."""
    rng = np.random.default_rng(7)
    grp, sid, pid, start, end, err = _gen_structure_batch(5, rng)
    n = len(grp)
    a = structure.analyze(grp, sid, pid, end, err, 5,
                          _bucket(n, 256), _bucket(5, 16))
    b = structure.analyze(grp, sid, pid, end, err, 5,
                          _bucket(n, 256) * 4, _bucket(5, 16) * 2)
    for k in a:
        assert np.array_equal(a[k], b[k]), k


# ---------------------------------------------------------------------------
# processor end-to-end
# ---------------------------------------------------------------------------


def _ta_cfg(**kw) -> GeneratorConfig:
    ta = dict(trace_idle_s=1.0, late_window_s=30.0)
    ta.update(kw)
    return GeneratorConfig(processors=("trace-analytics",),
                           traceanalytics=TraceAnalyticsConfig(**ta))


def _known_trace(b: SpanBatchBuilder, weights: list | None = None) -> None:
    """root(svc-a, 10s) -> c1(svc-b, ends 9s, ERR) -> g1(svc-c, ends 8s,
    ERR); root -> c2(svc-b, ends 5s). Critical path root->c1->g1 with
    self-times 1s/1s/7s; both errors root-cause to svc-c."""
    tid = b"\x01" * 16
    b.append(trace_id=tid, span_id=b"\x01" * 8, name="root", service="svc-a",
             start_unix_nano=_ns(T0), end_unix_nano=_ns(T0 + 10))
    b.append(trace_id=tid, span_id=b"\x02" * 8, parent_span_id=b"\x01" * 8,
             name="c1", service="svc-b", status_code=2,
             start_unix_nano=_ns(T0 + 0.5), end_unix_nano=_ns(T0 + 9))
    b.append(trace_id=tid, span_id=b"\x03" * 8, parent_span_id=b"\x02" * 8,
             name="g1", service="svc-c", status_code=2,
             start_unix_nano=_ns(T0 + 1), end_unix_nano=_ns(T0 + 8))
    b.append(trace_id=tid, span_id=b"\x04" * 8, parent_span_id=b"\x01" * 8,
             name="c2", service="svc-b",
             start_unix_nano=_ns(T0 + 0.5), end_unix_nano=_ns(T0 + 5))


def _collect(gi: GeneratorInstance) -> dict:
    from tempo_tpu import sched
    sched.flush()
    return {(s.name, s.labels): s.value
            for s in gi.registry.collect(ts_ms=1) if not s.is_stale_marker}


def _val(samples: dict, name: str, **labels) -> float:
    for (n, labs), v in samples.items():
        if n == name and all((k, want) in labs
                             for k, want in labels.items()):
            return v
    raise KeyError((name, labels, sorted(samples)))


def test_processor_known_topology_attribution():
    clock = [T0]
    gi = GeneratorInstance("t1", _ta_cfg(), now=lambda: clock[0])
    b = SpanBatchBuilder(gi.registry.interner)
    _known_trace(b)
    # corrupt second trace: a parent 2-cycle — counted, not attributed
    tid2 = b"\x02" * 16
    b.append(trace_id=tid2, span_id=b"\x0a" * 8, parent_span_id=b"\x0b" * 8,
             name="x", service="svc-a",
             start_unix_nano=_ns(T0), end_unix_nano=_ns(T0 + 1))
    b.append(trace_id=tid2, span_id=b"\x0b" * 8, parent_span_id=b"\x0a" * 8,
             name="y", service="svc-a",
             start_unix_nano=_ns(T0), end_unix_nano=_ns(T0 + 1))
    gi.push_batch(b.build())
    clock[0] += 2
    gi.tick()
    got = _collect(gi)
    cp = "tempo_critical_path_seconds_total"
    assert _val(got, cp, service="svc-a", operation="root") == \
        pytest.approx(1.0)
    assert _val(got, cp, service="svc-b", operation="c1") == \
        pytest.approx(1.0)
    assert _val(got, cp, service="svc-c", operation="g1") == \
        pytest.approx(7.0)
    # c2 is off-path: no series
    with pytest.raises(KeyError):
        _val(got, cp, operation="c2")
    rc = "tempo_error_root_cause_total"
    assert _val(got, rc, service="svc-b", root_service="svc-c") == 1.0
    assert _val(got, rc, service="svc-c", root_service="svc-c") == 1.0
    assert ta_mod._cycle_spans.get("t1") == 2.0
    assert ta_mod._cut_traces.get("t1") == 2.0
    # share quantile surface: g1 bounds 70% of its trace's duration
    q = gi.processors["trace-analytics"].quantile(0.5)
    shares = {dict(lab)["operation"]: v for lab, v in q.items()}
    assert shares["g1"] == pytest.approx(0.7, abs=0.05)
    assert shares["root"] == pytest.approx(0.1, abs=0.05)


def test_processor_weighted_attribution():
    """Horvitz-Thompson sample weights scale both planes linearly."""
    clock = [T0]
    gi = GeneratorInstance("t1", _ta_cfg(), now=lambda: clock[0])
    b = SpanBatchBuilder(gi.registry.interner)
    _known_trace(b)
    gi.push_batch(b.build(), sample_weights=np.full(4, 3.0, np.float32))
    clock[0] += 2
    gi.tick()
    got = _collect(gi)
    assert _val(got, "tempo_critical_path_seconds_total",
                service="svc-c", operation="g1") == pytest.approx(21.0)
    assert _val(got, "tempo_error_root_cause_total",
                service="svc-c", root_service="svc-c") == 3.0


def test_late_spans_counted_not_reattributed():
    clock = [T0]
    gi = GeneratorInstance("t1", _ta_cfg(late_window_s=10.0),
                           now=lambda: clock[0])
    b = SpanBatchBuilder(gi.registry.interner)
    _known_trace(b)
    gi.push_batch(b.build())
    clock[0] += 2
    gi.tick()
    base = _collect(gi)
    # a straggler for the already-cut trace: counted late, planes frozen
    b2 = SpanBatchBuilder(gi.registry.interner)
    b2.append(trace_id=b"\x01" * 16, span_id=b"\x05" * 8,
              parent_span_id=b"\x01" * 8, name="late", service="svc-b",
              start_unix_nano=_ns(T0), end_unix_nano=_ns(T0 + 20))
    gi.push_batch(b2.build())
    clock[0] += 1
    gi.tick()
    assert ta_mod._late_spans.get("t1") == 1.0
    assert _collect(gi) == base
    # past the late window the key expires and the id becomes a NEW
    # (single-span) trace — the documented re-open semantics
    clock[0] += 20
    gi.tick()
    gi.push_batch(b2.build())
    assert ta_mod._late_spans.get("t1") == 1.0


def test_orphan_spans_feed_dataquality_counter():
    from tempo_tpu.utils import dataquality as dq
    clock = [T0]
    gi = GeneratorInstance("t1", _ta_cfg(), now=lambda: clock[0])
    b = SpanBatchBuilder(gi.registry.interner)
    _known_trace(b)
    b.append(trace_id=b"\x01" * 16, span_id=b"\x06" * 8,
             parent_span_id=b"\xee" * 8, name="lost", service="svc-b",
             start_unix_nano=_ns(T0), end_unix_nano=_ns(T0 + 1))
    gi.push_batch(b.build())
    clock[0] += 2
    gi.tick()
    assert dq.orphan_spans_snapshot().get("t1") == 1


def test_max_spans_per_trace_overflow_counts_late():
    clock = [T0]
    gi = GeneratorInstance("t1", _ta_cfg(max_spans_per_trace=8),
                           now=lambda: clock[0])
    b = SpanBatchBuilder(gi.registry.interner)
    tid = b"\x03" * 16
    for i in range(12):
        b.append(trace_id=tid, span_id=bytes([i + 1]) * 8,
                 parent_span_id=b"" if i == 0 else bytes([1]) * 8,
                 name="op", service="svc",
                 start_unix_nano=_ns(T0), end_unix_nano=_ns(T0 + 1))
    gi.push_batch(b.build())
    assert gi.processors["trace-analytics"].spans_buffered == 8
    assert ta_mod._late_spans.get("t1") == 4.0


def test_max_live_traces_cuts_oldest_early():
    clock = [T0]
    gi = GeneratorInstance("t1", _ta_cfg(max_live_traces=8),
                           now=lambda: clock[0])
    b = SpanBatchBuilder(gi.registry.interner)
    for i in range(16):
        b.append(trace_id=bytes([i + 1]) * 16, span_id=b"\x01" * 8,
                 name="op", service="svc",
                 start_unix_nano=_ns(T0), end_unix_nano=_ns(T0 + 1))
    gi.push_batch(b.build())
    p = gi.processors["trace-analytics"]
    assert len(p._live) <= 8
    assert ta_mod._cut_traces.get("t1", 0) >= 8


# ---------------------------------------------------------------------------
# servicegraphs vectorized keys (satellite)
# ---------------------------------------------------------------------------


def test_void_keys_match_byte_concatenation():
    """The np.void fast path must produce EXACTLY the bytes the old
    per-span `tobytes() + tobytes()` concatenation produced — the edge
    store is keyed by these bytes across pushes."""
    rng = np.random.default_rng(3)
    tid = rng.integers(0, 256, (50, 16), dtype=np.uint8)
    sid = rng.integers(0, 256, (50, 8), dtype=np.uint8)
    keys = void_keys(tid, sid)
    for i in range(50):
        assert keys[i].item() == tid[i].tobytes() + sid[i].tobytes()
    # single-column form too (trace grouping in trace-analytics)
    k1 = void_keys(tid)
    assert k1[0].item() == tid[0].tobytes()
    # vectorized ops the processors rely on behave like bytes equality
    order = np.argsort(keys, kind="stable")
    py = sorted(range(50), key=lambda i: keys[i].item())
    assert order.tolist() == py


# ---------------------------------------------------------------------------
# fleet checkpoint/restore + WAL replay
# ---------------------------------------------------------------------------


def _random_push(gi: GeneratorInstance, seed: int, n_traces: int = 10,
                 now: float = T0) -> None:
    rng = np.random.default_rng(seed)
    b = SpanBatchBuilder(gi.registry.interner)
    for _ in range(n_traces):
        tid = rng.bytes(16)
        sids = [rng.bytes(8) for _ in range(6)]
        for i in range(6):
            par = b"" if i == 0 else sids[int(rng.integers(0, i))]
            b.append(trace_id=tid, span_id=sids[i], parent_span_id=par,
                     name=f"op-{i % 3}", service=f"svc-{i % 2}",
                     status_code=int(rng.random() < 0.3) * 2,
                     start_unix_nano=_ns(now) + i * 1000,
                     end_unix_nano=_ns(now) + int(rng.integers(10**6,
                                                               10**9)))
    gi.push_batch(b.build())


def test_checkpoint_roundtrip_aux_planes_bit_identical():
    """Fresh-instance restore is add-to-zero: counter planes AND the
    share-moments sidecar (aux mechanism) round-trip bit-identically."""
    clock = [T0]
    a = GeneratorInstance("t1", _ta_cfg(), now=lambda: clock[0])
    _random_push(a, 1)
    clock[0] += 5
    a.tick(immediate=True)
    from tempo_tpu import sched
    sched.flush()
    blob = ck.snapshot_instance(a)
    b = GeneratorInstance("t1", _ta_cfg(), now=lambda: clock[0])
    stats = ck.restore_instance(b, blob)
    assert stats["dropped"] == 0 and stats["series"] > 0
    assert _collect(b) == _collect(a)
    qa = a.processors["trace-analytics"].quantile(0.9)
    assert qa and b.processors["trace-analytics"].quantile(0.9) == qa


def test_checkpoint_merge_into_nonempty_adds():
    clock = [T0]
    a = GeneratorInstance("t1", _ta_cfg(), now=lambda: clock[0])
    _random_push(a, 1)
    clock[0] += 5
    a.tick(immediate=True)
    from tempo_tpu import sched
    sched.flush()
    want = _collect(a)
    blob = ck.snapshot_instance(a)
    c = GeneratorInstance("t1", _ta_cfg(), now=lambda: clock[0])
    _random_push(c, 2, now=clock[0])
    clock[0] += 5
    c.tick(immediate=True)
    sched.flush()
    before = _collect(c)
    ck.restore_instance(c, blob)
    after = _collect(c)
    for k, v in want.items():
        assert after[k] == pytest.approx(before.get(k, 0.0) + v, rel=1e-5)


def test_checkpoint_refuses_sketch_config_mismatch():
    """The traceanalytics fingerprint block: a sketch-enabled blob must
    not merge into a sketch-disabled instance (and the block is absent
    entirely for tenants without the processor — their fingerprints are
    unchanged by this feature)."""
    clock = [T0]
    a = GeneratorInstance("t1", _ta_cfg(), now=lambda: clock[0])
    _random_push(a, 1)
    clock[0] += 5
    a.tick(immediate=True)
    from tempo_tpu import sched
    sched.flush()
    blob = ck.snapshot_instance(a)
    d = GeneratorInstance(
        "t1", _ta_cfg(enable_latency_share_sketch=False),
        now=lambda: clock[0])
    with pytest.raises(ck.CheckpointMismatch):
        ck.restore_instance(d, blob)
    # the blob actually carries aux planes under the processor key
    meta, arrays = ck._decode(blob)
    assert meta["aux"]["trace-analytics"]["family"] == \
        "tempo_critical_path_seconds_total"
    assert any(k.startswith("__aux__::trace-analytics::") for k in arrays)


def test_wal_replay_reproduces_planes_bit_identically(tmp_path):
    """Kill-shape recovery: replaying the ingest WAL and cutting
    reproduces the analytics planes and quantile surface exactly —
    live (un-cut) traces are WAL state, not checkpoint state."""
    from tempo_tpu.generator.generator import Generator
    from tempo_tpu.generator.wal import GeneratorWal, IngestWalConfig
    from tempo_tpu.model.otlp import encode_spans_otlp
    from tempo_tpu.overrides import Overrides
    from tempo_tpu.overrides.limits import Limits
    from tempo_tpu import sched

    lim = Limits()
    lim.generator.processors = ("trace-analytics",)
    lim.generator.ingestion_time_range_slack_s = 0.0
    lim.generator.collection_interval_s = 3600.0

    def mkgen():
        wal = GeneratorWal(IngestWalConfig(enabled=True,
                                           dir=str(tmp_path / "wal")))
        return Generator(
            GeneratorConfig(
                traceanalytics=TraceAnalyticsConfig(trace_idle_s=1.0)),
            instance_id="m0", overrides=Overrides(defaults=lim), wal=wal)

    rng = np.random.default_rng(9)
    spans = []
    for _ in range(8):
        tid = rng.bytes(16)
        sids = [rng.bytes(8) for _ in range(5)]
        for i in range(5):
            spans.append(dict(
                trace_id=tid, span_id=sids[i],
                parent_span_id=b"" if i == 0
                else sids[int(rng.integers(0, i))],
                name=f"op-{i % 3}", service=f"svc-{i % 2}",
                status_code=int(rng.random() < 0.3) * 2,
                start_unix_nano=_ns(T0) + i,
                end_unix_nano=_ns(T0) + int(rng.integers(10**6, 10**9))))
    g1 = mkgen()
    g1.push_otlp("t1", encode_spans_otlp(spans))
    g1.instance("t1").tick(immediate=True)
    sched.flush()
    want = _collect(g1.instance("t1"))
    want_q = g1.instance("t1").processors["trace-analytics"].quantile(0.9)
    assert want_q

    g2 = mkgen()  # abandoned g1: no shutdown, no checkpoint
    assert g2.replay_wal_all()["batches"] == 1
    g2.instance("t1").tick(immediate=True)
    sched.flush()
    assert _collect(g2.instance("t1")) == want
    assert g2.instance("t1").processors["trace-analytics"].quantile(0.9) \
        == want_q


def test_quantile_endpoint_serves_latency_shares(tmp_path):
    """/internal/generator/quantile?proc=trace-analytics serves the
    critical-path latency-share quantiles over HTTP — the same maxent
    surface the processor's quantile() computes — and the default proc
    stays span-metrics (absent here: empty, not an error)."""
    import json
    import socket
    import time as _time
    import urllib.request

    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cfg = Config()
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.server.http_listen_port = port
    app = App(cfg)
    app.overrides.set_tenant_patch("single-tenant", {
        "generator": {"processors": ["trace-analytics"]}})
    srv = serve(app, block=False)
    base = f"http://127.0.0.1:{port}"
    try:
        rng = np.random.default_rng(3)
        now_ns = int(_time.time() * 1e9)
        spans = []
        tid = rng.bytes(16)
        sids = [rng.bytes(8) for _ in range(6)]
        for i in range(6):
            spans.append(dict(
                trace_id=tid, span_id=sids[i],
                parent_span_id=b"" if i == 0 else sids[i - 1],
                name=f"op-{i % 2}", service="svc", kind=2, status_code=0,
                start_unix_nano=now_ns + i,
                end_unix_nano=now_ns + (6 - i) * 10**6))
        from tempo_tpu.model.otlp import encode_spans_otlp
        req = urllib.request.Request(
            f"{base}/v1/traces", data=encode_spans_otlp(spans),
            headers={"Content-Type": "application/x-protobuf"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        app.generator.instance("single-tenant").tick(immediate=True)
        with urllib.request.urlopen(
                f"{base}/internal/generator/quantile"
                "?proc=trace-analytics&q=0.5", timeout=10) as r:
            doc = json.loads(r.read())
        got = {tuple(tuple(kv) for kv in e["labels"]): e["value"]
               for e in doc["quantiles"]}
        want = app.generator.instance("single-tenant") \
            .processors["trace-analytics"].quantile(0.5)
        assert got and got == {tuple(k): v for k, v in want.items()}
        # default proc (span-metrics) is not enabled for this tenant
        with urllib.request.urlopen(
                f"{base}/internal/generator/quantile?q=0.5",
                timeout=10) as r:
            assert json.loads(r.read())["quantiles"] == []
    finally:
        srv.shutdown()
        app.shutdown()
