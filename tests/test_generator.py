"""Generator processor tests — semantics mirrored from the reference's
`processor/spanmetrics/spanmetrics_test.go` and `servicegraphs_test.go`
table-driven fixtures, plus remote-write wire checks."""

import numpy as np
import pytest

from tempo_tpu.generator.instance import GeneratorConfig, GeneratorInstance
from tempo_tpu.generator.processors.spanmetrics import SpanMetricsConfig, SpanMetricsProcessor
from tempo_tpu.generator.processors.servicegraphs import ServiceGraphsConfig, ServiceGraphsProcessor
from tempo_tpu.generator import remote_write as rw
from tempo_tpu.model import proto_wire as pw
from tempo_tpu.model.span_batch import (
    KIND_CLIENT,
    KIND_SERVER,
    STATUS_ERROR,
    SpanBatchBuilder,
)
from tempo_tpu.registry import ManagedRegistry, RegistryOverrides
from tempo_tpu.registry.series import Sample
from tempo_tpu.utils.spanfilter import AttributeMatch, FilterPolicy, PolicyMatch


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _mk_batch(spans=None, interner=None):
    b = SpanBatchBuilder(interner=interner)
    for sp in spans:
        b.append(**sp)
    return b.build()


def _span(i, service="svc-a", name="op", kind=KIND_SERVER, status=0, dur_ns=10**9,
          attrs=None, parent=b"", trace=None, start=10**9):
    return dict(
        trace_id=(trace if trace is not None else bytes([i]) * 16),
        span_id=bytes([i]) * 8,
        parent_span_id=parent,
        name=name, service=service, kind=kind, status_code=status,
        start_unix_nano=start, end_unix_nano=start + dur_ns,
        attrs=attrs or {},
    )


def series_value(samples, name, **labels):
    for s in samples:
        if s.name != name or s.is_stale_marker:
            continue
        d = dict(s.labels)
        if all(d.get(k) == v for k, v in labels.items()):
            return s.value
    return None


def test_spanmetrics_red_families():
    reg = ManagedRegistry(now=FakeClock())
    p = SpanMetricsProcessor(reg, SpanMetricsConfig())
    sb = _mk_batch(interner=reg.interner, spans=[
        _span(1, service="a", name="op1", dur_ns=10**9),
        _span(2, service="a", name="op1", dur_ns=2 * 10**9),
        _span(3, service="b", name="op2", status=STATUS_ERROR, dur_ns=10**8),
    ])
    p.push_batch(sb, span_sizes=np.full(sb.capacity, 100.0, np.float32))
    samples = reg.collect(ts_ms=1)
    assert series_value(samples, "traces_spanmetrics_calls_total",
                        service="a", span_name="op1") == 2.0
    assert series_value(samples, "traces_spanmetrics_calls_total",
                        service="b", span_name="op2",
                        status_code="STATUS_CODE_ERROR") == 1.0
    assert series_value(samples, "traces_spanmetrics_latency_sum",
                        service="a", span_name="op1") == pytest.approx(3.0)
    assert series_value(samples, "traces_spanmetrics_latency_count",
                        service="a", span_name="op1") == 2.0
    assert series_value(samples, "traces_spanmetrics_size_total",
                        service="a", span_name="op1") == 200.0
    # le=2.048 bucket holds both 1s and 2s observations
    assert series_value(samples, "traces_spanmetrics_latency_bucket",
                        service="a", span_name="op1", le="2.048") == 2.0


def test_spanmetrics_custom_dimensions_and_quantile():
    reg = ManagedRegistry(now=FakeClock())
    p = SpanMetricsProcessor(reg, SpanMetricsConfig(dimensions=("http.method",)))
    sb = _mk_batch(interner=reg.interner, spans=[
        _span(1, attrs={"http.method": "GET"}, dur_ns=10**9),
        _span(2, attrs={"http.method": "POST"}, dur_ns=10**9),
        _span(3, dur_ns=10**9),
    ])
    p.push_batch(sb)
    samples = reg.collect(1)
    assert series_value(samples, "traces_spanmetrics_calls_total",
                        http_method="GET") == 1.0
    assert series_value(samples, "traces_spanmetrics_calls_total",
                        http_method="") == 1.0
    qs = p.quantile(0.5)
    assert qs and all(abs(v - 1.0) < 0.05 for v in qs.values())


def test_spanmetrics_filter_policy():
    reg = ManagedRegistry(now=FakeClock())
    pol = FilterPolicy(include=PolicyMatch("strict", (AttributeMatch("kind", "SPAN_KIND_SERVER"),)))
    p = SpanMetricsProcessor(reg, SpanMetricsConfig(filter_policies=(pol,)))
    sb = _mk_batch(interner=reg.interner, spans=[
        _span(1, kind=KIND_SERVER),
        _span(2, kind=KIND_CLIENT),
    ])
    p.push_batch(sb)
    samples = reg.collect(1)
    assert series_value(samples, "traces_spanmetrics_calls_total",
                        span_kind="SPAN_KIND_SERVER") == 1.0
    assert series_value(samples, "traces_spanmetrics_calls_total",
                        span_kind="SPAN_KIND_CLIENT") is None
    assert p.spans_discarded == 1


def test_servicegraphs_edge_completion():
    clock = FakeClock()
    reg = ManagedRegistry(now=clock)
    p = ServiceGraphsProcessor(reg, ServiceGraphsConfig())
    t = bytes(16)
    sb = _mk_batch(interner=reg.interner, spans=[
        _span(1, service="frontend", kind=KIND_CLIENT, trace=t, dur_ns=3 * 10**8),
        _span(2, service="backend", kind=KIND_SERVER, trace=t,
              parent=bytes([1]) * 8, dur_ns=2 * 10**8, status=STATUS_ERROR),
    ])
    p.push_batch(sb)
    samples = reg.collect(1)
    assert series_value(samples, "traces_service_graph_request_total",
                        client="frontend", server="backend") == 1.0
    assert series_value(samples, "traces_service_graph_request_failed_total",
                        client="frontend", server="backend") == 1.0
    assert series_value(samples, "traces_service_graph_request_client_seconds_sum",
                        client="frontend", server="backend") == pytest.approx(0.3)
    assert series_value(samples, "traces_service_graph_request_server_seconds_sum",
                        client="frontend", server="backend") == pytest.approx(0.2)


def test_servicegraphs_expiry_virtual_nodes():
    clock = FakeClock()
    reg = ManagedRegistry(now=clock)
    p = ServiceGraphsProcessor(reg, ServiceGraphsConfig(wait_s=5.0))
    # unmatched server span -> "user" virtual client after expiry
    sb = _mk_batch(interner=reg.interner, spans=[
        _span(1, service="api", kind=KIND_SERVER, parent=bytes([9]) * 8),
        _span(2, service="web", kind=KIND_CLIENT, attrs={"db.system": "mysql"}),
    ])
    p.push_batch(sb)
    assert series_value(reg.collect(1), "traces_service_graph_request_total",
                        client="user") is None
    clock.t += 10.0
    p.push_batch(_mk_batch([], interner=reg.interner))  # tick
    samples = reg.collect(2)
    assert series_value(samples, "traces_service_graph_request_total",
                        client="user", server="api") == 1.0
    assert series_value(samples, "traces_service_graph_request_total",
                        client="web", server="mysql") == 1.0
    assert p.expired == 2


def test_generator_instance_slack_filter():
    clock = FakeClock(t=1000.0)
    cfg = GeneratorConfig(processors=("span-metrics",),
                          ingestion_time_range_slack_s=30.0)
    g = GeneratorInstance("t1", cfg, now=clock)
    now_ns = int(1000.0 * 1e9)
    sb = _mk_batch(interner=g.registry.interner, spans=[
        _span(1, start=now_ns - 10**9),            # recent: kept
        _span(2, start=now_ns - 3600 * 10**9),     # 1h old: dropped
    ])
    g.push_batch(sb)
    assert g.spans_filtered_slack == 1
    samples = g.registry.collect(1)
    total = sum(s.value for s in samples if s.name == "traces_spanmetrics_calls_total")
    assert total == 1.0


# -- remote write wire ------------------------------------------------------

def snappy_decompress(data: bytes) -> bytes:
    """Tiny snappy block decoder (literals + copies) to validate framing."""
    ulen, pos = pw.read_varint(data, 0)
    out = bytearray()
    while pos < len(data):
        tag = data[pos]; pos += 1
        t = tag & 3
        if t == 0:
            ln = (tag >> 2) + 1
            if ln > 60:
                nb = ln - 60
                ln = int.from_bytes(data[pos:pos + nb], "little") + 1
                pos += nb
            out += data[pos:pos + ln]; pos += ln
        else:
            raise AssertionError("copy ops unexpected from literal-only encoder")
    assert len(out) == ulen
    return bytes(out)


def test_snappy_roundtrip_various_sizes():
    for n in (0, 1, 59, 60, 61, 255, 256, 257, 70000, 200001):
        data = bytes(range(256)) * (n // 256) + bytes(range(n % 256))
        assert snappy_decompress(rw.snappy_compress(data)) == data


def test_write_request_encoding_decodes():
    samples = [
        Sample("m_total", (("__name__", "m_total"), ("svc", "a")), 42.0, 1234),
    ]
    body = rw.encode_write_request(samples)
    ts_msgs = [v for f, _, v in pw.iter_fields(body) if f == 1]
    assert len(ts_msgs) == 1
    fields = pw.decode_fields(bytes(ts_msgs[0]))
    labels = {}
    for lb in fields[1]:
        lf = pw.decode_fields(bytes(lb))
        labels[bytes(lf[1][0]).decode()] = bytes(lf[2][0]).decode()
    assert labels == {"__name__": "m_total", "svc": "a"}
    sf = pw.decode_fields(bytes(fields[2][0]))
    assert pw.f64(sf[1][0]) == 42.0 and sf[2][0] == 1234


def test_native_histogram_encoding():
    counts = np.zeros(64)
    counts[3] = 5  # bucket b=3 covers [4,8) -> prom schema-0 index 3: (4,8]
    counts[4] = 2
    counts[10] = 1
    body = rw.encode_native_histogram(counts, total=8, zeros=0, sum_=40.0, ts_ms=7)
    f = pw.decode_fields(body)
    assert f[1][0] == 8          # count_int
    assert pw.f64(f[3][0]) == 40.0
    spans = [pw.decode_fields(bytes(s)) for s in f[11]]
    # two spans: [idx3 len2], [idx10 len1]
    assert pw.zigzag_decode(spans[0][1][0]) == 3 and spans[0][2][0] == 2
    # second span starts at prom idx 10; previous span ended at idx 5 -> gap 5
    assert pw.zigzag_decode(spans[1][1][0]) == 5 and spans[1][2][0] == 1
    deltas = [pw.zigzag_decode(d) for d in f[12]]
    assert np.cumsum(deltas).tolist() == [5, 2, 1]


def test_native_histogram_encoding_with_offset():
    # offset=32: bucket b covers [2^(b-33), 2^(b-32)). A 0.5s latency has
    # b = floor(log2 .5)+1+32 = 32 -> prom index b-32 = 0: (0.5, 1].
    counts = np.zeros(64)
    counts[32] = 4
    body = rw.encode_native_histogram(counts, total=4, zeros=0, sum_=2.0,
                                      ts_ms=7, offset=32)
    f = pw.decode_fields(body)
    spans = [pw.decode_fields(bytes(s)) for s in f[11]]
    assert pw.zigzag_decode(spans[0][1][0]) == 0 and spans[0][2][0] == 1


# -- staged fast paths (round-5 e2e throughput work) -------------------------
#
# The dedicated-spanmetrics generator resolves staged records straight to
# device arrays in C++ (`native.spanmetrics_resolve`), and the in-process
# distributor tee hands over scan RECORDS without re-parsing or slicing
# (`native.spanmetrics_from_recs`). Both must be bit-identical to the full
# SpanBatch staging path — same series table, same device states.

def _fast_slow_pair(n_spans=4096):
    import bench as _bench
    from tempo_tpu.generator.generator import Generator
    from tempo_tpu.generator.instance import GeneratorConfig
    from tempo_tpu.overrides import Overrides

    payload = _bench._make_otlp_payload(n_spans, seed=3)

    def mk():
        cfg = GeneratorConfig(processors=("span-metrics",))
        cfg.registry.disable_collection = True
        return Generator(cfg, overrides=Overrides())

    return payload, mk(), mk()


def _assert_state_equal(pa, pb):
    for a, b, what in (
            (pa.calls.state.values, pb.calls.state.values, "calls"),
            (pa.latency.state.bucket_counts, pb.latency.state.bucket_counts,
             "latency"),
            (pa.sizes.state.values, pb.sizes.state.values, "sizes"),
            (pa.dd.counts, pb.dd.counts, "ddsketch")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=what)


def test_staged_fast_path_matches_full_staging():
    payload, fast, slow = _fast_slow_pair()
    slow.instance("t").push_otlp_staged = lambda *a, **k: None  # force full
    for _ in range(2):                      # second push hits warm tables
        n1 = fast.push_otlp("t", payload)
        n2 = slow.push_otlp("t", payload)
    assert n1 == n2 == 4096
    pf = fast.instance("t").processors["span-metrics"]
    ps = slow.instance("t").processors["span-metrics"]
    _assert_state_equal(pf, ps)
    # collected samples agree (labels resolve through the same interner)
    sa = sorted((s.name, s.labels, s.value)
                for s in fast.instance("t").registry.collect(1000))
    sb = sorted((s.name, s.labels, s.value)
                for s in slow.instance("t").registry.collect(1000))
    assert sa == sb and sa


def test_tee_recs_route_matches_payload_route():
    from tempo_tpu import native
    payload, ga, gb = _fast_slow_pair()
    recs = native.otlp_scan(payload)
    if recs is None:
        pytest.skip("native layer unavailable")
    gb.push_otlp_recs = lambda *a, **k: None    # force payload-bytes route
    for _ in range(2):
        got = ga.push_otlp_recs("t", payload, recs)
        assert got == 4096
        gb.push_otlp("t", payload, trusted=True)
    _assert_state_equal(ga.instance("t").processors["span-metrics"],
                        gb.instance("t").processors["span-metrics"])


def test_tee_recs_route_sharded_subset():
    """A ring-sharded tee passes a record SUBSET with the ORIGINAL payload;
    series must match pushing the equivalent sliced payload."""
    from tempo_tpu import native
    from tempo_tpu.model.otlp import slice_otlp_payload
    payload, ga, gb = _fast_slow_pair()
    recs = native.otlp_scan(payload)
    if recs is None:
        pytest.skip("native layer unavailable")
    pick = np.arange(len(recs)) % 3 == 0
    sub = recs[pick]
    assert ga.push_otlp_recs("t", payload, sub) == int(pick.sum())
    sliced = slice_otlp_payload(payload, recs,
                                np.flatnonzero(pick).tolist())
    gb.push_otlp("t", sliced, trusted=True)
    _assert_state_equal(ga.instance("t").processors["span-metrics"],
                        gb.instance("t").processors["span-metrics"])


def test_staged_fast_path_slack_filter_counts():
    import bench as _bench
    from tempo_tpu.generator.generator import Generator
    from tempo_tpu.generator.instance import GeneratorConfig
    from tempo_tpu.overrides import Overrides

    cfg = GeneratorConfig(processors=("span-metrics",))
    cfg.registry.disable_collection = True
    cfg.ingestion_time_range_slack_s = 30.0
    gen = Generator(cfg, overrides=Overrides())
    payload = _bench._make_otlp_payload(512, seed=9)
    import time as _time
    inst = gen.instance("t")
    # make every span stale: pushes far in the "future" slide the window
    inst.now = lambda: _time.time() + 10_000
    gen.push_otlp("t", payload)
    assert inst.spans_filtered_slack == 512
    assert inst.spans_received == 512


def test_donating_push_vs_concurrent_collection():
    """The packed fast path DONATES state buffers; collect()/
    native_histograms()/quantile() run on the collection thread and must
    serialize on the registry state_lock — an unguarded reader dies with
    'Array has been deleted' (caught live by this hammer before the
    quantile read moved inside the lock)."""
    import threading

    import bench as _bench
    from tempo_tpu.generator.generator import Generator
    from tempo_tpu.generator.instance import GeneratorConfig
    from tempo_tpu.overrides import Overrides

    payload = _bench._make_otlp_payload(2048, seed=8)
    gen = Generator(GeneratorConfig(processors=("span-metrics",)),
                    overrides=Overrides())
    gen.push_otlp("t", payload)
    inst = gen.instance("t")
    proc = inst.processors["span-metrics"]
    # the hammer is vacuous unless the DONATING staged path is live
    assert proc.supports_staged_fast_path()
    assert inst.push_otlp_staged(payload) is not None
    stop = threading.Event()
    errs: list = []

    def collector():
        while not stop.is_set():
            try:
                inst.registry.collect(1000)
                inst.registry.native_histograms(1000)
                proc.quantile(0.99)
            except Exception as e:      # pragma: no cover - the regression
                errs.append(repr(e))
                return

    t = threading.Thread(target=collector)
    t.start()
    try:
        for i in range(40):
            gen.push_otlp("t", payload)
            if i % 8 == 0:      # the dict route donates too (push_batch)
                gen.push_spans("t", [{
                    "trace_id": b"\x01" * 16, "span_id": bytes([i]) * 8,
                    "name": "d", "service": "s", "kind": 2,
                    "status_code": 0, "start_unix_nano": 1,
                    "end_unix_nano": 2}])
    finally:
        stop.set()
        t.join()
    assert not errs, errs[:3]
