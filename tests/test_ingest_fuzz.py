"""Randomized OTLP payload parity for the fused ingest fast paths.

The round-5 C++ kernels (`spanmetrics_resolve`, `spanmetrics_from_recs`)
bypass SpanBatch staging entirely; their contract is BIT-IDENTICAL series
state vs the full staging path for any valid payload, and a clean bail
(None → full path) for the shapes they don't own (non-string
service.name). This fuzzer generates adversarial payloads — empty/unicode
span names, short trace ids, absent resources, absent service.name,
numeric service.name (the fixup case), duplicate attr keys, zero/reversed
timestamps, many resources — and asserts the parity triangle:

    full staging == staged fast path == tee from-recs path

plus malformed-bytes rejection. Seed pinnable via TEMPO_FUZZ_SEED.
"""

from __future__ import annotations

import os
import random

import numpy as np
import pytest

SEED = int(os.environ.get("TEMPO_FUZZ_SEED",
                          random.SystemRandom().randrange(1 << 30)))
N_CASES = int(os.environ.get("TEMPO_FUZZ_CASES", 25))


def _payload(rng: random.Random) -> bytes:
    """One random ExportTraceServiceRequest."""
    from tempo_tpu.model.proto_wire import (enc_field_bytes, enc_field_msg,
                                            enc_field_str, enc_field_varint)

    def attr(k: str, v) -> bytes:
        if isinstance(v, bool):
            av = enc_field_varint(2, int(v))
        elif isinstance(v, int):
            av = enc_field_varint(3, v)
        elif isinstance(v, float):
            from tempo_tpu.model.proto_wire import enc_field_double
            av = enc_field_double(4, v)
        else:
            av = enc_field_str(1, str(v))
        return enc_field_str(1, k) + enc_field_msg(2, av)

    out = []
    for _r in range(rng.randint(1, 5)):
        res_attrs = b""
        svc_kind = rng.choice(["str", "none", "absent_res", "dup",
                               "numeric"])
        if svc_kind == "str":
            res_attrs += enc_field_msg(1, attr(
                "service.name", f"svc-{rng.randrange(3)}"))
        elif svc_kind == "numeric":
            # non-string service.name: the fast path must BAIL to the
            # Python stringify fixup (the fallback branch below)
            res_attrs += enc_field_msg(1, attr(
                "service.name", rng.choice([7, 2.5, True])))
        elif svc_kind == "dup":
            # duplicate service.name: LAST occurrence wins
            res_attrs += enc_field_msg(1, attr("service.name", "loser"))
            res_attrs += enc_field_msg(1, attr(
                "service.name", f"svc-{rng.randrange(3)}"))
        if rng.random() < 0.5:
            res_attrs += enc_field_msg(1, attr(
                "deployment.env", rng.choice(["prod", "dev", 7, 2.5, True])))
        spans = []
        for _s in range(rng.randint(0, 40)):
            t0 = rng.randrange(10**18, 10**18 + 10**12)
            t1 = t0 + rng.choice([0, 1, 10**6, 10**9, -5])   # incl. reversed
            name = rng.choice(["", "op", "op-1", "längere-ops-µ", "x" * 300])
            b = (enc_field_bytes(1, rng.randbytes(rng.choice([16, 16, 8, 1])))
                 + enc_field_bytes(2, rng.randbytes(8))
                 + enc_field_str(5, name)
                 + enc_field_varint(6, rng.randrange(0, 8))   # incl. OOB kind
                 + enc_field_varint(7, t0)
                 + enc_field_varint(8, t1)
                 + enc_field_msg(15, enc_field_varint(3, rng.randrange(0, 4))))
            for _a in range(rng.randint(0, 3)):
                b += enc_field_msg(9, attr(
                    rng.choice(["k1", "k2", "http.url"]),
                    rng.choice([1, "v", 2.5, True, -7])))
            spans.append(enc_field_msg(2, b))
        rs = b""
        if svc_kind != "absent_res":
            rs += enc_field_msg(1, res_attrs)
        rs += enc_field_msg(2, b"".join(spans))
        out.append(enc_field_msg(1, rs))
    return b"".join(out)


def _mk_gen():
    from tempo_tpu.generator.generator import Generator
    from tempo_tpu.generator.instance import GeneratorConfig
    from tempo_tpu.overrides import Overrides

    cfg = GeneratorConfig(processors=("span-metrics",))
    cfg.registry.disable_collection = True
    cfg.ingestion_time_range_slack_s = 0     # keep every timestamp shape
    return Generator(cfg, overrides=Overrides())


def _samples(gen):
    # EXACT values: the fast paths' contract is bit-identical state
    return sorted((s.name, s.labels, s.value)
                  for s in gen.instance("t").registry.collect(10_000))


def test_fuzz_fast_paths_match_full_staging():
    from tempo_tpu import native

    rng = random.Random(SEED)
    fast, slow, tee = _mk_gen(), _mk_gen(), _mk_gen()
    slow.instance("t").push_otlp_staged = lambda *a, **k: None
    n_fast = n_fallback = 0
    for case in range(N_CASES):
        payload = _payload(rng)
        ctx = f"seed={SEED} case={case}"
        inst = fast.instance("t")
        took_fast = inst.push_otlp_staged(payload) is not None
        if not took_fast:
            fast.push_otlp("t", payload)     # numeric-service fixup path
            n_fallback += 1
        else:
            n_fast += 1
        slow.push_otlp("t", payload)
        # tee route: scan records + original payload
        recs = native.otlp_scan(payload)
        if recs is None:
            pytest.skip("native layer unavailable")
        if tee.push_otlp_recs("t", payload, recs) is None:
            tee.push_otlp("t", payload)
        assert _samples(fast) == _samples(slow), f"{ctx}: fast != full"
        assert _samples(tee) == _samples(slow), f"{ctx}: tee != full"
    # the generator really exercised BOTH routes across the fuzz corpus
    assert n_fast > 0, f"seed={SEED}: fast path never engaged"
    assert n_fallback > 0, \
        f"seed={SEED}: the non-string service.name fixup never exercised"


def test_fuzz_malformed_payloads_rejected():
    rng = random.Random(SEED + 7)
    gen = _mk_gen()
    base = _payload(rng)
    for case in range(20):
        bad = bytearray(base[:rng.randrange(1, len(base))])
        if bad and rng.random() < 0.7:
            bad[rng.randrange(len(bad))] ^= 0xFF
        try:
            gen.push_otlp("t", bytes(bad))
        except ValueError:
            pass                      # MalformedPayload — the right answer
        except Exception as e:        # anything else is a crash bug
            raise AssertionError(
                f"seed={SEED} case={case}: {type(e).__name__}: {e}") from e
