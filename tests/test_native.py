"""Native C++ layer: token hashing and OTLP wire scan vs python refs."""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu import native
from tempo_tpu.model.otlp import spans_from_otlp_proto
from tempo_tpu.ops import hashing

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native build unavailable")


def test_fnv_tokens_match_numpy():
    rng = np.random.default_rng(0)
    tids = rng.integers(0, 256, (100, 16), dtype=np.uint8)
    a = native.token_for("tenant-x", tids)
    b = hashing.token_for("tenant-x", tids)
    np.testing.assert_array_equal(a, b)


def _sample_proto() -> bytes:
    from tempo_tpu.model import proto_wire as pw

    def anyval_str(s):
        return pw.enc_field_str(1, s)

    def kv(k, v_msg):
        return pw.enc_field_str(1, k) + pw.enc_field_msg(2, v_msg)

    def span(tid, sid, name, start, end, kind=2, code=2, msg="boom",
             attrs=()):
        b = pw.enc_field_bytes(1, tid) + pw.enc_field_bytes(2, sid)
        b += pw.enc_field_str(5, name)
        b += pw.enc_field_varint(6, kind)
        b += pw.enc_field_varint(7, start) + pw.enc_field_varint(8, end)
        for k, v in attrs:
            b += pw.enc_field_msg(9, kv(k, anyval_str(v)))
        b += pw.enc_field_msg(15, pw.enc_field_str(2, msg)
                              + pw.enc_field_varint(3, code))
        return b

    # ResourceSpans.resource → Resource{attributes: [KeyValue]}
    resource = pw.enc_field_msg(
        1, pw.enc_field_msg(1, kv("service.name", anyval_str("svc-a"))))
    spans = b"".join(
        pw.enc_field_msg(2, span(bytes([i]) * 16, bytes([i]) * 8, f"op-{i}",
                                 10 ** 18 + i, 10 ** 18 + i + 1000,
                                 attrs=(("http.path", f"/p{i}"),)))
        for i in range(1, 6))
    scope_spans = pw.enc_field_msg(2, spans)
    return pw.enc_field_msg(1, resource + scope_spans)


def test_otlp_scan_matches_python_decoder():
    data = _sample_proto()
    nat = native.spans_from_otlp_proto_native(data)
    ref = list(spans_from_otlp_proto(data))
    assert nat is not None and len(nat) == len(ref) == 5
    for a, b in zip(nat, ref):
        for k in ("trace_id", "span_id", "name", "service", "kind",
                  "status_code", "status_message", "start_unix_nano",
                  "end_unix_nano", "attrs", "res_attrs"):
            assert a[k] == b[k], (k, a[k], b[k])


def test_otlp_scan_malformed_raises():
    with pytest.raises(ValueError):
        native.otlp_scan(b"\x0a\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff")


def test_otlp_scan_grows_capacity():
    """>16 spans with cap_hint=1 (clamped to 16) forces the re-scan/grow
    branch for both the span and attr buffers."""
    from tempo_tpu.model import proto_wire as pw
    spans = b"".join(
        pw.enc_field_msg(2,
            pw.enc_field_bytes(1, bytes([i]) * 16)
            + pw.enc_field_bytes(2, bytes([i]) * 8)
            + pw.enc_field_str(5, f"s{i}")
            + pw.enc_field_msg(9, pw.enc_field_str(1, "k")
                               + pw.enc_field_msg(2, pw.enc_field_str(1, "v"))))
        for i in range(1, 41))
    data = pw.enc_field_msg(1, pw.enc_field_msg(2, spans))
    recs = native.otlp_scan(data, cap_hint=1)
    assert len(recs) == 40
    recs2, attrs = native.otlp_scan2(data, cap_hint=1)
    assert len(recs2) == 40 and len(attrs) == 40


def test_missing_trace_id_matches_python_contract():
    """A span without a trace id must decode to b'' so the distributor's
    invalid-id validation fires identically on both paths."""
    from tempo_tpu.model import proto_wire as pw
    span = pw.enc_field_bytes(2, b"\x01" * 8) + pw.enc_field_str(5, "x")
    data = pw.enc_field_msg(1, pw.enc_field_msg(2, pw.enc_field_msg(2, span)))
    nat = native.spans_from_otlp_proto_native(data)
    ref = list(spans_from_otlp_proto(data))
    assert nat[0]["trace_id"] == ref[0]["trace_id"] == b""


def test_resource_after_spans_field_order():
    """Resource serialized after ScopeSpans is legal wire order; both
    decoders must attribute the service correctly."""
    from tempo_tpu.model import proto_wire as pw

    def kv(k, v):
        return pw.enc_field_str(1, k) + pw.enc_field_msg(2, pw.enc_field_str(1, v))

    span = (pw.enc_field_bytes(1, b"\x05" * 16) + pw.enc_field_bytes(2, b"\x01" * 8)
            + pw.enc_field_str(5, "x"))
    scope_spans = pw.enc_field_msg(2, pw.enc_field_msg(2, span))
    resource = pw.enc_field_msg(1, pw.enc_field_msg(1, kv("service.name", "late")))
    data = pw.enc_field_msg(1, scope_spans + resource)  # spans FIRST
    nat = native.spans_from_otlp_proto_native(data)
    ref = list(spans_from_otlp_proto(data))
    assert nat[0]["service"] == ref[0]["service"] == "late"


def test_large_int_attr_exact():
    from tempo_tpu.model import proto_wire as pw
    big = (1 << 53) + 1
    attr = (pw.enc_field_str(1, "n")
            + pw.enc_field_msg(2, pw.enc_field_varint(3, big)))
    span = (pw.enc_field_bytes(1, b"\x06" * 16) + pw.enc_field_bytes(2, b"\x01" * 8)
            + pw.enc_field_msg(9, attr))
    data = pw.enc_field_msg(1, pw.enc_field_msg(2, pw.enc_field_msg(2, span)))
    nat = native.spans_from_otlp_proto_native(data)
    assert nat[0]["attrs"]["n"] == big  # exact, no double round-trip


def test_group_keys_matches_numpy_grouping():
    """Native hash grouping must partition identically to np.unique over
    void views (group ids may differ — first-occurrence vs sorted order —
    but the induced partition and first-row sets must match)."""
    from tempo_tpu import native

    rng = np.random.default_rng(5)
    keys = rng.integers(0, 4, size=(2000, 17)).astype(np.uint8)
    first, inverse = native.group_keys(keys)
    void = np.ascontiguousarray(keys).view([("v", "V17")]).ravel()
    _, f2, inv2 = np.unique(void, return_index=True, return_inverse=True)
    assert len(first) == len(f2)
    # bijection between label spaces
    fwd: dict = {}
    for a, b in zip(inverse.tolist(), inv2.tolist()):
        assert fwd.setdefault(a, b) == b
    # each group's first row really is its earliest occurrence
    for g, fi in enumerate(first.tolist()):
        rows = np.flatnonzero(inverse == g)
        assert rows[0] == fi


def test_otlp_scan_mt_matches_sequential(monkeypatch):
    """The threaded scan must produce byte-identical records in the same
    order as the sequential scan, and reject malformed payloads."""
    from tempo_tpu import native

    if not native.available():
        pytest.skip("native layer unavailable")
    import bench as B

    payload = B._make_otlp_payload(8192, n_services=13)
    monkeypatch.setattr(native, "_SCAN_MT_BYTES", 1)      # force MT
    mt = native.otlp_scan(payload)
    monkeypatch.setattr(native, "_SCAN_MT_BYTES", 1 << 60)  # force seq
    seq = native.otlp_scan(payload)
    assert len(mt) == len(seq) == 8192
    assert (mt == seq).all()
    monkeypatch.setattr(native, "_SCAN_MT_BYTES", 1)
    with pytest.raises(ValueError):
        native.otlp_scan(payload[:-3])


def test_otlp_stage_mt_matches_serial(monkeypatch):
    """Parallel staging (skip-attrs shape) must emit the same records in
    the same order as the serial stage — intern ids may differ between
    interners, so string CONTENT is compared."""
    from tempo_tpu.model.interner import StringInterner

    if not native.available():
        pytest.skip("native layer unavailable")
    import bench as B

    payload = B._make_otlp_payload(8192, n_services=13)
    it_mt, it_s = StringInterner(), StringInterner()
    monkeypatch.setattr(native, "_SCAN_MT_BYTES", 1)
    monkeypatch.setattr(native, "_SCAN_THREADS", 4)   # force MT even on 1 cpu
    a = native.otlp_stage(it_mt.native_handle(), payload,
                          skip_span_attrs=True)
    monkeypatch.setattr(native, "_SCAN_MT_BYTES", 1 << 60)
    b = native.otlp_stage(it_s.native_handle(), payload,
                          skip_span_attrs=True)
    it_mt.sync(); it_s.sync()
    sa, sb = a[0], b[0]
    assert len(sa) == len(sb) == 8192
    for col in ("trace_id", "span_id", "start_ns", "end_ns", "kind",
                "status_code", "res_idx", "span_len"):
        assert (sa[col] == sb[col]).all(), col
    na = [it_mt.lookup(int(i)) for i in sa["name_id"]]
    nb = [it_s.lookup(int(i)) for i in sb["name_id"]]
    assert na == nb
    va = [it_mt.lookup(int(i)) for i in sa["service_id"]]
    vb = [it_s.lookup(int(i)) for i in sb["service_id"]]
    assert va == vb
    # malformed rejection on the mt path too
    monkeypatch.setattr(native, "_SCAN_MT_BYTES", 1)
    with pytest.raises(ValueError):
        native.otlp_stage(it_mt.native_handle(), payload[:-5],
                          skip_span_attrs=True)
