"""Frontend search-response caching + multi-tenant query federation.

Round-4 items 4 and 5 (VERDICT): sub-request results cached per
(block id, query hash, shard) with no invalidation — blocks are immutable
(`modules/frontend/frontend.go:101`, `cache_keys.go`) — and
`X-Scope-OrgID: a|b` reads fanning out per tenant and merging through the
same combiners (`frontend.go:113-136` multiTenantMiddleware; metrics
endpoints reject multi-tenant like newMultiTenantUnsupportedMiddleware).
"""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.cache import CacheProvider
from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.db.tempodb import TempoDB
from tempo_tpu.frontend import Frontend, FrontendConfig
from tempo_tpu.frontend.frontend import split_tenants
from tempo_tpu.frontend.slos import SLOConfig
from tempo_tpu.querier import Querier
from tempo_tpu.querier.querier import QuerierConfig
from tempo_tpu.ring import Ring

T0 = 1_700_000_000.0


def mkspan(tid, sid, name="op", svc="svc", t0_s=T0, dur_ms=50, **kw):
    t0 = int(t0_s * 1e9)
    return {"trace_id": tid, "span_id": sid, "name": name, "service": svc,
            "start_unix_nano": t0, "end_unix_nano": t0 + int(dur_ms * 1e6),
            **kw}


class CountingQuerier(Querier):
    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.search_block_calls = 0
        self.query_range_calls = 0

    def search_block(self, *a, **kw):
        self.search_block_calls += 1
        return super().search_block(*a, **kw)

    def query_range_block(self, *a, **kw):
        self.query_range_calls += 1
        return super().query_range_block(*a, **kw)


@pytest.fixture
def rig():
    clock = [T0 + 7200.0]
    now = lambda: clock[0]
    be = MemBackend()
    db = TempoDB(be, be)
    for base, (tenant, svc) in enumerate(
            (("acme", "acme-svc"), ("globex", "globex-svc"))):
        traces = []
        for i in range(1, 9):
            tid = bytes([base * 100 + i]) * 16
            traces.append((tid, [mkspan(tid, bytes([i]) * 8, svc=svc,
                                        t0_s=T0 + i)]))
        # one trace id shared across BOTH tenants (find_trace federation)
        shared = bytes([250]) * 16
        traces.append((shared, [mkspan(shared, bytes([base + 1]) * 8,
                                       svc=svc, t0_s=T0)]))
        db.write_block(tenant, traces, replication_factor=1)
    db.poll_now()
    ring = Ring(replication_factor=1, now=now)
    q = CountingQuerier(db, ring, {}, cfg=QuerierConfig(rf=1))
    fe = Frontend(db, q, cfg=FrontendConfig(
        target_bytes_per_job=1,
        slo={"search": SLOConfig(duration_slo_s=60.0)}),
        cache_provider=CacheProvider(), now=now)
    return clock, now, db, q, fe


def test_split_tenants():
    assert split_tenants("a") == ["a"]
    assert split_tenants("a|b") == ["a", "b"]
    assert split_tenants(" a | b |a|") == ["a", "b"]


def test_repeated_search_hits_cache(rig):
    clock, now, db, q, fe = rig
    res1 = fe.search("acme", '{ resource.service.name = "acme-svc" }',
                     limit=50, start_s=0, end_s=now())
    first_jobs = q.search_block_calls
    assert first_jobs > 0 and len(res1) == 9   # 8 distinct + the shared id
    res2 = fe.search("acme", '{ resource.service.name = "acme-svc" }',
                     limit=50, start_s=0, end_s=now())
    assert q.search_block_calls == first_jobs       # zero new block scans
    assert fe.cache_stats["hits"] >= first_jobs
    assert fe.cache_hit_ratio() > 0
    assert sorted(m.trace_id for m in res1) == \
        sorted(m.trace_id for m in res2)


def test_search_cache_key_includes_query(rig):
    clock, now, db, q, fe = rig
    fe.search("acme", '{ }', limit=50, start_s=0, end_s=now())
    jobs1 = q.search_block_calls
    fe.search("acme", '{ name = "op" }', limit=50, start_s=0, end_s=now())
    assert q.search_block_calls > jobs1             # different query → miss


def test_repeated_query_range_hits_cache(rig):
    clock, now, db, q, fe = rig
    kw = dict(start_s=T0, end_s=T0 + 60, step_s=10.0)
    s1 = fe.query_range("acme", '{ } | rate() by (name)', **kw)
    first = q.query_range_calls
    assert first > 0
    s2 = fe.query_range("acme", '{ } | rate() by (name)', **kw)
    assert q.query_range_calls == first
    a = {s.labels: s.samples.tolist() for s in s1}
    b = {s.labels: s.samples.tolist() for s in s2}
    assert a == b


def test_multi_tenant_search_federates(rig):
    clock, now, db, q, fe = rig
    res = fe.search("acme|globex", "{ }", limit=50, start_s=0, end_s=now())
    svcs = {m.root_service_name for m in res}
    assert svcs == {"acme-svc", "globex-svc"}
    assert len(res) == 17                  # 8 + 8 distinct + 1 shared id


def test_multi_tenant_find_trace_merges(rig):
    clock, now, db, q, fe = rig
    spans = fe.find_trace("acme|globex", bytes([250]) * 16)
    assert spans is not None
    svcs = {s.get("service") for s in spans}
    assert svcs == {"acme-svc", "globex-svc"}       # both tenants' spans


def test_multi_tenant_tags_merge(rig):
    clock, now, db, q, fe = rig
    vals = fe.tag_values("acme|globex", "resource.service.name")
    got = {v["value"] for v in vals}
    assert {"acme-svc", "globex-svc"} <= got


def test_multi_tenant_metrics_rejected(rig):
    clock, now, db, q, fe = rig
    with pytest.raises(ValueError, match="multi-tenant"):
        fe.query_range("acme|globex", "{ } | rate()",
                       start_s=T0, end_s=T0 + 60, step_s=10.0)


def test_cache_engages_on_worker_dispatch_path(rig):
    """Cache consult happens BEFORE dispatch, so the scaled-out worker
    path (not just inline execution) skips cached sub-requests."""
    clock, now, db, q, fe = rig
    fe.start_workers(2)
    try:
        fe.search("acme", '{ name = "op" }', limit=50, start_s=0,
                  end_s=now())
        first = q.search_block_calls
        assert first > 0
        fe.search("acme", '{ name = "op" }', limit=50, start_s=0,
                  end_s=now())
        assert q.search_block_calls == first
        assert fe.cache_stats["hits"] >= first
    finally:
        fe.shutdown()
