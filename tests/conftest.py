"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip sharding paths (tempo_tpu.parallel) are exercised without TPU
hardware via xla_force_host_platform_device_count, mirroring how the
reference tests multi-node behavior with in-memory fakes (SURVEY.md §4.2).
Must run before the first `import jax` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # tests never touch the real chip
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (after env setup, before any test imports)

# The axon sitecustomize hook registers the TPU platform and sets
# jax_platforms="axon,cpu" at interpreter start, which overrides the env
# var — and a wedged TPU tunnel then hangs every backend init. Explicitly
# pin the config so tests are CPU-only no matter what the hook did.
jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: XLA:CPU compiles cost ~1s each and dominate the
# suite; cache them across runs.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_device_scheduler():
    """The device scheduler (tempo_tpu.sched) is process-wide state that
    App construction configures; drop it between tests so standalone
    processors (which assert on device state right after a push) never
    inherit async dispatch from an earlier App-based test."""
    yield
    from tempo_tpu import sched

    sched.reset()
