"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip sharding paths (tempo_tpu.parallel) are exercised without TPU
hardware via xla_force_host_platform_device_count, mirroring how the
reference tests multi-node behavior with in-memory fakes (SURVEY.md §4.2).
Must run before the first `import jax` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # tests never touch the real chip
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (after env setup, before any test imports)

# The axon sitecustomize hook registers the TPU platform and sets
# jax_platforms="axon,cpu" at interpreter start, which overrides the env
# var — and a wedged TPU tunnel then hangs every backend init. Explicitly
# pin the config so tests are CPU-only no matter what the hook did.
jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: XLA:CPU compiles cost ~1s each and dominate the
# suite; cache them across runs.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_device_scheduler():
    """The device scheduler (tempo_tpu.sched) is process-wide state that
    App construction configures; drop it between tests so standalone
    processors (which assert on device state right after a push) never
    inherit async dispatch from an earlier App-based test."""
    yield
    from tempo_tpu import sched

    sched.reset()
    # the device page pool is process-wide the same way: an App-based
    # test leaving it configured would silently page every later test's
    # registries
    from tempo_tpu.registry import pages

    pages.reset()
    # the pallas kernel-tier fallback warns ONCE per process per reason
    # (the contract test_pallas_kernels.py::test_cpu_fallback_single_warning
    # enforces); re-arm it so every test observes its own first warning
    from tempo_tpu.ops import pages as ops_pages

    ops_pages.reset_kernel_warnings()
    # the TraceQL quantile query tier follows the spanmetrics sketch
    # config at App build; reset so a moments-tier App doesn't leak
    # moment grids into later tests' evaluators
    from tempo_tpu.ops import moments

    moments.set_query_tier("log2")
    # the materialized-view tier is process-wide the same way: an
    # App-based test leaving it configured would silently stream every
    # later test's generator pushes into stale grids (and serve its
    # frontend reads from them)
    from tempo_tpu import matview

    matview.reset()
    # the fault-injection registry is process-wide and module-flag
    # gated; a test (or an App built with faults armed) must never
    # leak injected failures into later tests
    from tempo_tpu.utils import faults

    faults.reset()
    # the installed self-tracer is process-wide; a test that installs a
    # SelfTracer (loopback App, propagation tests) must never leave it
    # live — later tests would emit spans into a dead sink and trip the
    # suppression/reserved-tenant guards in surprising places
    from tempo_tpu.utils import tracing

    tracing.install(tracing.NoopTracer())
    # trace-analytics operational counters and the dataquality orphan
    # tally are process-wide callback-family state (monotonic by
    # design); reset so per-test assertions on late/cycle/orphan counts
    # never see an earlier test's cuts
    from tempo_tpu.generator.processors import traceanalytics
    from tempo_tpu.utils import dataquality

    traceanalytics.reset_counters()
    dataquality.reset_orphan_spans()


# ---------------------------------------------------------------------------
# tier-1 runtime guard
# ---------------------------------------------------------------------------
#
# The tier-1 suite runs under a hard 870s budget (ROADMAP verify line),
# already pressured by the soak/pages/dryrun tests. Every test added
# AFTER this guard landed must keep its call phase under the budget
# below; the modules listed were grandfathered at introduction (their
# wall cost is tracked by the bench accept gates instead). A new test
# file — or any moments-tier test — that exceeds the budget fails the
# whole suite, so slow tests surface in the PR that adds them instead
# of silently eating the shared budget. Opt out (local debugging only)
# with TEMPO_TEST_NO_TIME_GUARD=1.

_RUNTIME_BUDGET_S = 10.0
# explicit, per-test budget exceptions — each must say WHY. The point
# of the guard is surfacing slow tests in the PR that adds them; an
# entry here is that surfacing, not an escape hatch.
_BUDGET_OVERRIDES = {
    # two REAL fleet-worker process boots (~4s of jax+App init each,
    # irreducible) around a SIGKILL: the ingest-WAL crash-recovery
    # contract cannot be exercised in-process
    "tests/test_fleet.py::test_sigkill_restart_replays_wal_bit_identically":
        25.0,
    # compiles the structure kernel at three EXTRA pad shapes on purpose
    # (the invariance under test is exactly that recompilation at a new
    # pow-2 pad cannot change results); ~5s of XLA compile per shape
    "tests/test_traceanalytics.py::test_structure_padding_invariance":
        30.0,
}
_GRANDFATHERED_MODULES = frozenset({
    "test_app.py", "test_aux.py", "test_backend.py",
    "test_bench_orchestration.py", "test_block.py", "test_cli.py",
    "test_db.py", "test_device_scan.py", "test_devtime.py",
    "test_engine.py", "test_frontend_features.py", "test_generator.py",
    "test_grpc.py", "test_ingest_bus.py", "test_ingest_fuzz.py",
    "test_ingest_pipeline.py", "test_localblocks.py",
    "test_mesh_serving.py", "test_microservices.py", "test_model.py",
    "test_multichip_dryrun.py", "test_native.py", "test_obs.py",
    "test_otlp_batch.py", "test_overload_smoke.py", "test_pages.py",
    "test_pallas_kernels.py", "test_parallel.py", "test_plane_arith.py",
    "test_plane_fuzz.py", "test_query_stats.py", "test_read_path.py",
    "test_read_plane.py", "test_registry.py", "test_ring.py",
    "test_sampling.py", "test_sched.py", "test_sketches.py",
    "test_traceql.py", "test_write_path.py",
})
_runtime_offenders: list = []


def pytest_runtest_logreport(report):
    if report.when != "call" or os.environ.get("TEMPO_TEST_NO_TIME_GUARD"):
        return
    module = report.nodeid.split("::", 1)[0].rsplit("/", 1)[-1]
    guarded = module not in _GRANDFATHERED_MODULES \
        or "moments" in report.nodeid
    budget = _BUDGET_OVERRIDES.get(report.nodeid.split("[", 1)[0],
                                   _RUNTIME_BUDGET_S)
    if guarded and report.duration > budget:
        _runtime_offenders.append((report.nodeid, report.duration))


def pytest_terminal_summary(terminalreporter):
    if _runtime_offenders:
        terminalreporter.section("tier-1 runtime guard")
        for nodeid, dur in _runtime_offenders:
            terminalreporter.write_line(
                f"FAILED budget: {nodeid} took {dur:.1f}s "
                f"(> {_RUNTIME_BUDGET_S:.0f}s per new test — the 870s "
                "tier-1 budget is shared; mark it slow or shrink it)")


def pytest_sessionfinish(session, exitstatus):
    if _runtime_offenders and session.exitstatus == 0:
        session.exitstatus = 1


# ---------------------------------------------------------------------------
# fleet child processes — spawned AND reliably reaped (no orphans on
# test failure; every fleet test stays under the 10s tier-1 guard)
# ---------------------------------------------------------------------------


@pytest.fixture
def fleet_procs():
    """Factory spawning `python -m tempo_tpu.fleet.worker ...` children.

    `spawn(args, env=...)` blocks until the worker prints its JSON ready
    line (or dies — surfaced with its stderr tail) and returns the
    Popen with `.ready` (the parsed line) attached. EVERY spawned child
    is reaped on teardown regardless of test outcome: SIGTERM, bounded
    wait, SIGKILL fallback — a failing test must not leak generator
    processes into the rest of the suite. The lifecycle itself lives in
    `tempo_tpu.fleet.worker.{spawn_worker,reap_workers}`, shared with
    bench.py."""
    from tempo_tpu.fleet.worker import reap_workers, spawn_worker

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs: list = []

    def spawn(args, env=None, wait_ready_s=60.0):
        e = dict(env or {})
        e.setdefault("JAX_PLATFORMS", "cpu")
        p = spawn_worker(args, env=e, wait_ready_s=wait_ready_s,
                         cwd=repo_root)
        procs.append(p)
        return p

    yield spawn

    reap_workers(procs, term_wait_s=8.0)


# ---------------------------------------------------------------------------
# fault injection — shared overload / retry-storm test helpers
# ---------------------------------------------------------------------------


def make_pressure_scheduler(pressure: float = 0.0, cfg=None):
    """A real DeviceScheduler whose live-ingest queue FILL is forced to
    `pressure` (0..1+): the keep-fraction controller, IngestBackpressure,
    and /status all read the injected value through the normal depth()
    surface, so overload tests exercise the genuine escalation path
    (full stream → sampled → 429) without racing a worker thread.
    Mutate `.forced_pressure` to ramp. Worker is NOT started."""
    from tempo_tpu.sched import DeviceScheduler, PRIO_INGEST, SchedConfig

    class _PressureScheduler(DeviceScheduler):
        def __init__(self):
            # pipeline_depth=0: the decode-ahead ring bounds in-flight
            # jobs and there is NO worker here to land them — a third
            # push would block in pipeline.acquire for its full timeout.
            # smoothing 0: tests assert on the raw control law.
            super().__init__(
                cfg or SchedConfig(sampling_smoothing_s=0.0,
                                   pipeline_depth=0),
                start_worker=False)
            self.forced_pressure = pressure

        def depth(self, prio):
            if prio == PRIO_INGEST:
                return int(round(self.forced_pressure * self._limit(prio)))
            return super().depth(prio)

    return _PressureScheduler()


@pytest.fixture
def forced_sched_saturation():
    """Factory fixture: install a forced-pressure scheduler as THE
    process scheduler for the test. `arm(pressure)` returns it; ramp by
    assigning `.forced_pressure`. Uninstalled on teardown."""
    from tempo_tpu import sched

    cms = []

    def arm(pressure: float = 1.0, cfg=None):
        sc = make_pressure_scheduler(pressure, cfg)
        cm = sched.use(sc)
        cm.__enter__()
        cms.append(cm)
        return sc

    yield arm
    for cm in reversed(cms):
        cm.__exit__(None, None, None)


@pytest.fixture
def faulty_remote_write():
    """A loopback HTTP endpoint with a scripted response sequence —
    the failing / Retry-After-emitting remote-write backend. Append
    `(status, headers)` tuples to `.script` (empty script → 200);
    received requests accumulate in `.requests`."""
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            srv = self.server
            n = int(self.headers.get("Content-Length", 0) or 0)
            body = self.rfile.read(n)
            srv.requests.append({"path": self.path, "n_bytes": len(body),
                                 "headers": dict(self.headers)})
            status, headers = (srv.script.pop(0) if srv.script
                               else (200, {}))
            self.send_response(status)
            for k, v in headers.items():
                self.send_header(k, str(v))
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):     # keep pytest output clean
            pass

    srv = HTTPServer(("127.0.0.1", 0), _Handler)
    srv.script = []
    srv.requests = []
    srv.url = f"http://127.0.0.1:{srv.server_address[1]}/api/v1/push"
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()
    t.join(timeout=2)
