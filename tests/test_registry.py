"""Registry tests — semantics from the reference registry unit tests
(`modules/generator/registry/{counter,gauge,histogram}_test.go`): collection
values, series limits, staleness markers, histogram bucket expansion."""

import math

import numpy as np

from tempo_tpu.registry import ManagedRegistry, RegistryOverrides


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def make_registry(**kw):
    clock = FakeClock()
    reg = ManagedRegistry("t1", RegistryOverrides(**kw), now=clock)
    return reg, clock


def sample_map(samples):
    return {(s.name, s.labels): s.value for s in samples if not s.is_stale_marker}


def test_counter_inc_and_collect():
    reg, _ = make_registry()
    c = reg.new_counter("traces_spanmetrics_calls_total", ("service", "span_name"))
    rows = reg.interner.intern_many(["svc-a", "op1", "svc-a", "op1", "svc-b", "op2"]).reshape(3, 2)
    c.inc_batch(rows)
    c.inc(["svc-a", "op1"], 2.0)
    got = sample_map(reg.collect(ts_ms=5))
    by_svc = {lbls: v for (_, lbls), v in got.items()}
    vals = sorted(by_svc.values())
    assert vals == [1.0, 4.0]
    assert reg.active_series == 2


def test_histogram_buckets_cumulative():
    reg, _ = make_registry()
    h = reg.new_histogram("latency", ("service",), edges=(1.0, 2.0, 4.0))
    rows = reg.interner.intern_many(["a"] * 4).reshape(4, 1)
    h.observe_batch(rows, np.array([0.5, 1.5, 3.0, 100.0], np.float32))
    samples = reg.collect(ts_ms=1)
    m = sample_map(samples)
    count = [v for (n, l), v in m.items() if n == "latency_count"][0]
    total = [v for (n, l), v in m.items() if n == "latency_sum"][0]
    assert count == 4 and abs(total - 105.0) < 1e-3
    les = {dict(l)["le"]: v for (n, l), v in m.items() if n == "latency_bucket"}
    assert les["1"] == 1 and les["2"] == 2 and les["4"] == 3 and les["+Inf"] == 4


def test_le_inclusive_boundary():
    reg, _ = make_registry()
    h = reg.new_histogram("lat", ("s",), edges=(1.0, 2.0))
    rows = reg.interner.intern_many(["x"]).reshape(1, 1)
    h.observe_batch(rows, np.array([2.0], np.float32))  # le="2" must include 2.0
    les = {dict(l)["le"]: v for (n, l), v in sample_map(reg.collect(1)).items()
           if n == "lat_bucket"}
    assert les["2"] == 1 and les["1"] == 0


def test_max_active_series_rejects_new():
    reg, _ = make_registry(max_active_series=2)
    c = reg.new_counter("c", ("k",))
    rows = reg.interner.intern_many(["a", "b", "c", "a"]).reshape(4, 1)
    slots = c.inc_batch(rows)
    assert (slots >= 0).sum() == 3  # a, b allocated; c rejected; second a ok
    assert slots[2] == -1
    assert reg.discarded_series == 1
    vals = sorted(sample_map(reg.collect(1)).values())
    assert vals == [1.0, 2.0]  # "c" never counted


def test_staleness_purge_zeroes_and_marks():
    reg, clock = make_registry(stale_duration_s=10.0)
    c = reg.new_counter("c", ("k",))
    c.inc(["old"], 5.0)
    clock.t += 100.0
    c.inc(["new"], 1.0)
    evicted = reg.purge_stale()
    assert evicted == 1 and reg.active_series == 1
    samples = reg.collect(1)
    markers = [s for s in samples if s.is_stale_marker]
    assert len(markers) == 1 and math.isnan(markers[0].value)
    assert dict(markers[0].labels)["k"] == "old"
    # slot must be reusable with zeroed state
    c.inc(["old2"], 7.0)
    live = sample_map(reg.collect(2))
    assert sorted(live.values()) == [1.0, 7.0]


def test_gauge_last_wins():
    reg, _ = make_registry()
    g = reg.new_gauge("g", ("k",))
    rows = reg.interner.intern_many(["a", "a", "a"]).reshape(3, 1)
    g.set_batch(rows, np.array([1.0, 2.0, 3.0], np.float32))
    assert list(sample_map(reg.collect(1)).values()) == [3.0]


def test_external_labels_and_name_label():
    reg, _ = make_registry(external_labels={"cluster": "eu-1"})
    c = reg.new_counter("c_total", ("k",))
    c.inc(["v"], 1.0)
    (s,) = [s for s in reg.collect(1)]
    d = dict(s.labels)
    assert d["cluster"] == "eu-1" and d["__name__"] == "c_total" and d["k"] == "v"


def test_native_histogram_counts():
    reg, _ = make_registry()
    nh = reg.new_native_histogram("lat", ("svc",))
    rows = reg.interner.intern_many(["a"] * 3).reshape(3, 1)
    nh.observe_batch(rows, np.array([0.0, 1.0, 8.0], np.float32))
    m = sample_map(reg.collect(1))
    assert [v for (n, _), v in m.items() if n == "lat_count"] == [3.0]
    slots, labels, hist, sums, counts, zeros = nh.native_payload()
    assert counts[0] == 3.0 and zeros[0] == 1.0 and sums[0] == 9.0
    # all 3 observations land in log2 buckets; the 0.0 goes to bucket 0
    assert hist[0].sum() == 3.0 and hist[0][0] == 1.0
