"""CLI toolkit, HTTP client, and vulture prober."""

from __future__ import annotations

import socket

import pytest

from tempo_tpu.app import App
from tempo_tpu.app.config import Config
from tempo_tpu.backend.local import LocalBackend
from tempo_tpu.cli.__main__ import main as cli_main
from tempo_tpu.db.tempodb import TempoDB

T0 = 1_700_000_000.0


@pytest.fixture
def block_dir(tmp_path):
    be = LocalBackend(str(tmp_path))
    db = TempoDB(be, be)
    traces = []
    for i in range(1, 11):
        tid = bytes([i]) * 16
        t0 = int((T0 + i) * 1e9)
        traces.append((tid, [{
            "trace_id": tid, "span_id": bytes([i]) * 8, "name": f"op-{i % 2}",
            "service": "svc", "start_unix_nano": t0,
            "end_unix_nano": t0 + 10 ** 6,
            "attrs": {"http.path": f"/page/{i}"}}]))
    meta = db.write_block("t1", traces)
    return str(tmp_path), meta


def test_cli_list_blocks(block_dir, capsys):
    path, meta = block_dir
    assert cli_main(["--path", path, "list", "blocks", "t1"]) == 0
    out = capsys.readouterr().out
    assert meta.block_id in out and "total: 1 blocks, 10 traces" in out
    assert cli_main(["--path", path, "list", "block", "t1", meta.block_id]) == 0
    out = capsys.readouterr().out
    assert '"total_objects": 10' in out and "row group 0" in out
    assert cli_main(["--path", path, "list", "compaction-summary", "t1"]) == 0


def test_cli_query(block_dir, capsys):
    path, meta = block_dir
    tid = (bytes([3]) * 16).hex()
    assert cli_main(["--path", path, "query", "trace", "t1", tid]) == 0
    assert '"op-1"' in capsys.readouterr().out
    assert cli_main(["--path", path, "query", "search", "t1",
                     '{ .http.path = "/page/4" }']) == 0
    out = capsys.readouterr().out
    assert (bytes([4]) * 16).hex() in out
    # missing trace returns nonzero
    assert cli_main(["--path", path, "query", "trace", "t1", "ff" * 16]) == 1


def test_cli_analyse(block_dir, capsys):
    path, meta = block_dir
    assert cli_main(["--path", path, "analyse", "block", "t1",
                     meta.block_id]) == 0
    out = capsys.readouterr().out
    assert "http.path" in out and "dedicated-column candidates" in out


def test_cli_gen_and_rewrite(block_dir, capsys):
    path, meta = block_dir
    assert cli_main(["--path", path, "gen", "bloom", "t1", meta.block_id]) == 0
    assert cli_main(["--path", path, "gen", "index", "t1", meta.block_id]) == 0
    capsys.readouterr()
    # drop trace 5 and verify the rewritten block lost exactly it
    tid = (bytes([5]) * 16).hex()
    assert cli_main(["--path", path, "rewrite", "drop", "t1",
                     meta.block_id, tid]) == 0
    assert "10 -> 9 traces" in capsys.readouterr().out
    be = LocalBackend(path)
    db = TempoDB(be, be)
    db.poll_now()
    live = [m for m in db.blocklist.metas("t1")]
    assert len(live) == 1 and live[0].total_objects == 9
    assert db.find_trace_by_id("t1", bytes([5]) * 16) is None
    assert db.find_trace_by_id("t1", bytes([6]) * 16) is not None


def test_cli_migrate_tenant(block_dir, capsys):
    path, meta = block_dir
    assert cli_main(["--path", path, "migrate", "tenant", "t1", "t2"]) == 0
    be = LocalBackend(path)
    db = TempoDB(be, be)
    db.poll_now()
    assert len(db.blocklist.metas("t2")) == 1
    assert db.find_trace_by_id("t2", bytes([1]) * 16) is not None


def test_vulture_against_live_server(tmp_path):
    from tempo_tpu.app.api import serve
    from tempo_tpu.vulture.__main__ import main as vulture_main

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    cfg = Config()
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.server.http_listen_port = port
    app = App(cfg)
    app.start_loops()
    srv = serve(app, block=False)
    try:
        rc = vulture_main(["--url", f"http://127.0.0.1:{port}",
                           "--cycles", "2", "--interval", "0",
                           "--read-delay", "0", "--seed", "42"])
        assert rc == 0
    finally:
        srv.shutdown()
        app.shutdown()


def test_cli_new_commands(block_dir, capsys):
    path, meta = block_dir
    # analyse blocks (rollup)
    assert cli_main(["--path", path, "analyse", "blocks", "t1"]) == 0
    assert "http.path" in capsys.readouterr().out
    # view pq-schema
    assert cli_main(["--path", path, "view", "pq-schema", "t1",
                     meta.block_id]) == 0
    out = capsys.readouterr().out
    assert "trace_id" in out and "row groups" in out
    # query metrics over the backend block
    assert cli_main(["--path", path, "query", "metrics", "t1",
                     "{ } | count_over_time()",
                     "--start", str(T0), "--end", str(T0 + 60),
                     "--step", "60"]) == 0
    out = capsys.readouterr().out
    assert '"samples"' in out
    # query tags
    assert cli_main(["--path", path, "query", "tags", "t1"]) == 0
    out = capsys.readouterr().out
    assert "http.path" in out        # span-scope key from the block
    # list index (poller wrote the tenant index during poll_now)
    assert cli_main(["--path", path, "list", "index", "t1"]) == 0
    assert meta.block_id in capsys.readouterr().out
    # version
    assert cli_main(["--path", path, "version"]) == 0
    assert "tempo_tpu" in capsys.readouterr().out
    # usage-stats: none written yet -> rc 1; after a report -> rc 0
    assert cli_main(["--path", path, "usage-stats"]) == 1
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.ring.kv import KVStore
    from tempo_tpu.utils.usagestats import UsageReporter
    rep = UsageReporter(KVStore(), LocalBackend(path), instance_id="cli")
    assert rep.report_once()
    assert cli_main(["--path", path, "usage-stats"]) == 0
    assert "clusterID" in capsys.readouterr().out


def test_tempo_query_jaeger_plugin(tmp_path):
    """tempo-query bridge: jaeger.storage.v1 gRPC calls against a live
    tempo_tpu server return api_v2 model spans (cmd/tempo-query analog)."""
    import json
    import time
    import urllib.request

    import grpc

    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.model import proto_wire as pw
    from tempo_tpu.tempoquery import build_tempo_query_server

    def free_port():
        s = socket.socket(); s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]; s.close(); return p

    port = free_port()
    cfg = Config(target="all")
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.server.http_listen_port = port
    app = App(cfg)
    srv = serve(app, block=False)
    qserver = qport = None
    try:
        t0 = int((time.time() - 3) * 1e9)
        otlp = {"resourceSpans": [{"resource": {"attributes": [
            {"key": "service.name", "value": {"stringValue": "jq-svc"}}]},
            "scopeSpans": [{"spans": [{
                "traceId": "fe" * 16, "spanId": "12" * 8, "name": "jq-op",
                "kind": 2, "startTimeUnixNano": str(t0),
                "endTimeUnixNano": str(t0 + 5_000_000),
                "attributes": [{"key": "http.status_code",
                                "value": {"intValue": "500"}}]}]}]}]}
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/traces",
            data=json.dumps(otlp).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10).close()

        qserver, qport = build_tempo_query_server(
            f"http://127.0.0.1:{port}")
        ch = grpc.insecure_channel(f"127.0.0.1:{qport}")

        # GetServices
        body = ch.unary_unary(
            "/jaeger.storage.v1.SpanReaderPlugin/GetServices")(b"")
        services = [bytes(v).decode() for v in
                    pw.decode_fields(body).get(1, [])]
        assert "jq-svc" in services

        # GetOperations
        body = ch.unary_unary(
            "/jaeger.storage.v1.SpanReaderPlugin/GetOperations")(b"")
        ops = [bytes(v).decode() for v in pw.decode_fields(body).get(1, [])]
        assert "jq-op" in ops

        # GetTrace -> api_v2 spans with process + tags
        chunks = list(ch.unary_stream(
            "/jaeger.storage.v1.SpanReaderPlugin/GetTrace")(
            pw.enc_field_bytes(1, bytes.fromhex("fe" * 16))))
        assert len(chunks) == 1
        spans = pw.decode_fields(chunks[0])[1]
        sp = pw.decode_fields(bytes(spans[0]))
        assert bytes(sp[1][0]) == bytes.fromhex("fe" * 16)    # trace_id
        assert bytes(sp[3][0]).decode() == "jq-op"            # operation
        proc = pw.decode_fields(bytes(sp[10][0]))
        assert bytes(proc[1][0]).decode() == "jq-svc"         # service
        tags = {bytes(pw.decode_fields(bytes(t))[1][0]).decode()
                for t in sp.get(8, [])}
        assert "span.kind" in tags and "http.status_code" in tags

        # FindTraces with a service filter
        query = (pw.enc_field_str(1, "jq-svc") +
                 pw.enc_field_varint(8, 10))
        chunks = list(ch.unary_stream(
            "/jaeger.storage.v1.SpanReaderPlugin/FindTraces")(
            pw.enc_field_msg(1, query)))
        assert len(chunks) == 1

        # unknown trace -> NOT_FOUND
        try:
            list(ch.unary_stream(
                "/jaeger.storage.v1.SpanReaderPlugin/GetTrace")(
                pw.enc_field_bytes(1, b"\x00" * 16)))
            raise AssertionError("expected NOT_FOUND")
        except grpc.RpcError as e:
            assert e.code() == grpc.StatusCode.NOT_FOUND
        ch.close()
    finally:
        if qserver is not None:
            qserver.stop(0)
        srv.shutdown()
        app.shutdown()


def test_cli_round4_commands(block_dir, capsys, tmp_path):
    """Round-4 operator commands: column sizes, row dump, attr search,
    wal inventory, compaction dry-run (`cmd-list-column.go`,
    `cmd-search.go`, wal + block-selector inspection)."""
    path, meta = block_dir
    # per-column byte stats
    assert cli_main(["--path", path, "list", "column-sizes", "t1",
                     meta.block_id]) == 0
    out = capsys.readouterr().out
    assert "name" in out and "COMPRESSED" in out and "row groups" in out
    # row dump (limited, JSON lines)
    assert cli_main(["--path", path, "view", "rows", "t1", meta.block_id,
                     "--limit", "3"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 3
    import json as _json
    row = _json.loads(out[0])
    assert row["service"] == "svc" and len(row["traceID"]) == 32
    # attr search
    assert cli_main(["--path", path, "query", "attr", "t1",
                     "http.path", "/page/3"]) == 0
    out = capsys.readouterr().out
    assert "1 traces" in out
    # wal inventory
    from tempo_tpu.block.wal import WALBlock
    wb = WALBlock(str(tmp_path / "wal"), "t1")
    wb.append([{"trace_id": b"\x01" * 16, "span_id": b"\x02" * 8,
                "name": "w", "service": "svc",
                "start_unix_nano": int(T0 * 1e9),
                "end_unix_nano": int(T0 * 1e9) + 1000}])
    assert cli_main(["--path", path, "list", "wal",
                     str(tmp_path / "wal")]) == 0
    out = capsys.readouterr().out
    assert "1 wal blocks, 1 spans" in out
    # compaction dry-run: one block -> nothing to compact; write three
    # more into the same window -> a pending job appears, and NO block
    # disappears (read-only)
    assert cli_main(["--path", path, "compact", "dry-run", "t1"]) == 0
    assert "nothing to compact" in capsys.readouterr().out
    be = LocalBackend(path)
    db = TempoDB(be, be)
    db.poll_now()
    for _ in range(3):
        traces = [(bytes([99]) * 16, [{
            "trace_id": bytes([99]) * 16, "span_id": bytes([9]) * 8,
            "name": "x", "service": "svc",
            "start_unix_nano": int((T0 + 1) * 1e9),
            "end_unix_nano": int((T0 + 1) * 1e9) + 1000}])]
        db.write_block("t1", traces)
    n_before = len(db.blocklist.metas("t1"))
    assert cli_main(["--path", path, "compact", "dry-run", "t1"]) == 0
    out = capsys.readouterr().out
    assert "compaction job(s) pending" in out
    db2 = TempoDB(be, be)
    db2.poll_now()
    assert len(db2.blocklist.metas("t1")) == n_before   # read-only


def test_cli_cachesummary_and_trace_summary(block_dir, capsys):
    """Round-5 additions: `list cachesummary` (bloom bytes by age x level,
    cmd-list-cachesummary.go) and `query trace-summary`
    (cmd-query-trace-summary.go)."""
    path, meta = block_dir
    assert cli_main(["--path", path, "list", "cachesummary", "t1"]) == 0
    out = capsys.readouterr().out
    assert "compaction level" in out and "total bloom bytes:" in out
    # bloom bytes are real object sizes, not zero
    total = int(out.rsplit("total bloom bytes:", 1)[1].strip())
    assert total > 0

    tid = (bytes([3]) * 16).hex()
    assert cli_main(["--path", path, "query", "trace-summary",
                     "t1", tid]) == 0
    out = capsys.readouterr().out
    assert "number of blocks: 1" in out
    assert "span count: 1" in out
    assert "root service name: svc" in out
    assert "op-1" in out                  # root span named
    # unknown trace: rc 1, friendly message
    assert cli_main(["--path", path, "query", "trace-summary",
                     "t1", "ff" * 16]) == 1
    assert "trace not found" in capsys.readouterr().out
