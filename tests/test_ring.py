"""Ring, KV, shuffle sharding, quorum batch, overrides resolution."""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.overrides import Limits, Overrides, UserConfigurableOverrides
from tempo_tpu.ring import (
    ACTIVE,
    InstanceDesc,
    KVStore,
    Lifecycler,
    Ring,
    do_batch,
)
from tempo_tpu.ring.ring import _instance_tokens


def make_ring(n=4, rf=3, now=None):
    r = Ring(replication_factor=rf, now=now or (lambda: 1000.0))
    for i in range(n):
        r.register(InstanceDesc(id=f"ing-{i}", addr=f"host{i}",
                                tokens=_instance_tokens(f"ing-{i}", 64),
                                state=ACTIVE, heartbeat_ts=1000.0))
    return r


def test_replication_set_distinct_and_deterministic():
    r = make_ring(5)
    rs1 = r.get(12345)
    rs2 = r.get(12345)
    assert [i.id for i in rs1.instances] == [i.id for i in rs2.instances]
    assert len(rs1.instances) == 3
    assert len({i.id for i in rs1.instances}) == 3
    assert rs1.max_errors == 1  # rf=3, quorum=2


def test_unhealthy_eats_error_budget():
    clock = [1000.0]
    r = make_ring(4, now=lambda: clock[0])
    rs = r.get(777)
    # age out one replica's heartbeat (others stay within the 60s timeout)
    dead = rs.instances[0].id
    r._instances[dead].heartbeat_ts = 900.0
    clock[0] = 1050.0
    rs2 = r.get(777)
    assert dead not in {i.id for i in rs2.instances}
    assert rs2.max_errors == 0


def test_ownership_single_owner():
    r = make_ring(4)
    owners = [m for m in ("ing-0", "ing-1", "ing-2", "ing-3")
              if r.owns(m, "tenant-a/job-1")]
    assert len(owners) == 1


def test_shuffle_shard_deterministic_subset():
    r = make_ring(10, rf=2)
    s1 = r.shuffle_shard("tenant-a", 3)
    s2 = r.shuffle_shard("tenant-a", 3)
    ids1 = {i.id for i in s1.instances()}
    assert ids1 == {i.id for i in s2.instances()}
    assert len(ids1) == 3
    sb = r.shuffle_shard("tenant-b", 3)
    # different tenants usually land on different shards (not guaranteed, but
    # with 10 choose 3 the collision chance for this seed pair is nil)
    assert {i.id for i in sb.instances()} != ids1


def test_lifecycler_joins_and_leaves_via_kv():
    kv = KVStore()
    ring = Ring(kv=kv, replication_factor=1, now=lambda: 1000.0)
    lc = Lifecycler(kv, "gen-0", n_tokens=32, now=lambda: 1000.0)
    assert len(ring) == 1
    assert ring.get(42).instances[0].id == "gen-0"
    lc.leave()
    assert len(ring) == 0


def test_do_batch_quorum_tolerates_one_failure():
    r = make_ring(5)
    got: dict[str, list] = {}

    def send(inst, items):
        if inst.id == "ing-0":
            raise RuntimeError("down")
        got.setdefault(inst.id, []).extend(items)

    tokens = np.arange(50, dtype=np.uint32) * 77_000_000
    do_batch(r, tokens, list(range(50)), send)
    assert sum(len(v) for v in got.values()) >= 100  # each item at 2+ replicas


def test_do_batch_fails_without_quorum():
    r = make_ring(3)

    def send(inst, items):
        if inst.id in ("ing-0", "ing-1"):
            raise RuntimeError("down")

    with pytest.raises(RuntimeError):
        do_batch(r, np.array([5], np.uint32), ["x"], send)


def test_overrides_layering(tmp_path):
    p = tmp_path / "rc.yaml"
    p.write_text(
        "overrides:\n"
        "  '*':\n"
        "    ingestion: {rate_limit_bytes: 1000}\n"
        "  tenant-a:\n"
        "    ingestion: {rate_limit_bytes: 2000}\n"
        "    generator: {processors: [span-metrics]}\n")
    o = Overrides(runtime_config_path=str(p))
    assert o.for_tenant("tenant-a").ingestion.rate_limit_bytes == 2000
    assert o.for_tenant("tenant-a").generator.processors == ("span-metrics",)
    assert o.for_tenant("other").ingestion.rate_limit_bytes == 1000
    assert o.for_tenant("other").generator.processors == ()
    # mtime-gated reload
    assert o.reload() is False


def test_user_configurable_overrides_api_and_validation():
    be = MemBackend()
    uc = UserConfigurableOverrides(be, be)
    o = Overrides(user_configurable=uc)
    v1 = uc.set("t1", {"generator": {"collection_interval_s": 30.0}})
    assert o.for_tenant("t1").generator.collection_interval_s == 30.0
    # version conflict
    with pytest.raises(RuntimeError):
        uc.set("t1", {"generator": {"collection_interval_s": 60.0}}, version="99")
    uc.set("t1", {"generator": {"collection_interval_s": 60.0}}, version=v1)
    assert o.for_tenant("t1").generator.collection_interval_s == 60.0
    # non-user-configurable field rejected
    with pytest.raises(ValueError):
        uc.set("t1", {"ingestion": {"rate_limit_bytes": 1}})
    uc.delete("t1")
    assert o.for_tenant("t1").generator.collection_interval_s == \
        Limits().generator.collection_interval_s
