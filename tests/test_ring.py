"""Ring, KV, shuffle sharding, quorum batch, overrides resolution."""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.overrides import Limits, Overrides, UserConfigurableOverrides
from tempo_tpu.ring import (
    ACTIVE,
    InstanceDesc,
    KVStore,
    Lifecycler,
    Ring,
    do_batch,
)
from tempo_tpu.ring.ring import _instance_tokens


def make_ring(n=4, rf=3, now=None):
    r = Ring(replication_factor=rf, now=now or (lambda: 1000.0))
    for i in range(n):
        r.register(InstanceDesc(id=f"ing-{i}", addr=f"host{i}",
                                tokens=_instance_tokens(f"ing-{i}", 64),
                                state=ACTIVE, heartbeat_ts=1000.0))
    return r


def test_replication_set_distinct_and_deterministic():
    r = make_ring(5)
    rs1 = r.get(12345)
    rs2 = r.get(12345)
    assert [i.id for i in rs1.instances] == [i.id for i in rs2.instances]
    assert len(rs1.instances) == 3
    assert len({i.id for i in rs1.instances}) == 3
    assert rs1.max_errors == 1  # rf=3, quorum=2


def test_unhealthy_eats_error_budget():
    clock = [1000.0]
    r = make_ring(4, now=lambda: clock[0])
    rs = r.get(777)
    # age out one replica's heartbeat (others stay within the 60s timeout)
    dead = rs.instances[0].id
    r._instances[dead].heartbeat_ts = 900.0
    clock[0] = 1050.0
    rs2 = r.get(777)
    assert dead not in {i.id for i in rs2.instances}
    assert rs2.max_errors == 0


def test_ownership_single_owner():
    r = make_ring(4)
    owners = [m for m in ("ing-0", "ing-1", "ing-2", "ing-3")
              if r.owns(m, "tenant-a/job-1")]
    assert len(owners) == 1


def test_shuffle_shard_deterministic_subset():
    r = make_ring(10, rf=2)
    s1 = r.shuffle_shard("tenant-a", 3)
    s2 = r.shuffle_shard("tenant-a", 3)
    ids1 = {i.id for i in s1.instances()}
    assert ids1 == {i.id for i in s2.instances()}
    assert len(ids1) == 3
    sb = r.shuffle_shard("tenant-b", 3)
    # different tenants usually land on different shards (not guaranteed, but
    # with 10 choose 3 the collision chance for this seed pair is nil)
    assert {i.id for i in sb.instances()} != ids1


def test_lifecycler_joins_and_leaves_via_kv():
    kv = KVStore()
    ring = Ring(kv=kv, replication_factor=1, now=lambda: 1000.0)
    lc = Lifecycler(kv, "gen-0", n_tokens=32, now=lambda: 1000.0)
    assert len(ring) == 1
    assert ring.get(42).instances[0].id == "gen-0"
    lc.leave()
    assert len(ring) == 0


def test_do_batch_quorum_tolerates_one_failure():
    r = make_ring(5)
    got: dict[str, list] = {}

    def send(inst, items):
        if inst.id == "ing-0":
            raise RuntimeError("down")
        got.setdefault(inst.id, []).extend(items)

    tokens = np.arange(50, dtype=np.uint32) * 77_000_000
    do_batch(r, tokens, list(range(50)), send)
    assert sum(len(v) for v in got.values()) >= 100  # each item at 2+ replicas


def test_do_batch_fails_without_quorum():
    r = make_ring(3)

    def send(inst, items):
        if inst.id in ("ing-0", "ing-1"):
            raise RuntimeError("down")

    with pytest.raises(RuntimeError):
        do_batch(r, np.array([5], np.uint32), ["x"], send)


def test_overrides_layering(tmp_path):
    p = tmp_path / "rc.yaml"
    p.write_text(
        "overrides:\n"
        "  '*':\n"
        "    ingestion: {rate_limit_bytes: 1000}\n"
        "  tenant-a:\n"
        "    ingestion: {rate_limit_bytes: 2000}\n"
        "    generator: {processors: [span-metrics]}\n")
    o = Overrides(runtime_config_path=str(p))
    assert o.for_tenant("tenant-a").ingestion.rate_limit_bytes == 2000
    assert o.for_tenant("tenant-a").generator.processors == ("span-metrics",)
    assert o.for_tenant("other").ingestion.rate_limit_bytes == 1000
    assert o.for_tenant("other").generator.processors == ()
    # mtime-gated reload
    assert o.reload() is False


def test_user_configurable_overrides_api_and_validation():
    be = MemBackend()
    uc = UserConfigurableOverrides(be, be)
    o = Overrides(user_configurable=uc)
    v1 = uc.set("t1", {"generator": {"collection_interval_s": 30.0}})
    assert o.for_tenant("t1").generator.collection_interval_s == 30.0
    # version conflict
    with pytest.raises(RuntimeError):
        uc.set("t1", {"generator": {"collection_interval_s": 60.0}}, version="99")
    uc.set("t1", {"generator": {"collection_interval_s": 60.0}}, version=v1)
    assert o.for_tenant("t1").generator.collection_interval_s == 60.0
    # non-user-configurable field rejected
    with pytest.raises(ValueError):
        uc.set("t1", {"ingestion": {"rate_limit_bytes": 1}})
    uc.delete("t1")
    assert o.for_tenant("t1").generator.collection_interval_s == \
        Limits().generator.collection_interval_s


# ---------------------------------------------------------------------------
# rebalancing invariants (fleet PR: tenants place on the ring RF1)
# ---------------------------------------------------------------------------


def _owners(r, keys):
    return {k: r.owner_of(k).id for k in keys}


def test_minimal_ownership_movement_on_join():
    """A joining instance steals ~1/N of the key space and NOTHING
    moves between surviving instances (consistent hashing's whole
    point); the stolen share is token-count bounded."""
    r = make_ring(4, rf=1)
    keys = [f"tenant-{i}" for i in range(1000)]
    before = _owners(r, keys)
    r.register(InstanceDesc(id="ing-new", addr="hostN",
                            tokens=_instance_tokens("ing-new", 64),
                            state=ACTIVE, heartbeat_ts=1000.0))
    after = _owners(r, keys)
    moved = [k for k in keys if before[k] != after[k]]
    # every moved key moved TO the joiner, never between old members
    assert all(after[k] == "ing-new" for k in moved)
    # token-count bound: the joiner owns 64 of 320 tokens (1/5);
    # allow 2x sampling slack, and demand it actually took a share
    assert 0 < len(moved) <= 2 * len(keys) * 64 / 320


def test_minimal_ownership_movement_on_leave():
    """A leaving instance's keys redistribute; keys owned by survivors
    do not move at all."""
    r = make_ring(5, rf=1)
    keys = [f"tenant-{i}" for i in range(1000)]
    before = _owners(r, keys)
    r.unregister("ing-2")
    after = _owners(r, keys)
    for k in keys:
        if before[k] != "ing-2":
            assert after[k] == before[k], k
        else:
            assert after[k] != "ing-2"
    # ownership fractions stay a partition of the space
    assert abs(sum(r.ownership().values()) - 1.0) < 1e-9


def test_shuffle_shard_stable_across_heartbeat_refresh():
    """Heartbeat-only KV republishes (same membership fingerprint) must
    not reshuffle any tenant's sub-ring — a shard that wobbled per
    heartbeat would smear tenant blast radius over the whole ring."""
    clock = [1000.0]
    r = make_ring(10, rf=2, now=lambda: clock[0])
    ids1 = {i.id for i in r.shuffle_shard("tenant-a", 3).instances()}
    # republish the SAME membership with fresh heartbeats, several times
    for step in range(1, 4):
        clock[0] = 1000.0 + step
        m = {i.id: i for i in r.instances()}
        for d in m.values():
            d.heartbeat_ts = clock[0]
        r._on_update(m)
        ids = {i.id for i in r.shuffle_shard("tenant-a", 3).instances()}
        assert ids == ids1, f"shard moved on heartbeat refresh #{step}"
    # membership change DOES reshuffle state (sanity: not frozen forever)
    r.register(InstanceDesc(id="ing-x", addr="hx",
                            tokens=_instance_tokens("ing-x", 64),
                            state=ACTIVE, heartbeat_ts=clock[0]))
    assert len(r.shuffle_shard("tenant-a", 3).instances()) == 3


def test_do_batch_quorum_accounting_persistent_failure():
    """One instance that fails EVERY call: each batch still succeeds
    (every item reaches quorum among the healthy replicas), the failure
    is charged to the right items, and the dead instance never absorbs
    an item's only copies."""
    r = make_ring(5, rf=3)
    delivered: dict[str, set] = {}

    def send(inst, items):
        if inst.id == "ing-3":
            raise RuntimeError("persistently down")
        delivered.setdefault(inst.id, set()).update(items)

    tokens = (np.arange(200, dtype=np.uint64) * 21_000_003 % (2**32)) \
        .astype(np.uint32)
    for _round in range(3):
        do_batch(r, tokens, list(range(200)), send)
    # every item reached at least quorum (2 of rf=3) distinct live instances
    for item in range(200):
        holders = {iid for iid, got in delivered.items() if item in got}
        assert len(holders) >= 2, item
    assert "ing-3" not in delivered
    # two persistent failures out of rf=3 breaks quorum for hit items
    def send2(inst, items):
        if inst.id in ("ing-3", "ing-4"):
            raise RuntimeError("down")
    hit = [t for t in tokens.tolist()
           if {i.id for i in r.get(t).instances} >= {"ing-3", "ing-4"}]
    if hit:
        with pytest.raises(RuntimeError):
            do_batch(r, np.array(hit[:1], np.uint32), ["x"], send2)


def test_lifecycler_background_heartbeat_loop():
    """start_heartbeat() keeps the KV descriptor fresh without manual
    heartbeat() calls; leave() stops AND joins the loop thread."""
    import time as _time

    kv = KVStore()
    lc = Lifecycler(kv, "gen-hb", n_tokens=8)
    t0 = lc.desc.heartbeat_ts
    lc.start_heartbeat(interval_s=0.05)
    lc.start_heartbeat(interval_s=0.05)       # idempotent
    deadline = _time.time() + 2.0
    while _time.time() < deadline:
        cur = kv.get(lc.key)["gen-hb"].heartbeat_ts
        if cur > t0:
            break
        _time.sleep(0.02)
    assert kv.get(lc.key)["gen-hb"].heartbeat_ts > t0
    thread = lc._hb_thread
    lc.leave()
    assert lc._hb_thread is None
    assert thread is not None and not thread.is_alive()
    assert kv.get(lc.key) == {}


def test_remote_kv_shutdown_joins_poller_and_backs_off():
    """RemoteKVStore.shutdown() must JOIN its poll thread (no leaked
    threads in embedded/test reuse), and the poll loop must back off
    exponentially while every fetch errors."""
    import threading as _threading
    import time as _time

    from tempo_tpu.ring.kv import RemoteKVStore, _poll_backoff

    # backoff math: doubles per failed pass, capped
    assert _poll_backoff(1.0, 0) == 1.0
    assert _poll_backoff(1.0, 1) == 2.0
    assert _poll_backoff(1.0, 5) == 32.0
    assert _poll_backoff(1.0, 50) == 32.0     # factor cap
    assert _poll_backoff(5.0, 50) == 60.0     # absolute cap

    # point at a dead endpoint; the watch thread starts, errors, and
    # shutdown still joins it promptly
    kv = RemoteKVStore("http://127.0.0.1:1", poll_interval_s=0.01,
                       timeout_s=0.05)
    kv.watch_key("ring", lambda v: None)
    poller = kv._poller
    assert poller is not None and poller.is_alive()
    _time.sleep(0.1)
    kv.shutdown()
    assert kv._poller is None
    assert not poller.is_alive()
    # no stray kv threads left behind
    assert not any(t is poller for t in _threading.enumerate())
