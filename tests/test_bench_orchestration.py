"""bench.py orchestration: the platform probe/re-probe/re-run machinery.

Round-4 postmortem: the bench probed the accelerator twice at startup and
then NEVER looked again, so a tunnel that wedged for 8 minutes cost the
whole round its TPU record (BENCH_r04: platform "cpu"). These tests drive
the round-5 orchestrator through its fault-injection hooks — stages are
stubbed (TEMPO_BENCH_STAGE_STUB), the probe can hang until a chosen epoch
(TEMPO_BENCH_PROBE_HANG_UNTIL) and report a fake platform
(TEMPO_BENCH_PROBE_FAKE) — asserting the healthy-startup, permanent-
failure, and (post-BENCH_r05) first-failure-commits-to-cpu paths
without any accelerator. The background re-probe machinery is gone:
one bounded startup probe decides the run's platform, and the only
accelerator retry left is re-running stages that individually failed
on a probe-confirmed accelerator.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _run(hang_s: float | None = None, fake: str = "tpu",
         probe_timeout: float = 3,
         timeout: float = 120) -> tuple[dict, str]:
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
        "TEMPO_BENCH_STAGE_STUB": "1",
        "TEMPO_BENCH_PROBE_FAKE": fake,
        "TEMPO_BENCH_PROBE_TIMEOUT_S": str(probe_timeout),
    })
    if hang_s is not None:
        env["TEMPO_BENCH_PROBE_HANG_UNTIL"] = str(time.time() + hang_s)
    proc = subprocess.run([sys.executable, BENCH], env=env,
                          capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, proc.stderr[-800:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    return line, proc.stderr


def test_healthy_startup_probe_uses_accelerator():
    line, err = _run(hang_s=None)
    assert line["extra"]["platform"] == "tpu"
    assert set(line["extra"]["stage_platform"].values()) == {"tpu"}
    assert "re-running" not in err          # nothing captured on cpu


def test_probe_failure_commits_to_cpu_without_retry():
    # BENCH_r05 postmortem: two back-to-back 360s startup timeouts burned
    # 12 minutes before the CPU fallback started. A failed FIRST probe now
    # commits the whole run to CPU: no startup retry, no background
    # probes — even though the tunnel here recovers 10s in.
    t0 = time.time()
    line, err = _run(hang_s=10)
    assert line["extra"]["platform"] == "cpu"
    assert set(line["extra"]["stage_platform"].values()) == {"cpu"}
    assert "committing to cpu" in err
    assert "background probe found" not in err
    assert "background probe timed out" not in err
    # one probe timeout (3s) + stub stages, not 2x timeouts + re-probes
    assert time.time() - t0 < 60


def test_probe_never_recovers_keeps_cpu_numbers():
    line, err = _run(hang_s=3600)
    assert line["extra"]["platform"] == "cpu"
    assert set(line["extra"]["stage_platform"].values()) == {"cpu"}
    # the bench still emitted a full record (rc 0, headline value present)
    assert line["value"] == 1.0
    # and spent only ONE probe timeout learning the tunnel was wedged
    assert "committing to cpu" in err


def test_confirmed_cpu_platform_stops_reprobing():
    # probe SUCCEEDS but reports cpu: the orchestrator must accept that
    # no accelerator exists and not burn re-probe budget
    line, err = _run(hang_s=None, fake="cpu")
    assert line["extra"]["platform"] == "cpu"
    assert "background probe" not in err
