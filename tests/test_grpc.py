"""gRPC plane: OTLP/gRPC ingest, inter-service RPC, worker-pull scale-out.

The gRPC analog of the reference's transport tests: a microservices
cluster wired over grpc:// peers (shim.go receivers + tempo.proto
services), plus the frontend↔querier worker-pull dispatch
(`v1/frontend.go:204-293`, `worker/frontend_processor.go:69-195`).
"""

from __future__ import annotations

import json
import socket
import time

import grpc
import pytest

from tempo_tpu.app import App
from tempo_tpu.app.config import Config
from tempo_tpu.grpcplane import build_grpc_server
from tempo_tpu.grpcplane.client import streaming_search


def _port() -> int:
    s = socket.socket(); s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]; s.close()
    return p


def _otlp_json_to_proto(payload: dict) -> bytes:
    """Build an ExportTraceServiceRequest protobuf from OTLP JSON (enough
    fields for the tests; exercises the receiver's real decode path)."""
    from tempo_tpu.model.proto_wire import (
        enc_field_bytes, enc_field_msg, enc_field_str, enc_field_varint)

    def anyval(v: dict) -> bytes:
        if "stringValue" in v:
            return enc_field_str(1, v["stringValue"])
        if "intValue" in v:
            return enc_field_varint(3, int(v["intValue"]))
        raise ValueError(v)

    def attr(kv: dict) -> bytes:
        return (enc_field_str(1, kv["key"]) +
                enc_field_msg(2, anyval(kv["value"])))

    out = b""
    for rs in payload["resourceSpans"]:
        rs_b = enc_field_msg(1, b"".join(
            enc_field_msg(1, attr(a))
            for a in rs.get("resource", {}).get("attributes", [])))
        for ss in rs.get("scopeSpans", []):
            spans_b = b""
            for sp in ss["spans"]:
                b = (enc_field_bytes(1, bytes.fromhex(sp["traceId"])) +
                     enc_field_bytes(2, bytes.fromhex(sp["spanId"])) +
                     enc_field_str(5, sp["name"]) +
                     enc_field_varint(6, sp.get("kind", 0)) +
                     enc_field_varint(7, int(sp["startTimeUnixNano"])) +
                     enc_field_varint(8, int(sp["endTimeUnixNano"])))
                for a in sp.get("attributes", []):
                    b += enc_field_msg(9, attr(a))
                spans_b += enc_field_msg(2, b)
            rs_b += enc_field_msg(2, spans_b)
        out += enc_field_msg(1, rs_b)
    return out


@pytest.fixture
def grpc_cluster(tmp_path):
    """distributor + ingester + generator + query tier over grpc:// peers."""
    store = str(tmp_path / "store")
    apps, servers = {}, {}

    def boot(name, cfg):
        cfg.server.http_listen_port = _port()
        app = App(cfg)
        app.overrides.set_tenant_patch("single-tenant", {
            "generator": {"processors": ["span-metrics", "local-blocks"]}})
        app.start_loops()
        srv, port = build_grpc_server(app)
        apps[name] = app
        servers[name] = srv
        return port

    ing_cfg = Config(target="ingester")
    ing_cfg.storage.backend = "local"
    ing_cfg.storage.local_path = store
    ing_cfg.storage.wal_path = str(tmp_path / "ing" / "wal")
    ing_cfg.ingester.instance.trace_idle_s = 0.1
    ing_port = boot("ing", ing_cfg)

    gen_cfg = Config(target="metrics-generator")
    gen_cfg.storage.backend = "local"
    gen_cfg.storage.local_path = store
    gen_cfg.generator.localblocks.data_dir = str(tmp_path / "gen-lb")
    gen_port = boot("gen", gen_cfg)

    q_cfg = Config(target="query-frontend")
    q_cfg.storage.backend = "local"
    q_cfg.storage.local_path = store
    q_cfg.peers.ingesters = {"ing-1": f"grpc://127.0.0.1:{ing_port}"}
    q_cfg.peers.generators = {"gen-1": f"grpc://127.0.0.1:{gen_port}"}
    q_port = boot("query", q_cfg)

    d_cfg = Config(target="distributor")
    d_cfg.peers.ingesters = {"ing-1": f"grpc://127.0.0.1:{ing_port}"}
    d_cfg.peers.generators = {"gen-1": f"grpc://127.0.0.1:{gen_port}"}
    d_port = boot("dist", d_cfg)

    yield apps, {"ing": ing_port, "gen": gen_port,
                 "query": q_port, "dist": d_port}
    for s in servers.values():
        s.stop(grace=0.5)
    for a in apps.values():
        a.shutdown()


def _otlp(trace_id: str, t0: int, name="grpc-op", svc="grpc-svc"):
    return {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name", "value": {"stringValue": svc}}]},
        "scopeSpans": [{"spans": [{
            "traceId": trace_id, "spanId": "ab" * 8, "name": name,
            "kind": 2, "startTimeUnixNano": str(t0),
            "endTimeUnixNano": str(t0 + 30_000_000),
            "attributes": [{"key": "http.status_code",
                            "value": {"intValue": "200"}}]}]}]}]}


def test_grpc_microservices_e2e(grpc_cluster):
    """OTLP/gRPC in at the distributor; trace-by-id, search, tag values and
    metrics out of the query tier — all inter-service hops over gRPC."""
    apps, ports = grpc_cluster
    t0 = int((time.time() - 5) * 1e9)
    body = _otlp_json_to_proto(_otlp("cd" * 16, t0))
    with grpc.insecure_channel(f"127.0.0.1:{ports['dist']}") as ch:
        export = ch.unary_unary(
            "/opentelemetry.proto.collector.trace.v1.TraceService/Export")
        resp = export(body, timeout=10)
        assert resp == b""
        # malformed payload → INVALID_ARGUMENT, not UNKNOWN/INTERNAL
        with pytest.raises(grpc.RpcError) as ei:
            export(b"\xff\xfe garbage", timeout=10)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    fe = apps["query"].frontend
    spans = fe.find_trace("single-tenant", bytes.fromhex("cd" * 16))
    assert spans and spans[0]["name"] == "grpc-op"

    res = fe.search("single-tenant",
                    '{ resource.service.name = "grpc-svc" }')
    assert len(res) == 1 and res[0].trace_id == "cd" * 16

    vals = fe.tag_values("single-tenant", ".http.status_code")
    assert any(v["value"] == "200" for v in vals)

    # generator got the tee: span-metrics series exist
    gi = apps["gen"].generator.instances.get("single-tenant")
    assert gi is not None and gi.spans_received == 1


def test_grpc_streaming_search(grpc_cluster):
    apps, ports = grpc_cluster
    t0 = int((time.time() - 5) * 1e9)
    body = _otlp_json_to_proto(_otlp("ef" * 16, t0, name="stream-op"))
    with grpc.insecure_channel(f"127.0.0.1:{ports['dist']}") as ch:
        ch.unary_unary(
            "/opentelemetry.proto.collector.trace.v1.TraceService/Export"
        )(body, timeout=10)
    msgs = list(streaming_search(
        f"127.0.0.1:{ports['query']}", "single-tenant", "{ }"))
    assert msgs[-1][1] is True                 # final message flagged
    final = msgs[-1][0]
    assert any(md.trace_id == "ef" * 16 for md in final)
    # the partial diff arrived before the final (ingester leg streams first)
    assert any(not fin and any(md.trace_id == "ef" * 16 for md in tr)
               for tr, fin in msgs[:-1])


def test_worker_pull_scale_out(tmp_path):
    """1 frontend + 2 standalone querier processes: backend search jobs
    demonstrably execute on both workers (VERDICT r1 item 4)."""
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.grpcplane.client import FrontendWorker

    store = str(tmp_path / "store")

    # seed the shared store with enough blocks to make many jobs
    from tempo_tpu.db.tempodb import TempoDB

    seed_db = TempoDB(LocalBackend(store), LocalBackend(store))
    t_base = int((time.time() - 7200) * 1e9)   # old: backend window
    for i in range(6):
        tid = bytes([i + 1] * 16)
        spans = [{"trace_id": tid, "span_id": bytes([i + 1] * 8),
                  "name": f"op-{i}", "kind": 2, "service": "scale",
                  "start_unix_nano": t_base + i * 1_000_000_000,
                  "end_unix_nano": t_base + i * 1_000_000_000 + 5_000_000,
                  "res_attrs": {"service.name": "scale"}}]
        seed_db.write_block("single-tenant", [(tid, spans)])
    seed_db.poll_now()
    n_blocks = len(seed_db.blocks("single-tenant"))
    assert n_blocks >= 2
    seed_db.shutdown()

    # frontend process (no local workers — remote pull only)
    fe_cfg = Config(target="query-frontend")
    fe_cfg.storage.backend = "local"
    fe_cfg.storage.local_path = store
    fe_cfg.server.http_listen_port = _port()
    fe_app = App(fe_cfg)
    fe_app.start_loops()
    fe_app.db.poll_now()
    fe_srv, fe_port = build_grpc_server(fe_app)

    # two standalone querier processes dialing the frontend
    workers = []
    qapps = []
    for i in range(2):
        q_cfg = Config(target="querier")
        q_cfg.storage.backend = "local"
        q_cfg.storage.local_path = store
        q_cfg.server.http_listen_port = _port()
        qa = App(q_cfg)
        qa.db.poll_now()
        w = FrontendWorker(f"127.0.0.1:{fe_port}", qa.querier,
                           worker_id=f"w{i}", parallelism=1)
        w.start()
        workers.append(w)
        qapps.append(qa)

    # wait for both worker streams to attach
    deadline = time.time() + 5
    while fe_app.frontend.remote_workers < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert fe_app.frontend.remote_workers == 2

    try:
        start = (t_base / 1e9) - 60
        end = (t_base / 1e9) + 3600
        res = fe_app.frontend.search("single-tenant", "{ }", limit=50,
                                     start_s=start, end_s=end)
        assert len(res) == 6
        counts = [w.jobs_executed for w in workers]
        assert sum(counts) >= n_blocks
        assert all(c > 0 for c in counts), counts  # both workers pulled jobs
    finally:
        for w in workers:
            w.shutdown()
        fe_srv.stop(grace=0.5)
        fe_app.shutdown()
        for qa in qapps:
            qa.shutdown()


def test_tempopb_wire_is_protobuf():
    """The tempopb seams carry PROTOBUF bodies, not JSON (VERDICT r2 #7):
    encode/decode round-trips through the hand-rolled codec, and the
    bytes parse as protobuf fields (first byte = a valid field tag)."""
    import numpy as np

    from tempo_tpu.model import tempopb
    from tempo_tpu.traceql.engine import TraceSearchMetadata
    from tempo_tpu.traceql.engine_metrics import TimeSeries

    md = TraceSearchMetadata(
        trace_id="ab" * 16, root_service_name="svc", root_trace_name="op",
        start_time_unix_nano=1_700_000_000_000_000_000, duration_ms=42,
        span_sets=[{"spans": [{"spanID": "cd" * 8, "name": "child",
                               "startTimeUnixNano": "123", "durationNanos": "456",
                               "attributes": [{"key": "k",
                                               "value": {"stringValue": "v"}}]}],
                    "matched": 3}])
    body = tempopb.enc_search_response([md], inspected=7, final=False)
    assert body[:1] != b"{"                      # not JSON
    mds, final, inspected, stats = tempopb.dec_search_response(body)
    assert not final and inspected == 7
    assert stats.inspected_traces == 7       # legacy scalar → stats field
    got = mds[0]
    assert got.trace_id == md.trace_id
    assert got.start_time_unix_nano == md.start_time_unix_nano
    assert got.duration_ms == 42
    assert got.span_sets[0]["matched"] == 3
    sp = got.span_sets[0]["spans"][0]
    assert sp["spanID"] == "cd" * 8 and sp["name"] == "child"
    assert sp["attributes"][0]["value"]["stringValue"] == "v"

    series = [TimeSeries(labels=(("service", "s1"), ("name", "op")),
                         samples=np.array([0.0, 2.5, 7.0])),
              # numeric label VALUES must keep their types: the combiner
              # keys on the exact labels tuple (log2 buckets are floats)
              TimeSeries(labels=(("__bucket", 0.002), ("code", 500),
                                 ("neg", -3), ("flag", True)),
                         samples=np.array([1.0]))]
    qr = tempopb.enc_query_range_response(series)
    back = tempopb.dec_query_range_response(qr)
    for want, got in zip(series, back):
        assert got.labels == want.labels
        assert [type(v) for _, v in got.labels] == \
            [type(v) for _, v in want.labels]
        np.testing.assert_array_equal(got.samples, want.samples)

    spans = [{"trace_id": b"\x01" * 16, "span_id": b"\x02" * 8,
              "name": "t", "service": "s",
              "start_unix_nano": 5, "end_unix_nano": 9,
              "events": [{"time_unix_nano": 7, "name": "ev"}],
              "links": [{"trace_id": b"\x03" * 16, "span_id": b"\x04" * 8}]}]
    tb = tempopb.enc_trace_by_id_response(spans)
    back_spans = tempopb.dec_trace_by_id_response(tb)
    assert back_spans[0]["name"] == "t"
    assert back_spans[0]["events"] == [{"time_unix_nano": 7, "name": "ev"}]
    assert back_spans[0]["links"][0]["trace_id"] == b"\x03" * 16
    assert tempopb.dec_trace_by_id_response(b"") is None

    pr = tempopb.enc_push_response([None, "trace_too_large", None])
    assert tempopb.dec_push_response(pr, 3) == [None, "trace_too_large", None]
    assert tempopb.dec_push_response(b"", 2) == [None, None]


def test_jaeger_grpc_post_spans(grpc_cluster):
    """api_v2 CollectorService/PostSpans end-to-end: a jaeger-proto batch
    (built with the tempo-query encoder — the inverse translation) lands
    in the ingester and is searchable, with span.kind/error tags mapped
    to intrinsics (shim.go:165-171 jaeger gRPC receiver)."""
    from tempo_tpu.model import proto_wire as pw
    from tempo_tpu.tempoquery.plugin import _jaeger_span

    apps, ports = grpc_cluster
    t0 = int((time.time() - 5) * 1e9)
    tid = bytes.fromhex("ef" * 16)
    span = {"trace_id": tid, "span_id": "aa" * 8, "name": "jgrpc-op",
            "service": "jgrpc-svc", "kind": 2, "status_code": 2,
            "start_unix_nano": t0, "end_unix_nano": t0 + 40_000_000,
            "attrs": {"http.method": "GET"},
            "res_attrs": {"service.name": "jgrpc-svc", "region": "r1"}}
    batch = (pw.enc_field_msg(1, _jaeger_span(span, tid)) +
             pw.enc_field_msg(2, pw.enc_field_str(1, "jgrpc-svc")))
    request = pw.enc_field_msg(1, batch)        # PostSpansRequest{batch=1}

    with grpc.insecure_channel(f"127.0.0.1:{ports['dist']}") as ch:
        post = ch.unary_unary("/jaeger.api_v2.CollectorService/PostSpans")
        assert post(request, timeout=10) == b""

    spans = apps["query"].frontend.find_trace("single-tenant", tid)
    assert spans and spans[0]["name"] == "jgrpc-op"
    assert spans[0]["service"] == "jgrpc-svc"
    assert spans[0]["kind"] == 2                # span.kind tag → intrinsic
    assert spans[0]["status_code"] == 2         # error tag → status
    assert spans[0]["attrs"]["http.method"] == "GET"
    res = apps["query"].frontend.search(
        "single-tenant", '{ status = error && name = "jgrpc-op" }')
    assert len(res) == 1 and res[0].trace_id == "ef" * 16


def test_opencensus_grpc_export(grpc_cluster):
    """OC agent TraceService/Export (bidi): Node+Resource on the first
    message persist for the stream; spans land and are searchable
    (shim.go:165-171 opencensus receiver)."""
    from tempo_tpu.model import proto_wire as pw

    apps, ports = grpc_cluster
    t0 = int((time.time() - 5) * 1e9)

    def ts(ns):
        return pw.enc_field_varint(1, ns // 10**9) + \
            pw.enc_field_varint(2, ns % 10**9)

    def trunc(s):
        return pw.enc_field_msg(1, s.encode()) if False else \
            pw.enc_field_str(1, s)

    def attr(k, v):
        av = pw.enc_field_msg(1, trunc(v)) if isinstance(v, str) else \
            pw.enc_field_varint(2, v)
        return pw.enc_field_msg(1, pw.enc_field_str(1, k) +
                                pw.enc_field_msg(2, av))

    tid = bytes.fromhex("1b" * 16)
    span = (pw.enc_field_bytes(1, tid) +
            pw.enc_field_bytes(2, bytes.fromhex("2c" * 8)) +
            pw.enc_field_msg(5, trunc("oc-op")) +
            pw.enc_field_varint(6, 1) +              # OC SERVER
            pw.enc_field_msg(7, ts(t0)) +
            pw.enc_field_msg(8, ts(t0 + 25_000_000)) +
            pw.enc_field_msg(9, attr("oc.key", "v1")) +
            pw.enc_field_msg(13, pw.enc_field_varint(1, 5)))  # status !=0
    node = pw.enc_field_msg(3, pw.enc_field_str(1, "oc-svc"))
    first = pw.enc_field_msg(1, node) + pw.enc_field_msg(2, span)
    # second message: spans only (node persists)
    span2 = (pw.enc_field_bytes(1, tid) +
             pw.enc_field_bytes(2, bytes.fromhex("3d" * 8)) +
             pw.enc_field_msg(5, trunc("oc-op2")) +
             pw.enc_field_msg(7, ts(t0)) +
             pw.enc_field_msg(8, ts(t0 + 1_000_000)))
    second = pw.enc_field_msg(2, span2)

    with grpc.insecure_channel(f"127.0.0.1:{ports['dist']}") as ch:
        export = ch.stream_stream(
            "/opencensus.proto.agent.trace.v1.TraceService/Export")
        responses = list(export(iter([first, second]), timeout=10))
        assert len(responses) == 2

    spans = apps["query"].frontend.find_trace("single-tenant", tid)
    assert spans and len(spans) == 2
    by_name = {s["name"]: s for s in spans}
    assert by_name["oc-op"]["kind"] == 2          # OC SERVER → OTel SERVER
    assert by_name["oc-op"]["status_code"] == 2   # nonzero code → ERROR
    assert by_name["oc-op"]["attrs"]["oc.key"] == "v1"
    assert by_name["oc-op2"]["service"] == "oc-svc"   # node persisted


def test_grpc_streaming_metrics_query_range(grpc_cluster):
    """StreamingQuerier/MetricsQueryRange delivers series-DIFF messages
    incrementally on a multi-block query (round-4 weak #5: the unary seam
    buffered the whole series set in one response)."""
    import numpy as np
    from tempo_tpu.grpcplane.client import streaming_metrics_query_range

    apps, ports = grpc_cluster
    qdb = apps["query"].db
    rng = np.random.default_rng(9)
    now_s = time.time()
    base = now_s - 7200          # squarely in the BACKEND window
    for b in range(3):           # three blocks → three fold steps
        traces = []
        for i in range(60):
            tid = rng.bytes(16)
            start = int((base + b * 300 + i) * 1e9)
            traces.append((tid, [{
                "trace_id": tid, "span_id": rng.bytes(8),
                "name": f"op-{b}", "service": "svc",
                "kind": 2, "status_code": 0,
                "start_unix_nano": start,
                "end_unix_nano": start + 10**7}]))
        traces.sort(key=lambda t: t[0])
        qdb.write_block("single-tenant", traces, replication_factor=1)
    qdb.poll_now()

    msgs = list(streaming_metrics_query_range(
        f"127.0.0.1:{ports['query']}", "single-tenant",
        "{ } | rate() by (name)", start_s=base - 60, end_s=now_s - 3600,
        step_s=300))
    # incremental: more than one message, and the pre-final messages do
    # not each carry the full final set (true diffs)
    assert len(msgs) >= 2, len(msgs)
    final = {tuple(s.labels): np.asarray(s.samples) for s in msgs[-1]}
    assert len(final) == 3       # op-0/1/2 series
    assert any(len(m) < len(final) for m in msgs[:-1]) or len(msgs) > 2
    # diffs compose to the final answer: last-write-wins per series
    acc: dict = {}
    for m in msgs[:-1]:
        for s in m:
            acc[tuple(s.labels)] = np.asarray(s.samples)
    assert set(acc) == set(final)
    for k in final:
        np.testing.assert_allclose(acc[k], final[k])


def test_grpc_streaming_search_tags(grpc_cluster):
    """StreamingQuerier/SearchTags streams scope diffs then the final
    scopes map."""
    from tempo_tpu.grpcplane.client import streaming_search_tags

    apps, ports = grpc_cluster
    t0 = int((time.time() - 5) * 1e9)
    body = _otlp_json_to_proto(_otlp("aa" * 16, t0, name="tag-op"))
    with grpc.insecure_channel(f"127.0.0.1:{ports['dist']}") as ch:
        ch.unary_unary(
            "/opentelemetry.proto.collector.trace.v1.TraceService/Export"
        )(body, timeout=10)
    msgs = list(streaming_search_tags(
        f"127.0.0.1:{ports['query']}", "single-tenant"))
    assert msgs[-1][1] is True
    scopes = msgs[-1][0]
    assert "http.status_code" in scopes.get("span", [])
    # at least one pre-final diff arrived (the ingester pass)
    assert len(msgs) >= 2 and msgs[0][1] is False


def test_grpc_streaming_search_tag_values(grpc_cluster):
    apps, ports = grpc_cluster
    t0 = int(time.time() - 5) * 10**9
    body = _otlp_json_to_proto(_otlp("bb" * 16, t0, name="tv-op"))
    with grpc.insecure_channel(f"127.0.0.1:{ports['dist']}") as ch:
        ch.unary_unary(
            "/opentelemetry.proto.collector.trace.v1.TraceService/Export"
        )(body, timeout=10)
    with grpc.insecure_channel(f"127.0.0.1:{ports['query']}") as ch:
        fn = ch.unary_stream("/tempopb.StreamingQuerier/SearchTagValues")
        msgs = [json.loads(m) for m in fn(
            json.dumps({"name": ".http.status_code"}).encode(), timeout=30,
            metadata=(("x-scope-orgid", "single-tenant"),))]
    assert msgs[-1]["final"] is True
    assert any(v["value"] == "200" for v in msgs[-1]["tagValues"])
    assert len(msgs) >= 2 and msgs[0]["final"] is False
