"""Generator fleet: placement, checkpoint/restore, drain/handoff.

The multi-host protocol is exercised in-process where possible (two
Generators + controllers over one KVStore — fast, deterministic) and
with ONE real child process for the worker/reap plumbing. Bit-identity
contract: count-kind samples (calls/size counters, histogram buckets
and counts, DDSketch grids) restore and merge EXACTLY; float sums are
f32-add-order class (the same tolerance the mesh/shard combines carry).
"""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.fleet import STATS, FleetConfig
from tempo_tpu.fleet import checkpoint as ck
from tempo_tpu.fleet.controller import FleetController
from tempo_tpu.fleet.placement import TenantPlacement, tenant_token
from tempo_tpu.generator.generator import Generator
from tempo_tpu.generator.instance import GeneratorConfig, GeneratorInstance
from tempo_tpu.generator.processors.spanmetrics import SpanMetricsConfig
from tempo_tpu.model.span_batch import SpanBatchBuilder
from tempo_tpu.registry import RegistryOverrides
from tempo_tpu.ring import KVStore, Lifecycler, Ring

NOW = 1700000000.0


def _cfg(sketch: str = "both", max_series: int = 1024,
         moments_k: int = 12) -> GeneratorConfig:
    return GeneratorConfig(
        processors=("span-metrics",),
        registry=RegistryOverrides(max_active_series=max_series),
        spanmetrics=SpanMetricsConfig(sketch=sketch, moments_k=moments_k))


def _inst(tenant="t1", **kw) -> GeneratorInstance:
    return GeneratorInstance(tenant, _cfg(**kw), now=lambda: NOW)


def _spans(seed: int, n: int = 40) -> list[dict]:
    rng = np.random.default_rng(seed)
    return [dict(trace_id=rng.bytes(16), span_id=rng.bytes(8),
                 name=f"op-{i % 5}", service=f"svc-{i % 3}", kind=2,
                 status_code=int(i % 7 == 0) * 2,
                 start_unix_nano=int(NOW * 1e9),
                 end_unix_nano=int(NOW * 1e9) + int(rng.integers(1, 5e8)))
            for i in range(n)]


def _push(inst: GeneratorInstance, seed: int, n: int = 40) -> None:
    b = SpanBatchBuilder(inst.registry.interner)
    for s in _spans(seed, n):
        b.append(**s)
    inst.push_batch(b.build())


def _samples(inst: GeneratorInstance) -> dict:
    return {(s.name, s.labels): s.value
            for s in inst.registry.collect(ts_ms=1)
            if not s.is_stale_marker}


def _assert_merge_equal(got: dict, want: dict) -> None:
    """Count kinds bit-identical; float sums within f32-add-order."""
    assert set(got) == set(want)
    for k, v in want.items():
        if k[0].endswith("_sum"):
            assert got[k] == pytest.approx(v, rel=1e-5)
        else:
            assert got[k] == v, k


# ---------------------------------------------------------------------------
# checkpoint round trips
# ---------------------------------------------------------------------------


def test_checkpoint_restore_roundtrip_bit_identical():
    """Fresh-instance restore is add-to-zero: collect() and the dd
    quantile surface round-trip bit-identically through the blob."""
    a = _inst()
    _push(a, 1)
    blob = ck.snapshot_instance(a)
    b = _inst()
    stats = ck.restore_instance(b, blob)
    assert stats["dropped"] == 0 and stats["series"] > 0
    assert _samples(b) == _samples(a)
    pa = a.processors["span-metrics"]
    pb = b.processors["span-metrics"]
    assert pb.quantile(0.99) == pa.quantile(0.99)


def test_checkpoint_restore_through_backend_objects():
    """The storage-layout helpers: write → list → read → delete."""
    be = MemBackend()
    a = _inst("te/nant")                 # path-hostile tenant name
    _push(a, 2)
    blob = ck.snapshot_instance(a)
    name = ck.checkpoint_name(NOW, "gen-a")
    ck.write_checkpoint(be, "fleet-checkpoints", "te/nant", blob, name)
    listed = ck.list_checkpoints(be, "fleet-checkpoints")
    assert listed == {"te/nant": [name]}
    got = ck.read_checkpoint(be, "fleet-checkpoints", "te/nant", name)
    b = _inst("te/nant")
    ck.restore_instance(b, got)
    assert _samples(b) == _samples(a)
    ck.delete_checkpoint(be, "fleet-checkpoints", "te/nant", name)
    assert ck.list_checkpoints(be, "fleet-checkpoints") == {}


def test_checkpoint_restore_roundtrip_paged_and_cross_layout():
    """Paged tenants snapshot backed pages only; the blob is layout-
    neutral (paged → paged AND paged → dense restores bit-identically),
    and dropping the paged instance releases its pages to the pool."""
    from tempo_tpu.registry import pages as pgs

    pool = pgs.PagePool(pgs.PagePoolConfig(enabled=True, page_rows=64,
                                           arena_slots=4096))
    with pgs.use(pool):
        a = _inst("pt")
        assert a.state_layout == "paged"
        _push(a, 3)
        blob = ck.snapshot_instance(a)
        b = _inst("pt")
        ck.restore_instance(b, blob)
        assert _samples(b) == _samples(a)
        assert b.processors["span-metrics"].quantile(0.9) == \
            a.processors["span-metrics"].quantile(0.9)
        want = _samples(a)
    dense = _inst("pt")
    ck.restore_instance(dense, blob)
    assert dense.state_layout == "dense"
    assert _samples(dense) == want


def test_restore_merges_inflight_deltas_like_oracle():
    """The handoff window: receiver already took fresh spans, then
    merges the mover's checkpoint — equals an uninterrupted oracle
    (count kinds exactly; sums to f32 add order; dd quantiles exact)."""
    a = _inst()
    _push(a, 1)
    blob = ck.snapshot_instance(a)
    b = _inst()
    _push(b, 2)                          # in-flight deltas land FIRST
    ck.restore_instance(b, blob)         # then the moved state merges
    oracle = _inst()
    _push(oracle, 1)
    _push(oracle, 2)
    _assert_merge_equal(_samples(b), _samples(oracle))


def test_restore_rejects_mismatched_sketch_meta():
    """The ValueError-guarded merge checks refuse a checkpoint cut
    under different moments parameters BEFORE any row merges."""
    a = _inst(moments_k=8)
    _push(a, 1)
    blob = ck.snapshot_instance(a)
    b = _inst(moments_k=12)
    with pytest.raises(ValueError):
        b.processors["span-metrics"].sketch_meta_check(
            ck._decode(blob)[0]["spanmetrics"])
    # the full restore path refuses on the overrides fingerprint first
    with pytest.raises(ck.CheckpointMismatch):
        ck.restore_instance(b, blob)
    assert _samples(b) == {}             # nothing merged


def test_restore_rejects_changed_label_layout():
    cfg = _cfg()
    cfg.spanmetrics = SpanMetricsConfig(sketch="both",
                                        dimensions=("http.status",))
    a = GeneratorInstance("t1", cfg, now=lambda: NOW)
    _push(a, 1)
    blob = ck.snapshot_instance(a)
    with pytest.raises(ck.CheckpointMismatch):
        ck.restore_instance(_inst(), blob)


# ---------------------------------------------------------------------------
# placement + controller handoff (in-process fleet over one KVStore)
# ---------------------------------------------------------------------------


def _member(kv, be, iid):
    g = Generator(_cfg(), instance_id=iid, now=lambda: NOW)
    ring = Ring(kv=kv, key="generator", replication_factor=1,
                now=lambda: NOW)
    lc = Lifecycler(kv, iid, key="generator", now=lambda: NOW)
    fc = FleetController(g, ring, iid, be, be,
                         cfg=FleetConfig(enabled=True), now=lambda: NOW)
    return g, ring, lc, fc


def test_placement_agrees_across_members_and_spills_over():
    kv = KVStore()
    be = MemBackend()
    ga, ra, la, _ = _member(kv, be, "gen-a")
    gb, rb, lb, _ = _member(kv, be, "gen-b")
    pa = TenantPlacement(ra, "gen-a")
    pb = TenantPlacement(rb, "gen-b")
    tenants = [f"t{i}" for i in range(50)]
    for t in tenants:
        assert pa.owner(t).id == pb.owner(t).id          # views agree
    owned_a = {t for t in tenants if pa.owns(t)}
    owned_b = {t for t in tenants if pb.owns(t)}
    assert owned_a | owned_b == set(tenants)
    assert not (owned_a & owned_b)
    assert owned_a and owned_b                           # both got a share
    # spillover: a's descriptor goes stale → b owns everything
    la.leave()
    assert all(pb.owner(t).id == "gen-b" for t in tenants)
    assert tenant_token("t1") == tenant_token("t1")      # deterministic


def test_controller_handoff_and_restore_zero_loss():
    """Owner leaves → its controller drains + checkpoints + drops; the
    survivor's tick restores; post-handoff state (with fresh in-flight
    deltas) equals the uninterrupted oracle on count kinds exactly."""
    kv = KVStore()
    be = MemBackend()
    ga, ra, la, fa = _member(kv, be, "gen-a")
    gb, rb, lb, fb = _member(kv, be, "gen-b")
    tenant = "handoff-tenant"
    owner_is_a = TenantPlacement(ra, "gen-a").owns(tenant)
    g_own, lc_own, fc_own = (ga, la, fa) if owner_is_a else (gb, lb, fb)
    g_other, fc_other = (gb, fb) if owner_is_a else (ga, fa)

    g_own.push_spans(tenant, _spans(1))
    restores0 = STATS["restores"]
    lc_own.leave()
    fc_own.tick()                        # loss: drain + checkpoint + drop
    assert tenant not in g_own.tenants()
    fc_other.tick()                      # gain: restore + consume blob
    assert tenant in g_other.tenants()
    assert STATS["restores"] == restores0 + 1
    assert ck.list_checkpoints(be, "fleet-checkpoints") == {}  # consumed
    g_other.push_spans(tenant, _spans(2))   # post-handoff traffic

    oracle = Generator(_cfg(), instance_id="oracle", now=lambda: NOW)
    oracle.push_spans(tenant, _spans(1))
    oracle.push_spans(tenant, _spans(2))
    _assert_merge_equal(_samples(g_other.instance(tenant)),
                        _samples(oracle.instance(tenant)))
    # dd quantiles ride integer grids: bit-identical post-handoff
    assert g_other.instance(tenant).processors["span-metrics"] \
        .quantile(0.99) == \
        oracle.instance(tenant).processors["span-metrics"].quantile(0.99)
    st = fc_other.status()
    assert st["held_tenants"] == 1 and st["owned_tenants"] == 1


def test_shutdown_checkpoint_then_boot_restore():
    """Single-host restart without data loss: shutdown cuts blobs for
    every held tenant; a fresh controller with the same identity
    restores them on its boot tick."""
    kv = KVStore()
    be = MemBackend()
    g1, r1, lc1, fc1 = _member(kv, be, "gen-solo")
    g1.push_spans("ta", _spans(4))
    g1.push_spans("tb", _spans(5))
    want_a = _samples(g1.instance("ta"))
    want_b = _samples(g1.instance("tb"))
    fc1.shutdown()                       # writes shutdown checkpoints
    assert set(ck.list_checkpoints(be, "fleet-checkpoints")) == \
        {"ta", "tb"}
    # "restart": same identity, fresh generator, same backend + KV
    g2, r2, lc2, fc2 = _member(kv, be, "gen-solo")
    fc2.tick()
    assert _samples(g2.instance("ta")) == want_a
    assert _samples(g2.instance("tb")) == want_b
    assert ck.list_checkpoints(be, "fleet-checkpoints") == {}


def test_quarantine_on_poison_checkpoint():
    """An incompatible blob is skipped loudly and kept in the store —
    never deleted, never retried forever, never half-merged."""
    kv = KVStore()
    be = MemBackend()
    poison_src = _inst("tq", moments_k=8)
    _push(poison_src, 1)
    blob = ck.snapshot_instance(poison_src)
    name = ck.checkpoint_name(NOW, "gen-old")
    ck.write_checkpoint(be, "fleet-checkpoints", "tq", blob, name)
    g, r, lc, fc = _member(kv, be, "gen-q")   # moments_k=12 fleet
    fc.tick()
    assert _samples(g.instance("tq")) == {}   # nothing merged
    assert ck.list_checkpoints(be, "fleet-checkpoints") == {"tq": [name]}
    assert fc.status()["quarantined_checkpoints"] == [f"tq/{name}"]
    fc.tick()                                  # stays quarantined, no churn
    assert fc.status()["quarantined_checkpoints"] == [f"tq/{name}"]


def test_checkpoint_ships_only_referenced_strings():
    """The blob carries the strings the checkpointed keys reference, not
    the whole interner table — dead strings from churned series must not
    grow blobs and receiving interners monotonically across handoffs."""
    a = _inst()
    _push(a, 1)
    a.registry.interner.intern_many(
        [f"dead-string-{i}" for i in range(500)])
    blob = ck.snapshot_instance(a)
    meta, _arrays = ck._decode(blob)
    assert not any(s.startswith("dead-string-") for s in meta["strings"])
    b = _inst()
    ck.restore_instance(b, blob)
    assert _samples(b) == _samples(a)


def test_consumed_marker_prevents_replay():
    """A blob carrying a store-side consumed marker (a crashed deleter,
    or a peer whose stale ring view already merged it) is deleted
    WITHOUT restoring — a scatter-add replay would double-count every
    count-kind series."""
    kv = KVStore()
    be = MemBackend()
    src = _inst("tm")
    _push(src, 3)
    blob = ck.snapshot_instance(src)
    name = ck.checkpoint_name(NOW, "gen-dead")
    ck.write_checkpoint(be, "fleet-checkpoints", "tm", blob, name)
    ck.mark_consumed(be, "fleet-checkpoints", "tm", name)
    assert ck.is_consumed(be, "fleet-checkpoints", "tm", name)
    # markers are invisible to the blob listing
    assert ck.list_checkpoints(be, "fleet-checkpoints") == {"tm": [name]}
    g, _r, _lc, fc = _member(kv, be, "gen-m")
    restores0 = STATS["restores"]
    fc.tick()
    assert _samples(g.instance("tm")) == {}          # NOT merged
    assert STATS["restores"] == restores0
    assert ck.list_checkpoints(be, "fleet-checkpoints") == {}  # cleaned
    assert not ck.is_consumed(be, "fleet-checkpoints", "tm", name)


def test_remove_instance_releases_pool_pages():
    from tempo_tpu.registry import pages as pgs

    pool = pgs.PagePool(pgs.PagePoolConfig(enabled=True, page_rows=64,
                                           arena_slots=4096))
    with pgs.use(pool):
        g = Generator(_cfg(), instance_id="gen-p", now=lambda: NOW)
        g.push_spans("pp", _spans(6))
        assert g.instance("pp").state_layout == "paged"
        free_before = pool.free_pages()
        assert g.remove_instance("pp") is not None
        assert g.tenants() == []
        assert pool.free_pages() > free_before
        assert pool.free_pages() == pool.total_pages()


# ---------------------------------------------------------------------------
# real child process: worker spawn/reap plumbing (conftest fixture)
# ---------------------------------------------------------------------------


def test_fleet_worker_process_spawn_and_reap(fleet_procs, tmp_path):
    """One real fleet member process: comes up ready, serves /status
    with the fleet + rings blocks, dies cleanly on terminate. The
    fixture guarantees the reap even if the asserts fail."""
    import json
    import socket
    import urllib.request

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg = tmp_path / "member.yaml"
    cfg.write_text(f"""
target: metrics-generator
server: {{http_listen_port: {port}}}
ring_kv_url: local
storage:
  backend: local
  local_path: {tmp_path}/blocks
  wal_path: {tmp_path}/wal
fleet: {{enabled: true, rebalance_interval_s: 0.5}}
distributor: {{generator_placement: tenant}}
generator:
  processors: [span-metrics]
  spanmetrics: {{sketch: moments}}
""")
    p = fleet_procs(["--config", str(cfg)])
    assert p.ready["port"] == port
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/status",
                                timeout=10) as r:
        st = json.loads(r.read())
    assert st["fleet"] is not None
    assert st["fleet"]["instance"].startswith("generator")
    assert "generator" in st["rings"]
    members = st["rings"]["generator"]["members"]
    assert len(members) == 1 and members[0]["ownership_ratio"] == 1.0
    p.terminate()
    assert p.wait(timeout=15) is not None


def test_sigkill_restart_replays_wal_bit_identically(fleet_procs,
                                                     tmp_path):
    """The SIGKILL variant of the worker handoff test: kill -9 a member
    (no drain, no shutdown checkpoint), restart it over the same dirs,
    and assert the ingest-WAL replay restores every ACKED push —
    collect() and quantile() bit-identical to an uninterrupted in-process
    oracle fed the same payloads."""
    import json
    import socket
    import urllib.request

    import numpy as np

    from tempo_tpu.generator.generator import Generator
    from tempo_tpu.model.otlp import encode_spans_otlp
    from tempo_tpu.overrides import Overrides
    from tempo_tpu.overrides.limits import Limits
    from tempo_tpu.rpc import RemoteGeneratorClient

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg = tmp_path / "member.yaml"
    cfg.write_text(f"""
target: metrics-generator
server: {{http_listen_port: {port}}}
ring_kv_url: local
usage_stats_enabled: false
storage:
  backend: local
  local_path: {tmp_path}/blocks
  wal_path: {tmp_path}/wal
wal: {{enabled: true, dir: {tmp_path}/gwal}}
fleet: {{enabled: true, rebalance_interval_s: 5.0}}
distributor: {{generator_placement: tenant}}
generator:
  processors: [span-metrics]
overrides_defaults:
  generator:
    processors: [span-metrics]
    max_active_series: 2048
    ingestion_time_range_slack_s: 0.0
    collection_interval_s: 3600.0
    sketch: dd
""")
    rng = np.random.default_rng(11)
    now_ns = int(NOW * 1e9)
    payloads = [encode_spans_otlp([
        dict(trace_id=rng.bytes(16), span_id=rng.bytes(8),
             name=f"op-{i % 4}", service=f"svc-{i % 3}", kind=2,
             status_code=0, start_unix_nano=now_ns,
             end_unix_nano=now_ns + int(rng.integers(1, 5e8)))
        for i in range(24)]) for _ in range(3)]

    p = fleet_procs(["--config", str(cfg)])
    client = RemoteGeneratorClient(f"http://127.0.0.1:{port}",
                                   timeout_s=30.0)
    for pl in payloads:
        assert client.push_otlp("t1", pl) == 24
    p.kill()                             # SIGKILL: nothing drains
    assert p.wait(timeout=10) is not None

    p2 = fleet_procs(["--config", str(cfg)])   # same dirs, same WAL
    req = urllib.request.Request(
        f"http://127.0.0.1:{p2.ready['port']}"
        "/internal/generator/collect?ts_ms=1",
        headers={"X-Scope-OrgID": "t1"})
    doc = json.loads(urllib.request.urlopen(req, timeout=30).read())
    got = {(s["name"], tuple(tuple(kv) for kv in s["labels"])):
           s["value"] for s in doc["samples"]}
    req = urllib.request.Request(
        f"http://127.0.0.1:{p2.ready['port']}"
        "/internal/generator/quantile?q=0.99",
        headers={"X-Scope-OrgID": "t1"})
    qdoc = json.loads(urllib.request.urlopen(req, timeout=30).read())
    got_q = {tuple(tuple(kv) for kv in e["labels"]): e["value"]
             for e in qdoc["quantiles"]}

    lim = Limits()
    lim.generator.processors = ("span-metrics",)
    lim.generator.max_active_series = 2048
    lim.generator.ingestion_time_range_slack_s = 0.0
    lim.generator.collection_interval_s = 3600.0
    lim.generator.sketch = "dd"
    oracle = Generator(GeneratorConfig(), instance_id="oracle",
                       overrides=Overrides(defaults=lim))
    for pl in payloads:
        oracle.push_otlp("t1", pl)
    inst = oracle.instance("t1")
    inst.drain()
    want = {(s.name, tuple(s.labels)): s.value
            for s in inst.registry.collect(ts_ms=1)
            if not s.is_stale_marker}
    _assert_merge_equal(got, want)
    want_q = {tuple(k): v for k, v in
              inst.processors["span-metrics"].quantile(0.99).items()}
    assert got_q == want_q


def test_kv_only_worker(fleet_procs):
    """The standalone /kv CAS server speaks the RemoteKVStore wire."""
    from tempo_tpu.ring.kv import RemoteKVStore

    p = fleet_procs(["--kv-only"])
    kv = RemoteKVStore(f"http://127.0.0.1:{p.ready['port']}",
                       poll_interval_s=0.05)
    try:
        assert kv.get("nope") is None
        kv.cas("k", lambda cur: {"v": (cur or {}).get("v", 0) + 1})
        kv.cas("k", lambda cur: {"v": cur["v"] + 1})
        assert kv.get("k") == {"v": 2}
        kv.delete("k")
        assert kv.get("k") is None
        # a Lifecycler round-trips ring descs through it
        lc = Lifecycler(kv, "gen-remote", n_tokens=8, now=lambda: NOW)
        ring = Ring(kv=kv, key="ring", replication_factor=1,
                    now=lambda: NOW)
        assert ring.owner_of("x").id == "gen-remote"
        lc.leave()
        assert kv.get("ring") == {}
    finally:
        kv.shutdown()
