"""Device-execution scheduler (tempo_tpu.sched) semantics.

Covers the ISSUE's scheduler contract: cross-tenant merge correctness
vs. unbatched results, priority ordering, deadline- and occupancy-based
batch close, shed accounting, backpressure propagation (distributor 429
+ Retry-After, frontend query shedding), zero steady-state jit
recompiles through the shape-bucket cache, and bit-identical
disabled-scheduler fallback.
"""

import threading
import time

import numpy as np
import pytest

from tempo_tpu import sched
from tempo_tpu.sched import (
    PRIO_COMPACTION,
    PRIO_INGEST,
    PRIO_QUERY,
    DeviceScheduler,
    SchedConfig,
    bucket_rows,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _manual(cfg=None, now=None):
    """A scheduler driven by hand (no worker thread)."""
    return DeviceScheduler(cfg or SchedConfig(), now=now or time.monotonic,
                           start_worker=False)


# ---------------------------------------------------------------------------
# coalescer mechanics
# ---------------------------------------------------------------------------


def test_bucket_rows_pow2():
    assert bucket_rows(1) == 64
    assert bucket_rows(64) == 64
    assert bucket_rows(65) == 128
    assert bucket_rows(300) == 512
    assert bucket_rows(300, hi=256) == 256


def test_coalesce_merges_same_key_into_one_padded_tensor():
    sc = _manual()
    got = []

    def dispatch(slots, w):
        got.append((slots.copy(), w.copy()))

    for base in (0, 10, 20):
        sc.submit_rows("k", "state-a",
                       (np.arange(base, base + 5, dtype=np.int32),
                        np.full(5, 2.0, np.float32)), 5, dispatch,
                       pads=(-1, 0.0))
    sc.drain_once(force=True)
    assert len(got) == 1                       # three jobs, ONE dispatch
    slots, w = got[0]
    assert slots.shape == (64,)                # pow-2 bucket, min 64
    np.testing.assert_array_equal(
        slots[:15], np.concatenate([np.arange(b, b + 5) for b in
                                    (0, 10, 20)]))
    assert (slots[15:] == -1).all()            # padding rows drop on device
    assert (w[15:] == 0.0).all()
    assert sc.batches_total["k"] == 1
    assert sc.coalesced_total["k"] == 3
    assert sc.mean_occupancy("k") == pytest.approx(15 / 64)
    # waste: (64-15) rows * (4B slots + 4B weights)
    assert sc.padding_waste_bytes["k"] == (64 - 15) * 8


def test_pack_mode_ships_one_matrix_per_batch():
    """pack=True coalesces all roles into ONE row-major f32 matrix
    [n_roles, bucket] — the single-H2D dispatch shape — with per-role
    pad values on the padding columns."""
    sc = _manual()
    got = []
    for base in (0, 100):
        sc.submit_rows("k", "m",
                       (np.arange(base, base + 5, dtype=np.float32),
                        np.full(5, 2.5, np.float32)), 5,
                       lambda mat: got.append(mat.copy()),
                       pads=(-1.0, 0.0), pack=True)
    sc.drain_once(force=True)
    assert len(got) == 1
    mat = got[0]
    assert mat.shape == (2, 64) and mat.dtype == np.float32
    np.testing.assert_array_equal(
        mat[0, :10], np.concatenate([np.arange(0, 5), np.arange(100, 105)]))
    assert (mat[0, 10:] == -1.0).all() and (mat[1, 10:] == 0.0).all()
    assert (mat[1, :10] == 2.5).all()


def test_spanmetrics_packed_sched_route_matches_direct():
    """The production packed-coalescer route (slots riding f32 under the
    capacity < 2^24 gate) must reproduce the direct dispatch exactly."""
    sc = DeviceScheduler(SchedConfig(batch_window_ms=50.0),
                         start_worker=True)
    reg, proc = _mk_proc()
    ref, proc_ref = _mk_proc(use_scheduler=False)
    assert proc.calls.table.capacity < (1 << 24)   # the packed gate holds
    batches = [_spans_for("t", 48, seed=i) for i in range(4)]
    with sched.use(sc):
        for b in batches:
            _push_spans(proc, reg, b)
        sc.flush()
    for b in batches:
        _push_spans(proc_ref, ref, b)
    np.testing.assert_array_equal(np.asarray(proc.calls.state.values),
                                  np.asarray(proc_ref.calls.state.values))
    np.testing.assert_array_equal(np.asarray(proc.dd.counts),
                                  np.asarray(proc_ref.dd.counts))
    sc.stop()


def test_distinct_merge_keys_do_not_merge():
    sc = _manual()
    calls = {"a": 0, "b": 0}

    def mk(key):
        def dispatch(slots):
            calls[key] += 1
        return dispatch

    da, db = mk("a"), mk("b")
    sc.submit_rows("k", "a", (np.zeros(4, np.int32),), 4, da, pads=(-1,))
    sc.submit_rows("k", "b", (np.zeros(4, np.int32),), 4, db, pads=(-1,))
    sc.submit_rows("k", "a", (np.zeros(4, np.int32),), 4, da, pads=(-1,))
    sc.drain_once(force=True)
    assert calls == {"a": 1, "b": 1}           # no cross-state bleed
    assert sc.coalesced_total["k"] == 3 and sc.batches_total["k"] == 2


def test_max_batch_rows_chunks_oversized_groups():
    sc = _manual(SchedConfig(max_batch_rows=128, min_bucket_rows=64))
    seen = []
    for _ in range(4):
        sc.submit_rows("k", "m", (np.zeros(100, np.int32),), 100,
                       lambda slots: seen.append(len(slots)), pads=(-1,))
    sc.drain_once(force=True)
    # 4 x 100 rows with a 128-row cap → 4 dispatches of one job each
    assert len(seen) == 4 and all(s == 128 for s in seen)


# ---------------------------------------------------------------------------
# batch-close policy: occupancy target or deadline, whichever first
# ---------------------------------------------------------------------------


def test_deadline_based_batch_close():
    clock = FakeClock()
    sc = _manual(SchedConfig(batch_window_ms=10.0, occupancy_target=1.0,
                             max_batch_rows=1 << 20), now=clock)
    done = []
    sc.submit_rows("k", "m", (np.zeros(8, np.int32),), 8,
                   lambda s: done.append(1), pads=(-1,))
    sc.drain_once()                            # window still open
    assert not done and sc.pending() == 1
    clock.t += 0.005
    sc.drain_once()                            # 5ms < 10ms: still open
    assert not done
    clock.t += 0.006                           # 11ms total: deadline hit
    sc.drain_once()
    assert done and sc.pending() == 0


def test_occupancy_target_closes_before_deadline():
    clock = FakeClock()
    sc = _manual(SchedConfig(batch_window_ms=10_000.0, occupancy_target=0.5,
                             max_batch_rows=1000), now=clock)
    done = []
    sc.submit_rows("k", "m", (np.zeros(100, np.int32),), 100,
                   lambda s: done.append(1), pads=(-1,))
    sc.drain_once()
    assert not done                            # 100 < 500 target rows
    sc.submit_rows("k", "m", (np.zeros(450, np.int32),), 450,
                   lambda s: done.append(1), pads=(-1,))
    sc.drain_once()                            # 550 >= 0.5 * 1000: close now
    assert done and sc.pending() == 0


# ---------------------------------------------------------------------------
# priority ordering + shed accounting
# ---------------------------------------------------------------------------


def test_priority_ordering_ingest_query_compaction():
    clock = FakeClock()
    sc = _manual(SchedConfig(batch_window_ms=0.0), now=clock)
    order = []
    results = []

    def submit_fn(tag, prio):
        job = sched.Job(priority=prio, kernel=tag,
                        fn=lambda: order.append(tag))
        with sc._cond:
            sc._queues[prio].append(job)
        results.append(job)

    submit_fn("compaction", PRIO_COMPACTION)
    submit_fn("query", PRIO_QUERY)
    sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4,
                   lambda s: order.append("ingest"), pads=(-1,))
    sc.drain_once()
    # compaction is deferred while better work exists…
    assert order == ["ingest", "query"]
    sc.drain_once()
    assert order == ["ingest", "query", "compaction"]


def test_query_jobs_never_wait_on_ingest_window():
    clock = FakeClock()
    sc = _manual(SchedConfig(batch_window_ms=10_000.0), now=clock)
    order = []
    sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4,
                   lambda s: order.append("ingest"), pads=(-1,))
    job = sched.Job(priority=PRIO_QUERY, kernel="q",
                    fn=lambda: order.append("query"))
    with sc._cond:
        sc._queues[PRIO_QUERY].append(job)
    sc.drain_once()
    assert order == ["query"]                  # window keeps ingest open


def test_shed_accounting_inline_execution():
    sc = _manual(SchedConfig(max_queue_ingest=2))
    dispatched_rows = []

    def dispatch(slots):
        dispatched_rows.append(int((slots >= 0).sum()))

    for _ in range(4):
        sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4, dispatch,
                       pads=(-1,))
    # two queued, two shed to inline dispatch (data is never dropped)
    assert sc.shed_total["ingest"] == 2
    assert dispatched_rows == [4, 4]           # the shed pair, one each
    sc.drain_once(force=True)
    # the queued pair merged into ONE dispatch carrying both jobs' rows
    assert dispatched_rows == [4, 4, 8]
    assert sc.jobs_total["ingest"] == 2


def test_run_sheds_inline_when_query_queue_full():
    sc = _manual(SchedConfig(max_queue_query=1))
    blocker = sched.Job(priority=PRIO_QUERY, kernel="q", fn=lambda: None)
    with sc._cond:
        sc._queues[PRIO_QUERY].append(blocker)
    out = sc.run(lambda: "inline")
    assert out == "inline"
    assert sc.shed_total["query"] == 1


def test_run_inline_when_idle_and_queued_when_busy():
    sc = _manual()
    assert sc.run(lambda: 7) == 7              # idle → inline, zero latency
    assert sc.jobs_total["query"] == 1
    sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4,
                   lambda s: None, pads=(-1,))
    done = {}

    def runner():
        done["v"] = sc.run(lambda: 9)

    t = threading.Thread(target=runner)
    t.start()
    deadline = time.monotonic() + 2.0
    while not sc._queues[PRIO_QUERY] and time.monotonic() < deadline:
        time.sleep(0.001)
    assert sc._queues[PRIO_QUERY], "busy scheduler should queue the job"
    sc.drain_once(force=True)
    t.join(2.0)
    assert done["v"] == 9


def test_flush_from_inside_a_dispatched_job_does_not_deadlock():
    """A scheduled job may itself need queued updates drained (e.g. a
    read that flushes sketch batches first): the nested flush drains
    queued work on the same thread instead of self-blocking."""
    sc = _manual(SchedConfig(batch_window_ms=60_000.0))
    seen = []

    def inner_dispatch(slots):
        seen.append("ingest")

    def outer():
        sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4,
                       inner_dispatch, pads=(-1,))
        sc.flush(timeout=2.0)              # nested: must not hang
        seen.append("outer-done")

    job = sched.Job(priority=PRIO_QUERY, kernel="q", fn=outer)
    with sc._cond:
        sc._queues[PRIO_QUERY].append(job)
    sc.drain_once(force=True)
    job.wait(2.0)
    assert seen == ["ingest", "outer-done"]


def test_dispatch_error_propagates_to_run_caller():
    sc = _manual()
    sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4,
                   lambda s: None, pads=(-1,))

    def boom():
        raise RuntimeError("kernel exploded")

    job = sched.Job(priority=PRIO_QUERY, kernel="q", fn=boom)
    with sc._cond:
        sc._queues[PRIO_QUERY].append(job)
    sc.drain_once(force=True)
    with pytest.raises(RuntimeError, match="kernel exploded"):
        job.wait(1.0)
    # fn-job errors belong to their waiting caller; dispatch_errors
    # counts only fire-and-forget ingest batches that were dropped
    assert sc.dispatch_errors == 0


def test_ingest_dispatch_error_is_counted():
    """Fire-and-forget ingest batches have no waiting caller: a failed
    dispatch must increment tempo_sched_dispatch_errors_total (and log)
    instead of vanishing."""
    sc = _manual()

    def bad_dispatch(slots):
        raise RuntimeError("scatter failed")

    job = sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4,
                         bad_dispatch, pads=(-1,))
    sc.drain_once(force=True)
    assert sc.dispatch_errors == 1
    with pytest.raises(RuntimeError, match="scatter failed"):
        job.wait(1.0)


# ---------------------------------------------------------------------------
# backpressure propagation
# ---------------------------------------------------------------------------


def _mini_distributor(now):
    from tempo_tpu.distributor import Distributor
    from tempo_tpu.overrides import Overrides
    from tempo_tpu.ring import ACTIVE, InstanceDesc, Ring
    from tempo_tpu.ring.ring import _instance_tokens

    class _NullIng:
        def push(self, tenant, traces):
            return [None] * len(traces)

        def push_otlp(self, tenant, payload):
            return {}

    ring = Ring(replication_factor=1, now=now)
    ring.register(InstanceDesc(id="i0", state=ACTIVE,
                               tokens=_instance_tokens("i0", 64),
                               heartbeat_ts=now()))
    ov = Overrides()
    ov.set_tenant_patch("t", {"ingestion": {"rate_limit_bytes": 1 << 40,
                                            "burst_size_bytes": 1 << 40}})
    return Distributor(ring, {"i0": _NullIng()}, overrides=ov, now=now)


def test_distributor_rejects_429_when_ingest_saturated():
    from tempo_tpu.distributor.distributor import (REASON_BACKPRESSURE,
                                                   RateLimited)

    now = FakeClock()
    sc = _manual(SchedConfig(max_queue_ingest=1, retry_after_s=3.0))
    sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4, lambda s: None,
                   pads=(-1,))
    assert sc.ingest_saturated()
    with sched.use(sc):
        d = _mini_distributor(now)
        spans = [{"trace_id": bytes([7]) * 16, "span_id": b"x" * 8,
                  "name": "op", "service": "s",
                  "start_unix_nano": 1, "end_unix_nano": 2}]
        with pytest.raises(RateLimited) as ei:
            d.push_spans("t", spans)
        assert ei.value.retry_after_s == 3.0
        assert ei.value.reason == REASON_BACKPRESSURE
        assert d.discarded.get(REASON_BACKPRESSURE) == 1
    # queue drained → admitted again
    sc.drain_once(force=True)
    with sched.use(sc):
        assert d.push_spans("t", spans) == {}


def test_backpressure_hook_injectable():
    from tempo_tpu.distributor.limiter import IngestBackpressure

    bp = IngestBackpressure(retry_after_fn=lambda: 2.5)
    assert bp.retry_after() == 2.5
    assert IngestBackpressure(lambda: None).retry_after() is None
    # default hook with no scheduler configured admits everything
    with sched.use(None):
        assert IngestBackpressure().retry_after() is None


def test_frontend_sheds_queries_when_query_class_saturated():
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db.tempodb import TempoDB
    from tempo_tpu.frontend import Frontend
    from tempo_tpu.querier import Querier
    from tempo_tpu.ring import Ring
    from tempo_tpu.sched import QueryBackpressure

    be = MemBackend()
    db = TempoDB(be, be)
    fe = Frontend(db, Querier(db, Ring(replication_factor=1), {}))
    sc = _manual(SchedConfig(max_queue_query=1, retry_after_s=2.0))
    blocker = sched.Job(priority=PRIO_QUERY, kernel="q", fn=lambda: None)
    with sc._cond:
        sc._queues[PRIO_QUERY].append(blocker)
    try:
        with sched.use(sc):
            with pytest.raises(QueryBackpressure) as ei:
                fe.search("t", "{ }")
            assert ei.value.retry_after_s == 2.0
            sc.drain_once(force=True)
            assert fe.search("t", "{ }") == []     # drained → admitted
    finally:
        fe.shutdown()
        db.shutdown()


# ---------------------------------------------------------------------------
# write-path integration: merge correctness, fallback parity, recompiles
# ---------------------------------------------------------------------------


def _push_spans(proc, reg, spans):
    from tests.test_generator import _mk_batch

    proc.push_batch(_mk_batch(spans, interner=reg.interner))


def _mk_proc(use_scheduler=True):
    from tempo_tpu.generator.processors.spanmetrics import (
        SpanMetricsConfig, SpanMetricsProcessor)
    from tempo_tpu.registry import ManagedRegistry

    reg = ManagedRegistry(now=FakeClock())
    proc = SpanMetricsProcessor(
        reg, SpanMetricsConfig(use_scheduler=use_scheduler))
    return reg, proc


def _spans_for(tenant_tag, n, seed):
    from tests.test_generator import _span

    rng = np.random.default_rng(seed)
    return [_span(1 + (i % 200), service=f"{tenant_tag}-svc-{i % 3}",
                  name=f"op-{i % 7}",
                  dur_ns=int(rng.integers(10**6, 10**10)))
            for i in range(n)]


def test_cross_tenant_merge_matches_unbatched_results():
    """Interleaved small pushes from two tenants through ONE scheduler
    must leave each tenant's device state equal to direct, unbatched
    dispatch — cross-tenant coalescing can amortize dispatch but never
    bleed state or drop rows (counts are exact integer adds in f32; the
    f32 latency sums only change accumulation order → allclose)."""
    sc = DeviceScheduler(SchedConfig(batch_window_ms=50.0),
                         start_worker=True)
    rega, proca = _mk_proc()
    regb, procb = _mk_proc()
    ref_a, proc_ref_a = _mk_proc(use_scheduler=False)
    ref_b, proc_ref_b = _mk_proc(use_scheduler=False)
    batches_a = [_spans_for("a", 40, seed=i) for i in range(6)]
    batches_b = [_spans_for("b", 40, seed=100 + i) for i in range(6)]
    with sched.use(sc):
        for sa, sb_ in zip(batches_a, batches_b):
            _push_spans(proca, rega, sa)
            _push_spans(procb, regb, sb_)
        sc.flush()
    for sa, sb_ in zip(batches_a, batches_b):
        _push_spans(proc_ref_a, ref_a, sa)
        _push_spans(proc_ref_b, ref_b, sb_)
    for proc, ref_proc in ((proca, proc_ref_a), (procb, proc_ref_b)):
        np.testing.assert_array_equal(
            np.asarray(proc.calls.state.values),
            np.asarray(ref_proc.calls.state.values))
        np.testing.assert_array_equal(
            np.asarray(proc.latency.state.bucket_counts),
            np.asarray(ref_proc.latency.state.bucket_counts))
        np.testing.assert_array_equal(np.asarray(proc.dd.counts),
                                      np.asarray(ref_proc.dd.counts))
        np.testing.assert_allclose(np.asarray(proc.latency.state.sums),
                                   np.asarray(ref_proc.latency.state.sums),
                                   rtol=1e-5, atol=1e-4)
    # the two tenants really did share batches through one scheduler
    assert sc.coalesced_total["spanmetrics_fused_update"] >= 12
    sc.stop()


def test_disabled_scheduler_fallback_bit_identical():
    """`use_scheduler=False` (or no configured scheduler) must take the
    untouched direct dispatch: states are BIT-identical, not just close."""
    sc = DeviceScheduler(SchedConfig(), start_worker=False)
    reg_off, proc_off = _mk_proc(use_scheduler=False)
    reg_none, proc_none = _mk_proc(use_scheduler=True)
    spans = [_spans_for("t", 64, seed=i) for i in range(3)]
    with sched.use(sc):
        for s in spans:                    # flag off, scheduler present
            _push_spans(proc_off, reg_off, s)
    with sched.use(None):
        for s in spans:                    # flag on, no scheduler
            _push_spans(proc_none, reg_none, s)
    np.testing.assert_array_equal(np.asarray(proc_off.calls.state.values),
                                  np.asarray(proc_none.calls.state.values))
    np.testing.assert_array_equal(np.asarray(proc_off.latency.state.sums),
                                  np.asarray(proc_none.latency.state.sums))
    np.testing.assert_array_equal(np.asarray(proc_off.dd.counts),
                                  np.asarray(proc_none.dd.counts))
    assert sc.jobs_total["ingest"] == 0    # nothing ever rode the scheduler


def test_zero_recompiles_after_warmup():
    """The shape-bucket cache satellite: steady-state scheduler traffic of
    VARYING caller batch sizes must trace each pow-2 bucket once and then
    never again — the obs compile counter stays flat."""
    from tempo_tpu.obs.jaxruntime import JIT_COMPILES

    sc = DeviceScheduler(SchedConfig(batch_window_ms=0.0),
                         start_worker=False)
    reg, proc = _mk_proc()
    label = ("spanmetrics_fused_update",)
    with sched.use(sc):
        for i in range(4):                 # warmup: buckets trace here
            _push_spans(proc, reg, _spans_for("t", 30 + 17 * i, seed=i))
            sc.drain_once(force=True)
        warm = JIT_COMPILES.value(label)
        warm_buckets = dict(sc.bucket_warmups)
        for i in range(8):                 # steady state: varying sizes
            _push_spans(proc, reg, _spans_for("t", 25 + 13 * i, seed=50 + i))
            sc.drain_once(force=True)
        assert JIT_COMPILES.value(label) == warm
        assert sc.bucket_warmups == warm_buckets


def test_collect_flushes_queued_batches():
    """A collection tick must see updates that were accepted before it
    (the instance wiring flushes the scheduler before purge+collect)."""
    from tests.test_generator import _span, series_value

    from tempo_tpu.generator.instance import (GeneratorConfig,
                                              GeneratorInstance)

    sc = DeviceScheduler(SchedConfig(batch_window_ms=60_000.0),
                         start_worker=False)
    with sched.use(sc):
        inst = GeneratorInstance("t", GeneratorConfig(
            processors=("span-metrics",)), now=FakeClock())
        from tests.test_generator import _mk_batch
        inst.push_batch(_mk_batch(
            [_span(1, service="s", name="op", start=10**12)],
            interner=inst.registry.interner))
        assert sc.pending() == 1           # queued, window far away
        inst.collect_and_push(ts_ms=1)
        assert sc.pending() == 0
        samples = inst.registry.collect(ts_ms=2)
        assert series_value(samples, "traces_spanmetrics_calls_total",
                            service="s", span_name="op") == 1.0


# ---------------------------------------------------------------------------
# read path: query stats threading + scheduler routing
# ---------------------------------------------------------------------------


def test_run_threads_query_stats_into_scheduled_jobs():
    from tempo_tpu.obs import querystats

    sc = _manual()
    sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4, lambda s: None,
                   pads=(-1,))               # make the scheduler non-idle
    with querystats.scope() as st:
        job = None

        def runner():
            with querystats.scope(st):
                sc.run(lambda: querystats.add(inspected_spans=5),
                       kernel="test_kernel")

        t = threading.Thread(target=runner)
        t.start()
        deadline = time.monotonic() + 2.0
        while not sc._queues[PRIO_QUERY] and time.monotonic() < deadline:
            time.sleep(0.001)
        sc.drain_once(force=True)
        t.join(2.0)
    assert st.sched_jobs == 1
    assert st.inspected_spans == 5          # recorded ON the worker thread
    assert st.stage_ns.get("sched_wait", 0) >= 0


def test_read_plane_routes_through_scheduler():
    """BlockScanPlane masks ride the scheduler's query class and still
    produce the same mask bits."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.block.device_scan import BlockScanPlane
    from tempo_tpu.block.fetch import condition_mask, scan_views
    from tempo_tpu.block.reader import BackendBlock
    from tempo_tpu.db.tempodb import TempoDB
    from tempo_tpu.traceql.conditions import extract_conditions
    from tempo_tpu.traceql.parser import parse

    rng = np.random.default_rng(7)
    be = MemBackend()
    db = TempoDB(be, be)
    traces = []
    for i in range(200):
        tid = rng.bytes(16)
        start = int((1_700_000_000 + i) * 1e9)
        traces.append((tid, [{
            "trace_id": tid, "span_id": rng.bytes(8),
            "name": f"op-{i % 5}", "service": f"svc-{i % 3}",
            "start_unix_nano": start,
            "end_unix_nano": start + 10**7}]))
    db.write_block("t", traces, replication_factor=1)
    db.poll_now()
    views = [v for m in db.blocklist.metas("t")
             for v, _ in scan_views(BackendBlock(db.r, m))]
    db.shutdown()
    req = extract_conditions(parse('{ name = "op-1" }'))
    preds = [c for c in req.conditions if c.op is not None]
    plane = BlockScanPlane(views)
    direct = plane.mask(preds, req.all_conditions)
    sc = DeviceScheduler(SchedConfig(), start_worker=True)
    with sched.use(sc):
        routed = plane.mask(preds, req.all_conditions)
    sc.stop()
    np.testing.assert_array_equal(direct, routed)
    want = np.concatenate([condition_mask(v, req) for v in views])
    np.testing.assert_array_equal(routed, want)
    assert sc.jobs_total["query"] >= 1


def test_obs_families_render_for_default_scheduler():
    """The sched metric families render on the process runtime registry
    (the drift gate's ground truth for dashboards/alerts)."""
    from tempo_tpu.obs.jaxruntime import RUNTIME
    from tempo_tpu.obs.registry import parse_exposition

    sc = sched.configure(SchedConfig(batch_window_ms=0.0))
    try:
        sc.submit_rows("k", "m", (np.zeros(4, np.int32),), 4,
                       lambda s: None, pads=(-1,))
        sc.flush()
        fams = parse_exposition(RUNTIME.render())
        for name in ("tempo_sched_queue_depth", "tempo_sched_queue_limit",
                     "tempo_sched_jobs_total", "tempo_sched_shed_jobs_total",
                     "tempo_sched_batches_total",
                     "tempo_sched_coalesced_jobs_total",
                     "tempo_sched_padding_waste_bytes_total",
                     "tempo_sched_bucket_warmups_total",
                     "tempo_sched_batch_occupancy_ratio",
                     "tempo_sched_dispatch_duration_seconds",
                     "tempo_sched_queue_wait_seconds"):
            assert name in fams, name
        key = ("tempo_sched_jobs_total", (("class", "ingest"),))
        assert fams["tempo_sched_jobs_total"]["samples"][key] >= 1.0
    finally:
        sched.reset()
