"""Write path: distributor → ring RF3 → ingester → WAL → block → flush."""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.distributor import Distributor, DistributorConfig
from tempo_tpu.distributor.distributor import (
    REASON_INVALID_TRACE_ID,
    RateLimited,
)
from tempo_tpu.ingester import Ingester, IngesterConfig
from tempo_tpu.ingester.instance import InstanceConfig
from tempo_tpu.overrides import Overrides
from tempo_tpu.ring import ACTIVE, InstanceDesc, Ring
from tempo_tpu.ring.ring import _instance_tokens


def mkspan(tid: bytes, sid: bytes, name="op", svc="svc", t0=10**18,
           dur=1_000_000, **kw):
    return {"trace_id": tid, "span_id": sid, "name": name, "service": svc,
            "start_unix_nano": t0, "end_unix_nano": t0 + dur, **kw}


def make_clock():
    t = [1000.0]
    def now():
        return t[0]
    return t, now


@pytest.fixture
def rig(tmp_path):
    """3 ingesters on a ring + 1 distributor, manual clock."""
    t, now = make_clock()
    cfg = IngesterConfig(
        instance=InstanceConfig(trace_idle_s=2.0, trace_live_s=10.0,
                                max_block_duration_s=30.0))
    backend = MemBackend()
    ring = Ring(replication_factor=3, now=now)
    ingesters = {}
    for i in range(3):
        ing = Ingester(str(tmp_path / f"ing{i}"), flush_writer=backend,
                       cfg=cfg, now=now, instance_id=f"ing-{i}")
        ingesters[f"ing-{i}"] = ing
        ring.register(InstanceDesc(id=f"ing-{i}", state=ACTIVE,
                                   tokens=_instance_tokens(f"ing-{i}", 64),
                                   heartbeat_ts=now()))
    dist = Distributor(ring, ingesters, cfg=DistributorConfig(rf=3), now=now)
    return t, now, backend, ring, ingesters, dist


def test_rf3_replication(rig):
    t, now, backend, ring, ingesters, dist = rig
    spans = [mkspan(bytes([i]) * 16, bytes([j]) * 8)
             for i in range(1, 11) for j in range(1, 4)]
    errs = dist.push_spans("t1", spans)
    assert errs == {}
    # every trace lands on all 3 ingesters (RF3 over 3 instances)
    for ing in ingesters.values():
        inst = ing.instance("t1")
        assert len(inst.live) == 10
    # spans grouped per trace
    inst = ingesters["ing-0"].instance("t1")
    assert len(inst.live.traces[bytes([1]) * 16].spans) == 3


def test_invalid_trace_id_discarded(rig):
    *_, dist = rig
    errs = dist.push_spans("t1", [mkspan(b"", b"\x01" * 8)])
    assert errs[REASON_INVALID_TRACE_ID] == 1


def test_rate_limit(rig):
    t, now, backend, ring, ingesters, dist = rig
    dist.overrides = Overrides()
    dist.overrides.set_tenant_patch(
        "t1", {"ingestion": {"rate_limit_bytes": 100, "burst_size_bytes": 300}})
    spans = [mkspan(bytes([i]) * 16, b"\x01" * 8) for i in range(1, 9)]
    with pytest.raises(RateLimited):
        dist.push_spans("t1", spans)   # ~1600B > 300B burst
    # refill after time passes
    t[0] += 10.0
    assert dist.push_spans("t1", spans[:1]) == {}


def test_quorum_survives_one_ingester_down(rig):
    t, now, backend, ring, ingesters, dist = rig

    class Down:
        def push(self, tenant, traces):
            raise RuntimeError("down")

    dist.ingester_clients = dict(ingesters)
    dist.ingester_clients["ing-1"] = Down()
    errs = dist.push_spans("t1", [mkspan(b"\x05" * 16, b"\x01" * 8)])
    assert errs == {}
    assert dist.metrics["traces_pushed_total"] == 1


def test_cut_complete_flush_cycle(rig, tmp_path):
    t, now, backend, ring, ingesters, dist = rig
    spans = [mkspan(bytes([i]) * 16, bytes([j]) * 8)
             for i in range(1, 6) for j in range(1, 3)]
    dist.push_spans("t1", spans)
    ing = ingesters["ing-0"]
    # nothing idle yet
    ing.sweep_instance("t1")
    assert ing.instance("t1").head is None
    # idle out the traces → head block
    t[0] += 5.0
    ing.sweep_instance("t1")
    inst = ing.instance("t1")
    assert len(inst.live) == 0
    assert inst.head is not None
    # age the block → seal + complete + flush
    t[0] += 31.0
    ing.sweep_instance("t1")
    assert inst.head is None
    n = ing.flush_tick()
    assert n >= 1
    ing.flush_tick()
    assert len(inst.complete) == 1
    meta = next(iter(inst.complete.values())).meta
    assert meta.total_objects == 5
    # flushed to object storage: meta + data present
    from tempo_tpu.backend.meta import read_block_meta
    m2 = read_block_meta(backend, meta.block_id, "t1")
    assert m2.total_objects == 5


def test_find_trace_spans_all_stages(rig):
    t, now, backend, ring, ingesters, dist = rig
    tid = b"\x07" * 16
    dist.push_spans("t1", [mkspan(tid, b"\x01" * 8)])
    ing = ingesters["ing-0"]
    inst = ing.instance("t1")
    assert inst.find_trace_by_id(tid) is not None          # live
    t[0] += 5.0
    ing.sweep_instance("t1")
    assert inst.find_trace_by_id(tid) is not None          # head WAL
    t[0] += 31.0
    ing.sweep_instance("t1")
    ing.flush_tick(); ing.flush_tick()
    spans = inst.find_trace_by_id(tid)                     # complete block
    assert spans is not None and len(spans) == 1
    assert inst.find_trace_by_id(b"\xff" * 16) is None


def test_wal_replay_after_crash(tmp_path):
    t, now = make_clock()
    backend = MemBackend()
    cfg = IngesterConfig(instance=InstanceConfig(trace_idle_s=1.0))
    ing = Ingester(str(tmp_path / "ing"), flush_writer=backend, cfg=cfg,
                   now=now, instance_id="ing-0")
    tid = b"\x09" * 16
    ing.push("t1", [(tid, [mkspan(tid, b"\x01" * 8)])])
    t[0] += 2.0
    ing.instance("t1").cut_complete_traces()   # data in WAL, then "crash"
    del ing
    ing2 = Ingester(str(tmp_path / "ing"), flush_writer=backend, cfg=cfg,
                    now=now, instance_id="ing-0")
    # replay queued the WAL block for completion
    assert ing2.instance("t1").find_trace_by_id(tid) is not None
    ing2.flush_all()
    from tempo_tpu.backend.raw import blocks as list_blocks
    assert len(list_blocks(backend, "t1")) == 1


def test_shutdown_flushes_everything(rig):
    t, now, backend, ring, ingesters, dist = rig
    dist.push_spans("t1", [mkspan(bytes([i]) * 16, b"\x01" * 8)
                           for i in range(1, 4)])
    for ing in ingesters.values():
        ing.shutdown()
    from tempo_tpu.backend.raw import blocks as list_blocks
    assert len(list_blocks(backend, "t1")) == 3  # one block per ingester


def test_push_error_counted_once_across_replicas(rig):
    """A trace rejected by all RF replicas is ONE discarded trace."""
    t, now, backend, ring, ingesters, dist = rig
    for ing in ingesters.values():
        ing.overrides.set_tenant_patch(
            "t1", {"read": {"max_bytes_per_trace": 10}})
    errs = dist.push_spans("t1", [mkspan(b"\x01" * 16, b"\x01" * 8)])
    assert errs == {"trace_too_large": 1}
    assert dist.discarded["trace_too_large"] == 1


def test_replay_dedupes_wal_handles(tmp_path):
    """Restart with both a WAL block and a local complete block must not
    leave duplicate WALBlock handles that crash reads after completion."""
    t, now = make_clock()
    backend = MemBackend()
    cfg = IngesterConfig(instance=InstanceConfig(trace_idle_s=1.0))
    ing = Ingester(str(tmp_path / "i"), flush_writer=backend, cfg=cfg,
                   now=now, instance_id="ing-0")
    tid1, tid2 = b"\x01" * 16, b"\x02" * 16
    ing.push("t1", [(tid1, [mkspan(tid1, b"\x01" * 8)])])
    t[0] += 2.0
    ing.sweep_instance("t1")
    sealed = ing.instance("t1").cut_block_if_ready(immediate=True)
    ing.instance("t1").complete_block(sealed)          # one local complete block
    ing.push("t1", [(tid2, [mkspan(tid2, b"\x02" * 8)])])
    t[0] += 2.0
    ing.instance("t1").cut_complete_traces()           # one WAL block, then crash
    del ing
    ing2 = Ingester(str(tmp_path / "i"), flush_writer=backend, cfg=cfg,
                    now=now, instance_id="ing-0")
    inst = ing2.instance("t1")
    ids = [b.block_id for b in inst.completing]
    assert len(ids) == len(set(ids))                   # no duplicate handles
    ing2.flush_all()
    # both traces survive, reads don't crash on cleared WAL dirs
    assert inst.find_trace_by_id(tid1) is not None
    assert inst.find_trace_by_id(tid2) is not None


def test_generator_tee(rig):
    t, now, backend, ring, ingesters, dist = rig

    class CapturingGen:
        """Tee protocol: OTLP bytes on the wire (PushOTLP), decoded here to
        count what arrived."""
        def __init__(self):
            self.spans = []
        def push_otlp(self, tenant, data):
            from tempo_tpu.model.otlp import spans_from_otlp_proto
            got = list(spans_from_otlp_proto(data))
            self.spans.extend(got)
            return len(got)

    gens = {"gen-0": CapturingGen(), "gen-1": CapturingGen()}
    gring = Ring(replication_factor=1, now=now)
    for gid in gens:
        gring.register(InstanceDesc(id=gid, state=ACTIVE,
                                    tokens=_instance_tokens(gid, 64),
                                    heartbeat_ts=now()))
    dist.generator_ring = gring
    dist.generator_clients = gens
    dist.overrides.set_tenant_patch(
        "t1", {"generator": {"processors": ["span-metrics"]}})
    spans = [mkspan(bytes([i]) * 16, b"\x01" * 8) for i in range(1, 21)]
    dist.push_spans("t1", spans)
    total = sum(len(g.spans) for g in gens.values())
    assert total == 20          # RF1: each span at exactly one generator
    assert all(len(g.spans) > 0 for g in gens.values())  # spread over both


def test_generator_tee_raw_otlp_slicing(rig):
    """An OTLP receiver hands the raw payload to push_spans; the tee must
    forward raw wire slices (no re-encode) partitioned per generator, with
    content identical to the decoded spans."""
    import numpy as np

    from tempo_tpu import native
    from tempo_tpu.model.otlp import encode_spans_otlp, spans_from_otlp_proto

    t, now, backend, ring, ingesters, dist = rig

    class CapturingGen:
        def __init__(self):
            self.spans = []
        def push_otlp(self, tenant, data):
            got = list(spans_from_otlp_proto(data))
            self.spans.extend(got)
            return len(got)

    gens = {"gen-0": CapturingGen(), "gen-1": CapturingGen()}
    gring = Ring(replication_factor=1, now=now)
    for gid in gens:
        gring.register(InstanceDesc(id=gid, state=ACTIVE,
                                    tokens=_instance_tokens(gid, 64),
                                    heartbeat_ts=now()))
    dist.generator_ring = gring
    dist.generator_clients = gens
    dist.overrides.set_tenant_patch(
        "t1", {"generator": {"processors": ["span-metrics"]}})

    src = [mkspan(bytes([i]) * 16, b"\x01" * 8,
                  attrs={"http.status_code": 200 + i},
                  res_attrs={"service.name": f"svc-{i % 3}"})
           for i in range(1, 21)]
    raw = encode_spans_otlp(src)
    decoded = list(spans_from_otlp_proto(raw))
    assert len(decoded) == 20
    dist.push_spans("t1", decoded, raw_otlp=raw)

    got = sorted((s["trace_id"], s) for g in gens.values() for s in g.spans)
    want = sorted((s["trace_id"], s) for s in decoded)
    assert len(got) == 20
    for (gt, gs), (wt, ws) in zip(got, want):
        assert gt == wt
        assert gs == ws          # full span dict round-trips the slice
    if native.available():
        assert all(len(g.spans) > 0 for g in gens.values())


def test_columnar_push_matches_dict_path(rig):
    """distributor.push_otlp (no span dicts in the distributor) must land
    the same traces, reasons, and usage as push_spans over the same
    payload — including RF3 replication content at every ingester."""
    import numpy as np

    from tempo_tpu import native
    from tempo_tpu.model.otlp import encode_spans_otlp, spans_from_otlp_proto

    if not native.available():
        import pytest
        pytest.skip("native scanner required")

    t, now, backend, ring, ingesters, dist = rig
    src = []
    for i in range(1, 16):
        src.append(mkspan(bytes([i]) * 16, bytes([i]) * 8,
                          name=f"cp-{i % 3}",
                          attrs={"http.status_code": 200 + i},
                          res_attrs={"service.name": f"cs-{i % 2}"}))
    # two spans of one trace in different resources + an invalid-id span
    src.append(mkspan(bytes([1]) * 16, b"\xaa" * 8, name="cp-x",
                      res_attrs={"service.name": "cs-1"}))
    raw = encode_spans_otlp(src) + encode_spans_otlp(
        [{**mkspan(b"", b"\x01" * 8), "trace_id": b""}])

    errs = dist.push_otlp("t1", raw)
    assert errs.get("invalid_trace_id") == 1
    # every ingester holds every valid trace (RF3, 3 members)
    for i in range(1, 16):
        held = sum(1 for ing in ingesters.values()
                   if ing.find_trace_by_id("t1", bytes([i]) * 16))
        assert held == 3, (i, held)
    # the multi-resource trace carries both spans everywhere
    for ing in ingesters.values():
        spans = ing.find_trace_by_id("t1", bytes([1]) * 16)
        assert {s["span_id"] for s in spans} == {bytes([1]) * 8, b"\xaa" * 8}
    # the invalid-id span was DISCARDED, not replicated (regression: the
    # full-coverage raw-payload fast path must not bypass validation)
    for ing in ingesters.values():
        assert not ing.find_trace_by_id("t1", b"")
    # usage attribution by service matches the dict path's labels
    snap = dist.usage.prometheus_text()
    assert 'service="cs-0"' in snap and 'service="cs-1"' in snap
    # metrics counters moved
    assert dist.metrics["spans_received_total"] >= 17
    assert dist.dataquality.snapshot() is not None

    # parity of ingester CONTENT vs the dict path on a fresh rig tenant
    decoded = list(spans_from_otlp_proto(raw))
    errs2 = dist.push_spans("t2", decoded)
    assert errs2.get("invalid_trace_id") == 1
    for i in range(1, 16):
        a = next(ing.find_trace_by_id("t1", bytes([i]) * 16)
                 for ing in ingesters.values())
        b = next(ing.find_trace_by_id("t2", bytes([i]) * 16)
                 for ing in ingesters.values())
        ka = sorted((s["span_id"], s["name"]) for s in a)
        kb = sorted((s["span_id"], s["name"]) for s in b)
        assert ka == kb


def test_kafka_receiver_consumes_topic(rig):
    """Kafka receiver (shim.go:165-171 "kafka"): OTLP payloads produced
    to a topic by an external pipeline are consumed into the distributor;
    offsets commit after the push (at-least-once)."""
    from tempo_tpu.distributor.receiver_kafka import (KafkaReceiver,
                                                      KafkaReceiverConfig)
    from tempo_tpu.ingest.bus import Bus
    from tempo_tpu.model.otlp import encode_spans_otlp

    t, now, backend, ring, ingesters, dist = rig
    bus = Bus(n_partitions=2)
    spans = [mkspan(bytes([40]) * 16, bytes([1]) * 8, name="kr-op",
                    res_attrs={"service.name": "kr-svc"})]
    bus.produce(0, "t1", encode_spans_otlp(spans))
    bus.produce(1, "t1", encode_spans_otlp(
        [mkspan(bytes([41]) * 16, bytes([2]) * 8, name="kr-op2")]))
    rx = KafkaReceiver(bus, dist, KafkaReceiverConfig(partitions=(0, 1)))
    assert rx.run_once() == 2
    held = sum(1 for ing in ingesters.values()
               if ing.find_trace_by_id("t1", bytes([40]) * 16))
    assert held == 3                       # RF3 replication applied
    assert bus.committed(rx.cfg.group, 0) == 1
    assert bus.committed(rx.cfg.group, 1) == 1
    assert rx.run_once() == 0              # nothing new: offsets held


def test_forwarder_filter_policies(rig):
    """pkg/spanfilter-shaped per-tenant policies on the forwarder tee
    (the OTTL-filter analog): regex include + strict exclude."""
    from tempo_tpu.distributor.forwarder import (Forwarder,
                                                 ForwarderConfig)

    t, now, backend, ring, ingesters, dist = rig
    got: list = []
    fwd = Forwarder(
        ForwarderConfig(
            name="f1",
            filter_policies=[{
                "include": {"match_type": "regex",
                            "attributes": [{"key": "span.name",
                                            "value": "keep-.*"}]},
                "exclude": {"match_type": "strict",
                            "attributes": [{"key": "span.kind",
                                            "value": "SPAN_KIND_CLIENT"}]},
            }]),
        sink=got.extend)
    dist.forwarders.register("t1", fwd)
    spans = [
        mkspan(bytes([50]) * 16, bytes([1]) * 8, name="keep-a", kind=2),
        mkspan(bytes([51]) * 16, bytes([2]) * 8, name="keep-b", kind=3),
        mkspan(bytes([52]) * 16, bytes([3]) * 8, name="drop-c", kind=2),
    ]
    dist.push_spans("t1", spans)
    fwd.flush()
    fwd.shutdown()
    names = sorted(s["name"] for s in got)
    assert names == ["keep-a"], names      # regex kept keep-*, CLIENT excluded
