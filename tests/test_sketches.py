"""Property tests for the sketch kernels against scalar references.

Mirrors the reference's sketch-layer unit tests
(`pkg/traceqlmetrics/metrics_test.go` LatencyHistogram record/combine/
percentile) plus accuracy-budget checks for HLL / count-min / DDSketch.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tempo_tpu import ops


def test_log2_bucket_matches_bit_length():
    vals = np.array([0, 1, 2, 3, 4, 7, 8, 1023, 1024, 2**40, 2**62], dtype=np.float64)
    got = np.asarray(ops.log2_bucket(jnp.asarray(vals, jnp.float32)))
    want = np.array([int(v).bit_length() if v < 2**53 else min(63, math.floor(math.log2(v)) + 1)
                     for v in vals])
    np.testing.assert_array_equal(got, np.minimum(want, 63))


def test_log2_hist_update_and_counts():
    h = ops.log2_hist_init(num_series=3)
    sids = jnp.array([0, 0, 1, 2, 2, 2])
    vals = jnp.array([1.0, 3.0, 100.0, 0.0, 5.0, 5.0])
    h = ops.log2_hist_update(h, sids, vals)
    c = np.asarray(h.counts)
    assert c[0, 1] == 1  # v=1 → bucket 1
    assert c[0, 2] == 1  # v=3 → bucket 2
    assert c[1, 7] == 1  # v=100 → bit_length(100)=7
    assert c[2, 0] == 1  # zero bucket
    assert c[2, 3] == 2  # v=5 → bucket 3
    assert c.sum() == 6


def test_log2_hist_mask_drops_padding():
    h = ops.log2_hist_init(1)
    sids = jnp.zeros(4, jnp.int32)
    vals = jnp.array([1.0, 2.0, 4.0, 8.0])
    mask = jnp.array([True, True, False, False])
    h = ops.log2_hist_update(h, sids, vals, mask=mask)
    assert float(h.counts.sum()) == 2.0


def test_log2_quantile_within_bucket_bounds():
    rng = np.random.default_rng(0)
    vals = rng.lognormal(mean=10, sigma=2, size=20000)
    h = ops.log2_hist_init(1)
    h = ops.log2_hist_update(h, jnp.zeros(vals.size, jnp.int32), jnp.asarray(vals, jnp.float32))
    for q in (0.5, 0.9, 0.99):
        est = float(ops.log2_quantile(h, q)[0])
        true = np.quantile(vals, q)
        # Power-of-two buckets: estimate within 2x of truth, monotone in q.
        assert true / 2 <= est <= true * 2, (q, est, true)
    qs = [float(ops.log2_quantile(h, q)[0]) for q in (0.1, 0.5, 0.9, 0.99)]
    assert qs == sorted(qs)


def test_log2_quantile_stays_inside_the_hit_bucket():
    # 1000 observations all equal to 3.5 (bucket 2 = [2,4)): every quantile
    # estimate must land inside [2,4], never below the bucket's lower edge.
    h = ops.log2_hist_init(1)
    h = ops.log2_hist_update(h, jnp.zeros(1000, jnp.int32),
                             jnp.full(1000, 3.5, jnp.float32))
    for q in (0.01, 0.5, 0.99):
        est = float(ops.log2_quantile(h, q)[0])
        assert 2.0 <= est <= 4.0, (q, est)


def test_log2_offset_keeps_subsecond_resolution():
    # offset=32 separates 1ms / 30ms / 500ms instead of collapsing (0,1)->0.
    h = ops.log2_hist_init(1, offset=32)
    vals = jnp.asarray([0.001, 0.03, 0.5], jnp.float32)
    h = ops.log2_hist_update(h, jnp.zeros(3, jnp.int32), vals)
    c = np.asarray(h.counts[0])
    assert c[0] == 0 and (c > 0).sum() == 3
    est = float(ops.log2_quantile(h, 0.99)[0])
    assert 0.25 <= est <= 1.0  # inside 500ms's bucket [2^-1, 2^0)


def test_log2_hist_merge_equals_concat():
    rng = np.random.default_rng(1)
    a_vals, b_vals = rng.exponential(1e6, 500), rng.exponential(1e3, 500)
    mk = lambda v: ops.log2_hist_update(ops.log2_hist_init(2),
                                        jnp.asarray(rng.integers(0, 2, v.size), jnp.int32),
                                        jnp.asarray(v, jnp.float32))
    rng = np.random.default_rng(1)
    a = mk(a_vals)
    rng = np.random.default_rng(1)
    # merged counts = sum of counts
    m = ops.log2_hist_merge(a, a)
    np.testing.assert_allclose(np.asarray(m.counts), 2 * np.asarray(a.counts))


def test_ddsketch_relative_error_budget():
    rng = np.random.default_rng(2)
    vals = rng.lognormal(mean=3, sigma=1.5, size=50000)
    dd = ops.dd_init(1, rel_err=0.01)
    dd = ops.dd_update(dd, jnp.zeros(vals.size, jnp.int32), jnp.asarray(vals, jnp.float32))
    for q in (0.5, 0.9, 0.99, 0.999):
        est = float(ops.dd_quantile(dd, q)[0])
        true = np.quantile(vals, q)
        rel = abs(est - true) / true
        assert rel < 0.02, (q, est, true, rel)  # 1% sketch + sampling slack


def test_ddsketch_merge_and_zeros():
    dd = ops.dd_init(1, rel_err=0.01)
    dd = ops.dd_update(dd, jnp.zeros(3, jnp.int32), jnp.array([0.0, 0.0, 10.0]))
    assert float(dd.zeros[0]) == 2.0
    m = ops.dd_merge(dd, dd)
    assert float(m.zeros[0]) == 4.0
    assert float(ops.dd_quantile(m, 0.25)[0]) == 0.0


def _hash_pair(n, seed=0):
    items = np.arange(n, dtype=np.uint32)
    h1 = ops.splitmix32(jnp.asarray(items))
    h2 = ops.murmur_fmix32(jnp.asarray(items) ^ jnp.uint32(0xDEADBEEF))
    return h1, h2


@pytest.mark.parametrize("n", [100, 10000, 200000])
def test_hll_estimate_within_error(n):
    hll = ops.hll_init(1, precision=14)
    h1, h2 = _hash_pair(n)
    hll = ops.hll_update(hll, jnp.zeros(n, jnp.int32), h1, h2)
    est = float(ops.hll_estimate(hll)[0])
    # Standard error for p=14 is ~0.81%; allow 5 sigma.
    assert abs(est - n) / n < 0.05, (n, est)


def test_hll_merge_is_union():
    a_items = jnp.arange(5000, dtype=jnp.uint32)
    b_items = jnp.arange(2500, 7500, dtype=jnp.uint32)
    mk = lambda it: ops.hll_update(
        ops.hll_init(1), jnp.zeros(it.shape[0], jnp.int32),
        ops.splitmix32(it), ops.murmur_fmix32(it ^ jnp.uint32(0xDEADBEEF)))
    merged = ops.hll_merge(mk(a_items), mk(b_items))
    est = float(ops.hll_estimate(merged)[0])
    assert abs(est - 7500) / 7500 < 0.05


def test_cms_overestimates_only_and_accurate_heavy_hitters():
    rng = np.random.default_rng(3)
    # Zipf-ish: item i appears ~ 10000/i times.
    items, true_counts = [], {}
    for i in range(1, 200):
        c = max(1, 10000 // i)
        items += [i] * c
        true_counts[i] = c
    items = np.array(items, dtype=np.uint32)
    rng.shuffle(items)
    h1 = ops.splitmix32(jnp.asarray(items))
    h2 = ops.murmur_fmix32(jnp.asarray(items) ^ jnp.uint32(0xDEADBEEF))
    cms = ops.cms_init(1, depth=4, width=2048)
    cms = ops.cms_update(cms, jnp.zeros(items.size, jnp.int32), h1, h2)
    q_items = np.array(sorted(true_counts), dtype=np.uint32)
    qh1 = ops.splitmix32(jnp.asarray(q_items))
    qh2 = ops.murmur_fmix32(jnp.asarray(q_items) ^ jnp.uint32(0xDEADBEEF))
    est = np.asarray(ops.cms_estimate(cms, jnp.zeros(q_items.size, jnp.int32), qh1, qh2))
    want = np.array([true_counts[int(i)] for i in q_items], dtype=np.float32)
    assert (est >= want - 1e-3).all()  # count-min never underestimates
    # Top heavy hitters essentially exact (error ≤ eN/w, N≈58k, w=2048 → ~77)
    heavy = want >= 1000
    assert (np.abs(est[heavy] - want[heavy]) <= 100).all()


def test_cms_merge_adds():
    items = jnp.arange(100, dtype=jnp.uint32)
    h1, h2 = ops.splitmix32(items), ops.murmur_fmix32(items ^ jnp.uint32(1))
    cms = ops.cms_update(ops.cms_init(1), jnp.zeros(100, jnp.int32), h1, h2)
    m = ops.cms_merge(cms, cms)
    est = np.asarray(ops.cms_estimate(m, jnp.zeros(100, jnp.int32), h1, h2))
    assert (est >= 2.0 - 1e-6).all()


def test_updates_are_jittable_and_donate():
    @jax.jit
    def step(h, sids, vals):
        return ops.log2_hist_update(h, sids, vals)

    h = ops.log2_hist_init(4)
    h = step(h, jnp.array([0, 1, 2, 3]), jnp.array([1.0, 2.0, 3.0, 4.0]))
    assert float(h.counts.sum()) == 4.0


def test_fnv_reference_vectors():
    # Known FNV-1a 32 test vectors ("" -> offset, "a" -> 0xe40c292c).
    assert int(ops.fnv1a_32(np.frombuffer(b"a", dtype=np.uint8))[0]) == 0xE40C292C
    assert int(ops.fnv1a_64(np.frombuffer(b"a", dtype=np.uint8))[0]) == 0xAF63DC4C8601EC8C
    # FNV-1 32 ("a" -> 0x050c5d7e).
    assert int(ops.fnv1_32(np.frombuffer(b"a", dtype=np.uint8))[0]) == 0x050C5D7E


def test_token_for_batches():
    tids = np.zeros((3, 16), dtype=np.uint8)
    tids[1, -1] = 1
    toks = ops.token_for("tenant-a", tids)
    assert toks.shape == (3,)
    assert toks[0] == toks[2] and toks[0] != toks[1]


def test_hash_columns32_deterministic_and_spread():
    cols = jnp.asarray(np.random.default_rng(4).integers(0, 50, size=(1000, 5)), jnp.int32)
    h1 = np.asarray(ops.hash_columns32(cols))
    h2 = np.asarray(ops.hash_columns32(cols))
    np.testing.assert_array_equal(h1, h2)
    # distinct rows should essentially never collide at n=1000
    uniq_rows = np.unique(np.asarray(cols), axis=0).shape[0]
    assert np.unique(h1).size >= uniq_rows - 2
