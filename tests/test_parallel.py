"""Sharded mesh pipelines on the 8-virtual-device CPU mesh (conftest)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tempo_tpu.parallel import (
    make_mesh,
    make_multihost_mesh,
    sharded_query_range_step,
)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def test_multihost_mesh_falls_back_single_process():
    mesh = make_multihost_mesh(series_shards=2)
    assert mesh.axis_names == ("data", "series")
    assert mesh.devices.shape == (4, 2)


def test_sharded_query_range_matches_single_device():
    mesh = make_mesh(8, series_shards=2)
    n_series, n_steps, n_spans = 32, 4, 256  # 16 slots per series shard
    rng = np.random.default_rng(0)
    slots = rng.integers(0, n_series, n_spans).astype(np.int32)
    steps = rng.integers(0, n_steps, n_spans).astype(np.int32)
    vals = rng.random(n_spans).astype(np.float32)

    step = sharded_query_range_step(mesh)
    grid = jax.device_put(jnp.zeros((n_series, n_steps), jnp.float32),
                          NamedSharding(mesh, P("series", None)))
    dsh = NamedSharding(mesh, P("data"))
    out = step(grid,
               jax.device_put(jnp.asarray(slots), dsh),
               jax.device_put(jnp.asarray(steps), dsh),
               jax.device_put(jnp.asarray(vals), dsh))
    ref = np.zeros((n_series, n_steps), np.float32)
    np.add.at(ref, (slots, steps), vals)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)
    # iterate: accumulates
    out2 = step(out, jax.device_put(jnp.asarray(slots), dsh),
                jax.device_put(jnp.asarray(steps), dsh),
                jax.device_put(jnp.asarray(vals), dsh))
    np.testing.assert_allclose(np.asarray(out2), 2 * ref, rtol=1e-5)


def test_sharded_query_range_histogram_plane():
    mesh = make_mesh(8, series_shards=2)
    n_series, n_steps, n_buckets, n_spans = 16, 2, 64, 128
    rng = np.random.default_rng(1)
    slots = rng.integers(0, n_series, n_spans).astype(np.int32)
    steps = rng.integers(0, n_steps, n_spans).astype(np.int32)
    dur_ns = rng.lognormal(17, 1.5, n_spans).astype(np.float32)

    step = sharded_query_range_step(mesh, n_buckets=n_buckets)
    grid = jax.device_put(
        jnp.zeros((n_series, n_steps, n_buckets), jnp.float32),
        NamedSharding(mesh, P("series", None, None)))
    dsh = NamedSharding(mesh, P("data"))
    out = np.asarray(step(grid,
                          jax.device_put(jnp.asarray(slots), dsh),
                          jax.device_put(jnp.asarray(steps), dsh),
                          jax.device_put(jnp.asarray(dur_ns), dsh)))
    assert out.sum() == n_spans
    b = np.clip(np.ceil(np.log2(np.maximum(dur_ns, 1.0))), 0, 63).astype(int)
    ref = np.zeros((n_series, n_steps, n_buckets), np.float32)
    np.add.at(ref, (slots, steps, b), 1.0)
    np.testing.assert_allclose(out, ref)


# -- PRODUCT paths under the mesh (round-4 weak #3 closure) ------------------

def _product_block(n=10_000):
    """A non-trivial block (group-by labels, boundary durations, partial
    attrs) through the real writer."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig

    rng = np.random.default_rng(17)
    T0 = 1_700_000_000
    be = MemBackend()
    traces = []
    for i in range(n):
        tid = rng.bytes(16)
        start = int((T0 + i * 0.05) * 1e9)
        traces.append((tid, [{
            "trace_id": tid, "span_id": rng.bytes(8),
            "name": f"op-{i % 7}", "service": f"svc-{i % 4}",
            "kind": int(i % 6), "status_code": int(i % 3),
            "start_unix_nano": start,
            "end_unix_nano": start + int(rng.lognormal(16, 1.2)),
            "attrs": ({"http.status_code": 200 + (i % 300),
                       "ratio": [0.5, 1.5, -2.25][i % 3]}
                      if i % 4 else
                      {"http.status_code": 200 + (i % 300)}),
        }]))
    return be, traces, T0


def test_sharded_plane_query_range_product_parity():
    """TempoDB.query_range with plane_mesh: the SAME fused product kernels
    run SPMD over 8 devices (span columns sharded over 'data', XLA
    inserts the grid reduce). Series must match BOTH the host engine and
    the single-device plane on a >=10k-span block with group-by, quantile
    histograms, and predicate pushdown."""
    from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
    from tempo_tpu.parallel import make_mesh
    from tempo_tpu.traceql.engine_metrics import QueryRangeRequest

    be, traces, T0 = _product_block()
    mesh = make_mesh(8, series_shards=1)
    dev1 = TempoDB(be, be, TempoDBConfig(device_plane=True))
    devm = TempoDB(be, be, TempoDBConfig(device_plane=True,
                                         plane_mesh=mesh))
    host = TempoDB(be, be, TempoDBConfig(device_plane=False))
    dev1.write_block("t", traces, replication_factor=1)
    for db in (dev1, devm, host):
        db.poll_now()

    def smap(series):
        return {tuple(sorted((str(k), str(v)) for k, v in s.labels)):
                np.nan_to_num(np.asarray(s.samples, np.float64))
                for s in series}

    for q in ('{ } | rate() by (resource.service.name)',
              '{ } | count_over_time() by (name)',
              '{ duration > 50ms } | rate() by (name)',
              '{ } | quantile_over_time(duration, .99)'
              ' by (resource.service.name)',
              '{ span.http.status_code >= 400 } | rate() by (name)',
              '{ } | avg_over_time(duration) by (resource.service.name)',
              '{ } | rate() by (resource.service.name, name)',
              # round-5 features under the mesh: float-attr compares on
              # the sortable-int64 encoding + pure-OR fusion
              '{ span.ratio > 0.5 } | rate() by (name)',
              '{ span.ratio = -2.25 || name = "op-3" }'
              ' | count_over_time() by (name)'):
        req = QueryRangeRequest(query=q, start_ns=int(T0 * 1e9),
                                end_ns=int((T0 + 600) * 1e9),
                                step_ns=int(60e9))
        am = smap(devm.query_range("t", req))
        a1 = smap(dev1.query_range("t", req))
        b = smap(host.query_range("t", req))
        assert set(am) == set(b) == set(a1), q
        for k in b:
            np.testing.assert_allclose(am[k], b[k], rtol=1e-5, atol=1e-4,
                                       err_msg=f"mesh-vs-host {q} {k}")
            np.testing.assert_allclose(am[k], a1[k], rtol=1e-6, atol=1e-6,
                                       err_msg=f"mesh-vs-1dev {q} {k}")
    # the sharded plane really served fused (not a silent host fallback)
    assert devm.plane_stats["fused_metric_blocks"] >= 7
    assert not any(k.startswith("fallback_") for k in devm.plane_stats)
    # search rides the sharded mask kernel too
    s_m = sorted(m.trace_id for m in devm.search(
        "t", '{ duration > 50ms && span.http.status_code >= 400 }',
        limit=5000))
    s_h = sorted(m.trace_id for m in host.search(
        "t", '{ duration > 50ms && span.http.status_code >= 400 }',
        limit=5000))
    assert s_m == s_h and s_m


def test_sharded_registry_product_push_collect_parity():
    """A REAL ManagedRegistry + SpanMetricsProcessor pushed under the mesh
    (state sharded over 'series', batch over 'data') must collect the
    same samples as the single-device processor — same series table, same
    interner, same exemplar plumbing."""
    from tempo_tpu.generator.processors.spanmetrics import (
        SpanMetricsConfig, SpanMetricsProcessor)
    from tempo_tpu.model.span_batch import SpanBatchBuilder
    from tempo_tpu.parallel import make_mesh
    from tempo_tpu.parallel.product import (shard_processor_state,
                                            sharded_push_batch)
    from tempo_tpu.registry import ManagedRegistry, RegistryOverrides

    mesh = make_mesh(8, series_shards=2)
    rng = np.random.default_rng(5)

    def mk():
        reg = ManagedRegistry("t", RegistryOverrides(max_active_series=512),
                              now=lambda: 1000.0)
        proc = SpanMetricsProcessor(reg, SpanMetricsConfig())
        return reg, proc

    reg_m, proc_m = mk()
    reg_1, proc_1 = mk()
    shard_processor_state(proc_m, mesh)

    def batch(reg, seed):
        b = SpanBatchBuilder(reg.interner)
        r = np.random.default_rng(seed)
        for i in range(3000):
            b.append(trace_id=r.bytes(16), span_id=r.bytes(8),
                     name=f"op-{i % 9}", service=f"svc-{i % 3}",
                     kind=int(i % 6), status_code=int(i % 3),
                     start_unix_nano=10**18,
                     end_unix_nano=10**18 + int(r.lognormal(16, 1.0)))
        return b.build()

    for seed in (1, 2):
        sharded_push_batch(proc_m, mesh, batch(reg_m, seed))
        proc_1.push_batch(batch(reg_1, seed))
    sm = sorted((s.name, s.labels, round(s.value, 4))
                for s in reg_m.collect(5000))
    s1 = sorted((s.name, s.labels, round(s.value, 4))
                for s in reg_1.collect(5000))
    assert sm == s1 and len(sm) > 100
    # quantile sketch plane agrees too
    qm = proc_m.quantile(0.99)
    q1 = proc_1.quantile(0.99)
    assert qm.keys() == q1.keys() and qm
    for k in qm:
        np.testing.assert_allclose(qm[k], q1[k], rtol=1e-5)
