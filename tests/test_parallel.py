"""Sharded mesh pipelines on the 8-virtual-device CPU mesh (conftest)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tempo_tpu.parallel import (
    make_mesh,
    make_multihost_mesh,
    sharded_query_range_step,
)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def test_multihost_mesh_falls_back_single_process():
    mesh = make_multihost_mesh(series_shards=2)
    assert mesh.axis_names == ("data", "series")
    assert mesh.devices.shape == (4, 2)


def test_sharded_query_range_matches_single_device():
    mesh = make_mesh(8, series_shards=2)
    n_series, n_steps, n_spans = 32, 4, 256  # 16 slots per series shard
    rng = np.random.default_rng(0)
    slots = rng.integers(0, n_series, n_spans).astype(np.int32)
    steps = rng.integers(0, n_steps, n_spans).astype(np.int32)
    vals = rng.random(n_spans).astype(np.float32)

    step = sharded_query_range_step(mesh)
    grid = jax.device_put(jnp.zeros((n_series, n_steps), jnp.float32),
                          NamedSharding(mesh, P("series", None)))
    dsh = NamedSharding(mesh, P("data"))
    out = step(grid,
               jax.device_put(jnp.asarray(slots), dsh),
               jax.device_put(jnp.asarray(steps), dsh),
               jax.device_put(jnp.asarray(vals), dsh))
    ref = np.zeros((n_series, n_steps), np.float32)
    np.add.at(ref, (slots, steps), vals)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)
    # iterate: accumulates
    out2 = step(out, jax.device_put(jnp.asarray(slots), dsh),
                jax.device_put(jnp.asarray(steps), dsh),
                jax.device_put(jnp.asarray(vals), dsh))
    np.testing.assert_allclose(np.asarray(out2), 2 * ref, rtol=1e-5)


def test_sharded_query_range_histogram_plane():
    mesh = make_mesh(8, series_shards=2)
    n_series, n_steps, n_buckets, n_spans = 16, 2, 64, 128
    rng = np.random.default_rng(1)
    slots = rng.integers(0, n_series, n_spans).astype(np.int32)
    steps = rng.integers(0, n_steps, n_spans).astype(np.int32)
    dur_ns = rng.lognormal(17, 1.5, n_spans).astype(np.float32)

    step = sharded_query_range_step(mesh, n_buckets=n_buckets)
    grid = jax.device_put(
        jnp.zeros((n_series, n_steps, n_buckets), jnp.float32),
        NamedSharding(mesh, P("series", None, None)))
    dsh = NamedSharding(mesh, P("data"))
    out = np.asarray(step(grid,
                          jax.device_put(jnp.asarray(slots), dsh),
                          jax.device_put(jnp.asarray(steps), dsh),
                          jax.device_put(jnp.asarray(dur_ns), dsh)))
    assert out.sum() == n_spans
    b = np.clip(np.ceil(np.log2(np.maximum(dur_ns, 1.0))), 0, 63).astype(int)
    ref = np.zeros((n_series, n_steps, n_buckets), np.float32)
    np.add.at(ref, (slots, steps, b), 1.0)
    np.testing.assert_allclose(out, ref)
