"""The self-tracing loop (runbook "Tracing Tempo with Tempo").

Propagation invariants and the loopback ingest contract:

- tail-keep: SLO-missing / errored trees survive a zero head-sample
  rate, plain trees are sampled out WHOLE, late spans (async sched jobs
  closing after the root) follow their trace's verdict;
- an RPC push that retries under fault injection stays ONE logical span
  tree (same traceparent, same X-Push-Id, one rpc.push span);
- loopback: a process ingesting its OWN spans emits zero new spans
  (recursion guard), refuses the reserved tenant on public push APIs,
  and answers TraceQL search / metrics over its own behavior, with
  SLO-missing request trees retrievable by the qlog `selfTraceId`.
"""

import json
import logging
import socket
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from tempo_tpu.model.otlp import encode_spans_otlp, spans_from_otlp_proto
from tempo_tpu.utils import faults, tracing


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _decode_names(batches: list) -> list:
    return [s["name"] for b in batches for s in spans_from_otlp_proto(b)]


# -- tail-keep ---------------------------------------------------------------


def test_tail_keep_slo_and_error_trees_survive_zero_rate():
    """At head_sample_rate 0 nothing exports EXCEPT trees forced past
    sampling: mark_keep() (the SLO-miss hook) and errored spans."""
    batches: list = []
    tr = tracing.SelfTracer(sink=batches.append, head_sample_rate=0.0,
                            flush_interval_s=3600)
    tracing.install(tr)
    # plain tree: buffered until root close, then sampled out whole
    with tracing.span("root-a"):
        with tracing.span("child-a"):
            pass
        assert tracing.kept_trace_id_hex() is None
    # SLO-miss analog: mark_keep forces the whole tree, and the verdict
    # is knowable before root close (the qlog selfTraceId bridge)
    with tracing.span("root-b") as rb:
        with tracing.span("child-b"):
            pass
        tracing.mark_keep()
        assert tracing.kept_trace_id_hex() == rb.trace_id.hex()
    # an errored span forces its tree too
    with pytest.raises(ValueError):
        with tracing.span("root-c"):
            raise ValueError("boom")
    assert tr.flush() == 3
    names = set(_decode_names(batches))
    assert names == {"root-b", "child-b", "root-c"}
    assert tr.stats["kept_traces"] == 2
    assert tr.stats["sampled_spans"] == 2          # root-a + child-a
    assert tr.stats["dropped_spans"] == 0          # sampling is not loss


def test_late_spans_follow_their_trace_verdict():
    """A span closing AFTER its trace finalized (async sched dispatch
    outliving the request root) follows the cached keep verdict."""
    batches: list = []
    tr = tracing.SelfTracer(sink=batches.append, head_sample_rate=0.0,
                            flush_interval_s=3600)
    tracing.install(tr)
    with tracing.span("kept-root") as root:
        tracing.mark_keep()
    tid = root.trace_id
    assert tr.flush() == 1
    # late arrival on the kept trace: adopted remote context, no open
    # local parent — exports alone under the same trace id
    with tracing.adopted(f"00-{tid.hex()}-{'ab' * 8}-01"):
        with tracing.span("late-dispatch"):
            pass
    assert tr.flush() == 1
    got = list(spans_from_otlp_proto(batches[-1]))
    assert got[0]["name"] == "late-dispatch"
    assert got[0]["trace_id"] == tid
    # late arrival on a SAMPLED-OUT trace: silently follows the drop
    with tracing.span("dropped-root") as dr:
        pass
    with tracing.adopted(f"00-{dr.trace_id.hex()}-{'cd' * 8}-01"):
        with tracing.span("late-dropped"):
            pass
    assert tr.flush() == 0


# -- RPC push retries: one logical tree --------------------------------------


class _FlakyGenHandler(BaseHTTPRequestHandler):
    """Scripted ring-owner: each entry of `script` is an HTTP status for
    one POST (then 200s forever); headers of every attempt recorded."""

    script: list = []
    requests: list = []

    def do_POST(self):  # noqa: N802
        self.rfile.read(int(self.headers.get("Content-Length", 0) or 0))
        type(self).requests.append(dict(self.headers.items()))
        status = type(self).script.pop(0) if type(self).script else 200
        body = json.dumps({"spans": 1}).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # noqa: A002
        pass


def test_rpc_push_retry_is_one_logical_tree():
    """Fault-injected + 503'd retries of one generator push stay ONE
    logical tree: every wire attempt carries the SAME X-Push-Id and the
    SAME traceparent, and the client emits exactly one rpc.push span."""
    from tempo_tpu.rpc import RemoteGeneratorClient

    _FlakyGenHandler.script = [503]
    _FlakyGenHandler.requests = []
    srv = HTTPServer(("127.0.0.1", 0), _FlakyGenHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    batches: list = []
    tr = tracing.SelfTracer(sink=batches.append, flush_interval_s=3600)
    tracing.install(tr)
    payload = encode_spans_otlp([dict(
        trace_id=b"\x01" * 16, span_id=b"\x02" * 8, name="op",
        service="svc", kind=2, status_code=0,
        start_unix_nano=10**18, end_unix_nano=10**18 + 10**6)])
    client = RemoteGeneratorClient(
        f"http://127.0.0.1:{srv.server_address[1]}", timeout_s=10.0)
    try:
        # attempt 0 dies in-process (fault point, never reaches the
        # wire), attempt 1 gets the scripted 503, attempt 2 lands
        spec = faults.FaultSpec(point="rpc.push", probability=1.0, count=1)
        with faults.use([spec]):
            with tracing.span("push-root") as root:
                assert client.push_otlp("t1", payload) == 1
    finally:
        srv.shutdown()
    assert tr.flush() == 2                     # push-root + ONE rpc.push
    got = list(spans_from_otlp_proto(b"".join(batches)))
    pushes = [s for s in got if s["name"] == "rpc.push"]
    assert len(pushes) == 1
    assert pushes[0]["trace_id"] == root.trace_id
    assert pushes[0]["attrs"]["retries"] == 2
    # both wire attempts: same push id, same traceparent, root's trace
    assert len(_FlakyGenHandler.requests) == 2
    ids = {r.get("X-Push-Id") for r in _FlakyGenHandler.requests}
    tps = {r.get("Traceparent") or r.get("traceparent")
           for r in _FlakyGenHandler.requests}
    assert len(ids) == 1 and None not in ids
    assert len(tps) == 1
    assert root.trace_id.hex() in next(iter(tps))


# -- config bounds -----------------------------------------------------------


def test_selftrace_config_check_bounds():
    from tempo_tpu.app.config import Config

    cfg = Config(target="all")
    cfg.selftrace.enabled = True
    assert not any("selftrace" in w for w in cfg.check())
    cfg.selftrace.head_sample_rate = 1.5
    cfg.selftrace.flush_interval_s = 0.0
    cfg.selftrace.max_trace_spans = 1
    cfg.selftrace.endpoint = "http://example:4318"
    warnings = [w for w in cfg.check() if w.startswith("selftrace:")]
    assert any("head_sample_rate" in w for w in warnings)
    assert any("flush_interval_s" in w for w in warnings)
    assert any("max_trace_spans" in w for w in warnings)
    assert any("loopback wins" in w for w in warnings)
    # loopback needs this process to HAVE a distributor
    cfg2 = Config(target="querier")
    cfg2.selftrace.enabled = True
    assert any("selftrace" in w and "distributor" in w
               for w in cfg2.check())


# -- the loopback E2E proof --------------------------------------------------


def test_loopback_e2e_self_observability(tmp_path):
    """Single binary with `selftrace.enabled`: the process ingests its
    own spans under the reserved ops tenant and (a) emits ZERO new spans
    while doing so, (b) refuses the reserved tenant on public push,
    (c) answers TraceQL search for its own sched.dispatch spans and a
    quantile_over_time over self-span latency, and (d) an SLO-missing
    request's tree is retrievable by the qlog line's selfTraceId."""
    import time as _time

    from tempo_tpu import sched
    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config
    from tempo_tpu.frontend.slos import SLOConfig
    from tempo_tpu.obs.qlog import LOGGER_NAME

    port = _free_port()
    cfg = Config(target="all")
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.server.http_listen_port = port
    cfg.selftrace.enabled = True
    cfg.selftrace.flush_interval_s = 3600.0     # flush manually
    # span-metrics gives the push path real device rows (the sched
    # coalescer emits the dispatch spans the TraceQL proof searches
    # for); local-blocks serves the metrics query over self-spans
    cfg.overrides_defaults.generator.processors = ("span-metrics",
                                                   "local-blocks")
    assert not any("selftrace" in w for w in cfg.check())
    app = App(cfg)
    app.start_loops()
    srv = serve(app, block=False)
    base = f"http://127.0.0.1:{port}"
    tr = tracing.tracer()
    try:
        assert tr.loopback and tracing.reserved_tenant() == "tempo-self"
        assert tracing.is_reserved("tempo-self")

        # (b) the reserved tenant is refused on the public push API
        req = urllib.request.Request(
            f"{base}/v1/traces", data=b"{}",
            headers={"Content-Type": "application/json",
                     "X-Scope-OrgID": "tempo-self"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400

        # drive traced work: HTTP push -> distributor -> tee ->
        # generator, then a deliberately SLO-missing search
        t0 = int((_time.time() - 3) * 1e9)
        otlp = {"resourceSpans": [{"scopeSpans": [{"spans": [{
            "traceId": "ab" * 16, "spanId": "cd" * 8, "name": "user-op",
            "startTimeUnixNano": str(t0),
            "endTimeUnixNano": str(t0 + 50_000_000)}]}]}]}
        req = urllib.request.Request(
            f"{base}/v1/traces", data=json.dumps(otlp).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10).close()
        sched.flush()                       # force async dispatch spans

        app.frontend.qlog.sample_every = 1  # every line logs
        app.frontend.slos.per_op["search"] = SLOConfig(duration_slo_s=1e-9)
        logger = logging.getLogger(LOGGER_NAME)
        records: list = []

        class _Capture(logging.Handler):
            def emit(self, rec):
                records.append(rec.getMessage())

        h = _Capture()
        prev_level = logger.level
        logger.setLevel(logging.INFO)
        logger.addHandler(h)
        try:
            app.frontend.search("single-tenant", "{ }", limit=5)
        finally:
            logger.removeHandler(h)
            logger.setLevel(prev_level)
            app.frontend.slos.per_op.pop("search", None)
        lines = [json.loads(x) for x in records]
        kept = [r for r in lines if r.get("selfTraceId")]
        assert kept, lines                  # (d) qlog carries the id
        self_tid = kept[0]["selfTraceId"]

        # (a) recursion guard: ingesting our own export emits no spans
        spans_before = tr.stats["spans"]
        assert tr.flush() > 0               # loopback into ourselves
        sched.flush()                       # drain the self-ingest rows
        assert tr.stats["spans"] == spans_before
        assert tr.stats["loopback_batches"] >= 1

        # (c) TraceQL search over our own dispatch spans, ops tenant
        q = urllib.parse.quote(
            '{ resource.service.name = "tempo-tpu" '
            '&& name =~ "sched.dispatch" }')
        req = urllib.request.Request(
            f"{base}/api/search?q={q}",
            headers={"X-Scope-OrgID": "tempo-self"})
        with urllib.request.urlopen(req, timeout=10) as r:
            found = json.loads(r.read())
        assert found.get("traces"), found

        # (c) metrics over self-span latency, ops tenant
        now = _time.time()
        q = urllib.parse.quote("{ } | quantile_over_time(duration, .5)")
        req = urllib.request.Request(
            f"{base}/api/metrics/query_range?q={q}"
            f"&start={now - 300}&end={now}&step=300",
            headers={"X-Scope-OrgID": "tempo-self"})
        with urllib.request.urlopen(req, timeout=10) as r:
            qr = json.loads(r.read())
        assert qr.get("series"), qr

        # (d) the SLO-missing tree, by its qlog selfTraceId
        req = urllib.request.Request(
            f"{base}/api/traces/{self_tid}",
            headers={"X-Scope-OrgID": "tempo-self"})
        with urllib.request.urlopen(req, timeout=10) as r:
            tree = json.loads(r.read())
        names = {s["name"] for s in tree["spans"]}
        assert "frontend.Search" in names, names

        # /status surfaces export health
        with urllib.request.urlopen(f"{base}/status", timeout=10) as r:
            status = json.loads(r.read())
        assert status["selftrace"]["loopback"] is True
        assert status["selftrace"]["tenant"] == "tempo-self"
    finally:
        srv.shutdown()
        app.shutdown()
