"""Mesh-resident serving path (tempo_tpu.parallel.serving): registry
state sharded over 'series' as donated device buffers, mesh-aware
coalescer dispatch, in-mesh frontend combine — bit-identity + donation
guarantees on the virtual 8-device CPU mesh (conftest)."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from tempo_tpu import sched
from tempo_tpu.parallel import serving

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


@pytest.fixture(autouse=True)
def _reset_serving_mesh():
    yield
    serving.reset()


def _mk_proc(max_series: int = 512):
    from tempo_tpu.generator.processors.spanmetrics import (
        SpanMetricsConfig, SpanMetricsProcessor)
    from tempo_tpu.registry import ManagedRegistry, RegistryOverrides

    reg = ManagedRegistry("t", RegistryOverrides(max_active_series=max_series),
                          now=lambda: 1000.0)
    return reg, SpanMetricsProcessor(reg, SpanMetricsConfig())


def _batch(reg, seed: int, n: int = 2000):
    from tempo_tpu.model.span_batch import SpanBatchBuilder

    b = SpanBatchBuilder(reg.interner)
    r = np.random.default_rng(seed)
    for i in range(n):
        b.append(trace_id=r.bytes(16), span_id=r.bytes(8),
                 name=f"op-{i % 9}", service=f"svc-{i % 3}",
                 kind=int(i % 6), status_code=int(i % 3),
                 start_unix_nano=10**18,
                 end_unix_nano=10**18 + int(r.lognormal(16, 1.0)))
    return b.build()


def _collect_exact(reg) -> list:
    # EXACT float values — the bit-identity surface
    return sorted((s.name, s.labels, s.value) for s in reg.collect(5000))


def _mesh(devices: int, series_shards: int,
          combine_min_elements: int = 16384) -> serving.ServingMesh:
    return serving.ServingMesh(serving.MeshConfig(
        enabled=True, devices=devices, series_shards=series_shards,
        combine_min_elements=combine_min_elements))


# -- bit identity ------------------------------------------------------------

def test_collect_bit_identical_across_series_shards():
    """collect() (and the quantile sketch plane) must be BIT-identical
    at series_shards 1, 2, 4: each shard scatters the same rows in the
    same order into the slots it owns, so per-slot float accumulation
    order never depends on the shard count (data axis fixed at 1)."""
    outs, quants = {}, {}
    for shards in (1, 2, 4):
        with serving.use(_mesh(shards, shards)):
            reg, proc = _mk_proc()
            for seed in (1, 2, 3):
                proc.push_batch(_batch(reg, seed))
            outs[shards] = _collect_exact(reg)
            quants[shards] = proc.quantile(0.99)
    assert outs[1] and outs[1] == outs[2] == outs[4]
    assert quants[1] and quants[1] == quants[2] == quants[4]


def test_mesh_vs_single_device_parity():
    """Mesh collect vs the plain single-device processor: same series
    set, values equal at float tolerance (the base+delta association
    differs, so bit-equality is not the contract here)."""
    with serving.use(_mesh(4, 4)):
        reg_m, proc_m = _mk_proc()
        for seed in (1, 2):
            proc_m.push_batch(_batch(reg_m, seed))
        got = _collect_exact(reg_m)
    reg_1, proc_1 = _mk_proc()
    for seed in (1, 2):
        proc_1.push_batch(_batch(reg_1, seed))
    ref = _collect_exact(reg_1)
    assert len(got) == len(ref) > 100
    for (n1, l1, v1), (n2, l2, v2) in zip(ref, got):
        assert (n1, l1) == (n2, l2)
        np.testing.assert_allclose(v2, v1, rtol=1e-5, atol=1e-6)


def test_scheduler_route_bit_identical_across_series_shards():
    """The mesh-aware coalescer (one aligned window, one shard_map
    dispatch) keeps the bit-identity guarantee when pushes ride the
    device scheduler."""
    outs = {}
    for shards in (1, 2, 4):
        with serving.use(_mesh(shards, shards)):
            sc = sched.DeviceScheduler(sched.SchedConfig(pipeline_depth=0),
                                       start_worker=False)
            with sched.use(sc):
                reg, proc = _mk_proc()
                for seed in (1, 2):
                    proc.push_batch(_batch(reg, seed))
                assert sc.flush()
                assert sc.batches_total.get("spanmetrics_fused_update",
                                            0) >= 1
                outs[shards] = _collect_exact(reg)
    assert outs[1] and outs[1] == outs[2] == outs[4]


# -- donation + residency ----------------------------------------------------

def test_sharded_state_donated_no_copy():
    """The sharded fused update DONATES: the previous device buffers are
    invalidated at dispatch (no per-push state copy), state stays a
    sharded device array (no host round-trip), and the sketch plane
    rides the same discipline."""
    with serving.use(_mesh(4, 4)) as sm:
        reg, proc = _mk_proc()
        proc.push_batch(_batch(reg, 1))
        calls0, dd0 = proc.calls.state.values, proc.dd.counts
        assert isinstance(calls0, jax.Array)
        assert calls0.sharding == sm.series_1d
        assert dd0.sharding.is_equivalent_to(sm.series_2d, dd0.ndim)
        assert len(calls0.sharding.device_set) == 4
        proc.push_batch(_batch(reg, 2))
        assert calls0.is_deleted()      # donated, not copied
        assert dd0.is_deleted()
        assert isinstance(proc.calls.state.values, jax.Array)
        assert proc.calls.state.values.sharding == sm.series_1d


def test_purge_then_push_keeps_working():
    """A stale-series purge (eager zero_slots) must not wedge the mesh
    route — the next dispatch re-places if placement drifted."""
    clock = [1000.0]
    from tempo_tpu.generator.processors.spanmetrics import (
        SpanMetricsConfig, SpanMetricsProcessor)
    from tempo_tpu.registry import ManagedRegistry, RegistryOverrides

    with serving.use(_mesh(4, 4)):
        reg = ManagedRegistry(
            "t", RegistryOverrides(max_active_series=512,
                                   stale_duration_s=10.0),
            now=lambda: clock[0])
        proc = SpanMetricsProcessor(reg, SpanMetricsConfig())
        proc.push_batch(_batch(reg, 1))
        clock[0] += 100.0
        assert reg.purge_stale() > 0
        proc.push_batch(_batch(reg, 2))
        calls = np.asarray(proc.calls.state.values)
        assert calls.sum() > 0


def test_unshardable_capacity_falls_back_single_device():
    """Capacities that don't split across the shards leave the processor
    on its single-device path (warned, never fatal)."""
    with serving.use(_mesh(4, 4)):
        reg, proc = _mk_proc(max_series=510)     # 510 % 4 != 0
        proc.push_batch(_batch(reg, 1, n=100))
        assert proc._mesh is None
        assert np.asarray(proc.calls.state.values).sum() > 0


# -- mesh-aware coalescer ----------------------------------------------------

def test_coalescer_aligns_bucket_and_emits_shard_obs():
    """submit_rows(align=N) rounds the merged bucket to a multiple of
    the data shards and mesh dispatches emit per-shard occupancy +
    padding-waste rows under the `shard` label."""
    from tempo_tpu.obs.jaxruntime import RUNTIME
    from tempo_tpu.obs.registry import parse_exposition

    got = {}
    sc = sched.DeviceScheduler(sched.SchedConfig(min_bucket_rows=64),
                               start_worker=False)
    with sched.use(sc):     # the obs render funcs read the process slot
        sc.submit_rows("mesh_k", "m", (np.zeros(48, np.int32),), 48,
                       lambda *a: got.setdefault("shape", a[0].shape),
                       pads=(-1,), align=3, shards=3)
        sc.drain_once(force=True)
        assert got["shape"] == (66,)   # pow2 64 rounded up to 3's multiple
        fams = parse_exposition(RUNTIME.render())
        occ = fams["tempo_sched_batch_occupancy_ratio"]["samples"]
        shard_rows = {k for k in occ
                      if k[0] == "tempo_sched_batch_occupancy_ratio_bucket"
                      and dict(k[1]).get("kernel") == "mesh_k"
                      and dict(k[1]).get("shard") in ("0", "1", "2")}
        assert shard_rows, "per-shard occupancy rows missing"
        pad = fams["tempo_sched_padding_waste_bytes_total"]["samples"]
        tail = [(k, v) for k, v in pad.items()
                if dict(k[1]).get("kernel") == "mesh_k"
                and dict(k[1]).get("shard") == "2"]
        assert tail and tail[0][1] > 0     # padding concentrates on the tail


# -- in-mesh frontend combine ------------------------------------------------

def test_frontend_combine_in_mesh_matches_host_fold():
    """SeriesCombiner under the serving mesh: count-exact kinds merge
    via the single in-mesh reduce, bit-equal to the host fold."""
    from tempo_tpu.traceql import ast as A
    from tempo_tpu.traceql.engine_metrics import SeriesCombiner, TimeSeries

    rng = np.random.default_rng(7)
    T = 10

    def mk_lists():
        return [[TimeSeries((("name", f"op-{i}"),),
                            rng.integers(0, 500, T).astype(np.float64),
                            [{"traceId": f"{j}-{i}"}])
                 for i in range(11)] for j in range(4)]

    for kind in (A.MetricsKind.RATE, A.MetricsKind.COUNT_OVER_TIME,
                 A.MetricsKind.MIN_OVER_TIME, A.MetricsKind.MAX_OVER_TIME):
        lists = mk_lists()

        def run(combiner):
            for lst in lists:
                combiner.add_all([TimeSeries(t.labels, t.samples.copy(),
                                             list(t.exemplars))
                                  for t in lst])
            return {k: (v.samples, len(v.exemplars))
                    for k, v in combiner.series.items()}

        ref = run(SeriesCombiner(kind, T))
        # threshold 1: force even this small fold onto the device path
        with serving.use(_mesh(4, 2, combine_min_elements=1)):
            got = run(SeriesCombiner(kind, T))
        assert set(ref) == set(got)
        for k in ref:
            np.testing.assert_array_equal(ref[k][0], got[k][0],
                                          err_msg=str(kind))
            assert ref[k][1] == got[k][1]


def test_frontend_combine_bit_identical_across_shard_counts():
    from tempo_tpu.traceql import ast as A
    from tempo_tpu.traceql.engine_metrics import SeriesCombiner, TimeSeries

    rng = np.random.default_rng(9)
    lists = [[TimeSeries((("svc", f"s{i}"),),
                         rng.integers(0, 100, 6).astype(np.float64))
              for i in range(9)] for _ in range(3)]
    outs = {}
    for shards in (1, 2, 4):
        with serving.use(_mesh(4, shards, combine_min_elements=1)):
            c = SeriesCombiner(A.MetricsKind.RATE, 6)
            for lst in lists:
                c.add_all([TimeSeries(t.labels, t.samples.copy())
                           for t in lst])
            outs[shards] = {k: v.samples.tobytes()
                            for k, v in c.series.items()}
    assert outs[1] == outs[2] == outs[4]


# -- config surface ----------------------------------------------------------

def test_mesh_config_check_warnings():
    from tempo_tpu.app.config import load_config

    cfg = load_config(text="mesh:\n  enabled: true\n  series_shards: -1\n")
    assert any("mesh" in w and "series_shards" in w for w in cfg.check())
    cfg = load_config(text="mesh:\n  enabled: true\n  devices: 4\n"
                           "  series_shards: 3\n")
    assert any("divide" in w for w in cfg.check())
    assert not load_config(text="mesh:\n  enabled: true\n").check()


def test_configure_falls_back_on_bad_shape():
    """serving.configure never raises at serve time — bad shapes warn
    and fall back to the largest pow-2 series sharding that fits (NOT
    all the way to the data-parallel layout) or disable."""
    sm = serving.configure(serving.MeshConfig(enabled=True, devices=4,
                                              series_shards=3))
    assert sm is not None and sm.series_shards == 2
    assert serving.configure(serving.MeshConfig(enabled=False)) is None
    assert serving.active() is None


def test_step_cache_not_keyed_by_mesh_id():
    """product._cached_step keys by mesh VALUE identity — two meshes
    with identical layouts share an entry; id() reuse can't alias."""
    from tempo_tpu.parallel.mesh import make_mesh, mesh_fingerprint
    from tempo_tpu.parallel.product import _STEP_CACHE, _cached_step

    _STEP_CACHE.clear()
    m1 = make_mesh(4, series_shards=2)
    m2 = make_mesh(4, series_shards=2)
    assert mesh_fingerprint(m1) == mesh_fingerprint(m2)
    f1 = _cached_step(m1, (0.1, 1.0), 1.02, 1e-9)
    f2 = _cached_step(m2, (0.1, 1.0), 1.02, 1e-9)
    assert f1 is f2 and len(_STEP_CACHE) == 1
    m3 = make_mesh(8, series_shards=2)
    assert mesh_fingerprint(m3) != mesh_fingerprint(m1)
    assert _cached_step(m3, (0.1, 1.0), 1.02, 1e-9) is not f1
    assert len(_STEP_CACHE) == 2
    _STEP_CACHE.clear()
