"""End-to-end engine tests: block fetch → search → query_range (the paths of
SURVEY.md §3.3/§3.4, tested like `vparquet4/block_traceql_test.go` — build a
real block, run queries against it)."""

import numpy as np
import pytest

from tempo_tpu.backend.local import LocalBackend
from tempo_tpu.block.fetch import scan_views
from tempo_tpu.block.writer import write_block
from tempo_tpu.block.reader import BackendBlock
from tempo_tpu.traceql.engine import (execute_search, execute_tag_values,
                                      compile_query)
from tempo_tpu.traceql.engine_metrics import (HBUCKETS, MetricsEvaluator,
                                              QueryRangeRequest,
                                              SeriesCombiner, log2_quantile,
                                              query_range)

T0 = 1_700_000_000_000_000_000  # base time (ns)


def build_block(tmp_path, n_traces=50, spans_per_trace=4):
    be = LocalBackend(str(tmp_path))
    traces = []
    for i in range(n_traces):
        tid = i.to_bytes(2, "big") * 8
        spans = []
        for j in range(spans_per_trace):
            spans.append({
                "trace_id": tid,
                "span_id": bytes([j + 1]) * 8,
                "parent_span_id": b"" if j == 0 else bytes([j]) * 8,
                "name": f"op-{j}",
                "service": f"svc-{i % 3}",
                "kind": 2,
                "status_code": 2 if (i % 10 == 0 and j == 1) else 0,
                "start_unix_nano": T0 + i * 1_000_000_000,
                "end_unix_nano": T0 + i * 1_000_000_000 + (j + 1) * 10_000_000,
                "attrs": {"http.status_code": 200 + (i % 2) * 300,
                          "region": ["us", "eu", "ap"][i % 3]},
                "res_attrs": {"cluster": f"c{i % 2}"},
            })
        traces.append((tid, spans))
    traces.sort()
    meta = write_block(be, "t1", traces, row_group_rows=64)
    return be, meta, traces


@pytest.fixture(scope="module")
def block(tmp_path_factory):
    be, meta, traces = build_block(tmp_path_factory.mktemp("blk"))
    return BackendBlock(be, meta), traces


def views(block, query, start_ns=0, end_ns=0):
    _, req = compile_query(query, start_ns, end_ns)
    return scan_views(block, req)


def test_row_groups_trace_aligned(block):
    b, _ = block
    pf = b.parquet_file()
    assert pf.num_row_groups > 1  # 200 rows, 64-row target
    seen = set()
    for rg in range(pf.num_row_groups):
        tbl = pf.read_row_group(rg, columns=["trace_idx"])
        tids = set(tbl.column("trace_idx").to_numpy().tolist())
        assert not (tids & seen)  # no trace spans two groups
        seen |= tids


def test_search_basic(block):
    b, _ = block
    res = execute_search('{ name = "op-1" }', views(b, '{ name = "op-1" }'),
                         limit=100)
    assert len(res) == 50
    assert all(md.span_sets[0]["matched"] == 1 for md in res)


def test_search_attr_pushdown(block):
    b, _ = block
    q = "{ span.http.status_code >= 500 }"
    res = execute_search(q, views(b, q), limit=100)
    assert len(res) == 25  # odd traces


def test_search_resource_attr(block):
    b, _ = block
    q = '{ resource.cluster = "c1" }'
    res = execute_search(q, views(b, q), limit=100)
    assert len(res) == 25


def test_search_structural_on_block(block):
    b, _ = block
    q = '{ name = "op-0" } > { name = "op-1" }'
    res = execute_search(q, views(b, q), limit=100)
    assert len(res) == 50
    q = '{ name = "op-0" } >> { name = "op-3" }'
    res = execute_search(q, views(b, q), limit=100)
    assert len(res) == 50


def test_search_limit_and_order(block):
    b, _ = block
    res = execute_search("{ }", views(b, "{ }"), limit=7)
    assert len(res) == 7
    starts = [md.start_time_unix_nano for md in res]
    assert starts == sorted(starts, reverse=True)  # most recent first


def test_search_time_window(block):
    b, _ = block
    start = T0 + 10 * 1_000_000_000
    end = T0 + 20 * 1_000_000_000
    q = "{ }"
    res = execute_search(q, views(b, q, start, end), limit=100,
                         start_ns=start, end_ns=end)
    assert 0 < len(res) <= 11


def test_search_root_metadata(block):
    b, _ = block
    md = execute_search('{ name = "op-2" }', views(b, '{ name = "op-2" }'),
                        limit=1)[0]
    assert md.root_trace_name == "op-0"
    assert md.root_service_name.startswith("svc-")


def test_tag_values(block):
    b, _ = block
    from tempo_tpu.traceql.engine import tag_values_request
    vals = execute_tag_values(
        "span.region", scan_views(b, tag_values_request("span.region")))
    assert {v["value"] for v in vals} == {"us", "eu", "ap"}


def test_rate_by_group(block):
    b, _ = block
    req = QueryRangeRequest(
        query="{ } | rate() by(resource.cluster)",
        start_ns=T0, end_ns=T0 + 50 * 1_000_000_000,
        step_ns=10 * 1_000_000_000)
    series = query_range(req, views(b, req.query, req.start_ns, req.end_ns))
    assert len(series) == 2  # c0/c1
    total = sum(ts.samples.sum() for ts in series)
    # 200 spans over 50s at step 10s → rate sums to 200/10 per label split
    assert total == pytest.approx(200 / 10.0)


def test_count_over_time(block):
    b, _ = block
    req = QueryRangeRequest(
        query="{ } | count_over_time()",
        start_ns=T0, end_ns=T0 + 50 * 1_000_000_000,
        step_ns=10 * 1_000_000_000)
    series = query_range(req, views(b, req.query, req.start_ns, req.end_ns))
    assert len(series) == 1
    assert series[0].samples.sum() == 200
    assert series[0].samples.shape == (5,)


def test_min_max_avg_sum_over_time(block):
    b, _ = block
    base = dict(start_ns=T0, end_ns=T0 + 50 * 1_000_000_000,
                step_ns=50 * 1_000_000_000)
    # duration aggregates are reported in seconds (ns→s like the reference)
    for fn, expect in [("min_over_time", 0.010), ("max_over_time", 0.040),
                       ("avg_over_time", 0.025), ("sum_over_time", 200 * 0.025)]:
        req = QueryRangeRequest(query=f"{{ }} | {fn}(duration)", **base)
        series = query_range(req, views(b, req.query, req.start_ns, req.end_ns))
        assert len(series) == 1, fn
        assert series[0].samples[0] == pytest.approx(expect, rel=1e-4), fn


def test_quantile_over_time(block):
    b, _ = block
    req = QueryRangeRequest(
        query="{ } | quantile_over_time(duration, .5)",
        start_ns=T0, end_ns=T0 + 50 * 1_000_000_000,
        step_ns=50 * 1_000_000_000)
    series = query_range(req, views(b, req.query, req.start_ns, req.end_ns))
    assert len(series) == 1
    # durations 10/20/30/40ms uniformly; log2-bucketed median within 2x
    p50 = series[0].samples[0]
    assert 0.01 <= p50 <= 0.045


def test_histogram_over_time_bucket_series(block):
    b, _ = block
    req = QueryRangeRequest(
        query="{ } | histogram_over_time(duration)",
        start_ns=T0, end_ns=T0 + 50 * 1_000_000_000,
        step_ns=50 * 1_000_000_000)
    ev = MetricsEvaluator(req)
    for view, cand in views(b, req.query, req.start_ns, req.end_ns):
        ev.observe(view)
    series = ev.results()
    assert all(any(k == "__bucket" for k, _ in ts.labels) for ts in series)
    assert sum(ts.samples.sum() for ts in series) == 200


def test_sharded_combine_equals_single(block):
    """Job-level series from split row-group shards combine to the same
    result as one pass — the frontend combiner contract."""
    b, _ = block
    req = QueryRangeRequest(
        query="{ } | quantile_over_time(duration, .9) by(span.region)",
        start_ns=T0, end_ns=T0 + 50 * 1_000_000_000,
        step_ns=25 * 1_000_000_000)
    single = query_range(req, views(b, req.query, req.start_ns, req.end_ns))

    pf = b.parquet_file()
    comb = SeriesCombiner(MetricsEvaluator(req).m.kind, req.n_steps)
    for rg in range(pf.num_row_groups):
        _, freq = compile_query(req.query, req.start_ns, req.end_ns)
        ev = MetricsEvaluator(req)
        for view, cand in scan_views(b, freq, row_groups=[rg]):
            ev.observe(view)
        comb.add_all(ev.results())
    sharded = comb.final(req)

    def as_map(series):
        return {ts.labels: ts.samples for ts in series}

    s1, s2 = as_map(single), as_map(sharded)
    assert set(s1) == set(s2)
    for k in s1:
        np.testing.assert_allclose(s1[k], s2[k], rtol=1e-9)


def test_log2_quantile_math():
    buckets = np.zeros(HBUCKETS)
    buckets[10] = 100  # values in (512, 1024] ns
    assert 512 / 1e9 < log2_quantile(0.5, buckets) <= 1024 / 1e9
    assert log2_quantile(0.0, buckets) == pytest.approx(512 / 1e9)
    assert log2_quantile(1.0, buckets) == pytest.approx(1024 / 1e9)


def test_metrics_second_pass_filter(block):
    b, _ = block
    req = QueryRangeRequest(
        query="{ status = error } | count_over_time()",
        start_ns=T0, end_ns=T0 + 50 * 1_000_000_000,
        step_ns=50 * 1_000_000_000)
    series = query_range(req, views(b, req.query, req.start_ns, req.end_ns))
    assert sum(ts.samples.sum() for ts in series) == 5  # i%10==0 traces
