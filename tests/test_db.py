"""tempodb tests: polling/index, find fan-out, compaction dedup, retention
(reference models: tempodb_test.go, blocklist/poller_test.go,
compactor_test.go)."""

import numpy as np

from tempo_tpu.backend import MemBackend, has_meta, read_tenant_index
from tempo_tpu.db import (
    CompactorConfig,
    Poller,
    Pool,
    TempoDB,
    TempoDBConfig,
    TimeWindowBlockSelector,
)
from tempo_tpu.backend.meta import BlockMeta
from tests.test_block import mkspan, trace


def _db(now=None):
    be = MemBackend()
    kw = {"now": now} if now else {}
    return TempoDB(be, be, TempoDBConfig(row_group_rows=32), **kw), be


def test_write_poll_find():
    db, be = _db()
    t5 = trace(5)
    db.write_block("t1", [trace(1), trace(2), t5])
    db.write_block("t1", [trace(8), trace(9)])
    db.write_block("t2", [trace(3)])
    # fresh db instance discovers blocks purely via polling
    db2 = TempoDB(be, be)
    db2.poll_now()
    assert len(db2.blocks("t1")) == 2
    spans = db2.find_trace_by_id("t1", t5[0])
    assert spans is not None and len(spans) == 3
    assert db2.find_trace_by_id("t2", t5[0]) is None
    # tenant index written by the builder
    assert len(read_tenant_index(be, "t1").metas) == 2


def test_find_combines_rf_duplicates():
    db, _ = _db()
    tid, spans = trace(4)
    # the same trace flushed by two "ingesters" (RF>1) into two blocks
    db.write_block("t1", [(tid, spans[:2])])
    db.write_block("t1", [(tid, spans)])  # overlap: spans[0:2] duplicated
    got = db.find_trace_by_id("t1", tid)
    assert len(got) == 3  # deduped by span id


def test_time_pruned_blocks():
    db, _ = _db()
    db.write_block("t1", [trace(1)])   # start_time ~ 1.0s
    db.write_block("t1", [trace(50)])  # start_time ~ 50.0s
    assert len(db.blocks("t1")) == 2
    assert len(db.blocks("t1", start_s=40.0)) == 1
    assert len(db.blocks("t1", end_s=10.0)) == 1


def test_selector_groups_by_level_and_window():
    cfg = CompactorConfig(max_compaction_window_s=100.0, min_inputs=2, max_inputs=3)
    sel = TimeWindowBlockSelector(cfg)
    metas = [BlockMeta.new("t", end_time=t, compaction_level=lvl, total_spans=1)
             for t, lvl in [(10, 0), (20, 0), (30, 0), (40, 0), (150, 0), (160, 0), (30, 1)]]
    jobs = sel.blocks_to_compact(metas)
    # window 0 level 0: 4 blocks -> one job of 3 (leftover 1 skipped);
    # window 1 level 0: 2 blocks -> one job; level 1 single block -> none
    assert [len(j) for j in jobs] == [3, 2]
    assert all(m.compaction_level == 0 for j in jobs for m in j)


def test_compaction_merges_and_marks():
    db, be = _db()
    tid, spans = trace(4, n_spans=3)
    m1 = db.write_block("t1", [trace(1), (tid, spans[:2])])
    m2 = db.write_block("t1", [(tid, spans), trace(9)])
    n = db.compact_tenant_once("t1")
    assert n == 1
    metas = db.blocks("t1")
    assert len(metas) == 1 and metas[0].compaction_level == 1
    assert metas[0].total_objects == 3  # traces 1, 4, 9
    assert metas[0].total_spans == 3 + 3 + 3
    # inputs marked compacted in the backend
    assert has_meta(be, m1.block_id, "t1") == (False, True)
    assert has_meta(be, m2.block_id, "t1") == (False, True)
    # merged trace deduped
    got = db.find_trace_by_id("t1", tid)
    assert len(got) == 3


def test_retention_deletes_after_grace():
    clock = [1000.0]
    db, be = _db(now=lambda: clock[0])
    db.cfg.compactor.retention_s = 100.0
    db.cfg.compactor.compacted_grace_s = 50.0
    db.write_block("t1", [trace(1)])  # end_time ~1s << cutoff
    marked, deleted = db.retention_once("t1")
    assert len(marked) == 1 and not deleted
    assert db.blocks("t1") == []
    clock[0] += 60.0
    marked, deleted = db.retention_once("t1")
    assert not marked and len(deleted) == 1
    from tempo_tpu.backend.raw import KeyPath

    assert be.list(KeyPath(("t1",))) == []


def test_pool_stop_when():
    pool = Pool(max_workers=4)
    results, errors = pool.run_jobs(
        range(100), lambda i: i if i % 10 == 0 else None,
        stop_when=lambda rs: len(rs) >= 3)
    assert len(results) >= 3
    assert not errors


def test_pool_collects_errors():
    pool = Pool(max_workers=2)

    def fn(i):
        if i == 1:
            raise ValueError("boom")
        return i

    results, errors = pool.run_jobs([0, 1, 2], fn)
    assert sorted(results) == [0, 2]
    assert len(errors) == 1
