"""Request-scoped query stats pipeline + structured query logging.

Covers: contextvar scope isolation under the frontend's thread-pool
fan-out, stats merge across ≥3 shard jobs, RPC round-trip of serialized
stats, wire-compat decode of old single-`inspected` search responses,
qlog capture rules, and the /api/search SearchMetrics surface.
"""

from __future__ import annotations

import json
import logging
import threading

import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.frontend import Frontend, FrontendConfig
from tempo_tpu.obs import querystats
from tempo_tpu.obs.qlog import LOGGER_NAME, LatencySketch, QueryLogger
from tempo_tpu.obs.querystats import QueryStats
from tempo_tpu.querier import Querier

T0 = 1_700_000_000.0


def mkspan(tid, sid, name="op", svc="svc", t0_s=T0, dur_ms=50, **kw):
    t0 = int(t0_s * 1e9)
    return {"trace_id": tid, "span_id": sid, "name": name, "service": svc,
            "start_unix_nano": t0, "end_unix_nano": t0 + int(dur_ms * 1e6),
            **kw}


@pytest.fixture
def stack():
    """Two backend blocks behind a frontend that shards 1 row group per
    job (≥ 3 shard jobs for any full-range search)."""
    clock = [T0 + 3600.0]
    now = lambda: clock[0]
    be = MemBackend()
    db = TempoDB(be, be, cfg=TempoDBConfig(row_group_rows=2))
    for blk in range(2):
        traces = []
        for i in range(1, 6):
            tid = bytes([blk * 16 + i]) * 16
            traces.append((tid, [mkspan(tid, bytes([i]) * 8,
                                        svc=f"svc-{blk}", t0_s=T0 + i)]))
        db.write_block("t1", traces, replication_factor=1)
    db.poll_now()
    q = Querier(db)
    fe = Frontend(db, q, cfg=FrontendConfig(
        target_bytes_per_job=1,       # one job per row group
        qlog_sample_every=1), now=now)
    yield clock, now, db, q, fe
    fe.shutdown()
    db.shutdown()


# -- scope mechanics ---------------------------------------------------------


def test_scope_isolation_across_threads():
    """Scopes are contextvar-local: recording on one thread never leaks
    into another thread's scope, and an unscoped thread records nothing."""
    results = {}
    barrier = threading.Barrier(2)

    def worker(name, n):
        with querystats.scope() as st:
            barrier.wait()
            for _ in range(n):
                querystats.add(inspected_spans=1)
            results[name] = st.inspected_spans

    ts = [threading.Thread(target=worker, args=("a", 3)),
          threading.Thread(target=worker, args=("b", 7))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results == {"a": 3, "b": 7}
    assert querystats.current() is None
    querystats.add(inspected_spans=99)           # no scope: silent no-op


def test_nested_scope_and_ensure_scope():
    with querystats.scope() as outer:
        with querystats.scope() as inner:
            querystats.add(cache_hits=1)
        assert inner.cache_hits == 1 and outer.cache_hits == 0
        with querystats.ensure_scope() as joined:
            assert joined is outer               # reuses the active scope
        querystats.add(cache_hits=1)
        assert outer.cache_hits == 1


def test_stage_timer_and_merge():
    with querystats.scope() as st:
        with querystats.stage("engine_eval"):
            pass
        with querystats.stage("engine_eval"):
            pass
    assert st.stage_ns["engine_eval"] > 0
    child = QueryStats(inspected_bytes=10, blocks_scanned=2,
                       stage_ns={"engine_eval": 5, "block_fetch": 7})
    st.merge(child)
    assert st.inspected_bytes == 10 and st.blocks_scanned == 2
    assert st.stage_ns["block_fetch"] == 7
    st.merge(st)                                  # self-merge: no double
    assert st.inspected_bytes == 10
    st.merge(None)


# -- frontend fan-out merge --------------------------------------------------


def _run_search(fe, now, limit=50):
    with querystats.scope() as st:
        res = fe.search("t1", "{ }", limit=limit, start_s=0, end_s=now())
    return res, st


def test_sharded_search_merges_stats_inline(stack):
    clock, now, db, q, fe = stack
    res, st = _run_search(fe, now)
    assert len(res) == 10
    assert st.total_jobs >= 3                    # 1-byte/job sharding
    assert st.completed_jobs == st.total_jobs
    assert st.blocks_scanned >= st.total_jobs    # one block slice per job
    assert st.total_blocks == 2
    assert st.inspected_bytes > 0
    assert st.inspected_traces >= 10
    assert st.inspected_spans >= 10
    assert st.stage_ns.get("block_fetch", 0) > 0
    assert st.stage_ns.get("engine_eval", 0) > 0
    assert st.stage_ns.get("merge", 0) > 0


def test_sharded_search_merges_stats_worker_pool(stack):
    """Thread-pool fan-out: jobs execute on worker threads that cannot see
    the issuer's contextvar — per-job stats objects + fold-time merge must
    still produce identical totals, and queue-wait appears."""
    clock, now, db, q, fe = stack
    _, inline = _run_search(fe, now)
    fe.start_workers(3)
    res, st = _run_search(fe, now)
    assert len(res) == 10
    assert st.completed_jobs == inline.completed_jobs
    assert st.inspected_bytes == inline.inspected_bytes
    assert st.inspected_traces == inline.inspected_traces
    assert "queue_wait" in st.stage_ns


def test_cache_hits_counted(stack):
    from tempo_tpu.backend.cache import CacheProvider

    clock, now, db, q, fe0 = stack
    fe = Frontend(db, q, cfg=FrontendConfig(target_bytes_per_job=1),
                  cache_provider=CacheProvider(), now=now)
    _, first = _run_search(fe, now)
    assert first.cache_hits == 0
    _, second = _run_search(fe, now)
    assert second.cache_hits == second.completed_jobs > 0
    assert second.inspected_bytes == 0           # nothing rescanned
    fe.shutdown()


# -- RPC serialization -------------------------------------------------------


def _full_stats() -> QueryStats:
    st = QueryStats()
    st.add(inspected_traces=11, inspected_bytes=1 << 30, inspected_spans=13,
           total_blocks=4, blocks_scanned=3, blocks_skipped=1,
           total_jobs=6, completed_jobs=6, cache_hits=2,
           device_scan_bytes=1 << 20, kernel_wall_ns=12345)
    st.add_stage_ns("queue_wait", 42)
    st.add_stage_ns("engine_eval", 1_000_000)
    return st


def test_stats_json_roundtrip():
    st = _full_stats()
    got = QueryStats.from_json(json.loads(json.dumps(st.to_json())))
    assert got.to_json() == st.to_json()
    assert QueryStats.from_json(None).to_json() == {}


def test_stats_proto_roundtrip_in_search_response():
    from tempo_tpu.model import tempopb

    st = _full_stats()
    body = tempopb.enc_search_response([], final=True, stats=st)
    mds, final, inspected, got = tempopb.dec_search_response(body)
    assert final and inspected == 11
    assert got.to_json() == st.to_json()


def test_old_format_search_response_still_decodes():
    """Old encoders emit only the single `inspected` varint (field 1 of
    the metrics submessage); new decoders must accept it."""
    from tempo_tpu.model import tempopb

    old = tempopb.enc_search_response([], inspected=7, final=False)
    mds, final, inspected, st = tempopb.dec_search_response(old)
    assert not final and inspected == 7
    assert st.inspected_traces == 7
    assert st.inspected_bytes == 0 and st.stage_ns == {}


def test_new_format_readable_by_old_decoder():
    """A peer running the OLD decode (reads only field 1 of the metrics
    submessage) must still see the legacy `inspected` scalar in a
    stats-bearing response — the wire-compat contract both ways."""
    from tempo_tpu.model import proto_wire as pw
    from tempo_tpu.model import tempopb

    body = tempopb.enc_search_response([], final=True, stats=_full_stats())
    d = pw.decode_fields(body)
    metrics = pw.decode_fields(bytes(d[2][0]))
    assert metrics[1][0] == 11                   # old decoder's view


def test_remote_worker_result_message_carries_stats(stack):
    """The worker-stream result path: a serialized stats payload on the
    result message merges into the job's stats object (server-side
    read_results analog) and then into the parent at fold."""
    st = _full_stats()
    wire = json.dumps({"stats": st.to_json()})
    child = QueryStats.from_json(json.loads(wire)["stats"])
    with querystats.scope() as parent:
        querystats.absorb(child)
    assert parent.inspected_bytes == st.inspected_bytes
    assert parent.stage_ns["engine_eval"] == st.stage_ns["engine_eval"]


# -- structured query log ----------------------------------------------------


def test_latency_sketch_quantile():
    sk = LatencySketch()
    for _ in range(99):
        sk.record(0.010)
    sk.record(10.0)
    p95 = sk.quantile(0.95)
    assert 0.005 < p95 < 0.025                   # log2 bucket of 10ms
    assert sk.quantile(1.0) > 5.0
    assert LatencySketch().quantile(0.5) == 0.0


def test_qlog_errors_always_slow_over_threshold_rest_sampled():
    ql = QueryLogger(slow_quantile=0.9, sample_every=1000,
                     min_observations=10, rate_limit_per_s=1e9)
    # errors log regardless of sketch state or sampling
    rec = ql.log_query(op="search", tenant="t", query="{}", status="error",
                       duration_s=0.001, error="boom")
    assert rec is not None and rec["reason"] == "error"
    # warm the sketch with fast queries (first one is the 1-in-N sample)
    reasons = [r["reason"] for r in
               (ql.log_query(op="search", tenant="t", query="{}",
                             status="ok", duration_s=0.001)
                for _ in range(50)) if r is not None]
    assert reasons.count("sampled") == 1
    # now a 100x outlier crosses the sketch-estimated p90
    rec = ql.log_query(op="search", tenant="t", query="{}", status="ok",
                       duration_s=0.1)
    assert rec is not None and rec["reason"] == "slow"
    assert ql.threshold("search") > 0
    assert ql.suppressed > 0
    reasons = dict(ql.emitted_by_reason())
    assert reasons[("error",)] == 1 and reasons[("slow",)] == 1


def test_qlog_rate_limit_spares_errors():
    t = [0.0]
    ql = QueryLogger(sample_every=1, min_observations=10**9,
                     rate_limit_per_s=0.0, burst=2, now=lambda: t[0])
    oks = [ql.log_query(op="s", tenant="t", query="{}", status="ok",
                        duration_s=0.01) for _ in range(5)]
    assert sum(r is not None for r in oks) == 2  # burst exhausted
    rec = ql.log_query(op="s", tenant="t", query="{}", status="error",
                       duration_s=0.01, error="x")
    assert rec is not None                       # errors bypass the bucket


def test_qlog_record_is_one_parseable_json_line(caplog):
    ql = QueryLogger(sample_every=1, rate_limit_per_s=1e9)
    with caplog.at_level(logging.INFO, logger=LOGGER_NAME):
        ql.log_query(op="search", tenant='te"nant', query='{ x = "y" }',
                     status="ok", duration_s=0.25, stats=_full_stats(),
                     trace_id="ab" * 16)
    lines = [r.getMessage() for r in caplog.records
             if r.name == LOGGER_NAME]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["msg"] == "query complete"
    assert rec["tenant"] == 'te"nant'
    assert rec["durationMs"] == 250.0
    assert rec["traceId"] == "ab" * 16
    assert rec["inspectedBytes"] == 1 << 30
    assert rec["stageDurationNanos"]["engine_eval"] == 1_000_000


def test_frontend_emits_exactly_one_query_complete_line(stack, caplog):
    """Acceptance: a sharded search emits ONE parseable JSON line whose
    numbers match the request's merged stats, carrying the active
    SelfTracer trace id."""
    from tempo_tpu.utils import tracing

    clock, now, db, q, fe = stack
    tracer = tracing.SelfTracer("http://127.0.0.1:9", flush_interval_s=3600)
    tracing.install(tracer)
    try:
        with caplog.at_level(logging.INFO, logger=LOGGER_NAME):
            res, st = _run_search(fe, now)
        lines = [r.getMessage() for r in caplog.records
                 if r.name == LOGGER_NAME]
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["op"] == "search" and rec["status"] == "ok"
        sm = st.search_metrics()
        assert rec["completedJobs"] == sm["completedJobs"] >= 3
        assert rec["inspectedBytes"] == sm["inspectedBytes"] > 0
        assert rec["totalBlocks"] == sm["totalBlocks"] == 2
        assert isinstance(rec["traceId"], str) and len(rec["traceId"]) == 32
    finally:
        tracing.install(tracing.NoopTracer())
        tracer._stop.set()


def test_self_tracer_counts_failed_export_as_dropped():
    """Satellite bugfix: a failed export must not silently swallow the
    batch NOR drop it immediately — it is held for exactly ONE retry on
    the next flush tick (export_retries) before counting into `dropped`."""
    from tempo_tpu.utils import tracing

    tracer = tracing.SelfTracer("http://127.0.0.1:9", flush_interval_s=3600)
    try:
        with tracer.span("doomed"):
            pass
        assert tracer.dropped == 0
        assert tracer.flush() == 0               # unreachable endpoint
        assert tracer.dropped == 0               # held, not yet lost
        assert tracer.stats["export_retries"] == 1
        assert tracer.flush() == 0               # bounded retry fails too
        assert tracer.dropped == 1               # NOW it's a counted loss
        assert tracer.exported == 0
    finally:
        tracer._stop.set()


def test_tenant_read_cost_counters(stack):
    clock, now, db, q, fe = stack
    _, st = _run_search(fe, now)
    fam = fe.obs.get("tempo_tpu_query_inspected_bytes_total")
    series = dict(fam.fn())
    assert series[("t1",)] == st.inspected_bytes > 0
    fam = fe.obs.get("tempo_tpu_query_blocks_scanned_total")
    assert dict(fam.fn())[("t1",)] == st.blocks_scanned


# -- HTTP surface ------------------------------------------------------------


def test_api_search_response_includes_merged_stats(tmp_path):
    """Acceptance: a sharded /api/search response carries the merged
    SearchMetrics (and /api/metrics/query_range carries its own)."""
    import socket
    import urllib.parse
    import urllib.request

    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cfg = Config(target="all")
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.server.http_listen_port = port
    cfg.frontend.target_bytes_per_job = 1
    app = App(cfg)
    srv = serve(app, block=False)
    try:
        traces = []
        for i in range(1, 6):
            tid = bytes([i]) * 16
            traces.append((tid, [mkspan(tid, bytes([i]) * 8)]))
        app.db.write_block("single-tenant", traces, replication_factor=1)
        app.db.poll_now()
        url = (f"http://127.0.0.1:{port}/api/search?q=%7B%20%7D"
               f"&start=0&end={T0 + 60}&limit=50")
        body = json.loads(urllib.request.urlopen(url, timeout=10).read())
        m = body["metrics"]
        assert len(body["traces"]) == 5
        assert m["inspectedTraces"] >= 5
        assert m["inspectedBytes"] > 0
        assert m["totalBlocks"] == 1
        assert m["completedJobs"] == m["totalJobs"] >= 1
        assert "stageDurationNanos" in m
        qr = (f"http://127.0.0.1:{port}/api/metrics/query_range"
              f"?q={urllib.parse.quote('{ } | rate()')}"
              f"&start={T0 - 60}&end={T0 + 60}&step=60")
        body = json.loads(urllib.request.urlopen(qr, timeout=10).read())
        assert "metrics" in body
        assert body["metrics"]["totalBlocks"] >= 1
    finally:
        srv.shutdown()
        app.shutdown()
