"""Pallas kernels vs XLA scatter: identical state deltas.

Two families under test: the dense MXU one-hot kernel (historical
template) and the paged ragged fused kernel (ISSUE 11) — the latter in
interpreter mode on SMALL shapes only (interpret is pure Python and
slow; these are the tier-1 parity + fallback-contract gates, the speed
gates live in benchmarks/bench_kernels.py on a real TPU)."""

from __future__ import annotations

import logging

import jax.numpy as jnp
import numpy as np
import pytest

from tempo_tpu.ops.pallas_kernels import (
    fused_spanmetrics_matmul,
    fused_spanmetrics_scatter,
)

EDGES = (0.002, 0.008, 0.032, 0.128, 0.512)


@pytest.mark.parametrize("seed", [0, 1])
def test_matmul_kernel_matches_scatter(seed):
    rng = np.random.default_rng(seed)
    n, s = 1024, 64
    slots = rng.integers(-1, s, n).astype(np.int32)   # -1 = dropped rows
    dur = rng.lognormal(-3, 1.5, n).astype(np.float32)
    sizes = rng.integers(100, 5000, n).astype(np.float32)
    w = rng.random(n).astype(np.float32)

    a = fused_spanmetrics_matmul(
        jnp.asarray(slots), jnp.asarray(dur), jnp.asarray(sizes),
        jnp.asarray(w), n_series=s, edges=EDGES, block=256, interpret=True)
    b = fused_spanmetrics_scatter(
        jnp.asarray(slots), jnp.asarray(dur), jnp.asarray(sizes),
        jnp.asarray(w), n_series=s, edges=EDGES)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)
    # masked rows contributed nothing
    total_w = w[slots >= 0].sum()
    np.testing.assert_allclose(float(jnp.sum(a[:, 0])), total_w, rtol=1e-5)


# ---------------------------------------------------------------------------
# paged ragged fused kernel (interpret-mode smoke + fallback contract)
# ---------------------------------------------------------------------------

PAGE_ROWS = 8
PAGE_SHIFT = 3
N_PHYS = 6          # physical pages per arena, page 0 = trash
DD_GAMMA = 1.1
DD_MIN = 1e-6
DD_NB = 32
MOM_META = (4, float(np.log(1e-6)), float(np.log(1e5)))


def _arenas(dd=True, mom=True):
    rows = N_PHYS * PAGE_ROWS
    n_hist = len(EDGES) + 1
    out = [jnp.zeros(rows, jnp.float32) for _ in range(4)]
    out.append(jnp.zeros((rows, n_hist), jnp.float32))
    if dd:
        out.append(jnp.zeros(rows, jnp.float32))
        out.append(jnp.zeros((rows, DD_NB), jnp.float32))
    if mom:
        out.append(jnp.zeros((rows, MOM_META[0] + 3), jnp.float32))
    return tuple(out)


def _tables(n_roles, lpages=4):
    # logical pages 0..2 backed by phys 1..3 (page 0 reserved as trash),
    # logical page 3 deliberately UNBACKED
    t = np.full(lpages, -1, np.int32)
    t[:3] = [1, 2, 3]
    return tuple(jnp.asarray(t) for _ in range(n_roles))


def _batch(seed, n=32, lpages=4):
    rng = np.random.default_rng(seed)
    cap = lpages * PAGE_ROWS
    mat = np.empty((4, n), np.float32)
    mat[0] = rng.integers(-1, cap, n)           # incl. discards
    mat[1] = rng.lognormal(-3, 1.5, n)
    mat[2] = rng.integers(100, 5000, n)
    mat[3] = rng.integers(1, 4, n)              # integer HT weights
    return mat


@pytest.mark.parametrize("dd,mom", [(True, True), (True, False),
                                    (False, True)])
def test_paged_pallas_matches_composed_scatter(dd, mom):
    from tempo_tpu.ops import pages as op

    dd_rows = 2 * PAGE_ROWS if dd else 0     # strict prefix of the table
    mom_rows = 3 * PAGE_ROWS if mom else 0
    meta = dict(edges=EDGES, gamma=DD_GAMMA, min_value=DD_MIN,
                dd_rows=dd_rows, page_shift=PAGE_SHIFT, packed=True,
                mom_rows=mom_rows, mom_meta=MOM_META if mom else None)
    xla = op.fused_step(**dict(meta, kernel="xla"))
    pal = op.fused_step(**dict(meta, kernel="pallas", interpret=True))
    n_roles = 5 + (2 if dd else 0) + (1 if mom else 0)
    a_x, a_p = _arenas(dd, mom)[:n_roles], _arenas(dd, mom)[:n_roles]
    tabs = _tables(n_roles)
    for seed in range(3):
        mat = _batch(seed)
        a_x = xla(*a_x, *tabs, mat)
        a_p = pal(*a_p, *tabs, mat)
    for r, (x, p) in enumerate(zip(a_x, a_p)):
        # integer-count planes bit-identical (integer weights); float
        # sums to f32 reduction-order tolerance (module docstring)
        if r in (1, 3) or (mom and r == n_roles - 1):
            np.testing.assert_allclose(np.asarray(x), np.asarray(p),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=f"role {r}")
        else:
            np.testing.assert_array_equal(np.asarray(x), np.asarray(p),
                                          err_msg=f"role {r}")
        # the trash page and the never-allocated phys pages stayed zero
        assert not np.asarray(p)[:PAGE_ROWS].any(), f"role {r} trash"
        assert not np.asarray(p)[4 * PAGE_ROWS:].any(), f"role {r} free"


def test_paged_pallas_vec_route_matches_packed():
    from tempo_tpu.ops import pages as op

    meta = dict(edges=EDGES, gamma=DD_GAMMA, min_value=DD_MIN,
                dd_rows=2 * PAGE_ROWS, page_shift=PAGE_SHIFT,
                mom_rows=0, mom_meta=None, kernel="pallas",
                interpret=True)
    packed = op.fused_step(**dict(meta, packed=True))
    vec = op.fused_step(**dict(meta, packed=False))
    a1, a2 = _arenas(True, False), _arenas(True, False)
    tabs = _tables(7)
    mat = _batch(7)
    a1 = packed(*a1, *tabs, mat)
    a2 = vec(*a2, *tabs, mat[0].astype(np.int32), mat[1], mat[2], mat[3])
    for x, p in zip(a1, a2):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(p))


def test_paged_pallas_unbacked_and_discards_drop():
    from tempo_tpu.ops import pages as op

    step = op.fused_step(edges=EDGES, gamma=DD_GAMMA, min_value=DD_MIN,
                         dd_rows=0, page_shift=PAGE_SHIFT, packed=True,
                         kernel="pallas", interpret=True)
    arenas = _arenas(False, False)
    tabs = _tables(5)
    n = 16
    mat = np.zeros((4, n), np.float32)
    # half discards, half aimed at the UNBACKED logical page 3
    mat[0, :8] = -1
    mat[0, 8:] = 3 * PAGE_ROWS + np.arange(8)
    mat[1] = 0.5
    mat[2] = 100.0
    mat[3] = 1.0
    out = step(*arenas, *tabs, mat)
    for r, a in enumerate(out):
        assert not np.asarray(a).any(), f"role {r} should be untouched"


def _paged_processor(kernel, interpret=False, tenant="t"):
    from tempo_tpu.generator.processors.spanmetrics import (
        SpanMetricsConfig, SpanMetricsProcessor)
    from tempo_tpu.registry import pages as device_pages
    from tempo_tpu.registry.registry import ManagedRegistry, RegistryOverrides

    pool = device_pages.PagePool(device_pages.PagePoolConfig(
        enabled=True, page_rows=16, arena_slots=512))
    with device_pages.use(pool):
        reg = ManagedRegistry(tenant,
                              RegistryOverrides(max_active_series=64),
                              now=lambda: 1000.0)
        proc = SpanMetricsProcessor(reg, SpanMetricsConfig(
            use_scheduler=False, sketch_max_series=32, sketch_rel_err=0.05,
            kernel=kernel, pallas_interpret=interpret))
    return reg, proc


def test_cpu_fallback_single_warning(caplog):
    """The per-PR fallback contract: selecting `kernel: pallas` on a
    backend that cannot lower Mosaic falls back to the composed-scatter
    path with EXACTLY ONE process-wide warning (re-armed per test by the
    conftest reset), and dispatch behaves identically to `kernel: xla`."""
    import jax

    assert jax.default_backend() != "tpu"  # conftest pins CPU
    with caplog.at_level(logging.WARNING, logger="tempo_tpu.pages"):
        reg_a, proc_a = _paged_processor("pallas")
        reg_b, proc_b = _paged_processor("pallas", tenant="t2")
    warns = [r for r in caplog.records
             if "pallas" in r.getMessage() and "falling back" in r.getMessage()]
    assert len(warns) == 1, [r.getMessage() for r in warns]
    assert proc_a._kernel_tier == "xla" and proc_b._kernel_tier == "xla"
    # the devtime/coalescer label reflects the RESOLVED tier, so the
    # cost model never attributes xla dispatches to a pallas regime
    assert proc_a._sched_kernel == "spanmetrics_fused_update"

    # and the resolved path is exactly the xla tier: same state bytes
    from tempo_tpu.model.span_batch import SpanBatchBuilder
    reg_x, proc_x = _paged_processor("xla", tenant="t3")
    for reg, proc in ((reg_a, proc_a), (reg_x, proc_x)):
        b = SpanBatchBuilder(reg.interner)
        for i in range(5):
            b.append(trace_id=bytes(16), span_id=bytes(8), name=f"op{i}",
                     service="s", kind=2, status_code=0,
                     start_unix_nano=10**18,
                     end_unix_nano=10**18 + 10**7 * (i + 1))
        proc.push_batch(b.build())
    sa = sorted((s.name, s.labels, s.value) for s in reg_a.collect(1))
    sx = sorted((s.name, s.labels, s.value) for s in reg_x.collect(1))
    assert sa == sx


def test_sched_route_pallas_parity_and_ledger_label():
    """The sched-coalesced route on the pallas tier: merged windows ride
    the same paged pallas step under the kernel-tier numerics contract
    (counts bit-identical, float sums to f32 reduction-order tolerance),
    and the devtime ledger keys the dispatches under the tier's OWN
    kernel name so the cost model / WindowTuner never mixes regimes."""
    import time

    from tempo_tpu import sched
    from tempo_tpu.model.span_batch import SpanBatchBuilder
    from tempo_tpu.obs import devtime
    from tempo_tpu.sched import DeviceScheduler, SchedConfig

    def world(kernel):
        from tempo_tpu.generator.processors.spanmetrics import (
            SpanMetricsConfig, SpanMetricsProcessor)
        from tempo_tpu.registry import pages as device_pages
        from tempo_tpu.registry.registry import (ManagedRegistry,
                                                 RegistryOverrides)

        pool = device_pages.PagePool(device_pages.PagePoolConfig(
            enabled=True, page_rows=16, arena_slots=512))
        with device_pages.use(pool):
            reg = ManagedRegistry("t", RegistryOverrides(max_active_series=64),
                                  now=lambda: 1000.0)
            proc = SpanMetricsProcessor(reg, SpanMetricsConfig(
                use_scheduler=True, sketch_max_series=32,
                sketch_rel_err=0.05, kernel=kernel,
                pallas_interpret=(kernel == "pallas")))
        return reg, proc

    devtime.reset()
    outs = {}
    for kernel in ("pallas", "xla"):
        sc = DeviceScheduler(SchedConfig(batch_window_ms=5.0),
                             start_worker=True)
        try:
            with sched.use(sc):
                reg, proc = world(kernel)
                for i in range(3):
                    b = SpanBatchBuilder(reg.interner)
                    for j in range(9):
                        b.append(trace_id=bytes(16), span_id=bytes(8),
                                 name=f"op{(i + j) % 5}", service="s",
                                 kind=2, status_code=0,
                                 start_unix_nano=10**18,
                                 end_unix_nano=10**18 + 10**6 * (j + 1))
                    proc.push_batch(b.build())
                sc.flush()
                outs[kernel] = sorted((s.name, s.labels, s.value)
                                      for s in reg.collect(1))
        finally:
            sc.stop()
    # counts/buckets exact, float sums to the documented f32
    # reduction-order tolerance (MXU tree order vs scatter sort order)
    from test_plane_fuzz import _kt_compare
    _kt_compare(outs["pallas"], outs["xla"], "sched route")
    kernels = {k[0] for k in devtime.LEDGER.snapshot()}
    assert "spanmetrics_fused_update_pallas" in kernels
    assert "spanmetrics_fused_update" in kernels


def test_resolve_kernel_matrix(caplog):
    """Tier resolution: every unlowerable combination falls back to xla
    (one warning each), the lowerable ones keep pallas."""
    from tempo_tpu.ops import pages as op

    with caplog.at_level(logging.WARNING, logger="tempo_tpu.pages"):
        assert op.resolve_kernel("xla") == "xla"
        assert op.resolve_kernel("pallas", paged=False) == "xla"
        assert op.resolve_kernel("pallas", mesh_active=True) == "xla"
        assert op.resolve_kernel("pallas") == "xla"          # CPU backend
        assert op.resolve_kernel("pallas", interpret=True) == "pallas"
    msgs = [r.getMessage() for r in caplog.records
            if "falling back" in r.getMessage()]
    assert len(msgs) == 3           # one per distinct reason
    # repeated resolution stays silent (warn-once contract)
    n = len(caplog.records)
    op.resolve_kernel("pallas", mesh_active=True)
    assert len(caplog.records) == n


def test_interpret_tier_selected_on_cpu(caplog):
    """`pallas_interpret` (the debug/CI parity knob) keeps the pallas
    tier live on CPU — no fallback, no warning."""
    with caplog.at_level(logging.WARNING, logger="tempo_tpu.pages"):
        _, proc = _paged_processor("pallas", interpret=True)
    assert proc._kernel_tier == "pallas"
    assert proc._sched_kernel == "spanmetrics_fused_update_pallas"
    assert not [r for r in caplog.records if "falling back" in r.getMessage()]
