"""Pallas MXU kernel vs XLA scatter: identical state deltas."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from tempo_tpu.ops.pallas_kernels import (
    fused_spanmetrics_matmul,
    fused_spanmetrics_scatter,
)

EDGES = (0.002, 0.008, 0.032, 0.128, 0.512)


@pytest.mark.parametrize("seed", [0, 1])
def test_matmul_kernel_matches_scatter(seed):
    rng = np.random.default_rng(seed)
    n, s = 1024, 64
    slots = rng.integers(-1, s, n).astype(np.int32)   # -1 = dropped rows
    dur = rng.lognormal(-3, 1.5, n).astype(np.float32)
    sizes = rng.integers(100, 5000, n).astype(np.float32)
    w = rng.random(n).astype(np.float32)

    a = fused_spanmetrics_matmul(
        jnp.asarray(slots), jnp.asarray(dur), jnp.asarray(sizes),
        jnp.asarray(w), n_series=s, edges=EDGES, block=256, interpret=True)
    b = fused_spanmetrics_scatter(
        jnp.asarray(slots), jnp.asarray(dur), jnp.asarray(sizes),
        jnp.asarray(w), n_series=s, edges=EDGES)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)
    # masked rows contributed nothing
    total_w = w[slots >= 0].sum()
    np.testing.assert_allclose(float(jnp.sum(a[:, 0])), total_w, rtol=1e-5)
