"""localblocks processor + span-metrics summary (traceqlmetrics analog)."""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.generator.instance import GeneratorConfig, GeneratorInstance
from tempo_tpu.generator.processors.localblocks import (
    LocalBlocksConfig,
    LocalBlocksProcessor,
)
from tempo_tpu.model.span_batch import SpanBatchBuilder
from tempo_tpu.traceql.engine_metrics import QueryRangeRequest
from tempo_tpu.traceql.metrics_summary import (
    LatencyHistogram,
    bucketize_ns,
    get_metrics,
)
from tempo_tpu.traceql.memview import view_from_traces

T0 = 1_700_000_000.0


def build_batch(n=20, interner=None, t0_s=T0):
    b = SpanBatchBuilder(interner)
    for i in range(n):
        tid = bytes([i + 1]) * 16
        b.append(trace_id=tid, span_id=bytes([1]) * 8,
                 name=f"op-{i % 3}", service=f"svc-{i % 2}",
                 status_code=(2 if i % 5 == 0 else 0),
                 start_unix_nano=int((t0_s + i) * 1e9),
                 end_unix_nano=int((t0_s + i) * 1e9) + (1 << (20 + i % 4)),
                 attrs={"http.path": f"/p{i % 2}", "n": i})
    return b.build()


def test_span_dicts_respect_valid_mask():
    """Rows invalidated (e.g. slack-filtered) must not be persisted."""
    import dataclasses as dc
    sb = build_batch(5)
    valid = sb.valid.copy()
    valid[2] = False
    sb2 = dc.replace(sb, valid=valid)
    spans = sb2.to_span_dicts()
    assert len(spans) == 4
    assert all(s["trace_id"] != bytes([3]) * 16 for s in spans)


def test_span_dicts_round_trip():
    sb = build_batch(5)
    spans = sb.to_span_dicts()
    assert len(spans) == 5
    s = spans[0]
    assert s["name"] == "op-0" and s["service"] == "svc-0"
    assert s["attrs"]["http.path"] == "/p0" and s["attrs"]["n"] == 0
    assert isinstance(s["attrs"]["n"], int)
    assert s["status_code"] == 2


def test_bucketize_matches_reference_semantics():
    # smallest b with 2^b >= d (metrics.go Record)
    assert bucketize_ns(np.array([1])).tolist() == [0]
    assert bucketize_ns(np.array([2])).tolist() == [1]
    assert bucketize_ns(np.array([3])).tolist() == [2]
    assert bucketize_ns(np.array([1024])).tolist() == [10]
    assert bucketize_ns(np.array([1025])).tolist() == [11]


def test_latency_histogram_percentile():
    h = LatencyHistogram.empty()
    h.buckets[10] = 100  # all values in (512, 1024]
    p50 = h.percentile(0.5)
    assert 512 < p50 <= 1024
    assert h.percentile(1.0) == 1024
    # interpolation is monotone
    assert h.percentile(0.1) <= h.percentile(0.5) <= h.percentile(0.9)


def test_get_metrics_grouping_and_errors():
    sb = build_batch(20)
    traces = {}
    for s in sb.to_span_dicts():
        traces.setdefault(s["trace_id"], []).append(s)
    view = view_from_traces(list(traces.items()))
    views = [(view, np.arange(view.n))]
    res = get_metrics("{ }", ["resource.service.name"], iter(views))
    assert len(res.series) == 2
    total = sum(s.histogram.count for s in res.results())
    assert total == 20
    errs = sum(s.error_count for s in res.results())
    assert errs == 4  # i % 5 == 0 → 0,5,10,15
    # filtered
    views = [(view, np.arange(view.n))]
    res2 = get_metrics('{ resource.service.name = "svc-0" }', [], iter(views))
    assert res2.results()[0].histogram.count == 10
    js = res.results()[0].to_json()
    assert js["p50"] > 0 and js["spanCount"] > 0


def test_localblocks_lifecycle_and_query(tmp_path):
    clock = [T0 + 100]
    now = lambda: clock[0]
    be = MemBackend()
    p = LocalBlocksProcessor(
        "t1",
        LocalBlocksConfig(data_dir=str(tmp_path), trace_idle_s=1.0,
                          max_block_duration_s=10.0, flush_to_storage=True),
        flush_writer=be, now=now)
    p.push_batch(build_batch(20))
    # live → query works immediately
    req = QueryRangeRequest(query="{ } | rate()",
                            start_ns=int(T0 * 1e9),
                            end_ns=int((T0 + 60) * 1e9),
                            step_ns=int(60 * 1e9))
    series = p.query_range(req)
    assert sum(float(np.nansum(s.samples)) for s in series) > 0
    # cut to WAL then to complete block
    clock[0] += 2
    p.cut_tick()
    clock[0] += 11
    p.cut_tick()
    assert len(p.inst.complete_blocks()) == 1
    meta = next(iter(p.inst.complete.values())).meta
    assert meta.replication_factor == 1      # RF1: metrics-eligible
    # flushed to object storage
    from tempo_tpu.backend.raw import blocks as list_blocks
    assert meta.block_id in list_blocks(be, "t1")
    # queries still see the data (now in the complete block)
    series = p.query_range(req)
    # job-level series are raw counts; the frontend combiner divides by step
    assert sum(float(np.nansum(s.samples)) for s in series) == 20
    res = p.get_metrics("{ }", ["name"])
    assert sum(s.histogram.count for s in res.results()) == 20


def test_generator_instance_localblocks_wiring(tmp_path):
    clock = [T0]
    cfg = GeneratorConfig(
        processors=("span-metrics", "local-blocks"),
        localblocks=LocalBlocksConfig(data_dir=str(tmp_path), trace_idle_s=1.0))
    gi = GeneratorInstance("t1", cfg, now=lambda: clock[0])
    sb = build_batch(10, interner=gi.registry.interner, t0_s=clock[0] - 5)
    gi.push_batch(sb)
    req = QueryRangeRequest(query="{ } | count_over_time()",
                            start_ns=int((clock[0] - 60) * 1e9),
                            end_ns=int((clock[0] + 60) * 1e9),
                            step_ns=int(120 * 1e9))
    series = gi.query_range(req)
    assert sum(float(np.nansum(s.samples)) for s in series) == 10
    res = gi.get_metrics("{ }", ["resource.service.name"])
    assert sum(s.histogram.count for s in res.results()) == 10
    gi.tick()  # maintenance pass runs without error


def test_generator_service_push_and_query(tmp_path):
    """Generator service: the distributor's client protocol end-to-end,
    through overrides-driven processor selection."""
    from tempo_tpu.generator import Generator
    from tempo_tpu.overrides import Overrides

    clock = [T0]
    ov = Overrides()
    ov.set_tenant_patch("t1", {"generator": {
        "processors": ["span-metrics", "local-blocks"]}})
    g = Generator(GeneratorConfig(
        localblocks=LocalBlocksConfig(data_dir=str(tmp_path))),
        overrides=ov, now=lambda: clock[0])
    spans = []
    for i in range(15):
        t0 = int((clock[0] - 5) * 1e9)
        spans.append({"trace_id": bytes([i + 1]) * 16, "span_id": b"\x01" * 8,
                      "name": "op", "service": "svc",
                      "start_unix_nano": t0, "end_unix_nano": t0 + 10 ** 7})
    g.push_spans("t1", spans)
    assert set(g.instance("t1").processors) == {"span-metrics", "local-blocks"}
    req = QueryRangeRequest(query="{ } | count_over_time()",
                            start_ns=int((clock[0] - 60) * 1e9),
                            end_ns=int((clock[0] + 60) * 1e9),
                            step_ns=int(120 * 1e9))
    series = g.query_range("t1", req)
    assert sum(float(np.nansum(s.samples)) for s in series) == 15
    # unknown tenant → empty, not an instance spawn
    assert g.query_range("ghost", req) == []
    assert "ghost" not in g.instances
    # collection tick covers all tenants
    g.collect_all()


def test_generator_without_localblocks_raises():
    gi = GeneratorInstance("t1", GeneratorConfig(processors=("span-metrics",)))
    with pytest.raises(RuntimeError):
        gi.get_metrics("{ }", [])
