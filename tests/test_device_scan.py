"""Device predicate plane vs the numpy mask loop: identical candidates.

The storage prefilter (`condition_mask`) may run dictionary-coded masks on
device (`block/device_scan.py`); the numpy path is the semantic reference.
Both must produce the same candidate rows for every supported shape, and
unsupported shapes must fall back cleanly.
"""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.block import device_scan
from tempo_tpu.db.tempodb import TempoDB
from tempo_tpu.traceql.conditions import extract_conditions
from tempo_tpu.traceql.parser import parse

T0 = 1_700_000_000


@pytest.fixture(scope="module")
def block_db():
    rng = np.random.default_rng(42)
    be = MemBackend()
    db = TempoDB(be, be)
    traces = []
    for i in range(600):
        tid = rng.bytes(16)
        start = int((T0 + i) * 1e9)
        traces.append((tid, [{
            "trace_id": tid, "span_id": rng.bytes(8),
            "name": f"op-{i % 7}", "service": f"svc-{i % 4}",
            "kind": int(i % 6), "status_code": int(i % 3),
            "start_unix_nano": start,
            "end_unix_nano": start + int(rng.integers(1, 500)) * 1_000_000,
            "attrs": {"http.status_code": 200 + (i % 300)},
        }]))
    db.write_block("t", traces, replication_factor=1)
    db.poll_now()
    return db


QUERIES = [
    '{ name = "op-3" }',
    '{ name != "op-3" }',
    '{ name =~ "op-[12]" }',
    '{ name !~ "op-[12]" }',
    '{ resource.service.name = "svc-2" }',
    '{ duration > 100ms }',
    '{ duration <= 20ms }',
    '{ kind = server }',
    '{ status = error }',
    '{ name = "op-3" && duration > 50ms }',
    '{ name = "op-1" || name = "op-2" }',
    # unsupported on device (attr list column) -> numpy fallback, still equal
    '{ span.http.status_code >= 400 }',
    '{ name = "op-3" && span.http.status_code >= 400 }',
]


def _candidates(db, query: str) -> list[tuple[int, np.ndarray]]:
    from tempo_tpu.block.fetch import scan_views
    from tempo_tpu.block.reader import BackendBlock

    q = parse(query)
    req = extract_conditions(q)
    out = []
    metas = db.blocklist.metas("t")
    for m in metas:
        block = BackendBlock(db.r, m)
        for i, (view, cand) in enumerate(scan_views(block, req)):
            out.append((i, np.sort(cand)))
    return out


@pytest.mark.parametrize("query", QUERIES)
def test_device_mask_matches_numpy(block_db, query, monkeypatch):
    monkeypatch.setenv("TEMPO_TPU_DEVICE_SCAN", "1")
    dev = _candidates(block_db, query)
    monkeypatch.setenv("TEMPO_TPU_DEVICE_SCAN", "0")
    ref = _candidates(block_db, query)
    assert len(dev) == len(ref)
    for (i, a), (j, b) in zip(dev, ref):
        assert i == j
        np.testing.assert_array_equal(a, b)


def test_device_plane_actually_engages(block_db, monkeypatch):
    """Sanity: the supported shapes really take the device path (guard
    against silent permanent fallback)."""
    from tempo_tpu.block.fetch import scan_views
    from tempo_tpu.block.reader import BackendBlock

    monkeypatch.setenv("TEMPO_TPU_DEVICE_SCAN", "1")
    q = parse('{ name = "op-3" && duration > 50ms }')
    req = extract_conditions(q)
    meta = block_db.blocklist.metas("t")[0]
    block = BackendBlock(block_db.r, meta)
    for view, _cand in scan_views(block, req):
        preds = [c for c in req.conditions if c.op is not None]
        mask = device_scan.device_pred_mask(view, preds, req.all_conditions)
        assert mask is not None and mask.dtype == bool
        break


def test_regex_is_anchored(block_db):
    """Regression: device regexes must fullmatch like the numpy plane —
    `op-1` must NOT match `op-10` (and !~ must keep it)."""
    from tempo_tpu.block.fetch import scan_views
    from tempo_tpu.block.reader import BackendBlock
    from tempo_tpu.traceql.ast import Op

    meta = block_db.blocklist.metas("t")[0]
    block = BackendBlock(block_db.r, meta)
    views = [v for v, _ in scan_views(block, None)]
    plane = device_scan.BlockScanPlane(views)
    q = parse('{ name =~ "op-1" }')
    req = extract_conditions(q)
    preds = [c for c in req.conditions if c.op is not None]
    mask = plane.mask(preds, req.all_conditions)
    names = np.concatenate([np.asarray(v.col("name").values) for v in views])
    assert mask is not None
    assert set(names[mask]) == {"op-1"}, set(names[mask])


def test_device_query_range_grid_matches_engine(block_db):
    """The full device metrics path — mask → step bucket → group scatter in
    ONE dispatch over the resident block — must produce the same counts as
    the engine's query_range for supported shapes."""
    from tempo_tpu.block.fetch import scan_views
    from tempo_tpu.block.reader import BackendBlock
    from tempo_tpu.traceql.engine_metrics import QueryRangeRequest

    meta = block_db.blocklist.metas("t")[0]
    block = BackendBlock(block_db.r, meta)
    views = [v for v, _ in scan_views(block, None)]
    plane = device_scan.BlockScanPlane(views)
    plane.load_times(views)

    start_ns = int(T0 * 1e9)
    end_ns = int((T0 + 600) * 1e9)
    step_ns = int(100 * 1e9)

    cases = [
        ('{ } | rate() by (name)', "name", []),
        ('{ } | count_over_time() by (resource.service.name)', "service", []),
        ('{ duration > 100ms } | rate() by (name)', "name", None),
        ('{ name = "op-3" } | count_over_time()', None, None),
    ]
    for query, group, _ in cases:
        req = QueryRangeRequest(query=query, start_ns=start_ns,
                                end_ns=end_ns, step_ns=step_ns)
        engine_series = block_db.query_range("t", req)
        # engine returns final-pass series: rate divides by step seconds
        q = parse(query)
        preds = [c for c in extract_conditions(q).conditions
                 if c.op is not None]
        got = plane.query_range_grid(
            preds, True, group, start_ns, end_ns, step_ns)
        assert got is not None, query
        labels, grid = got
        # db.query_range returns job-level RAW counts (AggregateModeSum;
        # the frontend's final pass applies the rate division)
        eng = {}
        for s in engine_series:
            d = dict(s.labels)
            key = d.get("name") or d.get("resource.service.name") or None
            eng[key] = np.nan_to_num(np.asarray(s.samples))
        for gi, label in enumerate(labels):
            row = grid[gi]
            if label not in eng:
                assert row.sum() == 0, (query, label, row)
                continue
            np.testing.assert_allclose(row, eng[label], rtol=1e-5,
                                       err_msg=f"{query} group={label}")


def test_device_query_range_unaligned_window(block_db):
    """Non-step-aligned end: the last bucket must clip at end_ns exactly
    like the engine (regression: spans past end_ns were counted while the
    ceil'd last step covered them)."""
    from tempo_tpu.block.fetch import scan_views
    from tempo_tpu.block.reader import BackendBlock
    from tempo_tpu.traceql.engine_metrics import (MetricsEvaluator,
                                                  QueryRangeRequest)

    meta = block_db.blocklist.metas("t")[0]
    block = BackendBlock(block_db.r, meta)
    views = [v for v, _ in scan_views(block, None)]
    plane = device_scan.BlockScanPlane(views)
    plane.load_times(views)
    # 250s window over 100s steps: last bucket covers only 50s of data
    start_ns = int(T0 * 1e9)
    end_ns = int((T0 + 250) * 1e9)
    step_ns = int(100 * 1e9)
    req = QueryRangeRequest(query="{ } | rate() by (name)",
                            start_ns=start_ns, end_ns=end_ns,
                            step_ns=step_ns)
    ev = MetricsEvaluator(req)
    for v in views:
        ev.observe(v)
    eng = {dict(s.labels)["name"]: np.nan_to_num(np.asarray(s.samples))
           for s in ev.results()}
    labels, grid = plane.query_range_grid([], True, "name",
                                          start_ns, end_ns, step_ns)
    assert grid.sum() == 250        # spans at T0..T0+249 inclusive
    for gi, lbl in enumerate(labels):
        np.testing.assert_allclose(grid[gi], eng[lbl], rtol=1e-5,
                                   err_msg=lbl)
