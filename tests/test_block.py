"""Block encoding tests: round-trip, nested set, trace-by-id, bloom, WAL
(reference test models: vparquet4 create/fetch round-trip tests,
nested_set_model_test.go, wal_test.go)."""

import numpy as np
import pytest

from tempo_tpu.backend import MemBackend, read_block_meta
from tempo_tpu.block import (
    BackendBlock,
    BloomFilter,
    ShardedBloom,
    WALBlock,
    nested_set,
    rescan_blocks,
    spans_by_trace,
    write_block,
)
from tempo_tpu.backend.meta import DedicatedColumn
from tempo_tpu.utils.livetraces import (
    ERR_LIVE_TRACES_EXCEEDED,
    ERR_TRACE_TOO_LARGE,
    LiveTraceStore,
)


def mkspan(tid, sid, parent=b"", name="op", service="svc", start=1_000, dur=50,
           attrs=None, res_attrs=None, **kw):
    return {
        "trace_id": tid, "span_id": sid, "parent_span_id": parent,
        "name": name, "service": service, "kind": 2, "status_code": 0,
        "status_message": "", "start_unix_nano": start,
        "end_unix_nano": start + dur, "attrs": attrs or {},
        "res_attrs": res_attrs or {}, **kw,
    }


def trace(tid_byte: int, n_spans: int = 3, **kw):
    tid = bytes([tid_byte] * 16)
    spans = [mkspan(tid, bytes([tid_byte, j] + [0] * 6),
                    parent=b"" if j == 0 else bytes([tid_byte, 0] + [0] * 6),
                    start=1_000_000_000 * tid_byte + j, **kw)
             for j in range(n_spans)]
    return tid, spans


# -- nested set --------------------------------------------------------------

def test_nested_set_chain():
    # root -> a -> b
    sids = [b"r" * 8, b"a" * 8, b"b" * 8]
    pids = [b"", b"r" * 8, b"a" * 8]
    left, right, parent = nested_set(sids, pids)
    assert parent == [-1, 0, 1]
    # containment: descendant interval inside ancestor interval
    assert left[0] < left[1] < left[2] < right[2] < right[1] < right[0]


def test_nested_set_orphan_and_cycle():
    sids = [b"a" * 8, b"b" * 8, b"c" * 8, b"d" * 8]
    pids = [b"", b"x" * 8, b"d" * 8, b"c" * 8]  # b orphan; c<->d cycle
    left, right, parent = nested_set(sids, pids)
    assert parent[0] == -1 and parent[1] == -1
    assert all(l > 0 and r > l for l, r in zip(left, right))


# -- bloom -------------------------------------------------------------------

def test_bloom_membership():
    ids = [bytes([i] * 16) for i in range(100)]
    bf = BloomFilter(len(ids), fpp=0.01)
    bf.add_many(ids)
    assert all(i in bf for i in ids)
    other = [bytes([200, i] + [7] * 14) for i in range(100)]
    fp = sum(1 for o in other if o in bf)
    assert fp <= 5
    rt = BloomFilter.from_bytes(bf.to_bytes())
    assert all(i in rt for i in ids)


def test_sharded_bloom_routes_by_first_byte():
    sb = ShardedBloom(4, 100)
    tid = bytes([7] + [0] * 15)
    sb.add(tid)
    assert sb.shard_of(tid) == 3
    assert tid in sb


# -- block round trip --------------------------------------------------------

@pytest.fixture
def block():
    be = MemBackend()
    traces = [trace(i, n_spans=4, attrs={"http.status_code": 200 + i, "route": f"/r{i}"},
                    res_attrs={"cluster": "c1"}) for i in range(1, 20)]
    meta = write_block(be, "t1", traces, row_group_rows=24,
                       dedicated_columns=[DedicatedColumn("span", "route")])
    return be, meta, traces


def test_write_block_meta_stats(block):
    be, meta, traces = block
    assert meta.total_objects == 19
    assert meta.total_spans == 19 * 4
    assert meta.size_bytes > 0
    got = read_block_meta(be, meta.block_id, "t1")
    assert got.version == "vtpu1"
    assert [c.name for c in got.dedicated_columns] == ["route"]


def test_find_trace_by_id(block):
    be, meta, traces = block
    b = BackendBlock(be, meta)
    tid, spans = traces[7]
    got = b.find_trace_by_id(tid)
    assert got is not None and len(got) == 4
    assert {s["name"] for s in got} == {"op"}
    assert got[0]["attrs"]["http.status_code"] == 200 + 8
    assert got[0]["res_attrs"]["cluster"] == "c1"
    # absent trace: bloom or scan miss
    assert b.find_trace_by_id(bytes([99] * 16)) is None


def test_column_batches_scan(block):
    be, meta, traces = block
    b = BackendBlock(be, meta)
    rows = 0
    for cb in b.column_batches(columns=["trace_idx", "duration_ns", "service"]):
        rows += cb["_rows"]
        assert cb["duration_ns"].dtype == np.int64
        assert (cb["duration_ns"] == 50).all()
    assert rows == meta.total_spans
    # multiple row groups given row_group_rows=24 < 76 spans
    assert len(b.row_group_index()) > 1


def test_dedicated_column(block):
    be, meta, traces = block
    b = BackendBlock(be, meta)
    name = b.dedicated_column_name("span", "route")
    assert name == "ded_s_00"
    vals = set()
    for cb in b.column_batches(columns=[name]):
        vals.update(cb[name].tolist())
    assert "/r1" in vals


# -- WAL ---------------------------------------------------------------------

def test_wal_append_replay_complete(tmp_path):
    w = WALBlock(str(tmp_path), "t1")
    t1, s1 = trace(1)
    t2, s2 = trace(2)
    w.append(s1[:2])
    w.append(s1[2:] + s2)
    # replay from disk via fresh handle
    blocks = rescan_blocks(str(tmp_path))
    assert len(blocks) == 1 and blocks[0].block_id == w.block_id
    groups = blocks[0].complete()
    assert [tid for tid, _ in groups] == [t1, t2]
    assert len(groups[0][1]) == 3 and len(groups[1][1]) == 3
    assert blocks[0].find_trace_by_id(t2) is not None
    blocks[0].clear()
    assert rescan_blocks(str(tmp_path)) == []


def test_wal_to_complete_block(tmp_path):
    be = MemBackend()
    w = WALBlock(str(tmp_path), "t1")
    for i in range(1, 6):
        _, spans = trace(i)
        w.append(spans)
    meta = write_block(be, "t1", w.complete(), block_id=w.block_id)
    assert meta.total_objects == 5
    b = BackendBlock(be, meta)
    assert b.find_trace_by_id(bytes([3] * 16)) is not None


# -- live traces -------------------------------------------------------------

def test_livetraces_limits_and_cutting():
    now = [100.0]
    st = LiveTraceStore(max_live_traces=2, max_trace_bytes=500, now=lambda: now[0])
    assert st.push(b"t1", [mkspan(b"t1" * 8, b"s1")]) is None
    assert st.push(b"t2", [mkspan(b"t2" * 8, b"s2")]) is None
    assert st.push(b"t3", [mkspan(b"t3" * 8, b"s3")]) == ERR_LIVE_TRACES_EXCEEDED
    assert st.push(b"t1", [mkspan(b"t1" * 8, b"s4")], size_bytes=1000) == ERR_TRACE_TOO_LARGE
    now[0] = 110.0
    st.push(b"t2", [mkspan(b"t2" * 8, b"s5")])
    cut = st.cut(idle_s=5.0)  # t1 idle 10s, t2 just appended
    assert [c.trace_id for c in cut] == [b"t1"]
    assert [c.trace_id for c in st.cut(immediate=True)] == [b"t2"]
    assert len(st) == 0
