"""SpanBatch / interner / OTLP decode tests (pkg/model + receiver analog)."""

import json

import numpy as np

from tempo_tpu.model import (
    KIND_SERVER,
    STATUS_ERROR,
    SpanBatchBuilder,
    StringInterner,
    otlp_json_to_batch,
    otlp_proto_to_batch,
)
from tempo_tpu.model import proto_wire as pw
from tempo_tpu.model.interner import INVALID_ID
from tempo_tpu.model.span_batch import synthetic_batch


def test_interner_roundtrip():
    it = StringInterner()
    a, b, a2 = it.intern("alpha"), it.intern("beta"), it.intern("alpha")
    assert a == a2 != b
    assert it.lookup(b) == "beta"
    assert it.get("gamma") == INVALID_ID
    assert it.lookup_many(np.array([a, b, INVALID_ID])) == ["alpha", "beta", ""]


def test_builder_padding_and_columns():
    b = SpanBatchBuilder()
    for i in range(10):
        b.append(
            trace_id=bytes([i]) * 16, span_id=bytes([i]) * 8,
            name=f"op-{i % 3}", service="svc", kind=KIND_SERVER,
            status_code=STATUS_ERROR if i == 0 else 0,
            start_unix_nano=1_000 + i, end_unix_nano=2_000 + i,
            attrs={"http.status_code": 500, "route": f"/r/{i % 2}"},
            res_attrs={"service.name": "svc", "cluster": "c1"},
        )
    sb = b.build()
    assert sb.n == 10 and sb.capacity == 256  # padded to bucket
    assert sb.valid[:10].all() and not sb.valid[10:].any()
    assert (sb.duration_ns[:10] == 1000).all()
    col = sb.attr_sval_column("route")
    routes = set(sb.interner.lookup_many(col[:10]))
    assert routes == {"/r/0", "/r/1"}
    assert (col[10:] == INVALID_ID).all()
    # numeric attr exposed through fval
    kid = sb.interner.get("http.status_code")
    hit = sb.span_attr_key[:10] == kid
    assert (sb.span_attr_fval[:10][hit] == 500.0).all()


def test_synthetic_batch_shapes():
    sb = synthetic_batch(1000, n_services=4, seed=1)
    assert sb.n == 1000 and sb.capacity == 1024
    dv, base = sb.device_view()
    assert dv["duration_ns"].shape == (1024,)
    assert dv["valid"].sum() == 1000
    assert (dv["start_rel_s"][:1000] >= 0).all()


def test_otlp_json_decode():
    payload = {
        "resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": "frontend"}}]},
            "scopeSpans": [{"spans": [{
                "traceId": "0102030405060708090a0b0c0d0e0f10",
                "spanId": "0102030405060708",
                "name": "GET /",
                "kind": "SPAN_KIND_SERVER",
                "startTimeUnixNano": "1000000000",
                "endTimeUnixNano": "1500000000",
                "status": {"code": "STATUS_CODE_ERROR", "message": "boom"},
                "attributes": [
                    {"key": "http.status_code", "value": {"intValue": "500"}}],
            }]}],
        }]
    }
    sb = otlp_json_to_batch(json.loads(json.dumps(payload)))
    assert sb.n == 1
    assert sb.interner.lookup(int(sb.name_id[0])) == "GET /"
    assert sb.interner.lookup(int(sb.service_id[0])) == "frontend"
    assert int(sb.kind[0]) == KIND_SERVER
    assert int(sb.status_code[0]) == STATUS_ERROR
    assert int(sb.duration_ns[0]) == 500000000
    assert sb.trace_id[0, 0] == 1 and sb.trace_id[0, 15] == 0x10


def _build_otlp_proto() -> bytes:
    def kv(key, buf):
        return pw.enc_field_msg(1, pw.enc_field_str(1, key)[2:]) if False else None

    def keyvalue(key: str, anyvalue: bytes) -> bytes:
        return pw.enc_field_str(1, key) + pw.enc_field_msg(2, anyvalue)

    sv = lambda s: pw.enc_field_str(1, s)
    iv = lambda i: pw.enc_field_varint(3, i)
    resource = pw.enc_field_msg(1, keyvalue("service.name", sv("cart")))
    status = pw.enc_field_varint(3, 2) + pw.enc_field_str(2, "err")
    span = (
        pw.enc_field_bytes(1, bytes(range(16)))
        + pw.enc_field_bytes(2, bytes(range(8)))
        + pw.enc_field_str(5, "checkout")
        + pw.enc_field_varint(6, 3)  # client
        + pw.enc_field_fixed64(7, 10**9)
        + pw.enc_field_fixed64(8, 2 * 10**9)
        + pw.enc_field_msg(9, keyvalue("retries", iv(4)))
        + pw.enc_field_msg(15, status)
    )
    scope_spans = pw.enc_field_msg(2, span)
    resource_spans = pw.enc_field_msg(1, resource) + pw.enc_field_msg(2, scope_spans)
    return pw.enc_field_msg(1, resource_spans)


def test_otlp_proto_decode():
    sb = otlp_proto_to_batch(_build_otlp_proto())
    assert sb.n == 1
    assert sb.interner.lookup(int(sb.name_id[0])) == "checkout"
    assert sb.interner.lookup(int(sb.service_id[0])) == "cart"
    assert int(sb.kind[0]) == 3
    assert int(sb.status_code[0]) == 2
    assert int(sb.duration_ns[0]) == 10**9
    kid = sb.interner.get("retries")
    hit = sb.span_attr_key[0] == kid
    assert hit.any() and (sb.span_attr_fval[0][hit] == 4.0).all()


def test_varint_roundtrip():
    for v in (0, 1, 127, 128, 300, 2**32, 2**63 - 1):
        enc = pw.enc_varint(v)
        dec, pos = pw.read_varint(enc, 0)
        assert dec == v and pos == len(enc)
