"""In-process mock Kafka broker (the kfake/testkafka analog).

A threaded socket server speaking the Kafka binary-protocol subset
`ingest/kafka.py` uses — Produce v3, Fetch v4, OffsetCommit v2,
OffsetFetch v1 — with independent verification of the wire: framing,
correlation ids, and the v2 RecordBatch layout INCLUDING the CRC32C
(computed here with its own table), so client-side encoding bugs fail
the way they would against a real broker.
"""

from __future__ import annotations

import struct
import threading

# independent crc32c table (same Castagnoli polynomial, built separately)
_TAB = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _TAB.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _TAB[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _i16(v):
    return struct.pack(">h", v)


def _i32(v):
    return struct.pack(">i", v)


def _i64(v):
    return struct.pack(">q", v)


class _R:
    def __init__(self, b):
        self.b = b
        self.i = 0

    def take(self, fmt):
        v = struct.unpack_from(fmt, self.b, self.i)[0]
        self.i += struct.calcsize(fmt)
        return v

    def string(self):
        n = self.take(">h")
        if n < 0:
            return None
        v = self.b[self.i:self.i + n].decode()
        self.i += n
        return v

    def bytes_(self):
        n = self.take(">i")
        if n < 0:
            return None
        v = self.b[self.i:self.i + n]
        self.i += n
        return v

    def uvarint(self):
        out = shift = 0
        while True:
            b = self.b[self.i]
            self.i += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def varint(self):
        v = self.uvarint()
        return (v >> 1) ^ -(v & 1)


class MockKafkaBroker:
    """One broker, N partitions per topic, stores (key, value) records."""

    def __init__(self, n_partitions: int = 2) -> None:
        self.n_partitions = n_partitions
        self.logs: dict[tuple[str, int], list[tuple[bytes, bytes]]] = {}
        self.offsets: dict[tuple[str, str, int], int] = {}
        self.lock = threading.Lock()
        self.produce_batches = 0      # verified batches accepted

    # -- record batch verification + decode ---------------------------------

    def _decode_batch(self, buf: bytes) -> list[tuple[bytes, bytes]]:
        r = _R(buf)
        out = []
        while r.i + 61 <= len(buf):
            r.take(">q")                        # base offset
            blen = r.take(">i")
            end = r.i + blen
            r.take(">i")                        # leader epoch
            magic = r.take(">b")
            if magic != 2:
                raise ValueError(f"bad magic {magic}")
            crc = r.take(">I")
            want = _crc32c(buf[r.i:end])
            if crc != want:
                raise ValueError(f"crc mismatch {crc:#x} != {want:#x}")
            r.take(">h"); r.take(">i")
            r.take(">q"); r.take(">q")
            r.take(">q"); r.take(">h"); r.take(">i")
            n = r.take(">i")
            for _ in range(n):
                r.varint()
                r.take(">b")
                r.varint(); r.varint()
                klen = r.varint()
                key = buf[r.i:r.i + max(klen, 0)]; r.i += max(klen, 0)
                vlen = r.varint()
                val = buf[r.i:r.i + max(vlen, 0)]; r.i += max(vlen, 0)
                for _h in range(r.uvarint()):
                    hk = r.varint(); r.i += max(hk, 0)
                    hv = r.varint(); r.i += max(hv, 0)
                out.append((bytes(key), bytes(val)))
            r.i = end
        return out

    def _encode_batch(self, base: int, recs: list[tuple[bytes, bytes]]
                      ) -> bytes:
        body = bytearray()
        for i, (k, v) in enumerate(recs):
            rec = (struct.pack(">b", 0) + _zig(0) + _zig(i) +
                   _zig(len(k)) + k + _zig(len(v)) + v + b"\x00")
            body += _zig(len(rec)) + rec
        after = (_i16(0) + _i32(len(recs) - 1) + _i64(0) + _i64(0) +
                 _i64(-1) + _i16(-1) + _i32(-1) + _i32(len(recs)) +
                 bytes(body))
        crc = _crc32c(after)
        inner = _i32(0) + struct.pack(">b", 2) + struct.pack(">I", crc) + after
        return _i64(base) + _i32(len(inner)) + inner

    # -- api handlers --------------------------------------------------------

    def handle(self, api_key: int, api_version: int, body: bytes) -> bytes:
        if api_key == 0:
            return self._produce(body)
        if api_key == 1:
            return self._fetch(body)
        if api_key == 8:
            return self._offset_commit(body)
        if api_key == 9:
            return self._offset_fetch(body)
        raise ValueError(f"unsupported api key {api_key}")

    def _produce(self, body: bytes) -> bytes:
        r = _R(body)
        r.string()                              # transactional id
        r.take(">h")                            # acks
        r.take(">i")                            # timeout
        out_topics = []
        for _t in range(r.take(">i")):
            topic = r.string()
            parts = []
            for _p in range(r.take(">i")):
                part = r.take(">i")
                batch = r.bytes_() or b""
                recs = self._decode_batch(batch)
                with self.lock:
                    log = self.logs.setdefault((topic, part), [])
                    base = len(log)
                    log.extend(recs)
                    self.produce_batches += 1
                parts.append(_i32(part) + _i16(0) + _i64(base) + _i64(-1))
            out_topics.append(
                _str(topic) + _i32(len(parts)) + b"".join(parts))
        return (_i32(len(out_topics)) + b"".join(out_topics) + _i32(0))

    def _fetch(self, body: bytes) -> bytes:
        r = _R(body)
        r.take(">i"); r.take(">i"); r.take(">i"); r.take(">i")
        r.take(">b")                            # isolation
        out_topics = []
        for _t in range(r.take(">i")):
            topic = r.string()
            parts = []
            for _p in range(r.take(">i")):
                part = r.take(">i")
                offset = r.take(">q")
                max_bytes = r.take(">i")
                with self.lock:
                    log = list(self.logs.get((topic, part), []))
                hw = len(log)
                recs = log[offset:]
                batch = (self._encode_batch(offset, recs)
                         if recs else b"")
                batch = batch[:max(max_bytes, 0)] if max_bytes < len(batch) \
                    else batch
                parts.append(_i32(part) + _i16(0) + _i64(hw) + _i64(hw) +
                             _i32(0) +           # aborted txns
                             _i32(len(batch)) + batch)
            out_topics.append(
                _str(topic) + _i32(len(parts)) + b"".join(parts))
        return _i32(0) + _i32(len(out_topics)) + b"".join(out_topics)

    def _offset_commit(self, body: bytes) -> bytes:
        r = _R(body)
        group = r.string()
        r.take(">i")                            # generation
        r.string()                              # member id
        r.take(">q")                            # retention
        out_topics = []
        for _t in range(r.take(">i")):
            topic = r.string()
            parts = []
            for _p in range(r.take(">i")):
                part = r.take(">i")
                off = r.take(">q")
                r.string()                      # metadata
                with self.lock:
                    self.offsets[(group, topic, part)] = off
                parts.append(_i32(part) + _i16(0))
            out_topics.append(
                _str(topic) + _i32(len(parts)) + b"".join(parts))
        return _i32(len(out_topics)) + b"".join(out_topics)

    def _offset_fetch(self, body: bytes) -> bytes:
        r = _R(body)
        group = r.string()
        out_topics = []
        for _t in range(r.take(">i")):
            topic = r.string()
            parts = []
            for _p in range(r.take(">i")):
                part = r.take(">i")
                with self.lock:
                    off = self.offsets.get((group, topic, part), -1)
                parts.append(_i32(part) + _i64(off) + _str("") + _i16(0))
            out_topics.append(
                _str(topic) + _i32(len(parts)) + b"".join(parts))
        return _i32(len(out_topics)) + b"".join(out_topics)


def _str(s: str) -> bytes:
    b = s.encode()
    return _i16(len(b)) + b


def _zig(v: int) -> bytes:
    v = (v << 1) ^ (v >> 63)
    out = bytearray()
    while True:
        x = v & 0x7F
        v >>= 7
        if v:
            out.append(x | 0x80)
        else:
            out.append(x)
            return bytes(out)


def start_mock_kafka(n_partitions: int = 2):
    """Returns (server_socket_thread_handle, port, broker). Serves until
    the returned closer is called."""
    import socketserver

    broker = MockKafkaBroker(n_partitions)

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            sock = self.request
            try:
                while True:
                    hdr = _readn(sock, 4)
                    if hdr is None:
                        return
                    (n,) = struct.unpack(">i", hdr)
                    msg = _readn(sock, n)
                    if msg is None:
                        return
                    r = _R(msg)
                    api_key = r.take(">h")
                    api_version = r.take(">h")
                    corr = r.take(">i")
                    r.string()                  # client id
                    resp = broker.handle(api_key, api_version, msg[r.i:])
                    out = _i32(corr) + resp
                    sock.sendall(_i32(len(out)) + out)
            except (ConnectionError, ValueError, struct.error):
                return

    def _readn(sock, n):
        out = b""
        while len(out) < n:
            chunk = sock.recv(n - len(out))
            if not chunk:
                return None
            out += chunk
        return out

    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1], broker
