"""In-process mock Kafka broker (the kfake/testkafka analog).

A threaded socket server speaking the Kafka binary-protocol subset
`ingest/kafka.py` uses — Produce v3, Fetch v4, OffsetCommit v2,
OffsetFetch v1 — with independent verification of the wire: framing,
correlation ids, and the v2 RecordBatch layout INCLUDING the CRC32C
(computed here with its own table), so client-side encoding bugs fail
the way they would against a real broker.
"""

from __future__ import annotations

import struct
import threading

# independent crc32c table (same Castagnoli polynomial, built separately)
_TAB = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _TAB.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _TAB[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _i16(v):
    return struct.pack(">h", v)


def _i32(v):
    return struct.pack(">i", v)


def _i64(v):
    return struct.pack(">q", v)


class _R:
    def __init__(self, b):
        self.b = b
        self.i = 0

    def take(self, fmt):
        v = struct.unpack_from(fmt, self.b, self.i)[0]
        self.i += struct.calcsize(fmt)
        return v

    def string(self):
        n = self.take(">h")
        if n < 0:
            return None
        v = self.b[self.i:self.i + n].decode()
        self.i += n
        return v

    def bytes_(self):
        n = self.take(">i")
        if n < 0:
            return None
        v = self.b[self.i:self.i + n]
        self.i += n
        return v

    def uvarint(self):
        out = shift = 0
        while True:
            b = self.b[self.i]
            self.i += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def varint(self):
        v = self.uvarint()
        return (v >> 1) ^ -(v & 1)


class MockCluster:
    """Shared state of a mock multi-broker cluster: logs, consumer-group
    offsets, and the leadership map (partition → broker id). Brokers that
    do NOT lead a partition answer NOT_LEADER_FOR_PARTITION(6); offset
    RPCs on a non-coordinator answer NOT_COORDINATOR(16) — the behaviors
    a leader-routing client must handle (franz-go does; a bootstrap-only
    client will fail against this, which is the point)."""

    def __init__(self, n_partitions: int = 2, n_brokers: int = 1) -> None:
        self.n_partitions = n_partitions
        self.n_brokers = n_brokers
        self.logs: dict[tuple[str, int], list[tuple[bytes, bytes]]] = {}
        self.offsets: dict[tuple[str, str, int], int] = {}
        self.lock = threading.Lock()
        self.produce_batches = 0
        self.leaders = {p: p % n_brokers for p in range(n_partitions)}
        self.coordinator = 0
        self.addrs: dict[int, tuple[str, int]] = {}   # set after bind
        # consumer groups: group -> state dict (members, generation,
        # per-generation sync barrier, leader-provided assignments)
        self.groups: dict[str, dict] = {}

    def move_leader(self, partition: int, broker_id: int) -> None:
        with self.lock:
            self.leaders[partition] = broker_id

    def move_coordinator(self, broker_id: int) -> None:
        """Coordinator failover: group state migrates (Kafka replicates
        __consumer_offsets); the OLD broker starts answering
        NOT_COORDINATOR, which clients must heal by re-discovery."""
        with self.lock:
            self.coordinator = broker_id

    def group_state(self, group: str) -> dict:
        # lock held by callers where it matters
        return self.groups.setdefault(group, {
            "members": {}, "generation": 0, "synced_gen": -1,
            "assignments": {}, "next_member": 0})

    def expire_member(self, group: str, member_id: str) -> None:
        """Session-timeout simulation: the coordinator drops the member
        and forces a rebalance (the live members learn via heartbeat)."""
        with self.lock:
            g = self.group_state(group)
            if member_id in g["members"]:
                del g["members"][member_id]
                g["generation"] += 1
                g["assignments"].clear()
                g["synced_gen"] = -1


class MockKafkaBroker:
    """One broker of a MockCluster (or standalone, leading everything)."""

    def __init__(self, n_partitions: int = 2,
                 cluster: "MockCluster | None" = None,
                 broker_id: int = 0) -> None:
        self.cluster = cluster or MockCluster(n_partitions, 1)
        self.broker_id = broker_id
        self.n_partitions = self.cluster.n_partitions
        # per-broker request counters (tests assert routing)
        self.produce_reqs = 0
        self.fetch_reqs = 0
        self.offset_reqs = 0

    # shared-state proxies (back-compat with the single-broker tests)
    @property
    def logs(self):
        return self.cluster.logs

    @property
    def offsets(self):
        return self.cluster.offsets

    @property
    def lock(self):
        return self.cluster.lock

    @property
    def produce_batches(self):
        return self.cluster.produce_batches

    def _leads(self, partition: int) -> bool:
        with self.cluster.lock:
            return self.cluster.leaders.get(partition) == self.broker_id

    def _is_coordinator(self) -> bool:
        return self.cluster.coordinator == self.broker_id

    # -- record batch verification + decode ---------------------------------

    def _decode_batch(self, buf: bytes) -> list[tuple[bytes, bytes]]:
        r = _R(buf)
        out = []
        while r.i + 61 <= len(buf):
            r.take(">q")                        # base offset
            blen = r.take(">i")
            end = r.i + blen
            r.take(">i")                        # leader epoch
            magic = r.take(">b")
            if magic != 2:
                raise ValueError(f"bad magic {magic}")
            crc = r.take(">I")
            want = _crc32c(buf[r.i:end])
            if crc != want:
                raise ValueError(f"crc mismatch {crc:#x} != {want:#x}")
            r.take(">h"); r.take(">i")
            r.take(">q"); r.take(">q")
            r.take(">q"); r.take(">h"); r.take(">i")
            n = r.take(">i")
            for _ in range(n):
                r.varint()
                r.take(">b")
                r.varint(); r.varint()
                klen = r.varint()
                key = buf[r.i:r.i + max(klen, 0)]; r.i += max(klen, 0)
                vlen = r.varint()
                val = buf[r.i:r.i + max(vlen, 0)]; r.i += max(vlen, 0)
                for _h in range(r.uvarint()):
                    hk = r.varint(); r.i += max(hk, 0)
                    hv = r.varint(); r.i += max(hv, 0)
                out.append((bytes(key), bytes(val)))
            r.i = end
        return out

    def _encode_batch(self, base: int, recs: list[tuple[bytes, bytes]]
                      ) -> bytes:
        body = bytearray()
        for i, (k, v) in enumerate(recs):
            rec = (struct.pack(">b", 0) + _zig(0) + _zig(i) +
                   _zig(len(k)) + k + _zig(len(v)) + v + b"\x00")
            body += _zig(len(rec)) + rec
        after = (_i16(0) + _i32(len(recs) - 1) + _i64(0) + _i64(0) +
                 _i64(-1) + _i16(-1) + _i32(-1) + _i32(len(recs)) +
                 bytes(body))
        crc = _crc32c(after)
        inner = _i32(0) + struct.pack(">b", 2) + struct.pack(">I", crc) + after
        return _i64(base) + _i32(len(inner)) + inner

    # -- api handlers --------------------------------------------------------

    def handle(self, api_key: int, api_version: int, body: bytes) -> bytes:
        if api_key == 0:
            return self._produce(body)
        if api_key == 1:
            return self._fetch(body)
        if api_key == 3:
            return self._metadata(body)
        if api_key == 8:
            return self._offset_commit(body)
        if api_key == 9:
            return self._offset_fetch(body)
        if api_key == 10:
            return self._find_coordinator(body)
        if api_key == 11:
            return self._join_group(body)
        if api_key == 12:
            return self._heartbeat(body)
        if api_key == 13:
            return self._leave_group(body)
        if api_key == 14:
            return self._sync_group(body)
        raise ValueError(f"unsupported api key {api_key}")

    # -- consumer groups (JoinGroup v5 / SyncGroup v3 / Heartbeat v3 /
    #    LeaveGroup v1) — the coordinator-side state machine a group
    #    client must drive: MEMBER_ID_REQUIRED on first contact, a
    #    generation bump + sync barrier on every membership change,
    #    REBALANCE_IN_PROGRESS heartbeats until the leader re-syncs ------

    def _join_group(self, body: bytes) -> bytes:
        r = _R(body)
        group = r.string()
        r.take(">i"); r.take(">i")              # session/rebalance timeout
        member = r.string() or ""
        r.string()                              # group instance id
        r.string()                              # protocol type
        protos = []
        for _ in range(max(r.take(">i"), 0)):
            protos.append((r.string(), r.bytes_()))
        meta = protos[0][1] if protos else b""
        resp_members = b""
        with self.lock:
            if not self._is_coordinator():
                return (_i32(0) + _i16(16) + _i32(-1) + _str("") +
                        _str("") + _str("") + _i32(0))
            g = self.cluster.group_state(group)
            if not member:
                g["next_member"] += 1
                member = f"{group}-m{g['next_member']}"
                # v4+ contract: park the id, demand a re-join with it
                return (_i32(0) + _i16(79) + _i32(-1) + _str("") +
                        _str("") + _str(member) + _i32(0))
            if member not in g["members"]:
                g["generation"] += 1
                g["assignments"].clear()
                g["synced_gen"] = -1
            g["members"][member] = meta
            leader = sorted(g["members"])[0]
            gen = g["generation"]
            if member == leader:
                resp_members = b"".join(
                    _str(m) + _i16(-1) +        # null instance id
                    _i32(len(mm)) + mm
                    for m, mm in sorted(g["members"].items()))
                n_members = len(g["members"])
            else:
                n_members = 0
        return (_i32(0) + _i16(0) + _i32(gen) + _str("range") +
                _str(leader) + _str(member) + _i32(n_members) +
                resp_members)

    def _sync_group(self, body: bytes) -> bytes:
        r = _R(body)
        group = r.string()
        gen = r.take(">i")
        member = r.string() or ""
        r.string()                              # instance id
        assigns = []
        for _ in range(max(r.take(">i"), 0)):
            assigns.append((r.string() or "", r.bytes_() or b""))
        with self.lock:
            if not self._is_coordinator():
                return _i32(0) + _i16(16) + _i32(0)
            g = self.cluster.group_state(group)
            if member not in g["members"]:
                return _i32(0) + _i16(25) + _i32(0)   # UNKNOWN_MEMBER
            if gen != g["generation"]:
                return _i32(0) + _i16(22) + _i32(0)   # ILLEGAL_GENERATION
            if assigns:                         # the leader's sync
                g["assignments"] = dict(assigns)
                g["synced_gen"] = gen
            if g["synced_gen"] != g["generation"]:
                return _i32(0) + _i16(27) + _i32(0)   # REBALANCE_IN_PROG
            mine = g["assignments"].get(member, b"")
        return _i32(0) + _i16(0) + _i32(len(mine)) + mine

    def _heartbeat(self, body: bytes) -> bytes:
        r = _R(body)
        group = r.string()
        gen = r.take(">i")
        member = r.string() or ""
        with self.lock:
            if not self._is_coordinator():
                return _i32(0) + _i16(16)
            g = self.cluster.group_state(group)
            if member not in g["members"]:
                return _i32(0) + _i16(25)
            if gen != g["generation"] or g["synced_gen"] != g["generation"]:
                return _i32(0) + _i16(27)
        return _i32(0) + _i16(0)

    def _leave_group(self, body: bytes) -> bytes:
        r = _R(body)
        group = r.string()
        member = r.string() or ""
        with self.lock:
            if not self._is_coordinator():
                return _i32(0) + _i16(16)
            g = self.cluster.group_state(group)
            if member in g["members"]:
                del g["members"][member]
                g["generation"] += 1
                g["assignments"].clear()
                g["synced_gen"] = -1
        return _i32(0) + _i16(0)

    def _metadata(self, body: bytes) -> bytes:
        # Metadata v1 response: brokers, controller, topics w/ leaders
        r = _R(body)
        topics = [r.string() for _ in range(max(r.take(">i"), 0))]
        c = self.cluster
        with c.lock:
            addrs = dict(c.addrs)
            leaders = dict(c.leaders)
        brokers = b"".join(
            _i32(nid) + _str(host) + _i32(port) + _i16(-1)   # rack null
            for nid, (host, port) in sorted(addrs.items()))
        out_topics = []
        for name in topics or ["tempo-ingest"]:
            parts = b"".join(
                _i16(0) + _i32(p) + _i32(leaders[p]) +
                _i32(0) + _i32(0)                # replicas, isr empty
                for p in range(c.n_partitions))
            out_topics.append(_i16(0) + _str(name) +
                              struct.pack(">b", 0) +   # is_internal
                              _i32(c.n_partitions) + parts)
        return (_i32(len(addrs)) + brokers + _i32(c.coordinator) +
                _i32(len(out_topics)) + b"".join(out_topics))

    def _find_coordinator(self, body: bytes) -> bytes:
        # FindCoordinator v1: throttle, err, errmsg, node, host, port
        c = self.cluster
        with c.lock:
            host, port = c.addrs.get(c.coordinator, ("127.0.0.1", 0))
        return (_i32(0) + _i16(0) + _str("") +
                _i32(c.coordinator) + _str(host) + _i32(port))

    def _produce(self, body: bytes) -> bytes:
        self.produce_reqs += 1
        r = _R(body)
        r.string()                              # transactional id
        r.take(">h")                            # acks
        r.take(">i")                            # timeout
        out_topics = []
        for _t in range(r.take(">i")):
            topic = r.string()
            parts = []
            for _p in range(r.take(">i")):
                part = r.take(">i")
                batch = r.bytes_() or b""
                if not self._leads(part):
                    parts.append(_i32(part) + _i16(6) +   # NOT_LEADER
                                 _i64(-1) + _i64(-1))
                    continue
                recs = self._decode_batch(batch)
                with self.lock:
                    log = self.logs.setdefault((topic, part), [])
                    base = len(log)
                    log.extend(recs)
                    self.cluster.produce_batches += 1
                parts.append(_i32(part) + _i16(0) + _i64(base) + _i64(-1))
            out_topics.append(
                _str(topic) + _i32(len(parts)) + b"".join(parts))
        return (_i32(len(out_topics)) + b"".join(out_topics) + _i32(0))

    def _fetch(self, body: bytes) -> bytes:
        self.fetch_reqs += 1
        r = _R(body)
        r.take(">i"); r.take(">i"); r.take(">i"); r.take(">i")
        r.take(">b")                            # isolation
        out_topics = []
        for _t in range(r.take(">i")):
            topic = r.string()
            parts = []
            for _p in range(r.take(">i")):
                part = r.take(">i")
                offset = r.take(">q")
                max_bytes = r.take(">i")
                if not self._leads(part):
                    parts.append(_i32(part) + _i16(6) + _i64(-1) +
                                 _i64(-1) + _i32(0) + _i32(0))
                    continue
                with self.lock:
                    log = list(self.logs.get((topic, part), []))
                hw = len(log)
                recs = log[offset:]
                batch = (self._encode_batch(offset, recs)
                         if recs else b"")
                batch = batch[:max(max_bytes, 0)] if max_bytes < len(batch) \
                    else batch
                parts.append(_i32(part) + _i16(0) + _i64(hw) + _i64(hw) +
                             _i32(0) +           # aborted txns
                             _i32(len(batch)) + batch)
            out_topics.append(
                _str(topic) + _i32(len(parts)) + b"".join(parts))
        return _i32(0) + _i32(len(out_topics)) + b"".join(out_topics)

    def _offset_commit(self, body: bytes) -> bytes:
        self.offset_reqs += 1
        r = _R(body)
        group = r.string()
        gen = r.take(">i")                      # generation
        member = r.string() or ""               # member id
        r.take(">q")                            # retention
        out_topics = []
        for _t in range(r.take(">i")):
            topic = r.string()
            parts = []
            for _p in range(r.take(">i")):
                part = r.take(">i")
                off = r.take(">q")
                r.string()                      # metadata
                if not self._is_coordinator():
                    parts.append(_i32(part) + _i16(16))   # NOT_COORDINATOR
                    continue
                with self.lock:
                    # generation fencing: a group-mode commit (gen >= 0)
                    # from a dead member or stale generation is rejected
                    # (simple bus commits pass gen -1 and stay ungated)
                    if gen >= 0 and group in self.cluster.groups:
                        g = self.cluster.group_state(group)
                        if member not in g["members"] or \
                                gen != g["generation"]:
                            parts.append(_i32(part) + _i16(22))
                            continue
                    self.offsets[(group, topic, part)] = off
                parts.append(_i32(part) + _i16(0))
            out_topics.append(
                _str(topic) + _i32(len(parts)) + b"".join(parts))
        return _i32(len(out_topics)) + b"".join(out_topics)

    def _offset_fetch(self, body: bytes) -> bytes:
        self.offset_reqs += 1
        r = _R(body)
        group = r.string()
        out_topics = []
        for _t in range(r.take(">i")):
            topic = r.string()
            parts = []
            for _p in range(r.take(">i")):
                part = r.take(">i")
                if not self._is_coordinator():
                    parts.append(_i32(part) + _i64(-1) + _str("") +
                                 _i16(16))
                    continue
                with self.lock:
                    off = self.offsets.get((group, topic, part), -1)
                parts.append(_i32(part) + _i64(off) + _str("") + _i16(0))
            out_topics.append(
                _str(topic) + _i32(len(parts)) + b"".join(parts))
        return _i32(len(out_topics)) + b"".join(out_topics)


def _str(s: str) -> bytes:
    b = s.encode()
    return _i16(len(b)) + b


def _zig(v: int) -> bytes:
    v = (v << 1) ^ (v >> 63)
    out = bytearray()
    while True:
        x = v & 0x7F
        v >>= 7
        if v:
            out.append(x | 0x80)
        else:
            out.append(x)
            return bytes(out)


def _readn(sock, n):
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            return None
        out += chunk
    return out


def _serve_broker(broker: MockKafkaBroker):
    import socketserver

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            sock = self.request
            try:
                while True:
                    hdr = _readn(sock, 4)
                    if hdr is None:
                        return
                    (n,) = struct.unpack(">i", hdr)
                    msg = _readn(sock, n)
                    if msg is None:
                        return
                    r = _R(msg)
                    api_key = r.take(">h")
                    api_version = r.take(">h")
                    corr = r.take(">i")
                    r.string()                  # client id
                    resp = broker.handle(api_key, api_version, msg[r.i:])
                    out = _i32(corr) + resp
                    sock.sendall(_i32(len(out)) + out)
            except (ConnectionError, ValueError, struct.error):
                return

    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]


def start_mock_kafka(n_partitions: int = 2):
    """Single-broker cluster. Returns (server, port, broker); the broker
    leads every partition and coordinates every group."""
    cluster = MockCluster(n_partitions, 1)
    cluster.leaders = {p: 0 for p in range(n_partitions)}
    broker = MockKafkaBroker(cluster=cluster, broker_id=0)
    srv, port = _serve_broker(broker)
    cluster.addrs[0] = ("127.0.0.1", port)
    return srv, port, broker


def start_mock_kafka_cluster(n_partitions: int = 4, n_brokers: int = 2):
    """Multi-broker cluster with SPLIT leadership (partition p led by
    broker p % n_brokers; broker 0 coordinates groups). Returns
    (servers, ports, brokers, cluster)."""
    cluster = MockCluster(n_partitions, n_brokers)
    servers, ports, brokers = [], [], []
    for bid in range(n_brokers):
        broker = MockKafkaBroker(cluster=cluster, broker_id=bid)
        srv, port = _serve_broker(broker)
        cluster.addrs[bid] = ("127.0.0.1", port)
        servers.append(srv)
        ports.append(port)
        brokers.append(broker)
    return servers, ports, brokers, cluster
