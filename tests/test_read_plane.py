"""The PRODUCT read path through the device plane: parity + routing.

Round-3 verdict weak #1: `BlockScanPlane` was bench/test-only. These tests
pin the integration — `TempoDB.query_range` and `TempoDB.search` must take
the fused device path for supported shapes (asserted via routing counters,
guarding against silent permanent fallback) and must produce the same
results as the host engine (device_plane=False) for every aggregation
kind, including `quantile_over_time` (the north-star query) and exact
integer boundary compares (round-3 weak #5: float32-only device compares).
"""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig
from tempo_tpu.traceql.engine_metrics import (QueryRangeRequest,
                                              SeriesCombiner, metrics_kind)

T0 = 1_700_000_000
# durations engineered to sit ON compare boundaries, including values not
# representable in float32 (2**24 + 1) — the exactness regression surface
_DUR_CYCLE_NS = [
    123_000_000,          # = 123ms exactly
    123_000_001,
    122_999_999,
    16_777_216,           # 2**24 ns (f32-exact)
    16_777_217,           # 2**24 + 1 ns (NOT f32-representable)
    16_777_215,
    50_000_000,
    1,
]


def _mk_db(be, device_plane: bool) -> TempoDB:
    return TempoDB(be, be, TempoDBConfig(device_plane=device_plane))


@pytest.fixture(scope="module")
def dbs():
    rng = np.random.default_rng(7)
    be = MemBackend()
    dev = _mk_db(be, True)
    host = _mk_db(be, False)
    traces = []
    for i in range(800):
        tid = rng.bytes(16)
        start = int((T0 + i * 0.5) * 1e9)
        traces.append((tid, [{
            "trace_id": tid, "span_id": rng.bytes(8),
            "name": f"op-{i % 5}", "service": f"svc-{i % 3}",
            "kind": int(i % 6), "status_code": int(i % 3),
            "start_unix_nano": start,
            "end_unix_nano": start + _DUR_CYCLE_NS[i % len(_DUR_CYCLE_NS)],
            "attrs": ({"http.status_code": 200 + (i % 300),
                       "region": f"r{i % 4}", "retries": i % 7}
                      if i % 3 != 2 else   # svc-2 spans carry NO retries:
                      {"http.status_code": 200 + (i % 300),   # the host
                       "region": f"r{i % 4}"}),  # engine still emits a
        # zero/inf series for that group — fused emission must agree
        }]))
    dev.write_block("t", traces, replication_factor=1)
    dev.poll_now()
    host.poll_now()
    return dev, host


def _series_map(series) -> dict:
    return {tuple(sorted((str(k), str(v)) for k, v in s.labels)):
            np.nan_to_num(np.asarray(s.samples, np.float64))
            for s in series}


QUERIES = [
    '{ } | rate() by (resource.service.name)',
    '{ } | count_over_time() by (name)',
    '{ duration > 123ms } | rate() by (name)',
    '{ duration >= 123ms } | rate()',
    '{ duration = 16777217ns } | count_over_time()',
    '{ duration > 16777216ns && duration < 17ms } | count_over_time()',
    '{ name = "op-3" && kind = server } | rate() by (resource.service.name)',
    '{ status = error } | count_over_time() by (name)',
    '{ } | quantile_over_time(duration, .5, .99) by (resource.service.name)',
    '{ duration > 1ms } | quantile_over_time(duration, .99) by (name)',
    '{ } | histogram_over_time(duration) by (resource.service.name)',
    '{ } | min_over_time(duration) by (name)',
    '{ } | max_over_time(duration) by (resource.service.name)',
    '{ } | sum_over_time(duration) by (name)',
    '{ } | avg_over_time(duration) by (resource.service.name)',
    # group-by on a generic span attribute (plane adopts the attr column)
    '{ } | rate() by (span.region)',
    '{ span.http.status_code >= 400 } | rate() by (name)',
    # value attribute missing on every svc-2 span: the group still gets a
    # zero/inf series on both paths (obs-count emission gate)
    '{ } | sum_over_time(span.retries) by (resource.service.name)',
    '{ } | avg_over_time(span.retries) by (resource.service.name)',
    '{ } | min_over_time(span.retries) by (resource.service.name)',
    '{ } | quantile_over_time(span.retries, .9) by (resource.service.name)',
    # two-key group-by (the RED-dashboard shape) rides the fused plane
    '{ } | rate() by (resource.service.name, name)',
    '{ duration > 50ms } | quantile_over_time(duration, .9)'
    ' by (resource.service.name, name)',
    '{ } | avg_over_time(duration) by (name, span.region)',
    # unsupported shapes must still match via host fallback
    '{ name = "op-1" || duration > 400ms } | rate() by (name)',
    # NEQ with a non-integral literal on an int column is constant-true
    # for present values but must still exclude spans MISSING the attr
    # (advisor r4 medium: the ("const", True) plan dropped the exists
    # mask; svc-2 spans carry no retries)
    '{ span.retries != 1.5 } | rate() by (resource.service.name)',
    # boolean literal filters: `false` matches nothing, `x && false`
    # matches nothing, `true` matches all — the extractor must not treat
    # the dropped literal as absent on the fused path (advisor r4 low)
    '{ false } | rate() by (name)',
    '{ name = "op-1" && false } | count_over_time() by (name)',
    '{ true } | rate() by (name)',
]


@pytest.mark.parametrize("query", QUERIES)
def test_query_range_product_parity(dbs, query):
    dev, host = dbs
    req = QueryRangeRequest(query=query, start_ns=int(T0 * 1e9),
                            end_ns=int((T0 + 400) * 1e9),
                            step_ns=int(60e9))
    a = _series_map(dev.query_range("t", req))
    b = _series_map(host.query_range("t", req))
    assert set(a) == set(b), query
    for k in b:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-4,
                                   err_msg=f"{query} {k}")


def test_fused_path_actually_engages(dbs):
    """Supported shapes must route through the device grid (guard against
    silent permanent fallback)."""
    dev, _ = dbs
    before = dev.plane_stats["fused_metric_blocks"]
    req = QueryRangeRequest(
        query='{ } | quantile_over_time(duration, .99) by (resource.service.name)',
        start_ns=int(T0 * 1e9), end_ns=int((T0 + 400) * 1e9),
        step_ns=int(60e9))
    dev.query_range("t", req)
    assert dev.plane_stats["fused_metric_blocks"] > before


def test_quantile_final_pass_parity(dbs):
    """End-to-end north-star shape: job-level series from the fused path
    must combine into the same interpolated quantiles as the host engine
    (`Log2Quantile` engine_metrics.go:1402)."""
    dev, host = dbs
    q = '{ } | quantile_over_time(duration, .99) by (resource.service.name)'
    req = QueryRangeRequest(query=q, start_ns=int(T0 * 1e9),
                            end_ns=int((T0 + 400) * 1e9), step_ns=int(60e9))
    out = {}
    for db in (dev, host):
        comb = SeriesCombiner(metrics_kind(q), req.n_steps)
        comb.add_all(db.query_range("t", req))
        out[db] = _series_map(comb.final(req))
    assert set(out[dev]) == set(out[host])
    for k in out[host]:
        np.testing.assert_allclose(out[dev][k], out[host][k], rtol=1e-6,
                                   err_msg=str(k))


def test_search_product_parity(dbs):
    dev, host = dbs
    for q in ('{ duration > 123ms }',
              '{ duration = 16777217ns }',
              '{ name = "op-2" && duration >= 50ms }',
              '{ resource.service.name = "svc-1" }',
              '{ span.region = "r2" && status = error }'):
        a = dev.search("t", q, limit=1000)
        b = host.search("t", q, limit=1000)
        ids = lambda res: sorted(m.trace_id for m in res)
        assert ids(a) == ids(b), q


def test_search_time_window_parity(dbs):
    """Windowed search: device and host prefilters must clip identically
    (both clip on span start from the same FetchSpansRequest bounds —
    regression guard for the suspected start-vs-overlap divergence)."""
    dev, host = dbs
    for lo, hi in ((T0 + 50, T0 + 150), (T0, T0 + 10), (T0 + 390, T0 + 500)):
        for q in ('{ duration > 50ms }', '{ name = "op-1" }'):
            a = sorted(m.trace_id for m in dev.search(
                "t", q, limit=1000, start_s=lo, end_s=hi))
            b = sorted(m.trace_id for m in host.search(
                "t", q, limit=1000, start_s=lo, end_s=hi))
            assert a == b, (q, lo, hi)


def test_search_uses_device_first_pass(dbs):
    dev, _ = dbs
    meta = dev.blocklist.metas("t")[0]
    cb = dev.planes.get(dev.backend_block(meta))
    before = cb.device_scans
    dev.search("t", '{ duration > 123ms }', limit=10)
    assert cb.device_scans > before


def test_row_group_shards_sum_to_whole(dbs):
    """Frontend-style row-group sharded sub-requests must tensor-add to
    the unsharded answer on the fused path."""
    dev, _ = dbs
    q = '{ } | count_over_time() by (name)'
    req = QueryRangeRequest(query=q, start_ns=int(T0 * 1e9),
                            end_ns=int((T0 + 400) * 1e9), step_ns=int(60e9))
    meta = dev.blocklist.metas("t")[0]
    n_rg = dev.backend_block(meta).parquet_file().num_row_groups
    whole = _series_map(dev.query_range("t", req, metas=[meta]))
    comb = SeriesCombiner(metrics_kind(q), req.n_steps)
    for rg in range(n_rg):
        comb.add_all(dev.query_range("t", req, metas=[meta],
                                     row_groups=[rg]))
    sharded = _series_map(list(comb.series.values()))
    assert set(whole) == set(sharded)
    for k in whole:
        np.testing.assert_allclose(sharded[k], whole[k], rtol=1e-6)


def test_plane_cache_lru_budget():
    """Device-byte budget evicts least-recently-used planes."""
    from tempo_tpu.db.plane_cache import PlaneCache

    rng = np.random.default_rng(3)
    be = MemBackend()
    db = _mk_db(be, True)
    for b in range(3):
        traces = []
        for i in range(50):
            tid = rng.bytes(16)
            start = int((T0 + i) * 1e9)
            traces.append((tid, [{
                "trace_id": tid, "span_id": rng.bytes(8),
                "name": f"op-{i % 3}", "service": "svc",
                "kind": 2, "status_code": 0,
                "start_unix_nano": start,
                "end_unix_nano": start + 1_000_000}]))
        db.write_block("t", traces, replication_factor=1)
    db.poll_now()
    db.planes = PlaneCache(budget_bytes=1, max_blocks=64)  # starvation budget
    req = QueryRangeRequest(query='{ } | rate() by (name)',
                            start_ns=int(T0 * 1e9),
                            end_ns=int((T0 + 100) * 1e9), step_ns=int(50e9))
    db.query_range("t", req)
    stats = db.planes.stats()
    assert stats["entries"] == 1          # budget keeps only the last block
    assert stats["misses"] >= 3


def test_exemplars_present_on_fused_path(dbs):
    dev, _ = dbs
    req = QueryRangeRequest(query='{ } | rate() by (name)',
                            start_ns=int(T0 * 1e9),
                            end_ns=int((T0 + 400) * 1e9), step_ns=int(60e9))
    series = dev.query_range("t", req)
    assert any(s.exemplars for s in series)


def test_nil_predicates_on_plane_path(dbs):
    """nil comparisons ride the plane's existence-mask term (regression:
    the packed-literal refactor missed the nil/const tuple arity and
    raised IndexError instead of serving or falling back)."""
    dev, host = dbs
    for q in ('{ span.retries != nil }', '{ span.retries = nil }',
              '{ span.nothere = nil }'):
        a = sorted(m.trace_id for m in dev.search("t", q, limit=1000))
        b = sorted(m.trace_id for m in host.search("t", q, limit=1000))
        assert a == b, q
    req = QueryRangeRequest(query='{ span.retries != nil } | rate() by (name)',
                            start_ns=int(T0 * 1e9),
                            end_ns=int((T0 + 400) * 1e9), step_ns=int(60e9))
    a = _series_map(dev.query_range("t", req))
    b = _series_map(host.query_range("t", req))
    assert set(a) == set(b)
    for k in b:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5)


def test_many_blocks_bounded_grid_drain():
    """More fused blocks than the in-flight grid window (8): the drain
    path must still sum identically to the host engine."""
    rng = np.random.default_rng(11)
    be = MemBackend()
    dev = _mk_db(be, True)
    host = _mk_db(be, False)
    for b in range(12):
        traces = []
        for i in range(40):
            tid = rng.bytes(16)
            start = int((T0 + b * 40 + i) * 1e9)
            traces.append((tid, [{
                "trace_id": tid, "span_id": rng.bytes(8),
                "name": f"op-{i % 3}", "service": f"svc-{b % 2}",
                "kind": 2, "status_code": 0,
                "start_unix_nano": start,
                "end_unix_nano": start + 5_000_000}]))
        dev.write_block("t", traces, replication_factor=1)
    dev.poll_now(); host.poll_now()
    req = QueryRangeRequest(
        query='{ } | quantile_over_time(duration, .9) by (name)',
        start_ns=int(T0 * 1e9), end_ns=int((T0 + 500) * 1e9),
        step_ns=int(100e9))
    a = _series_map(dev.query_range("t", req))
    b2 = _series_map(host.query_range("t", req))
    assert dev.plane_stats["fused_metric_blocks"] >= 12
    assert set(a) == set(b2)
    for k in b2:
        np.testing.assert_allclose(a[k], b2[k], rtol=1e-5)


def test_step_boundary_exact_bucketing():
    """Spans landing just either side of a step boundary — hours from the
    block base, where float32 seconds carry ~0.5ms of error — must bucket
    identically on the fused and host planes (advisor r4 low: the f32
    `rel + frac` path put boundary spans into the adjacent bucket; the
    limb-exact path snaps the estimate to the true integer floor in BOTH
    directions). Offsets are ±300ns: large enough to survive the float64
    `__startTime` quantization (ulp = 256ns at epoch 1.7e18) that erases
    ±1ns before either plane sees it, small enough that f32 rounds them
    onto the boundary."""
    be = MemBackend()
    dev = _mk_db(be, True)
    host = _mk_db(be, False)
    rng = np.random.default_rng(5)
    base_ns = int(T0 * 1e9)
    step_ns = int(60e9)
    traces = []
    # an anchor span AT base keeps time_base_ns == base_ns
    for k in range(1, 200):
        for off in (-300, 0, 300):
            tid = rng.bytes(16)
            start = base_ns + k * step_ns + off
            traces.append((tid, [{
                "trace_id": tid, "span_id": rng.bytes(8),
                "name": f"op-{k % 3}", "service": "svc",
                "kind": 2, "status_code": 0,
                "start_unix_nano": start,
                "end_unix_nano": start + 1_000_000}]))
    traces.append((rng.bytes(16), [{
        "trace_id": rng.bytes(16), "span_id": rng.bytes(8),
        "name": "op-0", "service": "svc", "kind": 2, "status_code": 0,
        "start_unix_nano": base_ns, "end_unix_nano": base_ns + 1_000_000}]))
    dev.write_block("t", traces, replication_factor=1)
    dev.poll_now(); host.poll_now()
    req = QueryRangeRequest(
        query='{ } | count_over_time() by (name)',
        start_ns=base_ns, end_ns=base_ns + 200 * step_ns, step_ns=step_ns)
    a = _series_map(dev.query_range("t", req))
    b = _series_map(host.query_range("t", req))
    assert dev.plane_stats["fused_metric_blocks"] >= 1
    assert set(a) == set(b)
    for k in b:
        np.testing.assert_array_equal(a[k], b[k], err_msg=str(k))


def test_plane_upload_race_refunds_budget():
    """A racing duplicate LUT upload must keep one entry and refund the
    loser's device_bytes (advisor r4 low: both uploads were counted, one
    entry overwritten, the eviction budget permanently over-counted)."""
    dev, _ = _race_dbs()
    meta = dev.blocklist.metas("t")[0]
    plane = dev.planes.get(dev.backend_block(meta)).plane
    before = plane.device_bytes
    # simulate the race: insert the key mid-upload via a patched _up
    key = ("rglut", (0,))
    real_up = plane._up

    def racing_up(arr, is_span_dim=True):
        out = real_up(arr, is_span_dim)     # our upload (accounted)
        if key not in plane._cols:
            # rival's insert
            plane._cols[key] = real_up(np.asarray(arr), is_span_dim)
        return out

    plane._up = racing_up
    try:
        got = plane._ensure_rg_lut([0])
    finally:
        plane._up = real_up
    rival = plane._cols[key]
    assert got is rival                     # the first insert won
    # exactly ONE surviving entry is accounted for
    assert plane.device_bytes == before + int(np.zeros(
        len(plane.sizes), bool).nbytes)


def _race_dbs():
    rng = np.random.default_rng(13)
    be = MemBackend()
    dev = _mk_db(be, True)
    traces = []
    for i in range(20):
        tid = rng.bytes(16)
        start = int((T0 + i) * 1e9)
        traces.append((tid, [{
            "trace_id": tid, "span_id": rng.bytes(8),
            "name": f"op-{i % 3}", "service": "svc", "kind": 2,
            "status_code": 0, "start_unix_nano": start,
            "end_unix_nano": start + 1_000_000}]))
    dev.write_block("t", traces, replication_factor=1)
    dev.poll_now()
    # a first query adopts the columns so the plane is resident
    dev.search("t", '{ name = "op-1" }', limit=10)
    return dev, None


def test_float_attribute_columns_on_fused_path():
    """Float-valued attribute columns ride the fused plane via the
    order-preserving sortable-int64 encoding (round-4 weak #4: they used
    to refuse and silently lose the whole fused win). Device must match
    host bit-for-bit on boundary literals, and the routing counters must
    show FUSED service, not a predicate fallback."""
    rng = np.random.default_rng(21)
    be = MemBackend()
    dev = _mk_db(be, True)
    host = _mk_db(be, False)
    # values engineered onto compare boundaries incl. negatives, exact
    # halves, and f32-unrepresentable doubles; svc-1 spans carry NO ratio
    vals = [0.5, 1.5, -2.25, 0.1, 16777217.5, -0.0, 3.0, 1e300]
    traces = []
    for i in range(400):
        tid = rng.bytes(16)
        start = int((T0 + i) * 1e9)
        attrs = {"ratio": vals[i % len(vals)]} if i % 3 != 1 else {}
        traces.append((tid, [{
            "trace_id": tid, "span_id": rng.bytes(8),
            "name": f"op-{i % 3}", "service": f"svc-{i % 2}",
            "kind": 2, "status_code": 0,
            "start_unix_nano": start,
            "end_unix_nano": start + 2_000_000,
            "attrs": attrs}]))
    dev.write_block("t", traces, replication_factor=1)
    dev.poll_now(); host.poll_now()
    queries = [
        '{ span.ratio > 0.5 } | rate() by (name)',
        '{ span.ratio >= 1.5 } | count_over_time()',
        '{ span.ratio < 0 } | rate() by (name)',
        '{ span.ratio = -2.25 } | count_over_time()',
        '{ span.ratio = 0.0 } | rate()',          # matches -0.0 rows too
        '{ span.ratio != 0.1 } | rate() by (name)',   # exists-gated NEQ
        '{ span.ratio = 16777217.5 } | count_over_time()',
        '{ span.ratio > 2 } | rate()',            # int literal, float col
    ]
    for q in queries:
        req = QueryRangeRequest(query=q, start_ns=int(T0 * 1e9),
                                end_ns=int((T0 + 500) * 1e9),
                                step_ns=int(100e9))
        a = _series_map(dev.query_range("t", req))
        b = _series_map(host.query_range("t", req))
        assert set(a) == set(b), q
        for k in b:
            np.testing.assert_array_equal(a[k], b[k], err_msg=f"{q} {k}")
        sa = sorted(m.trace_id for m in dev.search("t", q.split("|")[0].strip(),
                                                   limit=1000))
        sb = sorted(m.trace_id for m in host.search("t", q.split("|")[0].strip(),
                                                    limit=1000))
        assert sa == sb, q
    # every query above must have taken the fused path
    assert dev.plane_stats["fused_metric_blocks"] >= len(queries)
    assert not any(k.startswith("fallback_") for k in dev.plane_stats), \
        dev.plane_stats


def test_fallback_cause_counters():
    """Host fallbacks carry a cause in plane_stats (round-4 weak #4) and
    surface as tempo_read_plane_fallback_total{cause=...}."""
    dev, _ = _race_dbs()
    req = QueryRangeRequest(
        query='{ kind = server && (name = "op-1" || name = "op-2") }'
              ' | rate() by (name)',
        start_ns=int(T0 * 1e9), end_ns=int((T0 + 100) * 1e9),
        step_ns=int(50e9))
    dev.query_range("t", req)   # mixed AND/OR → not fusable (query shape;
    #                             pure disjunctions fuse since round 5)
    assert dev.plane_stats.get("fallback_query_shape", 0) >= 1
    # NaN column values have no consistent order → predicate cause
    rng = np.random.default_rng(23)
    be2 = MemBackend()
    dev2 = _mk_db(be2, True)
    traces = []
    for i in range(20):
        tid = rng.bytes(16)
        start = int((T0 + i) * 1e9)
        traces.append((tid, [{
            "trace_id": tid, "span_id": rng.bytes(8),
            "name": "op", "service": "svc", "kind": 2, "status_code": 0,
            "start_unix_nano": start, "end_unix_nano": start + 1_000_000,
            "attrs": {"x": float("nan") if i % 2 else 1.5}}]))
    dev2.write_block("t", traces, replication_factor=1)
    dev2.poll_now()
    req2 = QueryRangeRequest(
        query='{ span.x > 1.0 } | rate() by (name)',
        start_ns=int(T0 * 1e9), end_ns=int((T0 + 100) * 1e9),
        step_ns=int(50e9))
    dev2.query_range("t", req2)
    assert dev2.plane_stats.get("fallback_predicate", 0) >= 1, \
        dev2.plane_stats


def test_pure_or_filters_fuse_exactly(dbs):
    """`{ a || b } | rate()` (pure disjunction of pushable compares) rides
    the fused plane — the OR of exact device terms is exact (round 5);
    mixed AND/OR trees still fall back to the host's exact second pass."""
    dev, host = dbs
    before = dev.plane_stats["fused_metric_blocks"]
    for q in ('{ name = "op-1" || duration > 400ms } | rate() by (name)',
              '{ name = "op-0" || name = "op-2" || kind = server }'
              ' | count_over_time() by (resource.service.name)',
              '{ span.retries > 4 || status = error }'
              ' | quantile_over_time(duration, .9) by (name)'):
        req = QueryRangeRequest(query=q, start_ns=int(T0 * 1e9),
                                end_ns=int((T0 + 400) * 1e9),
                                step_ns=int(60e9))
        a = _series_map(dev.query_range("t", req))
        b = _series_map(host.query_range("t", req))
        assert set(a) == set(b), q
        for k in b:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-4,
                                       err_msg=f"{q} {k}")
    assert dev.plane_stats["fused_metric_blocks"] >= before + 3
    # mixed tree: NOT a pure disjunction → host fallback stays
    before_host = dev.plane_stats["host_metric_blocks"]
    req = QueryRangeRequest(
        query='{ kind = server && (name = "op-1" || name = "op-2") }'
              ' | rate() by (name)',
        start_ns=int(T0 * 1e9), end_ns=int((T0 + 400) * 1e9),
        step_ns=int(60e9))
    a = _series_map(dev.query_range("t", req))
    b = _series_map(host.query_range("t", req))
    assert set(a) == set(b)
    for k in b:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-4)
    assert dev.plane_stats["host_metric_blocks"] > before_host


def test_pure_disjunction_rejects_spoofed_shapes(dbs):
    """OR trees whose leaves are NOT single pushable compares must stay on
    the host's exact second pass — the round-5 review crafted shapes where
    a count heuristic certified a SUPERSET mask as exact (dedup'd AND arm,
    zero-push boolean literal). Parity + routing pinned here."""
    dev, host = dbs
    before_host = dev.plane_stats["host_metric_blocks"]
    for q in ('{ name = "op-1" || (name = "op-1" && kind = server) }'
              ' | rate() by (name)',
              '{ (name = "op-1" && false) || kind = server }'
              ' | rate() by (name)'):
        req = QueryRangeRequest(query=q, start_ns=int(T0 * 1e9),
                                end_ns=int((T0 + 400) * 1e9),
                                step_ns=int(60e9))
        a = _series_map(dev.query_range("t", req))
        b = _series_map(host.query_range("t", req))
        assert set(a) == set(b), q
        for k in b:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-4,
                                       err_msg=f"{q} {k}")
    assert dev.plane_stats["host_metric_blocks"] >= before_host + 2
