"""Vectorized OTLP→SpanBatch staging vs the per-span builder path.

Both paths must produce semantically identical batches (same spans, same
interned labels, same attr coding) — the fast path is an optimization of
`spans_from_otlp_proto` + `SpanBatchBuilder`, not a new contract.
"""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu import native
from tempo_tpu.model.interner import INVALID_ID, StringInterner
from tempo_tpu.model.otlp import spans_from_otlp_proto
from tempo_tpu.model.otlp_batch import batch_from_otlp
from tempo_tpu.model.proto_wire import (
    enc_field_bytes,
    enc_field_msg,
    enc_field_str,
    enc_field_varint,
)
from tempo_tpu.model.span_batch import SpanBatchBuilder


def _attr(k: str, v) -> bytes:
    if isinstance(v, bool):
        av = enc_field_varint(2, 1 if v else 0)
    elif isinstance(v, int):
        av = enc_field_varint(3, v)
    else:
        av = enc_field_str(1, str(v))
    return enc_field_str(1, k) + enc_field_msg(2, av)


def _payload() -> bytes:
    import time

    t0 = int((time.time() - 5) * 1e9)
    rng = np.random.default_rng(7)
    out = []
    for svc in range(3):
        spans = []
        for i in range(17):
            b = (enc_field_bytes(1, rng.bytes(16)) +
                 enc_field_bytes(2, rng.bytes(8)) +
                 enc_field_str(5, f"op-{i % 5}") +
                 enc_field_varint(6, i % 6) +
                 enc_field_varint(7, t0 + i) +
                 enc_field_varint(8, t0 + i + 1000) +
                 enc_field_msg(9, _attr("http.status_code", 200 + i)) +
                 enc_field_msg(9, _attr("http.method", "GET")) +
                 enc_field_msg(9, _attr("flag", True)) +
                 enc_field_msg(15, enc_field_varint(3, i % 3) +
                               enc_field_str(2, "boom" if i % 3 == 2 else "")))
            spans.append(enc_field_msg(2, b))
        rs = (enc_field_msg(1, enc_field_msg(1, _attr("service.name", f"s{svc}")) +
                            enc_field_msg(1, _attr("host", f"h{svc}"))) +
              enc_field_msg(2, b"".join(spans)))
        out.append(enc_field_msg(1, rs))
    return b"".join(out)


@pytest.mark.skipif(not native.available(), reason="native scanner required")
def test_fast_path_matches_builder_path():
    data = _payload()
    it_fast = StringInterner()
    fast = batch_from_otlp(data, it_fast)

    it_slow = StringInterner()
    b = SpanBatchBuilder(it_slow)
    for s in spans_from_otlp_proto(data):
        b.append(**s)
    slow = b.build()

    assert fast.n == slow.n == 51
    v = slice(0, fast.n)
    np.testing.assert_array_equal(fast.trace_id[v], slow.trace_id[v])
    np.testing.assert_array_equal(fast.span_id[v], slow.span_id[v])
    np.testing.assert_array_equal(fast.kind[v], slow.kind[v])
    np.testing.assert_array_equal(fast.status_code[v], slow.status_code[v])
    np.testing.assert_array_equal(fast.start_unix_nano[v],
                                  slow.start_unix_nano[v])
    np.testing.assert_array_equal(fast.end_unix_nano[v], slow.end_unix_nano[v])
    # interned ids differ across interners; compare decoded strings
    assert it_fast.lookup_many(fast.name_id[v]) == \
        it_slow.lookup_many(slow.name_id[v])
    assert it_fast.lookup_many(fast.service_id[v]) == \
        it_slow.lookup_many(slow.service_id[v])
    # status_message: INVALID_ID when empty, interned otherwise
    for i in range(fast.n):
        f_id, s_id = int(fast.status_message_id[i]), int(slow.status_message_id[i])
        assert (f_id == INVALID_ID) == (s_id == INVALID_ID)
        if f_id != INVALID_ID:
            assert it_fast.lookup(f_id) == it_slow.lookup(s_id)
    # attr round-trip: full decoded span dicts must match
    fd, sd = fast.to_span_dicts(), slow.to_span_dicts()
    for a, bb in zip(fd, sd):
        assert a == bb


@pytest.mark.skipif(not native.available(), reason="native scanner required")
def test_fast_path_feeds_spanmetrics_identically():
    from tempo_tpu.generator.generator import Generator
    from tempo_tpu.generator.instance import GeneratorConfig
    from tempo_tpu.overrides import Overrides

    data = _payload()
    g1 = Generator(GeneratorConfig(processors=("span-metrics",)),
                   overrides=Overrides())
    g1.push_otlp("t", data)
    g2 = Generator(GeneratorConfig(processors=("span-metrics",)),
                   overrides=Overrides())
    g2.push_spans("t", list(spans_from_otlp_proto(data)))

    p1 = g1.instance("t").processors["span-metrics"]
    p2 = g2.instance("t").processors["span-metrics"]
    # same total calls; same per-label-set counts
    v1 = np.asarray(p1.calls.state.values)
    v2 = np.asarray(p2.calls.state.values)
    assert v1.sum() == v2.sum() == 51
    c1 = {p1.calls.labels_of(int(s)): v1[s]
          for s in p1.calls.table.active_slots()}
    c2 = {p2.calls.labels_of(int(s)): v2[s]
          for s in p2.calls.table.active_slots()}
    assert c1 == c2


def test_fallback_without_native(monkeypatch):
    from tempo_tpu import native as nat

    monkeypatch.setattr(nat, "otlp_stage",
                        lambda interner, data, **kw: None)
    data = _payload()
    it = StringInterner()
    sb = batch_from_otlp(data, it)
    assert sb.n == 51
    assert it.lookup(int(sb.service_id[0])) == "s0"


@pytest.mark.skipif(not native.available(), reason="native scanner required")
def test_service_name_last_occurrence_wins():
    """Dict semantics: the LAST service.name occurrence wins regardless of
    value type (regression: the staged path let the last STRING win)."""
    import time

    t0 = int((time.time() - 5) * 1e9)

    def payload(attr_values) -> bytes:
        resource = b"".join(
            enc_field_msg(1, _attr("service.name", v)) for v in attr_values)
        span = enc_field_msg(2, (
            enc_field_bytes(1, b"\x01" * 16) + enc_field_bytes(2, b"\x02" * 8) +
            enc_field_str(5, "op") + enc_field_varint(7, t0) +
            enc_field_varint(8, t0 + 1000)))
        return enc_field_msg(1, enc_field_msg(1, resource) +
                             enc_field_msg(2, span))

    cases = [
        ([42, "strsvc"], "strsvc"),      # string last → string wins
        (["strsvc", 42], "42"),          # int last → stringified int wins
        (["x", True], "True"),           # bool last
    ]
    for values, want in cases:
        data = payload(values)
        it = StringInterner()
        sb = batch_from_otlp(data, it)
        got = it.lookup(int(sb.service_id[0]))
        assert got == want, (got, want)
        # and it must match the dict fallback path exactly
        it2 = StringInterner()
        b = SpanBatchBuilder(it2)
        for s in spans_from_otlp_proto(data):
            b.append(**s)
        slow = b.build()
        assert it2.lookup(int(slow.service_id[0])) == want
