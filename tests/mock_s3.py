"""In-process mock S3 server for the backend test matrix.

The analog of the reference's minio test containers
(`integration/poller/poller_test.go` backend fixtures): a ThreadingHTTPServer
speaking the S3 subset the backend uses — GET/PUT/DELETE/HEAD on objects,
Range reads, and ListObjectsV2 with prefix/delimiter/pagination.

It VERIFIES AWS SigV4 on every request by rebuilding the canonical request
from the wire (raw path + query + signed headers), independently of the
client's signing code — so client-side canonicalization bugs (e.g. double
percent-encoding) fail here the way they would against real S3/MinIO.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

ACCESS_KEY = "mock-access"
SECRET_KEY = "mock-secret"
REGION = "mock-region-1"


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class MockS3Handler(BaseHTTPRequestHandler):
    store: dict[str, bytes] = {}
    lock = threading.Lock()
    bucket = "test-bucket"

    def log_message(self, fmt, *args):  # noqa: A003
        pass

    # -- sigv4 verification (independent of the client implementation) -----

    def _verify_sig(self, payload: bytes) -> str | None:
        """Returns an error string, or None if the signature checks out."""
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            return "missing AWS4-HMAC-SHA256 authorization"
        fields = dict(
            p.strip().split("=", 1) for p in auth[len("AWS4-HMAC-SHA256 "):].split(","))
        cred = fields["Credential"].split("/")
        if cred[0] != ACCESS_KEY:
            return "unknown access key"
        datestamp, region, service = cred[1], cred[2], cred[3]
        signed_headers = fields["SignedHeaders"].split(";")
        amz_date = self.headers.get("x-amz-date", "")
        body_sha = self.headers.get("x-amz-content-sha256", "")
        if hashlib.sha256(payload).hexdigest() != body_sha:
            return "payload hash mismatch"

        split = urllib.parse.urlsplit(self.path)
        canon_uri = split.path or "/"
        q = urllib.parse.parse_qsl(split.query, keep_blank_values=True)
        canon_query = "&".join(
            f"{urllib.parse.quote(k, safe='-_.~')}="
            f"{urllib.parse.quote(v, safe='-_.~')}"
            for k, v in sorted(q))
        canon_headers = "".join(
            f"{h}:{(self.headers.get(h) or '').strip()}\n"
            for h in signed_headers)
        canon_req = "\n".join([
            self.command, canon_uri, canon_query, canon_headers,
            ";".join(signed_headers), body_sha])
        scope = f"{datestamp}/{region}/{service}/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canon_req.encode()).hexdigest()])
        k = _hmac(("AWS4" + SECRET_KEY).encode(), datestamp)
        k = _hmac(k, region)
        k = _hmac(k, service)
        k = _hmac(k, "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        if sig != fields["Signature"]:
            return "SignatureDoesNotMatch"
        # basic clock sanity, as S3 enforces
        try:
            dt = datetime.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ")
        except ValueError:
            return "bad x-amz-date"
        skew = abs((datetime.datetime.now(datetime.timezone.utc)
                    - dt.replace(tzinfo=datetime.timezone.utc)).total_seconds())
        if skew > 900:
            return "RequestTimeTooSkewed"
        return None

    # -- helpers ------------------------------------------------------------

    def _key(self) -> str | None:
        split = urllib.parse.urlsplit(self.path)
        parts = split.path.lstrip("/").split("/", 1)
        if parts[0] != self.bucket:
            return None
        return urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""

    def _reply(self, code: int, body: bytes = b"",
               headers: dict | None = None) -> None:
        self.send_response(code)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _deny(self, msg: str) -> None:
        self._reply(403, f"<Error><Code>{msg}</Code></Error>".encode())

    # -- verbs --------------------------------------------------------------

    def do_PUT(self) -> None:  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        if (err := self._verify_sig(body)) is not None:
            return self._deny(err)
        key = self._key()
        if key is None or not key:
            return self._reply(400)
        with self.lock:
            self.store[key] = body
        self._reply(200)

    def do_GET(self) -> None:  # noqa: N802
        if (err := self._verify_sig(b"")) is not None:
            return self._deny(err)
        key = self._key()
        if key is None:
            return self._reply(404)
        split = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(split.query, keep_blank_values=True))
        if key == "" and q.get("list-type") == "2":
            return self._list_v2(q)
        with self.lock:
            data = self.store.get(key)
        if data is None:
            return self._reply(
                404, b"<Error><Code>NoSuchKey</Code></Error>")
        rng = self.headers.get("Range", "")
        if rng.startswith("bytes="):
            lo_s, hi_s = rng[len("bytes="):].split("-", 1)
            lo = int(lo_s)
            hi = min(int(hi_s), len(data) - 1) if hi_s else len(data) - 1
            if lo >= len(data):
                return self._reply(416)
            part = data[lo:hi + 1]
            return self._reply(206, part, {
                "Content-Range": f"bytes {lo}-{hi}/{len(data)}"})
        self._reply(200, data)

    def do_HEAD(self) -> None:  # noqa: N802
        if (err := self._verify_sig(b"")) is not None:
            return self._reply(403)
        key = self._key()
        with self.lock:
            data = self.store.get(key or "")
        if data is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_DELETE(self) -> None:  # noqa: N802
        if (err := self._verify_sig(b"")) is not None:
            return self._deny(err)
        key = self._key()
        with self.lock:
            self.store.pop(key or "", None)
        self._reply(204)

    # -- ListObjectsV2 ------------------------------------------------------

    def _list_v2(self, q: dict) -> None:
        prefix = q.get("prefix", "")
        delim = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", "1000"))
        token = q.get("continuation-token", "")
        with self.lock:
            all_keys = sorted(k for k in self.store if k.startswith(prefix))
        if token:
            all_keys = [k for k in all_keys if k > token]
        contents: list[str] = []
        prefixes: list[str] = []
        for k in all_keys:
            if delim:
                rest = k[len(prefix):]
                if delim in rest:
                    p = prefix + rest.split(delim, 1)[0] + delim
                    if p not in prefixes:
                        prefixes.append(p)
                    continue
            contents.append(k)
            if len(contents) >= max_keys:
                break
        truncated = bool(contents) and contents[-1] != (all_keys[-1] if all_keys else "")
        # pagination token = last emitted key (lexicographic resume)
        parts = ["<?xml version=\"1.0\"?><ListBucketResult>"]
        for k in contents:
            parts.append(f"<Contents><Key>{k}</Key>"
                         f"<Size>{len(self.store[k])}</Size></Contents>")
        for p in prefixes:
            parts.append(f"<CommonPrefixes><Prefix>{p}</Prefix></CommonPrefixes>")
        parts.append(f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>")
        if truncated and contents:
            parts.append(
                f"<NextContinuationToken>{contents[-1]}</NextContinuationToken>")
        parts.append("</ListBucketResult>")
        self._reply(200, "".join(parts).encode())


def start_mock_s3() -> tuple[ThreadingHTTPServer, int, type]:
    """Returns (server, port, handler_cls). Each call gets an isolated
    store (a fresh Handler subclass)."""
    cls = type("BoundMockS3", (MockS3Handler,),
               {"store": {}, "lock": threading.Lock()})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), cls)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1], cls
