"""Microservices deployment: four processes' worth of Apps over HTTP RPC.

The e2e shape of the reference's `integration/e2e/deployments/
microservices_test.go`: distributor, ingester, metrics-generator, and
query tier run as separate Apps (in-process servers here) wired by static
peer addresses, sharing only the object-store backend.
"""

from __future__ import annotations

import json
import socket
import time
import urllib.request

import pytest

from tempo_tpu.app import App
from tempo_tpu.app.api import serve
from tempo_tpu.app.config import Config


def _port() -> int:
    s = socket.socket(); s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]; s.close()
    return p


@pytest.fixture
def cluster(tmp_path):
    store = str(tmp_path / "store")
    ports = {k: _port() for k in ("ing", "gen", "query", "dist")}
    url = {k: f"http://127.0.0.1:{p}" for k, p in ports.items()}
    apps, servers = {}, {}

    def boot(name, cfg):
        cfg.server.http_listen_port = ports[name]
        app = App(cfg)
        # per-tenant processor enablement: in a real deployment this is the
        # shared runtime-config overrides file every process reads
        app.overrides.set_tenant_patch("single-tenant", {
            "generator": {"processors": ["span-metrics", "local-blocks"]}})
        app.start_loops()
        apps[name] = app
        servers[name] = serve(app, block=False)

    ing_cfg = Config(target="ingester")
    ing_cfg.storage.backend = "local"
    ing_cfg.storage.local_path = store
    ing_cfg.storage.wal_path = str(tmp_path / "ing" / "wal")
    ing_cfg.ingester.instance.trace_idle_s = 0.1
    boot("ing", ing_cfg)

    gen_cfg = Config(target="metrics-generator")
    gen_cfg.storage.backend = "local"
    gen_cfg.storage.local_path = store
    gen_cfg.generator.localblocks.data_dir = str(tmp_path / "gen-lb")
    boot("gen", gen_cfg)

    q_cfg = Config(target="query-frontend")
    q_cfg.storage.backend = "local"
    q_cfg.storage.local_path = store
    q_cfg.peers.ingesters = {"ing-1": url["ing"]}
    q_cfg.peers.generators = {"gen-1": url["gen"]}
    boot("query", q_cfg)

    d_cfg = Config(target="distributor")
    d_cfg.peers.ingesters = {"ing-1": url["ing"]}
    d_cfg.peers.generators = {"gen-1": url["gen"]}
    boot("dist", d_cfg)

    yield apps, url
    for s in servers.values():
        s.shutdown()
    for a in apps.values():
        a.shutdown()


def _post(url, body, ctype="application/json"):
    req = urllib.request.Request(url, data=body,
                                 headers={"Content-Type": ctype})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, json.loads(r.read() or b"{}")


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, json.loads(r.read() or b"{}")


def test_microservices_write_read(cluster):
    apps, url = cluster
    t0 = int((time.time() - 5) * 1e9)
    otlp = {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name", "value": {"stringValue": "micro"}}]},
        "scopeSpans": [{"spans": [{
            "traceId": "ee" * 16, "spanId": "bb" * 8, "name": "ms-op",
            "kind": 2, "startTimeUnixNano": str(t0),
            "endTimeUnixNano": str(t0 + 40_000_000),
            "status": {"code": 0}}]}]}]}
    # write through the DISTRIBUTOR process
    code, _ = _post(url["dist"] + "/v1/traces", json.dumps(otlp).encode())
    assert code == 200
    # the INGESTER process holds the live trace
    assert apps["ing"].ingester.instance("single-tenant").live
    # the GENERATOR process aggregated it
    assert apps["gen"].generator.instance("single-tenant").spans_received == 1
    # trace-by-id through the QUERY tier (remote ingester RPC)
    code, tr = _get(url["query"] + f"/api/traces/{'ee' * 16}")
    assert code == 200 and tr["spans"][0]["name"] == "ms-op"
    # search through the QUERY tier
    code, res = _get(url["query"] + "/api/search?q=" + urllib.parse.quote(
        '{ resource.service.name = "micro" }'))
    assert code == 200 and len(res["traces"]) == 1
    # TraceQL metrics through the QUERY tier (remote generator RPC)
    now = time.time()
    code, qr = _get(url["query"] + "/api/metrics/query_range?q=" +
                    urllib.parse.quote("{ } | count_over_time()") +
                    f"&start={now - 300}&end={now}&step=300")
    assert code == 200
    total = sum(d["value"] for s in qr["series"]
                for d in s.get("samples", []) if d["value"] == d["value"])
    assert total == 1
    # tags through the QUERY tier (remote ingester tag RPC)
    code, tags = _get(url["query"] + "/api/search/tags")
    assert code == 200


def test_microservices_flush_to_shared_store(cluster):
    apps, url = cluster
    t0 = int((time.time() - 5) * 1e9)
    otlp = {"resourceSpans": [{"scopeSpans": [{"spans": [{
        "traceId": "dd" * 16, "spanId": "aa" * 8, "name": "flushed",
        "startTimeUnixNano": str(t0),
        "endTimeUnixNano": str(t0 + 10_000_000)}]}]}]}
    _post(url["dist"] + "/v1/traces", json.dumps(otlp).encode())
    # force the ingester to flush to the shared store
    time.sleep(0.2)
    apps["ing"].ingester.flush_all()
    # query tier polls the store and finds the trace from the BACKEND
    apps["query"].db.poll_now()
    spans = apps["query"].db.find_trace_by_id("single-tenant", b"\xdd" * 16)
    assert spans and spans[0]["name"] == "flushed"


def test_ring_kv_cluster_survives_ingester_death(tmp_path):
    """3 ingesters + distributor + query tier discovered via the shared
    HTTP CAS KV ring (the memberlist analog, `modules.go:593-625`), RF3.
    One ingester dies abruptly mid-test; writes (quorum 2/3) and reads
    (quorum + heartbeat failover) still succeed — VERDICT r1 item 6."""
    store = str(tmp_path / "store")
    apps, servers = {}, {}

    def boot(name, cfg, kv_url):
        cfg.server.http_listen_port = _port()
        cfg.ring_kv_url = kv_url
        cfg.heartbeat_interval_s = 0.2
        cfg.heartbeat_timeout_s = 1.5
        app = App(cfg)
        app.overrides.set_tenant_patch("single-tenant", {
            "generator": {"processors": ["span-metrics"]}})
        app.start_loops()
        apps[name] = app
        servers[name] = serve(app, block=False)
        return f"http://127.0.0.1:{cfg.server.http_listen_port}"

    # the distributor hosts the KV; everyone else dials it
    d_cfg = Config(target="distributor")
    d_cfg.distributor.rf = 3
    kv_url = boot("dist", d_cfg, "local")

    for i in range(3):
        ing_cfg = Config(target="ingester")
        ing_cfg.storage.backend = "local"
        ing_cfg.storage.local_path = store
        ing_cfg.storage.wal_path = str(tmp_path / f"ing{i}" / "wal")
        ing_cfg.ingester.instance.trace_idle_s = 0.1
        boot(f"ing{i}", ing_cfg, kv_url)

    q_cfg = Config(target="query-frontend")
    q_cfg.storage.backend = "local"
    q_cfg.storage.local_path = store
    q_cfg.querier.rf = 3
    boot("query", q_cfg, kv_url)

    try:
        # wait for all 3 ingesters to appear on the distributor's ring
        deadline = time.time() + 10
        while time.time() < deadline:
            if len(apps["dist"].distributor.ingester_ring) >= 3:
                break
            time.sleep(0.1)
        assert len(apps["dist"].distributor.ingester_ring) == 3

        url = {k: f"http://127.0.0.1:{a.cfg.server.http_listen_port}"
               for k, a in apps.items()}
        t0 = int((time.time() - 5) * 1e9)

        def push(tid_hex: str) -> int:
            otlp = {"resourceSpans": [{"resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": "rk"}}]},
                "scopeSpans": [{"spans": [{
                    "traceId": tid_hex, "spanId": "ab" * 8, "name": "rk-op",
                    "kind": 2, "startTimeUnixNano": str(t0),
                    "endTimeUnixNano": str(t0 + 10_000_000)}]}]}]}
            code, _ = _post(url["dist"] + "/v1/traces",
                            json.dumps(otlp).encode())
            return code

        assert push("11" * 16) == 200
        # RF3: every ingester holds the trace
        held = sum(1 for i in range(3)
                   if apps[f"ing{i}"].ingester.find_trace_by_id(
                       "single-tenant", b"\x11" * 16))
        assert held == 3

        # read through the query tier (quorum across the ring)
        code, tr = _get(url["query"] + f"/api/traces/{'11' * 16}")
        assert code == 200 and tr["spans"][0]["name"] == "rk-op"

        # --- kill one ingester ABRUPTLY (no graceful leave) ---
        victim = apps.pop("ing1")
        servers.pop("ing1").shutdown()
        victim._stop.set()              # loops stop; no lc.leave()
        for lc in victim._lifecyclers:  # heartbeat loops live on the
            lc.stop_heartbeat()         # lifecyclers now — kill those too

        # writes still succeed immediately: quorum 2 of RF3
        assert push("22" * 16) == 200
        held = sum(1 for i in (0, 2)
                   if apps[f"ing{i}"].ingester.find_trace_by_id(
                       "single-tenant", b"\x22" * 16))
        assert held == 2

        # reads still succeed immediately (error budget covers the corpse)
        code, tr = _get(url["query"] + f"/api/traces/{'22' * 16}")
        assert code == 200 and tr["spans"][0]["name"] == "rk-op"

        # after the heartbeat timeout the ring marks it unhealthy and
        # search fan-out no longer touches it
        time.sleep(2.0)
        ring = apps["query"].querier.ring
        healthy = {i.id for i in ring.healthy_instances()}
        assert len(healthy) == 2 and victim._iid("ingester") not in healthy
        code, res = _get(url["query"] + "/api/search?q=" +
                         urllib.parse.quote('{ resource.service.name = "rk" }'))
        assert code == 200 and len(res["traces"]) >= 1
    finally:
        for s in servers.values():
            s.shutdown()
        for a in apps.values():
            a.shutdown()


def test_replicated_kv_survives_kv_host_death(tmp_path):
    """The ring KV itself is replicated across the 3 ingester processes
    (per-member CAS, merged reads — the memberlist de-SPOF, VERDICT r2 #6).
    The KV member that dies is also a data member; writes, reads, ring
    convergence, and a brand-new instance JOINING all still work."""
    store = str(tmp_path / "store")
    apps, servers = {}, {}

    ports = [_port() for _ in range(3)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    kv_all = ",".join(urls)

    def boot(name, cfg, kv_url, port=None):
        cfg.server.http_listen_port = port or _port()
        cfg.ring_kv_url = kv_url
        cfg.heartbeat_interval_s = 0.2
        cfg.heartbeat_timeout_s = 1.5
        app = App(cfg)
        app.overrides.set_tenant_patch("single-tenant", {
            "generator": {"processors": ["span-metrics"]}})
        app.start_loops()
        apps[name] = app
        servers[name] = serve(app, block=False)

    def ing_cfg(i):
        cfg = Config(target="ingester")
        cfg.storage.backend = "local"
        cfg.storage.local_path = store
        cfg.storage.wal_path = str(tmp_path / f"ing{i}" / "wal")
        cfg.ingester.instance.trace_idle_s = 0.1
        return cfg

    # each ingester hosts a KV member: "local" replaces its own URL
    for i in range(3):
        members = ["local" if j == i else urls[j] for j in range(3)]
        boot(f"ing{i}", ing_cfg(i), ",".join(members), port=ports[i])

    d_cfg = Config(target="distributor")
    d_cfg.distributor.rf = 3
    boot("dist", d_cfg, kv_all)
    q_cfg = Config(target="query-frontend")
    q_cfg.storage.backend = "local"
    q_cfg.storage.local_path = store
    q_cfg.querier.rf = 3
    boot("query", q_cfg, kv_all)

    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if len(apps["dist"].distributor.ingester_ring) >= 3:
                break
            time.sleep(0.1)
        assert len(apps["dist"].distributor.ingester_ring) == 3

        url = {k: f"http://127.0.0.1:{a.cfg.server.http_listen_port}"
               for k, a in apps.items()}
        t0 = int((time.time() - 5) * 1e9)

        def push(tid_hex: str) -> int:
            otlp = {"resourceSpans": [{"resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": "rkv"}}]},
                "scopeSpans": [{"spans": [{
                    "traceId": tid_hex, "spanId": "ab" * 8, "name": "rkv-op",
                    "kind": 2, "startTimeUnixNano": str(t0),
                    "endTimeUnixNano": str(t0 + 10_000_000)}]}]}]}
            code, _ = _post(url["dist"] + "/v1/traces",
                            json.dumps(otlp).encode())
            return code

        assert push("31" * 16) == 200
        held = sum(1 for i in range(3)
                   if apps[f"ing{i}"].ingester.find_trace_by_id(
                       "single-tenant", b"\x31" * 16))
        assert held == 3

        # --- kill ingester 1: a KV MEMBER and a data replica, abruptly ---
        victim = apps.pop("ing1")
        servers.pop("ing1").shutdown()
        victim._stop.set()
        for lc in victim._lifecyclers:
            lc.stop_heartbeat()         # abrupt death: no beats, no leave

        # KV writes (heartbeats) keep landing on the 2 surviving members,
        # so the membership view stays writable: pushes/reads work NOW
        assert push("32" * 16) == 200
        held = sum(1 for i in (0, 2)
                   if apps[f"ing{i}"].ingester.find_trace_by_id(
                       "single-tenant", b"\x32" * 16))
        assert held == 2
        code, tr = _get(url["query"] + f"/api/traces/{'32' * 16}")
        assert code == 200 and tr["spans"][0]["name"] == "rkv-op"

        # ring convergence continues without the dead KV member
        time.sleep(2.0)
        healthy = {i.id for i in
                   apps["query"].querier.ring.healthy_instances()}
        assert len(healthy) == 2

        # a brand-new instance can still JOIN through the surviving members
        # (its member list still names the dead host)
        boot("ing3", ing_cfg(3), kv_all)
        deadline = time.time() + 10
        while time.time() < deadline:
            healthy = {i.id for i in
                       apps["query"].querier.ring.healthy_instances()}
            if len(healthy) >= 3:
                break
            time.sleep(0.1)
        assert len(healthy) == 3
        assert push("33" * 16) == 200
    finally:
        for s in servers.values():
            s.shutdown()
        for a in apps.values():
            a.shutdown()


def test_scaled_monolith_generator_fanout(tmp_path):
    """Two target=all processes share the ring KV: the distributor spreads
    generator spans across BOTH ring members, so the frontend must fan out
    over the whole generator ring — a local-only read would silently
    return partial metrics (ADVICE r2 #2)."""
    store = str(tmp_path / "store")
    apps, servers = {}, {}

    def boot(name, kv_url):
        cfg = Config(target="all")
        cfg.storage.backend = "local"
        cfg.storage.local_path = store
        cfg.storage.wal_path = str(tmp_path / name / "wal")
        cfg.generator.localblocks.data_dir = str(tmp_path / name / "lb")
        cfg.server.http_listen_port = _port()
        cfg.ring_kv_url = kv_url
        cfg.heartbeat_interval_s = 0.2
        cfg.heartbeat_timeout_s = 5.0
        app = App(cfg)
        app.overrides.set_tenant_patch("single-tenant", {
            "generator": {"processors": ["span-metrics", "local-blocks"]}})
        app.start_loops()
        apps[name] = app
        servers[name] = serve(app, block=False)
        return f"http://127.0.0.1:{cfg.server.http_listen_port}"

    kv_url = boot("a", "local")
    boot("b", kv_url)
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if len(apps["a"].distributor.generator_ring) >= 2 and \
                    len(apps["b"].distributor.generator_ring) >= 2:
                break
            time.sleep(0.1)
        assert len(apps["a"].distributor.generator_ring) == 2

        url_a = f"http://127.0.0.1:{apps['a'].cfg.server.http_listen_port}"
        t0 = int((time.time() - 5) * 1e9)
        spans = []
        for i in range(1, 41):
            spans.append({"traceId": ("%02x" % i) * 16, "spanId": "ab" * 8,
                          "name": "fan-op", "kind": 2,
                          "startTimeUnixNano": str(t0),
                          "endTimeUnixNano": str(t0 + 10_000_000)})
        otlp = {"resourceSpans": [{"resource": {"attributes": [
            {"key": "service.name", "value": {"stringValue": "fan"}}]},
            "scopeSpans": [{"spans": spans}]}]}
        code, _ = _post(url_a + "/v1/traces", json.dumps(otlp).encode())
        assert code == 200

        # spans really spread across BOTH processes' generators
        got = [apps[n].generator.instance("single-tenant").spans_received
               for n in ("a", "b")]
        assert sum(got) == 40 and all(g > 0 for g in got), got

        # metrics through EITHER frontend must see the FULL count
        now = time.time()
        for n in ("a", "b"):
            base = f"http://127.0.0.1:{apps[n].cfg.server.http_listen_port}"
            code, qr = _get(base + "/api/metrics/query_range?q=" +
                            urllib.parse.quote("{ } | count_over_time()") +
                            f"&start={now - 300}&end={now}&step=300")
            assert code == 200
            total = sum(d["value"] for s in qr["series"]
                        for d in s.get("samples", []) if d["value"] == d["value"])
            assert total == 40, (n, total, qr["series"])
    finally:
        for s in servers.values():
            s.shutdown()
        for a in apps.values():
            a.shutdown()
