"""Ingest bus, blockbuilder, compactor ring ownership."""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.blockbuilder import BlockBuilder, BlockBuilderConfig
from tempo_tpu.blockbuilder.blockbuilder import CONSUMER_GROUP, produce_traces
from tempo_tpu.compactor import Compactor
from tempo_tpu.db.tempodb import TempoDB
from tempo_tpu.ingest import Bus, decode_push, encode_push
from tempo_tpu.ops.hashing import token_for
from tempo_tpu.ring import KVStore

T0 = 1_700_000_000.0


def mktrace(i: int, n_spans: int = 2):
    tid = bytes([i, i]) * 8
    t0 = int((T0 + i) * 1e9)
    return tid, [{"trace_id": tid, "span_id": bytes([j + 1]) * 8,
                  "name": f"op-{j}", "service": "svc",
                  "start_unix_nano": t0, "end_unix_nano": t0 + 10 ** 6,
                  "attrs": {"k": j}} for j in range(n_spans)]


def test_encode_decode_round_trip():
    traces = [mktrace(i) for i in range(1, 6)]
    recs = encode_push(traces)
    back = [t for r in recs for t in decode_push(r)]
    assert len(back) == 5
    assert back[0][0] == traces[0][0]
    assert back[0][1][0]["name"] == "op-0"
    assert back[0][1][0]["attrs"] == {"k": 0}


def test_encode_splits_large_pushes():
    big = [mktrace(i, n_spans=40) for i in range(1, 30)]
    recs = encode_push(big, max_record_bytes=8192)
    assert len(recs) > 1
    assert all(len(r) <= 8192 * 2 for r in recs)
    back = [t for r in recs for t in decode_push(r)]
    assert len(back) == 29


def test_bus_offsets_and_lag():
    bus = Bus(n_partitions=2)
    for i in range(5):
        bus.produce(0, "t", b"x%d" % i)
    assert bus.high_watermark(0) == 5
    assert bus.lag("g", 0) == 5
    recs = bus.fetch(0, 0, 3)
    assert [r.offset for r in recs] == [0, 1, 2]
    bus.commit("g", 0, 3)
    assert bus.lag("g", 0) == 2
    assert bus.committed("g", 0) == 3


def test_blockbuilder_commit_after_flush():
    bus = Bus(n_partitions=2)
    be = MemBackend()
    traces = [mktrace(i) for i in range(1, 21)]
    mat = np.stack([np.frombuffer(t[0], np.uint8) for t in traces])
    tokens = token_for("acme", mat)
    produce_traces(bus, "acme", traces, tokens)
    total = bus.high_watermark(0) + bus.high_watermark(1)
    assert total >= 2  # spread over both partitions

    bb = BlockBuilder(bus, be, BlockBuilderConfig(partitions=(0, 1)))
    n = bb.consume_cycle()
    assert n == total
    assert bus.lag(CONSUMER_GROUP, 0) == 0
    assert bus.lag(CONSUMER_GROUP, 1) == 0
    db = TempoDB(be, be)
    db.poll_now()
    metas = db.blocklist.metas("acme")
    assert sum(m.total_objects for m in metas) == 20
    assert all(m.replication_factor == 1 for m in metas)
    # crash-replay: un-commit partition 0 and reconsume — blocks duplicate
    # (at-least-once), compaction dedupes
    bus.commit(CONSUMER_GROUP, 0, 0)
    bb.consume_cycle()
    db.poll_now()
    db.compact_tenant_once("acme")
    metas = db.blocklist.metas("acme")
    assert sum(m.total_objects for m in metas) == 20  # deduped again


def test_generator_consumes_bus():
    from tempo_tpu.generator import Generator, GeneratorConfig
    from tempo_tpu.overrides import Overrides

    bus = Bus(n_partitions=1)
    traces = [mktrace(i, 1) for i in range(1, 11)]
    mat = np.stack([np.frombuffer(t[0], np.uint8) for t in traces])
    produce_traces(bus, "acme", traces, token_for("acme", mat))
    ov = Overrides()
    ov.set_tenant_patch("acme", {"generator": {"processors": ["span-metrics"]}})
    g = Generator(GeneratorConfig(ingestion_time_range_slack_s=0),
                  overrides=ov, now=lambda: T0 + 30)
    n = g.consume_bus(bus, [0])
    assert n >= 1
    assert g.instance("acme").spans_received == 10
    assert bus.lag("metrics-generator", 0) == 0
    # nothing new: no-op
    assert g.consume_bus(bus, [0]) == 0


def test_generator_bus_skips_disabled_tenants():
    """Bus carries every trace (blockbuilder needs them) but generators
    must not spawn instances for tenants with generation disabled."""
    from tempo_tpu.generator import Generator, GeneratorConfig
    from tempo_tpu.overrides import Limits, Overrides
    import dataclasses as dc

    bus = Bus(n_partitions=1)
    traces = [mktrace(i, 1) for i in range(1, 4)]
    mat = np.stack([np.frombuffer(t[0], np.uint8) for t in traces])
    produce_traces(bus, "quiet-tenant", traces, token_for("q", mat))
    defaults = Limits()
    defaults.generator = dc.replace(defaults.generator, processors=())
    g = Generator(GeneratorConfig(), overrides=Overrides(defaults=defaults),
                  now=lambda: T0 + 30)
    g.consume_bus(bus, [0])
    assert "quiet-tenant" not in g.instances
    assert bus.lag("metrics-generator", 0) == 0  # still committed past


def test_compactor_ownership_fails_over_from_dead_instance():
    """A crashed compactor's job share moves to live instances instead of
    black-holing behind its stale ring descriptor."""
    clock = [1000.0]
    kv = KVStore()
    be = MemBackend()
    db = TempoDB(be, be)
    c1 = Compactor(db, kv, "compactor-1", now=lambda: clock[0])
    c2 = Compactor(db, kv, "compactor-2", now=lambda: clock[0])
    keys = [f"tenant-{i}/job" for i in range(40)]
    owned2 = {k for k in keys if c2.owns(k)}
    assert owned2
    # c2 crashes (no leave): its heartbeat goes stale
    clock[0] += 30.0
    c1.heartbeat()
    clock[0] += 50.0  # c2's heartbeat now 80s old > 60s timeout
    for k in keys:
        assert c1.owns(k)  # everything failed over to the live instance


def test_distributor_bus_replaces_generator_tee():
    """With the bus configured, direct ingester+generator sends are off."""
    from tempo_tpu.distributor import Distributor
    from tempo_tpu.overrides import Overrides
    from tempo_tpu.ring import ACTIVE, InstanceDesc, Ring
    from tempo_tpu.ring.ring import _instance_tokens

    class CapturingGen:
        def __init__(self):
            self.spans = []
        def push_otlp(self, tenant, data):
            from tempo_tpu.model.otlp import spans_from_otlp_proto
            got = list(spans_from_otlp_proto(data))
            self.spans.extend(got)
            return len(got)

    class NullIng:
        def __init__(self):
            self.pushes = 0
        def push(self, tenant, traces):
            self.pushes += 1
            return [None] * len(traces)

    now = lambda: 0.0
    iring = Ring(replication_factor=1, now=now)
    iring.register(InstanceDesc(id="i0", state=ACTIVE,
                                tokens=_instance_tokens("i0", 16),
                                heartbeat_ts=0))
    gring = Ring(replication_factor=1, now=now)
    gring.register(InstanceDesc(id="g0", state=ACTIVE,
                                tokens=_instance_tokens("g0", 16),
                                heartbeat_ts=0))
    gen = CapturingGen()
    ing = NullIng()
    ov = Overrides()
    ov.set_tenant_patch("t", {"generator": {"processors": ["span-metrics"]}})
    bus = Bus(1)
    d = Distributor(iring, {"i0": ing}, overrides=ov,
                    generator_ring=gring, generator_clients={"g0": gen},
                    bus=bus, now=now)
    tid, spans = mktrace(1)
    d.push_spans("t", spans)
    assert gen.spans == []                       # generator tee suppressed
    assert ing.pushes == 0                       # ingester path suppressed
    assert bus.high_watermark(0) == 1            # bus got the record


def test_compactor_ring_splits_ownership():
    kv = KVStore()
    be = MemBackend()
    db = TempoDB(be, be)
    c1 = Compactor(db, kv, "compactor-1", now=lambda: 0)
    c2 = Compactor(db, kv, "compactor-2", now=lambda: 0)
    keys = [f"tenant-{i}/job" for i in range(40)]
    owned1 = {k for k in keys if c1.owns(k)}
    owned2 = {k for k in keys if c2.owns(k)}
    assert owned1 | owned2 == set(keys)
    assert not (owned1 & owned2)
    assert owned1 and owned2
    # single instance owns everything
    solo = Compactor(db, None, "solo")
    assert all(solo.owns(k) for k in keys)


# -- real Kafka wire protocol (pkg/ingest external client) -------------------

def _kafka_rig():
    from tests.mock_kafka import start_mock_kafka
    from tempo_tpu.ingest.kafka import KafkaBus

    srv, port, broker = start_mock_kafka(n_partitions=2)
    bus = KafkaBus(f"127.0.0.1:{port}", n_partitions=2)
    return srv, broker, bus


def test_kafka_wire_produce_fetch_commit():
    srv, broker, bus = _kafka_rig()
    try:
        assert bus.produce(0, "t1", b"hello") == 0
        assert bus.produce(0, "t1", b"world") == 1
        assert bus.produce(1, "t2", b"other") == 0
        assert broker.produce_batches == 3      # crc32c verified per batch

        recs = bus.fetch(0, 0)
        assert [(r.offset, r.tenant, r.value) for r in recs] == \
            [(0, "t1", b"hello"), (1, "t1", b"world")]
        assert bus.fetch(0, 1)[0].value == b"world"
        assert bus.fetch(0, 2) == []
        assert bus.high_watermark(0) == 2 and bus.high_watermark(1) == 1

        assert bus.committed("g", 0) == 0       # no commit yet
        bus.commit("g", 0, 2)
        assert bus.committed("g", 0) == 2
        assert bus.lag("g", 0) == 0 and bus.lag("g", 1) == 1
    finally:
        bus.close()
        srv.shutdown()


def test_kafka_wire_crc_rejected():
    """A corrupted batch must be rejected broker-side AND client-side."""
    import struct

    from tempo_tpu.ingest.kafka import (decode_record_batches,
                                        encode_record_batch)

    batch = bytearray(encode_record_batch(0, [(b"t", b"payload")]))
    batch[-1] ^= 0xFF                           # flip a record byte
    try:
        decode_record_batches(bytes(batch))
        raise AssertionError("expected crc failure")
    except ValueError as e:
        assert "crc" in str(e)

    from tests.mock_kafka import MockKafkaBroker
    try:
        MockKafkaBroker()._decode_batch(bytes(batch))
        raise AssertionError("expected broker crc failure")
    except ValueError as e:
        assert "crc" in str(e)


def test_kafka_bus_feeds_blockbuilder_and_generator():
    """The product path over the REAL wire: distributor produce →
    blockbuilder consume (offset-commit-after-flush) + generator consume,
    unchanged from the in-memory bus."""
    import time

    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.blockbuilder import BlockBuilder
    from tempo_tpu.generator.generator import Generator
    from tempo_tpu.generator.instance import GeneratorConfig
    from tempo_tpu.blockbuilder.blockbuilder import produce_traces
    from tempo_tpu.overrides import Overrides

    srv, broker, bus = _kafka_rig()
    try:
        t0 = int((time.time() - 3) * 1e9)
        groups = []
        import numpy as np
        for i in range(1, 9):
            tid = bytes([i]) * 16
            groups.append((tid, [{"trace_id": tid, "span_id": bytes([i]) * 8,
                                  "name": f"k-{i % 2}", "service": "ksvc",
                                  "start_unix_nano": t0,
                                  "end_unix_nano": t0 + 10**6}]))
        tokens = np.arange(1, 9, dtype=np.uint32) * 1000
        produce_traces(bus, "t1", groups, tokens)
        total_recs = bus.high_watermark(0) + bus.high_watermark(1)
        assert total_recs >= 2          # records batch traces per partition

        be = MemBackend()
        from tempo_tpu.blockbuilder import BlockBuilderConfig
        from tempo_tpu.blockbuilder.blockbuilder import CONSUMER_GROUP
        bb = BlockBuilder(bus, be, BlockBuilderConfig(partitions=(0, 1)))
        n = bb.consume_cycle()
        assert n == total_recs
        from tempo_tpu.db.tempodb import TempoDB
        db = TempoDB(be, be)
        db.poll_now()
        assert sum(m.total_objects
                   for m in db.blocklist.metas("t1")) == 8
        # offsets committed AFTER flush
        assert bus.committed(CONSUMER_GROUP, 0) == bus.high_watermark(0)
        assert bus.committed(CONSUMER_GROUP, 1) == bus.high_watermark(1)

        ov = Overrides()
        ov.set_tenant_patch("t1", {"generator": {"processors": ["span-metrics"]}})
        gen = Generator(GeneratorConfig(processors=("span-metrics",)),
                        overrides=ov)
        got = gen.consume_bus(bus, (0, 1))   # returns RECORD count
        assert got == total_recs
        assert gen.instance("t1").spans_received == 8
    finally:
        bus.close()
        srv.shutdown()


def test_ingest_storage_deployment_over_kafka(tmp_path):
    """The full kafka-path deployment shape: a distributor App produces to
    a real-wire Kafka (mock broker), a block-builder App persists blocks,
    a generator App aggregates — three processes sharing only the broker
    and the object store (`modules.go:386-406` + generator_kafka.go)."""
    import json
    import socket
    import time
    import urllib.request

    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config
    from tests.mock_kafka import start_mock_kafka

    srv, kport, broker = start_mock_kafka(n_partitions=2)

    def port():
        s = socket.socket(); s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]; s.close(); return p

    store = str(tmp_path / "store")
    apps, servers = {}, {}

    def boot(name, cfg):
        cfg.server.http_listen_port = port()
        cfg.ingest.enabled = True
        cfg.ingest.kafka_bootstrap = f"127.0.0.1:{kport}"
        cfg.ingest.n_partitions = 2
        cfg.ingest.consume_interval_s = 0.1
        app = App(cfg)
        app.overrides.set_tenant_patch("single-tenant", {
            "generator": {"processors": ["span-metrics"]}})
        app.start_loops()
        apps[name] = app
        servers[name] = serve(app, block=False)

    d = Config(target="distributor")
    boot("dist", d)
    bbc = Config(target="block-builder")
    bbc.storage.backend = "local"
    bbc.storage.local_path = store
    boot("bb", bbc)
    g = Config(target="metrics-generator")
    g.storage.backend = "local"
    g.storage.local_path = store
    g.generator.localblocks.data_dir = str(tmp_path / "lb")
    boot("gen", g)

    try:
        t0 = int((time.time() - 3) * 1e9)
        spans = [{"traceId": ("%02x" % i) * 16, "spanId": "ab" * 8,
                  "name": "kf-op", "kind": 2,
                  "startTimeUnixNano": str(t0),
                  "endTimeUnixNano": str(t0 + 10_000_000)}
                 for i in range(1, 13)]
        otlp = {"resourceSpans": [{"resource": {"attributes": [
            {"key": "service.name", "value": {"stringValue": "kf"}}]},
            "scopeSpans": [{"spans": spans}]}]}
        url = f"http://127.0.0.1:{apps['dist'].cfg.server.http_listen_port}"
        req = urllib.request.Request(url + "/v1/traces",
                                     data=json.dumps(otlp).encode(),
                                     headers={"Content-Type":
                                              "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        # records landed on the broker (crc-verified) across partitions
        assert broker.produce_batches >= 1

        # block-builder persists, generator aggregates — via their loops
        deadline = time.time() + 10
        while time.time() < deadline:
            inst = apps["gen"].generator.instances.get("single-tenant")
            if inst is not None and inst.spans_received == 12:
                break
            time.sleep(0.1)
        assert apps["gen"].generator.instance(
            "single-tenant").spans_received == 12
        deadline = time.time() + 10
        while time.time() < deadline:
            apps["bb"].db.poll_now()
            metas = apps["bb"].db.blocklist.metas("single-tenant")
            if sum(m.total_objects for m in metas) == 12:
                break
            time.sleep(0.1)
        assert sum(m.total_objects for m in
                   apps["bb"].db.blocklist.metas("single-tenant")) == 12
        # and the blocks are queryable
        spans_back = apps["bb"].db.find_trace_by_id(
            "single-tenant", bytes.fromhex("05" * 16))
        assert spans_back and spans_back[0]["name"] == "kf-op"
    finally:
        for s in servers.values():
            s.shutdown()
        for a in apps.values():
            a.shutdown()
        srv.shutdown()


def test_kafka_leader_routing_split_cluster():
    """Two brokers with split partition leadership: the client must
    discover leaders via Metadata and route produce/fetch to the right
    broker (a bootstrap-only client would NOT_LEADER here), and offsets
    must go to the group coordinator (broker 0)."""
    from tempo_tpu.ingest.kafka import KafkaBus
    from tests.mock_kafka import start_mock_kafka_cluster

    servers, ports, brokers, cluster = start_mock_kafka_cluster(
        n_partitions=4, n_brokers=2)
    try:
        # bootstrap points ONLY at broker 0; partitions 1,3 lead on broker 1
        bus = KafkaBus(f"127.0.0.1:{ports[0]}", n_partitions=4,
                       timeout_s=5.0)
        for p in range(4):
            bus.produce(p, "t", b"v%d" % p)
        # every partition's record landed (routing found both brokers)
        for p in range(4):
            recs = bus.fetch(p, 0)
            assert [r.value for r in recs] == [b"v%d" % p], p
        assert brokers[1].produce_reqs > 0      # broker 1 really served
        # offsets route to the coordinator regardless of entry broker
        bus.commit("g", 1, 1)
        assert bus.committed("g", 1) == 1
        bus.close()
    finally:
        for s in servers:
            s.shutdown()


def test_kafka_releader_refresh_on_not_leader():
    """Moving a partition's leadership mid-stream must be healed by one
    metadata refresh + retry, not an error."""
    from tempo_tpu.ingest.kafka import KafkaBus
    from tests.mock_kafka import start_mock_kafka_cluster

    servers, ports, brokers, cluster = start_mock_kafka_cluster(
        n_partitions=2, n_brokers=2)
    try:
        bus = KafkaBus(f"127.0.0.1:{ports[0]}", n_partitions=2,
                       timeout_s=5.0)
        bus.produce(0, "t", b"a")               # leader: broker 0
        cluster.move_leader(0, 1)               # leadership moves
        bus.produce(0, "t", b"b")               # NOT_LEADER → refresh → ok
        recs = bus.fetch(0, 0)
        assert [r.value for r in recs] == [b"a", b"b"]
        bus.close()
    finally:
        for s in servers:
            s.shutdown()


def test_kafka_dead_broker_failover():
    """A crashed leader (connection refused, not a polite NOT_LEADER)
    must also trigger a metadata remap: leadership moved to a live
    broker, so the retry succeeds."""
    from tempo_tpu.ingest.kafka import KafkaBus
    from tests.mock_kafka import start_mock_kafka_cluster

    servers, ports, brokers, cluster = start_mock_kafka_cluster(
        n_partitions=2, n_brokers=2)
    try:
        bus = KafkaBus(f"127.0.0.1:{ports[0]}", n_partitions=2,
                       timeout_s=2.0)
        bus.produce(1, "t", b"a")               # leader: broker 1
        servers[1].shutdown()                   # broker 1 dies...
        cluster.move_leader(1, 0)               # ...election moves leadership
        with cluster.lock:
            cluster.addrs.pop(1, None)          # gone from metadata too
        bus.produce(1, "t", b"b")               # conn fail → remap → ok
        recs = bus.fetch(1, 0)
        # cluster log is shared state (replication): both records visible
        assert [r.value for r in recs] == [b"a", b"b"]
        bus.close()
    finally:
        for s in servers:
            s.shutdown()


# -- consumer groups (round 5: JoinGroup/SyncGroup/Heartbeat rebalance) ------

def _mk_group_bus(ports, n_partitions=4):
    from tempo_tpu.ingest.kafka import KafkaBus
    return KafkaBus(f"127.0.0.1:{ports[0]}", n_partitions=n_partitions,
                    timeout_s=5.0)


def test_consumer_group_join_and_range_assignment():
    """Two members split 4 partitions via the group protocol: the first
    member owns everything alone, then hands half over after the second
    joins (the rebalance dance: heartbeat → REBALANCE_IN_PROGRESS →
    rejoin → leader re-syncs)."""
    from tempo_tpu.ingest.kafka import ConsumerGroup
    from tests.mock_kafka import start_mock_kafka

    srv, port, broker = start_mock_kafka(n_partitions=4)
    try:
        bus = _mk_group_bus([port])
        fake_now = [1000.0]
        c1 = ConsumerGroup(bus, "bb", now=lambda: fake_now[0])
        c2 = ConsumerGroup(bus, "bb", now=lambda: fake_now[0])
        assert c1.ensure_active() == [0, 1, 2, 3]     # sole member
        # second member joins: its first sync is mid-rebalance (empty)
        assert c2.ensure_active() == []
        # c1's next heartbeat sees the rebalance and rejoins as leader
        fake_now[0] += 3600
        a1 = c1.ensure_active()
        a2 = c2.ensure_active()
        assert sorted(a1 + a2) == [0, 1, 2, 3]
        assert a1 and a2, (a1, a2)                    # both own something
        bus.close()
    finally:
        srv.shutdown()


def test_consumer_group_member_death_rebalances_without_loss():
    """A member dies (session expiry): its partitions move to the
    survivor, which resumes from the COMMITTED offsets — records the dead
    member had not committed are replayed, none are lost. Zombie commits
    from the dead member are fenced (ILLEGAL_GENERATION)."""
    import pytest
    from tempo_tpu.ingest.kafka import ConsumerGroup, KafkaError
    from tests.mock_kafka import start_mock_kafka

    srv, port, broker = start_mock_kafka(n_partitions=4)
    try:
        bus = _mk_group_bus([port])
        for p in range(4):
            for i in range(3):
                bus.produce(p, "t", b"p%d-%d" % (p, i))
        fake_now = [1000.0]
        c1 = ConsumerGroup(bus, "bb", now=lambda: fake_now[0])
        c2 = ConsumerGroup(bus, "bb", now=lambda: fake_now[0])
        c1.ensure_active()
        c2.ensure_active()
        fake_now[0] += 3600
        a1, a2 = c1.ensure_active(), c2.ensure_active()
        assert sorted(a1 + a2) == [0, 1, 2, 3]
        # both consume + commit part of their partitions
        c1.commit(a1[0], 2)
        c2.commit(a2[0], 1)          # c2 read 1 of 3 records, then dies
        broker.cluster.expire_member("bb", c2.member_id)
        # survivor heartbeats into the rebalance and takes everything
        fake_now[0] += 3600
        a1b = c1.ensure_active()
        if not a1b:                  # mid-rebalance tick → next tick owns
            a1b = c1.ensure_active()
        assert a1b == [0, 1, 2, 3]
        # offsets replay from the dead member's last COMMIT (no loss):
        assert bus.committed("bb", a2[0]) == 1
        recs = bus.fetch(a2[0], bus.committed("bb", a2[0]))
        assert len(recs) == 2        # the uncommitted tail replays
        # the zombie's generation-fenced commit is REJECTED
        with pytest.raises(KafkaError):
            c2.commit(a2[0], 3)
        assert bus.committed("bb", a2[0]) == 1
        bus.close()
    finally:
        srv.shutdown()


def test_blockbuilder_and_generator_group_mode():
    """partitions=None on a Kafka bus → the consume loops run in group
    mode end-to-end: blockbuilder flushes blocks from its ASSIGNED
    partitions and commits with the group generation."""
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.blockbuilder import BlockBuilder, BlockBuilderConfig
    from tempo_tpu.ingest.encoding import encode_push
    from tests.mock_kafka import start_mock_kafka

    srv, port, broker = start_mock_kafka(n_partitions=2)
    try:
        bus = _mk_group_bus([port], n_partitions=2)
        for p in range(2):
            bus.produce(p, "t", encode_push(
                [(b"\x01" * 16, [{"trace_id": b"\x01" * 16,
                                  "span_id": b"\x02" * 8,
                                  "name": f"op{p}", "service": "svc",
                                  "start_unix_nano": 1, "end_unix_nano": 2,
                                  "kind": 2, "status_code": 0}])])[0])
        be = MemBackend()
        bb = BlockBuilder(bus, be, BlockBuilderConfig(partitions=None))
        assert bb.consume_cycle() == 2           # group assigned both
        assert bb.blocks_flushed >= 1
        assert bb._cg is not None and bb._cg.generation >= 0
        assert bus.committed("blockbuilder", 0) == 1
        assert bus.committed("blockbuilder", 1) == 1
        bus.close()
    finally:
        srv.shutdown()


def test_consumer_group_survives_coordinator_move():
    """The group coordinator moves to another broker mid-membership
    (normal Kafka operation): heartbeats start answering NOT_COORDINATOR
    and the member must re-discover + retry — NOT go permanently dead."""
    from tempo_tpu.ingest.kafka import ConsumerGroup
    from tests.mock_kafka import start_mock_kafka_cluster

    servers, ports, brokers, cluster = start_mock_kafka_cluster(
        n_partitions=4, n_brokers=2)
    try:
        bus = _mk_group_bus(ports)
        fake_now = [1000.0]
        cg = ConsumerGroup(bus, "bb", now=lambda: fake_now[0])
        assert cg.ensure_active() == [0, 1, 2, 3]
        cluster.move_coordinator(1)
        fake_now[0] += 3600                      # next tick heartbeats
        assert cg.ensure_active() == [0, 1, 2, 3]
        cg.commit(0, 5)                          # commits heal too
        assert bus.committed("bb", 0) == 5
        bus.close()
    finally:
        for s in servers:
            s.shutdown()


def test_two_blockbuilder_apps_split_partitions_via_group(tmp_path):
    """Deployment shape round 5: TWO block-builder Apps with NO static
    partition assignment share a Kafka consumer group — the group
    protocol splits the 4 partitions between them, every produced record
    is persisted exactly once across the pair, and commits carry the
    group generation."""
    import time as _time

    from tempo_tpu.app import App
    from tempo_tpu.app.config import Config
    from tempo_tpu.backend.raw import blocks as list_blocks
    from tempo_tpu.ingest.encoding import encode_push
    from tempo_tpu.ingest.kafka import KafkaBus
    from tests.mock_kafka import start_mock_kafka

    srv, kport, broker = start_mock_kafka(n_partitions=4)
    store = str(tmp_path / "store")
    apps = []
    try:
        producer = KafkaBus(f"127.0.0.1:{kport}", n_partitions=4,
                            timeout_s=5.0)
        rng = __import__("numpy").random.default_rng(3)
        for p in range(4):
            for i in range(2):
                tid = rng.bytes(16)
                producer.produce(p, "t", encode_push([(tid, [{
                    "trace_id": tid, "span_id": rng.bytes(8),
                    "name": f"op-p{p}-{i}", "service": "svc",
                    "start_unix_nano": 1_700_000_000_000_000_000 + p,
                    "end_unix_nano": 1_700_000_000_000_000_001 + p,
                    "kind": 2, "status_code": 0}])])[0])

        clock = [1000.0]           # injected: heartbeats gate on half the
        #                            session timeout, so ticks advance time

        def boot():
            cfg = Config(target="block-builder")
            cfg.storage.backend = "local"
            cfg.storage.local_path = store
            cfg.storage.wal_path = str(tmp_path / f"wal{len(apps)}")
            cfg.ingest.enabled = True
            cfg.ingest.kafka_bootstrap = f"127.0.0.1:{kport}"
            cfg.ingest.n_partitions = 4
            cfg.ingest.partitions = ()       # () = group mode on kafka
            app = App(cfg, now=lambda: clock[0])
            apps.append(app)
            return app

        a, b = boot(), boot()
        assert a.blockbuilder.cfg.partitions is None
        # drive consume cycles by hand (deterministic, no timer threads):
        # the rebalance dance needs a few alternating ticks with time
        # advancing past the heartbeat gate
        for _ in range(6):
            clock[0] += 3600
            a.blockbuilder.consume_cycle()
            b.blockbuilder.consume_cycle()
        pa = a.blockbuilder._cg.assignment
        pb = b.blockbuilder._cg.assignment
        assert sorted(pa + pb) == [0, 1, 2, 3], (pa, pb)
        assert pa and pb                     # both replicas own partitions
        # every record persisted: 8 traces across the pair's blocks
        total = 0
        for bid in list_blocks(a.db.r if a.db else a.backend, "t"):
            from tempo_tpu.backend.meta import read_block_meta
            m = read_block_meta(a.backend, bid, "t")
            total += m.total_objects
        assert total == 8, total
        # offsets committed under the group generation (fenced)
        bus = a.bus
        for p in range(4):
            assert bus.committed("blockbuilder", p) == 2, p
    finally:
        for app in apps:
            app.shutdown()
        srv.shutdown()
