"""Generator ingest WAL + fault-injection registry (ISSUE 14).

Durability contract: every acked push is in the WAL (append before
ack), boot replay past the checkpoint watermark is bit-identical to the
uninterrupted run and exactly-once, torn tails and poison records
degrade to counted skips/quarantines — never to a crash-loop or a
double-count. Fault points are deterministic, zero-cost disarmed, and
refused by config.check unless explicitly allowed.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from tempo_tpu.backend.mem import MemBackend
from tempo_tpu.fleet import checkpoint as ck
from tempo_tpu.generator.generator import Generator
from tempo_tpu.generator.instance import GeneratorConfig
from tempo_tpu.generator.wal import (
    STATS,
    GeneratorWal,
    IngestWalConfig,
    decode_record,
)
from tempo_tpu.model.otlp import encode_spans_otlp
from tempo_tpu.overrides import Overrides
from tempo_tpu.overrides.limits import Limits
from tempo_tpu.utils import faults

NOW = time.time()


def _limits() -> Limits:
    lim = Limits()
    lim.generator.processors = ("span-metrics",)
    lim.generator.max_active_series = 2048
    lim.generator.ingestion_time_range_slack_s = 0.0
    lim.generator.collection_interval_s = 3600.0
    return lim


def _payload(seed: int, n: int = 24) -> bytes:
    rng = np.random.default_rng(seed)
    return encode_spans_otlp([
        dict(trace_id=rng.bytes(16), span_id=rng.bytes(8),
             name=f"op-{i % 4}", service=f"svc-{i % 3}", kind=2,
             status_code=int(i % 5 == 0) * 2,
             start_unix_nano=int(NOW * 1e9),
             end_unix_nano=int(NOW * 1e9) + int(rng.integers(1, 5e8)),
             attrs={"k": f"v{i % 2}"})
        for i in range(n)])


def _mkgen(tmp_path, iid: str = "m0", sub: str = "wal") -> Generator:
    wal = GeneratorWal(IngestWalConfig(
        enabled=True, dir=str(tmp_path / sub)))
    return Generator(GeneratorConfig(), instance_id=iid,
                     overrides=Overrides(defaults=_limits()), wal=wal)


def _collect(gen: Generator, tenant: str) -> dict:
    inst = gen.instance(tenant)
    inst.drain()
    return {(s.name, s.labels): s.value
            for s in inst.registry.collect(ts_ms=1)
            if not s.is_stale_marker}


# ---------------------------------------------------------------------------
# WAL append + replay
# ---------------------------------------------------------------------------


def test_replay_after_simulated_kill_is_bit_identical(tmp_path):
    """Abandon a generator (no shutdown, no checkpoint — the kill -9
    shape), rebuild over the same WAL dir: replay restores collect()
    AND quantile() bit-identically, exactly once."""
    g1 = _mkgen(tmp_path)
    for seed in (1, 2, 3):
        g1.push_otlp("t1", _payload(seed))
    want = _collect(g1, "t1")
    want_q = g1.instance("t1").processors["span-metrics"].quantile(0.99)

    g2 = _mkgen(tmp_path)
    got_stats = g2.replay_wal_all()
    assert got_stats == {"tenants": 1, "batches": 3, "dead_letters": 0}
    assert _collect(g2, "t1") == want
    assert g2.instance("t1").processors["span-metrics"].quantile(0.99) \
        == want_q


def test_staged_view_record_round_trips_sample_weights(tmp_path):
    """A sampled push's Horvitz-Thompson weights ride the WAL record:
    the replayed weighted rates match the live weighted rates."""
    from tempo_tpu.model.otlp_batch import stage_otlp

    g1 = _mkgen(tmp_path)
    inst = g1.instance("t1")
    st = stage_otlp(_payload(7), inst.registry.interner)
    if st is None:
        pytest.skip("native staging unavailable")
    w = np.linspace(1.0, 4.0, st.n).astype(np.float32)
    st.sample_weight = w
    assert g1.push_staged_view("t1", st.view()) == st.n
    want = _collect(g1, "t1")
    calls = [v for (name, _l), v in want.items()
             if name == "traces_spanmetrics_calls_total"]
    assert calls and not np.allclose(sum(calls), st.n)  # weights applied

    g2 = _mkgen(tmp_path)
    assert g2.replay_wal_all()["batches"] == 1
    assert _collect(g2, "t1") == want


def test_checkpoint_watermark_truncates_and_bounds_replay(tmp_path):
    """Records ≤ the snapshot watermark live in the blob (segments
    truncate once it lands); restore + replay applies each acked batch
    exactly once — the uninterrupted oracle matches bit-identically."""
    be = MemBackend()
    g1 = _mkgen(tmp_path)
    for seed in (1, 2):
        g1.push_otlp("t1", _payload(seed))
    inst = g1.instance("t1")
    blob = ck.snapshot_instance(inst)
    assert inst.checkpointed_wal_seq == 1
    ck.write_checkpoint(be, "fleet-checkpoints", "t1", blob,
                        ck.checkpoint_name(NOW, "m0"))
    t0 = STATS["truncated_segments"]
    g1.truncate_wal("t1", inst.checkpointed_wal_seq)
    assert STATS["truncated_segments"] > t0
    assert g1.wal._tw("t1").segments() == []
    for seed in (3, 4):
        g1.push_otlp("t1", _payload(seed))
    want = _collect(g1, "t1")

    g2 = _mkgen(tmp_path)
    inst2 = g2.instance("t1")
    ck.restore_instance(inst2, blob)
    assert inst2.wal_watermarks == {"m0": [0, 1]}
    assert g2.replay_wal_all()["batches"] == 2   # only seqs 2..3
    assert _collect(g2, "t1") == want

    # oracle: the same four pushes, never interrupted
    oracle = Generator(GeneratorConfig(), instance_id="oracle",
                       overrides=Overrides(defaults=_limits()))
    for seed in (1, 2, 3, 4):
        oracle.push_otlp("t1", _payload(seed))
    assert _collect(g2, "t1") == _collect(oracle, "t1")


def test_torn_tail_is_skipped_not_fatal(tmp_path):
    """A crash mid-append leaves a torn frame at the segment tail:
    replay recovers every COMPLETE record and counts the tear."""
    g1 = _mkgen(tmp_path)
    g1.push_otlp("t1", _payload(1))
    want = _collect(g1, "t1")
    tw = g1.wal._tw("t1")
    seg = os.path.join(tw.dir, tw.segments()[-1])
    with open(seg, "ab") as f:
        f.write(b"TWR1" + b"\x22" * 9)   # half a header, then nothing
    torn0 = STATS["torn_frames"]
    g2 = _mkgen(tmp_path)
    assert g2.replay_wal_all()["batches"] == 1
    assert STATS["torn_frames"] > torn0
    assert _collect(g2, "t1") == want


def test_poison_record_dead_letters_instead_of_crash_looping(tmp_path):
    """A record that deterministically raises quarantines to
    deadletter/ (original payload intact, decodable) and replay keeps
    going — later records still restore."""
    from tempo_tpu.generator.wal import _encode_record

    g1 = _mkgen(tmp_path)
    g1.push_otlp("t1", _payload(1))
    # hand-append a poison record between two good ones
    tw = g1.wal._tw("t1")
    tw.append(_encode_record({"v": 1, "kind": "bogus", "ts": NOW}, {}))
    g1.push_otlp("t1", _payload(2))
    want = _collect(g1, "t1")

    g2 = _mkgen(tmp_path)
    got = g2.replay_wal_all()
    assert got == {"tenants": 1, "batches": 2, "dead_letters": 1}
    assert _collect(g2, "t1") == want
    dl_dir = os.path.join(str(tmp_path / "wal"), "t1", "deadletter")
    files = sorted(os.listdir(dl_dir))
    assert files == ["000000000001.rec", "000000000001.strings.json"]
    with open(os.path.join(dl_dir, files[0]), "rb") as f:
        meta, _arrays = decode_record(f.read())
    assert meta["kind"] == "bogus"


def test_fsync_policies_and_rotation(tmp_path):
    cfg = IngestWalConfig(enabled=True, dir=str(tmp_path / "w"),
                          fsync="off", segment_max_bytes=1 << 20)
    wal = GeneratorWal(cfg)
    f0 = STATS["fsyncs"]
    g = Generator(GeneratorConfig(), overrides=Overrides(
        defaults=_limits()), wal=wal)
    g.push_otlp("t1", _payload(1))
    assert STATS["fsyncs"] == f0          # off: no per-append fsync
    wal.cfg.fsync = "batch"
    g.push_otlp("t1", _payload(2))
    assert STATS["fsyncs"] == f0 + 1
    # rotation by size: shrink the bound so the next append rotates
    wal.cfg.segment_max_bytes = 1 << 20
    tw = wal._tw("t1")
    tw.cfg = wal.cfg
    before = len(tw.segments())
    tw._seg_bytes = wal.cfg.segment_max_bytes  # force the size bound
    g.push_otlp("t1", _payload(3))
    assert len(tw.segments()) == before + 1
    # watermark names the newest segment + last seq
    assert wal.watermark("t1") == (2, 2)


def test_push_id_dedupe_survives_replay(tmp_path):
    """A retried push (same X-Push-Id) after a lost response applies
    once — live AND after a crash-restart (the WAL record re-seeds the
    dedupe window)."""
    g1 = _mkgen(tmp_path)
    n = g1.push_otlp("t1", _payload(1), push_id="abc")
    assert g1.push_otlp("t1", _payload(1), push_id="abc") == n
    want = _collect(g1, "t1")
    one = Generator(GeneratorConfig(), instance_id="one",
                    overrides=Overrides(defaults=_limits()))
    one.push_otlp("t1", _payload(1))
    assert want == _collect(one, "t1")    # second send never scattered

    g2 = _mkgen(tmp_path)
    g2.replay_wal_all()
    assert _collect(g2, "t1") == want
    # the retry landing AFTER recovery still dedupes
    assert g2.push_otlp("t1", _payload(1), push_id="abc") == n
    assert _collect(g2, "t1") == want


def test_push_otlp_recs_declines_when_wal_enabled(tmp_path):
    g = _mkgen(tmp_path)
    assert g.push_otlp_recs("t1", b"", None) is None


def test_pending_retry_redoes_only_the_append(tmp_path):
    """A push whose scatter landed but whose WAL append failed leaves a
    PENDING dedupe entry: the client retry (same push id) must not
    re-scatter, must re-append, and the batch ends both counted once
    and durable."""
    g = _mkgen(tmp_path)
    spec = faults.FaultSpec(point="wal.fsync", probability=1.0, count=1)
    with faults.use([spec]):
        with pytest.raises(OSError):
            g.push_otlp("t1", _payload(1), push_id="r1")
    assert g.instance("t1").seen_push("r1") == ("pending", 24)
    # retry: append succeeds this time, entry finalizes
    assert g.push_otlp("t1", _payload(1), push_id="r1") == 24
    assert g.instance("t1").seen_push("r1") == 24
    want = _collect(g, "t1")
    one = Generator(GeneratorConfig(), instance_id="one",
                    overrides=Overrides(defaults=_limits()))
    one.push_otlp("t1", _payload(1))
    assert want == _collect(one, "t1")   # scattered exactly once
    # and the record IS durable now: two frames on disk (the failed
    # attempt's unsynced frame + the retry's), replay applies one
    # (push-id dedupe re-seeded from the first record replayed)
    g2 = _mkgen(tmp_path)
    g2.replay_wal_all()
    assert _collect(g2, "t1") == want


def test_checkpoint_floor_bounds_replay_without_blob(tmp_path):
    """Finding-5 shape: a watermark landing mid-segment truncates no
    whole segment, and the member restarts NOT restoring the covering
    blob (ownership moved, blob consumed by a peer). The persisted
    CHECKPOINTED floor must still bound replay — below-floor records
    are in the blob's lineage and re-applying them double-counts."""
    g1 = _mkgen(tmp_path)
    for seed in (1, 2):
        g1.push_otlp("t1", _payload(seed))
    inst = g1.instance("t1")
    ck.snapshot_instance(inst)           # blob discarded on purpose
    g1.truncate_wal("t1", inst.checkpointed_wal_seq)
    # mid-segment watermark: the open segment holds seqs 0..2 after one
    # more push, nothing truncates
    g1.push_otlp("t1", _payload(3))
    assert g1.wal._tw("t1").segments() != []
    assert g1.wal._tw("t1").checkpoint_floor() == 1

    g2 = _mkgen(tmp_path)                # restart, NO blob restored
    got = g2.replay_wal_all()
    assert got["batches"] == 1           # only seq 2, past the floor
    oracle = Generator(GeneratorConfig(), instance_id="o",
                       overrides=Overrides(defaults=_limits()))
    oracle.push_otlp("t1", _payload(3))
    assert _collect(g2, "t1") == _collect(oracle, "t1")


def test_interner_replacement_rotates_segment(tmp_path):
    """A replaced tenant instance brings a FRESH interner (new id
    space): appends must rotate to a fresh segment whose string table
    starts from zero, or replayed ids would resolve through the OLD
    instance's strings — silent series misattribution."""
    rng = np.random.default_rng(31)

    def _pl(prefix: str) -> bytes:
        return encode_spans_otlp([
            dict(trace_id=rng.bytes(16), span_id=rng.bytes(8),
                 name=f"{prefix}-op-{i % 3}", service=f"{prefix}-svc",
                 kind=2, status_code=0, start_unix_nano=int(NOW * 1e9),
                 end_unix_nano=int(NOW * 1e9) + int(2e8))
            for i in range(12)])

    g = _mkgen(tmp_path)
    g.push_otlp("t1", _pl("a"))
    g.remove_instance("t1")              # instance + interner replaced
    g.push_otlp("t1", _pl("b"))          # fresh interner, same WAL
    assert len(g.wal._tw("t1").segments()) == 2   # forced rotation

    g2 = _mkgen(tmp_path)
    assert g2.replay_wal_all() == {"tenants": 1, "batches": 2,
                                   "dead_letters": 0}
    got = _collect(g2, "t1")
    names = {dict(labels).get("span_name") for (_n, labels) in got}
    assert any(n and n.startswith("a-op") for n in names)
    assert any(n and n.startswith("b-op") for n in names)
    # oracle: both payloads into ONE instance — replay merges the two
    # instance generations into the live registry the same way
    oracle = Generator(GeneratorConfig(), instance_id="oi",
                       overrides=Overrides(defaults=_limits()))
    rng2 = np.random.default_rng(31)

    def _pl2(prefix: str) -> bytes:
        return encode_spans_otlp([
            dict(trace_id=rng2.bytes(16), span_id=rng2.bytes(8),
                 name=f"{prefix}-op-{i % 3}", service=f"{prefix}-svc",
                 kind=2, status_code=0, start_unix_nano=int(NOW * 1e9),
                 end_unix_nano=int(NOW * 1e9) + int(2e8))
            for i in range(12)])
    oracle.push_otlp("t1", _pl2("a"))
    oracle.push_otlp("t1", _pl2("b"))
    assert got == _collect(oracle, "t1")


def test_seq_counter_survives_full_truncation_restart(tmp_path):
    """After a checkpoint truncates EVERY segment, a restarted process
    must seed its seq counter past the persisted floor — reusing seqs
    at or below it would make the next replay silently skip freshly
    acked records."""
    g1 = _mkgen(tmp_path)
    for seed in (1, 2):
        g1.push_otlp("t1", _payload(seed))
    inst = g1.instance("t1")
    ck.snapshot_instance(inst)
    g1.truncate_wal("t1", inst.checkpointed_wal_seq)
    assert g1.wal._tw("t1").segments() == []

    g2 = _mkgen(tmp_path)                # restart over the empty WAL
    g2.push_otlp("t1", _payload(3))
    assert g2.wal.watermark("t1") == (2, 2)    # floor 1 → next seq 2
    want = _collect(g2, "t1")

    g3 = _mkgen(tmp_path)                # crash again: replay seq 2
    assert g3.replay_wal_all()["batches"] == 1
    oracle = Generator(GeneratorConfig(), instance_id="o2",
                       overrides=Overrides(defaults=_limits()))
    oracle.push_otlp("t1", _payload(3))
    assert _collect(g3, "t1") == _collect(oracle, "t1")
    assert want == _collect(oracle, "t1")


def test_handoff_window_skips_wal_and_never_claims_foreign_records(
        tmp_path):
    """During a handoff cut (pop → blob → truncate), a straggler push
    builds a replacement instance whose records must NOT enter the WAL:
    the popped instance's snapshot claims the tenant watermark, and a
    foreign record under that claim would truncate without being in any
    blob. After end_handoff the WAL resumes."""
    g = _mkgen(tmp_path)
    g.push_otlp("t1", _payload(1))
    old = g.pop_instance("t1")           # opens the skip window
    n0 = STATS["appended_batches"]
    g.push_otlp("t1", _payload(2))       # straggler → fresh instance
    assert STATS["appended_batches"] == n0      # skipped
    blob_seq_claim = None
    assert old.wait_pushes_idle(2.0)
    ck.snapshot_instance(old)
    blob_seq_claim = old.checkpointed_wal_seq
    assert blob_seq_claim == 0           # only the old instance's record
    g.end_handoff("t1")
    g.push_otlp("t1", _payload(3))       # WAL resumes
    assert STATS["appended_batches"] == n0 + 1


# ---------------------------------------------------------------------------
# fault-injection registry
# ---------------------------------------------------------------------------


def test_faults_deterministic_and_bounded():
    spec = faults.FaultSpec(point="backend.write", probability=0.5,
                            count=3)
    fired = []
    for trial in range(2):
        with faults.use([spec], seed=42):
            hits = []
            for i in range(40):
                try:
                    faults.fire("backend.write")
                    hits.append(0)
                except OSError:
                    hits.append(1)
            fired.append(hits)
            assert faults.stats()["backend.write"] == 3  # count cap
    assert fired[0] == fired[1]           # same seed, same schedule
    assert not faults.ARMED               # context exit disarms


def test_faults_latency_only_and_after():
    spec = faults.FaultSpec(point="rpc.push", probability=1.0, after=2,
                            latency_s=0.0, error="none")
    with faults.use([spec]):
        faults.fire("rpc.push")           # skipped: after=2
        faults.fire("rpc.push")
        faults.fire("rpc.push")           # fires, but error="none"
        assert faults.stats()["rpc.push"] == 1


def test_faults_config_gate():
    cfg = faults.FaultsConfig(points={"rpc.push": {"probability": 0.1}})
    assert any("faults.allow" in w for w in cfg.check())
    cfg.allow = True
    assert cfg.check() == []
    # env spec honored only under the same allow gate
    os.environ["TEMPO_FAULTS"] = \
        '{"wal.fsync": {"probability": 1.0, "count": 1}}'
    try:
        faults.configure(faults.FaultsConfig(allow=False))
        assert not faults.ARMED
        faults.configure(faults.FaultsConfig(allow=True))
        assert faults.ARMED
        with pytest.raises(OSError):
            faults.fire("wal.fsync")
    finally:
        del os.environ["TEMPO_FAULTS"]
        faults.reset()


def test_wal_fsync_fault_fails_the_push_but_replay_covers_it(tmp_path):
    """An injected fsync failure errors the push (unacked) — but the
    scatter already landed and the frame is on disk, so the snapshot
    watermark still covers it: no replay double-count."""
    g = _mkgen(tmp_path)
    g.push_otlp("t1", _payload(1))
    spec = faults.FaultSpec(point="wal.fsync", probability=1.0, count=1)
    with faults.use([spec]):
        with pytest.raises(OSError):
            g.push_otlp("t1", _payload(2))
    want = _collect(g, "t1")              # both batches scattered
    blob = ck.snapshot_instance(g.instance("t1"))
    assert g.instance("t1").checkpointed_wal_seq == 1  # frame counted
    g2 = _mkgen(tmp_path)
    ck.restore_instance(g2.instance("t1"), blob)
    assert g2.replay_wal_all()["batches"] == 0         # all ≤ watermark
    assert _collect(g2, "t1") == want


# ---------------------------------------------------------------------------
# hardened retry paths the fault points flushed out
# ---------------------------------------------------------------------------


def test_resilient_backend_retries_transient_and_passes_semantic():
    from tempo_tpu.backend.cloud import ResilientBackend
    from tempo_tpu.backend.raw import DoesNotExist, KeyPath

    be = ResilientBackend(MemBackend(), retries=3, backoff_s=0.001)
    kp = KeyPath(("x",))
    spec = faults.FaultSpec(point="backend.write", probability=1.0,
                            count=2)
    with faults.use([spec]):
        be.write("a", kp, b"payload")     # 2 injected failures, retried
    assert be.read("a", kp) == b"payload"
    with pytest.raises(DoesNotExist):     # semantic error: no retry loop
        be.read("missing", kp)
    spec = faults.FaultSpec(point="backend.read", probability=1.0)
    with faults.use([spec]):
        with pytest.raises(OSError):      # retries exhausted → surfaces
            be.read("a", kp)


def test_controller_checkpoint_write_retries_with_cause_metric(tmp_path):
    from tempo_tpu.fleet import RETRY_CAUSES, FleetConfig
    from tempo_tpu.fleet.controller import FleetController
    from tempo_tpu.ring import KVStore, Lifecycler, Ring

    kv = KVStore()
    be = MemBackend()
    gen = _mkgen(tmp_path)
    Lifecycler(kv, "m0", key="generator", now=lambda: NOW)
    ring = Ring(kv=kv, key="generator", replication_factor=1,
                now=lambda: NOW)
    fc = FleetController(gen, ring, "m0", be, be,
                         cfg=FleetConfig(checkpoint_write_retries=3,
                                         checkpoint_retry_backoff_s=0.001),
                         now=lambda: NOW)
    gen.push_otlp("t1", _payload(1))
    spec = faults.FaultSpec(point="fleet.checkpoint.write",
                            probability=1.0, count=2)
    before = dict(RETRY_CAUSES)
    with faults.use([spec]):
        fc._checkpoint("t1", remove=False)
    assert ck.list_checkpoints(be, "fleet-checkpoints") != {}
    grew = {k: v - before.get(k, 0) for k, v in RETRY_CAUSES.items()
            if v - before.get(k, 0)}
    assert sum(grew.values()) == 2        # both injected failures counted
    # the successful write truncated the WAL below the watermark
    assert gen.wal._tw("t1").segments() == []


# ---------------------------------------------------------------------------
# block-WAL satellite: directory-entry durability + torn-dir rescan
# ---------------------------------------------------------------------------


def test_block_wal_dir_fsync_and_torn_directory_rescan(tmp_path):
    from tempo_tpu.block import wal as bwal

    root = str(tmp_path / "bwal")
    os.makedirs(root)
    blk = bwal.WALBlock(root, "t1")
    blk.append([
        dict(trace_id=b"\x01" * 16, span_id=b"\x02" * 8, name="op",
             service="svc", kind=2, status_code=0,
             start_unix_nano=1, end_unix_nano=2)])
    # torn directory shapes a rescan must tolerate: a block dir whose
    # crash left only a tmp file, an empty block dir (dirent fsynced,
    # nothing appended yet), and stray non-block entries
    torn = os.path.join(root, "11111111+t2+vtpu1")
    os.makedirs(torn)
    with open(os.path.join(torn, ".0000001.tmp"), "wb") as f:
        f.write(b"partial parquet")
    os.makedirs(os.path.join(root, "22222222+t3+vtpu1"))
    with open(os.path.join(root, "junk.txt"), "w") as f:
        f.write("not a block")
    blocks = bwal.rescan_blocks(root)
    by_tenant = {b.tenant: b for b in blocks}
    assert set(by_tenant) == {"t1", "t2", "t3"}
    assert by_tenant["t1"].complete()          # full segment readable
    assert by_tenant["t2"].complete() == []    # tmp file: not a segment
    assert by_tenant["t3"].complete() == []    # empty dir reads empty
    # appending after a rescan continues the segment numbering
    assert by_tenant["t2"]._next_seg == 0
