"""Property tests for the device plane's exactness arithmetic.

The fused plane's correctness rests on a few small encodings: the 33/31
and biased 32/32 int64 limb splits (lexicographic order == int64 order),
the order-preserving float64→int64 map, the 16-bit limb boundary compare
behind exact step bucketing, and `_int_literal`'s compare normalization.
These are exhaustive-ish randomized checks of those invariants — cheap,
seed-logged, and independent of jax (pure numpy)."""

from __future__ import annotations

import os
import random

import numpy as np

from tempo_tpu.block.device_scan import (
    _int_literal,
    _sortable_f64,
    _split_i64,
    _split_i64_biased,
    _split_lit,
    _split_lit_biased,
)
from tempo_tpu.traceql import ast as A

SEED = int(os.environ.get("TEMPO_FUZZ_SEED",
                          random.SystemRandom().randrange(1 << 30)))


def _rand_i64(rng: random.Random, n: int, lim: int) -> np.ndarray:
    vals = [rng.randrange(-lim, lim) for _ in range(n)]
    vals += [0, 1, -1, lim - 1, -lim, 2**31, -2**31, 2**31 - 1, 2**24,
             2**24 + 1]
    return np.asarray(vals, np.int64)


def test_split_i64_order_and_roundtrip():
    rng = random.Random(SEED)
    # the 33/31 split is used for values |v| < 2^62 (timestamps, int attrs)
    v = _rand_i64(rng, 500, 1 << 61)
    hi, lo = _split_i64(v)
    assert (lo >= 0).all()                      # low half non-negative
    back = hi.astype(np.int64) * (1 << 31) + lo
    np.testing.assert_array_equal(back, v, err_msg=f"seed={SEED}")
    # lexicographic (hi, lo) == int64 order
    order = np.lexsort((lo, hi))
    np.testing.assert_array_equal(v[order], np.sort(v),
                                  err_msg=f"seed={SEED}")
    # per-pair literal split agrees with the array split
    for x in v[:50].tolist():
        lh, ll = _split_lit(int(x))
        i = int(np.flatnonzero(v == x)[0])
        assert (lh, ll) == (int(hi[i]), int(lo[i])), f"seed={SEED} x={x}"


def test_split_i64_biased_full_range_order():
    rng = random.Random(SEED + 1)
    # the biased 32/32 split must order the FULL int64 range (sortable
    # float encodings reach |v| ~ 2^63)
    v = _rand_i64(rng, 500, (1 << 63) - 1)
    hi, lo = _split_i64_biased(v)
    order = np.lexsort((lo, hi))
    np.testing.assert_array_equal(v[order], np.sort(v),
                                  err_msg=f"seed={SEED}")
    for x in v[:50].tolist():
        lh, ll = _split_lit_biased(int(x))
        i = int(np.flatnonzero(v == x)[0])
        assert (lh, ll) == (int(hi[i]), int(lo[i])), f"seed={SEED} x={x}"
        assert -(1 << 31) <= lh < (1 << 31)     # both halves fit int32
        assert -(1 << 31) <= ll < (1 << 31)


def test_sortable_f64_is_order_preserving():
    rng = np.random.default_rng(SEED + 2)
    vals = np.concatenate([
        rng.uniform(-1e300, 1e300, 300),
        rng.uniform(-1.0, 1.0, 300),
        np.array([0.0, -0.0, np.inf, -np.inf, 1e-308, -1e-308,
                  16777217.5, -16777217.5, 2.0**52, -(2.0**52)]),
    ])
    m = _sortable_f64(vals)
    # total order matches float order; equal floats (0.0 == -0.0) equal
    for _ in range(2000):
        i, j = rng.integers(0, len(vals), 2)
        a, b = float(vals[i]), float(vals[j])
        ma, mb = int(m[i]), int(m[j])
        if a < b:
            assert ma < mb, f"seed={SEED} {a} {b}"
        elif a > b:
            assert ma > mb, f"seed={SEED} {a} {b}"
        else:
            assert ma == mb, f"seed={SEED} {a} {b}"


def test_int_literal_normalization_matches_float_compare():
    """`_int_literal` rewrites (op, float literal) into an exact integer
    compare; for every op × literal × int value the rewritten compare
    must agree with the host engine's float64 compare."""
    rng = random.Random(SEED + 3)
    ops = {A.Op.EQ: lambda a, b: a == b, A.Op.NEQ: lambda a, b: a != b,
           A.Op.GT: lambda a, b: a > b, A.Op.GTE: lambda a, b: a >= b,
           A.Op.LT: lambda a, b: a < b, A.Op.LTE: lambda a, b: a <= b}
    lits = [1.5, -2.5, 0.0, 3.0, -1.0, 0.5, 7, -7, 2**24 + 0.5, 1e-9]
    vals = [rng.randrange(-1000, 1000) for _ in range(50)] + [0, 1, -1]
    for op, py in ops.items():
        for lit in lits:
            norm = _int_literal(op, lit)
            for v in vals:
                want = py(float(v), float(lit))
                if norm[0] == "const":
                    got = norm[1]
                else:
                    _, op2, ilit = norm
                    got = ops[op2](v, ilit)
                assert got == want, \
                    f"seed={SEED} {op} {lit} {v}: {got} != {want}"


def test_limb_boundary_compare_matches_int_math():
    """The exact-bucketing kernel compares t_ns >= start_ns + q*step_ns
    via 16-bit limbs; mirror the limb algorithm in numpy over random
    operands within the kernel's guard bounds and check against exact
    python ints."""
    rng = random.Random(SEED + 4)
    for _ in range(500):
        start = rng.randrange(0, 1 << 62)
        step = rng.randrange(1, 1 << 40)
        q = rng.randrange(0, (1 << 14) + 1)
        t = start + q * step + rng.randrange(-3, 4)
        if t < 0:
            continue
        # limb compute (the kernel's ge_boundary, host-side mirror)
        sl = [(step >> s) & 0xFFFF for s in (0, 16, 32, 48)]
        ul = [(start >> s) & 0xFFFF for s in (0, 16, 32, 48)]
        carry = 0
        r = []
        for i in range(4):
            v = ul[i] + q * sl[i] + carry
            r.append(v & 0xFFFF)
            carry = v >> 16
        w = [(t >> s) & 0xFFFF for s in (0, 16, 32, 48)]
        ge = w[0] >= r[0]
        for wi, ri in zip(w[1:], r[1:]):
            ge = ge if wi == ri else wi > ri
        assert ge == (t >= start + q * step), \
            f"seed={SEED} start={start} step={step} q={q} t={t}"
