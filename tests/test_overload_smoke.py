"""Overload escalation smoke: full stream → device-scored sampling →
hard 429, through the REAL staged distributor path (tier-1-safe: forced
pressure, no worker races, small payloads)."""

from __future__ import annotations

import time

import numpy as np
import pytest

from tempo_tpu import native, sched
from tempo_tpu.distributor import Distributor
from tempo_tpu.distributor.distributor import (REASON_BACKPRESSURE,
                                               REASON_SAMPLED, RateLimited)
from tempo_tpu.generator.generator import Generator
from tempo_tpu.generator.instance import GeneratorConfig
from tempo_tpu.model.otlp import encode_spans_otlp
from tempo_tpu.overrides import Overrides
from tempo_tpu.ring import ACTIVE, InstanceDesc, Ring
from tempo_tpu.ring.ring import _instance_tokens

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native staging kernel required")

def make_payload(n: int, err_every: int = 0) -> bytes:
    # timestamps stamped at CALL time: the generator's ingestion slack
    # (tenant-limits default 30s) silently filters a payload built at
    # module import once the suite has been running that long
    t0 = int(time.time() * 1e9)
    src = []
    for i in range(n):
        s = {"trace_id": (b"%05d" % i).ljust(16, b"\0"),
             "span_id": bytes([i % 251 + 1]) * 8,
             "name": f"op-{i % 4}", "service": "svc",
             "start_unix_nano": t0 + i * 1000,
             "end_unix_nano": t0 + i * 1000 + 1_000_000,
             "res_attrs": {"service.name": "svc"}}
        if err_every and i % err_every == 0:
            s["status_code"] = 2
        src.append(s)
    return encode_spans_otlp(src)


class _CaptureStagedIng:
    """Staged-capable ingester sink that records which rows it saw."""

    staged_needs_attrs = False

    def __init__(self):
        self.rows: list[np.ndarray] = []
        self.status: list[np.ndarray] = []

    def push(self, tenant, traces):
        return [None] * len(traces)

    def push_otlp(self, tenant, payload):
        return {}

    def push_staged(self, tenant, view):
        self.rows.append(view.row_indices().copy())
        self.status.append(view.stage_rows()["status_code"].copy())
        return {}


def _ring_of(ids, now):
    r = Ring(replication_factor=1, now=now)
    for iid in ids:
        r.register(InstanceDesc(id=iid, state=ACTIVE,
                                tokens=_instance_tokens(iid, 64),
                                heartbeat_ts=now()))
    return r


def _rig(patch: dict | None = None):
    now = time.time
    cfg = GeneratorConfig(processors=("span-metrics",))
    cfg.registry.disable_collection = True
    ov = Overrides()
    gen = Generator(cfg, overrides=ov)
    ing = _CaptureStagedIng()
    p = {"generator": {"processors": ["span-metrics"]},
         "ingestion": {"rate_limit_bytes": 1 << 40,
                       "burst_size_bytes": 1 << 40}}
    p.update(patch or {})
    ov.set_tenant_patch("t1", p)
    dist = Distributor(_ring_of(["i0"], now), {"i0": ing}, overrides=ov,
                       generator_ring=_ring_of(["g0"], now),
                       generator_clients={"g0": gen}, now=now)
    return dist, ing, gen


def _gen_rows(gen, tenant="t1"):
    proc = gen.instance(tenant).processors["span-metrics"]
    return proc


def test_escalation_full_stream_then_sampling_then_429(
        forced_sched_saturation):
    sc = forced_sched_saturation(0.0)
    dist, ing, gen = _rig()
    payload = make_payload(256, err_every=16)

    # stage 1 — no pressure: everything admitted, sampling off
    assert dist.push_otlp("t1", payload) == {}
    assert dist.discarded.get(REASON_SAMPLED, 0) == 0
    assert len(ing.rows[-1]) == 256

    # stage 2 — pressure in the sampling band: push SUCCEEDS (no 429),
    # spans are hash-sampled, errors retained at 100%
    sc.forced_pressure = 0.95
    assert dist.push_otlp("t1", payload) == {}     # sampled ≠ client error
    n_dropped = dist.discarded.get(REASON_SAMPLED, 0)
    assert 0 < n_dropped < 256
    assert len(ing.rows[-1]) == 256 - n_dropped
    n_err_in = sum(1 for i in range(256) if i % 16 == 0)
    assert int((ing.status[-1] == 2).sum()) == n_err_in

    # stage 3 — saturation: the hard 429 fires, with the backpressure
    # reason and a Retry-After the client can obey
    sc.forced_pressure = 1.0
    with pytest.raises(RateLimited) as ei:
        dist.push_otlp("t1", payload)
    assert ei.value.reason == REASON_BACKPRESSURE
    assert ei.value.retry_after_s > 0

    # stage 4 — recovery: back to the bit-identical unsampled path
    sc.forced_pressure = 0.0
    before = dist.discarded.get(REASON_SAMPLED, 0)
    assert dist.push_otlp("t1", payload) == {}
    assert dist.discarded.get(REASON_SAMPLED, 0) == before
    assert len(ing.rows[-1]) == 256


def test_ingester_and_generator_tee_agree_on_every_span(
        forced_sched_saturation):
    """One decision, shared by both tee targets through the row views:
    the generator instance consumes exactly the rows the ingester saw."""
    forced_sched_saturation(0.9)
    dist, ing, gen = _rig()
    payload = make_payload(512)
    assert dist.push_otlp("t1", payload) == {}
    kept = len(ing.rows[-1])
    assert 0 < kept < 512
    inst = gen.instance("t1")
    assert inst.spans_received == kept


def test_sampled_push_upscales_spanmetrics_rates(forced_sched_saturation):
    """Horvitz-Thompson weights ride the staged view: calls_total on the
    sampled stream estimates the true span count."""
    import jax

    sc = forced_sched_saturation(0.0)
    dist, ing, gen = _rig({"sampling": {"floor": 0.25,
                                        "tail_quantile": 0.0}})
    payload = make_payload(4096)
    sc.forced_pressure = 0.95          # deep in the band → floor applies
    assert dist.push_otlp("t1", payload) == {}
    n_dropped = dist.discarded.get(REASON_SAMPLED, 0)
    assert n_dropped > 0
    proc = _gen_rows(gen)
    sched.flush()
    jax.block_until_ready(proc.calls.state.values)
    calls = np.asarray(proc.calls.state.values)
    total = sum(float(calls[int(s)])
                for s in proc.calls.table.active_slots())
    assert abs(total - 4096) / 4096 < 0.05


def test_sampling_off_is_bit_identical(forced_sched_saturation):
    """Below the pressure threshold the sampling stage must not perturb
    ANY output: registry state matches a distributor with the tenant
    opted out entirely."""
    import jax

    forced_sched_saturation(0.0)
    payload = make_payload(128)

    def run(opt_out: bool):
        dist, ing, gen = _rig({"sampling": {"enabled": False}}
                              if opt_out else None)
        assert dist.push_otlp("t1", payload) == {}
        proc = _gen_rows(gen)
        sched.flush()
        jax.block_until_ready(proc.calls.state.values)
        calls = np.asarray(proc.calls.state.values)
        state = {proc.calls.labels_of(int(s)): float(calls[int(s)])
                 for s in proc.calls.table.active_slots()}
        return state, ing.rows[-1]

    s_on, rows_on = run(opt_out=False)
    s_off, rows_off = run(opt_out=True)
    assert s_on == s_off
    assert np.array_equal(rows_on, rows_off)


def test_tenant_optout_keeps_hard_cliff(forced_sched_saturation):
    """A tenant with sampling disabled keeps the old binary behavior:
    full stream right up to the 429."""
    sc = forced_sched_saturation(0.9)
    dist, ing, gen = _rig({"sampling": {"enabled": False}})
    payload = make_payload(64)
    assert dist.push_otlp("t1", payload) == {}
    assert dist.discarded.get(REASON_SAMPLED, 0) == 0
    assert len(ing.rows[-1]) == 64
    sc.forced_pressure = 1.0
    with pytest.raises(RateLimited):
        dist.push_otlp("t1", payload)


def test_keep_fraction_gauge_renders(forced_sched_saturation):
    forced_sched_saturation(0.9)
    dist, ing, gen = _rig()
    dist.push_otlp("t1", make_payload(64))
    text = dist.obs.render()
    assert "tempo_distributor_sampling_keep_fraction" in text
    assert 'tenant="t1"' in text
    from tempo_tpu.obs.jaxruntime import RUNTIME
    assert "tempo_sched_ingest_keep_fraction" in RUNTIME.render()
