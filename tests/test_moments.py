"""Moments sketch tier (ops/moments.py + the spanmetrics/TraceQL wiring).

Covers: the device sketch (update/merge/zero semantics, merge guards
across ALL sketches), the maxent solver (accuracy on lognormal/bimodal,
monotone-in-q, degenerate inputs, cache + fallback accounting), the
spanmetrics tier knob (dense/paged parity, dd bit-identity, eviction
hygiene, per-tenant overrides, config warnings), the serving-mesh fused
step, and the TraceQL quantile_over_time moments axis (evaluator →
combiner → final, bound-series max merge, the one-fold multi-q
satellite).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from tempo_tpu.ops import moments as M
from tempo_tpu.ops import sketches


# ---------------------------------------------------------------------------
# device sketch
# ---------------------------------------------------------------------------

def test_moments_update_mask_weights_and_drop():
    st = M.moments_init(4, k=8)
    vals = np.array([0.5, 2.0, 1.0, 3.0], np.float32)
    st = M.moments_update(st, np.array([0, 0, -1, 1]), vals,
                          mask=np.array([True, True, True, False]),
                          weights=np.array([1.0, 3.0, 1.0, 1.0]))
    d = np.asarray(st.data)
    assert d[0, 0] == pytest.approx(4.0)     # weighted count 1 + 3
    assert d[1].sum() == 0.0                 # masked row dropped
    assert d[2].sum() == 0.0                 # negative slot dropped
    # bounds: shifted maxes of log(0.5), log(2.0)
    assert d[0, st.k + 1] == pytest.approx(np.log(2.0) - st.lo, rel=1e-5)
    assert d[0, st.k + 2] == pytest.approx(st.hi - np.log(0.5), rel=1e-5)


def test_moments_merge_matches_single_pass():
    rng = np.random.default_rng(0)
    x = rng.lognormal(-2, 0.7, 512).astype(np.float32)
    whole = M.moments_update(M.moments_init(2), np.zeros(512, np.int32), x)
    a = M.moments_update(M.moments_init(2), np.zeros(256, np.int32), x[:256])
    b = M.moments_update(M.moments_init(2), np.zeros(256, np.int32), x[256:])
    merged = M.moments_merge(a, b)
    np.testing.assert_allclose(np.asarray(merged.data)[0],
                               np.asarray(whole.data)[0], rtol=1e-4)


def test_moments_zero_slots_resets_to_empty():
    st = M.moments_update(M.moments_init(4), np.array([1, 2]),
                          np.array([0.1, 0.2], np.float32))
    st = M.moments_zero_slots(st, np.array([1]))
    d = np.asarray(st.data)
    assert d[1].sum() == 0.0 and d[2].sum() > 0.0


def test_merge_guards_raise_value_error_across_all_sketches():
    # moments: k / domain / shape mismatches
    with pytest.raises(ValueError, match="moments_merge"):
        M.moments_merge(M.moments_init(4, k=8), M.moments_init(4, k=12))
    with pytest.raises(ValueError, match="moments_merge"):
        M.moments_merge(M.moments_init(4, min_value=1e-6),
                        M.moments_init(4, min_value=1e-3))
    # log2: offset and shape
    with pytest.raises(ValueError, match="log2_hist_merge"):
        sketches.log2_hist_merge(sketches.log2_hist_init(4, offset=0),
                                 sketches.log2_hist_init(4, offset=32))
    with pytest.raises(ValueError, match="log2_hist_merge"):
        sketches.log2_hist_merge(sketches.log2_hist_init(4),
                                 sketches.log2_hist_init(8))
    # dd: gamma/min_value geometry
    with pytest.raises(ValueError, match="dd_merge"):
        sketches.dd_merge(sketches.dd_init(4, rel_err=0.01),
                          sketches.dd_init(4, rel_err=0.02))
    # hll: precision
    with pytest.raises(ValueError, match="hll_merge"):
        sketches.hll_merge(sketches.hll_init(4, precision=12),
                           sketches.hll_init(4, precision=14))
    # cms: width
    with pytest.raises(ValueError, match="cms_merge"):
        sketches.cms_merge(sketches.cms_init(4, width=1024),
                           sketches.cms_init(4, width=2048))


# ---------------------------------------------------------------------------
# maxent solver
# ---------------------------------------------------------------------------

def _row_for(x: np.ndarray, k: int = 12) -> tuple:
    st = M.moments_update(M.moments_init(1, k=k),
                          np.zeros(len(x), np.int32),
                          np.asarray(x, np.float32))
    return np.asarray(st.data)[0], st


def test_solver_accuracy_lognormal_and_bimodal():
    rng = np.random.default_rng(7)
    workloads = {
        "lognormal": rng.lognormal(np.log(0.1), 0.6, 30_000),
        "bimodal": np.concatenate([
            rng.lognormal(np.log(0.05), 0.6, 15_000),
            rng.lognormal(np.log(0.8), 0.5, 15_000)]),
    }
    for name, x in workloads.items():
        row, st = _row_for(x)
        qs = [0.5, 0.9, 0.99]
        got = M.solve_quantiles(row, st.k, st.lo, st.hi, qs)
        assert got is not None, name
        exact = np.quantile(x, qs)
        rel = np.abs(got - exact) / exact
        # value error where the density is smooth; rank error (the
        # sketch's actual guarantee, Gan et al.) where it is not —
        # in a bimodal trough every sketch's value error is unbounded
        xs = np.sort(x)
        rank = np.abs(np.searchsorted(xs, got) / len(xs) - np.asarray(qs))
        assert np.minimum(rel, rank).max() <= 0.05, (name, rel, rank)


def test_solver_monotone_in_q():
    rng = np.random.default_rng(1)
    row, st = _row_for(rng.lognormal(-3, 1.2, 5000))
    qs = np.linspace(0.01, 0.99, 25)
    got = M.solve_quantiles(row, st.k, st.lo, st.hi, qs)
    assert got is not None
    assert (np.diff(got) >= -1e-12).all()


def test_solver_degenerate_rows():
    # single repeated value: exact answer, no maxent needed
    row, st = _row_for(np.full(100, 0.25))
    got = M.solve_quantiles(row, st.k, st.lo, st.hi, [0.1, 0.5, 0.9])
    np.testing.assert_allclose(got, 0.25, rtol=1e-3)
    # empty row: None (callers render 0 like the bucket sketches)
    assert M.solve_quantiles(np.zeros(st.k + 3), st.k, st.lo, st.hi,
                             [0.5]) is None


def test_solver_cache_and_fallback_accounting():
    M.reset_solver_cache()
    rng = np.random.default_rng(2)
    row, st = _row_for(rng.lognormal(-2, 0.5, 1000))
    assert M.solve_quantiles(row, st.k, st.lo, st.hi, [0.5]) is not None
    s0, h0 = M.solves_total, M.cache_hits_total
    assert M.solve_quantiles(row, st.k, st.lo, st.hi, [0.9]) is not None
    assert M.solves_total == s0 and M.cache_hits_total == h0 + 1
    # an infeasible moment vector (corrupted sums) must fail closed:
    # None + fallback counter, never an exception
    bad = row.copy()
    bad[1:st.k + 1] = np.array([50.0, -50.0] * (st.k // 2)) * row[0]
    f0 = M.fallbacks_total
    assert M.solve_quantiles(bad, st.k, st.lo, st.hi, [0.5]) is None
    assert M.fallbacks_total == f0 + 1


def test_quantiles_for_rows_batch_flags():
    rng = np.random.default_rng(3)
    row, st = _row_for(rng.lognormal(-2, 0.5, 500))
    rows = np.stack([row, np.zeros_like(row)])
    vals, failed = M.quantiles_for_rows(rows, st.k, st.lo, st.hi, [0.5, 0.9])
    assert not failed.any()          # empty row is NOT a failure
    assert vals[1].sum() == 0.0      # …it renders 0 like bucket sketches
    assert vals[0, 0] < vals[0, 1]


# ---------------------------------------------------------------------------
# spanmetrics tier
# ---------------------------------------------------------------------------

def _mk_world(paged: bool, sketch: str, clock=None, k: int = 12):
    from tempo_tpu.generator.processors.spanmetrics import (
        SpanMetricsConfig, SpanMetricsProcessor)
    from tempo_tpu.registry import pages as device_pages
    from tempo_tpu.registry.registry import ManagedRegistry, RegistryOverrides

    clock = clock or [1000.0]
    pool = device_pages.PagePool(device_pages.PagePoolConfig(
        enabled=True, page_rows=16, arena_slots=512)) if paged else None
    with device_pages.use(pool):
        reg = ManagedRegistry("t", RegistryOverrides(
            max_active_series=64, stale_duration_s=50.0),
            now=lambda: clock[0])
        proc = SpanMetricsProcessor(reg, SpanMetricsConfig(
            use_scheduler=False, sketch=sketch, moments_k=k,
            sketch_max_series=32))
    return clock, reg, proc


def _push(reg, proc, durs, op="op", weights=None):
    from tempo_tpu.model.span_batch import SpanBatchBuilder
    b = SpanBatchBuilder(reg.interner)
    for d in durs:
        b.append(trace_id=bytes(16), span_id=bytes(8), name=op,
                 service="svc", kind=2, status_code=0,
                 start_unix_nano=10**18,
                 end_unix_nano=10**18 + int(float(d) * 1e9))
    proc.push_batch(b.build(), sample_weights=weights)


def test_moments_tier_paged_dense_bit_identical():
    rng = np.random.default_rng(5)
    results = {}
    for paged in (False, True):
        _, reg, proc = _mk_world(paged, "moments")
        r2 = np.random.default_rng(5)
        for op in ("a", "b"):
            _push(reg, proc, r2.lognormal(-2, 0.6, 100), op=op)
        results[paged] = (proc.quantile(0.9),
                          sorted((s.name, s.labels, s.value)
                                 for s in reg.collect(1)
                                 if s.value == s.value))
    assert results[False] == results[True]


def test_moments_tier_accuracy_and_state_shrink():
    rng = np.random.default_rng(6)
    durs = rng.lognormal(np.log(0.1), 0.8, 3000)
    _, reg_m, proc_m = _mk_world(False, "moments")
    _, reg_d, proc_d = _mk_world(False, "dd")
    _push(reg_m, proc_m, durs)
    _push(reg_d, proc_d, durs)
    for q in (0.5, 0.9, 0.99):
        est = next(iter(proc_m.quantile(q).values()))
        exact = float(np.quantile(durs, q))
        assert abs(est - exact) / exact < 0.05, q
    # ≥10x state shrink vs the DDSketch plane (ISSUE gate; ~90x here)
    assert proc_d.device_state_bytes() >= 10 * proc_m.device_state_bytes()


def test_both_tier_dd_plane_bit_identical_to_dd_tier():
    rng = np.random.default_rng(7)
    durs = rng.lognormal(-2, 0.7, 500)
    _, reg_d, proc_d = _mk_world(False, "dd")
    _, reg_b, proc_b = _mk_world(False, "both")
    _push(reg_d, proc_d, durs)
    _push(reg_b, proc_b, durs)
    assert (np.asarray(proc_d.dd.counts) ==
            np.asarray(proc_b.dd.counts)).all()
    assert (np.asarray(proc_d.dd.zeros) ==
            np.asarray(proc_b.dd.zeros)).all()


def test_both_tier_falls_back_to_dd_per_series():
    rng = np.random.default_rng(8)
    _, reg, proc = _mk_world(False, "both")
    _push(reg, proc, rng.lognormal(-2, 0.5, 200))
    slots = proc.calls.table.active_slots()
    vals = np.full(slots.size, np.nan)
    got = proc._sketch_fallback(0.9, slots, vals,
                                np.ones(slots.size, bool))
    dd_vals = np.asarray(sketches.dd_quantile(proc.dd, 0.9))[slots]
    np.testing.assert_allclose(got, dd_vals)


def test_moments_only_fallback_uses_classic_histogram():
    rng = np.random.default_rng(9)
    _, reg, proc = _mk_world(False, "moments")
    _push(reg, proc, rng.lognormal(-2, 0.5, 200))
    slots = proc.calls.table.active_slots()
    got = proc._sketch_fallback(0.9, slots, np.full(slots.size, np.nan),
                                np.ones(slots.size, bool))
    assert np.isfinite(got).all() and (got > 0).all()


def test_evicted_slot_reuse_does_not_inherit_moments_history():
    for paged in (False, True):
        clock, reg, proc = _mk_world(paged, "moments")
        _push(reg, proc, [5.0] * 50, op="old")     # slow series
        clock[0] += 1000.0
        assert reg.purge_stale() == 1
        _push(reg, proc, [0.001] * 50, op="new")   # fast series, reused slot
        got = proc.quantile(0.99)
        (labels, est), = got.items()
        assert dict(labels)["span_name"] == "new"
        assert est < 0.01, (paged, est)            # no 5s contamination


def test_weighted_pushes_upscale_moments():
    # HT weights: half the stream at weight 2 ≈ the full stream
    rng = np.random.default_rng(10)
    durs = rng.lognormal(-2, 0.6, 2000)
    _, reg_a, proc_a = _mk_world(False, "moments")
    _, reg_b, proc_b = _mk_world(False, "moments")
    _push(reg_a, proc_a, durs)
    _push(reg_b, proc_b, durs[::2], weights=np.full(1000, 2.0, np.float32))
    qa = next(iter(proc_a.quantile(0.9).values()))
    qb = next(iter(proc_b.quantile(0.9).values()))
    assert abs(qa - qb) / qa < 0.1


def test_per_tenant_sketch_override():
    from tempo_tpu.generator.generator import Generator
    from tempo_tpu.overrides import Overrides

    o = Overrides()
    o.set_tenant_patch("m-tenant", {"generator": {
        "sketch": "moments", "sketch_moments_k": 8}})
    g = Generator(overrides=o)
    proc = g.instance("m-tenant").processors["span-metrics"]
    assert proc.mom is not None and proc.mom.k == 8 and proc.dd is None
    proc2 = g.instance("other").processors["span-metrics"]
    assert proc2.dd is not None and proc2.mom is None


def test_unknown_tier_falls_back_to_dd_with_warning(caplog):
    import logging
    with caplog.at_level(logging.WARNING, logger="tempo_tpu.spanmetrics"):
        _, _reg, proc = _mk_world(False, "tdigest")
    assert proc.dd is not None and proc.mom is None
    assert any("unknown sketch tier" in r.message for r in caplog.records)


def test_config_check_sketch_bounds():
    from tempo_tpu.app.config import load_config

    good = load_config(text="generator:\n  spanmetrics:\n    sketch: moments\n")
    assert not [w for w in good.check() if "sketch" in w]
    bad = load_config(text="generator:\n  spanmetrics:\n    sketch: nope\n"
                           "    moments_k: 40\n")
    warns = bad.check()
    assert any("spanmetrics.sketch" in w for w in warns)
    assert any("moments_k" in w for w in warns)


def test_obs_families_render():
    from tempo_tpu.obs.jaxruntime import RUNTIME
    text = RUNTIME.render()
    for fam in ("tempo_moments_solves_total",
                "tempo_moments_solver_fallback_total",
                "tempo_moments_solve_cache_hits_total",
                "tempo_moments_solve_seconds_total"):
        assert fam in text, fam


def test_scheduler_coalesced_route_matches_direct():
    # the packed4 coalescer path must carry the moments plane exactly
    # like the direct dispatch (merged windows, padded slot -1 rows)
    from tempo_tpu import sched
    from tempo_tpu.sched import DeviceScheduler, SchedConfig

    rng_seed = 13
    results = {}
    for use_sched in (False, True):
        _, reg, proc = _mk_world(False, "moments")
        proc.cfg = dataclasses.replace(proc.cfg, use_scheduler=use_sched)
        sc = DeviceScheduler(SchedConfig(), start_worker=False) \
            if use_sched else None
        with sched.use(sc):
            rng = np.random.default_rng(rng_seed)
            for op in ("a", "b"):
                _push(reg, proc, rng.lognormal(-2, 0.5, 64), op=op)
            sched.flush()    # drain queued windows before the reads
            results[use_sched] = (
                proc.quantile(0.9),
                sorted((s.name, s.labels, s.value)
                       for s in reg.collect(1) if s.value == s.value))
    assert results[False] == results[True]


# ---------------------------------------------------------------------------
# serving mesh
# ---------------------------------------------------------------------------

def test_mesh_serving_step_with_moments_matches_single_device():
    from tempo_tpu.parallel import serving

    results = {}
    for shards in (1, 2):
        sm = serving.ServingMesh(serving.MeshConfig(
            enabled=True, devices=shards, series_shards=shards))
        with serving.use(sm):
            _, reg, proc = _mk_world(False, "moments")
            rng = np.random.default_rng(11)
            for op in ("a", "b"):
                _push(reg, proc, rng.lognormal(-2, 0.5, 64), op=op)
            results[shards] = (
                proc.quantile(0.9),
                sorted((s.name, s.labels, s.value)
                       for s in reg.collect(1) if s.value == s.value))
    assert results[1] == results[2]


def test_paged_mesh_step_with_moments_matches_dense():
    # the paged fused step's shard_map variant with a moments arena:
    # arenas shard page-aligned over 'series', the moments plane rides
    # its own localized pseudo page table — answers must match the
    # dense single-device world exactly
    from tempo_tpu.parallel import serving
    from tempo_tpu.registry import pages as device_pages

    sm = serving.ServingMesh(serving.MeshConfig(
        enabled=True, devices=2, series_shards=2))
    with serving.use(sm):
        pool = device_pages.PagePool(device_pages.PagePoolConfig(
            enabled=True, page_rows=16, arena_slots=512))
        clock = [1000.0]
        with device_pages.use(pool):
            from tempo_tpu.generator.processors.spanmetrics import (
                SpanMetricsConfig, SpanMetricsProcessor)
            from tempo_tpu.registry.registry import (ManagedRegistry,
                                                     RegistryOverrides)
            reg = ManagedRegistry("t", RegistryOverrides(
                max_active_series=64), now=lambda: clock[0])
            proc = SpanMetricsProcessor(reg, SpanMetricsConfig(
                use_scheduler=False, sketch="moments",
                sketch_max_series=32))
        rng = np.random.default_rng(14)
        for op in ("a", "b"):
            _push(reg, proc, rng.lognormal(-2, 0.5, 64), op=op)
        mesh_result = (proc.quantile(0.9),
                       sorted((s.name, s.labels, s.value)
                              for s in reg.collect(1)
                              if s.value == s.value))
    _, reg_d, proc_d = _mk_world(False, "moments")
    rng = np.random.default_rng(14)
    for op in ("a", "b"):
        _push(reg_d, proc_d, rng.lognormal(-2, 0.5, 64), op=op)
    dense_result = (proc_d.quantile(0.9),
                    sorted((s.name, s.labels, s.value)
                           for s in reg_d.collect(1)
                           if s.value == s.value))
    assert mesh_result == dense_result


# ---------------------------------------------------------------------------
# TraceQL quantile_over_time moments axis
# ---------------------------------------------------------------------------

def _ts(labels, samples):
    from tempo_tpu.traceql.engine_metrics import TimeSeries
    return TimeSeries(tuple(labels), np.asarray(samples, np.float64))


def test_combiner_moment_bounds_merge_by_max():
    from tempo_tpu.traceql import ast as A
    from tempo_tpu.traceql.engine_metrics import (_LABEL_MOMENT,
                                                  SeriesCombiner)

    comb = SeriesCombiner(A.MetricsKind.QUANTILE_OVER_TIME, 3)
    base = (("svc", "a"),)
    comb.add_all([_ts(base + ((_LABEL_MOMENT, "0"),), [1, 2, 3]),
                  _ts(base + ((_LABEL_MOMENT, "hi"),), [5, 1, 2])])
    comb.add_all([_ts(base + ((_LABEL_MOMENT, "0"),), [1, 1, 1]),
                  _ts(base + ((_LABEL_MOMENT, "hi"),), [2, 4, 1])])
    got = comb.series
    np.testing.assert_allclose(
        got[base + ((_LABEL_MOMENT, "0"),)].samples, [2, 3, 4])   # sum
    np.testing.assert_allclose(
        got[base + ((_LABEL_MOMENT, "hi"),)].samples, [5, 4, 2])  # max


def test_quantile_over_time_multi_q_single_fold(monkeypatch):
    """Satellite: 3 quantile params must fold the summed grid ONCE."""
    from tempo_tpu.traceql import engine_metrics as em

    calls = {"n": 0}
    orig = em._fold_cumulative

    def counting(g):
        calls["n"] += 1
        return orig(g)

    monkeypatch.setattr(em, "_fold_cumulative", counting)
    comb = em.SeriesCombiner(
        __import__("tempo_tpu.traceql.ast", fromlist=["ast"]).MetricsKind
        .QUANTILE_OVER_TIME, 4)
    base = (("svc", "a"),)
    rng = np.random.default_rng(0)
    series = [_ts(base + ((em._LABEL_BUCKET, 2.0 ** b / 1e9),),
                  rng.integers(0, 10, 4)) for b in range(20, 30)]
    comb.add_all(series)
    req = em.QueryRangeRequest(
        query="{ } | quantile_over_time(duration, .5, .9, .99)",
        start_ns=0, end_ns=4 * 10**9, step_ns=10**9)
    out = comb.final(req)
    assert len(out) == 3                      # one series per q
    assert calls["n"] == 1                    # ONE fold for all three
    # and the multi-q helper matches the scalar reference math
    g = np.zeros((4, em.HBUCKETS))
    for ts in series:
        b = int(round(np.log2(dict(ts.labels)[em._LABEL_BUCKET] * 1e9)))
        g[:, b] += ts.samples
    for ts in out:
        qv = dict(ts.labels)["p"]
        ref = [em.log2_quantile(qv, g[s]) for s in range(4)]
        np.testing.assert_allclose(ts.samples, ref)


def test_quantile_over_time_moments_axis_end_to_end():
    from tempo_tpu.traceql.engine_metrics import (MetricsEvaluator,
                                                  QueryRangeRequest,
                                                  SeriesCombiner,
                                                  _LABEL_MOMENT,
                                                  metrics_kind)
    from tempo_tpu.traceql.memview import view_from_traces

    rng = np.random.default_rng(12)
    t0 = 1_700_000_000
    traces = []
    durs = []
    for _ in range(3000):
        tid = rng.bytes(16)
        start = int((t0 + float(rng.random()) * 50) * 1e9)
        d = int(rng.lognormal(np.log(4e7), 0.9))
        durs.append(d)
        traces.append((tid, [{
            "trace_id": tid, "span_id": rng.bytes(8), "name": "op",
            "service": "svc", "kind": 2, "status_code": 0,
            "start_unix_nano": start, "end_unix_nano": start + d}]))
    q = "{ } | quantile_over_time(duration, .5, .9, .99)"
    req = QueryRangeRequest(query=q, start_ns=int(t0 * 1e9),
                            end_ns=int((t0 + 60) * 1e9),
                            step_ns=int(60e9))
    view = view_from_traces(traces)
    with M.use_query_tier("moments"):
        ev = MetricsEvaluator(req)
        ev.observe(view)
        job = ev.results()
        # job-level payload is moment series, not 64-bucket series
        assert all(_LABEL_MOMENT in dict(s.labels) for s in job)
        assert len(job) <= M.QUERY_K + 3
        comb = SeriesCombiner(metrics_kind(q), req.n_steps)
        comb.add_all(job)
        final = {dict(s.labels)["p"]: float(s.samples[0])
                 for s in comb.final(req)}
    exact = {qv: float(np.quantile(durs, qv)) / 1e9
             for qv in (0.5, 0.9, 0.99)}
    xs = np.sort(np.asarray(durs, np.float64)) / 1e9
    for qv, est in final.items():
        rel = abs(est - exact[qv]) / exact[qv]
        rank = abs(np.searchsorted(xs, est) / len(xs) - qv)
        assert min(rel, rank) < 0.05, (qv, est, exact[qv], rel, rank)
    # monotone across the requested quantiles
    assert final[0.5] <= final[0.9] <= final[0.99]
    # identical data split across two evaluators merges to ≈ the same
    # answer (the psum-only combine property)
    with M.use_query_tier("moments"):
        comb2 = SeriesCombiner(metrics_kind(q), req.n_steps)
        for half in (traces[:len(traces) // 2],
                     traces[len(traces) // 2:]):
            ev = MetricsEvaluator(req)
            ev.observe(view_from_traces(half))
            comb2.add_all(ev.results())
        final2 = {dict(s.labels)["p"]: float(s.samples[0])
                  for s in comb2.final(req)}
    for qv in final:
        assert abs(final[qv] - final2[qv]) / final[qv] < 0.02
