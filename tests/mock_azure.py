"""In-process mock Azure Blob server (the Azurite analog).

Speaks the Blob REST subset `backend/azure.py` uses — Put/Get/Delete/HEAD
Blob, Range reads, List Blobs with prefix/delimiter/marker — and VERIFIES
the SharedKey signature on every request by rebuilding the canonicalized
string independently of the client's signer, so canonicalization bugs
fail here the way they would against real Azure.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

ACCOUNT = "mockaccount"
ACCOUNT_KEY = base64.b64encode(b"mock-azure-shared-key-0123456789").decode()
CONTAINER = "test-container"


class MockAzureHandler(BaseHTTPRequestHandler):
    store: dict[str, bytes] = {}
    lock = threading.Lock()

    def log_message(self, fmt, *args):  # noqa: A003
        pass

    # -- shared-key verification (independent of the client) ----------------

    def _verify_sig(self, content_length: int) -> str | None:
        auth = self.headers.get("Authorization", "")
        want_prefix = f"SharedKey {ACCOUNT}:"
        if not auth.startswith(want_prefix):
            return "missing SharedKey authorization"
        got_sig = auth[len(want_prefix):]
        parsed = urllib.parse.urlsplit(self.path)
        h = {k.lower(): v for k, v in self.headers.items()}
        canon_headers = "".join(
            f"{k}:{h[k]}\n" for k in sorted(k for k in h
                                            if k.startswith("x-ms-")))
        canon_resource = f"/{ACCOUNT}{parsed.path}"
        if parsed.query:
            q = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
            for k in sorted(q):
                canon_resource += f"\n{k.lower()}:{','.join(q[k])}"
        string_to_sign = "\n".join([
            self.command,
            h.get("content-encoding", ""),
            h.get("content-language", ""),
            str(content_length) if content_length else "",
            h.get("content-md5", ""),
            h.get("content-type", ""),
            "",
            h.get("if-modified-since", ""),
            h.get("if-match", ""),
            h.get("if-none-match", ""),
            h.get("if-unmodified-since", ""),
            h.get("range", ""),
        ]) + "\n" + canon_headers + canon_resource
        want = base64.b64encode(hmac.new(
            base64.b64decode(ACCOUNT_KEY), string_to_sign.encode(),
            hashlib.sha256).digest()).decode()
        if got_sig != want:
            return f"signature mismatch (want {want}, got {got_sig})"
        return None

    # -- helpers ------------------------------------------------------------

    def _blob(self) -> str | None:
        path = urllib.parse.urlsplit(self.path).path
        parts = path.lstrip("/").split("/", 1)
        if parts[0] != CONTAINER:
            return None
        return urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""

    def _reply(self, code: int, body: bytes = b"",
               headers: dict | None = None) -> None:
        self.send_response(code)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _deny(self, msg: str) -> None:
        self._reply(403, msg.encode())

    # -- verbs --------------------------------------------------------------

    def do_PUT(self) -> None:  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        err = self._verify_sig(n)
        if err:
            return self._deny(err)
        if self.headers.get("x-ms-blob-type") != "BlockBlob":
            return self._reply(400, b"missing x-ms-blob-type")
        key = self._blob()
        if not key:
            return self._reply(400, b"no blob name")
        with self.lock:
            self.store[key] = body
        self._reply(201)

    def do_GET(self) -> None:  # noqa: N802
        err = self._verify_sig(0)
        if err:
            return self._deny(err)
        q = {k: v[0] for k, v in urllib.parse.parse_qs(
            urllib.parse.urlsplit(self.path).query).items()}
        if q.get("comp") == "list":
            return self._list(q)
        key = self._blob()
        with self.lock:
            data = self.store.get(key)
        if data is None:
            return self._reply(404)
        rng = self.headers.get("Range", "")
        if rng.startswith("bytes="):
            lo, hi = rng[len("bytes="):].split("-")
            lo, hi = int(lo), int(hi)
            if lo >= len(data):
                return self._reply(416)
            part = data[lo:hi + 1]
            return self._reply(206, part)
        self._reply(200, data)

    def do_HEAD(self) -> None:  # noqa: N802
        err = self._verify_sig(0)
        if err:
            return self._deny(err)
        key = self._blob()
        with self.lock:
            data = self.store.get(key)
        if data is None:
            return self._reply(404)
        # HEAD: Content-Length advertises the blob size, no body follows
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.send_header("x-ms-blob-type", "BlockBlob")
        self.end_headers()

    def do_DELETE(self) -> None:  # noqa: N802
        err = self._verify_sig(0)
        if err:
            return self._deny(err)
        key = self._blob()
        with self.lock:
            existed = self.store.pop(key, None) is not None
        self._reply(202 if existed else 404)

    def _list(self, q: dict) -> None:
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        marker = q.get("marker", "")
        max_results = int(q.get("maxresults", 1000))
        with self.lock:
            all_names = sorted(k for k in self.store if k.startswith(prefix))
        if marker:
            all_names = [k for k in all_names if k > marker]
        blobs: list[str] = []
        prefixes: list[str] = []
        for k in all_names:
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    p = prefix + rest.split(delimiter)[0] + delimiter
                    if p not in prefixes:
                        prefixes.append(p)
                    continue
            blobs.append(k)
            if len(blobs) >= max_results:
                break
        truncated = bool(blobs) and blobs[-1] != (all_names[-1]
                                                  if all_names else "")
        parts = ['<?xml version="1.0"?><EnumerationResults><Blobs>']
        for k in blobs:
            parts.append(f"<Blob><Name>{k}</Name></Blob>")
        for p in prefixes:
            parts.append(f"<BlobPrefix><Name>{p}</Name></BlobPrefix>")
        parts.append("</Blobs>")
        if truncated and blobs:
            parts.append(f"<NextMarker>{blobs[-1]}</NextMarker>")
        parts.append("</EnumerationResults>")
        self._reply(200, "".join(parts).encode())


def start_mock_azure() -> tuple[ThreadingHTTPServer, int, type]:
    cls = type("BoundMockAzure", (MockAzureHandler,),
               {"store": {}, "lock": threading.Lock()})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), cls)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1], cls
