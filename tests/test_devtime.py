"""Device-time ledger + online dispatch cost model + scheduler tuning.

ISSUE 8's test surface: ledger accounting and per-tenant attribution
invariants, the robust affine cost-model fit (synthetic affine data,
outlier poisoning, nearest-bucket extrapolation), the WindowTuner's
choices under an injected cost model (feasibility, latency minimization,
static fallback, hard clamps), tuned-vs-static BIT-IDENTITY of drained
state, the qlog/querystats device-seconds threading, the /status +
/metrics surfaces, and the tier-1 smoke of the bench soak loop.
"""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.obs import devtime
from tempo_tpu.obs.devtime import CostModel, DeviceTimeLedger
from tempo_tpu.sched import (
    DeviceScheduler,
    PRIO_QUERY,
    SchedConfig,
    WindowTuner,
)


@pytest.fixture(autouse=True)
def _fresh_devtime():
    devtime.reset()
    yield
    devtime.reset()


# -- ledger -----------------------------------------------------------------

def test_ledger_accounting_and_keys():
    led = DeviceTimeLedger()
    led.record_batch(kernel="k", bucket=256, prio=0, shards=0,
                     wall_ns=1000, rows=200, padded_rows=56,
                     queue_wait_ns=300, h2d_bytes=4096,
                     tenant_rows={"a": 150, "b": 50})
    led.record_batch(kernel="k", bucket=256, prio=0, shards=0,
                     wall_ns=500, rows=100, padded_rows=156,
                     queue_wait_ns=100, h2d_bytes=2048,
                     tenant_rows={"a": 100})
    led.record_batch(kernel="scan", bucket=0, prio=1, shards=4,
                     wall_ns=700, rows=0, padded_rows=0,
                     queue_wait_ns=0, h2d_bytes=0)
    snap = led.snapshot()
    cell = snap[("k", 256, "ingest", "")]
    assert cell == {"wall_ns": 1500, "batches": 2, "rows": 300,
                    "padded_rows": 212, "queue_wait_ns": 400,
                    "h2d_bytes": 6144}
    assert ("scan", 0, "query", "4") in snap
    assert led.total_device_ns() == 2200


def test_ledger_tenant_attribution_sums_to_total():
    led = DeviceTimeLedger()
    rng = np.random.default_rng(0)
    for i in range(200):
        tenants = {f"t{j}": int(rng.integers(1, 50))
                   for j in range(int(rng.integers(1, 5)))}
        led.record_batch(kernel=f"k{i % 3}", bucket=64, prio=0, shards=0,
                         wall_ns=int(rng.integers(1000, 100000)),
                         rows=sum(tenants.values()),
                         padded_rows=7, queue_wait_ns=5, h2d_bytes=1,
                         tenant_rows=tenants)
    # unattributed work keeps the invariant exact through its own bucket
    led.record_batch(kernel="fn", bucket=0, prio=1, shards=0,
                     wall_ns=12345, rows=0, padded_rows=0,
                     queue_wait_ns=0, h2d_bytes=0)
    total = led.total_device_ns()
    by_tenant = led.tenant_device_ns()
    assert by_tenant["_unattributed"] == 12345
    # integer-division truncation loses < len(tenants) ns per batch
    assert abs(total - sum(by_tenant.values())) <= total * 0.001
    st = led.status(top_tenants=3)
    assert len(st["top_tenant_device_seconds"]) == 3
    assert st["device_seconds_total"] == pytest.approx(total / 1e9,
                                                       rel=1e-3)


# -- cost model -------------------------------------------------------------

def test_cost_model_fits_affine_data():
    cm = CostModel(min_samples=10)
    rng = np.random.default_rng(1)
    a_true, b_true = 2e-4, 3e-6
    for _ in range(300):
        r = int(rng.integers(8, 64))
        cm.observe("k", 64, r, a_true + b_true * r
                   + float(rng.normal(0, 1e-6)))
    pred = cm.predict("k", 64, 32)
    assert pred == pytest.approx(a_true + b_true * 32, rel=0.05)
    assert cm.warm("k", 64)
    assert cm.rel_error_median("k", 64) <= 0.25
    assert cm.typical_error("k", 64) <= 0.25
    assert cm.status()[0]["typical_error"] is not None


def test_cost_model_winsorizes_outliers():
    cm = CostModel(min_samples=10, clip=8.0)
    for _ in range(50):
        cm.observe("k", 64, 32, 1e-4)
    # a burst of 1000x stalls must not poison the fit
    for _ in range(5):
        cm.observe("k", 64, 32, 0.1)
    assert cm.predict("k", 64, 32) < 1e-3
    # and the early-sample guard: stalls BEFORE warm are clipped too
    cm2 = CostModel(min_samples=20)
    cm2.observe("k", 64, 32, 1e-4)
    cm2.observe("k", 64, 32, 1e-4)
    cm2.observe("k", 64, 32, 1e-4)
    cm2.observe("k", 64, 32, 0.5)        # 5000x stall at n=3
    for _ in range(30):
        cm2.observe("k", 64, 32, 1e-4)
    assert cm2.predict("k", 64, 32) < 1e-3


def test_cost_model_cold_and_neighbor_extrapolation():
    cm = CostModel(min_samples=5)
    assert cm.predict("k", 64) is None
    for _ in range(10):
        cm.observe("k", 256, 200, 1e-3)
    # exact pair cold, same-kernel neighbor warm: extrapolate
    assert cm.predict("k", 512, 200) == pytest.approx(1e-3, rel=0.2)
    assert cm.predict("other", 256) is None
    assert cm.warm_pairs() == [("k", 256)]
    st = cm.status()
    assert st[0]["warm"] and st[0]["kernel"] == "k"


def test_cost_model_degenerate_single_rows_value():
    """One distinct rows value → variance 0 → fall back to a pure mean
    (b = 0), never a division blow-up."""
    cm = CostModel(min_samples=5)
    for _ in range(10):
        cm.observe("k", 64, 64, 2e-4)
    assert cm.predict("k", 64, 64) == pytest.approx(2e-4, rel=0.01)
    assert cm.predict("k", 64, 1) == pytest.approx(2e-4, rel=0.01)


# -- window tuner -----------------------------------------------------------

def _warm_model(kernel: str, bucket: int, cost_s: float, n: int = 80):
    for _ in range(n):
        devtime.COST_MODEL.observe(kernel, bucket, bucket, cost_s)


def test_tuner_cold_model_returns_none():
    t = [0.0]
    tu = WindowTuner(now=lambda: t[0])
    cfg = SchedConfig(tuning="auto")
    tu.note_rows("k", 1000)
    t[0] += 1.0
    assert tu.choice("k", cfg) is None
    assert tu.windows_ms() == []


def test_tuner_picks_feasible_latency_minimum():
    """Cheap dispatch → the smallest feasible window wins (cost ≤ w and
    w + cost minimal at the low end of the grid)."""
    t = [0.0]
    tu = WindowTuner(now=lambda: t[0])
    cfg = SchedConfig(tuning="auto", tuning_window_min_ms=0.25,
                      tuning_window_max_ms=8.0)
    _warm_model("k", 64, 1e-4)           # 0.1ms per dispatch
    tu.note_rows("k", 2000)
    t[0] += 1.0                          # rate = 2000 rows/s
    w_s, target = tu.choice("k", cfg)
    assert w_s == pytest.approx(0.25e-3, rel=0.01)
    assert target == 64
    assert dict(tu.windows_ms())["k"] == pytest.approx(0.25, rel=0.01)


def test_tuner_infeasible_cost_falls_back_to_max_window():
    """Dispatch slower than every candidate window → no feasible w →
    maximum amortization (largest window)."""
    t = [0.0]
    tu = WindowTuner(now=lambda: t[0])
    cfg = SchedConfig(tuning="auto", tuning_window_min_ms=0.25,
                      tuning_window_max_ms=4.0)
    _warm_model("k", 64, 0.05)           # 50ms per dispatch
    tu.note_rows("k", 1000)
    t[0] += 1.0
    w_s, _target = tu.choice("k", cfg)
    assert w_s == pytest.approx(4.0e-3, rel=0.01)


def test_tuner_choice_cached_until_interval():
    t = [0.0]
    tu = WindowTuner(now=lambda: t[0])
    cfg = SchedConfig(tuning="auto", tuning_interval_s=0.5)
    _warm_model("k", 64, 1e-4)
    tu.note_rows("k", 1000)
    t[0] += 1.0
    first = tu.choice("k", cfg)
    devtime.reset()                      # model gone...
    t[0] += 0.1
    assert tu.choice("k", cfg) == first  # ...but the cached choice holds
    t[0] += 1.0
    assert tu.choice("k", cfg) is None   # refit sees the cold model


def test_scheduler_close_params_hard_guard():
    """Auto mode can shrink the close target but never exceed the static
    occupancy close, and the window stays inside the clamp bounds."""
    sc = DeviceScheduler(SchedConfig(
        tuning="auto", batch_window_ms=2.0, occupancy_target=0.75,
        max_batch_rows=16384, tuning_window_min_ms=0.5,
        tuning_window_max_ms=3.0), start_worker=False)
    # cold model: static params
    w, target = sc._group_close_params("k")
    assert w == pytest.approx(2.0e-3)
    assert target == pytest.approx(0.75 * 16384)
    assert sc.tuned_window_ms("k") == pytest.approx(2.0)
    assert not sc.tuning_active()
    # warm model with a huge dispatch cost: tuner wants 8ms (its grid
    # max) but the config clamp holds it at 3ms
    _warm_model("k", 64, 0.05)
    sc._tuner.note_rows("k", 1000)
    sc._tuner._state["k"][1] = -10.0     # force a refit now
    w, target = sc._group_close_params("k")
    assert w <= 3.0e-3 + 1e-9
    assert target <= 0.75 * 16384
    assert sc.tuning_active()


def test_tuned_drain_bit_identical_to_static():
    """Tuning changes WHEN batches close, never what they compute: the
    same submitted jobs drain to the same final state."""
    def run(cfg: SchedConfig) -> np.ndarray:
        state = np.zeros(64, np.float64)

        def dispatch(slots, vals):
            np.add.at(state, slots[slots >= 0].astype(int),
                      vals[slots >= 0])

        sc = DeviceScheduler(cfg, start_worker=False)
        rng = np.random.default_rng(7)
        for i in range(50):
            n = int(rng.integers(1, 40))
            slots = rng.integers(0, 64, n).astype(np.float64)
            vals = rng.normal(size=n)
            sc.submit_rows("k", "m", (slots, vals), n, dispatch,
                           pads=(-1.0, 0.0), tenant=f"t{i % 5}")
            if i % 7 == 0:
                sc.drain_once(force=(i % 14 == 0))
        sc.flush()
        return state

    _warm_model("k", 64, 1e-4)
    static = run(SchedConfig(tuning="static"))
    devtime.reset()
    _warm_model("k", 64, 1e-4)
    auto = run(SchedConfig(tuning="auto", tuning_window_min_ms=0.25))
    assert np.array_equal(static, auto)


# -- scheduler → ledger wiring ---------------------------------------------

def test_dispatch_records_ledger_and_feeds_model():
    sc = DeviceScheduler(SchedConfig(), start_worker=False)
    seen = []
    lat0 = devtime.INGEST_LATENCY.snapshot(("k",))
    count0 = lat0["count"] if lat0 else 0   # RUNTIME histograms are
    #                                         process-wide, not reset

    def dispatch(slots, vals):
        seen.append(len(slots))

    for i in range(3):
        sc.submit_rows("k", "m", (np.full(30, i, np.float32),
                                  np.ones(30, np.float32)), 30, dispatch,
                       tenant=f"t{i}")
    sc.drain_once(force=True)
    assert seen == [128]                       # 90 rows → bucket 128
    snap = devtime.LEDGER.snapshot()
    cell = snap[("k", 128, "ingest", "")]
    assert cell["batches"] == 1 and cell["rows"] == 90
    assert cell["padded_rows"] == 128 - 90
    assert cell["h2d_bytes"] == 2 * 128 * 4    # two f32 roles, padded
    tenants = devtime.LEDGER.tenant_device_ns()
    assert set(tenants) == {"t0", "t1", "t2"}
    assert abs(devtime.LEDGER.total_device_ns()
               - sum(tenants.values())) <= 3
    # the cost model saw the clean dispatch
    with devtime.COST_MODEL._lock:
        assert ("k", 128) in devtime.COST_MODEL._pairs
    # and the per-job ingest-visible latency histogram has 3 new samples
    got = devtime.INGEST_LATENCY.snapshot(("k",))
    assert got is not None and got["count"] - count0 == 3


def test_failed_dispatch_ledgered_but_not_learned():
    sc = DeviceScheduler(SchedConfig(), start_worker=False)

    def boom(slots, vals):
        raise RuntimeError("kernel exploded")

    sc.submit_rows("k", "m", (np.zeros(4, np.float32),
                              np.zeros(4, np.float32)), 4, boom)
    sc.drain_once(force=True)
    assert devtime.LEDGER.total_device_ns() >= 0
    assert ("k", 64, "ingest", "") in devtime.LEDGER.snapshot()
    with devtime.COST_MODEL._lock:
        assert ("k", 64) not in devtime.COST_MODEL._pairs
    assert sc.dispatch_errors == 1


def test_run_fn_attributes_device_ns_to_querystats():
    from tempo_tpu.obs import querystats

    sc = DeviceScheduler(SchedConfig(), start_worker=False)
    with querystats.scope() as st:
        out = sc.run(lambda: 41 + 1, kernel="scan", priority=PRIO_QUERY,
                     tenant="tq")
    assert out == 42
    assert st.device_ns > 0
    assert st.search_metrics()["deviceNanos"] == st.device_ns
    # inline (idle) path still ledgered, attributed to the tenant
    assert devtime.LEDGER.tenant_device_ns().get("tq", 0) > 0
    assert ("scan", 0, "query", "") in devtime.LEDGER.snapshot()


def test_qlog_line_carries_device_seconds_and_wait_share():
    import logging

    from tempo_tpu.obs.qlog import QueryLogger
    from tempo_tpu.obs.querystats import QueryStats

    records = []

    class _H(logging.Handler):
        def emit(self, r):
            records.append(r.getMessage())

    lg = logging.getLogger("test.devtime.qlog")
    lg.addHandler(_H())
    lg.setLevel(logging.DEBUG)
    ql = QueryLogger(sample_every=1, logger=lg)
    st = QueryStats()
    st.add(device_ns=5_000_000)
    st.add_stage_ns("sched_wait", 20_000_000)
    rec = ql.log_query(op="search", tenant="t", query="{}", status="ok",
                       duration_s=0.1, stats=st)
    assert rec["deviceNanos"] == 5_000_000
    assert rec["deviceSeconds"] == pytest.approx(0.005)
    assert rec["schedWaitShare"] == pytest.approx(0.2)
    import json as _json
    assert _json.loads(records[-1])["schedWaitShare"] == pytest.approx(0.2)


def test_querystats_device_ns_round_trips_wire():
    from tempo_tpu.model import tempopb
    from tempo_tpu.obs.querystats import QueryStats

    st = QueryStats()
    st.add(device_ns=123456, inspected_traces=3)
    st2 = tempopb.dec_query_stats(tempopb.enc_query_stats(st))
    assert st2.device_ns == 123456
    assert st2.inspected_traces == 3
    st3 = QueryStats.from_json(st.to_json())
    assert st3.device_ns == 123456


# -- exposition -------------------------------------------------------------

def test_devtime_metric_families_render_conformant():
    from tempo_tpu.obs.jaxruntime import RUNTIME
    from tempo_tpu.obs.registry import parse_exposition

    devtime.LEDGER.record_batch(kernel="k", bucket=64, prio=0, shards=2,
                                wall_ns=1_000_000, rows=50,
                                padded_rows=14, queue_wait_ns=100,
                                h2d_bytes=512, tenant_rows={"a": 50})
    for _ in range(30):
        devtime.COST_MODEL.observe("k", 64, 50, 1e-4)
    fams = parse_exposition(RUNTIME.render())
    key = ("tempo_devtime_device_seconds_total",
           (("bucket", "64"), ("class", "ingest"), ("kernel", "k"),
            ("shard", "2")))
    assert fams["tempo_devtime_device_seconds_total"]["samples"][key] \
        == pytest.approx(1e-3)
    assert ("tempo_devtime_tenant_device_seconds_total",
            (("tenant", "a"),)) in \
        fams["tempo_devtime_tenant_device_seconds_total"]["samples"]
    for name in ("tempo_sched_cost_model_coeff_a_seconds",
                 "tempo_sched_cost_model_coeff_b_seconds_per_row",
                 "tempo_sched_cost_model_rel_error",
                 "tempo_sched_cost_model_rel_error_median",
                 "tempo_sched_cost_model_age_seconds"):
        assert any(k[0] == name for k in fams[name]["samples"])


def test_quantile_from_counts_interpolates():
    edges = (0.001, 0.002, 0.004, 0.008)
    assert devtime.quantile_from_counts(edges, [0, 0, 0, 0, 0], 0.99) == 0.0
    # all mass in one bucket: quantile inside (0.002, 0.004]
    q = devtime.quantile_from_counts(edges, [0, 0, 100, 0, 0], 0.5)
    assert 0.002 < q <= 0.004
    # overflow bucket floors at the top edge
    assert devtime.quantile_from_counts(edges, [0, 0, 0, 0, 10], 0.99) \
        == 0.008


def test_status_surfaces_devtime_and_cost_model(tmp_path):
    import json as _json
    import socket
    import urllib.request

    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config

    cfg = Config()
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    cfg.server.http_listen_port = s.getsockname()[1]
    s.close()
    cfg.sched.tuning = "auto"
    app = App(cfg)
    srv = serve(app, block=False)
    try:
        devtime.LEDGER.record_batch(
            kernel="k", bucket=64, prio=0, shards=0, wall_ns=1000,
            rows=10, padded_rows=1, queue_wait_ns=1, h2d_bytes=1,
            tenant_rows={"a": 10})
        for _ in range(60):
            devtime.COST_MODEL.observe("k", 64, 50, 1e-4)
        url = (f"http://127.0.0.1:{cfg.server.http_listen_port}/status")
        with urllib.request.urlopen(url, timeout=10) as r:
            body = _json.loads(r.read())
        assert body["devtime"]["device_seconds_total"] > 0
        assert body["devtime"]["top_tenant_device_seconds"]["a"] > 0
        assert body["cost_model"]["tuning"] == "auto"
        pairs = body["cost_model"]["pairs"]
        assert pairs and pairs[0]["kernel"] == "k" and pairs[0]["warm"]
    finally:
        srv.shutdown()
        app.shutdown()


def test_config_warns_on_bad_tuning():
    from tempo_tpu.app.config import Config

    cfg = Config()
    cfg.sched.tuning = "bogus"
    assert any("sched.tuning" in w for w in cfg.check())
    cfg.sched.tuning = "auto"
    cfg.sched.tuning_window_min_ms = 5.0
    cfg.sched.tuning_window_max_ms = 1.0
    assert any("tuning_window" in w for w in cfg.check())
    cfg.sched.tuning_window_min_ms = 0.25
    cfg.sched.tuning_window_max_ms = 8.0
    assert not any("tuning" in w for w in cfg.check())


def test_sched_dispatch_span_emitted():
    from tempo_tpu.utils import tracing

    spans = []

    class _Tracer(tracing.NoopTracer):
        def span(self, name, **attrs):
            spans.append((name, attrs))
            return super().span(name, **attrs)

    tracing.install(_Tracer())
    try:
        sc = DeviceScheduler(SchedConfig(), start_worker=False)
        sc.submit_rows("k", "m", (np.zeros(4, np.float32),
                                  np.zeros(4, np.float32)), 4,
                       lambda *a: None, tenant="t")
        sc.drain_once(force=True)
    finally:
        tracing.install(tracing.NoopTracer())
    names = [s for s in spans if s[0] == "sched.dispatch"]
    assert names and names[0][1]["kernel"] == "k"
    assert names[0][1]["bucket"] == 64 and names[0][1]["rows"] == 4


# -- the tier-1 soak smoke --------------------------------------------------

def test_soak_smoke():
    """The bench soak loop in miniature: static + auto arms against a
    real App (distributor → ingester/generator, frontend reads, vulture
    canary over HTTP), gating the machinery — tuning goes active from a
    warm cost model, attribution sums, ledger populated, no tuning-loop
    recompiles, vulture writes read back. Arms are seconds, not
    minutes, so the p99/throughput comparison is reported, not gated
    (bench.py --stage=soak holds those)."""
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench

    out = bench._soak_run(n_tenants=12, warm_s=1.0, steady_s=2.0,
                          spans_per_push=64, duty=0.6,
                          read_every_s=0.5, vulture_every_s=1.0,
                          smoke=True)
    assert out["soak_accept_ok"], out
    assert out["soak_tenants_attributed"] >= 12
    assert out["soak_tuned_window_ms"]       # tuner published a window
    assert out["soak_vulture"]["read_missing"] == 0
