"""tempo_tpu.obs: registry exposition, conformance, drift gate, exemplars.

The observability substrate's own tests: Counter/Gauge/Histogram family
semantics, HELP/TYPE text exposition with centralized escaping, the
Prometheus text-format round-trip parser against a LIVE `/metrics`, the
alert/dashboard ↔ registry drift gate, the SelfTracer dogfood path
(spans exported over OTLP/HTTP into this very process, queryable by
trace id), and the slow-request trace-id exemplar bridge.
"""

from __future__ import annotations

import json
import socket
import time
import urllib.parse
import urllib.request

import pytest

from tempo_tpu.obs import (
    Registry,
    escape_label,
    exponential_buckets,
    parse_exposition,
)


# -- instrument / family semantics ------------------------------------------

def test_counter_gauge_render_with_help_type():
    reg = Registry()
    c = reg.counter("tempo_t_things_total", "things processed",
                    labels=("reason",))
    c.inc(2, ("full",))
    c.inc(labels=("full",))
    reg.gauge("tempo_t_depth", "queue depth").set(4.5)
    text = reg.render()
    assert "# HELP tempo_t_things_total things processed" in text
    assert "# TYPE tempo_t_things_total counter" in text
    assert '# TYPE tempo_t_depth gauge' in text
    assert 'tempo_t_things_total{reason="full"} 3' in text
    assert "tempo_t_depth 4.5" in text
    fams = parse_exposition(text)
    assert fams["tempo_t_things_total"]["type"] == "counter"
    key = ("tempo_t_things_total", (("reason", "full"),))
    assert fams["tempo_t_things_total"]["samples"][key] == 3.0


def test_get_or_create_identity_and_mismatch():
    reg = Registry()
    a = reg.counter("tempo_t_total", "h", labels=("x",))
    assert reg.counter("tempo_t_total", labels=("x",)) is a
    with pytest.raises(ValueError):          # kind mismatch
        reg.gauge("tempo_t_total", labels=("x",))
    with pytest.raises(ValueError):          # label-set mismatch
        reg.counter("tempo_t_total", labels=("y",))
    with pytest.raises(ValueError):          # wrong label arity at use
        a.inc(1, ())
    with pytest.raises(ValueError):          # invalid metric name
        reg.counter("tempo bad name")
    reg.counter_func("tempo_t_cb_total", lambda: [((), 1)])
    with pytest.raises(ValueError):          # func families never merge
        reg.counter_func("tempo_t_cb_total", lambda: [((), 2)])


def test_label_escaping_centralized_roundtrip():
    evil = 'a"} 9\ninjected{x="y'
    assert "\\n" in escape_label(evil) and '\\"' in escape_label(evil)
    reg = Registry()
    reg.counter("tempo_t_total", "h", labels=("tenant",)).inc(1, (evil,))
    text = reg.render()
    # every physical line is metadata or a well-formed sample — nothing
    # the attacker-controlled value injected
    fams = parse_exposition(text)
    (name, labels), v = next(iter(fams["tempo_t_total"]["samples"].items()))
    assert v == 1.0 and name == "tempo_t_total"
    # the parser un-escapes nothing: the escaped form survives intact
    assert "injected" in dict(labels)["tenant"]


def test_histogram_cumulative_buckets_and_exemplar():
    reg = Registry()
    h = reg.histogram("tempo_t_seconds", "latency", labels=("op",),
                      buckets=exponential_buckets(0.001, 2.0, 4))
    h.observe(0.0005, ("read",))             # below first edge
    h.observe(0.003, ("read",))
    h.observe(99.0, ("read",))               # above last edge -> +Inf only
    h.observe(0.1, ("read",), trace_id="ab" * 16)
    snap = h.snapshot(("read",))
    assert snap["count"] == 4
    assert snap["exemplar"][0] == "ab" * 16
    assert h.exemplar(("write",)) is None
    fams = parse_exposition(reg.render())
    samples = fams["tempo_t_seconds"]["samples"]
    inf_key = ("tempo_t_seconds_bucket",
               tuple(sorted((("op", "read"), ("le", "+Inf")))))
    assert samples[inf_key] == 4.0
    count_key = ("tempo_t_seconds_count", (("op", "read"),))
    assert samples[count_key] == 4.0
    # metric_names exposes the derived sample names for the drift gate
    assert "tempo_t_seconds_bucket" in reg.metric_names()


def test_func_families_and_failing_collector():
    state = {"hits": 3}
    reg = Registry()
    reg.counter_func("tempo_t_hits_total",
                     lambda: [((), state["hits"])], help="hits")
    reg.gauge_func("tempo_t_broken",
                   lambda: (_ for _ in ()).throw(RuntimeError("boom")),
                   help="always fails")
    text = reg.render()
    assert "tempo_t_hits_total 3" in text
    # a failing collector contributes nothing but never breaks /metrics
    assert "# TYPE tempo_t_broken gauge" in text
    parse_exposition(text)
    state["hits"] = 7
    assert "tempo_t_hits_total 7" in reg.render()


def test_disabled_registry_is_noop():
    reg = Registry(enabled=False)
    c = reg.counter("tempo_t_total", "h")
    h = reg.histogram("tempo_t_seconds", "h")
    c.inc()
    h.observe(1.0)
    assert c.value() == 0.0 and h.snapshot() is None
    reg.counter_func("tempo_t_cb_total", lambda: [((), 1)])
    assert reg.render() == "" and reg.metric_names() == set()


def test_parser_rejects_nonconformant_text():
    with pytest.raises(ValueError, match="no TYPE"):
        parse_exposition("tempo_x_total 1\n")
    dup = ("# TYPE tempo_x_total counter\n"
           "tempo_x_total 1\ntempo_x_total 2\n")
    with pytest.raises(ValueError, match="duplicate series"):
        parse_exposition(dup)
    bad_labels = ('# TYPE tempo_x_total counter\n'
                  'tempo_x_total{tenant="a} 1\n')
    with pytest.raises(ValueError, match="malformed"):
        parse_exposition(bad_labels)
    noncum = ('# TYPE tempo_h histogram\n'
              'tempo_h_bucket{le="0.1"} 5\n'
              'tempo_h_bucket{le="+Inf"} 3\n'
              'tempo_h_count 3\n')
    with pytest.raises(ValueError, match="not cumulative"):
        parse_exposition(noncum)


def test_route_template_bounds_label_cardinality():
    """Unauthenticated garbage paths must not mint new route labels."""
    from tempo_tpu.app.api import _route_of

    assert _route_of("/v1/traces") == "/v1/traces"
    assert _route_of("/api/traces/abcd1234") == "/api/traces/{id}"
    assert _route_of("/api/v2/search/tag/x/values") == \
        "/api/v2/search/tag/{name}/values"
    assert _route_of("/kv/collectors/i-12") == "/kv/{key}"
    assert _route_of("/internal/ingester/push") == "/internal/ingester/push"
    # attacker-controlled segments collapse to a bounded label
    assert _route_of("/internal/ingester/zzz9") == "/internal/other"
    assert _route_of("/internal/x/y/z/w") == "/internal/other"
    assert _route_of("/wp-admin/setup.php") == "other"


def test_queue_wait_observed_at_claim_exactly_once():
    """The wait histogram observes at CLAIM — the one point common to
    local workers, remote worker streams (which never invoke fn), and
    the issuer's inline fallback — and only for the winning claim."""
    import time as _time

    from tempo_tpu.frontend.frontend import _Job

    reg = Registry()
    h = reg.histogram("tempo_t_wait_seconds", "w")
    wj = _Job(job=None, fn=lambda j: None, spec={"kind": "x"})
    wj.enqueued_at = _time.perf_counter()
    wj.queue_wait = h
    assert wj.try_claim() is True       # remote-stream shape: claim only
    assert wj.try_claim() is False      # losers never double-observe
    assert h.snapshot(())["count"] == 1
    # a job that was never enqueued (inline run) records no wait
    wj2 = _Job(job=None, fn=lambda j: None)
    wj2.run()
    assert h.snapshot(())["count"] == 1


# -- live process: /metrics round-trip, drift gate, exemplars ----------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _mk_app(tmp_path):
    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config

    cfg = Config(target="all")
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.server.http_listen_port = _free_port()
    app = App(cfg)
    app.overrides.set_tenant_patch("single-tenant", {
        "generator": {"processors": ["span-metrics", "local-blocks"]}})
    app.start_loops()
    srv = serve(app, block=False)
    return app, srv, f"http://127.0.0.1:{cfg.server.http_listen_port}"


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    app, srv, base = _mk_app(tmp_path_factory.mktemp("obs"))
    yield app, base
    srv.shutdown()
    app.shutdown()


def _push_one_trace(base: str, tid_hex: str = "ab" * 16) -> None:
    t0 = int((time.time() - 3) * 1e9)
    otlp = {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name", "value": {"stringValue": "shop"}}]},
        "scopeSpans": [{"spans": [{
            "traceId": tid_hex, "spanId": "cd" * 8, "name": "obs-op",
            "startTimeUnixNano": str(t0),
            "endTimeUnixNano": str(t0 + 1_000_000)}]}]}]}
    req = urllib.request.Request(
        f"{base}/v1/traces", data=json.dumps(otlp).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=10).close()


def test_metrics_exposition_roundtrip(server):
    """`/metrics` is one registry render: HELP/TYPE on every family, no
    duplicate series, parseable end to end — and the duration histograms
    from every instrumented layer are present after real traffic."""
    app, base = server
    _push_one_trace(base)
    now = time.time()
    with urllib.request.urlopen(
            f"{base}/api/metrics/query_range?q=" +
            urllib.parse.quote("{ } | rate()") +
            f"&start={now - 300}&end={now}&step=300", timeout=10) as r:
        assert r.status == 200
    app.ingester.sweep_all()
    app.generator.collect_all()
    app.db.compact_tenant_once("single-tenant")
    with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    fams = parse_exposition(text)       # conformance: raises on violation
    histograms = {n for n, f in fams.items() if f["type"] == "histogram"}
    # >= 8 duration histograms across >= 6 modules (acceptance floor)
    for name in ("tempo_request_duration_seconds",              # app/api
                 "tempo_grpc_request_duration_seconds",         # grpcplane
                 "tempo_distributor_push_duration_seconds",     # distributor
                 "tempo_ingester_cut_duration_seconds",         # ingester
                 "tempo_ingester_flush_duration_seconds",
                 "tempo_query_frontend_request_duration_seconds",  # frontend
                 "tempo_query_frontend_queue_wait_seconds",
                 "tempo_querier_block_scan_duration_seconds",   # querier
                 "tempo_compactor_cycle_duration_seconds",      # compactor/db
                 "tempo_metrics_generator_collect_duration_seconds",
                 "tempo_jax_kernel_duration_seconds"):          # jax runtime
        assert name in histograms, name
    # byte-compat: every pre-registry metric name still present
    for name in ("tempo_distributor_spans_received_total",
                 "tempo_distributor_bytes_received_total",
                 "tempo_distributor_traces_pushed_total",
                 "tempo_distributor_push_failures_total",
                 "tempo_query_frontend_queries_total",
                 "tempo_query_frontend_cache_hits_total",
                 "tempo_query_frontend_cache_misses_total",
                 "tempo_read_plane_fused_metric_blocks_total",
                 "tempo_read_plane_host_metric_blocks_total",
                 "tempo_usage_stats_reports_written_total",
                 "tempo_ingester_live_traces"):
        assert name in fams, name
    # HELP metadata made it out for module-owned families
    assert fams["tempo_distributor_spans_received_total"]["help"]
    # traffic actually landed in the request-duration histogram
    dur = fams["tempo_request_duration_seconds"]["samples"]
    assert any(n == "tempo_request_duration_seconds_count" and v > 0
               for (n, _l), v in dur.items())
    # jit-compile counters from the instrumented spanmetrics path
    assert "tempo_jax_jit_compile_total" in fams
    assert any(v > 0 for (n, _l), v in
               fams["tempo_jax_jit_compile_total"]["samples"].items())


def test_usage_metrics_share_exposition_writer(server):
    """`/usage_metrics` renders through the same obs writer: HELP/TYPE
    lines, centralized escaping, parseable."""
    app, base = server
    _push_one_trace(base)
    with urllib.request.urlopen(f"{base}/usage_metrics", timeout=10) as r:
        text = r.read().decode()
    fams = parse_exposition(text)
    assert "tempo_usage_tracker_bytes_received_total" in fams
    assert fams["tempo_usage_tracker_bytes_received_total"]["type"] == \
        "counter"


def test_ops_metric_names_registered(server, tmp_path):
    """The drift gate: every tempo_* name referenced by alerts.yaml and
    the dashboards is registered; an aspirational name is caught."""
    import os

    import tempo_tpu.app.api as api_mod
    from tempo_tpu.obs import drift
    from tempo_tpu.obs.jaxruntime import RUNTIME

    app, _base = server
    ops_dir = os.path.join(os.path.dirname(api_mod.__file__),
                           "..", "..", "operations")
    refs = drift.referenced_metric_names(ops_dir)
    assert "tempo_distributor_push_failures_total" in refs
    assert drift.check_drift(ops_dir, [app.obs, RUNTIME]) == []
    # negative: a made-up metric in an alert expression must be flagged
    bogus = tmp_path / "ops"
    bogus.mkdir()
    (bogus / "alerts.yaml").write_text(
        "expr: rate(tempo_nonexistent_total[5m]) > 0\n")
    problems = drift.check_drift(str(bogus), [app.obs, RUNTIME])
    assert len(problems) == 1 and "tempo_nonexistent_total" in problems[0]
    # histogram PromQL suffixes (_bucket/_sum/_count) resolve via the
    # family's derived names
    (bogus / "alerts.yaml").write_text(
        "expr: rate(tempo_request_duration_seconds_bucket[5m])\n")
    assert drift.check_drift(str(bogus), [app.obs, RUNTIME]) == []


def test_bail_causes_documented(tmp_path):
    """The fallback-cause gate: every `_bail(...)` string in
    device_scan.py has a row in the runbook's cause table, and an
    undocumented cause is caught."""
    import os
    import shutil

    import tempo_tpu.app.api as api_mod
    from tempo_tpu.obs import drift

    ops_dir = os.path.abspath(os.path.join(
        os.path.dirname(api_mod.__file__), "..", "..", "operations"))
    assert drift.check_bail_causes(ops_dir) == []
    # negative: strip one documented cause from a runbook copy
    repo2 = tmp_path / "repo"
    (repo2 / "operations").mkdir(parents=True)
    (repo2 / "tempo_tpu" / "block").mkdir(parents=True)
    shutil.copy(
        os.path.join(os.path.dirname(ops_dir),
                     "tempo_tpu", "block", "device_scan.py"),
        repo2 / "tempo_tpu" / "block" / "device_scan.py")
    runbook = open(os.path.join(ops_dir, "runbook.md")).read()
    (repo2 / "operations" / "runbook.md").write_text(
        runbook.replace("| `grid_size` |", "| `gridsize_typo` |"))
    problems = drift.check_bail_causes(str(repo2 / "operations"))
    assert len(problems) == 1 and "grid_size" in problems[0]


def test_slow_request_exemplar_carries_trace_id(server):
    """A frontend op that misses its SLO stamps the active self-tracing
    span's trace id onto the histogram observation (the exemplar bridge:
    p99 spike -> concrete slow trace)."""
    from tempo_tpu.frontend.slos import SLOConfig
    from tempo_tpu.utils import tracing

    app, _base = server
    tracer = tracing.SelfTracer("http://127.0.0.1:1", flush_interval_s=3600)
    prev = tracing.tracer()
    app.frontend.slos.per_op["search"] = SLOConfig(duration_slo_s=1e-9)
    try:
        tracing.install(tracer)
        with tracing.span("slow-query") as s:
            app.frontend.search("single-tenant", "{ }", limit=5)
        ex = app.frontend.op_duration.exemplar(("search",))
        assert ex is not None and ex[0] == s.trace_id.hex()
        # a within-SLO op does not overwrite the exemplar with None
        app.frontend.slos.per_op["search"] = SLOConfig()
        app.frontend.search("single-tenant", "{ }", limit=5)
        assert app.frontend.op_duration.exemplar(("search",))[0] == \
            s.trace_id.hex()
    finally:
        app.frontend.slos.per_op.pop("search", None)
        tracing.install(prev)
        tracer.shutdown()


# -- SelfTracer dogfood: own spans queryable by trace id ---------------------

def test_dogfood_spans_queryable_by_trace_id(tmp_path):
    """Dogfood mode: the app's own spans export over OTLP/HTTP into this
    very process's distributor and are queryable BY TRACE ID under the
    self-tenant, like any user trace."""
    from tempo_tpu.app import App
    from tempo_tpu.app.api import serve
    from tempo_tpu.app.config import Config
    from tempo_tpu.utils import tracing

    port = _free_port()
    cfg = Config(target="all")
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = str(tmp_path / "wal")
    cfg.generator.localblocks.data_dir = str(tmp_path / "lb")
    cfg.server.http_listen_port = port
    cfg.self_tracing_endpoint = f"http://127.0.0.1:{port}"
    app = App(cfg)
    app.start_loops()
    srv = serve(app, block=False)
    base = f"http://127.0.0.1:{port}"
    try:
        assert not isinstance(tracing.tracer(), tracing.NoopTracer)
        with tracing.span("obs-dogfood-root") as root:
            app.frontend.search("single-tenant", "{ }", limit=5)
            tid_hex = root.trace_id.hex()
        assert tracing.tracer().flush() > 0    # export into ourselves
        req = urllib.request.Request(
            f"{base}/api/traces/{tid_hex}",
            headers={"X-Scope-OrgID": app.cfg.self_tracing_tenant})
        with urllib.request.urlopen(req, timeout=10) as r:
            got = json.loads(r.read())
        assert got["trace_id"] == tid_hex
        names = {s["name"] for s in got["spans"]}
        assert "obs-dogfood-root" in names
        assert "frontend.Search" in names      # child span, same trace
    finally:
        srv.shutdown()
        app.shutdown()


# -- concurrent record + scrape (the device-time ledger adds a
#    high-frequency writer; a render racing a resizing series dict must
#    neither crash nor emit non-conformant text) --------------------------

def test_concurrent_record_and_scrape_conformant():
    import threading

    from tempo_tpu.obs import devtime

    reg = Registry()
    c = reg.counter("tempo_t_race_total", "r", labels=("k",))
    g = reg.gauge("tempo_t_race_depth", "r", labels=("k",))
    h = reg.histogram("tempo_t_race_seconds", "r", labels=("k",),
                      buckets=(0.1, 1.0, 10.0))
    led = devtime.DeviceTimeLedger()

    def by_ledger_key():
        return [(k, v / 1e9) for k, v in led._rows("wall_ns")]

    reg.counter_func(
        "tempo_t_race_ledger_seconds_total", by_ledger_key,
        labels=("kernel", "bucket", "class", "shard"))
    stop = threading.Event()
    errors: list = []

    def writer(i: int) -> None:
        n = 0
        while not stop.is_set():
            n += 1
            label = (f"k{n % 17}",)
            try:
                c.inc(1, label)
                g.set(n, label)
                h.observe(n % 13 / 3.0, label)
                led.record_batch(kernel=f"k{n % 17}", bucket=64 << (n % 3),
                                 prio=n % 3, shards=n % 2, wall_ns=1000,
                                 rows=10, padded_rows=3, queue_wait_ns=5,
                                 h2d_bytes=80,
                                 tenant_rows={f"t{i}": 7, "s": 3})
            except Exception as e:       # noqa: BLE001 — recorded
                errors.append(e)
                return

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 1.0
        renders = 0
        while time.time() < deadline:
            parse_exposition(reg.render())      # raises on nonconformance
            renders += 1
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    assert not errors
    assert renders > 10
    # the ledger's tenant attribution stays consistent under the race
    total = led.total_device_ns()
    assert total > 0
    assert abs(total - sum(led.tenant_device_ns().values())) \
        <= total * 0.05
