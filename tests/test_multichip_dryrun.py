"""Tier-1 multichip gate: the full `dryrun_multichip` parity path runs
on every PR via a forced virtual CPU mesh — mesh regressions surface
here instead of only at MULTICHIP bench time (when a TPU may or may not
be reachable)."""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_on_virtual_cpu_mesh():
    """Run the dryrun CHILD directly (skip the parent's device probe —
    this test pins the backend itself): 8 virtual CPU devices, the
    sharded kernel steps + PRODUCT registry/tempodb parity asserts."""
    env = dict(os.environ)
    env["_TEMPO_TPU_DRYRUN_CHILD"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    # the axon sitecustomize hook would re-register the TPU plugin and
    # override JAX_PLATFORMS; drop its trigger like __graft_entry__ does
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-3000:])
    assert "dryrun_multichip ok" in proc.stdout, proc.stdout[-1000:]
