"""Materialized query grids (tempo_tpu/matview) — ISSUE 13.

The correctness contract under test:

- dd/count kinds served from a grid are BIT-IDENTICAL to the recompute
  path (`GeneratorInstance.query_range` → SeriesCombiner → final),
  including across an overrides-change expiry/rebuild cycle;
- moments-tier quantiles stay inside the plane-fuzz error class (f32
  add-order only — same solver, same grids);
- reads are served only when aligned, covered, and fresh; every other
  outcome falls through with a per-reason miss counter;
- the shared fingerprint (obs/queryfp.py) is stable across whitespace,
  filter operand order, and time-window shifts — qlog and the
  materializer must agree on "same query".
"""

from __future__ import annotations

import itertools

import numpy as np
import pytest

from tempo_tpu import matview, sched
from tempo_tpu.generator.generator import Generator
from tempo_tpu.generator.instance import GeneratorConfig
from tempo_tpu.generator.processors.localblocks import LocalBlocksConfig
from tempo_tpu.matview.materializer import MatViewConfig, query_supported
from tempo_tpu.model.span_batch import SpanBatchBuilder
from tempo_tpu.obs.queryfp import canonical_query, query_fingerprint
from tempo_tpu.overrides import Overrides
from tempo_tpu.traceql.engine_metrics import (
    QueryRangeRequest,
    SeriesCombiner,
    metrics_kind,
)

T0 = 1_700_000_000.0
_ids = itertools.count(1)


def mkgen(now):
    cfg = GeneratorConfig(processors=("span-metrics", "local-blocks"),
                          localblocks=LocalBlocksConfig())
    return Generator(cfg, overrides=Overrides(), now=now)


def push(inst, n_ops=3, per=6, statuses=(0,), attr=None):
    b = SpanBatchBuilder(inst.registry.interner)
    t0 = int(inst.now() * 1e9)
    for i in range(n_ops):
        for j in range(per):
            c = next(_ids)
            b.append(trace_id=c.to_bytes(16, "big"),
                     span_id=c.to_bytes(8, "big"),
                     name=f"op{i}", service="svc", kind=2,
                     status_code=statuses[j % len(statuses)],
                     start_unix_nano=t0 - j * 1_000_000_000,
                     end_unix_nano=t0 - j * 1_000_000_000
                     + (5 + i) * 1_000_000,
                     attrs=attr)
    inst.push_batch(b.build())


def final_map(series, req):
    comb = SeriesCombiner(metrics_kind(req.query), req.n_steps)
    comb.add_all(series or [])
    return {ts.labels: ts.samples for ts in comb.final(req)}


def aligned_req(now_s, query, step_s=10.0, back_steps=11, span_steps=12):
    start = (int(now_s) // int(step_s) - back_steps) * int(step_s)
    return QueryRangeRequest(query, int(start * 1e9),
                             int((start + span_steps * step_s) * 1e9),
                             int(step_s * 1e9))


def assert_bitident(got, recompute, req):
    f1, f2 = final_map(got, req), final_map(recompute, req)
    assert set(f1) == set(f2), (sorted(f1), sorted(f2))
    for k in f1:
        assert np.array_equal(f1[k], f2[k]), (k, f1[k], f2[k])
    return f1


# ---------------------------------------------------------------------------
# fingerprint (satellite: shared obs helper, stability gates)
# ---------------------------------------------------------------------------

def test_fingerprint_whitespace_and_label_order_stable():
    a = '{ resource.service.name = "a" && name = "b" } | rate() by (name)'
    b = '{name="b"&&resource.service.name="a"}   |   rate()   by(name)'
    assert canonical_query(a) == canonical_query(b)
    assert query_fingerprint("metrics", a, 10.0) == \
        query_fingerprint("metrics", b, 10.0)
    # || chains and spanset combines sort too
    assert canonical_query('{ .a = 1 || .b = 2 }') == \
        canonical_query('{ .b = 2 || .a = 1 }')
    assert canonical_query('{.a=1} && {.b=2}') == \
        canonical_query('{.b=2} && {.a=1}')


def test_fingerprint_time_window_independent_but_step_sensitive():
    q = "{ } | rate()"
    # the window never enters the hash (same dashboard, shifted poll)
    assert query_fingerprint("metrics", q, 10.0) == \
        query_fingerprint("metrics", q, 10.0)
    assert query_fingerprint("metrics", q, 10.0) != \
        query_fingerprint("metrics", q, 60.0)
    assert query_fingerprint("metrics", q, 10.0) != \
        query_fingerprint("search", q, 10.0)
    # distinct queries stay distinct
    assert query_fingerprint("metrics", "{ } | count_over_time()", 10.0) \
        != query_fingerprint("metrics", q, 10.0)


def test_fingerprint_unparseable_fallback_stable():
    assert canonical_query("  not   a query ") == "not a query"
    assert query_fingerprint("metrics", "not a query", 1.0) == \
        query_fingerprint("metrics", " not  a  query", 1.0)


def test_qlog_recurrence_counter():
    from tempo_tpu.obs.qlog import QueryLogger
    clock = [T0]
    ql = QueryLogger(now=lambda: clock[0])
    fp = query_fingerprint("metrics", "{ } | rate()", 10.0)
    assert [ql.note_fingerprint(fp) for _ in range(3)] == [1, 2, 3]
    assert ql.fingerprint_count(fp) == 3
    clock[0] += 700.0                       # past the sliding window
    assert ql.fingerprint_count(fp) == 0
    assert ql.note_fingerprint(fp) == 1     # window restarted


# ---------------------------------------------------------------------------
# subscription gating
# ---------------------------------------------------------------------------

def test_query_supported_gates():
    ok, _ = query_supported("{ } | rate() by (name)")
    assert ok
    ok, _ = query_supported(
        "{ } | quantile_over_time(duration, .5, .99) by (name)")
    assert ok
    for bad in ("{ } | min_over_time(duration)",     # kind not gridable
                "{ } | avg_over_time(duration)",
                "{ nestedSetLeft > 0 } | rate()",    # structural intrinsic
                "{ rootName = `x` } | rate()",       # whole-trace root
                "{ parent.name = `x` } | rate()",    # parent scope
                "{.a=1} && {.b=2} | rate()",         # spanset combine
                "{ }",                               # not a metrics query
                "{{{"):                              # unparseable
        ok, why = query_supported(bad)
        assert not ok and why, bad


def test_subscribe_refusals_and_budget():
    mv = matview.configure(MatViewConfig(max_subscriptions=2))
    sub, why = mv.subscribe("t", "{ } | min_over_time(duration)", 10.0)
    assert sub is None and "not materializable" in why
    sub, why = mv.subscribe("t", "{ } | rate()", 0.1)
    assert sub is None and "outside" in why
    s1, _ = mv.subscribe("t", "{ } | rate()", 10.0)
    s1b, _ = mv.subscribe("t", "{ } | rate()", 10.0)
    assert s1 is s1b                       # idempotent, not double-counted
    s2, _ = mv.subscribe("t", "{ } | count_over_time()", 10.0)
    assert s1 is not None and s2 is not None
    s3, why = mv.subscribe("t", "{ } | rate() by (name)", 10.0)
    assert s3 is None and "budget" in why
    assert mv.unsubscribe("t", "{ } | rate()", 10.0)
    assert not mv.wants("t") or mv.wants("t")   # map consistent
    s3, why = mv.subscribe("t", "{ } | rate() by (name)", 10.0)
    assert s3 is not None


# ---------------------------------------------------------------------------
# streaming append + read: bit-identity vs the recompute path
# ---------------------------------------------------------------------------

def test_rate_read_bit_identical_to_recompute():
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(MatViewConfig(max_staleness_s=1e9), now=now)
    query = "{ } | rate() by (name)"
    mv.subscribe("t1", query, 10.0)
    push(inst)                       # builds (empty backfill) + appends
    clock[0] += 25
    push(inst)
    sched.flush()
    req = aligned_req(now(), query)
    got = mv.read("t1", req)
    assert got is not None and mv.reads.get("hit") == 1
    assert_bitident(got, inst.query_range(req), req)


def test_backfill_on_late_subscribe_bit_identical():
    """Subscribing AFTER data exists backfills from local-blocks state
    through the real evaluator — first read already covers history."""
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(MatViewConfig(max_staleness_s=1e9), now=now)
    query = "{ } | count_over_time() by (name)"
    push(inst)
    clock[0] += 30
    push(inst)                       # pre-subscription history
    mv.subscribe("t1", query, 10.0)
    clock[0] += 10
    push(inst)                       # triggers build (backfill) + append
    sched.flush()
    req = aligned_req(now(), query)
    got = mv.read("t1", req)
    assert got is not None
    assert_bitident(got, inst.query_range(req), req)


def test_quantile_dd_bit_identical_across_override_rebuild():
    """The differential satellite: dd-tier quantile grids must match the
    recompute path bit-for-bit BEFORE and AFTER an overrides-change
    expiry/rebuild cycle."""
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(
        MatViewConfig(max_staleness_s=1e9, overrides_check_interval_s=0.0),
        now=now)
    query = "{ } | quantile_over_time(duration, .5, .9, .99) by (name)"
    mv.subscribe("t1", query, 10.0)
    push(inst)
    clock[0] += 15
    push(inst)
    sched.flush()
    req = aligned_req(now(), query)
    got = mv.read("t1", req)
    assert got is not None
    assert_bitident(got, inst.query_range(req), req)

    # flip the tenant's overrides: next batch expires + rebuilds
    gen.overrides.set_tenant_patch(
        "t1", {"generator": {"collection_interval_s": 30.0}})
    clock[0] += 10
    push(inst)
    sched.flush()
    assert mv.rebuilds.get("overrides", 0) >= 1
    sub = mv.subscriptions()[0]
    assert not sub.needs_build           # rebuilt on the push path
    req2 = aligned_req(now(), query)
    got2 = mv.read("t1", req2)
    assert got2 is not None
    assert_bitident(got2, inst.query_range(req2), req2)


def test_moments_tier_within_error_budget():
    from tempo_tpu.ops import moments as msk
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(MatViewConfig(max_staleness_s=1e9), now=now)
    query = "{ } | quantile_over_time(duration, .5, .99) by (name)"
    with msk.use_query_tier("moments"):
        mv.subscribe("t1", query, 10.0)
        push(inst, per=12)
        clock[0] += 15
        push(inst, per=12)
        sched.flush()
        req = aligned_req(now(), query)
        got = mv.read("t1", req)
        assert got is not None
        f1 = final_map(got, req)
        f2 = final_map(inst.query_range(req), req)
        assert set(f1) == set(f2)
        for k in f1:
            a, b = f1[k], f2[k]
            denom = np.maximum(np.abs(b), 1e-12)
            rel = np.max(np.abs(a - b) / denom)
            assert rel <= 0.02, (k, a, b)   # f32 add-order class only


def test_tier_change_expires_grid():
    from tempo_tpu.ops import moments as msk
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(MatViewConfig(max_staleness_s=1e9), now=now)
    query = "{ } | quantile_over_time(duration, .5) by (name)"
    mv.subscribe("t1", query, 10.0)
    push(inst)
    sched.flush()
    req = aligned_req(now(), query)
    assert mv.read("t1", req) is not None
    with msk.use_query_tier("moments"):
        assert mv.read("t1", req) is None        # tier flip → miss
        assert mv.reads.get("miss_tier_changed") == 1
        push(inst)                               # rebuilds on moments axis
        sched.flush()
        assert mv.read("t1", req) is not None


# ---------------------------------------------------------------------------
# ring mechanics, coverage, staleness
# ---------------------------------------------------------------------------

def test_ring_advance_and_coverage_misses():
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(
        MatViewConfig(window_steps=8, max_staleness_s=1e9), now=now)
    query = "{ } | rate() by (name)"
    mv.subscribe("t1", query, 10.0)
    push(inst, per=1)
    clock[0] += 200                  # advance far: ring recycles columns
    push(inst, per=1)
    sched.flush()
    # a window inside coverage serves…
    req = aligned_req(now(), query, back_steps=5, span_steps=6)
    assert mv.read("t1", req) is not None
    # …the evicted past does not
    req_old = aligned_req(now(), query, back_steps=30, span_steps=6)
    assert mv.read("t1", req_old) is None
    assert mv.reads.get("miss_coverage", 0) >= 1
    # unaligned start can never map onto the step-aligned ring
    req_un = QueryRangeRequest(query, req.start_ns + 1, req.end_ns + 1,
                               req.step_ns)
    assert mv.read("t1", req_un) is None
    assert mv.reads.get("miss_unaligned") == 1


def test_late_spans_dropped_and_counted():
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(
        MatViewConfig(window_steps=4, max_staleness_s=1e9), now=now)
    mv.subscribe("t1", "{ } | rate()", 10.0)
    push(inst, n_ops=1, per=1)
    sub = mv.subscriptions()[0]
    # a span 100 steps old lands outside the 4-column ring
    b = SpanBatchBuilder(inst.registry.interner)
    c = next(_ids)
    old = int((now() - 1000) * 1e9)
    b.append(trace_id=c.to_bytes(16, "big"), span_id=c.to_bytes(8, "big"),
             name="op0", service="svc", kind=2, status_code=0,
             start_unix_nano=old, end_unix_nano=old + 1_000_000)
    inst.cfg.ingestion_time_range_slack_s = 0   # let the old span through
    inst.push_batch(b.build())
    sched.flush()
    assert sub.late_dropped >= 1


def test_staleness_gate_and_gauge():
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(MatViewConfig(max_staleness_s=30.0), now=now)
    query = "{ } | rate()"
    mv.subscribe("t1", query, 10.0)
    push(inst)
    sched.flush()
    req = aligned_req(now(), query)
    assert mv.read("t1", req) is not None
    clock[0] += 120                  # no batches: grid goes stale
    req2 = aligned_req(now(), query)
    assert mv.read("t1", req2) is None
    assert mv.reads.get("miss_stale") == 1
    # the gauge reports the per-tenant worst case
    from tempo_tpu.matview.materializer import _mv_staleness
    rows = dict(_mv_staleness())
    assert rows[("t1",)] == pytest.approx(120.0, abs=1.0)


def test_series_overflow_budget():
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(
        MatViewConfig(max_series=64, max_staleness_s=1e9), now=now)
    mv.subscribe("t1", "{ } | rate() by (name)", 10.0)
    push(inst, n_ops=100, per=1)     # 100 groups > 64-series budget
    sched.flush()
    sub = mv.subscriptions()[0]
    assert sub.overflow_dropped > 0
    req = aligned_req(now(), "{ } | rate() by (name)")
    got = mv.read("t1", req)
    assert got is not None and len(got) <= 64


# ---------------------------------------------------------------------------
# auto-subscribe + idle expiry + fast-route gate
# ---------------------------------------------------------------------------

def test_auto_subscribe_and_idle_expiry():
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(
        MatViewConfig(auto_subscribe_after=3, idle_expire_s=100.0,
                      max_staleness_s=1e9), now=now)
    q = "{ } | rate()"
    mv.consider_auto_subscribe("t1", q, 10.0, recurrences=2)
    assert not mv.subscriptions()
    mv.consider_auto_subscribe("t1", q, 10.0, recurrences=3)
    subs = mv.subscriptions()
    assert len(subs) == 1 and subs[0].origin == "auto"
    assert mv.auto_subscribed == 1
    push(inst)
    sched.flush()
    assert not subs[0].needs_build
    clock[0] += 200                  # never read → idle expiry on push
    push(inst)
    assert not mv.subscriptions()
    # a tenant that STOPS ingesting still expires, via the rate-limited
    # sweep on the read/scrape paths (fleet handoff / idle tenant)
    mv.consider_auto_subscribe("t-gone", q, 10.0, recurrences=3)
    assert len(mv.subscriptions()) == 1
    clock[0] += 200
    mv.status()                      # scrape-path sweep
    assert not mv.subscriptions()


def test_matview_disables_staged_fast_route():
    clock = [T0]
    now = lambda: clock[0]
    gen = Generator(GeneratorConfig(processors=("span-metrics",)),
                    overrides=Overrides(), now=now)
    inst = gen.instance("t1")
    assert inst._fast_spanmetrics() is not None
    mv = matview.configure(MatViewConfig(), now=now)
    mv.subscribe("t1", "{ } | rate()", 10.0)
    assert inst._fast_spanmetrics() is None      # full SpanBatch route
    assert gen.instance("t2")._fast_spanmetrics() is not None


# ---------------------------------------------------------------------------
# frontend integration: hit path, auto-subscribe wiring, per-op cache
# ---------------------------------------------------------------------------

@pytest.fixture
def fe_rig():
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.db.tempodb import TempoDB
    from tempo_tpu.frontend import Frontend, FrontendConfig
    from tempo_tpu.querier import Querier
    from tempo_tpu.querier.querier import QuerierConfig
    from tempo_tpu.ring import Ring

    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    be = MemBackend()
    db = TempoDB(be, be)
    ring = Ring(replication_factor=1, now=now)
    q = Querier(db, ring, {}, cfg=QuerierConfig(rf=1))
    fe = Frontend(db, q, cfg=FrontendConfig(
        query_backend_after_s=10 * 365 * 86400.0),   # generator-only leg
        generator_query_range=gen.query_range, now=now)
    return clock, now, gen, fe


def test_frontend_serves_hit_and_matches_recompute(fe_rig):
    clock, now, gen, fe = fe_rig
    inst = gen.instance("t1")
    mv = matview.configure(MatViewConfig(max_staleness_s=1e9), now=now)
    query = "{ } | rate() by (name)"
    ok, why = fe.subscribe_query("t1", query, 10.0)
    assert ok, why
    push(inst)
    clock[0] += 20
    push(inst)
    sched.flush()
    start = (int(now()) // 10 - 11) * 10
    kw = dict(start_s=float(start), end_s=float(start + 120), step_s=10.0)
    served = fe.query_range("t1", query, **kw)
    assert mv.reads.get("hit") == 1
    matview.reset()                       # force the recompute path
    recomputed = fe.query_range("t1", query, **kw)
    a = {s.labels: s.samples.tolist() for s in served}
    b = {s.labels: s.samples.tolist() for s in recomputed}
    assert a == b
    assert fe.unsubscribe_query("t1", query, 10.0) is False  # mv reset


def test_frontend_auto_subscribes_recurring_query(fe_rig):
    clock, now, gen, fe = fe_rig
    inst = gen.instance("t1")
    mv = matview.configure(
        MatViewConfig(auto_subscribe_after=3, max_staleness_s=1e9),
        now=now)
    push(inst)
    query = "{ } | rate() by (name)"
    start = (int(now()) // 10 - 5) * 10
    kw = dict(start_s=float(start), end_s=float(start + 60), step_s=10.0)
    for _ in range(3):                    # misses feed qlog recurrence
        fe.query_range("t1", query, **kw)
    subs = mv.subscriptions()
    assert len(subs) == 1 and subs[0].origin == "auto"
    push(inst)                            # builds the grid
    sched.flush()
    fe.query_range("t1", query, **kw)
    assert mv.reads.get("hit", 0) >= 1


def test_per_op_cache_counters(fe_rig):
    """Satellite: per-op frontend cache hit/miss counter families."""
    from tempo_tpu.backend.cache import CacheProvider
    from tempo_tpu.db.tempodb import TempoDB
    from tempo_tpu.backend.mem import MemBackend
    from tempo_tpu.frontend import Frontend, FrontendConfig
    from tempo_tpu.frontend.slos import SLOConfig
    from tempo_tpu.querier import Querier
    from tempo_tpu.querier.querier import QuerierConfig
    from tempo_tpu.ring import Ring

    clock = [T0 + 7200.0]
    now = lambda: clock[0]
    be = MemBackend()
    db = TempoDB(be, be)
    traces = []
    for i in range(1, 6):
        tid = bytes([i]) * 16
        t0 = int((T0 + i) * 1e9)
        traces.append((tid, [{
            "trace_id": tid, "span_id": bytes([i]) * 8, "name": "op",
            "service": "svc", "start_unix_nano": t0,
            "end_unix_nano": t0 + 50_000_000}]))
    db.write_block("acme", traces, replication_factor=1)
    db.poll_now()
    ring = Ring(replication_factor=1, now=now)
    q = Querier(db, ring, {}, cfg=QuerierConfig(rf=1))
    fe = Frontend(db, q, cfg=FrontendConfig(
        target_bytes_per_job=1,
        slo={"search": SLOConfig(duration_slo_s=60.0)}),
        cache_provider=CacheProvider(), now=now)
    fe.search("acme", "{ }", limit=10, start_s=0, end_s=now())
    assert fe._cache_ops["search"]["misses"] > 0
    assert fe._cache_ops["search"].get("hits", 0) == 0
    fe.search("acme", "{ }", limit=10, start_s=0, end_s=now())
    assert fe._cache_ops["search"]["hits"] > 0
    kw = dict(start_s=T0, end_s=T0 + 60, step_s=10.0)
    fe.query_range("acme", "{ } | rate()", **kw)
    fe.query_range("acme", "{ } | rate()", **kw)
    assert fe._cache_ops["metrics"]["misses"] > 0
    assert fe._cache_ops["metrics"]["hits"] > 0
    text = fe.obs.render()
    assert 'tempo_tpu_frontend_cache_hits_total{op="search"}' in text
    assert 'tempo_tpu_frontend_cache_misses_total{op="metrics"}' in text


# ---------------------------------------------------------------------------
# obs + config + status surfaces
# ---------------------------------------------------------------------------

def test_matview_obs_families_render():
    from tempo_tpu.obs.jaxruntime import RUNTIME
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(MatViewConfig(max_staleness_s=1e9), now=now)
    mv.subscribe("t1", "{ } | rate()", 10.0)
    push(inst)
    sched.flush()
    mv.read("t1", aligned_req(now(), "{ } | rate()"))
    mv.read("t1", QueryRangeRequest("{ } | count_over_time()",
                                    int(T0 * 1e9), int((T0 + 60) * 1e9),
                                    int(10e9)))
    text = RUNTIME.render()
    assert 'tempo_matview_subscriptions{origin="explicit"} 1' in text
    assert "tempo_matview_grids 1" in text
    assert 'tempo_matview_reads_total{result="hit"} 1' in text
    assert 'tempo_matview_reads_total{result="miss_unsubscribed"} 1' in text
    assert "tempo_matview_appends_total" in text
    assert "tempo_matview_state_bytes" in text
    assert 'tempo_matview_staleness_seconds{tenant="t1"}' in text
    st = mv.status()
    assert st["subscriptions"] == 1 and st["grids_built"] == 1
    assert st["subscribed"][0]["tenant"] == "t1"


def test_config_check_matview_bounds():
    from tempo_tpu.app.config import Config
    cfg = Config()
    assert not [w for w in cfg.check() if "matview" in w]
    cfg.matview.window_steps = 1
    cfg.matview.max_staleness_s = 0.0
    cfg.matview.auto_subscribe_after = 0
    warns = "\n".join(cfg.check())
    assert "matview.window_steps < 2" in warns
    assert "matview.max_staleness_s" in warns
    assert "matview.auto_subscribe_after" in warns


def test_zero_steady_state_recompiles_on_append():
    """Warm appends must reuse the shared engine scatter traces — the
    acceptance criterion's zero-recompile gate, in miniature."""
    from tempo_tpu.obs.jaxruntime import JIT_COMPILES
    clock = [T0]
    now = lambda: clock[0]
    gen = mkgen(now)
    inst = gen.instance("t1")
    mv = matview.configure(MatViewConfig(max_staleness_s=1e9), now=now)
    mv.subscribe("t1", "{ } | rate() by (name)", 10.0)
    for _ in range(3):                   # warm every shape bucket
        push(inst, n_ops=3, per=6)
        clock[0] += 10
    sched.flush()

    def compiles():
        with JIT_COMPILES._lock:
            return sum(v for k, v in JIT_COMPILES._series.items()
                       if k and k[0].startswith(("matview", "engine")))

    warm = compiles()
    for _ in range(5):
        push(inst, n_ops=3, per=6)
        clock[0] += 10
    sched.flush()
    assert compiles() == warm


def test_batchview_dict_codes_parity():
    """view_from_span_batch attaches interner dictionary sidecars to its
    string intrinsics, and group factorization over the codes assigns
    the SAME series keys as the string path (the codes are an
    optimization, never a semantic change)."""
    import dataclasses as dc

    from tempo_tpu.matview.batchview import view_from_span_batch
    from tempo_tpu.traceql.engine_metrics import SeriesIndex, group_slots
    from tempo_tpu.traceql.parser import parse

    b = SpanBatchBuilder()
    for i in range(64):
        b.append(trace_id=bytes([i % 7 + 1]) * 16, span_id=bytes([2]) * 8,
                 name=f"op-{i % 5}", service=f"svc-{i % 3}",
                 status_code=0,
                 start_unix_nano=int(T0 * 1e9) + i,
                 end_unix_nano=int(T0 * 1e9) + i + 1000)
    view = view_from_span_batch(b.build())

    for key in ("name", "resource.service.name", "statusMessage"):
        c = view.col(key)
        assert c.codes is not None and c.code_values is not None
        got = [str(c.code_values[int(cd)]) for cd in c.codes]
        assert got == [str(v) for v in c.values]

    by = parse(
        "{ } | rate() by (name, resource.service.name)").metrics.by
    rows = np.arange(view.n, dtype=np.int64)
    si_code, si_str = SeriesIndex(), SeriesIndex()
    keep_c, slots_c = group_slots(list(by), si_code, view, rows)
    for key in ("name", "resource.service.name"):
        view.set_col(key, dc.replace(view.col(key),
                                     codes=None, code_values=None))
    keep_s, slots_s = group_slots(list(by), si_str, view, rows)
    assert np.array_equal(keep_c, keep_s)
    lab_c = {si_code.keys[int(s)] for s in np.unique(slots_c)}
    lab_s = {si_str.keys[int(s)] for s in np.unique(slots_s)}
    assert lab_c == lab_s == {
        (("name", f"op-{i}"), ("resource.service.name", f"svc-{j}"))
        for i in range(5) for j in range(3)}
